"""Fairness repair: masked gradient repair and two-stage retraining.

Re-implements the reference's two repair pipelines TPU-first with optax:

* **Masked repair** (``src/AC/detect_bias.py:304-437``): freeze everything
  except the localized biased neurons — the reference builds per-layer
  kernel/bias masks (``create_neuron_masks:320-347``) and multiplies
  gradients inside a custom train step (``masked_train_step:350-378``).
  Here the mask lives in the optax chain, the step is one jitted update.
* **Two-stage retraining** (``src/AC/new_model.py:179-263``): stage 1
  fine-tunes on original data; stage 2 trains on counterexample batches at
  low LR with an accuracy floor (0.80) early stop.

Training math runs in f32 (these are 6-30-feature MLPs; bf16 would add
noise with no MXU payoff at this size), one jitted step per epoch loop.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fairify_tpu.models.mlp import MLP, forward


def bce_loss(net: MLP, x: jax.Array, y: jax.Array) -> jax.Array:
    """Binary cross-entropy on logits (the reference trains sigmoid+BCE)."""
    logits = forward(net, x)
    return optax.sigmoid_binary_cross_entropy(logits, y.astype(jnp.float32)).mean()


def neuron_gradient_masks(net: MLP, targets: Sequence[Tuple[int, int]]) -> MLP:
    """Masks selecting only the (layer, neuron) targets' incoming weights.

    Mirrors ``create_neuron_masks`` (``src/AC/detect_bias.py:320-347``): for a
    target neuron j of layer l, unfreeze column j of ``weights[l]`` and
    ``biases[l][j]``; everything else gets gradient 0.
    """
    wmasks = [np.zeros_like(np.asarray(w)) for w in net.weights]
    bmasks = [np.zeros_like(np.asarray(b)) for b in net.biases]
    for l, j in targets:
        wmasks[l][:, j] = 1.0
        bmasks[l][j] = 1.0
    return MLP(
        tuple(jnp.asarray(m) for m in wmasks),
        tuple(jnp.asarray(m) for m in bmasks),
        net.masks,
    )


@dataclass
class RepairResult:
    net: MLP
    history: List[dict]


def _fit(net: MLP, X, y, optimizer, epochs: int, batch_size: int, seed: int,
         grad_mask: MLP | None = None, trainable=None):
    X = jnp.asarray(np.asarray(X), jnp.float32)
    y = jnp.asarray(np.asarray(y), jnp.float32)
    params = (net.weights, net.biases)
    opt_state = optimizer.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        def loss_fn(p):
            return bce_loss(MLP(p[0], p[1], net.masks), xb, yb)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        if grad_mask is not None:
            grads = (
                tuple(g * m for g, m in zip(grads[0], grad_mask.weights)),
                tuple(g * m for g, m in zip(grads[1], grad_mask.biases)),
            )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    rng = np.random.default_rng(seed)
    n = X.shape[0]
    history = []
    for epoch in range(epochs):
        order = rng.permutation(n)
        losses = []
        for s in range(0, n, batch_size):
            idx = order[s : s + batch_size]
            params, opt_state, loss = step(params, opt_state, X[idx], y[idx])
            losses.append(float(loss))
        history.append({"epoch": epoch, "loss": float(np.mean(losses))})
    return MLP(params[0], params[1], net.masks), history


def masked_repair(
    net: MLP,
    targets: Sequence[Tuple[int, int]],
    X, y,
    epochs: int = 5,
    lr: float = 1e-3,
    batch_size: int = 32,
    seed: int = 0,
) -> RepairResult:
    """Gradient-masked fine-tune updating only the biased neurons
    (``masked_train_step``, ``src/AC/detect_bias.py:350-405``)."""
    mask = neuron_gradient_masks(net, targets)
    repaired, history = _fit(
        net, X, y, optax.adam(lr), epochs, batch_size, seed, grad_mask=mask
    )
    return RepairResult(repaired, history)


def same_label_relabel_retrain(
    net: MLP,
    ce_pairs: Sequence[Tuple[np.ndarray, np.ndarray]],
    epochs: int = 5,
    lr: float = 1e-3,
    batch_size: int = 32,
    seed: int = 0,
) -> RepairResult:
    """The reference's conservative same-label relabeling retrain —
    faithfully, as a measured BASELINE arm (``src/AC/detect_bias.py:
    412-433``; VERDICT r4 missing #2).

    Each counterexample pair ``(x, x')`` contributes BOTH points labeled
    with the *max* of the model's two predictions ("more conservative" in
    the reference's words — a flip pair always relabels to 1), then the
    net is retrained on exactly that relabeled set with plain BCE for 5
    epochs.  No consensus labels, no pair-consistency loss, no guarded
    checkpoint selection — those are the consensus arm's departures
    (:func:`counterexample_retrain`), and keeping this arm faithful is the
    point: the experiment record measures the departures' value instead of
    asserting it.
    """
    if not ce_pairs:
        return RepairResult(net, [])
    xs = np.stack([p[0] for p in ce_pairs]).astype(np.float32)
    xps = np.stack([p[1] for p in ce_pairs]).astype(np.float32)
    px = np.asarray(forward(net, jnp.asarray(xs)) > 0.0).astype(np.float32)
    pp = np.asarray(forward(net, jnp.asarray(xps)) > 0.0).astype(np.float32)
    labels = np.maximum(px, pp)  # detect_bias.py:421 ``max(...)``
    X_ce = np.concatenate([xs, xps], axis=0)
    y_ce = np.concatenate([labels, labels], axis=0)
    repaired, history = _fit(
        net, X_ce, y_ce, optax.adam(lr), epochs, batch_size, seed)
    return RepairResult(repaired, history)


def _group_snapshot(netp: MLP, Xv, yv, prot: np.ndarray) -> dict:
    """Val accuracy + the group metrics the success criteria guard."""
    from fairify_tpu.analysis import metrics as gm

    pred = np.asarray(forward(netp, Xv) > 0.0).astype(int)
    yv = np.asarray(yv)
    return {
        "acc": float((pred == yv).mean()),
        "di": gm.disparate_impact(pred, prot),
        "spd": gm.statistical_parity_difference(pred, prot),
        "eod": gm.equal_opportunity_difference(yv, pred, prot),
        "aod": gm.average_odds_difference(yv, pred, prot),
    }


# Shared repair-success bar — the checkpoint-selection guard here and the
# experiment-level ``repair_success`` assertion MUST agree, so both build on
# these helpers/constants (divergence would let the selector accept epochs
# the experiment then reports as FAILED).
GROUP_TOL = 0.02


def derive_accuracy_floor(orig_acc: float) -> float:
    """The reference's 0.80 floor (``new_model.py:233-241``) presumes
    adult-level accuracy (~0.84); models that never reached 0.80 (german
    ≈ 0.71) get a floor relative to their own starting accuracy."""
    return min(0.80, orig_acc - 0.005)


def di_not_worse(after_di: float, before_di: float, tol: float = GROUP_TOL) -> bool:
    """Disparate impact no farther from 1 (within tol)."""
    return abs(after_di - 1.0) <= abs(before_di - 1.0) + tol


def magnitude_not_worse(after: float, before: float, tol: float = GROUP_TOL) -> bool:
    """|metric| not worse (within tol) — SPD/EOD/AOD style differences."""
    return abs(after) <= abs(before) + tol


def _not_worse(after: dict, before: dict, tol: float) -> bool:
    """DI no farther from 1; |SPD|/|EOD|/|AOD| not worse (within tol)."""
    return di_not_worse(after["di"], before["di"], tol) and all(
        magnitude_not_worse(after[k], before[k], tol)
        for k in ("spd", "eod", "aod"))


def counterexample_retrain(
    net: MLP,
    X, y,
    ce_pairs: Sequence[Tuple[np.ndarray, np.ndarray]],
    X_val, y_val,
    stage1_epochs: int = 0,
    stage2_epochs: int = 10,
    stage1_lr: float = 1e-3,
    stage2_lr: float = 5e-3,
    accuracy_floor: Optional[float] = None,
    batch_size: int = 64,
    seed: int = 0,
    pair_consistency_weight: float = 4.0,
    anchor_weight: float = 1e-4,
    protected_col: Optional[int] = None,
    group_tol: float = GROUP_TOL,
    stage2_steps_per_epoch: int = 150,
    label_weight: float = 0.5,
) -> RepairResult:
    """Two-stage fairness retraining (``src/AC/new_model.py:179-263``).

    Stage 1 fine-tunes on the original data; stage 2 trains on the
    counterexample *pairs*.  Three deliberate departures from a naive
    re-labelling pass — each closes a failure mode observed in round 2,
    where the retrained model got *less* fair by most metrics:

    * **Consensus labels.**  A counterexample pair flips by construction, so
      "the model's prediction on x" is systematically the label of one PA
      role — training on it collapses the positive rate of the other group
      (observed: DI 0.486 → 0.047).  Instead both points get the pair's
      confidence-weighted consensus: 1 iff the mean sigmoid over the pair
      exceeds ½ (the more confident side of the flip wins, symmetric in the
      protected attribute).
    * **Pair-consistency loss.**  Stage 2 minimises
      ``BCE + λc·mean((σ(f(x)) − σ(f(x')))²) + λa·‖θ − θ_stage1‖²`` — the
      consistency term drives the *individual-fairness* objective (treat the
      pair alike) directly instead of through labels, and the anchor keeps
      the net near its accurate stage-1 weights (the reference stores
      stage-1 weights "for regularization", ``new_model.py:201-207``).
    * **Guarded checkpoint selection.**  After each stage-2 epoch the val
      accuracy and group metrics are snapshotted; the returned net is the
      epoch that (a) holds the accuracy floor (``new_model.py:233-241``),
      (b) leaves DI no farther from 1 and |SPD|/|EOD|/|AOD| not worse than
      the ORIGINAL model (within ``group_tol``), and (c) among those, has
      the lowest pair inconsistency.  If no epoch qualifies the lowest-
      inconsistency floor-holding epoch is returned and the history says so
      (``selected`` record) — the experiment-level success criteria then
      fail loudly instead of shipping a regression silently.

    ``stage1_epochs`` defaults to 0 — a measured departure from the
    reference's 8-epoch stage 1 (``new_model.py:192-199``): fine-tuning
    AC-3 on the adult training distribution moves DI 0.486 → 0.303 *before
    any repair happens* (the data's own bias), which is exactly how the
    round-2 record ended up less fair than its input.  The accuracy role
    stage 1 played is covered by the anchor + floor-guarded selection.
    With the defaults (λ_label 0.5, λ_cons 4.0, no stage 1) the AC-3 →
    AC-16 run passes every criterion: acc 0.843 (floor 0.840), DI 0.486 →
    0.512, |SPD| down, causal rate 0.0221 → 0.0000.

    ``protected_col`` enables the group-metric guard (b); without it only
    the accuracy floor gates selection.
    """
    Xv = jnp.asarray(np.asarray(X_val), jnp.float32)
    yv = np.asarray(y_val)
    prot = np.asarray(X_val)[:, protected_col] if protected_col is not None else None
    baseline = _group_snapshot(net, Xv, yv, prot) if prot is not None else None
    if accuracy_floor is None:
        orig_acc = float((np.asarray(forward(net, Xv) > 0.0).astype(int) == yv).mean())
        accuracy_floor = derive_accuracy_floor(orig_acc)

    stage1, hist1 = _fit(net, X, y, optax.adam(stage1_lr), stage1_epochs, batch_size, seed)
    history = list(hist1)
    if not ce_pairs:
        return RepairResult(stage1, history)

    xs = np.stack([p[0] for p in ce_pairs]).astype(np.float32)
    xps = np.stack([p[1] for p in ce_pairs]).astype(np.float32)
    probs = 0.5 * (
        jax.nn.sigmoid(forward(stage1, jnp.asarray(xs)))
        + jax.nn.sigmoid(forward(stage1, jnp.asarray(xps))))
    labels = np.asarray(probs > 0.5).astype(np.float32)

    anchor = (stage1.weights, stage1.biases)
    optimizer = optax.adam(stage2_lr)
    params = (stage1.weights, stage1.biases)
    opt_state = optimizer.init(params)

    @jax.jit
    def pair_step(params, opt_state, xb, xpb, yb):
        def loss_fn(p):
            m = MLP(p[0], p[1], net.masks)
            lx = forward(m, xb)
            lp = forward(m, xpb)
            bce = 0.5 * (
                optax.sigmoid_binary_cross_entropy(lx, yb).mean()
                + optax.sigmoid_binary_cross_entropy(lp, yb).mean())
            cons = jnp.mean((jax.nn.sigmoid(lx) - jax.nn.sigmoid(lp)) ** 2)
            anc = sum(jnp.sum((w - w0) ** 2) for w, w0 in zip(p[0], anchor[0]))
            anc = anc + sum(jnp.sum((b - b0) ** 2) for b, b0 in zip(p[1], anchor[1]))
            return (label_weight * bce + pair_consistency_weight * cons
                    + anchor_weight * anc)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def inconsistency(params):
        m = MLP(params[0], params[1], net.masks)
        return jnp.mean(jnp.abs(
            jax.nn.sigmoid(forward(m, jnp.asarray(xs)))
            - jax.nn.sigmoid(forward(m, jnp.asarray(xps)))))

    rng = np.random.default_rng(seed + 1)
    xs_j, xps_j, y_j = jnp.asarray(xs), jnp.asarray(xps), jnp.asarray(labels)
    n = xs.shape[0]
    # Fixed optimizer-step count per epoch, batches resampled with
    # replacement: a small counterexample set must not starve the repair of
    # gradient steps (98 pairs at batch 64 is 2 steps/epoch — nothing moves).
    steps = max(stage2_steps_per_epoch, -(-n // batch_size))
    candidates = []  # (tier, inconsistency, −acc, epoch, params)
    for epoch in range(stage2_epochs):
        losses = []
        for _ in range(steps):
            idx = rng.integers(0, n, size=min(batch_size, n))
            params, opt_state, loss = pair_step(
                params, opt_state, xs_j[idx], xps_j[idx], y_j[idx])
            losses.append(float(loss))
        snap_net = MLP(params[0], params[1], net.masks)
        inc = float(inconsistency(params))
        if prot is not None:
            snap = _group_snapshot(snap_net, Xv, yv, prot)
        else:
            pred = np.asarray(forward(snap_net, Xv) > 0.0).astype(int)
            snap = {"acc": float((pred == yv).mean())}
        ok_floor = snap["acc"] >= accuracy_floor
        ok_group = baseline is None or _not_worse(snap, baseline, group_tol)
        history.append({"epoch": f"stage2-{epoch}", "loss": float(np.mean(losses)),
                        "val_acc": snap["acc"], "pair_inconsistency": inc,
                        "floor_ok": ok_floor, "group_ok": ok_group})
        if ok_floor:
            candidates.append((0 if ok_group else 1, inc, -snap["acc"], epoch, params))
        if not ok_floor:  # accuracy floor early stop, new_model.py:233-241
            break
    if candidates:
        # Qualified epochs (group guard holds) outrank unqualified; then
        # lowest pair inconsistency, then accuracy.
        candidates.sort(key=lambda t: t[:3])
        tier, inc, nacc, epoch, params = candidates[0]
        history.append({"selected": f"stage2-{epoch}", "group_ok": tier == 0,
                        "pair_inconsistency": inc, "val_acc": -nacc})
        return RepairResult(MLP(params[0], params[1], net.masks), history)
    # No floor-holding epoch: refuse the repair and hand back the ORIGINAL
    # net (not stage 1 — a fine-tuned net can already be a fairness
    # regression, see the stage1_epochs note above).
    history.append({"selected": "original", "group_ok": False})
    return RepairResult(net, history)

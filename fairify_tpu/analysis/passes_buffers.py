"""IR pass ``ir-buffers``: launch-payload and executable-footprint audit.

On the tunnelled chip the launch cost is transfer-dominated; this pass
audits what each executable actually moves and holds:

* **dead arguments** — a top-level invar no equation consumes is payload
  uploaded per launch for nothing.  Flagged by tree keystr (e.g.
  ``[0][4][1]`` = the parity kernel's old final-layer alive mask, a dead
  ``(P, 1)`` buffer this pass found and this PR removed); the spec's
  ``dead_ok`` carries the reviewed exemptions (the MLP final-layer
  all-ones mask contract).
* **pass-through outputs** — an output that is verbatim an input is a
  pointless device→host copy at drain time.
* **wasted donation** — a kernel that declares ``donate_argnums``/
  ``donate_argnames`` for a buffer no output can absorb (XLA aliases a
  donated input only into a shape+dtype-matching output) keeps the
  donated buffer live AND loses it to the caller: worst of both.
* **temp blowup** — the largest single equation output is the
  jaxpr-derived temp estimate; if it exceeds ``BLOWUP_RATIO`` × the
  larger of argument/output bytes, the kernel materialises a tensor its
  interface never pays for (the (B, V, V, d) class the certify scan
  exists to avoid).  The same estimate is cross-checked against the
  compiled ``memory_analysis().temp_size_in_bytes`` gauge: an actual
  temp footprint ``TEMP_XCHECK_RATIO`` × beyond the biggest op we wrote
  means XLA failed to fuse the kernel (head ratios are ≤ ~6×).
"""
from __future__ import annotations

from typing import List

from fairify_tpu.analysis.ir import KernelIR, aval_bytes

PASS_ID = "ir-buffers"

#: Largest-intermediate : max(args, outs) ratio beyond which a kernel is
#: materialising an interface-invisible tensor (head max is ~12x, on the
#: lattice sign kernel whose V x chunk tensor IS the point).
BLOWUP_RATIO = 64

#: memory_analysis() temp : largest-intermediate ratio beyond which XLA
#: failed to fuse (head max is ~6x on CPU).
TEMP_XCHECK_RATIO = 64


def _check_donation(kir: KernelIR):
    """Wasted donation: a donated leaf with no shape/dtype-matching output.

    XLA can only alias a donated input into an output of identical
    shape+dtype; a donated buffer no output matches is lost to the caller
    AND stays live in the executable — worst of both.  Checked at the
    jaxpr level (deterministic, backend-independent; the runtime alias
    table is not exposed by jax's ``Compiled``).  Donation composed with
    static args shifts positional indices, which no kernel here uses —
    skipped with a finding so the limitation is loud, not silent.
    """
    argnums = kir.jit_kwargs.get("donate_argnums")
    argnames = kir.jit_kwargs.get("donate_argnames")
    if not argnums and not argnames:
        return
    if kir.statics:
        yield (f"kernel '{kir.name}' combines donation with static args — "
               f"positional donation indices shift after the static split; "
               f"the buffer audit cannot attribute them (restructure, or "
               f"teach _check_donation the mapping)")
        return
    if isinstance(argnums, int):
        argnums = (argnums,)
    # Multiset of output (shape, dtype): each output can absorb one donor.
    budget = {}
    for ov in kir.closed_jaxpr.jaxpr.outvars:
        av = getattr(ov, "aval", None)
        if av is not None and hasattr(av, "shape"):
            k = (tuple(av.shape), str(av.dtype))
            budget[k] = budget.get(k, 0) + 1
    for keystr, leaf_aval in _donated_leaves(kir, argnums, argnames):
        k = (tuple(leaf_aval.shape), str(leaf_aval.dtype))
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            yield (f"kernel '{kir.name}' donates argument leaf {keystr} "
                   f"({leaf_aval.str_short()}) but no output matches its "
                   f"shape/dtype — XLA cannot alias it, so the buffer is "
                   f"lost to the caller AND stays live in the executable")


def _donated_leaves(kir: KernelIR, argnums, argnames):
    """(keystr, aval) of every flattened leaf under a donated argument.

    Leaf keystrs for positional args start ``[0][<i>]`` (dynamic args
    tuple first, kwargs dict second — `_leaf_paths` flattens
    ``(dyn_args, dyn_kwargs)``).
    """
    roots = tuple(f"[0][{i}]" for i in (argnums or ())) + \
        tuple(f"[1]['{n}']" for n in (argnames or ()))

    def under(keystr: str) -> bool:
        # Exact leaf, or a strict subtree entry ("[0][1].x" / "[0][1][0]")
        # — plain startswith would also match "[0][10]".
        return any(keystr == r or keystr.startswith(r + ".")
                   or keystr.startswith(r + "[") for r in roots)

    invars = kir.closed_jaxpr.jaxpr.invars
    for i, (keystr, _leaf) in enumerate(kir.leaves):
        if under(keystr) and i < len(invars):
            yield keystr, invars[i].aval


def check_kernel(kir: KernelIR) -> List[str]:
    if kir.closed_jaxpr is None:
        return []
    out: List[str] = []
    dead_ok = set(kir.spec.dead_ok) if kir.spec else set()
    for keystr, aval in kir.dead_invars():
        if keystr in dead_ok:
            continue
        out.append(
            f"kernel '{kir.name}' argument leaf {keystr} "
            f"({aval.str_short()}) is dead — uploaded per launch, "
            f"consumed by nothing; drop it from the kernel signature or "
            f"add a reviewed dead_ok entry to its aval spec")
    for keystr in kir.passthrough_outputs():
        out.append(
            f"kernel '{kir.name}' returns argument leaf {keystr} "
            f"verbatim — a pointless device->host copy at drain; return "
            f"only computed values")
    out.extend(_check_donation(kir))
    big, desc = kir.largest_intermediate()
    base = max(kir.arg_bytes(), kir.out_bytes(), 1)
    if big > BLOWUP_RATIO * base:
        out.append(
            f"kernel '{kir.name}' materialises a {big}-byte intermediate "
            f"({desc}) — {big // base}x its whole argument/output "
            f"footprint; restructure (scan/chunk) so the tensor is never "
            f"materialised whole")
    ma = kir.memory_analysis()
    if ma is not None:
        try:
            temp = int(ma.temp_size_in_bytes)
        except Exception:
            temp = None
        if temp is not None and big > 0 and temp > TEMP_XCHECK_RATIO * big:
            out.append(
                f"kernel '{kir.name}' compiled temp footprint is {temp} "
                f"bytes vs a {big}-byte largest written intermediate "
                f"({desc}) — {temp // max(big, 1)}x; XLA failed to fuse "
                f"this kernel")
    return out

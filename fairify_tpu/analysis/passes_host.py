"""IR pass ``ir-host-transfer``: dispatch-floor killers inside hot kernels.

Every launch on the tunnelled chip costs ~110 ms flat (PERF.md); a host
callback inside a registered kernel doesn't add a launch — it adds a
device→host→device round trip *per executed callback*, which is strictly
worse and invisible to the launch counters.  This pass walks the closed
jaxpr (all sub-jaxprs included) and flags:

* **callback primitives** — ``pure_callback`` / ``io_callback`` /
  ``debug_callback`` (``jax.debug.print`` lowers to the latter) and the
  legacy infeed/outfeed pair.  There is no legitimate use inside a
  registered hot kernel: diagnostics belong on the heartbeat/span layer,
  host math belongs in the decode half of the pipeline.
* **large captured constants** — a closed-over host array (≥ 64 KiB)
  becomes an executable constant re-uploaded per compile and bloating the
  executable image; big tensors must be arguments so the runtime manages
  them as device buffers.

Pass functions take a :class:`fairify_tpu.analysis.ir.KernelIR` and return
finding messages — the rule adapter in ``irlint`` owns locations/severity,
and the fixture corpus calls :func:`check_kernel` directly.
"""
from __future__ import annotations

from typing import List

import numpy as np

from fairify_tpu.analysis.ir import KernelIR

PASS_ID = "ir-host-transfer"

#: Primitives that move control or data through the host mid-kernel.
HOST_TRANSFER_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "host_callback_call", "outside_call", "infeed", "outfeed",
})

#: Captured constants at or above this size are flagged (bytes).
CONST_BYTES_LIMIT = 64 * 1024


def check_kernel(kir: KernelIR) -> List[str]:
    if kir.closed_jaxpr is None:
        return []  # the recompile pass owns unlowerable kernels
    out: List[str] = []
    hits = {}
    for eqn in kir.eqns():
        pname = eqn.primitive.name
        if pname in HOST_TRANSFER_PRIMS:
            hits[pname] = hits.get(pname, 0) + 1
    for pname, n in sorted(hits.items()):
        out.append(
            f"kernel '{kir.name}' executes host-transfer primitive "
            f"'{pname}' x{n} inside its jaxpr — a device->host round trip "
            f"per call on the hot path; move diagnostics to obs spans and "
            f"host math to the pipeline decode half")
    for i, const in enumerate(kir.consts()):
        try:
            nbytes = int(np.asarray(const).nbytes)
        except Exception:
            continue
        if nbytes >= CONST_BYTES_LIMIT:
            out.append(
                f"kernel '{kir.name}' captures a {nbytes}-byte host "
                f"constant (const #{i}) — baked into every executable and "
                f"re-uploaded per compile; pass it as an argument instead")
    return out

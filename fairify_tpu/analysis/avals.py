"""Representative avals + per-kernel specs for the IR analysis suite.

Every kernel registered through ``obs_jit`` is lowered to its closed jaxpr
under ONE small, deterministic "analysis world" (tiny net, tiny encoding,
tiny grid) before any pass runs.  The world is chosen so each kernel traces
the same code paths production does — a PA dim with two assignments, an RA
dim with ε = 1 (so the RA-widening and RA-lattice branches are live), one
hidden layer (so sign-BaB and CROWN relaxations are live), and a stacked
two-model family — while staying small enough that tracing all 24 kernels
plus the buffer pass's compiles finishes well inside the 30 s CPU budget
(``tests/test_analysis.py`` pins it).

A :class:`KernelSpec` is the reviewed contract for one kernel:

* ``build(world)`` — the representative ``(args, kwargs)``, assembled the
  way the real call sites assemble them (``_stage0_block_submit``,
  ``pgd_attack_submit``, ``decide_box_exhaustive``, …), so the lowered
  signature IS the production signature shape-for-shape;
* ``sound`` — whether the kernel's float outputs carry verdict weight
  (certify path).  The soundness pass restricts exactly these kernels to
  the sound-ops allowlist; attack/sampling kernels are exempt because
  their outputs are exact-validated on host before any verdict settles;
* ``dead_ok`` — reviewed dead-argument exemptions (keystr of the flattened
  leaf, e.g. the MLP final-layer mask: all-ones by contract, 4 bytes, and
  part of the single network pytree — not a transfer problem);
* ``variants`` — production call-shape variants with a declared
  same-executable expectation; the recompile pass checks the declaration
  against the ground-truth ``ObsJit.signature_key`` of each variant;
* ``expected_signatures`` — the compile-signature budget over the baseline
  + variants (e.g. ``engine.certify_attack`` legitimately buckets into
  stage-0 (``alpha_iters=0``) and BaB (``alpha_iters=8``) executables —
  PR 3 measured exactly those 2).

``SOUND_KERNELS`` (derived) names which kernels carry verdict weight; it is
the registry DESIGN.md §11's soundness catalog documents.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

import numpy as np


@dataclass(frozen=True)
class Variant:
    """One production call-shape variant of a kernel.

    ``same_exec`` declares whether this variant must reuse the baseline
    executable (same obs_jit cache key).  A declaration the lowered
    signature contradicts is a finding either way: ``same_exec=True`` with
    a differing key is a predicted silent recompile; ``same_exec=False``
    with an equal key is a stale bucketing expectation.
    """

    desc: str
    build: Callable[["AnalysisWorld"], Tuple[tuple, dict]]
    same_exec: bool


@dataclass(frozen=True)
class KernelSpec:
    name: str
    build: Callable[["AnalysisWorld"], Tuple[tuple, dict]]
    sound: bool = False
    dead_ok: Tuple[str, ...] = ()
    variants: Tuple[Variant, ...] = ()
    expected_signatures: Optional[int] = None


class AnalysisWorld:
    """The deterministic tiny universe every kernel is lowered under.

    d = 5 input dims: PA dim 0 (range {0, 1} → V = 2 assignments), RA dim 1
    (ε = 1), shared dims 2-4 (width 4 each).  One 5→8→1 net (n_hidden = 1),
    a 2-model stacked family, B = 4 partition boxes, S = 8 attack samples.
    """

    def __init__(self):
        import jax
        import jax.numpy as jnp

        from fairify_tpu.models import mlp as mlp_mod
        from fairify_tpu.parallel.mesh import stack_models
        from fairify_tpu.utils.prng import grid_keys
        from fairify_tpu.verify import engine
        from fairify_tpu.verify import property as prop

        self.d = d = 5
        self.B = B = 4
        self.S = S = 8
        self.sim_size = 16

        def tiny_net(seed):
            r = np.random.default_rng(seed)
            w1 = r.normal(size=(d, 8)).astype(np.float32)
            b1 = r.normal(size=(8,)).astype(np.float32)
            w2 = r.normal(size=(8, 1)).astype(np.float32)
            b2 = r.normal(size=(1,)).astype(np.float32)
            return mlp_mod.from_numpy([w1, w2], [b1, b2])

        self.net = tiny_net(0)
        self.stacked = stack_models([tiny_net(0), tiny_net(1)])
        self.enc = prop.PairEncoding(
            pa_idx=np.array([0], dtype=np.int32),
            ra_idx=np.array([1], dtype=np.int32),
            eps=1,
            assignments=np.array([[0], [1]], dtype=np.int32),
            valid_pair=np.array([[False, True], [True, False]]),
            n_dim=d)
        self.lo = np.tile(np.array([0, 0, 0, 0, 0], np.int64), (B, 1))
        self.hi = np.tile(np.array([1, 4, 3, 3, 3], np.int64), (B, 1))
        self.flo = self.lo.astype(np.float32)
        self.fhi = self.hi.astype(np.float32)
        (self.x_lo, self.x_hi, self.xp_lo, self.xp_hi,
         self.valid) = prop.role_boxes(self.enc, self.flo, self.fhi)
        (self.assign_vals, self.pa_mask,
         self.ra_mask) = engine._enc_tensors(self.enc, d)
        rng = np.random.default_rng(0)
        self.xr, self.pr = engine.build_attack_candidates(
            self.enc, rng, self.lo, self.hi, S)
        self.eps = float(self.enc.eps)
        self.vp = self.enc.valid_pair
        self.vp_f = self.vp.astype(np.float32)
        self.key = jax.random.PRNGKey(0)
        self.keys = grid_keys(0, 0, B)
        self.sign0 = (np.zeros((B, 8), np.float32),)  # n_hidden = 1
        # Parity alive masks: HIDDEN layers only (the kernel rebuilds the
        # final all-ones mask itself — the IR buffer pass found the old
        # all-layers tuple shipped a dead (P, 1) buffer per launch).
        self.alive_hidden = (np.ones((B, 8), np.float32),)

        # Lattice scan layouts (decide_box_exhaustive's device tensors).
        # Non-RA: suffix dims (2, 3, 4), width 4 each → 64 points.
        self.lat = dict(
            strides=np.array([16, 4, 1], np.int32),
            widths=np.array([4, 4, 4], np.int32),
            lo_shared=np.array([0, 0, 0], np.int32),
            chunk=64, dims_tuple=(2, 3, 4), n_total=64)
        # RA: dim 1 expanded ±ε (width 5 + 2 = 7) laid out innermost.
        self.lat_ra = dict(
            strides=np.array([112, 28, 7, 1], np.int32),
            widths=np.array([4, 4, 4, 7], np.int32),
            lo_shared=np.array([0, 0, 0, -1], np.int32),
            chunk=63, dims_tuple=(2, 3, 4, 1), n_total=448, ra_ws=(7,))
        bases = np.tile(self.flo[0], (self.enc.n_assign, 1))
        bases[:, 0] = [0.0, 1.0]
        self.bases = bases.astype(np.float32)
        self.valid_mask = np.array([True, True])
        self.jnp = jnp

        # Mega-segment stacks (DESIGN.md §17): TWO chunks of the B-box
        # world along the leading scan axis — the smallest segment that
        # exercises the mega kernels' scan-shaped avals.  The second chunk
        # shifts the shared dims and draws its own attack RNG, the way a
        # real segment stacks per-chunk streams keyed to global starts.
        lo2, hi2 = self.lo.copy(), self.hi.copy()
        lo2[:, 2:] += 1
        hi2[:, 2:] += 1

        def _chunk(lo_c, hi_c, seed):
            flo, fhi = lo_c.astype(np.float32), hi_c.astype(np.float32)
            x_lo, x_hi, xp_lo, xp_hi, valid = prop.role_boxes(
                self.enc, flo, fhi)
            r = np.random.default_rng(seed)
            xr, pr = engine.build_attack_candidates(self.enc, r, lo_c,
                                                    hi_c, S)
            return (x_lo, x_hi, xp_lo, xp_hi, flo, fhi, valid, xr, pr)

        self.mega_seg = tuple(np.stack(a) for a in zip(
            _chunk(self.lo, self.hi, 0), _chunk(lo2, hi2, 1)))
        # Reversed chunk order: same shapes, different content — a later
        # segment of the same sweep, which must reuse the executable.
        self.mega_seg2 = tuple(np.stack(a) for a in zip(
            _chunk(lo2, hi2, 1), _chunk(self.lo, self.hi, 0)))
        self.mkeys = jnp.stack([grid_keys(0, 0, B), grid_keys(0, B, B)])
        self.malive = (np.ones((2, B, 8), np.float32),)


#: Flattened-leaf keystrs of the MLP final-layer mask (all-ones by the
#: model contract — ``utils/prune.py:235-236`` never prunes the output
#: layer) for a net passed as argument 0.  Reviewed dead-arg exemption.
_NET_FINAL_MASK = "[0][0].masks[1]"


def _shift(lo, hi, by=1):
    """Same-shape variant boxes: shifted shared dims (a ragged-but-padded
    later chunk of the same sweep — must reuse the executable)."""
    lo2, hi2 = lo.copy(), hi.copy()
    lo2[:, 2:] += by
    hi2[:, 2:] += by
    return lo2, hi2


def _role_args(w: AnalysisWorld, lo, hi):
    from fairify_tpu.verify import property as prop

    flo, fhi = lo.astype(np.float32), hi.astype(np.float32)
    x_lo, x_hi, xp_lo, xp_hi, valid = prop.role_boxes(w.enc, flo, fhi)
    return flo, fhi, x_lo, x_hi, xp_lo, xp_hi, valid


def _certify_args(w: AnalysisWorld, lo, hi, alpha_iters: int):
    flo, fhi, x_lo, x_hi, xp_lo, xp_hi, valid = _role_args(w, lo, hi)
    return ((w.net, x_lo, x_hi, xp_lo, xp_hi, flo, fhi, w.assign_vals,
             w.pa_mask, w.ra_mask, w.eps, valid, w.vp),
            {"alpha_iters": alpha_iters})


def _certify_attack_args(w: AnalysisWorld, lo, hi, alpha_iters: int):
    args, kw = _certify_args(w, lo, hi, alpha_iters)
    return args + (w.xr, w.pr), kw


def _mega_stage0_args(w: AnalysisWorld, seg, first, alpha_iters: int):
    x_lo, x_hi, xp_lo, xp_hi, plo, phi, valid, xr, pr = seg
    # Per-chunk real-row counts (the funnel-statistics padding mask);
    # the analysis segments are 2 full chunks of B rows each.
    nv = np.full(plo.shape[0], plo.shape[1], np.int32)
    return ((first, x_lo, x_hi, xp_lo, xp_hi, plo, phi, w.assign_vals,
             w.pa_mask, w.ra_mask, w.eps, valid, w.vp, xr, pr, nv),
            {"alpha_iters": alpha_iters})


def _family_certify_args(w: AnalysisWorld, alpha_iters: int):
    return ((w.stacked, w.x_lo, w.x_hi, w.xp_lo, w.xp_hi, w.flo, w.fhi,
             w.assign_vals, w.pa_mask, w.ra_mask, w.eps, w.valid, w.vp),
            {"alpha_iters": alpha_iters})


def _pgd_args(w: AnalysisWorld, steps: int, restarts: int):
    return ((w.net, w.flo, w.fhi, w.assign_vals, w.pa_mask, w.ra_mask,
             w.valid, w.eps, w.key), {"steps": steps, "restarts": restarts})


def _lat_args(w: AnalysisWorld, c0: int):
    L = w.lat
    return ((w.net, np.int32(c0), np.int32(L["n_total"]), L["strides"],
             L["widths"], L["lo_shared"], w.bases, w.valid_mask, w.vp_f),
            {"chunk": L["chunk"], "dims_tuple": L["dims_tuple"], "d": w.d})


def _bab_args(w: AnalysisWorld, alpha_iters: int, shift: int = 0):
    """Representative device-BaB segment: Q = 4 slots + canary, one root.

    Assembled the way ``engine._device_bab_phase`` assembles a group —
    root box in slot 0, canary slot dead and all-zero, ``root_valid``
    padded to (G, V) — so the lowered signature is the production one.
    ``shift`` mutates the box contents only (a later group of the same
    sweep, which must reuse the executable).
    """
    d = w.d
    Q, Qs = 4, 5  # bab_frontier_cap floor + integrity canary slot
    q_lo = np.zeros((Qs, d), np.float32)
    q_hi = np.zeros((Qs, d), np.float32)
    q_root = np.zeros(Qs, np.int32)
    q_live = np.zeros(Qs, bool)
    q_found = np.zeros(Qs, bool)
    wit_a = np.zeros(Qs, np.int32)
    wit_b = np.zeros(Qs, np.int32)
    wit_pt = np.zeros((Qs, d), np.float32)
    lo, hi = w.lo[0].copy(), w.hi[0].copy()
    lo[2:] += shift
    hi[2:] += shift
    q_lo[0] = lo
    q_hi[0] = hi
    q_live[0] = True
    slot_ok = np.zeros(Qs, bool)
    slot_ok[:Q] = True
    root_valid = np.ones((1, w.enc.n_assign), bool)
    branch_mask = np.zeros(d, np.float32)
    branch_mask[[2, 3, 4]] = 1.0  # shared dims (PA enumerated, not split)
    return ((w.net, q_lo, q_hi, q_root, q_live, q_found, wit_a, wit_b,
             wit_pt, slot_ok, root_valid, w.assign_vals, w.pa_mask,
             w.ra_mask, w.eps, w.vp, branch_mask),
            {"rounds": 2, "alpha_iters": alpha_iters})


def _lat_ra_args(w: AnalysisWorld, c0: int):
    L = w.lat_ra
    return ((w.net, np.int32(c0), np.int32(L["n_total"]), L["strides"],
             L["widths"], L["lo_shared"], w.bases, w.valid_mask, w.vp_f),
            {"chunk": L["chunk"], "dims_tuple": L["dims_tuple"], "d": w.d,
             "ra_ws": L["ra_ws"], "eps": 1})


def kernel_specs() -> Dict[str, KernelSpec]:
    """The reviewed spec registry: one entry per obs_jit kernel."""
    specs = [
        KernelSpec(
            "engine.role_logit_bounds",
            lambda w: ((w.net, w.x_lo, w.x_hi, w.xp_lo, w.xp_hi, True), {}),
            sound=True, dead_ok=(_NET_FINAL_MASK,),
            variants=(Variant(
                "shifted boxes, same shapes",
                lambda w: ((w.net,) + _role_args(w, *_shift(w.lo, w.hi))[2:6]
                           + (True,), {}),
                same_exec=True),),
            expected_signatures=1),
        KernelSpec(
            "engine.role_certify",
            lambda w: _certify_args(w, w.lo, w.hi, 0),
            sound=True, dead_ok=(_NET_FINAL_MASK,),
            variants=(
                Variant("shifted boxes, same shapes",
                        lambda w: _certify_args(w, *_shift(w.lo, w.hi), 0),
                        same_exec=True),
                Variant("BaB bucket (alpha_iters=8)",
                        lambda w: _certify_args(w, w.lo, w.hi, 8),
                        same_exec=False),
            ),
            expected_signatures=2),
        KernelSpec(
            "engine.certify_attack",
            lambda w: _certify_attack_args(w, w.lo, w.hi, 0),
            sound=True, dead_ok=(_NET_FINAL_MASK,),
            variants=(
                Variant("shifted boxes, same shapes",
                        lambda w: _certify_attack_args(
                            w, *_shift(w.lo, w.hi), 0),
                        same_exec=True),
                Variant("BaB bucket (alpha_iters=8)",
                        lambda w: _certify_attack_args(w, w.lo, w.hi, 8),
                        same_exec=False),
            ),
            expected_signatures=2),
        KernelSpec(
            "engine.bab_segment",
            lambda w: _bab_args(w, 0),
            sound=True, dead_ok=(_NET_FINAL_MASK,),
            variants=(
                Variant("later group, same shapes",
                        lambda w: _bab_args(w, 0, shift=1),
                        same_exec=True),
                Variant("escalated bucket (alpha_iters=8)",
                        lambda w: _bab_args(w, 8),
                        same_exec=False),
            ),
            # Segment-indexed escalation (engine._device_bab_phase): one
            # plain-CROWN executable for segment 0, one α-CROWN executable
            # for every later segment — exactly 2, like certify_attack.
            expected_signatures=2),
        KernelSpec(
            "engine.attack_logits",
            lambda w: ((w.net, w.xr, w.pr), {}),
            dead_ok=(_NET_FINAL_MASK,)),
        KernelSpec(
            "engine.pgd_attack_kernel",
            lambda w: _pgd_args(w, 30, 32),
            dead_ok=(_NET_FINAL_MASK,),
            variants=(Variant("deep-PGD bucket (60, 96)",
                              lambda w: _pgd_args(w, 60, 96),
                              same_exec=False),),
            expected_signatures=2),
        KernelSpec(
            "engine.sign_bound_kernel",
            lambda w: ((w.net, w.flo, w.fhi, w.sign0),
                       {"alpha_iters": 0}),
            sound=True, dead_ok=(_NET_FINAL_MASK,),
            variants=(Variant(
                "BaB bucket (alpha_iters=8)",
                lambda w: ((w.net, w.flo, w.fhi, w.sign0),
                           {"alpha_iters": 8}),
                same_exec=False),),
            expected_signatures=2),
        KernelSpec(
            "engine.inter_bounds_kernel",
            lambda w: ((w.net, w.flo, w.fhi), {}),
            sound=True, dead_ok=(_NET_FINAL_MASK,),
            expected_signatures=1),
        KernelSpec(
            "engine.sample_role_logits",
            lambda w: ((w.net, w.xr, w.pr), {}),
            dead_ok=(_NET_FINAL_MASK,)),
        KernelSpec(
            "sweep.family_certify_kernel",
            lambda w: _family_certify_args(w, 0),
            sound=True, dead_ok=(_NET_FINAL_MASK,),
            variants=(Variant("BaB bucket (alpha_iters=8)",
                              lambda w: _family_certify_args(w, 8),
                              same_exec=False),),
            expected_signatures=2),
        KernelSpec(
            "sweep.family_stage0_kernel",
            lambda w: (_family_certify_args(w, 0)[0] + (w.xr, w.pr),
                       {"alpha_iters": 0}),
            sound=True, dead_ok=(_NET_FINAL_MASK,),
            expected_signatures=1),
        KernelSpec(
            "sweep.family_bounds_kernel",
            lambda w: ((w.stacked, w.x_lo, w.x_hi, w.xp_lo, w.xp_hi, True),
                       {}),
            sound=True, dead_ok=(_NET_FINAL_MASK,)),
        KernelSpec(
            "sweep.family_logits_kernel",
            lambda w: ((w.stacked, w.xr, w.pr), {}),
            dead_ok=(_NET_FINAL_MASK,)),
        KernelSpec(
            "sweep.mega_stage0_kernel",
            lambda w: _mega_stage0_args(w, w.mega_seg, w.net, 0),
            sound=True, dead_ok=(_NET_FINAL_MASK,),
            variants=(Variant(
                "later segment, same shapes",
                lambda w: _mega_stage0_args(w, w.mega_seg2, w.net, 0),
                same_exec=True),),
            expected_signatures=1),
        KernelSpec(
            "sweep.mega_family_stage0_kernel",
            lambda w: _mega_stage0_args(w, w.mega_seg, w.stacked, 0),
            sound=True, dead_ok=(_NET_FINAL_MASK,),
            variants=(Variant(
                "later segment, same shapes",
                lambda w: _mega_stage0_args(w, w.mega_seg2, w.stacked, 0),
                same_exec=True),),
            expected_signatures=1),
        KernelSpec(
            "sweep.mega_parity_kernel",
            lambda w: ((w.net, w.mkeys, w.mega_seg[4], w.mega_seg[5],
                        w.malive), {"sim_size": w.sim_size}),
            dead_ok=(_NET_FINAL_MASK,),
            expected_signatures=1),
        KernelSpec(
            "pruning.mega_sim_and_bounds",
            lambda w: ((w.net, w.mkeys, w.mega_seg[4], w.mega_seg[5],
                        np.full(w.mega_seg[4].shape[0],
                                w.mega_seg[4].shape[1], np.int32)),
                       {"sim_size": w.sim_size}),
            dead_ok=(_NET_FINAL_MASK,),
            expected_signatures=1),
        KernelSpec(
            "sweep.parity_grid_from_keys",
            lambda w: ((w.net, w.keys, w.flo, w.fhi, w.alive_hidden),
                       {"sim_size": w.sim_size}),
            dead_ok=(_NET_FINAL_MASK,),
            expected_signatures=1),
        KernelSpec(
            "sweep.sim_rows",
            lambda w: ((w.keys[0], w.flo[0], w.fhi[0]),
                       {"sim_size": w.sim_size})),
        KernelSpec(
            "pruning.sim_and_bounds",
            lambda w: ((w.net, w.keys, w.flo, w.fhi),
                       {"sim_size": w.sim_size, "with_sim": True}),
            dead_ok=(_NET_FINAL_MASK,),
            variants=(Variant(
                "transfer-light bucket (with_sim=False)",
                lambda w: ((w.net, w.keys, w.flo, w.fhi),
                           {"sim_size": w.sim_size, "with_sim": False}),
                same_exec=False),),
            expected_signatures=2),
        KernelSpec(
            "pruning.sim_stats",
            lambda w: ((w.net, w.keys, w.flo, w.fhi),
                       {"sim_size": w.sim_size}),
            dead_ok=(_NET_FINAL_MASK,)),
        KernelSpec(
            "lattice.lattice_scan_kernel",
            lambda w: _lat_args(w, 0),
            sound=True, dead_ok=(_NET_FINAL_MASK,),
            variants=(Variant("later chunk (c0=64), same shapes",
                              lambda w: _lat_args(w, 64),
                              same_exec=True),),
            expected_signatures=1),
        KernelSpec(
            "lattice.lattice_signs_kernel",
            lambda w: ((w.net, np.int32(0), w.lat["strides"],
                        w.lat["widths"], w.lat["lo_shared"], w.bases),
                       {"chunk": w.lat["chunk"],
                        "dims_tuple": w.lat["dims_tuple"], "d": w.d}),
            sound=True, dead_ok=(_NET_FINAL_MASK,)),
        KernelSpec(
            "lattice.lattice_scan_kernel_ra",
            lambda w: _lat_ra_args(w, 0),
            sound=True, dead_ok=(_NET_FINAL_MASK,),
            variants=(Variant("later chunk (c0=63), same shapes",
                              lambda w: _lat_ra_args(w, 63),
                              same_exec=True),),
            expected_signatures=1),
    ]
    return {s.name: s for s in specs}


def sound_kernels() -> Tuple[str, ...]:
    """Kernels whose float outputs carry verdict weight (certify path)."""
    return tuple(sorted(n for n, s in kernel_specs().items() if s.sound))


SOUND_KERNELS = sound_kernels

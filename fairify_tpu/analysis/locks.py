"""Whole-program lock-acquisition analysis (AST-only — no jax import).

The runtime is a thicket of threads: the serve worker loop, the fleet
router with heartbeat leases, the SMT pool's dispatch lanes, the
background SMT drainer, and a ``ReplicaKilled(BaseException)`` thrown
into live threads at yield points.  The per-class ``lock-discipline``
rule (``lint/rules_locks.py``) checks that guarded attributes are read
under *a* lock; this module answers the questions that rule cannot see:

* **Which locks exist?**  Every ``threading.Lock`` / ``RLock`` /
  ``Condition`` construction in scope becomes a node — class attributes
  (``self._lock = threading.Lock()``), module globals, and function
  locals.  A Condition *aliases* the lock it wraps (``threading.
  Condition(self._lock)`` — ``with self._cv:`` acquires ``self._lock``),
  so the graph's nodes are canonical underlying locks.
* **In which order are they taken?**  Acquisition edges come from
  lexically nested ``with``/``acquire`` scopes AND from cross-function
  call edges inside ``fairify_tpu/``: holding lock A while calling a
  function that (transitively) acquires lock B is an A → B edge.  Call
  resolution is type-driven — ``self`` methods, module functions through
  the import table, attribute/local types from constructor assignments
  and annotations (``self._replicas: List[Optional[VerificationServer]]``
  resolves ``self._replicas[i].load()``), and chained calls through
  return annotations (``obs.registry().gauge(...).set(...)``).
* **What happens while they are held?**  A reviewed registry of blocking
  calls (:data:`BLOCKING_DOTTED` / :data:`BLOCKING_ATTRS` + typed
  ``Thread.join`` / ``Popen.wait`` / ``Future.result`` /
  ``Condition.wait`` on a *different* lock) is checked at every point a
  lock is held, including through calls (a call that can *reach* a
  blocking operation is flagged at the call site, where the lock is
  actually held).
* **Is the region kill-safe?**  ``serve.fleet`` kills replicas by
  raising ``ReplicaKilled`` at yield points and the chaos registry
  raises at ``faults.check`` sites.  A ``with <lock>`` region that
  mutates guarded state ≥2 times *around* such a yield point publishes
  torn state when the kill lands between the mutations — the failover
  re-homing path then reads a broken invariant.  Manual ``.acquire()``
  without a ``try/finally`` release is the other kill hazard (the lock
  leaks on any exception).
* **Is the Condition used correctly?**  ``Condition.wait`` outside a
  ``while``-predicate loop (spurious wakeups + ignored ``wait(timeout)``
  returns), ``notify``/``notify_all`` without holding, and ``wait``
  without holding are each findings.

The four lint rules in ``lint/rules_concurrency.py`` share ONE instance
of :class:`ConcurrencyAnalysis` per engine run, so the whole-program walk
happens once however many rules consume it.  The same graph is the
ground truth for the dynamic cross-check (:mod:`fairify_tpu.obs.
lockprof`): observed runtime acquisition edges must be a subset of the
static edges — an unmodeled edge is a bug in THIS analysis.

Known limits (lexical, documented rather than papered over): calls
through lambdas/callbacks passed as arguments are invisible (e.g. a
``Supervisor.run(lambda: ...)`` body); a helper documented as "caller
holds the lock" contributes its events with an empty held set.  Nested
``def``s keep the enclosing lexical held set (the closures in this
codebase are invoked synchronously by their enclosing method).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

#: threading factory names that construct a lock-like object.
LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition"})

#: Reviewed registry of blocking calls by dotted name ``module.attr``.
#: Reached under a held lock, each of these stalls every sibling thread
#: contending for that lock (and, for server/fleet Conditions, the whole
#: request path).  Grow this ONLY with a genuinely blocking operation —
#: a false entry turns the rule into noise.
BLOCKING_DOTTED = frozenset({
    ("time", "sleep"),
    ("select", "select"),
    ("subprocess", "run"), ("subprocess", "call"),
    ("subprocess", "check_call"), ("subprocess", "check_output"),
    ("jax", "device_get"),
    ("np", "asarray"), ("numpy", "asarray"),
    ("os", "fsync"), ("os", "replace"), ("os", "remove"),
    ("os", "listdir"), ("os", "makedirs"),
    ("shutil", "rmtree"),
})

#: Blocking attribute calls on ANY receiver (unambiguous names).
BLOCKING_ATTRS = frozenset({"communicate", "block_until_ready"})

#: Blocking methods gated on an inferred receiver type (names too common
#: to flag untyped: ``str.join``, dict ``.get`` etc. must not match).
BLOCKING_TYPED = {
    "threading.Thread": frozenset({"join"}),
    "subprocess.Popen": frozenset({"wait", "communicate"}),
    "Future": frozenset({"result"}),
}

#: Constructor calls whose result type we track for BLOCKING_TYPED.
_SPECIAL_CTORS = {
    ("threading", "Thread"): "threading.Thread",
    ("subprocess", "Popen"): "subprocess.Popen",
    ("concurrent.futures", "Future"): "Future",
}

_FAULTS_ALIASES = frozenset({"faults", "faults_mod", "faults_lib"})

_MAX_CHAIN = 4  # witness call-chain depth kept per reachable lock/blocker


# ---------------------------------------------------------------------------
# Model
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LockInfo:
    """One lock-like construction site."""

    id: str          # '<rel>::<owner>' e.g. 'fairify_tpu/serve/server.py::VerificationServer._lock'
    rel: str
    line: int
    kind: str        # Lock | RLock | Condition
    canonical: str   # id of the underlying lock (self for non-aliasing)


@dataclass
class EdgeWitness:
    """Where one acquisition-order edge was observed statically."""

    rel: str
    line: int
    function: str
    chain: Tuple[str, ...] = ()   # call chain, outermost first

    def render(self) -> str:
        at = f"{self.rel}:{self.line} in {self.function}()"
        if self.chain:
            return f"{at} via {' -> '.join(self.chain)}"
        return at


@dataclass
class RawFinding:
    """Engine-agnostic finding; the lint rules wrap these into Findings."""

    rel: str
    line: int
    function: str
    message: str


@dataclass
class _FnSummary:
    key: Tuple[str, str]                       # (rel, qualname)
    acquires: List[Tuple[str, int]] = field(default_factory=list)
    blockers: List[Tuple[str, FrozenSet[str], int]] = field(default_factory=list)
    calls: List[Tuple[Tuple[Tuple[str, str], ...], FrozenSet[str], int, str]] = \
        field(default_factory=list)            # (callees, held, line, label)


def _short(lock_id: str) -> str:
    """Human name of a lock id: drop the path, keep the owner."""
    return lock_id.split("::", 1)[-1]


# ---------------------------------------------------------------------------
# Per-file tables
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _lock_factory(call: ast.AST) -> Optional[str]:
    """'Lock'|'RLock'|'Condition' when ``call`` is ``threading.X(...)``."""
    if isinstance(call, ast.Call):
        d = _dotted(call.func)
        if d is not None and d.startswith("threading."):
            name = d.split(".", 1)[1]
            if name in LOCK_FACTORIES:
                return name
    return None


def _annotation_names(node: ast.AST) -> Set[str]:
    """All Name ids + dotted names mentioned in a type annotation."""
    out: Set[str] = set()
    for n in ast.walk(node):
        d = _dotted(n)
        if d:
            out.add(d)
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.add(n.value)  # string annotations
    return out


class _ClassInfo:
    def __init__(self, rel: str, node: ast.ClassDef):
        self.rel = rel
        self.name = node.name
        self.node = node
        self.methods: Dict[str, ast.AST] = {}
        self.lock_attrs: Dict[str, LockInfo] = {}   # attr -> LockInfo
        self.attr_types: Dict[str, Set[str]] = {}   # attr -> type names
        for n in node.body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[n.name] = n

    def self_name(self, method: ast.AST) -> str:
        pos = list(method.args.posonlyargs) + list(method.args.args)
        return pos[0].arg if pos else "self"


class _FileInfo:
    def __init__(self, rel: str, tree: ast.AST):
        self.rel = rel
        self.tree = tree
        self.mod_aliases: Dict[str, str] = {}       # alias -> dotted module
        self.from_names: Dict[str, Tuple[str, str]] = {}  # name -> (module, orig)
        self.module_locks: Dict[str, LockInfo] = {}
        self.module_var_types: Dict[str, Set[str]] = {}
        self.classes: Dict[str, _ClassInfo] = {}
        self.functions: Dict[str, ast.AST] = {}     # module-level defs


# ---------------------------------------------------------------------------
# Analysis
# ---------------------------------------------------------------------------


class ConcurrencyAnalysis:
    """Shared whole-program analysis (see module docstring).

    Feed files via :meth:`add_file` (idempotent per rel), then call
    :meth:`finalize` once; the findings and the graph are attributes
    afterwards.  ``lint/rules_concurrency.py`` shares one instance across
    its four rules so the walk runs once per engine run.
    """

    def __init__(self):
        self.files: Dict[str, _FileInfo] = {}
        self.locks: Dict[str, LockInfo] = {}
        # (src canonical, dst canonical) -> first witness
        self.edges: Dict[Tuple[str, str], EdgeWitness] = {}
        self.blocking: List[RawFinding] = []
        self.kill: List[RawFinding] = []
        self.cv: List[RawFinding] = []
        self._classes_by_name: Dict[str, List[_ClassInfo]] = {}
        self._summaries: Dict[Tuple[str, str], _FnSummary] = {}
        self._finalized = False

    # -- ingestion ---------------------------------------------------------

    def add_file(self, rel: str, tree: ast.AST) -> None:
        if rel in self.files or not rel.endswith(".py"):
            return
        info = _FileInfo(rel, tree)
        self._collect_imports(info)
        self._collect_module_scope(info)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                ci = _ClassInfo(rel, node)
                self._collect_class(info, ci)
                info.classes[ci.name] = ci
                self._classes_by_name.setdefault(ci.name, []).append(ci)
        self.files[rel] = info

    def _collect_imports(self, info: _FileInfo) -> None:
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    info.mod_aliases[a.asname or a.name.split(".", 1)[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    # `from pkg import submodule` is a module alias when the
                    # submodule resolves to a file; recorded both ways and
                    # disambiguated at resolution time.
                    info.from_names[a.asname or a.name] = (node.module, a.name)

    def _collect_module_scope(self, info: _FileInfo) -> None:
        for node in info.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.functions[node.name] = node
            elif isinstance(node, ast.Assign):
                fac = _lock_factory(node.value)
                for t in node.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if fac:
                        # Keyed by the threading CALL's line (not the
                        # assign statement's): the dynamic profiler names
                        # locks by the call frame's line, and the two must
                        # agree for multi-line constructions.
                        lid = f"{info.rel}::{t.id}"
                        info.module_locks[t.id] = LockInfo(
                            lid, info.rel, node.value.lineno, fac, lid)
                    elif isinstance(node.value, ast.Call):
                        d = _dotted(node.value.func)
                        if d:
                            info.module_var_types.setdefault(t.id, set()).add(d)
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                fac = _lock_factory(node.value) if node.value is not None \
                    else None
                if fac:
                    lid = f"{info.rel}::{node.target.id}"
                    info.module_locks[node.target.id] = LockInfo(
                        lid, info.rel, node.value.lineno, fac, lid)
                else:
                    info.module_var_types.setdefault(node.target.id, set()) \
                        .update(_annotation_names(node.annotation))

    def _collect_class(self, info: _FileInfo, ci: _ClassInfo) -> None:
        # Pass 0: class-BODY locks (`class X: _lock = threading.Lock()`),
        # in source order so a later Condition(_lock) in the body aliases.
        for n in ci.node.body:
            if isinstance(n, ast.Assign):
                value, names = n.value, [t.id for t in n.targets
                                         if isinstance(t, ast.Name)]
            elif isinstance(n, ast.AnnAssign) and n.value is not None \
                    and isinstance(n.target, ast.Name):
                value, names = n.value, [n.target.id]
            else:
                continue
            fac = _lock_factory(value)
            if not fac or not names:
                continue
            canonical = None
            if fac == "Condition" and isinstance(value, ast.Call) \
                    and value.args and isinstance(value.args[0], ast.Name) \
                    and value.args[0].id in ci.lock_attrs:
                canonical = ci.lock_attrs[value.args[0].id].canonical
            for name in names:
                lid = f"{info.rel}::{ci.name}.{name}"
                ci.lock_attrs[name] = LockInfo(
                    lid, info.rel, value.lineno, fac, canonical or lid)
        # Pass 1: lock attributes (Condition aliasing resolved in pass 2).
        pending_cv: List[Tuple[str, ast.Call, int, str]] = []
        for m in ci.methods.values():
            sn = ci.self_name(m)
            for node in ast.walk(m):
                targets: List[Tuple[ast.AST, ast.AST, int]] = []
                if isinstance(node, ast.Assign):
                    targets = [(t, node.value, node.lineno)
                               for t in node.targets]
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets = [(node.target, node.value, node.lineno)]
                elif isinstance(node, ast.AnnAssign):
                    # type-only declaration: record the annotation
                    attr = _self_attr(node.target, sn)
                    if attr:
                        ci.attr_types.setdefault(attr, set()).update(
                            _annotation_names(node.annotation))
                    continue
                for t, value, line in targets:
                    attr = _self_attr(t, sn)
                    if not attr:
                        continue
                    if isinstance(node, ast.AnnAssign):
                        ci.attr_types.setdefault(attr, set()).update(
                            _annotation_names(node.annotation))
                    fac = _lock_factory(value)
                    if fac == "Condition" and isinstance(value, ast.Call) \
                            and value.args:
                        pending_cv.append((attr, value, value.lineno, sn))
                    elif fac:
                        # Construction line = the threading CALL's line
                        # (matches the dynamic profiler's frame line on
                        # multi-line constructions).
                        lid = f"{info.rel}::{ci.name}.{attr}"
                        ci.lock_attrs[attr] = LockInfo(
                            lid, info.rel, value.lineno, fac, lid)
                    else:
                        # Constructor calls anywhere in the value feed the
                        # attr's candidate types (`A() if flag else B()`,
                        # list/dict comprehensions of instances, ...).
                        for n in ast.walk(value):
                            if isinstance(n, ast.Call):
                                d = _dotted(n.func)
                                if d:
                                    ci.attr_types.setdefault(
                                        attr, set()).add(d)
        # Pass 2: Condition(arg) aliasing — wrap of a known lock shares its
        # canonical node; anything else (incl. Condition(threading.Lock()))
        # is its own node.
        for attr, call, line, sn in pending_cv:
            arg = call.args[0]
            canonical = f"{info.rel}::{ci.name}.{attr}"
            wrapped = _self_attr(arg, sn)
            if wrapped and wrapped in ci.lock_attrs:
                canonical = ci.lock_attrs[wrapped].canonical
            elif isinstance(arg, ast.Name) and arg.id in info.module_locks:
                canonical = info.module_locks[arg.id].canonical
            ci.lock_attrs[attr] = LockInfo(
                f"{info.rel}::{ci.name}.{attr}", info.rel, line, "Condition",
                canonical)

    # -- finalize ----------------------------------------------------------

    def finalize(self) -> None:
        if self._finalized:
            return
        self._finalized = True
        for lk in self._iter_locks():
            self.locks[lk.id] = lk
        for info in self.files.values():
            for name, fn in info.functions.items():
                self._walk_function(info, None, fn, name)
            for ci in info.classes.values():
                for mname, m in ci.methods.items():
                    self._walk_function(info, ci, m, f"{ci.name}.{mname}")
            self._walk_module_body(info)
        self._propagate()

    def _iter_locks(self) -> Iterable[LockInfo]:
        for info in self.files.values():
            yield from info.module_locks.values()
            for ci in info.classes.values():
                yield from ci.lock_attrs.values()

    def catalog(self) -> Dict[Tuple[str, int], str]:
        """(rel, construction line) → canonical lock id.

        The dynamic profiler (:mod:`obs.lockprof`) names locks by caller
        construction site; this map translates observed sites into the
        static graph's nodes.  Local (function-scoped) locks are included
        by the walk below via :attr:`locks` too.
        """
        return {(lk.rel, lk.line): lk.canonical for lk in self.locks.values()}

    def cycles(self) -> List[List[Tuple[str, str, EdgeWitness]]]:
        """Elementary cycles of the canonical lock graph.

        Each cycle is ``[(src, dst, witness), ...]`` closing back on the
        first src, rotated so the lexically-smallest node leads (stable
        reporting).  Cycle count in this graph is tiny; a bounded DFS
        enumeration is plenty.
        """
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, []).append(b)
        for v in adj.values():
            v.sort()
        cycles: List[Tuple[str, ...]] = []
        seen: Set[Tuple[str, ...]] = set()

        def dfs(start: str, node: str, path: List[str]) -> None:
            for nxt in adj.get(node, ()):
                if nxt == start and len(path) >= 1:
                    cyc = tuple(path)
                    lo = cyc.index(min(cyc))
                    key = cyc[lo:] + cyc[:lo]
                    if key not in seen:
                        seen.add(key)
                        cycles.append(key)
                elif nxt not in path and nxt > start and len(path) < 8:
                    # only explore nodes > start: each cycle found once,
                    # from its smallest node
                    dfs(start, nxt, path + [nxt])

        for start in sorted(adj):
            dfs(start, start, [start])
        out = []
        for cyc in cycles:
            steps = []
            for i, a in enumerate(cyc):
                b = cyc[(i + 1) % len(cyc)]
                steps.append((a, b, self.edges[(a, b)]))
            out.append(steps)
        return out

    # -- resolution helpers ------------------------------------------------

    def _module_rel(self, dotted: str) -> Optional[str]:
        cand = dotted.replace(".", "/")
        for rel in (f"{cand}/__init__.py", f"{cand}.py"):
            if rel in self.files:
                return rel
        return None

    def _resolve_func(self, mod_rel: str, name: str, depth: int = 0
                      ) -> Optional[Tuple[str, str, ast.AST]]:
        """(rel, qualname, node) of a module-level function, following
        re-export chains (``from x import f``) up to 3 hops."""
        info = self.files.get(mod_rel)
        if info is None or depth > 3:
            return None
        fn = info.functions.get(name)
        if fn is not None:
            return (mod_rel, name, fn)
        chain = info.from_names.get(name)
        if chain is not None:
            target = self._module_rel(chain[0])
            if target is not None:
                return self._resolve_func(target, chain[1], depth + 1)
        return None

    def _class_named(self, name: str) -> List[_ClassInfo]:
        return self._classes_by_name.get(name.rsplit(".", 1)[-1], [])

    def _return_types(self, fn_node: ast.AST) -> Set[str]:
        ret = getattr(fn_node, "returns", None)
        return _annotation_names(ret) if ret is not None else set()

    # -- the walk ----------------------------------------------------------

    def _walk_module_body(self, info: _FileInfo) -> None:
        stmts = [n for n in info.tree.body
                 if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                       ast.ClassDef))]
        if stmts:
            _FunctionWalker(self, info, None, "<module>", None).walk_body(stmts)

    def _walk_function(self, info: _FileInfo, ci: Optional[_ClassInfo],
                       fn: ast.AST, qualname: str) -> None:
        _FunctionWalker(self, info, ci, qualname, fn).walk()

    # -- propagation (call-site lifting) -----------------------------------

    def _propagate(self) -> None:
        """Lift callee acquisitions/blockers to lock-holding call sites.

        ``reach_acquire[fn]`` / ``reach_block[fn]`` are the locks /
        blocking operations a call to ``fn`` can transitively reach
        (fixed point over the call graph, chains capped for witnesses).
        A call made while holding H then yields edges H → each reachable
        lock and a blocking finding per reachable blocker, attributed at
        the call site — the place the lock is actually held.
        """
        reach_acquire: Dict[Tuple[str, str], Dict[str, Tuple[str, ...]]] = {}
        reach_block: Dict[Tuple[str, str], Dict[str, Tuple[str, ...]]] = {}
        for key, s in self._summaries.items():
            reach_acquire[key] = {lk: () for lk, _ in s.acquires}
            reach_block[key] = {desc: () for desc, _, _ in s.blockers}
        changed = True
        while changed:
            changed = False
            for key, s in self._summaries.items():
                ra, rb = reach_acquire[key], reach_block[key]
                for callees, _held, line, label in s.calls:
                    step = f"{label} ({key[0].rsplit('/', 1)[-1]}:{line})"
                    for callee in callees:
                        # Reachability always propagates; _MAX_CHAIN only
                        # truncates the STORED witness chain (an edge deep
                        # down a call chain is still an edge).
                        for lk, chain in reach_acquire.get(callee, {}).items():
                            if lk not in ra:
                                ra[lk] = ((step,) + chain)[:_MAX_CHAIN]
                                changed = True
                        for desc, chain in reach_block.get(callee, {}).items():
                            if desc not in rb:
                                rb[desc] = ((step,) + chain)[:_MAX_CHAIN]
                                changed = True
        for key, s in self._summaries.items():
            rel, qual = key
            for callees, held, line, label in s.calls:
                if not held:
                    continue
                # Edges lift from EVERY candidate callee (an ambiguous
                # receiver must not hide an edge the runtime can take)...
                for callee in callees:
                    for lk, chain in reach_acquire.get(callee, {}).items():
                        for h in held:
                            if h != lk and (h, lk) not in self.edges:
                                self.edges[(h, lk)] = EdgeWitness(
                                    rel, line, qual,
                                    (f"{label}()",) + chain)
                # ...while blocking reports at most ONE finding per call
                # site (a single fix resolves it, whatever the callee).
                for callee in callees:
                    blocked = reach_block.get(callee, {})
                    if blocked:
                        desc, chain = sorted(blocked.items())[0]
                        via = " -> ".join((f"{label}()",) + chain)
                        self.blocking.append(RawFinding(
                            rel, line, qual.rsplit(".", 1)[-1],
                            f"call under lock "
                            f"{'/'.join(sorted(_short(h) for h in held))} "
                            f"reaches blocking {desc} (via {via}) — move "
                            f"the call outside the `with` block, or "
                            f"allowlist with a reason if the lock exists "
                            f"to serialize exactly this operation"))
                        break


def _self_attr(node: ast.AST, self_name: str) -> str:
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == self_name:
        return node.attr
    return ""


class _FunctionWalker:
    """Single lexical pass over one function: held-set tracking, local
    type inference, event collection, and the purely-local findings
    (kill-safety regions, cv-discipline)."""

    def __init__(self, an: ConcurrencyAnalysis, info: _FileInfo,
                 ci: Optional[_ClassInfo], qualname: str,
                 fn: Optional[ast.AST]):
        self.an = an
        self.info = info
        self.ci = ci
        self.qualname = qualname
        self.fn = fn
        self.fname = qualname.rsplit(".", 1)[-1]
        self.self_name = ci.self_name(fn) if ci is not None and fn is not None \
            else "self"
        self.summary = _FnSummary((info.rel, qualname))
        an._summaries[(info.rel, qualname)] = self.summary
        self.local_types: Dict[str, Set[str]] = {}
        self.local_locks: Dict[str, LockInfo] = {}
        self.cv_names: Set[str] = set()  # lock ids that are Conditions

    # -- entry -------------------------------------------------------------

    def walk(self) -> None:
        self.walk_body(self.fn.body)

    def walk_body(self, stmts: Sequence[ast.AST]) -> None:
        nodes: List[ast.AST] = []
        for s in stmts:
            nodes.extend(ast.walk(s))
        # Two passes: local types feed each other (`v = self.x; w = v.m()`),
        # and source order does not always match data order.
        self._pre_pass(nodes)
        self._pre_pass(nodes)
        self._stmts(list(stmts), frozenset(), in_while=False)

    # -- local type / local lock pre-pass ----------------------------------

    def _pre_pass(self, nodes: List[ast.AST]) -> None:
        for node in nodes:
            if isinstance(node, ast.Assign):
                self._note_assign(node.targets, node.value, node.lineno)
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                self.local_types.setdefault(node.target.id, set()).update(
                    _annotation_names(node.annotation))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                types = self._expr_types(node.iter)
                for t in ast.walk(node.target):
                    if isinstance(t, ast.Name):
                        self.local_types.setdefault(t.id, set()).update(types)

    def _note_assign(self, targets, value, line) -> None:
        fac = _lock_factory(value)
        names = [t.id for t in targets if isinstance(t, ast.Name)
                 and t.id not in self.info.module_locks]
        if fac and names:
            for name in names:
                lid = f"{self.info.rel}::{self.qualname}.{name}"
                lk = LockInfo(lid, self.info.rel, value.lineno, fac, lid)
                self.local_locks[name] = lk
                self.an.locks[lid] = lk
                if fac == "Condition":
                    self.cv_names.add(lid)
            return
        types = self._expr_types(value)
        if types:
            for name in names:
                self.local_types.setdefault(name, set()).update(types)

    def _expr_types(self, expr: ast.AST) -> Set[str]:
        """Candidate type names of an expression (union over sub-exprs)."""
        out: Set[str] = set()
        inner_selfs: Set[int] = set()  # Name nodes that are the `self` of a
        #                                matched self-attr (not receivers)
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                d = _dotted(n.func)
                if d is not None:
                    if "." in d:
                        mod, base = d.rsplit(".", 1)
                        if (mod, base) in _SPECIAL_CTORS:
                            out.add(_SPECIAL_CTORS[(mod, base)])
                            continue
                    else:
                        base = d
                    if base == "Future":
                        out.add("Future")
                    if self.an._class_named(base):
                        out.add(base)
                for _rel, _qual, fnode in self._resolve_call_target(n):
                    out.update(self.an._return_types(fnode))
            else:
                attr = _self_attr(n, self.self_name)
                if attr and self.ci is not None:
                    out.update(self.ci.attr_types.get(attr, ()))
                    base_node = n.value if isinstance(n, ast.Subscript) else n
                    if isinstance(base_node, ast.Attribute):
                        inner_selfs.add(id(base_node.value))
                elif isinstance(n, ast.Name) and id(n) not in inner_selfs:
                    if self.ci is not None and n.id == self.self_name \
                            and expr is n:
                        out.add(self.ci.name)  # a bare `self` receiver only
                    out.update(self.local_types.get(n.id, ()))
                    out.update(self.info.module_var_types.get(n.id, ()))
        return {t for t in out if t not in ("None", "Optional", "List",
                                            "Dict", "Tuple", "Set", "str",
                                            "int", "float", "bool", "deque")}

    # -- lock / cv resolution ----------------------------------------------

    def _resolve_lock(self, expr: ast.AST) -> Optional[LockInfo]:
        attr = _self_attr(expr, self.self_name)
        if attr and self.ci is not None and attr in self.ci.lock_attrs:
            return self.ci.lock_attrs[attr]
        if isinstance(expr, ast.Name):
            if expr.id in self.local_locks:
                return self.local_locks[expr.id]
            if expr.id in self.info.module_locks:
                return self.info.module_locks[expr.id]
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            # Class-level lock accessed as `ClassName._lock`.
            cls = self.info.classes.get(expr.value.id)
            if cls is not None:
                return cls.lock_attrs.get(expr.attr)
        return None

    def _is_condition(self, lk: LockInfo) -> bool:
        return lk.kind == "Condition"

    # -- statement walk ----------------------------------------------------

    def _stmts(self, stmts: List[ast.AST], held: FrozenSet[str],
               in_while: bool) -> None:
        i = 0
        while i < len(stmts):
            st = stmts[i]
            lk = self._manual_acquire(st)
            if lk is not None:
                nxt = stmts[i + 1] if i + 1 < len(stmts) else None
                self._note_acquire(lk, held, st.lineno)
                inner = held | {lk.canonical}
                if isinstance(nxt, ast.Try) and \
                        self._releases_in_finally(nxt, lk):
                    # Handlers and else run BEFORE finally: the lock is
                    # still held there.
                    self._stmts(list(nxt.body), inner, in_while)
                    for h in nxt.handlers:
                        self._stmts(list(h.body), inner, in_while)
                    self._stmts(list(nxt.orelse), inner, in_while)
                    self._stmts(list(nxt.finalbody), held, in_while)
                    i += 2
                    continue
                self.an.kill.append(RawFinding(
                    self.info.rel, st.lineno, self.fname,
                    f"manual {_short(lk.id)}.acquire() without an immediate "
                    f"try/finally release — a ReplicaKilled/fault raised "
                    f"before the release leaks the lock forever; use `with` "
                    f"or wrap the guarded region in try/finally"))
                self._stmts(stmts[i + 1:], inner, in_while)
                return
            rel_lk = self._manual_release(st)
            if rel_lk is not None and rel_lk.canonical in held:
                # An explicit .release() ends the held region for the
                # rest of this statement list.
                held = held - {rel_lk.canonical}
                i += 1
                continue
            self._stmt(st, held, in_while)
            i += 1

    def _stmt(self, st: ast.AST, held: FrozenSet[str], in_while: bool) -> None:
        cls = st.__class__
        if cls in (ast.With, ast.AsyncWith):
            inner = held
            acquired: List[LockInfo] = []
            for item in st.items:
                self._exprs(item.context_expr, inner, in_while)
                lk = self._resolve_lock(item.context_expr)
                if lk is not None:
                    self._note_acquire(lk, inner, item.context_expr.lineno)
                    inner = inner | {lk.canonical}
                    acquired.append(lk)
            if acquired:
                self._kill_scan(st, acquired)
            self._stmts(list(st.body), inner, in_while)
        elif cls is ast.While:
            self._exprs(st.test, held, True)
            self._stmts(list(st.body), held, True)
            self._stmts(list(st.orelse), held, in_while)
        elif cls in (ast.For, ast.AsyncFor):
            self._exprs(st.iter, held, in_while)
            self._stmts(list(st.body), held, in_while)
            self._stmts(list(st.orelse), held, in_while)
        elif cls is ast.If:
            self._exprs(st.test, held, in_while)
            self._stmts(list(st.body), held, in_while)
            self._stmts(list(st.orelse), held, in_while)
        elif cls is ast.Try:
            self._stmts(list(st.body), held, in_while)
            for h in st.handlers:
                self._stmts(list(h.body), held, in_while)
            self._stmts(list(st.orelse), held, in_while)
            self._stmts(list(st.finalbody), held, in_while)
        elif cls in (ast.FunctionDef, ast.AsyncFunctionDef):
            # Nested def: keep the lexical held set (closures here are
            # invoked synchronously by the enclosing method).
            self._stmts(list(st.body), held, False)
        elif cls is ast.ClassDef:
            pass
        else:
            self._exprs(st, held, in_while)

    def _manual_acquire(self, st: ast.AST) -> Optional[LockInfo]:
        return self._lock_method_stmt(st, "acquire")

    def _manual_release(self, st: ast.AST) -> Optional[LockInfo]:
        return self._lock_method_stmt(st, "release")

    def _lock_method_stmt(self, st: ast.AST, method: str
                          ) -> Optional[LockInfo]:
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            f = st.value.func
            if isinstance(f, ast.Attribute) and f.attr == method:
                return self._resolve_lock(f.value)
        return None

    def _releases_in_finally(self, tr: ast.Try, lk: LockInfo) -> bool:
        """The finally must release THE acquired lock — releasing some
        other lock would mask the leak."""
        for n in tr.finalbody:
            if isinstance(n, ast.Expr) and isinstance(n.value, ast.Call):
                f = n.value.func
                if isinstance(f, ast.Attribute) and f.attr == "release":
                    got = self._resolve_lock(f.value)
                    if got is not None and got.canonical == lk.canonical:
                        return True
        return False

    # -- expression walk ----------------------------------------------------

    def _exprs(self, node: ast.AST, held: FrozenSet[str],
               in_while: bool) -> None:
        # Lambda bodies inside the expression keep the lexical held set
        # (same policy as nested defs — invoked synchronously here).
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                self._call(n, held, in_while)

    def _call(self, call: ast.Call, held: FrozenSet[str],
              in_while: bool) -> None:
        f = call.func
        d = _dotted(f)
        # Condition discipline -------------------------------------------------
        if isinstance(f, ast.Attribute) and f.attr in ("wait", "notify",
                                                       "notify_all"):
            lk = self._resolve_lock(f.value)
            if lk is not None and self._is_condition(lk):
                self._cv_op(f.attr, lk, held, in_while, call.lineno)
                return
        # Blocking registry ----------------------------------------------------
        desc = self._blocking_desc(call, d)
        if desc is not None:
            self.summary.blockers.append((desc, held, call.lineno))
            if held:
                self.an.blocking.append(RawFinding(
                    self.info.rel, call.lineno, self.fname,
                    f"blocking {desc} while holding "
                    f"{'/'.join(sorted(_short(h) for h in held))} — every "
                    f"thread contending for the lock stalls behind it; "
                    f"move it outside the `with` block"))
            return
        # Call-graph edge ------------------------------------------------------
        callees = self._resolve_call_target(call)
        if callees:
            keys = tuple((rel, qual) for rel, qual, _ in callees)
            label = d or (f.attr if isinstance(f, ast.Attribute) else "?")
            self.summary.calls.append((keys, held, call.lineno, label))

    def _blocking_desc(self, call: ast.Call, d: Optional[str]
                       ) -> Optional[str]:
        if d == "open" or (d is not None and d.endswith(".open")):
            return "open()"
        if d is not None and "." in d:
            mod, attr = d.rsplit(".", 1)
            if (mod, attr) in BLOCKING_DOTTED:
                return f"{d}()"
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr in BLOCKING_ATTRS:
                return f".{f.attr}()"
            rtypes = self._expr_types(f.value)
            for tname, methods in BLOCKING_TYPED.items():
                if tname in rtypes and f.attr in methods:
                    return f"{tname}.{f.attr}()"
        return None

    def _resolve_call_target(self, call: ast.Call
                             ) -> List[Tuple[str, str, ast.AST]]:
        f = call.func
        out: List[Tuple[str, str, ast.AST]] = []
        if isinstance(f, ast.Name):
            # bare call: same-module function, from-import, or class ctor
            fn = self.info.functions.get(f.id)
            if fn is not None:
                return [(self.info.rel, f.id, fn)]
            chain = self.info.from_names.get(f.id)
            if chain is not None:
                mod = self.an._module_rel(chain[0])
                if mod is not None:
                    got = self.an._resolve_func(mod, chain[1])
                    if got is not None:
                        return [got]
            for ci in self.an._class_named(f.id):
                init = ci.methods.get("__init__")
                if init is not None:
                    out.append((ci.rel, f"{ci.name}.__init__", init))
            if self.ci is not None:
                # same-class ctor/class reference
                pass
            return out
        if not isinstance(f, ast.Attribute):
            return out
        recv = f.value
        # module-attribute call: alias.func(...)
        rd = _dotted(recv)
        if rd is not None:
            mod_dotted = self.info.mod_aliases.get(rd)
            if mod_dotted is None and rd in self.info.from_names:
                base, name = self.info.from_names[rd]
                mod_dotted = f"{base}.{name}"
            if mod_dotted is not None:
                mod = self.an._module_rel(mod_dotted)
                if mod is not None:
                    got = self.an._resolve_func(mod, f.attr)
                    if got is not None:
                        return [got]
                    # class method through a module alias: mod.Class? rare
        # typed method call
        rtypes = self._expr_types(recv)
        for tname in sorted(rtypes):
            for ci in self.an._class_named(tname):
                m = ci.methods.get(f.attr)
                if m is not None:
                    out.append((ci.rel, f"{ci.name}.{f.attr}", m))
        return out

    # -- events ------------------------------------------------------------

    def _note_acquire(self, lk: LockInfo, held: FrozenSet[str],
                      line: int) -> None:
        self.summary.acquires.append((lk.canonical, line))
        for h in held:
            if h != lk.canonical and (h, lk.canonical) not in self.an.edges:
                self.an.edges[(h, lk.canonical)] = EdgeWitness(
                    self.info.rel, line, self.qualname)

    def _cv_op(self, op: str, lk: LockInfo, held: FrozenSet[str],
               in_while: bool, line: int) -> None:
        name = _short(lk.id)
        if lk.canonical not in held:
            self.an.cv.append(RawFinding(
                self.info.rel, line, self.fname,
                f"{name}.{op}() without holding the condition — "
                f"{'wait' if op == 'wait' else 'notify'} requires the lock "
                f"(RuntimeError at runtime); take `with {name}:` first"))
            return
        others = held - {lk.canonical}
        if op == "wait" and others:
            self.an.blocking.append(RawFinding(
                self.info.rel, line, self.fname,
                f"{name}.wait() releases only its own lock — "
                f"{'/'.join(sorted(_short(h) for h in others))} stays held "
                f"for the whole sleep (classic nested-cv deadlock shape); "
                f"restructure so the wait holds one lock"))
        if op == "wait" and not in_while:
            self.an.cv.append(RawFinding(
                self.info.rel, line, self.fname,
                f"{name}.wait() outside a while-predicate loop — spurious "
                f"wakeups and an ignored wait(timeout) return value make "
                f"the guarded predicate unchecked; use `while not <pred>: "
                f"{name}.wait(...)`"))

    # -- kill-safety region scan -------------------------------------------

    def _kill_scan(self, with_node: ast.AST, acquired: List[LockInfo]) -> None:
        """Torn-state hazard inside one `with <lock>` region: ≥2 guarded
        mutations with a yield point (faults.check / raise ReplicaKilled)
        between them — the kill releases the lock (with = try/finally)
        with the invariant half-published."""
        events: List[Tuple[int, str]] = []  # (line, 'mut'|'yield')
        # Manual stack walk so nested def/lambda bodies are PRUNED (their
        # mutations run at call time, not inside this locked region).
        stack: List[ast.AST] = [with_node]
        region: List[ast.AST] = []
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not with_node:
                continue
            region.append(node)
            stack.extend(ast.iter_child_nodes(node))
        for node in region:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if _self_attr(t, self.self_name):
                        events.append((node.lineno, "mut"))
                        break
            elif isinstance(node, ast.AugAssign):
                if _self_attr(node.target, self.self_name):
                    events.append((node.lineno, "mut"))
            elif isinstance(node, ast.Raise):
                d = _dotted(node.exc.func) if isinstance(node.exc, ast.Call) \
                    else (_dotted(node.exc) if node.exc is not None else None)
                if d is not None and d.rsplit(".", 1)[-1] == "ReplicaKilled":
                    events.append((node.lineno, "yield"))
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "check" \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in _FAULTS_ALIASES:
                    events.append((node.lineno, "yield"))
        events.sort()
        muts = [ln for ln, k in events if k == "mut"]
        if len(muts) < 2:
            return
        for ln, k in events:
            if k != "yield":
                continue
            before = sum(1 for m in muts if m < ln)
            after = sum(1 for m in muts if m > ln)
            if before >= 1 and after >= 1:
                names = "/".join(sorted(_short(lk.id) for lk in acquired))
                self.an.kill.append(RawFinding(
                    self.info.rel, ln, self.fname,
                    f"kill/yield point between {before + after} mutations "
                    f"of state guarded by {names} — a ReplicaKilled or "
                    f"injected fault here releases the lock with the "
                    f"invariant half-published (torn state read by "
                    f"failover); make the region one mutation or move the "
                    f"yield point out"))
                return


# ---------------------------------------------------------------------------
# Standalone builders (lockprof checker, chaos harness, tests)
# ---------------------------------------------------------------------------


def build_analysis(files: Iterable[Tuple[str, str]]) -> ConcurrencyAnalysis:
    """Analysis over explicit ``(abs_path, repo_relative)`` pairs."""
    an = ConcurrencyAnalysis()
    for path, rel in files:
        with open(path) as fp:
            src = fp.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue
        an.add_file(rel, tree)
    an.finalize()
    return an


def build_repo_analysis(root: Optional[str] = None) -> ConcurrencyAnalysis:
    """Whole-repo analysis over ``fairify_tpu/`` (the lockprof ground truth)."""
    from fairify_tpu.lint.core import iter_py_files, repo_root

    return build_analysis(iter_py_files(root or repo_root()))

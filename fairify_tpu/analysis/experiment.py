"""The full experiment pipeline: verify → localize → repair → route → audit.

Reproduces the reference's experiment drivers
(``src/AC/Verify-AC-experiment-new2.py:562-794`` and the detect_bias/new_model
stages they feed) as one composable function over this framework's parts:

1. run the verification sweep for one model (partition verdict memo);
2. collect validated counterexample pairs;
3. localize biased neurons from the pairs (``src/AC/detect_bias.py:205-302``);
4. repair: masked fine-tune on the biased neurons *and/or* two-stage
   counterexample retraining (``src/AC/new_model.py:179-263``);
5. hybrid-route test points by partition verdict
   (``Verify-AC-experiment-new2.py:613-638``);
6. audit original vs fairer vs hybrid with group metrics + causal
   discrimination rates (``:653-787``).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from fairify_tpu.analysis import causal as causal_mod
from fairify_tpu.analysis import hybrid as hybrid_mod
from fairify_tpu.analysis import localize as localize_mod
from fairify_tpu.analysis import repair as repair_mod
from fairify_tpu.data import loaders
from fairify_tpu.models import mlp as mlp_mod
from fairify_tpu.verify import sweep as sweep_mod
from fairify_tpu.verify.config import SweepConfig


@dataclass
class ExperimentResult:
    report: sweep_mod.ModelReport
    ce_pairs: List[Tuple[np.ndarray, np.ndarray]]
    localization: Optional[localize_mod.BiasLocalization]
    fairer_net: object
    metrics: Dict[str, dict] = field(default_factory=dict)
    causal_rates: Dict[str, float] = field(default_factory=dict)
    # Verdict profile of the REPAIRED model over the same grid — the
    # reference's verified-repair story (UNSAT regions must exist for the
    # hybrid router to be meaningful) — plus routing counts and the
    # asserted success criteria.
    fairer_verdicts: Optional[Dict[str, int]] = None
    routing: Optional[Dict[str, int]] = None
    success: Optional[Dict[str, bool]] = None


def repair_success(
    metrics: Dict[str, dict],
    causal_rates: Dict[str, float],
    accuracy_floor: Optional[float] = None,
    group_tol: Optional[float] = None,
) -> Dict[str, bool]:
    """The reference pipeline's own bar, asserted (VERDICT r2 weak #3).

    The reference judges its AC-3 → AC-16 repair by *improving* these
    numbers (``src/AC/new_model.py:248-260``): causal rate down, DI toward
    1, |SPD|/|EOD|/|AOD| not worse, accuracy above the floor.  Returns one
    boolean per criterion plus the conjunction under ``passed``.
    """
    before, after = metrics["original"], metrics["fairer"]
    if accuracy_floor is None:
        # Same derivation as counterexample_retrain's checkpoint guard —
        # both sides share repair_mod's helpers so the bars cannot diverge.
        accuracy_floor = repair_mod.derive_accuracy_floor(before["accuracy"])
    tol = group_tol if group_tol is not None else repair_mod.GROUP_TOL
    out = {
        "causal_rate_down": causal_rates.get("fairer", np.inf)
        <= causal_rates.get("original", 0.0),
        "di_toward_1": repair_mod.di_not_worse(
            after["disparate_impact"], before["disparate_impact"], tol),
        "spd_not_worse": repair_mod.magnitude_not_worse(
            after["statistical_parity_difference"],
            before["statistical_parity_difference"], tol),
        "eod_not_worse": repair_mod.magnitude_not_worse(
            after["equal_opportunity_difference"],
            before["equal_opportunity_difference"], tol),
        "aod_not_worse": repair_mod.magnitude_not_worse(
            after["average_odds_difference"],
            before["average_odds_difference"], tol),
        "accuracy_floor": after["accuracy"] >= accuracy_floor,
    }
    out["passed"] = all(out.values())
    return out


def run_experiment(
    net,
    cfg: SweepConfig,
    model_name: str,
    dataset: Optional[loaders.LoadedDataset] = None,
    repair_mode: str = "retrain",  # 'masked' | 'retrain' | 'both'
    top_k_neurons: int = 5,
    causal_samples: int = 2000,
    verify_repaired: bool = True,
    mesh=None,
) -> ExperimentResult:
    ds = dataset or loaders.load(cfg.dataset)
    query = cfg.query()
    report = sweep_mod.verify_model(net, cfg, model_name=model_name, dataset=ds, mesh=mesh)

    pairs = [o.counterexample for o in report.outcomes if o.counterexample]
    pa_idx = [query.columns.index(a) for a in query.protected]

    loc = localize_mod.localize(net, pairs, pa_idx, top_k=top_k_neurons) if pairs else None

    fairer = net
    if pairs and repair_mode in ("masked", "both") and loc and loc.ranked:
        targets = [(l, j) for l, j, _ in loc.ranked]
        fairer = repair_mod.masked_repair(
            fairer, targets, ds.X_train, ds.y_train, epochs=3
        ).net
    if pairs and repair_mode in ("retrain", "both"):
        fairer = repair_mod.counterexample_retrain(
            fairer, ds.X_train, ds.y_train, pairs, ds.X_test, ds.y_test,
            protected_col=pa_idx[0],
        ).net

    # Verdict profile of the repaired model over the same grid: the repair's
    # *verifiable* effect (certified-fair UNSAT regions must appear for the
    # hybrid story to be non-degenerate), mirroring the reference re-running
    # its driver on the repaired AC-16.
    fairer_verdicts = None
    if verify_repaired and fairer is not net:
        rep_cfg = cfg.with_(result_dir=cfg.result_dir.rstrip("/") + "-repaired")
        fairer_report = sweep_mod.verify_model(
            fairer, rep_cfg, model_name=f"{model_name}-repaired",
            dataset=ds, mesh=mesh)
        fairer_verdicts = fairer_report.counts

    # Hybrid routing over the sweep's own partition grid + verdict memo.
    _, lo, hi = sweep_mod.build_partitions(cfg)
    attempted = len(report.outcomes)
    verdicts = [o.verdict for o in report.outcomes]
    pa_col = pa_idx[0]
    metrics_out, routing_rep = hybrid_mod.evaluate_hybrid(
        ds.X_test, ds.y_test, pa_col, net, fairer,
        lo[:attempted], hi[:attempted], verdicts,
    )
    routing = {"fair": routing_rep.routed_fair,
               "original": routing_rep.routed_original,
               "miss": routing_rep.routed_miss}

    # Black-box causal audit of all three predictors on the query domain.
    dlo, dhi = query.domain.lo_hi()
    hybrid_fn = lambda X: hybrid_mod.hybrid_predict(
        X, net, fairer, lo[:attempted], hi[:attempted], verdicts
    ).predictions
    causal_rates = {}
    for name, pred in (
        ("original", lambda X: np.asarray(mlp_mod.predict(net, jnp.asarray(X, jnp.float32)))),
        ("fairer", lambda X: np.asarray(mlp_mod.predict(fairer, jnp.asarray(X, jnp.float32)))),
        ("hybrid", hybrid_fn),
    ):
        causal_rates[name] = causal_mod.causal_discrimination(
            pred, dlo.astype(np.int64), dhi.astype(np.int64), pa_col,
            min_samples=200, max_samples=causal_samples,
        ).rate

    return ExperimentResult(
        report=report,
        ce_pairs=pairs,
        localization=loc,
        fairer_net=fairer,
        metrics=metrics_out,
        causal_rates=causal_rates,
        fairer_verdicts=fairer_verdicts,
        routing=routing,
        success=repair_success(metrics_out, causal_rates) if fairer is not net else None,
    )

"""Post-verification analysis & repair (the reference's L4 layer).

Covers SURVEY.md §2.3: group fairness metrics (an AIF360-equivalent suite in
numpy/jax — the reference imports ``aif360``), the causal-discrimination
black-box tester, biased-neuron localization, masked gradient repair,
two-stage counterexample retraining, and the hybrid fair/original router.
"""

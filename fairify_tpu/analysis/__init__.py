"""Post-verification analysis & repair (the reference's L4 layer) and the
jaxpr/IR-level static-analysis suite.

L4 (SURVEY.md §2.3): group fairness metrics (an AIF360-equivalent suite in
numpy/jax — the reference imports ``aif360``), the causal-discrimination
black-box tester, biased-neuron localization, masked gradient repair,
two-stage counterexample retraining, and the hybrid fair/original router.

IR suite (DESIGN.md §11 "IR-level passes", ``fairify_tpu lint --ir``):
:mod:`.avals` (representative avals + per-kernel specs), :mod:`.ir` (the
shared lowered-registry traversal), :mod:`.passes_host` /
:mod:`.passes_sound` / :mod:`.passes_recompile` / :mod:`.passes_buffers`
(the four passes), and :mod:`.irlint` (the ``fairify_tpu.lint`` rule
adapters).  None of these import at package-import time — the L4 layer
stays importable without lowering any kernels.
"""

"""Jaxpr-level kernel view + one cached traversal for the IR passes.

The AST lint engine (PR 6) sees Python source; this module sees what XLA
sees.  :class:`KernelIR` lowers one ``obs_jit`` kernel to its closed jaxpr
under the representative avals of :mod:`fairify_tpu.analysis.avals` —
through :meth:`ObsJit.lowered_for_analysis`, the same explicit AOT path the
compile registry uses, minus the accounting (analysis must never pollute
``xla_compiles`` or the kernel stats) — and precomputes everything every
pass needs:

* the recursive equation list (sub-jaxprs of ``scan``/``cond``/``pjit``/
  custom calls flattened in),
* the flat dynamic-leaf list with tree keystrs, aligned 1:1 with the
  jaxpr's invars (dead-argument attribution by name, not index),
* the ground-truth executable-cache signature key (and one per declared
  production variant),
* lazily, the compiled executable's ``memory_analysis()`` (buffer pass
  cross-check).

:class:`IRContext` builds the whole registry once and is shared by all
four pass rules — the "one cached traversal" contract: tracing all 19
kernels costs ~3 s on CPU, so each pass iterating its own lowering would
blow the 30 s sweep budget four times over.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

from fairify_tpu.analysis import avals as avals_mod


def iter_eqns(jaxpr, _seen=None) -> Iterable:
    """Every equation of ``jaxpr`` including all nested sub-jaxprs.

    Sub-jaxprs hide in eqn params (``jaxpr``/``branches``/``cond_jaxpr``/
    ``call_jaxpr``…) as either open jaxprs or ClosedJaxpr wrappers; the
    walk dedupes by id so shared closures are visited once.
    """
    if _seen is None:
        _seen = set()
    for eqn in jaxpr.eqns:
        yield eqn
        for pv in eqn.params.values():
            vals = pv if isinstance(pv, (list, tuple)) else [pv]
            for v in vals:
                inner = getattr(v, "jaxpr", None)
                if inner is not None:
                    j = inner if hasattr(inner, "eqns") else inner.jaxpr
                    if id(j) not in _seen:
                        _seen.add(id(j))
                        yield from iter_eqns(j, _seen)
                elif hasattr(v, "eqns") and id(v) not in _seen:
                    _seen.add(id(v))
                    yield from iter_eqns(v, _seen)


def aval_bytes(aval) -> int:
    """Byte size of one abstract value (0 when it has no array layout,
    e.g. extended PRNG-key dtypes whose itemsize is opaque)."""
    try:
        n = 1
        for s in aval.shape:
            n *= int(s)
        return n * int(aval.dtype.itemsize)
    except Exception:
        return 0


@dataclass
class KernelIR:
    """One kernel's lowered view + spec metadata (see module docstring)."""

    name: str
    path: str  # repo-relative source file of the wrapped function
    line: int  # def line of the wrapped function
    function: str  # attribution key (wrapped function's __name__)
    spec: Optional[avals_mod.KernelSpec] = None
    closed_jaxpr: Any = None
    lower_error: Optional[str] = None
    statics: Tuple = ()
    signature_key: Any = None
    #: [(keystr, aval)] aligned with closed_jaxpr.jaxpr.invars.
    leaves: List[Tuple[str, Any]] = field(default_factory=list)
    #: Variant desc → (signature_key | None, same_exec declaration).
    variant_keys: Dict[str, Tuple[Any, bool]] = field(default_factory=dict)
    #: Runtime stats of the live ObsJit (None for fixture kernels).
    stats: Any = None
    jit_kwargs: Dict[str, Any] = field(default_factory=dict)
    _compiled: Any = None
    _compile_error: Optional[str] = None

    # -- derived views ----------------------------------------------------
    def eqns(self) -> Iterable:
        return iter_eqns(self.closed_jaxpr.jaxpr) if self.closed_jaxpr \
            else ()

    def consts(self) -> list:
        return list(self.closed_jaxpr.consts) if self.closed_jaxpr else []

    def arg_bytes(self) -> int:
        return sum(aval_bytes(v.aval)
                   for v in self.closed_jaxpr.jaxpr.invars)

    def out_bytes(self) -> int:
        return sum(aval_bytes(getattr(v, "aval", None)) if hasattr(
            getattr(v, "aval", None), "shape") else 0
            for v in self.closed_jaxpr.jaxpr.outvars)

    def largest_intermediate(self) -> Tuple[int, str]:
        """(bytes, 'prim:aval') of the biggest single equation output —
        the jaxpr-derived temp-buffer estimate the buffer pass
        cross-checks against ``memory_analysis()``."""
        big, desc = 0, ""
        for eqn in self.eqns():
            for ov in eqn.outvars:
                av = getattr(ov, "aval", None)
                if av is not None and hasattr(av, "shape"):
                    nb = aval_bytes(av)
                    if nb > big:
                        big = nb
                        desc = f"{eqn.primitive.name}:{av.str_short()}"
        return big, desc

    def dead_invars(self) -> List[Tuple[str, Any]]:
        """Top-level invars no equation consumes (keystr, aval).

        Jaxprs are lexically scoped, so an argument used only inside a
        ``scan``/``cond``/``pjit`` body still appears in that call
        equation's invars — the top-level scan is exact for top-level
        deadness (deadness *inside* an inner call is the inner kernel's
        own report).
        """
        if self.closed_jaxpr is None:
            return []
        used = set()
        for eqn in self.closed_jaxpr.jaxpr.eqns:
            for iv in eqn.invars:
                if not _is_literal(iv):
                    used.add(id(iv))
        # An argument returned verbatim IS consumed — that case is the
        # passthrough finding, not a dead argument ("drop it" would be
        # wrong advice for a value the caller reads back).
        for ov in self.closed_jaxpr.jaxpr.outvars:
            if not _is_literal(ov):
                used.add(id(ov))
        out = []
        invars = self.closed_jaxpr.jaxpr.invars
        for i, v in enumerate(invars):
            if id(v) not in used:
                ks = self.leaves[i][0] if i < len(self.leaves) else f"[{i}]"
                out.append((ks, v.aval))
        return out

    def passthrough_outputs(self) -> List[str]:
        """Outputs that are verbatim inputs (a pointless round-trip)."""
        if self.closed_jaxpr is None:
            return []
        inv = {id(v): i for i, v in
               enumerate(self.closed_jaxpr.jaxpr.invars)}
        out = []
        for v in self.closed_jaxpr.jaxpr.outvars:
            if id(v) in inv:
                i = inv[id(v)]
                ks = self.leaves[i][0] if i < len(self.leaves) else f"[{i}]"
                out.append(ks)
        return out

    # -- compiled view (lazy; buffer pass only) ---------------------------
    def memory_analysis(self):
        """``memory_analysis()`` of the compiled executable, or None.

        Compiled lazily and cached; every failure mode (backend without
        the analysis, compile error) degrades to None — the cross-check
        is an extra gauge, never a gate on its own availability.
        """
        if self._compiled is None and self._compile_error is None \
                and self.closed_jaxpr is not None and self._lowered is not None:
            try:
                self._compiled = self._lowered.compile()
            except Exception as exc:  # pragma: no cover - backend-specific
                self._compile_error = f"{type(exc).__name__}: {exc}"
        if self._compiled is None:
            return None
        try:
            return self._compiled.memory_analysis()
        except Exception:  # pragma: no cover - backend-specific
            return None

    _lowered: Any = None

    # -- constructors -----------------------------------------------------
    @classmethod
    def from_obs_jit(cls, kernel, spec: avals_mod.KernelSpec,
                     world: avals_mod.AnalysisWorld,
                     include_stats: bool = False) -> "KernelIR":
        """``include_stats=True`` attaches the kernel's LIVE process stats
        (fallback-only detection in the recompile pass) — for interactive
        diagnosis of a running process.  The lint gate leaves it off:
        process-cumulative stats depend on what else ran first (chaos
        tests inject compile faults), and a repo gate must be a function
        of the repo, not of test ordering.
        """
        fn = getattr(kernel, "__wrapped__", kernel)
        code = fn.__code__
        kir = cls(name=kernel.name, path=_rel(code.co_filename),
                  line=code.co_firstlineno, function=fn.__name__,
                  spec=spec,
                  stats=getattr(kernel, "stats", None) if include_stats
                  else None,
                  jit_kwargs=dict(getattr(kernel, "_jit_kwargs", {}) or {}))
        try:
            args, kwargs = spec.build(world)
            traced = kernel.lowered_for_analysis(*args, **kwargs)
            kir.closed_jaxpr = traced.jaxpr
            kir._lowered = traced.lower()
            kir.signature_key = kernel.signature_key(*args, **kwargs)
            _, _, kir.statics = kernel._split(args, kwargs)
            kir.leaves = _leaf_paths(kernel, args, kwargs)
        except Exception as exc:
            kir.lower_error = f"{type(exc).__name__}: {exc}"
            return kir
        for var in spec.variants:
            try:
                vargs, vkwargs = var.build(world)
                vkey = kernel.signature_key(*vargs, **vkwargs)
            except Exception:
                vkey = None
            kir.variant_keys[var.desc] = (vkey, var.same_exec)
        return kir

    @classmethod
    def from_fn(cls, fn, args, kwargs=None, static_argnames=(),
                name: Optional[str] = None,
                spec: Optional[avals_mod.KernelSpec] = None,
                **jit_kwargs) -> "KernelIR":
        """Lower a plain function the way the registry kernels are lowered
        — the entry the fixture corpus (and ad-hoc tooling) uses.  Wraps
        with an UNREGISTERED ObsJit so signature keys and the split logic
        are the real ones, without polluting :func:`obs.compile.kernels`.
        """
        from fairify_tpu.obs.compile import ObsJit

        kernel = ObsJit(fn, name=name or f"fixture.{fn.__name__}",
                        static_argnames=static_argnames, register=False,
                        **jit_kwargs)
        kwargs = kwargs or {}
        spec = spec or avals_mod.KernelSpec(kernel.name,
                                            lambda w: (args, kwargs))
        code = getattr(fn, "__code__", None)
        kir = cls(name=kernel.name,
                  path=_rel(code.co_filename) if code else "<fixture>",
                  line=code.co_firstlineno if code else 0,
                  function=getattr(fn, "__name__", "<fixture>"),
                  spec=spec, stats=kernel.stats,
                  jit_kwargs=dict(jit_kwargs))
        try:
            traced = kernel.lowered_for_analysis(*args, **kwargs)
            kir.closed_jaxpr = traced.jaxpr
            kir._lowered = traced.lower()
            kir.signature_key = kernel.signature_key(*args, **kwargs)
            _, _, kir.statics = kernel._split(args, kwargs)
            kir.leaves = _leaf_paths(kernel, args, kwargs)
        except Exception as exc:
            kir.lower_error = f"{type(exc).__name__}: {exc}"
            return kir
        for var in spec.variants:
            try:
                vargs, vkwargs = var.build(None)
                vkey = kernel.signature_key(*vargs, **vkwargs)
            except Exception:
                vkey = None
            kir.variant_keys[var.desc] = (vkey, var.same_exec)
        return kir


def _is_literal(v) -> bool:
    return v.__class__.__name__ == "Literal"


def _leaf_paths(kernel, args, kwargs) -> List[Tuple[str, Any]]:
    import jax.tree_util as jtu

    dyn_args, dyn_kwargs, _ = kernel._split(args, kwargs)
    flat, _ = jtu.tree_flatten_with_path((dyn_args, dyn_kwargs))
    return [(jtu.keystr(path), leaf) for path, leaf in flat]


def _rel(path: str) -> str:
    from fairify_tpu.lint.core import repo_root

    try:
        return os.path.relpath(path, repo_root()).replace(os.sep, "/")
    except ValueError:  # pragma: no cover - cross-drive on win
        return path


def kernel_in_scope(kernel) -> bool:
    """True iff the kernel's wrapped function lives under ``fairify_tpu/``.

    The IR suite audits the repo's kernels — the same path-prefix scope
    the AST rules use.  Kernels registered by test files, fixtures, or
    scratch scripts (anything outside the package) are out of scope, so
    the repo gate is independent of which tests ran first in the process.
    """
    fn = getattr(kernel, "__wrapped__", kernel)
    code = getattr(fn, "__code__", None)
    if code is None:
        return False
    return _rel(code.co_filename).startswith("fairify_tpu/")


class IRContext:
    """Lowered view of the whole obs_jit registry, built once, shared.

    Importing the kernel modules is what populates the registry — the
    constructor imports exactly the modules the registry contract names
    (``verify.engine`` / ``verify.sweep`` / ``verify.pruning`` /
    ``ops.lattice``), then lowers every registered kernel under the one
    :class:`avals.AnalysisWorld`.  ``missing_specs`` names kernels that
    registered without a spec — the recompile pass turns those into
    findings, so a new kernel cannot silently dodge IR analysis.
    """

    def __init__(self, include_stats: bool = False):
        import time

        t0 = time.perf_counter()
        # Registry population: the four kernel-bearing modules.
        import fairify_tpu.ops.lattice  # noqa: F401
        import fairify_tpu.verify.engine  # noqa: F401
        import fairify_tpu.verify.pruning  # noqa: F401
        import fairify_tpu.verify.sweep  # noqa: F401
        from fairify_tpu.obs import compile as obs_compile

        specs = avals_mod.kernel_specs()
        world = avals_mod.AnalysisWorld()
        self.world = world
        self.kernels: List[KernelIR] = []
        self.missing_specs: List[Any] = []
        for name, kernel in sorted(obs_compile.kernels().items()):
            if not kernel_in_scope(kernel):
                continue  # test/scratch kernels: outside the repo scope
            spec = specs.get(name)
            if spec is None:
                self.missing_specs.append(kernel)
                continue
            self.kernels.append(KernelIR.from_obs_jit(
                kernel, spec, world, include_stats=include_stats))
        self.unlowered_specs = sorted(set(specs) - set(
            obs_compile.kernels()))
        self.build_s = time.perf_counter() - t0


_SHARED: Dict[str, IRContext] = {}


def shared_context() -> IRContext:
    """The process-wide cached context all four pass rules share."""
    if "ctx" not in _SHARED:
        _SHARED["ctx"] = IRContext()
    return _SHARED["ctx"]

"""Causal-discrimination tester: black-box fairness rate with CI stopping.

Re-implements ``src/AC/metrics.py:40-264`` (``CausalDiscriminationDetector``)
TPU-first: where the reference calls ``model.predict`` per PA value per
sample inside a Python loop (``:229-241``), here each round draws a *batch*
of non-protected assignments, sweeps every PA value for the whole batch in
one device forward pass, and applies the same Wald-interval stopping rule
(``_check_stopping_condition``, ``:243-257``).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence, Tuple

import numpy as np
from scipy import stats as scipy_stats


@dataclass
class CausalResult:
    rate: float
    tested: int
    discriminatory: int
    interval: Tuple[float, float]
    examples: list


def _wald_interval(successes: int, trials: int, conf: float):
    """Normal-approximation CI, as the reference's scipy-based rule."""
    if trials == 0:
        return 0.0, 1.0
    p = successes / trials
    z = scipy_stats.norm.ppf(0.5 + conf / 2.0)
    half = z * np.sqrt(p * (1 - p) / trials)
    return max(0.0, p - half), min(1.0, p + half)


def causal_discrimination(
    predict_batch: Callable[[np.ndarray], np.ndarray],
    lo: Sequence[int],
    hi: Sequence[int],
    pa_index,
    conf: float = 0.99,
    max_error: float = 0.01,
    min_samples: int = 100,
    max_samples: int = 50_000,
    batch_size: int = 512,
    rng: Optional[np.random.Generator] = None,
    keep_examples: int = 100,
    max_combos: int = 4096,
) -> CausalResult:
    """Causal discrimination rate of a black-box classifier.

    ``pa_index`` is one attribute index or a sequence of them.  A sampled
    assignment of the non-protected attributes is *discriminatory* if
    sweeping the protected attribute(s) over the full cartesian product of
    their [lo, hi] ranges changes the prediction (``causal_discrimination``,
    ``src/AC/metrics.py:101-168``; the attribute-set case is the joint sweep
    of ``discrimination_search``, ``:170-227``).  Stops when the Wald
    interval at ``conf`` is narrower than ``2·max_error`` (after
    ``min_samples``), like ``_check_stopping_condition`` (``:243-257``).
    """
    rng = rng or np.random.default_rng(0)
    lo = np.asarray(lo, dtype=np.int64)
    hi = np.asarray(hi, dtype=np.int64)
    d = lo.shape[0]
    idx = np.atleast_1d(np.asarray(pa_index, dtype=np.int64))
    V = int(np.prod([hi[k] - lo[k] + 1 for k in idx]))
    if V > max_combos:  # before meshgrid materializes the product
        raise ValueError(
            f"joint PA sweep of {V} combinations exceeds max_combos="
            f"{max_combos}; narrow the attribute set or ranges")
    grids = np.meshgrid(*(np.arange(lo[k], hi[k] + 1) for k in idx),
                        indexing="ij")
    combos = np.stack([g.ravel() for g in grids], axis=1)  # (V, |idx|)

    tested = 0
    disc = 0
    examples = []
    while tested < max_samples:
        n = min(batch_size, max_samples - tested)
        x = rng.integers(lo[None, :], hi[None, :] + 1, size=(n, d))
        sweep = np.repeat(x[:, None, :], V, axis=1).astype(np.float32)
        sweep[:, :, idx] = combos[None, :, :]
        preds = np.asarray(predict_batch(sweep.reshape(n * V, d))).reshape(n, V)
        flips = (preds != preds[:, :1]).any(axis=1)
        for i in np.where(flips)[0][: max(0, keep_examples - len(examples))]:
            examples.append(x[i].copy())
        disc += int(flips.sum())
        tested += n
        if tested >= min_samples:
            lo_ci, hi_ci = _wald_interval(disc, tested, conf)
            if (hi_ci - lo_ci) / 2.0 <= max_error:
                break
    lo_ci, hi_ci = _wald_interval(disc, tested, conf)
    return CausalResult(
        rate=disc / tested if tested else 0.0,
        tested=tested,
        discriminatory=disc,
        interval=(lo_ci, hi_ci),
        examples=examples,
    )


def discrimination_search(
    predict_batch: Callable[[np.ndarray], np.ndarray],
    lo: Sequence[int],
    hi: Sequence[int],
    pa_indices: Sequence[int],
    **kw,
) -> dict:
    """Per-attribute causal rates with superset pruning.

    Mirrors ``discrimination_search`` (``src/AC/metrics.py:170-227``): test
    singletons first; a multi-attribute set whose subset already discriminates
    above threshold is skipped.  Here limited to singletons + pairs, which is
    what the reference CLI exercises.
    """
    results = {}
    flagged = set()
    for i in pa_indices:
        res = causal_discrimination(predict_batch, lo, hi, i, **kw)
        results[(i,)] = res
        if res.rate > kw.get("max_error", 0.01):
            flagged.add(i)
    for i in pa_indices:
        for j in pa_indices:
            if j <= i or i in flagged or j in flagged:
                continue
            # Joint sweep over the (i, j) value product — one batch per
            # round, every combination for every sampled base assignment.
            res = causal_discrimination(predict_batch, lo, hi, (i, j), **kw)
            results[(i, j)] = res
    return results

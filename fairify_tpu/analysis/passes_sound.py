"""IR pass ``ir-soundness``: certify-path kernels vs the sound-ops allowlist.

A float UNSAT certificate is only sound under the repo's error model:
f32 arithmetic whose round-off is absorbed by outward widening
(``ops.interval.SOUND_SLACK_*``, the lattice kernels' per-point roundoff
recurrence) and matmuls pinned to ``Precision.HIGHEST``
(``utils.num.matmul`` — the TPU MXU's default path multiplies in bf16,
which the interval-arithmetic toolbox line of work (PAPERS.md: arxiv
2306.15340) shows breaks interval-bound soundness outright).  The kernels
whose outputs carry verdict weight are named by
``analysis.avals.SOUND_KERNELS``; for exactly those this pass flags, on
the lowered jaxpr:

* **low-precision contractions** — any ``dot_general`` whose precision is
  not HIGHEST (the "fastmath-rewritable reduction": XLA may legally
  rewrite a default-precision contraction into bf16 passes on TPU);
* **float downcasts** — ``convert_element_type`` to a float type with
  fewer mantissa bits (f32→bf16/f16, f64→f32) anywhere inside a bound
  computation, and the ``reduce_precision`` primitive at all;
* **primitives outside the sound-ops allowlist** — the reviewed closure
  of everything the certify kernels legitimately lower to (affine maps,
  lattice decodes, comparisons, structural ops, the CROWN relaxation's
  guarded divide).  A transcendental (``exp``/``log``/``tanh``…) or RNG
  primitive showing up in a certify kernel means bound math drifted
  outside the error model — exactly the non-directed-rounding
  subtract/multiply regime the widening slack cannot be shown to cover.

Attack/sampling kernels are exempt by design: their outputs only propose
counterexamples, which are re-proved in exact rational arithmetic before
any SAT settles.
"""
from __future__ import annotations

from typing import List

from fairify_tpu.analysis.ir import KernelIR

PASS_ID = "ir-soundness"

#: Mantissa bits per float dtype (ordering for downcast detection).
_FBITS = {"float64": 52, "float32": 23, "float16": 10, "bfloat16": 7}

#: The reviewed closure of primitives the certify-path kernels lower to.
#: Assembled from the head inventory of every SOUND_KERNELS jaxpr; grows
#: only by review (a new primitive here is a soundness-model decision,
#: not a formality).  Notable EXCLUSIONS: exp/log/tanh/pow (transcendental
#: round-off is not covered by the additive slack model), random_* (a
#: certify kernel must be deterministic in its inputs), sort/top_k
#: (order-dependent f32 reductions).
SOUND_PRIMS = frozenset({
    # arithmetic under the slack model
    "add", "sub", "mul", "div", "neg", "abs", "sign", "max", "min",
    "dot_general", "reduce_sum", "reduce_max", "reduce_min", "cumsum",
    "rem", "round", "floor", "ceil", "integer_pow",
    # comparisons / boolean structure
    "eq", "ne", "lt", "le", "gt", "ge", "and", "or", "not", "xor",
    "reduce_or", "reduce_and", "select_n", "argmax", "argmin",
    # dtype/structural (downcasts are separately screened)
    "convert_element_type", "broadcast_in_dim", "reshape", "transpose",
    "squeeze", "slice", "dynamic_slice", "dynamic_update_slice",
    "concatenate", "gather", "scatter", "scatter-add", "iota", "rev",
    "pad", "device_put", "copy",
    # control / call structure
    "scan", "while", "cond", "pjit", "closed_call", "custom_jvp_call",
    "custom_vjp_call", "remat",
})


def _precision_ok(prec) -> bool:
    """True iff a dot_general's precision pins the f32-exact MXU path."""
    if prec is None:
        return False
    vals = prec if isinstance(prec, (tuple, list)) else (prec,)
    return all("HIGHEST" in str(p) for p in vals)


def check_kernel(kir: KernelIR) -> List[str]:
    if kir.closed_jaxpr is None or kir.spec is None or not kir.spec.sound:
        return []
    out: List[str] = []
    bad_prec = 0
    downcasts = {}
    outside = {}
    reduce_prec = 0
    for eqn in kir.eqns():
        pname = eqn.primitive.name
        if pname == "dot_general":
            if not _precision_ok(eqn.params.get("precision")):
                bad_prec += 1
        elif pname == "convert_element_type":
            src = getattr(eqn.invars[0].aval.dtype, "name", "")
            dst = getattr(eqn.params.get("new_dtype"), "name", "")
            if src in _FBITS and dst in _FBITS and _FBITS[dst] < _FBITS[src]:
                key = f"{src}->{dst}"
                downcasts[key] = downcasts.get(key, 0) + 1
        elif pname == "reduce_precision":
            reduce_prec += 1
        if pname not in SOUND_PRIMS and pname != "reduce_precision":
            outside[pname] = outside.get(pname, 0) + 1
    if bad_prec:
        out.append(
            f"certify kernel '{kir.name}' contracts {bad_prec} "
            f"dot_general(s) below Precision.HIGHEST — the MXU default is "
            f"bf16-pass rewritable; route every verification matmul "
            f"through utils.num.matmul")
    for key, n in sorted(downcasts.items()):
        out.append(
            f"certify kernel '{kir.name}' downcasts {key} x{n} inside a "
            f"bound computation — mantissa loss is outside the "
            f"SOUND_SLACK error model")
    if reduce_prec:
        out.append(
            f"certify kernel '{kir.name}' applies reduce_precision x"
            f"{reduce_prec} — explicit mantissa truncation on the "
            f"certify path")
    for pname, n in sorted(outside.items()):
        out.append(
            f"certify kernel '{kir.name}' lowers to primitive '{pname}' "
            f"x{n}, outside the sound-ops allowlist — extend "
            f"passes_sound.SOUND_PRIMS only after reviewing its round-off "
            f"against the widening slack model")
    return out

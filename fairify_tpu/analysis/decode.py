"""Counterexample decoding: encoded integer vectors → raw category values.

Re-implements ``decode_counterexample``
(``src/AC/Verify-AC-experiment-new2.py:344-407``): verification operates on
label-encoded/discretized integers; for reporting, each coordinate is mapped
back through the loader's fitted encoder (LabelEncoder classes, KBins bin
edges, passthrough for numeric columns).
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from fairify_tpu.data.loaders import LoadedDataset


def decode_point(ds: LoadedDataset, x: np.ndarray) -> Dict[str, object]:
    """Decode one encoded feature vector to raw values, column by column."""
    out: Dict[str, object] = {}
    cols = ds.feature_columns
    for i, col in enumerate(cols):
        v = x[i]
        enc = ds.encoders.get(col)
        if enc is None:
            out[col] = float(v) if float(v) != int(v) else int(v)
            continue
        if hasattr(enc, "classes_"):  # LabelEncoder
            idx = int(round(float(v)))
            if 0 <= idx < len(enc.classes_):
                out[col] = enc.classes_[idx]
            else:  # outside the fitted range (e.g. RA-shifted x')
                out[col] = f"<{col}:{idx}>"
        elif hasattr(enc, "bin_edges_"):  # KBinsDiscretizer
            edges = enc.bin_edges_[0]
            idx = int(np.clip(round(float(v)), 0, len(edges) - 2))
            out[col] = f"[{edges[idx]:.0f}, {edges[idx + 1]:.0f})"
        else:
            out[col] = float(v)
    return out


def decode_pair(ds: LoadedDataset, x: np.ndarray, xp: np.ndarray) -> List[dict]:
    return [decode_point(ds, np.asarray(x)), decode_point(ds, np.asarray(xp))]


def counterexample_table(ds: LoadedDataset, pairs) -> "object":
    """DataFrame of decoded pairs (rows alternate x / x'), as the reference's
    decoded counterexample CSV (``Verify-AC-experiment-new2.py:383-407``)."""
    import pandas as pd

    rows = []
    for k, (x, xp) in enumerate(pairs):
        for role, vec in (("x", x), ("x'", xp)):
            rec = {"pair": k, "role": role}
            rec.update(decode_point(ds, np.asarray(vec)))
            rows.append(rec)
    return pd.DataFrame(rows)

"""Biased-neuron localization from counterexample pairs.

Re-implements ``src/AC/detect_bias.py:205-302``: for each counterexample pair
(x, x') differing only in the protected attribute, accumulate per-neuron
absolute activation deltas and rank.  The reference builds a Keras
sub-model emitting every layer's activations and loops pairs in Python
(``:209-255``); here it is one vmapped forward over all pairs — the deltas
of every layer for every pair come from a single batched kernel.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from fairify_tpu.models import mlp as mlp_mod


@dataclass
class BiasLocalization:
    scores: List[np.ndarray]  # per layer, (n_l,) accumulated |Δ activation|
    ranked: List[Tuple[int, int, float]]  # (layer, neuron, score), descending
    skipped_pairs: int  # pairs not differing exactly in the PA set


def _check_pair(x: np.ndarray, xp: np.ndarray, pa_idx: Sequence[int]) -> bool:
    """Pair sanity check: differs on PA, equal elsewhere
    (``src/AC/detect_bias.py:226-234`` warns and skips otherwise)."""
    pa = set(int(i) for i in pa_idx)
    for i in range(len(x)):
        if i in pa:
            if x[i] == xp[i]:
                return False
        elif x[i] != xp[i]:
            return False
    return True


def localize(
    net,
    pairs: Sequence[Tuple[np.ndarray, np.ndarray]],
    pa_idx: Sequence[int],
    top_k: int = 10,
) -> BiasLocalization:
    """Rank neurons by accumulated activation difference over CE pairs."""
    valid = [(x, xp) for x, xp in pairs if _check_pair(np.asarray(x), np.asarray(xp), pa_idx)]
    skipped = len(pairs) - len(valid)
    if not valid:
        return BiasLocalization(
            scores=[np.zeros_like(np.asarray(b)) for b in net.biases],
            ranked=[], skipped_pairs=skipped,
        )
    xs = jnp.asarray(np.stack([v[0] for v in valid]), jnp.float32)
    xps = jnp.asarray(np.stack([v[1] for v in valid]), jnp.float32)
    outs_x = mlp_mod.layer_outputs(net, xs)
    outs_p = mlp_mod.layer_outputs(net, xps)
    scores = [
        np.asarray(jnp.abs(a - b).sum(axis=0)) for a, b in zip(outs_x, outs_p)
    ]
    flat = [
        (l, j, float(scores[l][j]))
        for l in range(len(scores) - 1)  # output layer excluded from repair targets
        for j in range(scores[l].shape[0])
    ]
    flat.sort(key=lambda t: -t[2])
    return BiasLocalization(scores=scores, ranked=flat[:top_k], skipped_pairs=skipped)


def global_index_map(layer_sizes: Sequence[int]):
    """Global neuron index ↔ (layer, neuron), as ``detect_bias.py:278-302``."""
    fwd = {}
    rev = {}
    g = 0
    for l, n in enumerate(layer_sizes):
        for j in range(n):
            fwd[g] = (l, j)
            rev[(l, j)] = g
            g += 1
    return fwd, rev

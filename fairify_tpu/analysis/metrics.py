"""Group fairness metrics — native replacement for the AIF360 suite.

The reference computes DI, SPD (mean difference), EOD, AOD, ERD, consistency
and Theil index through ``aif360.metrics`` (``src/CP/Verify-CP.py:398-458``,
``src/AC/new_model.py:49-114``).  Those are closed-form statistics; here they
are direct vectorized implementations (definitions follow AIF360's public
docs/source semantics: privileged group = protected attribute == privileged
value, favorable label = 1).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np


def _groups(protected: np.ndarray, privileged_value: float):
    priv = np.asarray(protected) == privileged_value
    return priv, ~priv


def base_rate(y: np.ndarray) -> float:
    return float(np.mean(np.asarray(y) == 1))


def statistical_parity_difference(y_pred, protected, privileged_value=1) -> float:
    """P(ŷ=1 | unprivileged) − P(ŷ=1 | privileged)."""
    priv, unpriv = _groups(protected, privileged_value)
    return base_rate(np.asarray(y_pred)[unpriv]) - base_rate(np.asarray(y_pred)[priv])


def disparate_impact(y_pred, protected, privileged_value=1) -> float:
    """P(ŷ=1 | unprivileged) / P(ŷ=1 | privileged)."""
    priv, unpriv = _groups(protected, privileged_value)
    p = base_rate(np.asarray(y_pred)[priv])
    u = base_rate(np.asarray(y_pred)[unpriv])
    return float(u / p) if p > 0 else float("inf")


def _rates(y_true, y_pred, sel):
    yt = np.asarray(y_true)[sel]
    yp = np.asarray(y_pred)[sel]
    pos = yt == 1
    neg = yt == 0
    tpr = float(np.mean(yp[pos] == 1)) if pos.any() else 0.0
    fpr = float(np.mean(yp[neg] == 1)) if neg.any() else 0.0
    err = float(np.mean(yp != yt)) if yt.size else 0.0
    return tpr, fpr, err


def equal_opportunity_difference(y_true, y_pred, protected, privileged_value=1) -> float:
    """TPR(unprivileged) − TPR(privileged)."""
    priv, unpriv = _groups(protected, privileged_value)
    tpr_p, _, _ = _rates(y_true, y_pred, priv)
    tpr_u, _, _ = _rates(y_true, y_pred, unpriv)
    return tpr_u - tpr_p


def average_odds_difference(y_true, y_pred, protected, privileged_value=1) -> float:
    """½[(FPRu−FPRp) + (TPRu−TPRp)]."""
    priv, unpriv = _groups(protected, privileged_value)
    tpr_p, fpr_p, _ = _rates(y_true, y_pred, priv)
    tpr_u, fpr_u, _ = _rates(y_true, y_pred, unpriv)
    return 0.5 * ((fpr_u - fpr_p) + (tpr_u - tpr_p))


def error_rate_difference(y_true, y_pred, protected, privileged_value=1) -> float:
    """ERR(unprivileged) − ERR(privileged)."""
    priv, unpriv = _groups(protected, privileged_value)
    _, _, err_p = _rates(y_true, y_pred, priv)
    _, _, err_u = _rates(y_true, y_pred, unpriv)
    return err_u - err_p


def consistency(X, y_pred, n_neighbors: int = 5) -> float:
    """1 − mean |ŷᵢ − mean(ŷ of i's k nearest neighbors)| (AIF360 definition).

    Vectorized kNN on Euclidean distance, matching
    ``aif360.metrics.BinaryLabelDatasetMetric.consistency``.
    """
    from sklearn.neighbors import NearestNeighbors

    X = np.asarray(X, dtype=np.float64)
    y_pred = np.asarray(y_pred).astype(np.float64)
    nbrs = NearestNeighbors(n_neighbors=n_neighbors).fit(X)
    _, idx = nbrs.kneighbors(X)
    return float(1.0 - np.mean(np.abs(y_pred - y_pred[idx].mean(axis=1))))


def f1_score(y_true, y_pred) -> float:
    """Binary F1 (favorable label 1) — the reference's per-partition metric
    CSV carries original/pruned F1 next to accuracy
    (``src/CP/Verify-CP.py:448-451``)."""
    yt = np.asarray(y_true) == 1
    yp = np.asarray(y_pred) == 1
    tp = float(np.sum(yt & yp))
    fp = float(np.sum(~yt & yp))
    fn = float(np.sum(yt & ~yp))
    denom = 2 * tp + fp + fn
    return float(2 * tp / denom) if denom > 0 else 0.0


def theil_index(y_true, y_pred) -> float:
    """Generalized entropy (α=1) of benefit b = ŷ − y + 1 (AIF360 definition)."""
    b = np.asarray(y_pred, dtype=np.float64) - np.asarray(y_true, dtype=np.float64) + 1.0
    mu = b.mean()
    if mu == 0:
        return 0.0
    with np.errstate(divide="ignore", invalid="ignore"):
        terms = np.where(b > 0, (b / mu) * np.log(b / mu), 0.0)
    return float(terms.mean())


@dataclass
class GroupFairnessReport:
    accuracy: float
    disparate_impact: float
    statistical_parity_difference: float
    equal_opportunity_difference: float
    average_odds_difference: float
    error_rate_difference: float
    consistency: float
    theil_index: float

    def as_dict(self) -> dict:
        return dict(self.__dict__)


def group_report(X, y_true, y_pred, protected, privileged_value=1,
                 n_neighbors: int = 5) -> GroupFairnessReport:
    """The reference's per-run metric block (``src/CP/Verify-CP.py:398-458``)."""
    y_true = np.asarray(y_true)
    y_pred = np.asarray(y_pred)
    return GroupFairnessReport(
        accuracy=float(np.mean(y_true == y_pred)),
        disparate_impact=disparate_impact(y_pred, protected, privileged_value),
        statistical_parity_difference=statistical_parity_difference(
            y_pred, protected, privileged_value),
        equal_opportunity_difference=equal_opportunity_difference(
            y_true, y_pred, protected, privileged_value),
        average_odds_difference=average_odds_difference(
            y_true, y_pred, protected, privileged_value),
        error_rate_difference=error_rate_difference(
            y_true, y_pred, protected, privileged_value),
        consistency=consistency(X, y_pred, n_neighbors),
        theil_index=theil_index(y_true, y_pred),
    )

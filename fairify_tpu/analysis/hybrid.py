"""Hybrid fair/original model routing from per-partition verdicts.

Re-implements the reference's hybrid predictor
(``src/AC/Verify-AC-experiment-new2.py:562-794``): during verification the
per-partition verdicts are memoized; at inference an input is routed to the
*fairer* model if its partition was SAT (bias proven there), to the original
if UNSAT, and to the original on a miss or UNKNOWN.  The reference scans the
memo linearly per point (``find_partition_result_for_point:587-611``); here
membership of all points in all partitions is one broadcast box test, and
both models run one batched forward each.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from fairify_tpu.models import mlp as mlp_mod


@dataclass
class HybridReport:
    predictions: np.ndarray
    routed_fair: int
    routed_original: int
    routed_miss: int


def route_points(X: np.ndarray, lo: np.ndarray, hi: np.ndarray,
                 verdicts: Sequence[str]) -> np.ndarray:
    """Partition index of each point (first containing box), −1 on miss."""
    X = np.asarray(X, dtype=np.float64)
    inside = (X[None, :, :] >= lo[:, None, :]) & (X[None, :, :] <= hi[:, None, :])
    member = inside.all(axis=2)  # (P, N)
    any_hit = member.any(axis=0)
    first = member.argmax(axis=0)
    return np.where(any_hit, first, -1)


def hybrid_predict(
    X: np.ndarray,
    original,
    fairer,
    lo: np.ndarray,
    hi: np.ndarray,
    verdicts: Sequence[str],
) -> HybridReport:
    """Route each row: SAT partition → fairer model, else original
    (``hybrid_predict``, ``Verify-AC-experiment-new2.py:613-638``)."""
    idx = route_points(X, lo, hi, verdicts)
    verdict_arr = np.asarray(list(verdicts))
    use_fair = np.zeros(X.shape[0], dtype=bool)
    hit = idx >= 0
    use_fair[hit] = verdict_arr[idx[hit]] == "sat"

    Xj = jnp.asarray(np.asarray(X), jnp.float32)
    pred_orig = np.asarray(mlp_mod.predict(original, Xj)).astype(int)
    pred_fair = np.asarray(mlp_mod.predict(fairer, Xj)).astype(int)
    preds = np.where(use_fair, pred_fair, pred_orig)
    return HybridReport(
        predictions=preds,
        routed_fair=int(use_fair.sum()),
        routed_original=int((hit & ~use_fair).sum()),
        routed_miss=int((~hit).sum()),
    )


def evaluate_hybrid(
    X, y, protected_col: int,
    original, fairer,
    lo, hi, verdicts,
    privileged_value=1,
) -> Tuple[Dict[str, dict], HybridReport]:
    """Accuracy + group metrics for original/fairer/hybrid side by side
    (``Verify-AC-experiment-new2.py:653-787``), plus the routing report
    (one ``hybrid_predict`` call serves both — the partition-membership
    broadcast is the expensive part on adult-scale grids)."""
    from fairify_tpu.analysis import metrics as gm

    Xj = jnp.asarray(np.asarray(X), jnp.float32)
    prot = np.asarray(X)[:, protected_col]
    routing = hybrid_predict(X, original, fairer, lo, hi, verdicts)
    out = {}
    preds = {
        "original": np.asarray(mlp_mod.predict(original, Xj)).astype(int),
        "fairer": np.asarray(mlp_mod.predict(fairer, Xj)).astype(int),
        "hybrid": routing.predictions,
    }
    for name, p in preds.items():
        out[name] = gm.group_report(X, y, p, prot, privileged_value).as_dict()
    return out, routing

"""Exact rational certification of dead-neuron masks (host-side).

Replaces the reference's per-neuron Z3 "singular verification"
(``utils/prune.py:276-364``) with a closed-form exact computation — and the
replacement is not an approximation but an equivalence:

Each reference query asks, for neuron *n* of layer *ℓ*: "is there a point in
the constraint box with pre-activation > 0?".  Its constraint set is exactly
an axis-aligned box — the integer input domain for ℓ=0
(``input_domain_constraint``, ``utils/prune.py:253-263``) or the previous
layer's interval box for ℓ>0 (``intermediate_domain_constraint``,
``utils/prune.py:266-273``) — and the objective ``w·x + b`` is linear.  The
maximum of a linear function over a box is attained at the sign-split corner,
which is precisely the interval-arithmetic upper bound; for ℓ=0 the box
corners are integers, so integrality adds nothing.  Therefore the Z3 verdict
equals the sign of the exact-rational IBP upper bound, computed here with
`fractions.Fraction` (float32 weights are dyadic rationals, so the conversion
is exact).  No SMT solver is needed, and unlike the float32 TPU bounds this
pass cannot suffer round-off: it is the soundness anchor of pruning.

The TPU float bounds (``fairify_tpu.ops.interval``) act as the fast filter;
this pass certifies (or vetoes) every neuron the filter proposes to prune.
"""
from __future__ import annotations

from fractions import Fraction
from typing import List, Sequence, Tuple

import numpy as np

_ZERO = Fraction(0)


def _layer_interval(
    w: np.ndarray, b: np.ndarray, lb: List[Fraction], ub: List[Fraction]
) -> Tuple[List[Fraction], List[Fraction]]:
    """Exact sign-split interval image of ``x @ w + b`` over the box [lb, ub].

    The single soundness-critical inner loop, shared by the bounds pass and
    the certification pass (mirrors ``neuron_bounds``, ``utils/prune.py:132-149``).
    """
    wf = [[Fraction(float(v)) for v in row] for row in np.asarray(w, dtype=np.float64)]
    bf = [Fraction(float(v)) for v in np.asarray(b, dtype=np.float64)]
    lo_l, hi_l = [], []
    for j in range(len(bf)):
        mn = bf[j]
        mx = bf[j]
        for i in range(len(wf)):
            wij = wf[i][j]
            if wij < 0:
                mn += wij * ub[i]
                mx += wij * lb[i]
            else:
                mn += wij * lb[i]
                mx += wij * ub[i]
        lo_l.append(mn)
        hi_l.append(mx)
    return lo_l, hi_l


def _relu_box(
    lo_l: List[Fraction], hi_l: List[Fraction], dead_row: np.ndarray | None
) -> Tuple[List[Fraction], List[Fraction]]:
    """Post-activation box: ReLU clamp, with dead neurons pinned to [0, 0]."""
    lb = [
        _ZERO if (dead_row is not None and dead_row[j] > 0.5) else max(_ZERO, v)
        for j, v in enumerate(lo_l)
    ]
    ub = [
        _ZERO if (dead_row is not None and dead_row[j] > 0.5) else max(_ZERO, v)
        for j, v in enumerate(hi_l)
    ]
    return lb, ub


def _input_box(lo: Sequence[int], hi: Sequence[int]):
    return [Fraction(int(v)) for v in lo], [Fraction(int(v)) for v in hi]


def exact_network_bounds(
    weights: Sequence[np.ndarray],
    biases: Sequence[np.ndarray],
    lo: Sequence[int],
    hi: Sequence[int],
    alive: Sequence[np.ndarray] | None = None,
):
    """Exact ws/pl bounds per layer over the integer input box [lo, hi].

    Mirrors ``neuron_bounds`` (``utils/prune.py:105-164``) in rational
    arithmetic.  ``alive`` masks (1 = alive) pin pruned neurons to [0, 0],
    matching excision.  Returns (ws_lb, ws_ub, pl_lb, pl_ub) as nested lists
    of Fractions.
    """
    n = len(weights)
    lb, ub = _input_box(lo, hi)
    ws_lb, ws_ub, pl_lb, pl_ub = [], [], [], []
    for l in range(n):
        lo_l, hi_l = _layer_interval(weights[l], biases[l], lb, ub)
        ws_lb.append(lo_l)
        ws_ub.append(hi_l)
        if l == n - 1:
            pl_lo, pl_hi = lo_l, hi_l
        else:
            dead_row = None
            if alive is not None:
                dead_row = 1.0 - np.asarray(alive[l], dtype=np.float64)
            pl_lo, pl_hi = _relu_box(lo_l, hi_l, dead_row)
        pl_lb.append(pl_lo)
        pl_ub.append(pl_hi)
        lb, ub = pl_lo, pl_hi
    return ws_lb, ws_ub, pl_lb, pl_ub


def certify_dead_masks(
    weights: Sequence[np.ndarray],
    biases: Sequence[np.ndarray],
    lo: Sequence[int],
    hi: Sequence[int],
    proposed_dead: Sequence[np.ndarray],
) -> List[np.ndarray]:
    """Exact-rational veto of a proposed dead-mask set.

    A proposed-dead neuron is certified iff its *exact* pre-activation upper
    bound over the box is ≤ 0, where the bound is computed on the network
    with previously certified layers' masks applied (layer-by-layer, like the
    reference's sequential sweep).  Uncertifiable proposals are revived, so
    the returned masks are sound regardless of float round-off on device.

    The output layer is never dead (``utils/prune.py:235-236``).
    """
    from fairify_tpu.ops import exact_native

    native = exact_native.certify_dead(weights, biases, lo, hi, proposed_dead)
    if native is not None:
        return native[: len(proposed_dead)]
    n = len(weights)
    certified = [np.zeros_like(np.asarray(d), dtype=np.float64) for d in proposed_dead]
    lb, ub = _input_box(lo, hi)
    for l in range(n - 1):
        lo_l, hi_l = _layer_interval(weights[l], biases[l], lb, ub)
        proposed = np.asarray(proposed_dead[l])
        for j in range(len(lo_l)):
            if proposed[j] > 0.5 and hi_l[j] <= 0:
                certified[l][j] = 1.0
        lb, ub = _relu_box(lo_l, hi_l, certified[l])
    return [np.asarray(c, dtype=np.float32) for c in certified]

"""Planet-style triangle-relaxation LP and the complete sign BaB built on it.

The decisive certificate for the AC-7-class residue (deep nets whose logit is
one-signed over the box but whose CROWN/β-CROWN bound gap stays ~3 units):
relax every unstable ReLU with the triangle (Ehlers 2017 "Planet") envelope,
solve one small LP (≤ ~260 vars on the zoo's nets, milliseconds in HiGHS),
and branch on the neuron whose LP solution most violates the exact ReLU
semantics.  With only ~15-25 unstable neurons per partition box, the tree
closes in tens of nodes where the reference's Z3 spent its 100 s soft
timeout and round 2's device β-CROWN frontier burned 2,000+ s without
converging (``PERF.md`` AC-7 rows; ``/root/reference/src/AC/Verify-AC.py``
run, BASELINE.md AC7: ~half the attempted partitions UNKNOWN).

Division of labour with the device path: XLA computes the *batched* CROWN
pre-activation bounds for every box in one launch (`ops.crown.crown_bounds`);
the host solves the per-box LPs — the same split as the reference's
TPU-pruning + host-Z3 design, with HiGHS in the solver seat.

Evidence class: f64 LP with scale-aware margin — identical posture to
``engine._leaf_sign_lp`` (which remains the fully-resolved special case of
this relaxation), NOT exact rational arithmetic.  Certificates from here are
audited by the certificate-attack harness like every other UNSAT.
"""
from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import numpy as np


def _lp_margin(obj_scale: float) -> float:
    """Certification margin against f64 accumulation + HiGHS tolerances.

    ``obj_scale`` must bound the *objective magnitude range* (Σ|w_out|·|h|
    over the variable bounds, plus |b_out|), not just the coefficient sums:
    HiGHS feasibility/duality tolerances act on the solved system's scale,
    so on wide integer domains (variables ~10⁶) the optimum can be off by
    ~tol × scale — a margin blind to the variable magnitudes would certify
    through that noise.
    """
    return 1e-5 + 1e-6 * max(obj_scale, 1.0)


class TriangleLP:
    """Reusable triangle-relaxation tableau for one box of one network.

    Variables: input x (d) then post-activations h_k per hidden layer.
    Pre-activations are eliminated (z_k = W_k·h_{k-1} + b_k substituted into
    every constraint).  Stable/forced neurons contribute equalities or fixed
    bounds; unstable free neurons contribute the triangle:

        h ≥ 0,  h ≥ z,  h ≤ u·(z − l)/(u − l).

    Forcing a neuron active adds ``h = z ∧ z ≥ 0``; inactive adds
    ``h = 0 ∧ z ≤ 0`` — exactly the sign-split semantics of
    ``crown.sign_constrained_output_bounds``, but solved to LP optimality
    instead of iterated to a β-ascent fixed point.
    """

    def __init__(self, weights, biases, masks, lo, hi, pre_lb, pre_ub):
        self.d = len(lo)
        self.nh = len(weights) - 1
        self.sizes = [int(w.shape[1]) for w in weights[: self.nh]]
        self.W = [np.asarray(w, np.float64) for w in weights]
        self.b = [np.asarray(b, np.float64) for b in biases]
        self.alive = [np.asarray(m, np.float64) > 0.5 for m in masks[: self.nh]]
        self.lo = np.asarray(lo, np.float64)
        self.hi = np.asarray(hi, np.float64)
        self.pre_lb = [np.asarray(p, np.float64) for p in pre_lb]
        self.pre_ub = [np.asarray(p, np.float64) for p in pre_ub]
        self.off = [self.d]
        for s in self.sizes[:-1]:
            self.off.append(self.off[-1] + s)
        self.nvar = self.d + sum(self.sizes)
        self.out_w = np.asarray(weights[self.nh], np.float64)[:, 0]
        self.out_b = float(np.asarray(biases[self.nh], np.float64)[0])

    def _prev_span(self, k: int) -> Tuple[int, int]:
        return (0, self.d) if k == 0 else (self.off[k - 1], self.sizes[k - 1])

    def solve_min(self, forced: Sequence[np.ndarray]):
        """Minimise the output logit subject to the relaxation + forcings.

        Returns ``(status, value, x)``: status 'ok' | 'infeasible' | 'error';
        on 'ok', ``value`` is the LP optimum (a sound lower bound of the
        region minimum) and ``x`` the full variable vector for branching.
        """
        from scipy.optimize import linprog

        lb_v = np.empty(self.nvar)
        ub_v = np.empty(self.nvar)
        lb_v[: self.d] = self.lo
        ub_v[: self.d] = self.hi
        A_ub: List[np.ndarray] = []
        b_ub: List[float] = []
        A_eq: List[np.ndarray] = []
        b_eq: List[float] = []
        for k in range(self.nh):
            W, bb = self.W[k], self.b[k]
            l, u = self.pre_lb[k], self.pre_ub[k]
            po, pn = self._prev_span(k)
            o = self.off[k]
            f = forced[k]
            for j in range(self.sizes[k]):
                hv = o + j
                if not self.alive[k][j] or u[j] <= 0.0 or f[j] == -1:
                    lb_v[hv] = ub_v[hv] = 0.0
                    if f[j] == -1 and u[j] > 0.0:  # z ≤ 0
                        row = np.zeros(self.nvar)
                        row[po: po + pn] = W[:, j]
                        A_ub.append(row)
                        b_ub.append(-bb[j])
                    continue
                if l[j] >= 0.0 or f[j] == 1:  # h = z (≥ 0 via var bound)
                    row = np.zeros(self.nvar)
                    row[po: po + pn] = W[:, j]
                    row[hv] = -1.0
                    A_eq.append(row)
                    b_eq.append(-bb[j])
                    lb_v[hv] = max(float(l[j]), 0.0)
                    ub_v[hv] = max(float(u[j]), 0.0)
                    continue
                # Unstable, free: the triangle.
                lb_v[hv] = 0.0
                ub_v[hv] = float(u[j])
                row = np.zeros(self.nvar)  # z − h ≤ 0
                row[po: po + pn] = W[:, j]
                row[hv] = -1.0
                A_ub.append(row)
                b_ub.append(-bb[j])
                s = float(u[j] / (u[j] - l[j]))
                row = np.zeros(self.nvar)  # h − s·z ≤ −s·l
                row[po: po + pn] = -s * W[:, j]
                row[hv] = 1.0
                A_ub.append(row)
                b_ub.append(s * bb[j] - s * float(l[j]))
        c = np.zeros(self.nvar)
        oo, on = self._prev_span(self.nh)
        c[oo: oo + on] = self.out_w
        res = linprog(
            c,
            A_ub=np.stack(A_ub) if A_ub else None,
            b_ub=np.asarray(b_ub) if b_ub else None,
            A_eq=np.stack(A_eq) if A_eq else None,
            b_eq=np.asarray(b_eq) if b_eq else None,
            bounds=np.stack([lb_v, ub_v], axis=1),
            method="highs",
        )
        if res.status == 2:
            return "infeasible", None, None
        if res.status != 0 or res.fun is None:
            return "error", None, None
        return "ok", float(res.fun) + self.out_b, res.x

    def branch_neuron(self, x: np.ndarray, forced) -> Optional[Tuple[int, int]]:
        """Free unstable neuron whose LP point most violates exact ReLU."""
        best, pick = 0.0, None
        for k in range(self.nh):
            l, u = self.pre_lb[k], self.pre_ub[k]
            po, pn = self._prev_span(k)
            prev = x[po: po + pn]
            for j in range(self.sizes[k]):
                if forced[k][j] != 0 or not self.alive[k][j]:
                    continue
                if not (l[j] < 0.0 < u[j]):
                    continue
                z = float(self.W[k][:, j] @ prev + self.b[k][j])
                v = abs(float(x[self.off[k] + j]) - max(0.0, z))
                if v > best:
                    best, pick = v, (k, j)
        return pick

    def margin(self) -> float:
        # Objective magnitude over the relaxation: last-hidden post-activation
        # bounds are [0, max(u, 0)] (post-ReLU), so Σ|w_out|·u⁺ + |b_out|
        # bounds |objective| over the entire feasible set.
        k = self.nh - 1
        h_hi = np.maximum(self.pre_ub[k], 0.0)
        scale = float(np.abs(self.out_w) @ h_hi) + abs(self.out_b)
        return _lp_margin(scale)


def sign_bab_lp(
    weights,
    biases,
    masks,
    lo,
    hi,
    pre_lb,
    pre_ub,
    want_positive: bool,
    max_nodes: int = 4000,
    deadline_s: float = 30.0,
) -> Tuple[str, int]:
    """Complete LP branch-and-bound for a uniform output sign over a box.

    Proves ``min f > margin`` (``want_positive``) or ``max f < −margin``
    over the triangle relaxation, branching on ReLU-violating neurons until
    every branch is certified or refuted.  Returns ``(outcome, nodes)``:

    * ``'certified'`` — every branch cleared the margin: uniform sign proved;
    * ``'refuted'``   — a fully-resolved affine region's true optimum lands
      at or inside the margin band: the conjecture fails (or is too marginal
      for the f64+margin evidence class) and NO sign method can certify it —
      the caller should hand the root to the pair BaB, not retry;
    * ``'budget'``    — node/deadline budget exhausted before closure.

    For ``want_positive=False`` the network is negated (out_w, out_b ↦ −)
    so one minimisation path serves both signs.
    """
    t0 = time.perf_counter()
    lp = TriangleLP(weights, biases, masks, lo, hi, pre_lb, pre_ub)
    if not want_positive:
        lp.out_w = -lp.out_w
        lp.out_b = -lp.out_b
    margin = lp.margin()
    root = [np.zeros(s, dtype=np.int8) for s in lp.sizes]
    stack = [root]
    nodes = 0
    while stack:
        if nodes >= max_nodes or (time.perf_counter() - t0) > deadline_s:
            return "budget", nodes
        forced = stack.pop()
        nodes += 1
        st, val, x = lp.solve_min(forced)
        if st == "infeasible":
            continue  # empty branch region: discharged
        if st == "error":
            return "budget", nodes
        if val > margin:
            continue  # branch certified
        pick = lp.branch_neuron(x, forced)
        if pick is None:
            return "refuted", nodes
        k, j = pick
        for sign in (1, -1):
            child = [f.copy() for f in forced]
            child[k][j] = sign
            stack.append(child)
    return "certified", nodes


class PairTriangleLP:
    """Triangle-relaxation LP of the *pair* network over tied coordinates.

    NOTE: the per-neuron stable/forced/triangle row emission, the
    max-violation branch pick, and the margin posture deliberately mirror
    :class:`TriangleLP` — any change to the relaxation or margin rule must
    be applied to BOTH classes (they are audited in lockstep by the
    certificate-attack harness).

    Two towers of the same net share the free (non-PA) input coordinates;
    tower b's RA dims are shifted by bounded deltas and each tower's PA
    dims are pinned to its assignment.  The flip query for one direction —
    ∃ tied inputs with f_a > 0 ∧ f_b < 0 — becomes emptiness of one
    polyhedron once every unstable ReLU is relaxed by its triangle.  This
    is the *relational* certificate the separate-role and uniform-sign
    paths lack: the shared coordinates tie the towers, so boxes where both
    logits straddle zero but track each other (the relaxed-AC-7 residue)
    still die.

    Variables: s (free dims) + r (RA deltas) + h per hidden layer per
    tower.  Emptiness is certified through the slack LP ``min t`` s.t.
    every row relaxed by t: a minimum above the scale-aware margin is an
    f64+margin proof that the region (with t = 0) is empty — no reliance
    on a float solver's infeasibility status.
    """

    def __init__(self, weights, biases, masks, enc, lo, hi,
                 assign_a, assign_b,
                 pre_lb_a, pre_ub_a, pre_lb_b, pre_ub_b):
        self.nh = len(weights) - 1
        self.sizes = [int(w.shape[1]) for w in weights[: self.nh]]
        self.W = [np.asarray(w, np.float64) for w in weights]
        self.b = [np.asarray(b, np.float64) for b in biases]
        self.alive = [np.asarray(m, np.float64) > 0.5 for m in masks[: self.nh]]
        d = len(lo)
        pa = list(enc.pa_idx)
        ra = list(enc.ra_idx) if enc.eps else []
        self.free = [i for i in range(d) if i not in pa]
        self.n_free = len(self.free)
        self.n_ra = len(ra)
        self.eps = int(enc.eps)
        # Input maps: per tower, x = M·[s, r] + t (rows = input dims).
        nv_in = self.n_free + self.n_ra
        self.maps = []
        for assign, shifted in ((assign_a, False), (assign_b, True)):
            M = np.zeros((d, nv_in))
            t = np.zeros(d)
            for k, i in enumerate(self.free):
                M[i, k] = 1.0
            for k, i in enumerate(pa):
                t[i] = float(assign[k])
            if shifted:
                for k, i in enumerate(ra):
                    M[i, self.n_free + k] = 1.0
            self.maps.append((M, t))
        self.s_lo = np.asarray([lo[i] for i in self.free], np.float64)
        self.s_hi = np.asarray([hi[i] for i in self.free], np.float64)
        self.pre = [(pre_lb_a, pre_ub_a), (pre_lb_b, pre_ub_b)]
        self.nvar = nv_in + 2 * sum(self.sizes)
        self.h_off = []
        o = nv_in
        for tower in range(2):
            offs = []
            for s_ in self.sizes:
                offs.append(o)
                o += s_
            self.h_off.append(offs)
        self.out_w = np.asarray(weights[self.nh], np.float64)[:, 0]
        self.out_b = float(np.asarray(biases[self.nh], np.float64)[0])

    def _margin(self) -> float:
        scale = 0.0
        for tower in range(2):
            h_hi = np.maximum(np.asarray(self.pre[tower][1][self.nh - 1],
                                         np.float64), 0.0)
            scale = max(scale, float(np.abs(self.out_w) @ h_hi) + abs(self.out_b))
        return _lp_margin(scale)

    def solve_direction(self, forced_a, forced_b, flip: bool = False):
        """Slack LP of {towers' triangles ∧ sign constraints}.

        ``flip=False``: f_a ≥ 0 ∧ f_b ≤ 0; ``flip=True``: f_a ≤ 0 ∧
        f_b ≥ 0.  Both are needed when an RA shift is present — the shift
        stays attached to tower b, so swapping the assignment pair does
        NOT mirror the direction (the mirrored witness may live in the
        out-of-box ε band only tower b can reach).

        Returns ``(t_min, margin, x, viol)``: ``t_min > margin`` certifies
        the region empty; otherwise ``x`` is the LP point and ``viol`` the
        max-ReLU-violating free neuron as (tower, layer, neuron), or None
        when fully resolved.  ``(None, ...)`` on solver failure.
        """
        from scipy.optimize import linprog

        nv_in = self.n_free + self.n_ra
        lb_v = np.empty(self.nvar + 1)
        ub_v = np.empty(self.nvar + 1)
        lb_v[: self.n_free] = self.s_lo
        ub_v[: self.n_free] = self.s_hi
        lb_v[self.n_free: nv_in] = -float(self.eps)
        ub_v[self.n_free: nv_in] = float(self.eps)
        lb_v[self.nvar] = 0.0
        ub_v[self.nvar] = np.inf
        A_rows, b_rows = [], []
        forced = (forced_a, forced_b)

        def add(row, rhs, slack=True):
            r = np.zeros(self.nvar + 1)
            r[: len(row)] = row[: len(row)]
            if slack:
                r[self.nvar] = -1.0
            A_rows.append(r)
            b_rows.append(rhs)

        for tower in range(2):
            M, t = self.maps[tower]
            pre_lb, pre_ub = self.pre[tower]
            for k in range(self.nh):
                Wk = self.W[k]
                bk = self.b[k]
                l = np.asarray(pre_lb[k], np.float64)
                u = np.asarray(pre_ub[k], np.float64)
                for j in range(self.sizes[k]):
                    hv = self.h_off[tower][k] + j
                    f = forced[tower][k][j]
                    # Row of z_j over the LP vars.
                    zrow = np.zeros(self.nvar + 1)
                    if k == 0:
                        zin = M.T @ Wk[:, j]  # (nv_in,)
                        zrow[:nv_in] = zin
                        zc = float(t @ Wk[:, j]) + bk[j]
                    else:
                        po = self.h_off[tower][k - 1]
                        zrow[po: po + self.sizes[k - 1]] = Wk[:, j]
                        zc = bk[j]
                    if not self.alive[k][j] or u[j] <= 0.0 or f == -1:
                        lb_v[hv] = ub_v[hv] = 0.0
                        if f == -1 and u[j] > 0.0:
                            add(zrow, -zc)          # z ≤ 0
                        continue
                    if l[j] >= 0.0 or f == 1:
                        r = zrow.copy()
                        r[hv] -= 1.0
                        add(r, -zc)                 # z − h ≤ 0
                        r2 = -zrow
                        r2[hv] += 1.0
                        add(r2, zc)                 # h − z ≤ 0 (equality)
                        lb_v[hv] = max(float(l[j]), 0.0)
                        ub_v[hv] = max(float(u[j]), 0.0)
                        continue
                    lb_v[hv] = 0.0
                    ub_v[hv] = float(u[j])
                    r = zrow.copy()
                    r[hv] -= 1.0
                    add(r, -zc)                     # z − h ≤ 0
                    sl = float(u[j] / (u[j] - l[j]))
                    r2 = -sl * zrow
                    r2[hv] += 1.0
                    add(r2, sl * zc - sl * float(l[j]))  # h ≤ s(z−l)
            # Output sign constraint for this tower (flipped per direction).
            oo = self.h_off[tower][self.nh - 1]
            orow = np.zeros(self.nvar + 1)
            orow[oo: oo + self.sizes[-1]] = self.out_w
            want_pos = (tower == 0) != flip
            if want_pos:
                add(-orow, self.out_b)              # −f ≤ 0  (f ≥ 0)
            else:
                add(orow, -self.out_b)              # f ≤ 0
        c = np.zeros(self.nvar + 1)
        c[self.nvar] = 1.0
        res = linprog(c, A_ub=np.stack(A_rows), b_ub=np.asarray(b_rows),
                      bounds=np.stack([lb_v, ub_v], axis=1), method="highs")
        if res.status != 0 or res.fun is None:
            return None, self._margin(), None, None
        x = res.x
        # Max ReLU violation among free unstable neurons of both towers.
        best, pick = 0.0, None
        for tower in range(2):
            M, t = self.maps[tower]
            pre_lb, pre_ub = self.pre[tower]
            for k in range(self.nh):
                l = np.asarray(pre_lb[k], np.float64)
                u = np.asarray(pre_ub[k], np.float64)
                for j in range(self.sizes[k]):
                    if forced[tower][k][j] != 0 or not self.alive[k][j]:
                        continue
                    if not (l[j] < 0.0 < u[j]):
                        continue
                    if k == 0:
                        zin = M.T @ self.W[0][:, j]
                        z = float(zin @ x[: self.n_free + self.n_ra]
                                  + t @ self.W[0][:, j] + self.b[0][j])
                    else:
                        po = self.h_off[tower][k - 1]
                        z = float(self.W[k][:, j]
                                  @ x[po: po + self.sizes[k - 1]]
                                  + self.b[k][j])
                    v = abs(float(x[self.h_off[tower][k] + j]) - max(0.0, z))
                    if v > best:
                        best, pick = v, (tower, k, j)
        return float(res.fun), self._margin(), x, pick


def pair_bab_lp(
    weights, biases, masks, enc, lo, hi,
    assign_a, assign_b,
    pre_bounds_a, pre_bounds_b,
    max_nodes: int = 2000,
    deadline_s: float = 30.0,
    flip: bool = False,
) -> Tuple[str, int, Optional[Tuple[np.ndarray, np.ndarray]]]:
    """Relational LP BaB for one flip direction of one assignment pair.

    Branches on joint (tower, layer, neuron) ReLU violations until every
    region's slack LP clears the margin ('killed'), a fully-resolved
    feasible region yields an exact-validated lattice witness ('sat'), or
    the budget runs out ('open' — the caller keeps the root undecided).
    ``pre_bounds_*``: per-layer (lb, ub) pre-activation bound lists for
    each tower's role box (CROWN, outward-widened f32 — the usual
    engine evidence class).
    """
    import time as _time

    t0 = _time.perf_counter()
    lp = PairTriangleLP(weights, biases, masks, enc, lo, hi,
                        assign_a, assign_b,
                        pre_bounds_a[0], pre_bounds_a[1],
                        pre_bounds_b[0], pre_bounds_b[1])
    root = ([np.zeros(s, dtype=np.int8) for s in lp.sizes],
            [np.zeros(s, dtype=np.int8) for s in lp.sizes])
    stack = [root]
    nodes = 0
    d = len(lo)
    pa = list(enc.pa_idx)
    ra = list(enc.ra_idx) if enc.eps else []
    while stack:
        if nodes >= max_nodes or (_time.perf_counter() - t0) > deadline_s:
            return "open", nodes, None
        fa, fb = stack.pop()
        nodes += 1
        t_min, margin, x, pick = lp.solve_direction(fa, fb, flip=flip)
        if t_min is None:
            return "open", nodes, None
        if t_min > margin:
            continue  # region certified empty
        if pick is None:
            # Fully resolved, feasible: try an exact lattice witness.
            if x is not None:
                s_vals = np.round(x[: lp.n_free]).astype(np.int64)
                s_vals = np.clip(s_vals, lp.s_lo.astype(np.int64),
                                 lp.s_hi.astype(np.int64))
                xa = np.zeros(d, dtype=np.int64)
                xb = np.zeros(d, dtype=np.int64)
                for k, i in enumerate(lp.free):
                    xa[i] = s_vals[k]
                    xb[i] = s_vals[k]
                for k, i in enumerate(pa):
                    xa[i] = int(assign_a[k])
                    xb[i] = int(assign_b[k])
                for k, i in enumerate(ra):
                    dv = int(round(float(x[lp.n_free + k])))
                    xb[i] += int(np.clip(dv, -lp.eps, lp.eps))
                from fairify_tpu.verify.engine import validate_pair

                wnp = [np.asarray(w) for w in weights]
                bnp = [np.asarray(bb) for bb in biases]
                if validate_pair(wnp, bnp, xa, xb):
                    return "sat", nodes, (xa, xb)
            return "open", nodes, None  # continuous-feasible, no witness
        tower, k, j = pick
        for sign in (1, -1):
            ca = [f.copy() for f in fa]
            cb = [f.copy() for f in fb]
            (ca if tower == 0 else cb)[k][j] = sign
            stack.append((ca, cb))
    return "killed", nodes, None


def clip_box_with_form(D: np.ndarray, c: float, lo: np.ndarray,
                       hi: np.ndarray) -> Tuple[np.ndarray, np.ndarray, bool]:
    """Clip an integer box to where the linear form ``D·s + c`` can be > 0.

    f64 host mirror of the device-BaB domain-clip rule (DESIGN.md §22,
    ``engine._tied_diff_ub_keep``): the form's maximum over the box is
    attained at the corner ``s*_j = hi_j if D_j > 0 else lo_j`` with value
    ``w = Σ max(D_j·hi_j, D_j·lo_j) + c``; moving coordinate ``j`` a
    distance ``t`` off that corner lowers the form by ``|D_j|·t``, so any
    point with the form positive must satisfy ``s_j > hi_j − w/|D_j|``
    (``D_j > 0``) resp. ``s_j < lo_j + w/|D_j|`` (``D_j < 0``).  The
    device kernel inflates ``w`` and the shift with the sound slack before
    applying this rule; the mirror is the EXACT f64 version, so the device
    keep hull must always contain this one — the containment is what
    tests/test_bab.py pins.

    Returns ``(new_lo, new_hi, empty)`` with the keep interval rounded
    INWARD to the lattice (``ceil``/``floor`` — exact, since only strictly
    impossible points are discarded); ``empty=True`` iff ``w ≤ 0`` (no
    point of the box can make the form positive) or the rounded interval
    inverted, in which case the returned box is the untouched input.
    """
    D = np.asarray(D, dtype=np.float64)
    lo64 = np.asarray(lo, dtype=np.float64)
    hi64 = np.asarray(hi, dtype=np.float64)
    w = float(np.sum(np.maximum(D * hi64, D * lo64)) + float(c))
    if w <= 0.0:
        return np.array(lo), np.array(hi), True
    shift = w / np.maximum(np.abs(D), 1e-300)
    keep_lo = np.where(D > 0.0, hi64 - shift, lo64)
    keep_hi = np.where(D < 0.0, lo64 + shift, hi64)
    new_lo = np.maximum(np.asarray(lo), np.ceil(keep_lo).astype(np.int64))
    new_hi = np.minimum(np.asarray(hi), np.floor(keep_hi).astype(np.int64))
    if np.any(new_lo > new_hi):
        return np.array(lo), np.array(hi), True
    return new_lo, new_hi, False

"""ctypes loader for the native exact-arithmetic core (``native/exact_core.cc``).

The C++ library computes exact dyadic-rational signs of network logits and
neuron interval bounds — the same values as the ``fractions.Fraction`` paths
in :mod:`fairify_tpu.ops.exact` and :mod:`fairify_tpu.verify.engine`, two to
three orders of magnitude faster.  It is built from source with ``g++`` on
first use (cached in ``native/build/``); every public helper here returns
``None``-equivalent availability via :func:`available`, and callers fall back
to the pure-Python exact path when the toolchain or library is missing.

Set ``FAIRIFY_TPU_NO_NATIVE=1`` to force the fallback (used by the parity
tests to compare both implementations).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

import numpy as np

_REPO_NATIVE = Path(__file__).resolve().parents[2] / "native"
_SO_NAME = "libfairify_exact.so"
_ABI = 1

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build(src: Path, out: Path) -> bool:
    out.parent.mkdir(parents=True, exist_ok=True)
    tmp = out.with_suffix(f".so.tmp.{os.getpid()}")  # unique per process; replace is atomic
    base = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", str(tmp), str(src)]
    for cmd in (base + ["-fopenmp"], base):
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError):
            continue
        os.replace(tmp, out)
        return True
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("FAIRIFY_TPU_NO_NATIVE"):
            return None
        src = _REPO_NATIVE / "exact_core.cc"
        so = _REPO_NATIVE / "build" / _SO_NAME
        try:
            stale = src.is_file() and (
                not so.is_file() or so.stat().st_mtime < src.stat().st_mtime
            )
            if stale and not _build(src, so):
                return None
            if not so.is_file():
                return None
            lib = ctypes.CDLL(str(so))
            if lib.ft_abi_version() != _ABI:
                return None
        except OSError:
            return None
        lib.ft_forward_signs.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int8),
        ]
        lib.ft_certify_dead.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.ft_certify_dead_batch.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.ft_bound_signs.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int8),
            ctypes.POINTER(ctypes.c_int8),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _pack(weights: Sequence[np.ndarray], biases: Sequence[np.ndarray]):
    sizes = [np.asarray(weights[0]).shape[0]] + [np.asarray(w).shape[1] for w in weights]
    sizes_c = np.ascontiguousarray(sizes, dtype=np.int32)
    w_flat = np.ascontiguousarray(
        np.concatenate([np.asarray(w, dtype=np.float32).ravel() for w in weights])
    )
    b_flat = np.ascontiguousarray(
        np.concatenate([np.asarray(b, dtype=np.float32).ravel() for b in biases])
    )
    return sizes, sizes_c, w_flat, b_flat


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def forward_signs(
    weights: Sequence[np.ndarray], biases: Sequence[np.ndarray], points: np.ndarray
) -> Optional[np.ndarray]:
    """Exact logit signs at integer points; (N, d_in) → int8 (N,), or None."""
    lib = _load()
    if lib is None:
        return None
    sizes, sizes_c, w_flat, b_flat = _pack(weights, biases)
    pts = np.ascontiguousarray(np.asarray(points, dtype=np.int64).reshape(-1, sizes[0]))
    out = np.zeros(pts.shape[0], dtype=np.int8)
    lib.ft_forward_signs(
        len(weights), _ptr(sizes_c, ctypes.c_int), _ptr(w_flat, ctypes.c_float),
        _ptr(b_flat, ctypes.c_float), pts.shape[0], _ptr(pts, ctypes.c_int64),
        _ptr(out, ctypes.c_int8),
    )
    return out


def certify_dead(
    weights: Sequence[np.ndarray],
    biases: Sequence[np.ndarray],
    lo: Sequence[int],
    hi: Sequence[int],
    proposed_dead: Sequence[np.ndarray],
) -> Optional[List[np.ndarray]]:
    """Native twin of :func:`fairify_tpu.ops.exact.certify_dead_masks`."""
    lib = _load()
    if lib is None:
        return None
    sizes, sizes_c, w_flat, b_flat = _pack(weights, biases)
    lo_c = np.ascontiguousarray(np.asarray(lo, dtype=np.int64))
    hi_c = np.ascontiguousarray(np.asarray(hi, dtype=np.int64))
    hidden = sizes[1:-1]
    prop = np.ascontiguousarray(
        np.concatenate(
            [np.asarray(proposed_dead[l], dtype=np.float64).ravel() > 0.5 for l in range(len(hidden))]
        ).astype(np.uint8)
        if hidden
        else np.zeros(0, dtype=np.uint8)
    )
    cert = np.zeros_like(prop)
    lib.ft_certify_dead(
        len(weights), _ptr(sizes_c, ctypes.c_int), _ptr(w_flat, ctypes.c_float),
        _ptr(b_flat, ctypes.c_float), _ptr(lo_c, ctypes.c_int64), _ptr(hi_c, ctypes.c_int64),
        _ptr(prop, ctypes.c_uint8), _ptr(cert, ctypes.c_uint8),
    )
    out, off = [], 0
    for l, n in enumerate(hidden):
        out.append(cert[off : off + n].astype(np.float32))
        off += n
    out.append(np.zeros(sizes[-1], dtype=np.float32))  # output layer never dead
    return out


def certify_dead_batch(
    weights: Sequence[np.ndarray],
    biases: Sequence[np.ndarray],
    lo: np.ndarray,
    hi: np.ndarray,
    proposed_dead: Sequence[np.ndarray],
) -> Optional[List[np.ndarray]]:
    """Batched exact certification over P boxes in one native call.

    ``lo``/``hi``: (P, d_in) int boxes.  ``proposed_dead``: per weight layer,
    (P, n_l) masks.  Returns per-layer (P, n_l) float32 certified masks (the
    output layer all-zero), or None when the library is unavailable.
    """
    lib = _load()
    if lib is None:
        return None
    sizes, sizes_c, w_flat, b_flat = _pack(weights, biases)
    lo_c = np.ascontiguousarray(np.asarray(lo, dtype=np.int64).reshape(-1, sizes[0]))
    hi_c = np.ascontiguousarray(np.asarray(hi, dtype=np.int64).reshape(-1, sizes[0]))
    P = lo_c.shape[0]
    hidden = sizes[1:-1]
    if hidden:
        prop = np.ascontiguousarray(
            np.concatenate(
                [
                    (np.asarray(proposed_dead[l], dtype=np.float64).reshape(P, -1) > 0.5)
                    for l in range(len(hidden))
                ],
                axis=1,
            ).astype(np.uint8)
        )
    else:
        prop = np.zeros((P, 0), dtype=np.uint8)
    cert = np.zeros_like(prop)
    lib.ft_certify_dead_batch(
        len(weights), _ptr(sizes_c, ctypes.c_int), _ptr(w_flat, ctypes.c_float),
        _ptr(b_flat, ctypes.c_float), P, _ptr(lo_c, ctypes.c_int64),
        _ptr(hi_c, ctypes.c_int64), _ptr(prop, ctypes.c_uint8), _ptr(cert, ctypes.c_uint8),
    )
    out, off = [], 0
    for n in hidden:
        out.append(cert[:, off : off + n].astype(np.float32))
        off += n
    out.append(np.zeros((P, sizes[-1]), dtype=np.float32))
    return out


def bound_signs(
    weights: Sequence[np.ndarray],
    biases: Sequence[np.ndarray],
    lo: Sequence[int],
    hi: Sequence[int],
    alive: Optional[Sequence[np.ndarray]] = None,
) -> Optional[Tuple[List[np.ndarray], List[np.ndarray]]]:
    """Exact per-neuron pre-activation bound signs over an integer box.

    Returns (ws_lb_sign, ws_ub_sign) as per-layer int8 arrays, or None.
    """
    lib = _load()
    if lib is None:
        return None
    sizes, sizes_c, w_flat, b_flat = _pack(weights, biases)
    lo_c = np.ascontiguousarray(np.asarray(lo, dtype=np.int64))
    hi_c = np.ascontiguousarray(np.asarray(hi, dtype=np.int64))
    total = sum(sizes[1:])
    lbs = np.zeros(total, dtype=np.int8)
    ubs = np.zeros(total, dtype=np.int8)
    alive_ptr = ctypes.c_void_p(0)
    alive_arr = None
    if alive is not None:
        alive_arr = np.ascontiguousarray(
            np.concatenate(
                [np.asarray(alive[l], dtype=np.float64).ravel() > 0.5 for l in range(len(sizes) - 1)]
            ).astype(np.uint8)
        )
        alive_ptr = ctypes.c_void_p(alive_arr.ctypes.data)
    lib.ft_bound_signs(
        len(weights), _ptr(sizes_c, ctypes.c_int), _ptr(w_flat, ctypes.c_float),
        _ptr(b_flat, ctypes.c_float), _ptr(lo_c, ctypes.c_int64), _ptr(hi_c, ctypes.c_int64),
        alive_ptr, _ptr(lbs, ctypes.c_int8), _ptr(ubs, ctypes.c_int8),
    )
    out_lb, out_ub, off = [], [], 0
    for n in sizes[1:]:
        out_lb.append(lbs[off : off + n].copy())
        out_ub.append(ubs[off : off + n].copy())
        off += n
    return out_lb, out_ub

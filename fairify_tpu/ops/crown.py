"""Batched CROWN backward linear-relaxation bounds for masked ReLU MLPs.

The reference's only bounding device is interval arithmetic
(``utils/prune.py:105-164``); its decision procedure then leans on Z3 to
close the gap.  The native TPU engine instead tightens bounds with CROWN
(backward propagation of linear relaxations, Zhang et al. 2018 — public
algorithm), which is typically 2-10x tighter than IBP on small MLPs and
turns most partitions into one-kernel UNSAT certificates instead of SMT
queries.

Design notes (TPU-first):

* Fully batched: every function takes ``lb``/``ub`` with arbitrary leading
  batch axes (partitions × PA-assignments × roles) and is `vmap`/`jit`
  compatible — the whole branch-and-bound frontier is bounded in one XLA
  launch, all matmuls on the MXU at ``Precision.HIGHEST``.
* Static shapes: pruned neurons participate with slope 0 via the MLP's
  alive masks, never as ragged deletes.
* Soundness: computed in f32 and widened outward like the IBP kernel; the
  engine treats bound-certified verdicts as sound-with-slack and leaf
  evaluations are exact (``fairify_tpu.ops.exact``).

The layer-k pre-activation bounds are computed by a backward pass through
layers k-1..0, each hidden layer relaxed with the standard CROWN ReLU
envelope: upper line ``u/(u-l)·(z-l)``, lower line ``α·z`` with adaptive
``α = 1 if u ≥ |l| else 0``.  Intermediate-layer bounds come from the same
procedure applied depth-by-depth (full backward CROWN, O(L²) small matmuls
— irrelevant next to HBM traffic for these ≤100-wide nets).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from fairify_tpu.models.mlp import MLP
from fairify_tpu.ops.interval import LayerBounds, SOUND_SLACK_ABS, SOUND_SLACK_REL, affine_interval
from fairify_tpu.utils.num import matmul


def _widen(lo: jax.Array, hi: jax.Array):
    slack = SOUND_SLACK_REL * jnp.maximum(jnp.abs(lo), jnp.abs(hi)) + SOUND_SLACK_ABS
    return lo - slack, hi + slack


def _relu_relaxation(lo: jax.Array, hi: jax.Array, mask: jax.Array):
    """Per-neuron CROWN ReLU envelope coefficients.

    Returns (upper_slope, upper_intercept, lower_slope); lower intercept is 0.
    Stable-active neurons get slope 1 / intercept 0, stable-dead (or pruned)
    get 0/0, unstable the triangle relaxation.
    """
    unstable = (lo < 0.0) & (hi > 0.0)
    denom = jnp.where(unstable, hi - lo, 1.0)
    us = jnp.where(unstable, hi / denom, (lo >= 0.0).astype(lo.dtype))
    ui = jnp.where(unstable, -hi * lo / denom, 0.0)
    ls = jnp.where(unstable, (hi >= -lo).astype(lo.dtype), us)
    us = us * mask
    ui = ui * mask
    ls = ls * mask
    return us, ui, ls


def _backward_bounds(params: MLP, k: int, pre_lbs, pre_ubs, in_lb, in_ub,
                     alphas_low=None, alphas_up=None):
    """CROWN bounds on layer-k pre-activations given bounds for layers < k.

    ``in_lb``/``in_ub``: (..., d) input box.  ``pre_lbs[j]``/``pre_ubs[j]``:
    (..., n_j) pre-activation bounds of hidden layer j.  Returns (lo, hi) of
    shape (..., n_k).

    ``alphas_low``/``alphas_up``: optional per-hidden-layer (..., n_j) lower
    ReLU slopes in [0, 1] for unstable neurons — the α of α-CROWN (Xu et
    al. 2021, public algorithm).  ``relu(z) ≥ α·z`` holds for every
    α ∈ [0, 1] when ``lo < 0 < hi``, so *any* values are sound; the
    optimizer below tunes them per box.  ``None`` keeps the adaptive
    heuristic slope.
    """
    w_k = params.weights[k]
    batch = in_lb.shape[:-1]
    n_k = w_k.shape[1]
    # Linear forms: z_k ≥ h_j @ A_low + c_low and z_k ≤ h_j @ A_up + c_up.
    A_low = jnp.broadcast_to(w_k, batch + w_k.shape)
    A_up = A_low
    c_low = jnp.broadcast_to(params.biases[k], batch + (n_k,))
    c_up = c_low
    for j in range(k - 1, -1, -1):
        us, ui, ls = _relu_relaxation(pre_lbs[j], pre_ubs[j], params.masks[j])
        unstable = (pre_lbs[j] < 0.0) & (pre_ubs[j] > 0.0)
        if alphas_low is not None:
            ls_low = jnp.where(unstable, alphas_low[j], ls) * params.masks[j]
        else:
            ls_low = ls
        if alphas_up is not None:
            ls_up = jnp.where(unstable, alphas_up[j], ls) * params.masks[j]
        else:
            ls_up = ls
        # Pass through h_j = relu(z_j): pick relaxation per coefficient sign.
        Ap = jnp.maximum(A_low, 0.0)
        An = jnp.minimum(A_low, 0.0)
        c_low = c_low + matmul(jnp.expand_dims(ui, -2), An)[..., 0, :]
        A_low = Ap * ls_low[..., :, None] + An * us[..., :, None]
        Ap = jnp.maximum(A_up, 0.0)
        An = jnp.minimum(A_up, 0.0)
        c_up = c_up + matmul(jnp.expand_dims(ui, -2), Ap)[..., 0, :]
        A_up = Ap * us[..., :, None] + An * ls_up[..., :, None]
        # Pass through z_j = h_{j-1} @ w_j + b_j.
        w_j, b_j = params.weights[j], params.biases[j]
        c_low = c_low + matmul(jnp.expand_dims(b_j, -2), A_low)[..., 0, :]
        c_up = c_up + matmul(jnp.expand_dims(b_j, -2), A_up)[..., 0, :]
        A_low = matmul(jnp.broadcast_to(w_j, batch + w_j.shape), A_low)
        A_up = matmul(jnp.broadcast_to(w_j, batch + w_j.shape), A_up)
    # Concretize over the input box.
    lo = (
        matmul(jnp.expand_dims(in_lb, -2), jnp.maximum(A_low, 0.0))[..., 0, :]
        + matmul(jnp.expand_dims(in_ub, -2), jnp.minimum(A_low, 0.0))[..., 0, :]
        + c_low
    )
    hi = (
        matmul(jnp.expand_dims(in_ub, -2), jnp.maximum(A_up, 0.0))[..., 0, :]
        + matmul(jnp.expand_dims(in_lb, -2), jnp.minimum(A_up, 0.0))[..., 0, :]
        + c_up
    )
    return lo, hi


def crown_bounds(params: MLP, lb: jax.Array, ub: jax.Array, widen: bool = True) -> LayerBounds:
    """Full-network CROWN pre-activation bounds (tightened against IBP).

    Layer 0 is affine over the box (exact); each deeper layer runs a backward
    pass using the already-computed shallower bounds, then intersects with
    the plain interval bound (CROWN is not uniformly tighter per-neuron, so
    take the elementwise min/max of both).
    """
    n = params.depth
    ws_lb, ws_ub, pl_lb, pl_ub = [], [], [], []
    lo_run, hi_run = lb, ub
    for k in range(n):
        zlo_i, zhi_i = affine_interval(params.weights[k], params.biases[k], lo_run, hi_run)
        if k == 0:
            zlo, zhi = zlo_i, zhi_i
        else:
            zlo_c, zhi_c = _backward_bounds(params, k, ws_lb, ws_ub, lb, ub)
            zlo = jnp.maximum(zlo_i, zlo_c)
            zhi = jnp.minimum(zhi_i, zhi_c)
        if widen:
            zlo, zhi = _widen(zlo, zhi)
        ws_lb.append(zlo)
        ws_ub.append(zhi)
        if k == n - 1:
            plo, phi = zlo, zhi
        else:
            m = params.masks[k]
            plo = jax.nn.relu(zlo) * m
            phi = jax.nn.relu(zhi) * m
        pl_lb.append(plo)
        pl_ub.append(phi)
        lo_run, hi_run = plo, phi
    return LayerBounds(tuple(ws_lb), tuple(ws_ub), tuple(pl_lb), tuple(pl_ub))


def crown_output_bounds(params: MLP, lb: jax.Array, ub: jax.Array, widen: bool = True):
    """CROWN bounds of the scalar output logit over a batch of boxes."""
    bounds = crown_bounds(params, lb, ub, widen=widen)
    return bounds.ws_lb[-1][..., 0], bounds.ws_ub[-1][..., 0]


def alpha_crown_output_bounds(params: MLP, lb: jax.Array, ub: jax.Array,
                              iters: int = 8, widen: bool = True):
    """α-CROWN output-logit bounds: per-box optimized lower ReLU slopes.

    Standard α-CROWN (Xu et al. 2021): intermediate-layer bounds stay fixed
    (plain CROWN), and the final backward pass is re-run with free lower
    slopes ``α ∈ [0, 1]`` for unstable neurons, tuned by signed-gradient
    ascent to maximize the output lower bound and minimize the upper bound
    (separate α sets per direction).  Every iterate is sound — the search
    only moves between valid relaxations — so the result is intersected
    with the unoptimized bound and widened like every other bound kernel.

    Batched over arbitrary leading axes and fully jit-compatible (``iters``
    is static, the loop unrolls).  Typically worthwhile only for the
    branch-and-bound leftovers: several extra backward passes per call.
    """
    bounds = crown_bounds(params, lb, ub, widen=True)
    k = params.depth - 1
    pre_lbs = [bounds.ws_lb[j] for j in range(k)]
    pre_ubs = [bounds.ws_ub[j] for j in range(k)]
    lo0, hi0 = bounds.ws_lb[-1][..., 0], bounds.ws_ub[-1][..., 0]
    if k == 0 or iters <= 0:
        return lo0, hi0

    # Start from the adaptive heuristic slope (what plain CROWN uses).
    init = [jnp.where(pre_ubs[j] >= -pre_lbs[j], 1.0, 0.0) for j in range(k)]
    al = [a for a in init]
    au = [a for a in init]

    def width(al_, au_):
        lo, hi = _backward_bounds(params, k, pre_lbs, pre_ubs, lb, ub,
                                  alphas_low=al_, alphas_up=au_)
        return jnp.sum(hi[..., 0] - lo[..., 0]), (lo[..., 0], hi[..., 0])

    lr = 0.5
    # Track the best *unwidened* optimized bounds; widen once at the end and
    # only then intersect with the (already-widened) plain-CROWN baseline —
    # the result can never be looser than plain CROWN.
    opt_lo = opt_hi = None
    for _ in range(iters):
        (_, (lo, hi)), grads = jax.value_and_grad(width, argnums=(0, 1),
                                                  has_aux=True)(al, au)
        opt_lo = lo if opt_lo is None else jnp.maximum(opt_lo, lo)
        opt_hi = hi if opt_hi is None else jnp.minimum(opt_hi, hi)
        g_al, g_au = grads
        # Signed updates: per-box α gradients decouple (the objective sums
        # over the batch), and sign steps need no per-net learning rate.
        al = [jnp.clip(a - lr * jnp.sign(g), 0.0, 1.0) for a, g in zip(al, g_al)]
        au = [jnp.clip(a - lr * jnp.sign(g), 0.0, 1.0) for a, g in zip(au, g_au)]
        lr *= 0.6
    _, (lo, hi) = width(al, au)
    opt_lo, opt_hi = jnp.maximum(opt_lo, lo), jnp.minimum(opt_hi, hi)
    if widen:
        opt_lo, opt_hi = _widen(opt_lo, opt_hi)
    return jnp.maximum(opt_lo, lo0), jnp.minimum(opt_hi, hi0)

"""Batched CROWN backward linear-relaxation bounds for masked ReLU MLPs.

The reference's only bounding device is interval arithmetic
(``utils/prune.py:105-164``); its decision procedure then leans on Z3 to
close the gap.  The native TPU engine instead tightens bounds with CROWN
(backward propagation of linear relaxations, Zhang et al. 2018 — public
algorithm), which is typically 2-10x tighter than IBP on small MLPs and
turns most partitions into one-kernel UNSAT certificates instead of SMT
queries.

Design notes (TPU-first):

* Fully batched: every function takes ``lb``/``ub`` with arbitrary leading
  batch axes (partitions × PA-assignments × roles) and is `vmap`/`jit`
  compatible — the whole branch-and-bound frontier is bounded in one XLA
  launch, all matmuls on the MXU at ``Precision.HIGHEST``.
* Static shapes: pruned neurons participate with slope 0 via the MLP's
  alive masks, never as ragged deletes.
* Soundness: computed in f32 and widened outward like the IBP kernel; the
  engine treats bound-certified verdicts as sound-with-slack and leaf
  evaluations are exact (``fairify_tpu.ops.exact``).

The layer-k pre-activation bounds are computed by a backward pass through
layers k-1..0, each hidden layer relaxed with the standard CROWN ReLU
envelope: upper line ``u/(u-l)·(z-l)``, lower line ``α·z`` with adaptive
``α = 1 if u ≥ |l| else 0``.  Intermediate-layer bounds come from the same
procedure applied depth-by-depth (full backward CROWN, O(L²) small matmuls
— irrelevant next to HBM traffic for these ≤100-wide nets).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from fairify_tpu.models.mlp import MLP
from fairify_tpu.ops.interval import LayerBounds, SOUND_SLACK_ABS, SOUND_SLACK_REL, affine_interval
from fairify_tpu.utils.num import matmul


def _widen(lo: jax.Array, hi: jax.Array):
    slack = SOUND_SLACK_REL * jnp.maximum(jnp.abs(lo), jnp.abs(hi)) + SOUND_SLACK_ABS
    return lo - slack, hi + slack


def _relu_relaxation(lo: jax.Array, hi: jax.Array, mask: jax.Array):
    """Per-neuron CROWN ReLU envelope coefficients.

    Returns (upper_slope, upper_intercept, lower_slope); lower intercept is 0.
    Stable-active neurons get slope 1 / intercept 0, stable-dead (or pruned)
    get 0/0, unstable the triangle relaxation.
    """
    unstable = (lo < 0.0) & (hi > 0.0)
    denom = jnp.where(unstable, hi - lo, 1.0)
    us = jnp.where(unstable, hi / denom, (lo >= 0.0).astype(lo.dtype))
    ui = jnp.where(unstable, -hi * lo / denom, 0.0)
    ls = jnp.where(unstable, (hi >= -lo).astype(lo.dtype), us)
    us = us * mask
    ui = ui * mask
    ls = ls * mask
    return us, ui, ls


def _backward_linear(params: MLP, k: int, pre_lbs, pre_ubs, batch,
                     alphas_low=None, alphas_up=None,
                     beta_signs=None, betas_low=None, betas_up=None):
    """CROWN linear forms of layer-k pre-activations in terms of the input.

    Backward-propagates through layers k-1..0 and returns
    ``(A_low, c_low, A_up, c_up)`` with ``A_*`` of shape batch + (d, n_k)
    and ``c_*`` of shape batch + (n_k,) such that for every x in the box the
    ``pre_*`` bounds were computed over::

        z_k ≥ x @ A_low + c_low      z_k ≤ x @ A_up + c_up

    ``alphas_low``/``alphas_up``: optional per-hidden-layer (..., n_j) lower
    ReLU slopes in [0, 1] for unstable neurons — the α of α-CROWN (Xu et
    al. 2021, public algorithm).  ``relu(z) ≥ α·z`` holds for every
    α ∈ [0, 1] when ``lo < 0 < hi``, so *any* values are sound; the
    optimizer below tunes them per box.  ``None`` keeps the adaptive
    heuristic slope.

    ``beta_signs``/``betas_low``/``betas_up``: the β of β-CROWN (Wang et
    al. 2021, public algorithm) — per-hidden-layer (..., n_j) arrays
    encoding branch-and-bound split constraints ``s_j · z_j ≥ 0``
    (``beta_signs`` ∈ {−1, 0, +1}; 0 = unconstrained).  By weak duality,
    for any β ≥ 0::

        min_{x ∈ box, s·z(x) ≥ 0} f(x)  ≥  min_{x ∈ box} [f(x) − β·s·z(x)]
        max_{x ∈ box, s·z(x) ≥ 0} f(x)  ≤  max_{x ∈ box} [f(x) + β·s·z(x)]

    so the constraint enters the backward pass as an extra exact-linear
    ``∓β·s`` coefficient on ``z_j`` — no relaxation involved — and the
    multipliers are tunable by gradient ascent exactly like the α's.
    Without β the branch constraint can only tighten *intermediate* bounds
    (the clamps in :func:`sign_constrained_output_bounds`), which leaves the
    final concretization ranging over the whole box and stalls BaB.
    """
    w_k = params.weights[k]
    n_k = w_k.shape[1]
    # Linear forms: z_k ≥ h_j @ A_low + c_low and z_k ≤ h_j @ A_up + c_up.
    A_low = jnp.broadcast_to(w_k, batch + w_k.shape)
    A_up = A_low
    c_low = jnp.broadcast_to(params.biases[k], batch + (n_k,))
    c_up = c_low
    for j in range(k - 1, -1, -1):
        us, ui, ls = _relu_relaxation(pre_lbs[j], pre_ubs[j], params.masks[j])
        unstable = (pre_lbs[j] < 0.0) & (pre_ubs[j] > 0.0)
        if alphas_low is not None:
            ls_low = jnp.where(unstable, alphas_low[j], ls) * params.masks[j]
        else:
            ls_low = ls
        if alphas_up is not None:
            ls_up = jnp.where(unstable, alphas_up[j], ls) * params.masks[j]
        else:
            ls_up = ls
        # Pass through h_j = relu(z_j): pick relaxation per coefficient sign.
        Ap = jnp.maximum(A_low, 0.0)
        An = jnp.minimum(A_low, 0.0)
        c_low = c_low + matmul(jnp.expand_dims(ui, -2), An)[..., 0, :]
        A_low = Ap * ls_low[..., :, None] + An * us[..., :, None]
        Ap = jnp.maximum(A_up, 0.0)
        An = jnp.minimum(A_up, 0.0)
        c_up = c_up + matmul(jnp.expand_dims(ui, -2), Ap)[..., 0, :]
        A_up = Ap * us[..., :, None] + An * ls_up[..., :, None]
        # β split terms: A_* now holds coefficients on z_j, where the
        # constraint s_j·z_j ≥ 0 contributes its exact linear penalty.
        if beta_signs is not None:
            A_low = A_low - (betas_low[j] * beta_signs[j])[..., :, None]
            A_up = A_up + (betas_up[j] * beta_signs[j])[..., :, None]
        # Pass through z_j = h_{j-1} @ w_j + b_j.
        w_j, b_j = params.weights[j], params.biases[j]
        c_low = c_low + matmul(jnp.expand_dims(b_j, -2), A_low)[..., 0, :]
        c_up = c_up + matmul(jnp.expand_dims(b_j, -2), A_up)[..., 0, :]
        A_low = matmul(jnp.broadcast_to(w_j, batch + w_j.shape), A_low)
        A_up = matmul(jnp.broadcast_to(w_j, batch + w_j.shape), A_up)
    return A_low, c_low, A_up, c_up


def _concretize(A_low, c_low, A_up, c_up, in_lb, in_ub):
    """Extreme values of the linear forms over the input box."""
    lo = (
        matmul(jnp.expand_dims(in_lb, -2), jnp.maximum(A_low, 0.0))[..., 0, :]
        + matmul(jnp.expand_dims(in_ub, -2), jnp.minimum(A_low, 0.0))[..., 0, :]
        + c_low
    )
    hi = (
        matmul(jnp.expand_dims(in_ub, -2), jnp.maximum(A_up, 0.0))[..., 0, :]
        + matmul(jnp.expand_dims(in_lb, -2), jnp.minimum(A_up, 0.0))[..., 0, :]
        + c_up
    )
    return lo, hi


def _backward_bounds(params: MLP, k: int, pre_lbs, pre_ubs, in_lb, in_ub,
                     alphas_low=None, alphas_up=None,
                     beta_signs=None, betas_low=None, betas_up=None):
    """CROWN bounds on layer-k pre-activations given bounds for layers < k.

    ``in_lb``/``in_ub``: (..., d) input box.  ``pre_lbs[j]``/``pre_ubs[j]``:
    (..., n_j) pre-activation bounds of hidden layer j.  Returns (lo, hi) of
    shape (..., n_k).
    """
    A_low, c_low, A_up, c_up = _backward_linear(
        params, k, pre_lbs, pre_ubs, in_lb.shape[:-1],
        alphas_low=alphas_low, alphas_up=alphas_up,
        beta_signs=beta_signs, betas_low=betas_low, betas_up=betas_up)
    return _concretize(A_low, c_low, A_up, c_up, in_lb, in_ub)


def _optimize_relaxation(width, init, iters: int, with_beta: bool,
                         lr0: float = 0.5, decay: float = 0.7, lr_b: float = 0.8):
    """Signed-gradient ascent on CROWN relaxation parameters (α and β).

    ``width(al, au, bl, bu) -> (summed_width, (lo, hi))``; ``init`` is the
    per-layer starting α list (the adaptive heuristic slope).  α's clip to
    [0, 1], β's to [0, ∞); every iterate is a valid relaxation so the best
    (lo, hi) across iterates — including the final parameters — is kept.
    Returns ``(lo, hi, al, au)`` with the final α's (for form extraction).

    The ascent runs under ``lax.fori_loop`` so the compiled graph holds ONE
    traced backward pass, not ``iters`` inlined copies — with per-layer
    optimized intermediates the unrolled form is O(iters·L²) backward
    passes and its XLA compile time on the TPU tunnel dwarfed the runtime
    it was meant to save.
    """
    al = [a for a in init]
    au = [a for a in init]
    bl = [jnp.zeros_like(a) for a in init]
    bu = [jnp.zeros_like(a) for a in init]
    # ±inf seeds: iteration 0 evaluates the init params anyway, so a real
    # pre-loop width() call would only duplicate one backward pass.
    _, (lo_s, hi_s) = jax.eval_shape(width, al, au, bl, bu)
    lo0 = jnp.full(lo_s.shape, -jnp.inf, lo_s.dtype)
    hi0 = jnp.full(hi_s.shape, jnp.inf, hi_s.dtype)

    def body(_, carry):
        al, au, bl, bu, best_lo, best_hi, lr = carry
        (_, (lo, hi)), grads = jax.value_and_grad(
            width, argnums=(0, 1, 2, 3), has_aux=True)(al, au, bl, bu)
        best_lo = jnp.maximum(best_lo, lo)
        best_hi = jnp.minimum(best_hi, hi)
        g_al, g_au, g_bl, g_bu = grads
        al = [jnp.clip(a - lr * jnp.sign(g), 0.0, 1.0) for a, g in zip(al, g_al)]
        au = [jnp.clip(a - lr * jnp.sign(g), 0.0, 1.0) for a, g in zip(au, g_au)]
        if with_beta:
            bl = [jnp.maximum(b - lr_b * jnp.sign(g), 0.0)
                  for b, g in zip(bl, g_bl)]
            bu = [jnp.maximum(b - lr_b * jnp.sign(g), 0.0)
                  for b, g in zip(bu, g_bu)]
        return al, au, bl, bu, best_lo, best_hi, lr * decay

    al, au, bl, bu, best_lo, best_hi, _ = jax.lax.fori_loop(
        0, iters, body, (al, au, bl, bu, lo0, hi0, jnp.asarray(lr0, lo_s.dtype)))
    _, (lo, hi) = width(al, au, bl, bu)
    best_lo = jnp.maximum(best_lo, lo)
    best_hi = jnp.minimum(best_hi, hi)
    return best_lo, best_hi, al, au


def crown_bounds(params: MLP, lb: jax.Array, ub: jax.Array, widen: bool = True) -> LayerBounds:
    """Full-network CROWN pre-activation bounds (tightened against IBP).

    Layer 0 is affine over the box (exact); each deeper layer runs a backward
    pass using the already-computed shallower bounds, then intersects with
    the plain interval bound (CROWN is not uniformly tighter per-neuron, so
    take the elementwise min/max of both).
    """
    n = params.depth
    ws_lb, ws_ub, pl_lb, pl_ub = [], [], [], []
    lo_run, hi_run = lb, ub
    for k in range(n):
        zlo_i, zhi_i = affine_interval(params.weights[k], params.biases[k], lo_run, hi_run)
        if k == 0:
            zlo, zhi = zlo_i, zhi_i
        else:
            zlo_c, zhi_c = _backward_bounds(params, k, ws_lb, ws_ub, lb, ub)
            zlo = jnp.maximum(zlo_i, zlo_c)
            zhi = jnp.minimum(zhi_i, zhi_c)
        if widen:
            zlo, zhi = _widen(zlo, zhi)
        ws_lb.append(zlo)
        ws_ub.append(zhi)
        if k == n - 1:
            plo, phi = zlo, zhi
        else:
            m = params.masks[k]
            plo = jax.nn.relu(zlo) * m
            phi = jax.nn.relu(zhi) * m
        pl_lb.append(plo)
        pl_ub.append(phi)
        lo_run, hi_run = plo, phi
    return LayerBounds(tuple(ws_lb), tuple(ws_ub), tuple(pl_lb), tuple(pl_ub))


def crown_output_bounds(params: MLP, lb: jax.Array, ub: jax.Array, widen: bool = True):
    """CROWN bounds of the scalar output logit over a batch of boxes."""
    bounds = crown_bounds(params, lb, ub, widen=widen)
    return bounds.ws_lb[-1][..., 0], bounds.ws_ub[-1][..., 0]


def sign_constrained_output_bounds(params: MLP, lb: jax.Array, ub: jax.Array,
                                   signs, alpha_iters: int = 0):
    """Output bounds under per-neuron activation-sign branch constraints.

    ``signs``: per-hidden-layer (..., n_j) arrays — +1 forces the neuron
    active (pre-activation ≥ 0), −1 forces it inactive (≤ 0), 0 free.  These
    are the branch-and-bound splits of the β-CROWN family (Wang et al. 2021,
    public algorithm), enforced through two mechanisms:

    * **clamps** — the constrained neuron's own interval is clipped
      (``lo ← max(lo, 0)`` when forced active, ``hi ← min(hi, 0)`` when
      inactive), which stabilises its relaxation for deeper layers;
    * **β multipliers** — every backward pass carries the Lagrange penalty
      ``∓β·s·z_j`` of each split (see :func:`_backward_linear`), which is
      what actually transfers the constraint into the *concretized* bound
      (clamps alone leave the final concretization ranging over the whole
      input box and stall BaB — measured on AC-7: lb pinned at −3.18
      regardless of split depth).

    With ``alpha_iters > 0`` every intermediate layer bound is α/β-optimized
    (signed-gradient ascent, β clipped ≥ 0, best iterate kept) — full
    α-CROWN with optimized intermediate bounds, not just an optimized final
    pass.  On deep narrow nets this is the difference between useless and
    decisive: AC-7 (64-32-16-8-4-1) partitions whose plain-CROWN root bound
    is −3.18 certify *at the root* with the optimized pipeline.  Cost is
    O(L²·iters) small matmuls per batch — irrelevant against HBM traffic
    for these ≤100-wide nets, and the whole frontier batches in one launch.

    Returns ``(out_lo, out_hi, feasible, scores, resolved)``:

    * ``out_lo``/``out_hi``: (...,) widened output-logit bounds, valid for
      every input in the box satisfying the sign pattern;
    * ``feasible``: (...,) bool — False when some clamp produced an empty
      interval, i.e. the branch region is provably empty;
    * ``scores``: per-hidden-layer (..., n_j) branch-selection scores — the
      CROWN triangle intercept ``ub·(−lb)/(ub−lb)`` of still-free unstable
      neurons (0 for stable/constrained/pruned ones): BaBSR-style proxy for
      which split removes the most relaxation slack;
    * ``resolved``: per-hidden-layer (..., n_j) int8 — the sign every alive
      neuron is known to have within this branch (+1/−1 from stability or
      the split pattern, 0 = still unstable).  A branch with no unresolved
      neuron defines an affine region; the caller can finish it exactly
      (``verify.engine._leaf_sign_lp``).
    """
    n = params.depth
    ws_lb, ws_ub, feas = [], [], None
    scores, resolved = [], []
    lo_run, hi_run = lb, ub
    sgn = None
    for k in range(n):
        zlo_i, zhi_i = affine_interval(params.weights[k], params.biases[k], lo_run, hi_run)
        if k == 0:
            zlo, zhi = zlo_i, zhi_i
        else:
            if alpha_iters <= 0:
                zlo_c, zhi_c = _backward_bounds(
                    params, k, ws_lb, ws_ub, lb, ub,
                    beta_signs=sgn, betas_low=[jnp.zeros_like(s) for s in sgn],
                    betas_up=[jnp.zeros_like(s) for s in sgn])
            else:

                def width(al_, au_, bl_, bu_, k=k):
                    lo_o, hi_o = _backward_bounds(
                        params, k, ws_lb, ws_ub, lb, ub,
                        alphas_low=al_, alphas_up=au_,
                        beta_signs=sgn, betas_low=bl_, betas_up=bu_)
                    return jnp.sum(hi_o - lo_o), (lo_o, hi_o)

                init = [jnp.where(ws_ub[j] >= -ws_lb[j], 1.0, 0.0)
                        for j in range(k)]
                zlo_c, zhi_c, _, _ = _optimize_relaxation(
                    width, init, alpha_iters, with_beta=True)
            zlo = jnp.maximum(zlo_i, zlo_c)
            zhi = jnp.minimum(zhi_i, zhi_c)
        zlo, zhi = _widen(zlo, zhi)
        if k < n - 1:
            s = signs[k]
            zlo = jnp.where(s > 0, jnp.maximum(zlo, 0.0), zlo)
            zhi = jnp.where(s < 0, jnp.minimum(zhi, 0.0), zhi)
            bad = (zlo > zhi).any(axis=-1)
            feas = bad if feas is None else (feas | bad)
            # Empty interval: collapse to a point so downstream layers stay
            # numerically sane; the feasible flag already excludes the branch.
            zhi = jnp.maximum(zhi, zlo)
            unstable = (zlo < 0.0) & (zhi > 0.0)
            denom = jnp.where(unstable, zhi - zlo, 1.0)
            scores.append(
                jnp.where(unstable, zhi * (-zlo) / denom, 0.0) * params.masks[k])
            resolved.append(jnp.where(
                zlo >= 0.0, 1, jnp.where(zhi <= 0.0, -1, 0)
            ).astype(jnp.int8) * (params.masks[k] > 0.5))
            sgn = [signs[j].astype(lb.dtype) * params.masks[j]
                   for j in range(k + 1)]
        ws_lb.append(zlo)
        ws_ub.append(zhi)
        if k == n - 1:
            break
        m = params.masks[k]
        lo_run = jax.nn.relu(zlo) * m
        hi_run = jax.nn.relu(zhi) * m
    out_lo, out_hi = ws_lb[-1][..., 0], ws_ub[-1][..., 0]
    if feas is None:
        feasible = jnp.ones(out_lo.shape, dtype=bool)
    else:
        feasible = ~feas
    return out_lo, out_hi, feasible, scores, resolved


def alpha_crown_output_bounds(params: MLP, lb: jax.Array, ub: jax.Array,
                              iters: int = 8, widen: bool = True):
    """α-CROWN output-logit bounds: per-box optimized lower ReLU slopes.

    Standard α-CROWN (Xu et al. 2021): intermediate-layer bounds stay fixed
    (plain CROWN), and the final backward pass is re-run with free lower
    slopes ``α ∈ [0, 1]`` for unstable neurons, tuned by signed-gradient
    ascent to maximize the output lower bound and minimize the upper bound
    (separate α sets per direction).  Every iterate is sound — the search
    only moves between valid relaxations — so the result is intersected
    with the unoptimized bound and widened like every other bound kernel.

    Batched over arbitrary leading axes and fully jit-compatible (``iters``
    is static; the ascent runs under ``lax.fori_loop``, see
    ``_optimize_relaxation``).  Typically worthwhile only for the
    branch-and-bound leftovers: several extra backward passes per call.
    """
    bounds = crown_bounds(params, lb, ub, widen=True)
    k = params.depth - 1
    pre_lbs = [bounds.ws_lb[j] for j in range(k)]
    pre_ubs = [bounds.ws_ub[j] for j in range(k)]
    lo0, hi0 = bounds.ws_lb[-1][..., 0], bounds.ws_ub[-1][..., 0]
    if k == 0 or iters <= 0:
        return lo0, hi0

    def width(al_, au_, bl_, bu_):
        lo, hi = _backward_bounds(params, k, pre_lbs, pre_ubs, lb, ub,
                                  alphas_low=al_, alphas_up=au_)
        return jnp.sum(hi[..., 0] - lo[..., 0]), (lo[..., 0], hi[..., 0])

    # Start from the adaptive heuristic slope (what plain CROWN uses); track
    # the best *unwidened* optimized bounds, widen once at the end, and only
    # then intersect with the (already-widened) plain-CROWN baseline — the
    # result can never be looser than plain CROWN.
    init = [jnp.where(pre_ubs[j] >= -pre_lbs[j], 1.0, 0.0) for j in range(k)]
    opt_lo, opt_hi, _, _ = _optimize_relaxation(width, init, iters,
                                                with_beta=False)
    if widen:
        opt_lo, opt_hi = _widen(opt_lo, opt_hi)
    return jnp.maximum(opt_lo, lo0), jnp.minimum(opt_hi, hi0)


def crown_output_form_sets(params: MLP, lb: jax.Array, ub: jax.Array,
                           alpha_iters: int = 0):
    """Output-logit linear forms over the box, for relational certificates.

    Returns ``(form_sets, lo, hi)`` where ``form_sets`` is a list of one or
    two tuples ``(A_low, c_low, A_up, c_up)`` — ``A_*`` of shape (..., d),
    ``c_*`` of shape (...,) — each satisfying, for every x in [lb, ub]::

        f(x) ≥ x·A_low + c_low        f(x) ≤ x·A_up + c_up

    Set 0 is plain CROWN (adaptive heuristic slopes); with ``alpha_iters > 0``
    a second set is added whose lower slopes were α-optimized against the
    output width (final iterate — every iterate is a valid relaxation, so the
    forms are sound; a consumer may take the elementwise best bound across
    sets).  ``lo``/``hi`` are the concretized, outward-widened scalar output
    bounds intersected across sets (matching
    :func:`alpha_crown_output_bounds` semantics).  The forms themselves are
    returned *unwidened*: any certificate derived from them must add its own
    outward slack (see ``_widen``).

    The relational consumer (``verify.engine``) ties the two roles of the
    fairness pair through these forms — bounding f(x) − f(x') over the tied
    pair set — which is strictly tighter than differencing the concretized
    per-role bounds the reference's interval analysis would give
    (``utils/prune.py:105-164``).
    """
    bounds = crown_bounds(params, lb, ub, widen=True)
    k = params.depth - 1
    pre_lbs = [bounds.ws_lb[j] for j in range(k)]
    pre_ubs = [bounds.ws_ub[j] for j in range(k)]
    batch = lb.shape[:-1]
    A_l, c_l, A_u, c_u = _backward_linear(params, k, pre_lbs, pre_ubs, batch)
    plain = (A_l[..., 0], c_l[..., 0], A_u[..., 0], c_u[..., 0])
    lo0, hi0 = bounds.ws_lb[-1][..., 0], bounds.ws_ub[-1][..., 0]
    if k == 0 or alpha_iters <= 0:
        return [plain], lo0, hi0

    def width(al_, au_, bl_, bu_):
        lo, hi = _backward_bounds(params, k, pre_lbs, pre_ubs, lb, ub,
                                  alphas_low=al_, alphas_up=au_)
        return jnp.sum(hi[..., 0] - lo[..., 0]), (lo[..., 0], hi[..., 0])

    init = [jnp.where(pre_ubs[j] >= -pre_lbs[j], 1.0, 0.0) for j in range(k)]
    opt_lo, opt_hi, al, au = _optimize_relaxation(width, init, alpha_iters,
                                                  with_beta=False)
    A_l, c_l, A_u, c_u = _backward_linear(params, k, pre_lbs, pre_ubs, batch,
                                          alphas_low=al, alphas_up=au)
    tuned = (A_l[..., 0], c_l[..., 0], A_u[..., 0], c_u[..., 0])
    lo1, hi1 = _concretize(A_l, c_l, A_u, c_u, lb, ub)
    lo1, hi1 = lo1[..., 0], hi1[..., 0]
    opt_lo, opt_hi = jnp.maximum(opt_lo, lo1), jnp.minimum(opt_hi, hi1)
    opt_lo, opt_hi = _widen(opt_lo, opt_hi)
    return [plain, tuned], jnp.maximum(opt_lo, lo0), jnp.minimum(opt_hi, hi0)


def output_form_stack(params: MLP, lb: jax.Array, ub: jax.Array,
                      alpha_iters: int = 0, n_sets: int = 0):
    """:func:`crown_output_form_sets` with the sets stacked on a static axis.

    A ``lax.scan`` body (the device-BaB segment kernel, DESIGN.md §22)
    cannot carry a Python list whose length depends on runtime values, and
    a consumer that must present ONE signature across configurations needs
    the set axis to have a fixed length.  This wrapper stacks each of the
    four form arrays on a new leading axis of length ``n_sets`` (default:
    however many sets the inner call produced — 1, or 2 when
    ``alpha_iters > 0``).  When the inner call produces fewer sets than
    requested the last set is REPEATED: every set is independently sound,
    so a duplicate can never change a min-over-sets bound nor an
    intersect-over-sets keep hull, and the pad keeps the stacked shape —
    and therefore the compiled executable — identical across configs.

    Returns ``((A_low, c_low, A_up, c_up), lo, hi)`` with ``A_*`` of shape
    ``(n_sets, ..., d)`` and ``c_*`` of shape ``(n_sets, ...)``; ``lo``/
    ``hi`` are the same concretized widened scalar bounds as the inner
    call.
    """
    sets_, lo, hi = crown_output_form_sets(params, lb, ub, alpha_iters)
    want = int(n_sets) if n_sets else len(sets_)
    if want < len(sets_):
        raise ValueError(f"n_sets={want} < {len(sets_)} computed form sets")
    sets_ = sets_ + [sets_[-1]] * (want - len(sets_))
    stacked = tuple(jnp.stack([s[i] for s in sets_]) for i in range(4))
    return stacked, lo, hi

"""Batched interval bound propagation (IBP) over input boxes.

The reference computes per-neuron pre-activation (WS) and post-ReLU (PL)
bounds with a triple Python loop over layers × neurons × inputs
(``utils/prune.py:105-164``).  Here the same sign-split interval arithmetic is
two matmuls per layer — ``lb @ W⁺ + ub @ W⁻`` and ``ub @ W⁺ + lb @ W⁻`` — and
`vmap` lifts it over a batch of boxes (one box per input partition), so the
whole partition grid's bounds are a single MXU-friendly kernel launch.

Soundness note: the reference evaluates these expressions in float64 numpy
(and re-checks them in exact rationals via per-neuron Z3 queries,
``utils/prune.py:276-364``).  On TPU we compute in float32 and widen each
bound by ``SOUND_SLACK`` (relative + absolute outward rounding); the exact
certification pass in :mod:`fairify_tpu.ops.exact` re-derives the final dead
masks in rational arithmetic, so pruning soundness never rests on floats.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from fairify_tpu.models.mlp import MLP
from fairify_tpu.utils.num import matmul

# Outward widening applied to computed bounds to absorb f32 round-off.
SOUND_SLACK_REL = 1e-5
SOUND_SLACK_ABS = 1e-6


class LayerBounds(NamedTuple):
    """Bounds per layer: ws = pre-activation, pl = post-activation."""

    ws_lb: tuple
    ws_ub: tuple
    pl_lb: tuple
    pl_ub: tuple


def affine_interval(w: jax.Array, b: jax.Array, lb: jax.Array, ub: jax.Array):
    """Interval image of ``x @ w + b`` for ``x`` in ``[lb, ub]``.

    Exact (up to rounding) for an affine map: split ``w`` by sign.
    Supports leading batch axes on ``lb``/``ub``.
    """
    wp = jnp.maximum(w, 0.0)
    wn = jnp.minimum(w, 0.0)
    lo = matmul(lb, wp) + matmul(ub, wn) + b
    hi = matmul(ub, wp) + matmul(lb, wn) + b
    return lo, hi


def _widen(lo: jax.Array, hi: jax.Array):
    slack = SOUND_SLACK_REL * jnp.maximum(jnp.abs(lo), jnp.abs(hi)) + SOUND_SLACK_ABS
    return lo - slack, hi + slack


def network_bounds(params: MLP, lb: jax.Array, ub: jax.Array, widen: bool = True) -> LayerBounds:
    """WS/PL interval bounds for every layer given an input box.

    ``lb``/``ub`` may carry leading batch axes (e.g. ``(P, d)`` for P
    partitions).  Masked (pruned) neurons propagate a [0, 0] interval, exactly
    like an excised neuron.  The final layer is linear: its PL bounds equal
    its WS bounds (the reference never applies ReLU there,
    ``utils/GC-1-Model-Functions.py:20``).
    """
    ws_lb, ws_ub, pl_lb, pl_ub = [], [], [], []
    lo, hi = lb, ub
    n = params.depth
    for i, (w, b, m) in enumerate(zip(params.weights, params.biases, params.masks)):
        zlo, zhi = affine_interval(w, b, lo, hi)
        if widen:
            zlo, zhi = _widen(zlo, zhi)
        ws_lb.append(zlo)
        ws_ub.append(zhi)
        if i == n - 1:
            plo, phi = zlo, zhi
        else:
            plo = jax.nn.relu(zlo) * m
            phi = jax.nn.relu(zhi) * m
        pl_lb.append(plo)
        pl_ub.append(phi)
        lo, hi = plo, phi
    return LayerBounds(tuple(ws_lb), tuple(ws_ub), tuple(pl_lb), tuple(pl_ub))


def output_bounds(params: MLP, lb: jax.Array, ub: jax.Array):
    """Interval bounds of the output logit only."""
    bounds = network_bounds(params, lb, ub)
    return bounds.ws_lb[-1][..., 0], bounds.ws_ub[-1][..., 0]


def dead_from_ws_ub(bounds: LayerBounds) -> list:
    """Provably-dead masks from WS upper bounds (1 = dead).

    A hidden neuron with ``ws_ub <= 0`` can never activate anywhere in the
    box — the reference's interval-based pruning criterion
    (``utils/prune.py:226-251``).  The output layer is skipped (all-alive),
    matching ``utils/prune.py:235-236``.
    """
    deads = []
    n = len(bounds.ws_ub)
    for i, ub in enumerate(bounds.ws_ub):
        if i == n - 1:
            deads.append(jnp.zeros_like(ub))
        else:
            deads.append((ub <= 0.0).astype(ub.dtype))
    return deads

"""Phase E — exhaustive integer-lattice decision for finite boxes.

The reference's Z3 query ranges over the *integer lattice* of the partition
box (``ToReal(Int x)`` inputs, ``src/GC/Verify-GC.py:128-143``), so the pair
property is decidable by finite enumeration.  The engine's input-split BaB
diverges on exactly one box class: wide flip-slab boxes whose logit surface
crosses zero throughout (millions of nodes without convergence, e.g.
stress-AC box 768 on AC-1 — a 33M-point shared lattice the BaB burned 3.4M
nodes on).  For those boxes enumeration on the MXU is *cheap*: a 16-8-1 net
over the full lattice is a handful of batched forward launches.

Tunnel-aware layout (the single-chip TPU sits behind a ~MB/s relay):
coordinates are decoded from flat indices **on device** (mixed-radix over
the shared dims, static per-dim gather — no scatter, which stalled XLA's
compiler for minutes), all PA assignments are evaluated in one vmapped
kernel, and flip/margin *detection* also runs on device — each chunk
returns only scalars and a fixed-size margin-index buffer, never the logit
arrays.

Evidence classes (docs/DESIGN.md numeric policy):

* Device pass: f32 at ``Precision.HIGHEST`` with a **per-point rigorous
  roundoff bound** computed alongside the forward from the same ``|W|``
  matmuls (standard running-error analysis, 4× outward on the float32 γ
  constants).  |logit| above its bound ⇒ certain sign.
* Margin points (|logit| ≤ bound) fall back to the host ladder
  ``float64 → exact rational`` — the same posture as
  ``engine.exact_logit_sign``.
* Every SAT verdict is re-proved by ``engine.validate_pair`` in exact
  arithmetic, so SAT never rests on float arithmetic at all.

Scope: queries without relaxed attributes (RA ε pairs range over a delta
lattice whose points leave the box — ``engine.decide_leaf`` semantics — and
are served by Phase P instead); shared-lattice size gated by
``EngineConfig.lattice_max``.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fairify_tpu.models.mlp import MLP
from fairify_tpu.utils.num import matmul
from fairify_tpu.verify.property import shared_dims, valid_assignments

MARGIN_BUF = 4096  # device→host margin-index buffer per chunk


def shared_lattice_size(enc, lo: np.ndarray, hi: np.ndarray) -> int:
    """Number of shared-coordinate lattice points of the box (python int —
    stress grids can overflow int64)."""
    dims = shared_dims(enc, len(lo))
    n = 1
    for d in dims:
        n *= int(hi[d]) - int(lo[d]) + 1
    return n


def _signed_forward(net: MLP, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """f32/HIGHEST forward with a running rigorous roundoff bound.

        e_{l+1} = e_l·|W| + γ_l·(|h_l|·|W| + |b|),   γ_l = 4(n_in+4)·2⁻²⁴

    ReLU is 1-Lipschitz so ``e`` passes through unchanged (masked like the
    activation); integer inputs ≤ 2²⁴ are exact in f32, so e₀ = 0.  The
    exact-rational logit differs from the returned f32 logit by at most the
    returned bound.
    """
    h = x
    e = jnp.zeros_like(x)
    n_layers = len(net.weights)
    u32 = jnp.float32(2.0 ** -24)
    for i, (w, b, m) in enumerate(zip(net.weights, net.biases, net.masks)):
        gamma = 4.0 * (w.shape[0] + 4) * u32
        abs_acc = matmul(jnp.abs(h), jnp.abs(w)) + jnp.abs(b)
        e = matmul(e, jnp.abs(w)) + gamma * abs_acc
        z = matmul(h, w) + b
        if i < n_layers - 1:
            h = jax.nn.relu(z) * m
            e = e * m
        else:
            h = z
    return jnp.squeeze(h, axis=-1), jnp.squeeze(e, axis=-1)


def _device_signs(net, start, strides, widths, lo_shared, bases,
                  chunk: int, dims_tuple: tuple, d: int):
    """(V, chunk) int8 sign tensor (0 = inside roundoff bound), on device.

    Decodes flat indices ``start..start+chunk`` mixed-radix into shared
    coordinates (indices ≥ N wrap modulo the widths — still-in-box
    duplicates, so the tail of the last chunk is safe) and assembles the
    input for every assignment (``bases`` (V, d) carries PA values) with
    static per-dim gathers — a dynamic scatter here stalled XLA's compiler
    for minutes.
    """
    idx = start + jnp.arange(chunk, dtype=jnp.int32)
    coords = (idx[:, None] // strides[None, :]) % widths[None, :] \
        + lo_shared[None, :]  # (chunk, n_shared) int32
    pos_of = {dim: j for j, dim in enumerate(dims_tuple)}
    cols = [coords[:, pos_of[k]].astype(jnp.float32) if k in pos_of else None
            for k in range(d)]

    def per_assignment(base):
        x = jnp.stack(
            [cols[k] if cols[k] is not None
             else jnp.full((chunk,), base[k], dtype=jnp.float32)
             for k in range(d)], axis=1)
        return _signed_forward(net, x)

    f, e = jax.vmap(per_assignment)(bases)  # (V, chunk) each
    return jnp.where(f > e, jnp.int8(1),
                     jnp.where(f < -e, jnp.int8(-1), jnp.int8(0)))


@partial(jax.jit, static_argnames=("chunk", "dims_tuple", "d"))
def _lattice_scan_kernel(net: MLP, start, n_total, strides, widths,
                         lo_shared, bases, valid_mask, valid_pair_f,
                         chunk: int, dims_tuple: tuple, d: int):
    """Scan ``chunk`` lattice points on device; return only reductions.

    Returns (first_flip, margin_count, margin_idx[MARGIN_BUF],
    sign_cols[V, MARGIN_BUF+1]):
    * ``first_flip``: first in-chunk index admitting a VALID ordered pair
      (a, b) with certain signs (+1, −1) — computed against the full
      ``valid_pair`` matrix (multi-PA safe), −1 if none.
      ``sign_cols[:, -1]`` holds that index's sign column.
    * ``margin_idx``/``margin_count``: indices whose sign is inside the
      roundoff bound for ≥1 valid assignment; ``sign_cols[:, :MARGIN_BUF]``
      their sign columns.  count > MARGIN_BUF ⇒ host refetches the chunk's
      full sign tensor.
    """
    s = _device_signs(net, start, strides, widths, lo_shared, bases,
                      chunk, dims_tuple, d)
    # Tail indices ≥ n_total are modulo-wrapped duplicates of earlier points
    # — mask them so a dup can't inflate margin_count past the buffer (a
    # needless full-tensor refetch) or shadow an in-range first_flip.
    in_range = (start + jnp.arange(chunk, dtype=jnp.int32)) < n_total
    vm = valid_mask[:, None]
    posf = ((s == 1) & vm).astype(jnp.float32)
    negf = ((s == -1) & vm).astype(jnp.float32)
    # partner[a, j] > 0 ⇔ some b with valid_pair[a, b] is certainly negative
    # at point j — the exact ordered-pair semantics, not an any-sign proxy.
    partner = matmul(valid_pair_f, negf)
    flip = ((posf > 0) & (partner > 0)).any(axis=0) & in_range
    first_flip = jnp.where(flip.any(), jnp.argmax(flip), -1)

    is_margin = ((s == 0) & vm).any(axis=0) & in_range
    margin_count = is_margin.sum()
    (margin_idx,) = jnp.nonzero(is_margin, size=MARGIN_BUF, fill_value=-1)

    take = jnp.concatenate(
        [jnp.clip(margin_idx, 0, chunk - 1),
         jnp.clip(first_flip, 0, chunk - 1)[None]])
    sign_cols = s[:, take]  # (V, MARGIN_BUF + 1)
    return first_flip, margin_count, margin_idx, sign_cols


@partial(jax.jit, static_argnames=("chunk", "dims_tuple", "d"))
def _lattice_signs_kernel(net: MLP, start, strides, widths, lo_shared,
                          bases, chunk: int, dims_tuple: tuple, d: int):
    """Full (V, chunk) sign tensor — the margin-overflow fallback pull."""
    return _device_signs(net, start, strides, widths, lo_shared, bases,
                         chunk, dims_tuple, d)


def _host_signs(weights, biases, pts: np.ndarray) -> np.ndarray:
    """Signs for margin points: vectorized f64 forward, exact rational for
    the |f64| ≤ 1e-6 residue (``exact_logit_sign``'s ladder, batched)."""
    from fairify_tpu.models.mlp import forward_np
    from fairify_tpu.verify.engine import exact_logit_sign

    if pts.shape[0] == 0:
        return np.zeros(0, dtype=np.int8)
    v = np.atleast_1d(forward_np(weights, biases, pts.astype(np.float64)))
    out = np.sign(v).astype(np.int8)
    near = np.abs(v) <= 1e-6
    for k in np.where(near)[0]:
        out[k] = exact_logit_sign(weights, biases, pts[k])
    return out


def _pair_flip(signs: np.ndarray, valid: list, valid_pair: np.ndarray):
    """First (a, b) valid ordered pair with signs (+1, −1), else None.
    ``signs`` is a (V,) column over ALL encoding assignments."""
    for a in valid:
        if signs[a] != 1:
            continue
        for b in valid:
            if valid_pair[a, b] and signs[b] == -1:
                return a, b
    return None


def decide_box_exhaustive(
    net: MLP,
    enc,
    lo: np.ndarray,
    hi: np.ndarray,
    chunk: int = 1 << 21,
    deadline_s: Optional[float] = None,
) -> Tuple[str, Optional[Tuple[np.ndarray, np.ndarray]]]:
    """Complete decision of one box by lattice enumeration.

    Returns ``('sat', (x, xp))`` with an exact-validated pair, ``('unsat',
    None)`` when no exact strict flip exists anywhere on the lattice, or
    ``('unknown', None)`` on deadline, on a lattice too large for the
    32-bit device decode, or on an evidence-ladder disagreement (a device
    "certain" sign failing exact validation — then no sign is trusted).
    Caller gates RA and lattice size (``engine._lattice_phase``).
    """
    import time

    from fairify_tpu.verify.engine import validate_pair

    t0 = time.perf_counter()

    def time_left() -> float:
        if deadline_s is None:
            return float("inf")
        return deadline_s - (time.perf_counter() - t0)

    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    lo = np.asarray(lo, dtype=np.int64)
    hi = np.asarray(hi, dtype=np.int64)
    d = int(lo.shape[0])
    dims = shared_dims(enc, d)
    widths = (hi[dims] - lo[dims] + 1).astype(np.int64)
    N = shared_lattice_size(enc, lo, hi)
    if N >= 2 ** 31 - (1 << 22):
        # The device decode runs in int32 (idx, strides); a larger lattice
        # would silently wrap and enumerate the WRONG points — soundness
        # guard independent of the caller's configurable lattice_max.
        return "unknown", None
    strides = np.ones(len(dims), dtype=np.int64)
    for k in range(len(dims) - 2, -1, -1):
        strides[k] = strides[k + 1] * widths[k + 1]

    V = enc.n_assign
    valid = valid_assignments(enc, lo, hi)
    if not any(enc.valid_pair[a, b] for a in valid for b in valid):
        return "unsat", None  # no legal pair in the box — trivially fair

    # Device memory cap: V × chunk × widest-layer activations in f32.
    widest = max([d] + [int(w.shape[1]) for w in weights])
    max_chunk = max(1 << 12, int((1 << 28) // max(V * widest, 1)))
    chunk = int(min(chunk, max_chunk))

    bases = np.tile(lo.astype(np.float32), (V, 1))
    bases[:, np.asarray(enc.pa_idx)] = enc.assignments.astype(np.float32)
    valid_np = np.zeros(V, dtype=bool)
    valid_np[valid] = True

    # valid_pair restricted to in-box assignments for the device reduction.
    vp = enc.valid_pair & valid_np[:, None] & valid_np[None, :]
    dev = dict(
        strides=jnp.asarray(strides.astype(np.int32)),
        widths=jnp.asarray(widths.astype(np.int32)),
        lo_shared=jnp.asarray(lo[dims].astype(np.int32)),
        bases=jnp.asarray(bases),
        valid_mask=jnp.asarray(valid_np),
        valid_pair_f=jnp.asarray(vp.astype(np.float32)),
    )
    dims_tuple = tuple(int(x) for x in dims)

    def decode(idx_flat: np.ndarray) -> np.ndarray:
        pts = np.tile(lo, (len(idx_flat), 1))
        pts[:, dims] = (idx_flat[:, None] // strides[None, :]) \
            % widths[None, :] + lo[dims][None, :]
        return pts

    def settle_sat(idx_flat: int, a: int, b: int):
        x = decode(np.array([idx_flat]))[0]
        xp = x.copy()
        x[np.asarray(enc.pa_idx)] = enc.assignments[a]
        xp[np.asarray(enc.pa_idx)] = enc.assignments[b]
        # Already certain at the evidence-class level; re-prove exactly
        # before any SAT settles.
        if validate_pair(weights, biases, x, xp):
            return "sat", (x, xp)
        # A device "certain" sign failed exact validation: the error-bound
        # construction is broken for this net/box, so NO device sign is
        # trustworthy — refuse to certify anything.
        raise _EvidenceMismatch

    try:
        for c0 in range(0, N, chunk):
            if time_left() <= 0:
                return "unknown", None
            n_here = min(chunk, N - c0)
            # One batched device→host pull per chunk — per-array pulls cost
            # a tunnel round-trip each (~0.1 s) and dominated the scan loop.
            first_flip, margin_count, margin_idx, sign_cols = jax.device_get(
                _lattice_scan_kernel(
                    net, jnp.int32(c0), jnp.int32(N), dev["strides"],
                    dev["widths"], dev["lo_shared"], dev["bases"],
                    dev["valid_mask"], dev["valid_pair_f"], chunk,
                    dims_tuple, d))

            if 0 <= int(first_flip) < n_here:
                pair = _pair_flip(sign_cols[:, -1], valid, enc.valid_pair)
                if pair is None:  # device/host pair-matrix disagreement
                    raise _EvidenceMismatch
                return settle_sat(c0 + int(first_flip), *pair)

            mc = int(margin_count)
            if mc > MARGIN_BUF:
                # Margin buffer overflow: pull the chunk's full sign tensor
                # and resolve everything on host.
                s_full = np.asarray(_lattice_signs_kernel(
                    net, jnp.int32(c0), dev["strides"], dev["widths"],
                    dev["lo_shared"], dev["bases"], chunk, dims_tuple,
                    d))[:, :n_here]
                verdict = _resolve_signs(enc, weights, biases, decode, valid,
                                         c0, s_full, validate_pair, time_left)
            elif mc > 0:
                midx = margin_idx[margin_idx >= 0]
                verdict = _resolve_margin(
                    enc, weights, biases, decode, valid, c0, midx,
                    sign_cols[:, :MARGIN_BUF], n_here, validate_pair,
                    time_left)
            else:
                continue
            if verdict is not None:
                return verdict
    except (_EvidenceMismatch, _DeadlineHit):
        return "unknown", None

    return "unsat", None


class _EvidenceMismatch(Exception):
    """A device 'certain' sign contradicted exact arithmetic."""


class _DeadlineHit(Exception):
    """Per-point host resolution ran past the deadline."""


def _resolve_margin(enc, weights, biases, decode, valid, c0, midx,
                    sign_cols, n_here, validate_pair, time_left):
    """Exact-ladder the margin points of one chunk; SAT iff a strict exact
    flip appears once their true signs replace the device zeros."""
    for j, k in enumerate(midx):
        k = int(k)
        if k >= n_here:
            continue
        if time_left() <= 0:
            raise _DeadlineHit
        col = sign_cols[:, j].copy()
        out = _settle_column(enc, weights, biases, decode, valid, c0, k,
                             col, validate_pair)
        if out is not None:
            return out
    return None


def _resolve_signs(enc, weights, biases, decode, valid, c0, s_full,
                   validate_pair, time_left):
    """Host resolution of a full chunk sign tensor (overflow fallback)."""
    vp = enc.valid_pair
    pos = (s_full == 1)
    neg = (s_full == -1)
    flip_pts = np.zeros(s_full.shape[1], dtype=bool)
    for a in valid:
        if not pos[a].any():
            continue
        partners = [b for b in valid if vp[a, b]]
        if partners:
            flip_pts |= pos[a] & neg[partners].any(axis=0)
    margin_pts = np.where((s_full[valid] == 0).any(axis=0))[0]
    for k in np.where(flip_pts)[0].tolist() + margin_pts.tolist():
        if time_left() <= 0:
            raise _DeadlineHit
        out = _settle_column(enc, weights, biases, decode, valid, c0,
                             int(k), s_full[:, int(k)].copy(),
                             validate_pair)
        if out is not None:
            return out
    return None


def _settle_column(enc, weights, biases, decode, valid, c0, k, col,
                   validate_pair):
    """Resolve one lattice point: exact-ladder its margin signs, then SAT
    iff a valid ordered pair flips (exact-validated)."""
    for a in valid:
        if col[a] == 0:
            pt = decode(np.array([c0 + k]))[0]
            pt[np.asarray(enc.pa_idx)] = enc.assignments[a]
            col[a] = _host_signs(weights, biases, pt[None])[0]
    pair = _pair_flip(col, valid, enc.valid_pair)
    if pair is None:
        return None
    a, b = pair
    x = decode(np.array([c0 + k]))[0]
    xp = x.copy()
    x[np.asarray(enc.pa_idx)] = enc.assignments[a]
    xp[np.asarray(enc.pa_idx)] = enc.assignments[b]
    if validate_pair(weights, biases, x, xp):
        return "sat", (x, xp)
    # Margin entries of ``col`` were exact-laddered, so a failed validation
    # convicts a device "certain" ±1 — no device sign is trustworthy.
    raise _EvidenceMismatch

"""Phase E — exhaustive integer-lattice decision for finite boxes.

The reference's Z3 query ranges over the *integer lattice* of the partition
box (``ToReal(Int x)`` inputs, ``src/GC/Verify-GC.py:128-143``), so the pair
property is decidable by finite enumeration.  The engine's input-split BaB
diverges on exactly one box class: wide flip-slab boxes whose logit surface
crosses zero throughout (millions of nodes without convergence, e.g.
stress-AC box 768 on AC-1 — a 33M-point shared lattice the BaB burned 3.4M
nodes on).  For those boxes enumeration on the MXU is *cheap*: a 16-8-1 net
over the full lattice is a handful of batched forward launches.

Tunnel-aware layout (the single-chip TPU sits behind a ~MB/s relay):
coordinates are decoded from flat indices **on device** (mixed-radix over
the shared dims, static per-dim gather — no scatter, which stalled XLA's
compiler for minutes), all PA assignments are evaluated in one vmapped
kernel, and flip/margin *detection* also runs on device — each chunk
returns only scalars and a fixed-size margin-index buffer, never the logit
arrays.

Evidence classes (docs/DESIGN.md numeric policy):

* Device pass: f32 at ``Precision.HIGHEST`` with a **per-point rigorous
  roundoff bound** computed alongside the forward from the same ``|W|``
  matmuls (standard running-error analysis, 4× outward on the float32 γ
  constants).  |logit| above its bound ⇒ certain sign.
* Margin points (|logit| ≤ bound) fall back to the host ladder
  ``float64 → exact rational`` — the same posture as
  ``engine.exact_logit_sign``.
* Every SAT verdict is re-proved by ``engine.validate_pair`` in exact
  arithmetic, so SAT never rests on float arithmetic at all.

Scope: RA-free queries, and k-RA queries via ε-expanded axes with
on-device window dilation (x′ partners unclamped, ``engine.decide_leaf``
semantics; flip candidates and margin-touched core points settle exactly
through ``decide_leaf``).  The (2ε+1)^k window is **separable** — an L∞
box dilation is the composition of k per-axis dilations — so the kernel
pays k(2ε+1) rolls, not (2ε+1)^k, for any k.  Queries whose delta window
exceeds the margin resolver's 10⁵ cap (``decide_leaf``) stay Phase P's
job.  Scan size is gated
by ``EngineConfig.lattice_max``.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fairify_tpu.models.mlp import MLP
from fairify_tpu.obs import obs_jit
from fairify_tpu.utils import profiling
from fairify_tpu.utils.num import matmul
from fairify_tpu.verify.property import shared_dims, valid_assignments

# Device→host margin-index buffer per chunk.  Kept small: the buffer (plus
# its sign columns) is most of each chunk's transfer over the ~MB/s tunnel,
# margin points are rare (typically 0/chunk), and overflow degrades safely
# to a full sign-tensor pull for that chunk.
MARGIN_BUF = 512

# Coordinate-magnitude ceiling for the roundoff-bound base case (ADVICE r3):
# ``_signed_forward``'s e₀ = 0 assumes every input coordinate is exactly
# representable in f32, true only for integers with |v| ≤ 2²⁴.  Decoded
# lattice coordinates (and peeled prefix values baked into ``bases``) are
# cast to f32, so a dim ranging past 2²⁴ would silently scan *rounded*
# points — an unsound UNSAT.  Every current dataset dim is far below
# (default-credit tops out ~10⁶), so the guard is cheap insurance against
# future domains; boxes over the ceiling are not enumerable.
COORD_EXACT_F32 = 1 << 24


def _ra_strides(ra_ws: tuple) -> list:
    """Mixed-radix strides of the RA tile, aligned with ``ra_ws`` order
    (innermost axis last, stride 1).  Shared by the device scan kernel's
    core-mask decode and the host margin-resolution decode — these MUST
    agree or margin cells resolve at the wrong core points."""
    strides = []
    acc = 1
    for w in reversed(ra_ws):
        strides.append(acc)
        acc *= w
    return list(reversed(strides))


def _coords_exceed_f32(enc, lo: np.ndarray, hi: np.ndarray) -> bool:
    """True iff any ε-expanded coordinate magnitude reaches 2²⁴."""
    lo_eff = np.asarray(lo, dtype=np.int64).copy()
    hi_eff = np.asarray(hi, dtype=np.int64).copy()
    if len(enc.ra_idx) and enc.eps:
        ra = np.asarray(enc.ra_idx)
        lo_eff[ra] -= int(enc.eps)
        hi_eff[ra] += int(enc.eps)
    return bool(max(np.abs(lo_eff).max(), np.abs(hi_eff).max())
                >= COORD_EXACT_F32)


def shared_lattice_size(enc, lo: np.ndarray, hi: np.ndarray) -> int:
    """Number of shared-coordinate lattice points of the box (python int —
    stress grids can overflow int64)."""
    dims = shared_dims(enc, len(lo))
    n = 1
    for d in dims:
        n *= int(hi[d]) - int(lo[d]) + 1
    return n


def enumerable_size(enc, lo: np.ndarray, hi: np.ndarray) -> Optional[int]:
    """Scan size of the box if Phase E can enumerate it, else None.

    RA-free: the shared lattice.  k RA dims with ε > 0: the lattice with
    each RA axis expanded by ±ε (x' partners range over the unclamped delta
    window, ``engine.decide_leaf`` semantics).  The k-dim box window is an
    L∞ ball, so its device dilation is separable — per-axis dilations
    composed — for ANY k; the limit is the margin-point resolver
    (``decide_leaf`` enumerates (2ε+1)^k deltas, honest-unknown past 10⁵,
    which also bounds the device tile).  Boxes whose (ε-expanded)
    coordinates reach 2²⁴ are also None: the device roundoff bound assumes
    exact-f32 integer inputs (ADVICE r3).
    """
    if _coords_exceed_f32(enc, lo, hi):
        return None
    if len(enc.ra_idx) and enc.eps:
        if (2 * int(enc.eps) + 1) ** len(enc.ra_idx) > 100_000:
            return None
        ra_set = {int(j) for j in enc.ra_idx}
        dims = shared_dims(enc, len(lo))
        n = 1
        for d in dims:
            w = int(hi[d]) - int(lo[d]) + 1
            if d in ra_set:
                w += 2 * int(enc.eps)
            n *= w
        return n
    return shared_lattice_size(enc, lo, hi)


def _signed_forward(net: MLP, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """f32/HIGHEST forward with a running rigorous roundoff bound.

        e_{l+1} = e_l·|W| + γ_l·(|h_l|·|W| + |b|),   γ_l = 4(n_in+4)·2⁻²⁴

    ReLU is 1-Lipschitz so ``e`` passes through unchanged (masked like the
    activation); integer inputs ≤ 2²⁴ are exact in f32, so e₀ = 0.  The
    exact-rational logit differs from the returned f32 logit by at most the
    returned bound.
    """
    h = x
    e = jnp.zeros_like(x)
    n_layers = len(net.weights)
    u32 = jnp.float32(2.0 ** -24)
    for i, (w, b, m) in enumerate(zip(net.weights, net.biases, net.masks)):
        gamma = 4.0 * (w.shape[0] + 4) * u32
        abs_acc = matmul(jnp.abs(h), jnp.abs(w)) + jnp.abs(b)
        e = matmul(e, jnp.abs(w)) + gamma * abs_acc
        z = matmul(h, w) + b
        if i < n_layers - 1:
            h = jax.nn.relu(z) * m
            e = e * m
        else:
            h = z
    return jnp.squeeze(h, axis=-1), jnp.squeeze(e, axis=-1)


def _device_signs(net, start, strides, widths, lo_shared, bases,
                  chunk: int, dims_tuple: tuple, d: int):
    """(V, chunk) int8 sign tensor (0 = inside roundoff bound), on device.

    Decodes flat indices ``start..start+chunk`` mixed-radix into shared
    coordinates (indices ≥ N wrap modulo the widths — still-in-box
    duplicates, so the tail of the last chunk is safe) and assembles the
    input for every assignment (``bases`` (V, d) carries PA values) with
    static per-dim gathers — a dynamic scatter here stalled XLA's compiler
    for minutes.
    """
    idx = start + jnp.arange(chunk, dtype=jnp.int32)
    coords = (idx[:, None] // strides[None, :]) % widths[None, :] \
        + lo_shared[None, :]  # (chunk, n_shared) int32
    pos_of = {dim: j for j, dim in enumerate(dims_tuple)}
    cols = [coords[:, pos_of[k]].astype(jnp.float32) if k in pos_of else None
            for k in range(d)]

    def per_assignment(base):
        x = jnp.stack(
            [cols[k] if cols[k] is not None
             else jnp.full((chunk,), base[k], dtype=jnp.float32)
             for k in range(d)], axis=1)
        return _signed_forward(net, x)

    f, e = jax.vmap(per_assignment)(bases)  # (V, chunk) each
    return jnp.where(f > e, jnp.int8(1),
                     jnp.where(f < -e, jnp.int8(-1), jnp.int8(0)))


@obs_jit(static_argnames=("chunk", "dims_tuple", "d"))
def _lattice_scan_kernel(net: MLP, start, n_total, strides, widths,
                         lo_shared, bases, valid_mask, valid_pair_f,
                         chunk: int, dims_tuple: tuple, d: int):
    """Scan ``chunk`` lattice points on device; return only reductions.

    Returns (first_flip, margin_count, margin_idx[MARGIN_BUF],
    sign_cols[V, MARGIN_BUF+1]):
    * ``first_flip``: first in-chunk index admitting a VALID ordered pair
      (a, b) with certain signs (+1, −1) — computed against the full
      ``valid_pair`` matrix (multi-PA safe), −1 if none.
      ``sign_cols[:, -1]`` holds that index's sign column.
    * ``margin_idx``/``margin_count``: indices whose sign is inside the
      roundoff bound for ≥1 valid assignment; ``sign_cols[:, :MARGIN_BUF]``
      their sign columns.  count > MARGIN_BUF ⇒ host refetches the chunk's
      full sign tensor.
    """
    s = _device_signs(net, start, strides, widths, lo_shared, bases,
                      chunk, dims_tuple, d)
    # Tail indices ≥ n_total are modulo-wrapped duplicates of earlier points
    # — mask them so a dup can't inflate margin_count past the buffer (a
    # needless full-tensor refetch) or shadow an in-range first_flip.
    in_range = (start + jnp.arange(chunk, dtype=jnp.int32)) < n_total
    vm = valid_mask[:, None]
    posf = ((s == 1) & vm).astype(jnp.float32)
    negf = ((s == -1) & vm).astype(jnp.float32)
    # partner[a, j] > 0 ⇔ some b with valid_pair[a, b] is certainly negative
    # at point j — the exact ordered-pair semantics, not an any-sign proxy.
    partner = matmul(valid_pair_f, negf)
    flip = ((posf > 0) & (partner > 0)).any(axis=0) & in_range
    first_flip = jnp.where(flip.any(), jnp.argmax(flip), -1)

    is_margin = ((s == 0) & vm).any(axis=0) & in_range
    margin_count = is_margin.sum()
    (margin_idx,) = jnp.nonzero(is_margin, size=MARGIN_BUF, fill_value=-1)

    take = jnp.concatenate(
        [jnp.clip(margin_idx, 0, chunk - 1),
         jnp.clip(first_flip, 0, chunk - 1)[None]])
    sign_cols = s[:, take]  # (V, MARGIN_BUF + 1)
    return first_flip, margin_count, margin_idx, sign_cols


@obs_jit(static_argnames=("chunk", "dims_tuple", "d"))
def _lattice_signs_kernel(net: MLP, start, strides, widths, lo_shared,
                          bases, chunk: int, dims_tuple: tuple, d: int):
    """Full (V, chunk) sign tensor — the margin-overflow fallback pull."""
    return _device_signs(net, start, strides, widths, lo_shared, bases,
                         chunk, dims_tuple, d)


@obs_jit(static_argnames=("chunk", "dims_tuple", "d", "ra_ws", "eps"))
def _lattice_scan_kernel_ra(net: MLP, start, n_total, strides, widths,
                            lo_shared, bases, valid_mask, valid_pair_f,
                            chunk: int, dims_tuple: tuple, d: int,
                            ra_ws: tuple, eps: int):
    """RA-aware scan: the RA axes are the innermost suffix
    dims, each expanded by ±ε, and x' partners are found by dilating the
    certain-negative cells over the delta window (``engine.decide_leaf``
    pair semantics: x core-ranged, x' at an unclamped delta within ±ε per
    RA dim).  The k-RA box window is separable: per-axis dilations
    composed, k(2ε+1) rolls instead of (2ε+1)^k.

    Returns (first_flip, margin_count, margin_idx[MARGIN_BUF],
    sign_cols[V, MARGIN_BUF+1]):
    * ``first_flip``: first CORE point (every RA coord inside its
      unexpanded range) admitting a valid ordered pair (a, b) with a
      certain positive sign at x and a certain negative sign at some
      window partner.
    * ``margin_idx``: expanded-lattice cells whose sign is inside the
      roundoff bound — the host resolves every core point whose window
      touches one, exactly, via ``decide_leaf``.
    """
    s = _device_signs(net, start, strides, widths, lo_shared, bases,
                      chunk, dims_tuple, d)
    in_range = (start + jnp.arange(chunk, dtype=jnp.int32)) < n_total
    # start and chunk are multiples of the RA tile (prod(ra_ws)), so cell
    # coordinates within the tile are position-stable across chunks.
    tile = 1
    for w in ra_ws:
        tile *= w
    idxs = jnp.arange(chunk, dtype=jnp.int32)
    core = in_range
    rem = idxs % tile
    # Per-axis in-core masks: decode each RA coordinate from the in-tile
    # remainder (mixed radix, innermost = last of ra_ws).
    strides_ra = _ra_strides(ra_ws)
    for w, st in zip(ra_ws, strides_ra):
        col = (rem // st) % w
        core = core & (col >= eps) & (col < w - eps)
    vm = valid_mask[:, None]
    V = s.shape[0]
    rows = chunk // tile

    # Dilate certain signs over the ±ε box window along the RA axes.  Dups
    # (≥ n_total) are masked BEFORE dilation: a wrapped cell belongs to a
    # different shared-coordinate row and must not donate a partner.
    # Separable: dilate one axis at a time.
    def dilate(mask):
        m = mask.reshape((V, rows) + tuple(ra_ws))
        for ax, w in enumerate(ra_ws):
            axis = 2 + ax
            out = jnp.zeros_like(m)
            cidx_shape = [1] * m.ndim
            cidx_shape[axis] = w
            cidx = jnp.arange(w).reshape(cidx_shape)
            for dlt in range(-eps, eps + 1):
                ok = (cidx + dlt >= 0) & (cidx + dlt < w)
                out = out | (jnp.roll(m, -dlt, axis=axis) & ok)
            m = out
        return m.reshape(V, chunk).astype(jnp.float32)

    live = vm & in_range[None, :]
    dil_neg = dilate((s == -1) & live)
    dil_pos = dilate((s == 1) & live)
    posc = ((s == 1) & vm & core[None, :]).astype(jnp.float32)
    negc = ((s == -1) & vm & core[None, :]).astype(jnp.float32)
    # decide_leaf accepts EITHER sign direction for a pair (a, b): the
    # core point x may be the positive endpoint with a negative window
    # partner, or the negative endpoint with a positive one (the positive
    # cell can live only in the ε-expanded boundary ring).
    flip = ((posc > 0) & (matmul(valid_pair_f, dil_neg) > 0)).any(axis=0) \
        | ((negc > 0) & (matmul(valid_pair_f, dil_pos) > 0)).any(axis=0)
    first_flip = jnp.where(flip.any(), jnp.argmax(flip), -1)

    is_margin = ((s == 0) & vm).any(axis=0) & in_range
    margin_count = is_margin.sum()
    (margin_idx,) = jnp.nonzero(is_margin, size=MARGIN_BUF, fill_value=-1)

    take = jnp.concatenate(
        [jnp.clip(margin_idx, 0, chunk - 1),
         jnp.clip(first_flip, 0, chunk - 1)[None]])
    sign_cols = s[:, take]
    return first_flip, margin_count, margin_idx, sign_cols


def _host_signs(weights, biases, pts: np.ndarray) -> np.ndarray:
    """Signs for margin points: vectorized f64 forward, exact rational for
    the |f64| ≤ 1e-6 residue (``exact_logit_sign``'s ladder, batched)."""
    from fairify_tpu.models.mlp import forward_np
    from fairify_tpu.verify.engine import exact_logit_sign

    if pts.shape[0] == 0:
        return np.zeros(0, dtype=np.int8)
    v = np.atleast_1d(forward_np(weights, biases, pts.astype(np.float64)))
    out = np.sign(v).astype(np.int8)
    near = np.abs(v) <= 1e-6
    for k in np.where(near)[0]:
        out[k] = exact_logit_sign(weights, biases, pts[k])
    return out


def _pair_flip(signs: np.ndarray, valid: list, valid_pair: np.ndarray):
    """First (a, b) valid ordered pair with signs (+1, −1), else None.
    ``signs`` is a (V,) column over ALL encoding assignments."""
    for a in valid:
        if signs[a] != 1:
            continue
        for b in valid:
            if valid_pair[a, b] and signs[b] == -1:
                return a, b
    return None


def decide_box_exhaustive(
    net: MLP,
    enc,
    lo: np.ndarray,
    hi: np.ndarray,
    chunk: int = 1 << 21,
    deadline_s: Optional[float] = None,
    pipeline_depth: int = 32,
    int32_limit: int = 2 ** 31 - (1 << 23),
) -> Tuple[str, Optional[Tuple[np.ndarray, np.ndarray]]]:
    """Complete decision of one box by lattice enumeration.

    Returns ``('sat', (x, xp))`` with an exact-validated pair, ``('unsat',
    None)`` when no exact strict flip exists anywhere on the lattice, or
    ``('unknown', None)`` on deadline or on an evidence-ladder
    disagreement (a device "certain" sign failing exact validation — then
    no sign is trusted).  Caller gates the scan size
    (``engine._lattice_phase``); queries whose (2ε+1)^k delta window
    exceeds the 10⁵ margin-resolver cap return unknown here.

    k RA dims are handled completely: each axis is expanded ±ε, laid out
    innermost, and certain-sign partner cells are dilated over the L∞
    delta window on device (``engine.decide_leaf`` pair semantics, x′
    unclamped; separable per-axis dilation for any k — round 5); flip
    candidates and margin-touched core points are settled exactly by
    ``decide_leaf``.

    Lattices past the 32-bit device decode are **prefix-peeled**: shared
    dims are enumerated host-side (their values baked into the per-sweep
    ``bases``) until the suffix lattice fits int32; one kernel compile
    serves every prefix.  Chunks are **pipeline-dispatched**
    ``pipeline_depth`` ahead — on the tunnelled chip the per-chunk cost is
    the device→host round-trip, not compute, so overlapping transfers is
    what makes 10^10-point boxes (stress-BM class) enumerable in minutes.
    """
    import itertools
    import time
    from collections import deque

    from fairify_tpu.verify.engine import validate_pair

    t0 = time.perf_counter()

    def time_left() -> float:
        if deadline_s is None:
            return float("inf")
        return deadline_s - (time.perf_counter() - t0)

    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    lo = np.asarray(lo, dtype=np.int64)
    hi = np.asarray(hi, dtype=np.int64)
    d = int(lo.shape[0])
    if _coords_exceed_f32(enc, lo, hi):
        # e₀ = 0 in the device roundoff recurrence requires exact-f32
        # integer coordinates (|v| < 2²⁴); a wider dim would scan rounded
        # points and could return an unsound UNSAT (ADVICE r3).
        return "unknown", None

    # RA mode: k relaxed dims are handled by expanding each axis ±ε and
    # dilating partners over the L∞ window on device (separable for any k:
    # per-axis dilations composed).  The same (2ε+1)^k ≤ 10⁵ guard as
    # ``enumerable_size``/``decide_leaf`` keeps the margin resolver and the
    # device tile bounded — past it, honest unknown.
    ra_mode = bool(len(enc.ra_idx)) and int(enc.eps) > 0
    if ra_mode and (2 * int(enc.eps) + 1) ** len(enc.ra_idx) > 100_000:
        return "unknown", None
    ra_dims = [int(j) for j in enc.ra_idx] if ra_mode else []
    eps = int(enc.eps) if ra_mode else 0
    lo_eff = lo.copy()
    hi_eff = hi.copy()
    for rd in ra_dims:
        lo_eff[rd] -= eps
        hi_eff[rd] += eps

    dims = shared_dims(enc, d)
    if ra_mode:
        # RA axes innermost (the last one stride 1): partner windows then
        # live inside one contiguous tile and never cross a chunk boundary.
        dims = np.array([x for x in dims if x not in ra_dims] + ra_dims)
    N = 1
    for dm in dims:
        N *= int(hi_eff[dm]) - int(lo_eff[dm]) + 1
    ra_ws = tuple(int(hi_eff[rd] - lo_eff[rd] + 1) for rd in ra_dims)
    tile = 1
    for w in ra_ws:
        tile *= w

    V = enc.n_assign
    valid = valid_assignments(enc, lo, hi)
    if not any(enc.valid_pair[a, b] for a in valid for b in valid):
        return "unsat", None  # no legal pair in the box — trivially fair

    # Prefix peeling: enumerate shared dims host-side until the suffix
    # lattice fits the int32 device decode.  Peel smallest widths first —
    # the prefix count is N/n_suf, so removing the least width necessary
    # keeps host round-trips (and last-chunk padding waste) minimal; fixed
    # leading-order peeling could overshoot by orders of magnitude when an
    # early dim is very wide.  The RA axis is never peeled (its window
    # dilation runs on device).
    n_suf = N
    by_width = sorted(
        (j for j in range(len(dims)) if int(dims[j]) not in ra_dims),
        key=lambda j: int(hi_eff[dims[j]]) - int(lo_eff[dims[j]]) + 1)
    peeled = []
    for j in by_width:
        if n_suf < int32_limit - chunk:
            break
        n_suf //= int(hi_eff[dims[j]]) - int(lo_eff[dims[j]]) + 1
        peeled.append(j)
    if n_suf >= int32_limit - chunk:
        return "unknown", None  # RA axis alone exceeds int32 — not expected
    peel_dims = dims[sorted(peeled)]
    suf_dims = dims[sorted(set(range(len(dims))) - set(peeled))]
    suf_widths = (hi_eff[suf_dims] - lo_eff[suf_dims] + 1).astype(np.int64)
    suf_strides = np.ones(len(suf_dims), dtype=np.int64)
    for k in range(len(suf_dims) - 2, -1, -1):
        suf_strides[k] = suf_strides[k + 1] * suf_widths[k + 1]

    # Device memory cap: V × chunk × widest-layer activations in f32.
    widest = max([d] + [int(w.shape[1]) for w in weights])
    max_chunk = max(1 << 12, int((1 << 28) // max(V * widest, 1)))
    chunk = int(min(chunk, max_chunk))
    if ra_mode:
        # Chunks hold whole RA tiles so windows never cross a boundary.
        if tile > max_chunk:
            return "unknown", None  # one RA tile exceeds device memory
        chunk = max(tile, chunk - chunk % tile)
        if n_suf >= int32_limit - chunk:
            # Re-check the int32 headroom with the aligned chunk (the peel
            # guard above used the pre-alignment value).
            return "unknown", None

    valid_np = np.zeros(V, dtype=bool)
    valid_np[valid] = True
    # valid_pair restricted to in-box assignments for the device reduction.
    vp = enc.valid_pair & valid_np[:, None] & valid_np[None, :]
    dev = dict(
        strides=jnp.asarray(suf_strides.astype(np.int32)),
        widths=jnp.asarray(suf_widths.astype(np.int32)),
        lo_shared=jnp.asarray(lo_eff[suf_dims].astype(np.int32)),
        valid_mask=jnp.asarray(valid_np),
        valid_pair_f=jnp.asarray(vp.astype(np.float32)),
    )
    dims_tuple = tuple(int(x) for x in suf_dims)

    def make_decode(prefix_vals):
        def decode(idx_flat: np.ndarray) -> np.ndarray:
            pts = np.tile(lo, (len(idx_flat), 1))
            if len(peel_dims):
                pts[:, peel_dims] = np.asarray(prefix_vals, dtype=np.int64)
            pts[:, suf_dims] = (idx_flat[:, None] // suf_strides[None, :]) \
                % suf_widths[None, :] + lo_eff[suf_dims][None, :]
            return pts
        return decode

    def settle_sat(decode, idx_flat: int, a: int, b: int):
        x = decode(np.array([idx_flat]))[0]
        xp = x.copy()
        x[np.asarray(enc.pa_idx)] = enc.assignments[a]
        xp[np.asarray(enc.pa_idx)] = enc.assignments[b]
        # Already certain at the evidence-class level; re-prove exactly
        # before any SAT settles.
        if validate_pair(weights, biases, x, xp):
            return "sat", (x, xp)
        # A device "certain" sign failed exact validation: the error-bound
        # construction is broken for this net/box, so NO device sign is
        # trustworthy — refuse to certify anything.
        raise _EvidenceMismatch

    def work_items():
        """(prefix_vals, bases_dev, c0) stream covering the full lattice."""
        spaces = [range(int(lo[dm]), int(hi[dm]) + 1) for dm in peel_dims]
        for prefix_vals in itertools.product(*spaces):
            base = np.tile(lo.astype(np.float32), (V, 1))
            if len(peel_dims):
                base[:, peel_dims] = np.asarray(prefix_vals, np.float32)
            base[:, np.asarray(enc.pa_idx)] = \
                enc.assignments.astype(np.float32)
            bases_dev = jnp.asarray(base)
            for c0 in range(0, n_suf, chunk):
                yield prefix_vals, bases_dev, c0

    def leaf_core(decode, idx_flat: int) -> Optional[tuple]:
        """Exact per-point decision (RA mode): decide_leaf enumerates every
        assignment pair and delta at the decoded core point."""
        from fairify_tpu.verify.engine import decide_leaf

        point = decode(np.array([idx_flat]))[0]
        verdict, ce = decide_leaf(enc, weights, biases, point, lo, hi)
        if verdict == "sat":
            return "sat", ce
        return None

    def ra_core_candidates(c0, cells) -> list:
        """Core flat indices whose ±ε window touches any of ``cells``.
        Mixed-radix over the RA tile (ra_ws order, innermost last)."""
        strides_ra = _ra_strides(ra_ws)
        out = set()
        for m in cells:
            m = int(m)
            rem = m % tile
            row0 = m - rem
            cols = [(rem // st) % w for w, st in zip(ra_ws, strides_ra)]
            spans = [range(max(eps, c - eps), min(w - eps - 1, c + eps) + 1)
                     for c, w in zip(cols, ra_ws)]
            for combo in itertools.product(*spans):
                out.add(c0 + row0
                        + sum(c * st for c, st in zip(combo, strides_ra)))
        return sorted(out)

    def resolve_ra_cells(decode, c0, cells) -> Optional[tuple]:
        for idx_flat in ra_core_candidates(c0, cells):
            if time_left() <= 0:
                raise _DeadlineHit
            out = leaf_core(decode, idx_flat)
            if out is not None:
                return out
        return None

    def process(prefix_vals, c0, bases_dev, results) -> Optional[tuple]:
        first_flip, margin_count, margin_idx, sign_cols = results
        decode = make_decode(prefix_vals)
        n_here = min(chunk, n_suf - c0)
        if 0 <= int(first_flip) < n_here:
            if ra_mode:
                # The certain flip pairs x with a window partner; the exact
                # per-point leaf re-derives it (and the witness) exactly.
                out = leaf_core(decode, c0 + int(first_flip))
                if out is None:  # certain flip refuted exactly
                    raise _EvidenceMismatch
                return out
            pair = _pair_flip(sign_cols[:, -1], valid, enc.valid_pair)
            if pair is None:  # device/host pair-matrix disagreement
                raise _EvidenceMismatch
            return settle_sat(decode, c0 + int(first_flip), *pair)
        mc = int(margin_count)
        if mc > MARGIN_BUF:
            # Margin buffer overflow: pull the chunk's full sign tensor and
            # resolve everything on host.
            s_full = np.asarray(_lattice_signs_kernel(
                net, jnp.int32(c0), dev["strides"], dev["widths"],
                dev["lo_shared"], bases_dev, chunk, dims_tuple,
                d))[:, :n_here]
            if ra_mode:
                cells = np.where((s_full[valid] == 0).any(axis=0))[0]
                return resolve_ra_cells(decode, c0, cells)
            return _resolve_signs(enc, weights, biases, decode, valid,
                                  c0, s_full, validate_pair, time_left)
        if mc > 0:
            midx = margin_idx[margin_idx >= 0]
            midx = midx[midx < n_here]
            if ra_mode:
                return resolve_ra_cells(decode, c0, midx)
            return _resolve_margin(
                enc, weights, biases, decode, valid, c0, midx,
                sign_cols[:, :MARGIN_BUF], n_here, validate_pair,
                time_left)
        return None

    # Pipeline: dispatch up to `pipeline_depth` chunks ahead; collect in
    # order.  Dispatch is async (jax futures); device_get blocks only on
    # the oldest in-flight chunk, so transfers overlap compute and the
    # tunnel round-trip is paid once per depth-window, not per chunk.
    inflight: deque = deque()
    stream = work_items()
    try:
        while True:
            while len(inflight) < pipeline_depth:
                nxt = next(stream, None)
                if nxt is None:
                    break
                if time_left() <= 0:
                    return "unknown", None
                prefix_vals, bases_dev, c0 = nxt
                if ra_mode:
                    profiling.bump_launch()
                    fut = _lattice_scan_kernel_ra(
                        net, jnp.int32(c0), jnp.int32(n_suf),
                        dev["strides"], dev["widths"], dev["lo_shared"],
                        bases_dev, dev["valid_mask"], dev["valid_pair_f"],
                        chunk, dims_tuple, d, ra_ws, eps)
                else:
                    profiling.bump_launch()
                    fut = _lattice_scan_kernel(
                        net, jnp.int32(c0), jnp.int32(n_suf),
                        dev["strides"], dev["widths"], dev["lo_shared"],
                        bases_dev, dev["valid_mask"], dev["valid_pair_f"],
                        chunk, dims_tuple, d)
                inflight.append((prefix_vals, c0, bases_dev, fut))
            if not inflight:
                break
            if time_left() <= 0:
                return "unknown", None
            prefix_vals, c0, bases_dev, fut = inflight.popleft()
            results = jax.device_get(fut)
            verdict = process(prefix_vals, c0, bases_dev, results)
            if verdict is not None:
                return verdict
    except (_EvidenceMismatch, _DeadlineHit):
        return "unknown", None

    return "unsat", None


class _EvidenceMismatch(Exception):
    """A device 'certain' sign contradicted exact arithmetic."""


class _DeadlineHit(Exception):
    """Per-point host resolution ran past the deadline."""


def _resolve_margin(enc, weights, biases, decode, valid, c0, midx,
                    sign_cols, n_here, validate_pair, time_left):
    """Exact-ladder the margin points of one chunk; SAT iff a strict exact
    flip appears once their true signs replace the device zeros."""
    for j, k in enumerate(midx):
        k = int(k)
        if k >= n_here:
            continue
        if time_left() <= 0:
            raise _DeadlineHit
        col = sign_cols[:, j].copy()
        out = _settle_column(enc, weights, biases, decode, valid, c0, k,
                             col, validate_pair)
        if out is not None:
            return out
    return None


def _resolve_signs(enc, weights, biases, decode, valid, c0, s_full,
                   validate_pair, time_left):
    """Host resolution of a full chunk sign tensor (overflow fallback)."""
    vp = enc.valid_pair
    pos = (s_full == 1)
    neg = (s_full == -1)
    flip_pts = np.zeros(s_full.shape[1], dtype=bool)
    for a in valid:
        if not pos[a].any():
            continue
        partners = [b for b in valid if vp[a, b]]
        if partners:
            flip_pts |= pos[a] & neg[partners].any(axis=0)
    margin_pts = np.where((s_full[valid] == 0).any(axis=0))[0]
    for k in np.where(flip_pts)[0].tolist() + margin_pts.tolist():
        if time_left() <= 0:
            raise _DeadlineHit
        out = _settle_column(enc, weights, biases, decode, valid, c0,
                             int(k), s_full[:, int(k)].copy(),
                             validate_pair)
        if out is not None:
            return out
    return None


def _settle_column(enc, weights, biases, decode, valid, c0, k, col,
                   validate_pair):
    """Resolve one lattice point: exact-ladder its margin signs, then SAT
    iff a valid ordered pair flips (exact-validated)."""
    for a in valid:
        if col[a] == 0:
            pt = decode(np.array([c0 + k]))[0]
            pt[np.asarray(enc.pa_idx)] = enc.assignments[a]
            col[a] = _host_signs(weights, biases, pt[None])[0]
    pair = _pair_flip(col, valid, enc.valid_pair)
    if pair is None:
        return None
    a, b = pair
    x = decode(np.array([c0 + k]))[0]
    xp = x.copy()
    x[np.asarray(enc.pa_idx)] = enc.assignments[a]
    xp[np.asarray(enc.pa_idx)] = enc.assignments[b]
    if validate_pair(weights, biases, x, xp):
        return "sat", (x, xp)
    # Margin entries of ``col`` were exact-laddered, so a failed validation
    # convicts a device "certain" ±1 — no device sign is trustworthy.
    raise _EvidenceMismatch

"""Fused whole-network IBP as a single Pallas TPU kernel.

The XLA path (:func:`fairify_tpu.ops.interval.network_bounds`) issues four
``Precision.HIGHEST`` matmuls per layer (sign-split) plus elementwise widen/
ReLU/mask stages; for the zoo's small layers the launch+HBM round-trips
dominate.  This kernel computes the same bounds in the center–radius form —
``z_c = c @ W``, ``z_r = r @ |W|``, ``[z_c - z_r + b, z_c + z_r + b]`` — which
is algebraically identical to the sign-split interval image and needs only
TWO matmuls per layer.  All layers run inside one ``pallas_call``: the whole
(padded) weight stack lives in VMEM, activations never touch HBM, and one
batch tile flows through every layer back-to-back on the MXU.

Rounding: both forms are exact in real arithmetic; their f32 round-off
differs, and both are absorbed by the same outward widening
(``SOUND_SLACK_REL/ABS``) that the XLA path applies — and, as everywhere,
pruning/UNSAT soundness is anchored by the exact-rational pass, not floats.
Matmuls request ``Precision.HIGHEST`` so the MXU uses the full-f32 passes.

Nets wider than the 128-lane pad (none in the reference zoo,
``models/`` max width 100) fall back to the XLA path; on CPU backends the
kernel runs in interpreter mode (tests) unless disabled.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from fairify_tpu.models.mlp import MLP
from fairify_tpu.ops.interval import SOUND_SLACK_ABS, SOUND_SLACK_REL

LANE = 128  # pad every layer width to one MXU tile
_TILE_B = 256  # batch rows per grid step


def _supported(params: MLP) -> bool:
    # layer_sizes are the out-dims; include the input width too.  Uses static
    # shape info only, so it works on traced nets.
    d_in = int(params.weights[0].shape[0])
    return max((d_in,) + tuple(params.layer_sizes)) <= LANE


def padded_stack(params: MLP) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(L, 128, 128) weight stack, (L, 128) biases and post-ReLU masks.

    Padded rows/cols are zero weights with zero mask, so a padded dim's
    pre-activation is exactly its (zero) bias and its post-ReLU value is 0 —
    it can never leak into live dims (their padded weight rows are zero).
    Built with jnp scatter-writes so it traces under ``jit`` (the engine
    passes the net as a traced argument); XLA hoists it when weights are
    constants.
    """
    L = params.depth
    w = jnp.zeros((L, LANE, LANE), jnp.float32)
    b = jnp.zeros((L, LANE), jnp.float32)
    m = jnp.zeros((L, LANE), jnp.float32)
    for l, (wl, bl, ml) in enumerate(zip(params.weights, params.biases, params.masks)):
        n_in, n_out = wl.shape
        w = w.at[l, :n_in, :n_out].set(jnp.asarray(wl, jnp.float32))
        b = b.at[l, :n_out].set(jnp.asarray(bl, jnp.float32))
        m = m.at[l, :n_out].set(jnp.asarray(ml, jnp.float32))
    return w, b, m


def _ibp_kernel(w_ref, b_ref, m_ref, lo_ref, hi_ref, out_lo_ref, out_hi_ref, *, depth: int):
    lo = lo_ref[:]
    hi = hi_ref[:]
    for l in range(depth):  # static unroll: activations stay in registers/VMEM
        c = (lo + hi) * 0.5
        r = (hi - lo) * 0.5
        w = w_ref[l]
        zc = jax.lax.dot_general(
            c, w, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST, preferred_element_type=jnp.float32,
        )
        zr = jax.lax.dot_general(
            r, jnp.abs(w), (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST, preferred_element_type=jnp.float32,
        )
        zlo = zc - zr + b_ref[l][None, :]
        zhi = zc + zr + b_ref[l][None, :]
        slack = SOUND_SLACK_REL * jnp.maximum(jnp.abs(zlo), jnp.abs(zhi)) + SOUND_SLACK_ABS
        zlo = zlo - slack
        zhi = zhi + slack
        out_lo_ref[l] = zlo
        out_hi_ref[l] = zhi
        if l < depth - 1:
            mask = m_ref[l][None, :]
            lo = jnp.maximum(zlo, 0.0) * mask
            hi = jnp.maximum(zhi, 0.0) * mask
    # (final layer is linear: no ReLU/mask, matching the XLA path)


@functools.partial(jax.jit, static_argnames=("depth", "interpret"))
def _ibp_call(w, b, m, lo, hi, depth: int, interpret: bool):
    B = lo.shape[0]
    grid = (pl.cdiv(B, _TILE_B),)
    kernel = functools.partial(_ibp_kernel, depth=depth)
    out_shape = [
        jax.ShapeDtypeStruct((depth, B, LANE), jnp.float32),
        jax.ShapeDtypeStruct((depth, B, LANE), jnp.float32),
    ]
    from jax.experimental.pallas import tpu as pltpu

    space = pl.ANY if interpret else pltpu.VMEM
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((depth, LANE, LANE), lambda i: (0, 0, 0), memory_space=space),
            pl.BlockSpec((depth, LANE), lambda i: (0, 0), memory_space=space),
            pl.BlockSpec((depth, LANE), lambda i: (0, 0), memory_space=space),
            pl.BlockSpec((_TILE_B, LANE), lambda i: (i, 0), memory_space=space),
            pl.BlockSpec((_TILE_B, LANE), lambda i: (i, 0), memory_space=space),
        ],
        out_specs=[
            pl.BlockSpec((depth, _TILE_B, LANE), lambda i: (0, i, 0), memory_space=space),
            pl.BlockSpec((depth, _TILE_B, LANE), lambda i: (0, i, 0), memory_space=space),
        ],
        out_shape=out_shape,
        interpret=interpret,
    )(w, b, m, lo, hi)


def available(params: MLP) -> bool:
    return _supported(params)


def network_ws_bounds(params: MLP, lb: jax.Array, ub: jax.Array):
    """Pre-activation (ws) bounds for every layer via the fused kernel.

    ``lb``/``ub``: (..., d_in).  Returns per-layer (..., n_l) ws_lb/ws_ub
    tuples matching :func:`fairify_tpu.ops.interval.network_bounds` (widened).
    """
    if not _supported(params):
        raise ValueError("layer width exceeds the 128-lane pallas pad")
    w, b, m = padded_stack(params)
    batch_shape = lb.shape[:-1]
    d = lb.shape[-1]
    B = int(np.prod(batch_shape)) if batch_shape else 1
    lo = jnp.zeros((B, LANE), jnp.float32).at[:, :d].set(lb.reshape(B, d))
    hi = jnp.zeros((B, LANE), jnp.float32).at[:, :d].set(ub.reshape(B, d))
    pad_b = (-B) % _TILE_B
    if pad_b:
        lo = jnp.concatenate([lo, jnp.zeros((pad_b, LANE), jnp.float32)])
        hi = jnp.concatenate([hi, jnp.zeros((pad_b, LANE), jnp.float32)])
    interpret = jax.default_backend() != "tpu"
    out_lo, out_hi = _ibp_call(w, b, m, lo, hi, int(params.depth), interpret)
    ws_lb, ws_ub = [], []
    for l, n in enumerate(params.layer_sizes):
        ws_lb.append(out_lo[l, :B, :n].reshape(*batch_shape, n))
        ws_ub.append(out_hi[l, :B, :n].reshape(*batch_shape, n))
    return tuple(ws_lb), tuple(ws_ub)


def output_bounds(params: MLP, lb: jax.Array, ub: jax.Array):
    """Fused-kernel interval bounds of the output logit."""
    ws_lb, ws_ub = network_ws_bounds(params, lb, ub)
    return ws_lb[-1][..., 0], ws_ub[-1][..., 0]

"""Heuristic (unsound) pruning: the UNKNOWN-fallback distribution test.

Re-implements the reference's ``heuristic_prune`` (``utils/prune.py:862-939``)
as array statistics.  When the decision engine cannot decide a partition
within budget, borderline-quiet candidate neurons are killed to shrink the
problem; verdicts after heuristic pruning are flagged (the reference reports
``h_attempt``/``h_success`` and counts the result against the unsound tier).

Rules, kept bit-for-bit from the reference:

* per hidden layer, split pre-activation upper bounds (``ws_ub``) into
  simulation-candidates vs non-candidates;
* layers with no non-candidates kill every solver-surviving candidate
  (``utils/prune.py:883-885``); layers with no candidates do nothing;
* otherwise require distribution separation (non-candidate mean AND median
  > 2× candidate's, ``utils/prune.py:908``), then kill a surviving candidate
  iff its ``ws_ub`` is below the non-candidate ``perc``-percentile AND below
  ``0.1 ×`` the non-candidate ``(100-perc)``-percentile AND below ``|ws_lb|``
  (``utils/prune.py:916-921``);
* keep-one-per-layer guard, then union with the sound dead set.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

from fairify_tpu.ops import masks as mops


def heuristic_prune(
    ws_lb: Sequence[np.ndarray],
    ws_ub: Sequence[np.ndarray],
    candidates: Sequence[np.ndarray],
    surviving_candidates: Sequence[np.ndarray],
    sound_dead: Sequence[np.ndarray],
    perc_threshold: float,
):
    """Returns (heuristic_dead, merged_dead) as float arrays (1 = dead)."""
    n_layers = len(candidates)
    new_dead = [np.zeros_like(np.asarray(c), dtype=np.float32) for c in candidates]

    for l in range(n_layers - 1):
        ub = np.asarray(ws_ub[l], dtype=np.float64)
        lb = np.asarray(ws_lb[l], dtype=np.float64)
        cand_mask = np.asarray(candidates[l]) > 0.5
        surv_mask = np.asarray(surviving_candidates[l]) > 0.5

        cand = ub[cand_mask]
        noncand = ub[~cand_mask]

        if noncand.size == 0:
            # Reference kills the whole layer in this case (every index of the
            # s_candidates row, not just survivors), utils/prune.py:883-885;
            # the keep-one-alive guard below then revives neuron 0.
            new_dead[l][:] = 1.0
            continue
        if cand.size == 0:
            continue

        if np.mean(noncand) > 2 * np.mean(cand) and np.median(noncand) > 2 * np.median(cand):
            lo_perc = np.percentile(noncand, perc_threshold)
            hi_perc = np.percentile(noncand, 100 - perc_threshold)
            kill = surv_mask & (ub < lo_perc) & (ub < 0.1 * hi_perc) & (ub < np.abs(lb))
            new_dead[l][kill] = 1.0

    new_dead = [np.asarray(d) for d in mops.keep_one_alive(new_dead)]
    merged = [np.maximum(a, np.asarray(b)) for a, b in zip(new_dead, sound_dead)]
    merged = [np.asarray(d) for d in mops.keep_one_alive(merged)]
    return new_dead, merged

"""Keyed uniform-integer simulation + activation statistics, fully batched.

The reference draws 1000 random integer rows per partition with a Python
double loop (``simluate_data``, ``utils/prune.py:205-222``) and then counts
per-neuron activations by running a per-sample numpy forward pass in a
triple-nested loop (``candidate_dead_nodes``, ``utils/prune.py:168-192``) —
the hottest loop of the whole pipeline (SURVEY.md §3.1).  Here both stages
are single XLA kernels: one `jax.random.randint` draw per box and one batched
forward pass whose activation counts are a reduction over the sample axis.

Keyed PRNG replaces the reference's global `np.random` so a sweep is
reproducible per (seed, partition) regardless of execution order or sharding.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from fairify_tpu.models.mlp import MLP
from fairify_tpu.utils.num import matmul


class ActivationStats(NamedTuple):
    candidates: tuple  # per layer, (out,) 1.0 = never activated on samples
    positive_prob: tuple  # per layer, (out,) fraction of samples activating


def simulate_box(key: jax.Array, lo: jax.Array, hi: jax.Array, size: int) -> jax.Array:
    """``size`` uniform integer samples from the inclusive box [lo, hi].

    Returns float32 ``(size, d)``.  Bounds may carry a leading batch axis
    (vmap over partitions).
    """
    shape = (size,) + lo.shape
    return jax.random.randint(
        key, shape, lo.astype(jnp.int32), hi.astype(jnp.int32) + 1
    ).astype(jnp.float32)


def activation_stats(params: MLP, x: jax.Array) -> ActivationStats:
    """Per-neuron activation frequency over a sample batch ``x`` (N, d).

    A neuron that never produces a non-zero output on any sample is a
    *candidate* dead neuron — the reference's criterion
    (``utils/prune.py:176-187``), which includes the (linear) output layer;
    downstream pruning skips the output layer when converting candidates to
    dead masks.
    """
    n = params.depth
    h = x
    candidates, pos_prob = [], []
    for i, (w, b, m) in enumerate(zip(params.weights, params.biases, params.masks)):
        z = matmul(h, w) + b
        h = z if i == n - 1 else jax.nn.relu(z) * m
        active_frac = jnp.mean((h != 0.0).astype(jnp.float32), axis=0)
        candidates.append((active_frac == 0.0).astype(jnp.float32))
        pos_prob.append(active_frac)
    return ActivationStats(tuple(candidates), tuple(pos_prob))


def simulate_and_stats(params: MLP, key: jax.Array, lo: jax.Array, hi: jax.Array, size: int):
    """One fused step: sample a box and compute activation stats + samples."""
    sim = simulate_box(key, lo, hi, size)
    return activation_stats(params, sim), sim

"""Dead-neuron mask algebra: derive, guard, merge, report, excise.

Masks are per-layer float vectors with 1 = dead (matching the reference's
convention in ``utils/prune.py:168-192``), converted to *alive* masks
(1 = alive) when attached to an :class:`~fairify_tpu.models.mlp.MLP`.

The reference's excision (``prune_neurons``, ``utils/prune.py:950-977``)
mutates array shapes per partition; on TPU that would force a recompile per
partition, so the framework applies masks inside static-shape kernels and
only materializes dense matrices host-side for reporting and external
solvers (``fairify_tpu.models.mlp.excise``).
"""
from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

from fairify_tpu.models.mlp import MLP
from fairify_tpu.ops.interval import LayerBounds


def intersect_with_candidates(dead: Sequence, candidates: Sequence) -> list:
    """A neuron is only prunable if simulation also never saw it activate
    (the reference requires candidacy before bound-pruning,
    ``utils/prune.py:241-242``)."""
    return [jnp.asarray(d) * jnp.asarray(c) for d, c in zip(dead, candidates)]


def keep_one_alive(dead: Sequence) -> list:
    """Guard: never prune an entire layer — if every neuron of a layer is
    dead, revive neuron 0 (``utils/prune.py:689-691`` ``if not 0 in l: l[0]=0``).
    Fully-dead layers would otherwise collapse the network to a constant in a
    shape-breaking way for the excised form."""
    out = []
    for d in dead:
        d = jnp.asarray(d)
        all_dead = jnp.all(d > 0.5)
        revive = jnp.zeros_like(d).at[0].set(1.0)
        out.append(jnp.where(all_dead, d - revive, d))
    return out


def merge_dead(a: Sequence, b: Sequence) -> list:
    """Union of two dead-mask sets (``merge_dead_nodes``, ``utils/prune.py:941-948``)."""
    return [jnp.maximum(jnp.asarray(x), jnp.asarray(y)) for x, y in zip(a, b)]


def compression_ratio(dead: Sequence) -> float:
    """Fraction of neurons removed (``compression_ratio``, ``utils/prune.py:194-203``).

    Note: the reference computes this over *all* layers including the output
    layer; kept identical for CSV parity.
    """
    total = sum(int(np.asarray(d).size) for d in dead)
    dead_n = sum(int(np.asarray(d).sum()) for d in dead)
    return dead_n / total if total else 0.0


def alive_masks(dead: Sequence) -> list:
    """Convert dead masks (1 = dead) to alive masks (1 = alive)."""
    return [1.0 - jnp.asarray(d) for d in dead]


def apply_dead_masks(params: MLP, dead: Sequence) -> MLP:
    return params.with_masks(tuple(alive_masks(dead)))


def zero_dead_masks(params: MLP) -> list:
    return [jnp.zeros_like(b) for b in params.biases]

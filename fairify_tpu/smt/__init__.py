"""Out-of-process SMT: worker pool, wire protocol, solver backends.

The package that contains Z3 (DESIGN.md §14).  Public surface:

* :class:`fairify_tpu.smt.pool.SmtPool` / :class:`PoolConfig` — the
  worker pool (hard wall-clock kills, RSS caps, crash containment,
  parallel fan-out, portfolio racing).
* :func:`fairify_tpu.smt.pool.solve_box` / ``submit_box`` — the
  ``decide_box_smt``-shaped entry points the sweep and serve stack use.
* :mod:`fairify_tpu.smt.worker` — the subprocess entry
  (``python -m fairify_tpu.smt.worker``).
* :mod:`fairify_tpu.smt.protocol` / :mod:`fairify_tpu.smt.brute` —
  stdlib-only wire format and exact enumeration backend.

Exports resolve lazily (PEP 562): the worker subprocess imports this
package on every spawn and must never pay for the pool's obs/resilience
imports, let alone jax.
"""
from __future__ import annotations

_LAZY = {
    "SmtPool": ("fairify_tpu.smt.pool", "SmtPool"),
    "PoolConfig": ("fairify_tpu.smt.pool", "PoolConfig"),
    "SmtResult": ("fairify_tpu.smt.pool", "SmtResult"),
    "WorkerDied": ("fairify_tpu.smt.pool", "WorkerDied"),
    "solve_box": ("fairify_tpu.smt.pool", "solve_box"),
    "submit_box": ("fairify_tpu.smt.pool", "submit_box"),
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(entry[0]), entry[1])

"""SMT worker subprocess: ``python -m fairify_tpu.smt.worker``.

One worker owns one native solver at a time, and NOTHING else — no jax,
no device handles, no shared state with the host.  The host talks framed
JSON over stdin/stdout (:mod:`fairify_tpu.smt.protocol`); everything else
about the worker is disposable by design:

* **RSS cap** — ``--memory-cap-mb`` applies ``RLIMIT_AS`` before the
  first query, so a solver memory blowup lands as a Python
  ``MemoryError`` inside *this* process (reported as a clean ``memout``
  response, then exit) or as a malloc-failure death — either way the
  host's sweep never feels it.
* **hard kills are fine** — the worker holds no files open for write and
  no partial state the host cares about; the pool SIGKILLs on deadline
  and respawns.
* **chaos directives** — ``hang`` (sleep through any deadline) and
  ``memout`` (allocate past the cap) let the fault sites
  ``smt.worker.hang`` / ``smt.worker.memout`` exercise the host's
  containment against a REAL wedged/dying subprocess, not a mock.

Backends: ``z3`` parses the shipped SMT-LIB2 text with the native solver
(soft ``timeout`` + ``random_seed`` set per request — portfolio variants
differ only in seed); ``brute`` is the exact enumeration backend
(:mod:`fairify_tpu.smt.brute`), the default wherever ``z3-solver`` is not
installed; ``auto`` picks z3 when importable.
"""
from __future__ import annotations

import argparse
import sys
import time

from fairify_tpu.obs import trace as trace_mod
from fairify_tpu.smt import brute, protocol

try:  # pragma: no cover - exercised only where z3-solver is installed
    import z3  # type: ignore

    HAVE_Z3 = True
except ImportError:
    z3 = None
    HAVE_Z3 = False


def _respond(obj: dict) -> None:
    sys.stdout.write(protocol.dump_msg(obj))
    sys.stdout.flush()


def _apply_memory_cap(cap_mb: int) -> None:
    if cap_mb <= 0:
        return
    import resource

    cap = int(cap_mb) * 1024 * 1024
    resource.setrlimit(resource.RLIMIT_AS, (cap, cap))


def _solve_z3(query: dict, timeout_s: float, seed: int):
    """(verdict, ce, reason) via the native solver on the shipped script."""
    meta = query["meta"]
    s = z3.Solver()
    s.set("timeout", max(int(timeout_s * 1000), 1))
    try:
        s.set("random_seed", int(seed))
    except z3.Z3Exception:
        pass  # older solvers without the param: seedless, still sound
    s.from_string(query["smtlib"])
    res = s.check()
    if res == z3.sat:
        m = s.model()
        d = int(meta["dims"])

        def val(name):
            return int(m.eval(z3.Int(name), model_completion=True).as_long())

        ce = [[val(f"x{i}") for i in range(d)],
              [val(f"xp{i}") for i in range(d)]]
        return "sat", ce, None
    if res == z3.unsat:
        return "unsat", None, None
    return "unknown", None, protocol.unknown_reason(s.reason_unknown())


def solve_one(req: dict, backend: str, pair_cap: int) -> dict:
    """One solve request → one response dict (never raises).

    The worker's whole contract is "respond or die": any error deciding a
    query — a solver exception, a malformed script, a MemoryError under
    the RSS cap — becomes a sound UNKNOWN response (``memout`` exits
    afterwards: a heap that just failed allocation is not trustworthy for
    the next query).
    """
    qid = req.get("qid")
    t0 = time.perf_counter()
    timeout_s = float(req.get("timeout_s", 60.0))
    try:
        query = req["query"]
        if backend == "z3":
            verdict, ce, reason = _solve_z3(query, timeout_s,
                                            int(req.get("seed", 0)))
        else:
            verdict, ce, reason = brute.solve(
                query["smtlib"], query["meta"], timeout_s=timeout_s,
                pair_cap=pair_cap)
    except MemoryError:
        return {"qid": qid, "verdict": "unknown", "ce": None,
                "reason": "memout", "backend": backend, "exit": True,
                "elapsed_s": time.perf_counter() - t0}
    except BaseException as exc:  # lint: disable=obs-broad-except
        # Respond-or-die: an exception must become a sound UNKNOWN, not a
        # dead pipe the host has to classify as a crash.
        return {"qid": qid, "verdict": "unknown", "ce": None,
                "reason": "solver-error", "error": type(exc).__name__,
                "backend": backend, "elapsed_s": time.perf_counter() - t0}
    return {"qid": qid, "verdict": verdict, "ce": ce, "reason": reason,
            "backend": backend, "elapsed_s": time.perf_counter() - t0}


def _chaos_memout(qid) -> dict:
    """Allocate until the RSS cap kills the allocation (chaos directive)."""
    blocks = []
    try:
        while True:
            blocks.append(bytearray(16 * 1024 * 1024))
    except MemoryError:
        del blocks
        return {"qid": qid, "verdict": "unknown", "ce": None,
                "reason": "memout", "chaos": True, "exit": True}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--backend", default="auto",
                    choices=("auto", "z3", "brute"))
    ap.add_argument("--memory-cap-mb", type=int, default=0)
    ap.add_argument("--pair-cap", type=int, default=brute.DEFAULT_PAIR_CAP)
    ap.add_argument("--trace-dir", default=None,
                    help="shared trace-shard directory: the worker appends "
                         "its solve spans to trace.<pid>.jsonl so the "
                         "merged view shows the host-solver leg of each "
                         "request (obs.trace is stdlib-only — no jax)")
    args = ap.parse_args(argv)
    backend = args.backend
    if backend == "auto":
        backend = "z3" if HAVE_Z3 else "brute"
    if backend == "z3" and not HAVE_Z3:
        _respond({"fatal": "z3-solver is not installed in the worker env"})
        return 2
    _apply_memory_cap(args.memory_cap_mb)
    if args.trace_dir:
        # Hard kills are in this worker's contract: the shard is append-
        # per-record (flushed, no close needed), so a SIGKILL tears at
        # most the final line — same tolerance as every JSONL ledger.
        trace_mod.activate(trace_mod.Tracer(
            trace_mod.shard_path(args.trace_dir), run_id="smt-worker"))
    _respond({"hello": True, "backend": backend,
              "memory_cap_mb": args.memory_cap_mb})
    for line in sys.stdin:
        req = protocol.parse_msg(line)
        if req is None:
            continue  # torn/garbage frame: ignore, stay alive
        op = req.get("op")
        if op == "exit":
            return 0
        if op == "ping":
            _respond({"qid": req.get("qid"), "pong": True})
            continue
        if op == "hang":
            # Chaos directive: wedge like a stuck tactic — ignore the soft
            # deadline entirely; only the host's SIGKILL ends this.
            time.sleep(float(req.get("duration_s", 3600.0)))
            continue
        if op == "memout":
            _respond(_chaos_memout(req.get("qid")))
            return 0
        if op == "solve":
            # The request's trace context rides the solve frame: bind it
            # so the worker's span joins the merged tree, and echo it in
            # the response so the host can assert propagation end-to-end.
            with trace_mod.context(trace_mod.TraceContext.from_fields(req)), \
                    trace_mod.span("smt.worker_solve", qid=req.get("qid"),
                                   backend=backend):
                resp = solve_one(req, backend, args.pair_cap)
            if req.get("trace"):
                resp["trace"] = req["trace"]
            _respond(resp)
            if resp.get("exit"):
                return 0
            continue
        _respond({"qid": req.get("qid"), "verdict": "unknown",
                  "reason": "solver-error", "error": f"unknown op {op!r}"})
    return 0


if __name__ == "__main__":
    sys.exit(main())

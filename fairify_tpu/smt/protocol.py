"""Wire protocol between the SMT pool and its worker subprocesses.

One JSON object per line over the worker's stdin/stdout pipes — the same
torn-line-tolerant framing the JSONL ledgers use, chosen for the same
reason: a SIGKILLed worker can leave at most one truncated line, and the
host treats any undecodable/short read as a worker death (contained), not
a protocol error (crash).

Requests (host → worker), discriminated by ``op``:

* ``solve`` — ``{"op": "solve", "qid": n, "timeout_s": t, "seed": s,
  "query": {"smtlib": ..., "meta": {...}}}``; the query payload is
  :func:`fairify_tpu.verify.smt.build_query`'s output, i.e. the SMT-LIB2
  serialization is the ONLY thing that crosses the process boundary.
* ``hang`` / ``memout`` — chaos directives (driven by the
  ``smt.worker.hang`` / ``smt.worker.memout`` fault sites): wedge the
  worker past any deadline / allocate past the RSS cap, so the host's
  containment paths are exercised against a REAL stuck/dying subprocess.
* ``ping`` — liveness probe; ``exit`` — orderly shutdown.

Responses (worker → host): ``{"qid": n, "verdict": "sat"|"unsat"|
"unknown", "ce": [[...],[...]]|null, "reason": null|"timeout"|"memout"|
"solver-error", "elapsed_s": t, "backend": "z3"|"brute"}``.  ``reason``
uses the same taxonomy as :func:`verify.smt._unknown_reason`; the
worker-death reasons (``smt.worker:*``) are assigned by the HOST — a dead
worker by definition cannot report its own cause of death.
"""
from __future__ import annotations

import json
from typing import Optional

#: Machine-readable degradation reasons the pool assigns when a worker
#: dies (a worker cannot report these itself).  They share the namespace
#: of `ChunkFailure.reason` (site:kind) so the report's degradation
#: table and the resume machinery treat them like any other fault.
REASON_CRASH = "smt.worker:crash"
REASON_HANG = "smt.worker:hang"
REASON_MEMOUT = "smt.worker:memout"
REASON_SPAWN = "smt.worker:spawn"

#: Reasons that must SKIP the escalating-timeout ladder: re-running the
#: query at a bigger time budget cannot help (memory exhaustion only OOMs
#: harder; a deterministic solver error repeats at any budget).
NO_ESCALATE_REASONS = frozenset(
    {"memout", "solver-error", REASON_MEMOUT})


def unknown_reason(reason_str: str) -> str:
    """Map a solver's ``reason_unknown`` text to the degradation taxonomy.

    Single source of truth shared by the in-process backend
    (:func:`verify.smt._unknown_reason` delegates here) and the worker —
    kept stdlib-only so worker startup never imports the jax stack.
    ``memout`` is distinct from ``timeout``: re-running a memory-exhausted
    query at a bigger TIME budget only OOMs harder, so the escalation
    ladder must skip it (the pool's higher-RSS-cap retry is the sanctioned
    second attempt).
    """
    r = (reason_str or "").lower()
    if "memout" in r or "memory" in r or "resource" in r:
        return "memout"
    if "timeout" in r or "canceled" in r:
        return "timeout"
    return "solver-error"


def dump_msg(obj: dict) -> str:
    """One framed message (newline-terminated single-line JSON)."""
    return json.dumps(obj, separators=(",", ":")) + "\n"


def parse_msg(line: str) -> Optional[dict]:
    """Decode one framed line; None for torn/empty/undecodable input."""
    line = line.strip()
    if not line:
        return None
    try:
        obj = json.loads(line)
    except json.JSONDecodeError:
        return None
    return obj if isinstance(obj, dict) else None


def solve_request(qid: int, query: dict, timeout_s: float,
                  seed: int = 0, trace: Optional[dict] = None) -> dict:
    """One solve frame; ``trace`` is the distributed-trace context
    (``{"id": ..., "span": ...}``) the worker echoes back in its response
    and binds around its own spans — how a host-solver leg joins the
    request's merged trace tree (DESIGN.md §19)."""
    req = {"op": "solve", "qid": int(qid), "timeout_s": float(timeout_s),
           "seed": int(seed), "query": query}
    if trace:
        req["trace"] = dict(trace)
    return req


def result_ce(resp: dict):
    """Counterexample pair from a response (None when absent)."""
    import numpy as np

    ce = resp.get("ce")
    if not ce:
        return None
    return (np.asarray(ce[0], dtype=np.int64),
            np.asarray(ce[1], dtype=np.int64))

"""Exact brute-force backend: a tiny decision procedure for the emitted
SMT-LIB subset, solver-free.

``to_smtlib`` serializes every query over a FINITE integer box, so the
formula is decidable by enumeration: walk the integer assignments the
box/PA/RA constraints admit, evaluate the emitted script's define-funs in
exact :class:`fractions.Fraction` arithmetic (floats are dyadic rationals
— no rounding anywhere), and check every ``assert``.  Any satisfying
assignment is a ground-truth witness; exhausting the space is a
ground-truth UNSAT.

This is NOT a z3 replacement for production grids (a GC partition has
~10^5+ pairs per box and real sweeps hand the pool much bigger boxes) —
``pair_cap`` concedes ``unknown/"solver-error"`` past a fixed enumeration
budget, exactly like a solver conceding incompleteness.  What it buys:

* worker subprocesses give REAL verdicts in environments without
  ``z3-solver`` (this repo's CI), so the pool's containment, parity, and
  throughput contracts are pinned against genuine solving, not mocks;
* a second, independent decision procedure: where z3 IS installed, the
  agreement suite can cross-check both backends against the native
  engine on small boxes.

The evaluator supports exactly the operator set ``to_smtlib`` emits
(mirroring the pinned interpreter in ``tests/test_smt.py``); an
unsupported operator is a ``solver-error``, never a wrong verdict.
"""
from __future__ import annotations

import time
from fractions import Fraction
from itertools import product
from typing import Dict, List, Optional, Tuple

#: Enumeration budget: queries whose admissible-pair count exceeds this
#: are conceded unknown (deterministically) instead of ground to dust.
DEFAULT_PAIR_CAP = 200_000


def _tokenize(text: str):
    for line in text.splitlines():
        line = line.split(";", 1)[0]
        for tok in line.replace("(", " ( ").replace(")", " ) ").split():
            yield tok


def parse_script(text: str) -> List:
    """All top-level s-expressions of an SMT-LIB script."""
    toks = list(_tokenize(text))
    pos = 0

    def parse():
        nonlocal pos
        tok = toks[pos]
        pos += 1
        if tok == "(":
            items = []
            while toks[pos] != ")":
                items.append(parse())
            pos += 1
            return items
        return tok

    forms = []
    while pos < len(toks):
        forms.append(parse())
    return forms


class UnsupportedForm(ValueError):
    """The script uses a form outside the emitted subset."""


def _ev(e, env: Dict[str, object]):
    if isinstance(e, str):
        if e in env:
            return env[e]
        if e == "true":
            return True
        if e == "false":
            return False
        return Fraction(e)
    op = e[0]
    if op == "+":
        return sum((_ev(a, env) for a in e[1:]), Fraction(0))
    if op == "*":
        r = Fraction(1)
        for a in e[1:]:
            r *= _ev(a, env)
        return r
    if op == "-":
        if len(e) == 2:
            return -_ev(e[1], env)
        return _ev(e[1], env) - _ev(e[2], env)
    if op == "/":
        return _ev(e[1], env) / _ev(e[2], env)
    if op == "to_real":
        return _ev(e[1], env)
    if op == "ite":
        return _ev(e[2], env) if _ev(e[1], env) else _ev(e[3], env)
    if op == ">=":
        return _ev(e[1], env) >= _ev(e[2], env)
    if op == "<=":
        return _ev(e[1], env) <= _ev(e[2], env)
    if op == ">":
        return _ev(e[1], env) > _ev(e[2], env)
    if op == "<":
        return _ev(e[1], env) < _ev(e[2], env)
    if op == "=":
        return _ev(e[1], env) == _ev(e[2], env)
    if op == "distinct":
        return _ev(e[1], env) != _ev(e[2], env)
    if op == "and":
        return all(_ev(a, env) for a in e[1:])
    if op == "or":
        return any(_ev(a, env) for a in e[1:])
    if op == "not":
        return not _ev(e[1], env)
    if op == "let":
        inner = dict(env)
        for name, expr in e[1]:
            inner[name] = _ev(expr, env)
        return _ev(e[2], inner)
    raise UnsupportedForm(f"unhandled op {op!r}")


def _pair_count(meta: dict) -> int:
    """Admissible (x, x') assignments under the box/PA/RA constraints."""
    lo, hi = meta["lo"], meta["hi"]
    pa, ra, eps = set(meta["pa"]), set(meta["ra"]), int(meta["eps"])
    n = 1
    for i in range(len(lo)):
        size = int(hi[i]) - int(lo[i]) + 1
        n *= size
        if i in pa:
            n *= max(size - 1, 0)
        elif i in ra:
            n *= 2 * eps + 1
    return n


def _partner_choices(meta: dict) -> List[Tuple[int, str]]:
    """Per-dim partner rule: ('pa'|'ra'|'eq') in dim order."""
    pa, ra = set(meta["pa"]), set(meta["ra"])
    out = []
    for i in range(len(meta["lo"])):
        out.append((i, "pa" if i in pa else ("ra" if i in ra else "eq")))
    return out


def solve(smtlib: str, meta: dict, timeout_s: float = 60.0,
          pair_cap: int = DEFAULT_PAIR_CAP):
    """Decide one emitted script by exact enumeration.

    Returns ``(verdict, ce, reason)`` with the same contract as
    :func:`verify.smt.decide_box_smt`: ``ce`` is an int-list pair for
    ``sat``; ``reason`` is ``None`` / ``"timeout"`` / ``"solver-error"``.
    """
    lo = [int(v) for v in meta["lo"]]
    hi = [int(v) for v in meta["hi"]]
    eps = int(meta["eps"])
    d = len(lo)
    if _pair_count(meta) > pair_cap:
        return "unknown", None, "solver-error"
    try:
        forms = parse_script(smtlib)
    except (IndexError, ValueError):
        return "unknown", None, "solver-error"
    defs = [f for f in forms if f and f[0] == "define-fun"]
    asserts = [f[1] for f in forms if f and f[0] == "assert"]
    # Split the straight-line network into its two role halves: a_* funs
    # read only x-vars, b_* only xp-vars — evaluating the x half once per
    # x instead of once per pair is the whole enumeration speedup.
    a_defs = [f for f in defs if f[1].startswith("a_")]
    b_defs = [f for f in defs if f[1].startswith("b_")]
    other = [f for f in defs if not (f[1].startswith(("a_", "b_")))]
    if other:
        return "unknown", None, "solver-error"
    rules = _partner_choices(meta)
    deadline = time.monotonic() + max(float(timeout_s), 1e-3)
    checked = 0
    try:
        for x in product(*(range(lo[i], hi[i] + 1) for i in range(d))):
            env_x: Dict[str, object] = {f"x{i}": Fraction(x[i])
                                        for i in range(d)}
            for f in a_defs:
                env_x[f[1]] = _ev(f[4], env_x)
            partner_axes = []
            for i, kind in rules:
                if kind == "pa":
                    partner_axes.append([v for v in range(lo[i], hi[i] + 1)
                                         if v != x[i]])
                elif kind == "ra":
                    # x' is NOT box-constrained on RA dims (the emitted
                    # formula drops that constraint, like the reference).
                    partner_axes.append(list(range(x[i] - eps,
                                                   x[i] + eps + 1)))
                else:
                    partner_axes.append([x[i]])
            for xp in product(*partner_axes):
                checked += 1
                if checked % 512 == 0 and time.monotonic() > deadline:
                    return "unknown", None, "timeout"
                env = dict(env_x)
                env.update({f"xp{i}": Fraction(xp[i]) for i in range(d)})
                for f in b_defs:
                    env[f[1]] = _ev(f[4], env)
                if all(_ev(a, env) for a in asserts):
                    return "sat", [list(map(int, x)), list(map(int, xp))], None
    except UnsupportedForm:
        return "unknown", None, "solver-error"
    return "unsat", None, None

"""Crash-contained out-of-process SMT worker pool.

The ONLY way the sweep, its UNKNOWN-retry ladder, and the serve stack
reach a native solver.  Queries are serialized via ``verify.smt.
build_query`` (the ``to_smtlib`` emitter — nothing but text crosses the
process boundary) and dispatched to N worker subprocesses
(:mod:`fairify_tpu.smt.worker`), each disposable:

* **hard wall-clock kill** — every dispatch has a deadline of its solver
  tier's soft timeout plus ``grace_s``; a worker that has not answered by
  then is SIGKILLed (z3's soft ``timeout`` is best-effort — a wedged
  tactic ignores it, and before this pool it wedged the whole run).
* **RSS cap** — workers start under ``RLIMIT_AS``
  (``memory_cap_mb``), so a solver memory blowup dies in its own
  process; the pool retries the query ONCE on a fresh worker with a
  doubled cap (``memout`` never enters the timeout-escalation ladder —
  more time only OOMs harder).
* **crash containment** — any worker death (EOF, SIGKILL, kernel OOM) is
  classified through the ``resilience.supervisor`` transient/fatal
  taxonomy and retried on a fresh worker up to ``max_retries``;
  exhaustion degrades exactly that query to UNKNOWN with a
  machine-readable reason (``smt.worker:crash|hang|memout|spawn``) —
  never a crashed run or a hung server.
* **parallel fan-out** — ``submit_serialized`` returns a future;
  UNKNOWN boxes from a chunk fan out across all workers (z3 is
  single-threaded; the pre-pool UNKNOWN-retry ladder was serial).
* **portfolio racing** — ``portfolio=K`` races K seed variants of the
  same query on K workers and takes the first decisive answer.  The
  VERDICT is deterministic (every variant is sound, so decisive answers
  agree); the witness and which variant wins are not — DESIGN.md §14.

Chaos: the ``smt.worker.{spawn,crash,hang,memout}`` fault sites fire in
the dispatch path and convert to REAL subprocess events — a crash fault
SIGKILLs the live worker mid-query, a hang fault wedges it past the
deadline, a memout fault makes it allocate past its cap — so the chaos
suite exercises the true containment machinery, not a simulation of it.
Arrival counting is per dispatch attempt; deterministic schedules want
``workers=1`` or ``N+`` specs (concurrent dispatch order is not).
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from fairify_tpu import obs
from fairify_tpu.obs import trace as trace_mod
from fairify_tpu.resilience import faults as faults_mod
from fairify_tpu.resilience.faults import InjectedFault
from fairify_tpu.smt import protocol
from fairify_tpu.smt.brute import DEFAULT_PAIR_CAP


@dataclass(frozen=True)
class PoolConfig:
    """Knobs of one pool (the ``--smt-*`` CLI flags)."""

    workers: int = 1
    # RLIMIT_AS per worker, MB; 0 = uncapped (no memout containment, no
    # higher-cap retry tier).
    memory_cap_mb: int = 0
    # K seed variants raced per query; 0/1 = off.  Each variant occupies
    # a worker, so the pool sizes its dispatch concurrency to
    # workers // K.
    portfolio: int = 0
    backend: str = "auto"          # auto | z3 | brute (worker --backend)
    grace_s: float = 1.0           # SIGKILL this long after the deadline
    max_retries: int = 2           # fresh-worker retries per query
    backoff_s: float = 0.02        # first respawn backoff (jittered, 2x)
    pair_cap: int = DEFAULT_PAIR_CAP  # brute backend enumeration budget
    seed: int = 0
    spawn_timeout_s: float = 20.0  # worker hello deadline
    # Shared trace-shard directory (obs.trace.shard_path): workers append
    # their solve spans to trace.<pid>.jsonl there; None = no worker-side
    # tracing (trace contexts still ride the solve frames either way).
    trace_dir: Optional[str] = None


@dataclass
class SmtResult:
    """One query's pooled outcome (the ``decide_box_smt`` triple + audit)."""

    verdict: str                   # 'sat' | 'unsat' | 'unknown'
    ce: Optional[Tuple] = None
    reason: Optional[str] = None   # None for decided; taxonomy code else
    attempts: int = 0              # dispatches actually made
    elapsed_s: float = 0.0
    backend: str = ""

    @property
    def triple(self):
        return self.verdict, self.ce, self.reason


class WorkerDied(RuntimeError):
    """A worker failed to answer: crashed, hung past deadline, or could
    not spawn.  ``kind`` ∈ {crash, hang, spawn, memout}; ``injected`` is
    the fault kind when the chaos machinery caused it (drives the
    transient/fatal classification)."""

    def __init__(self, kind: str, detail: str, injected: Optional[str] = None):
        super().__init__(f"smt worker {kind}: {detail}")
        self.kind = kind
        self.injected = injected


class _Worker:
    """One live subprocess + its pipes.  NOT thread-safe: a worker is
    owned by exactly one dispatch between checkout and checkin."""

    _next_id = 0

    def __init__(self, cfg: PoolConfig, cap_mb: int):
        _Worker._next_id += 1
        self.id = _Worker._next_id
        self.cap_mb = cap_mb
        cmd = [sys.executable, "-m", "fairify_tpu.smt.worker",
               "--backend", cfg.backend,
               "--memory-cap-mb", str(int(cap_mb)),
               "--pair-cap", str(int(cfg.pair_cap))]
        if cfg.trace_dir:
            cmd += ["--trace-dir", cfg.trace_dir]
        self.proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, bufsize=1,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        hello = self.recv(cfg.spawn_timeout_s)
        if hello is None or not hello.get("hello"):
            self.kill()
            raise WorkerDied("spawn", f"no hello from worker {self.id} "
                                      f"({hello!r})")
        self.backend = hello.get("backend", "?")

    def alive(self) -> bool:
        return self.proc.poll() is None

    def send(self, obj: dict) -> None:
        try:
            self.proc.stdin.write(protocol.dump_msg(obj))
            self.proc.stdin.flush()
        except (OSError, ValueError) as exc:
            raise WorkerDied("crash", f"write to worker {self.id}: {exc}")

    def recv(self, timeout_s: float) -> Optional[dict]:
        """One framed response; None on deadline (caller kills), raises
        :class:`WorkerDied` on EOF (the worker is gone)."""
        import select

        fd = self.proc.stdout
        deadline = time.monotonic() + max(timeout_s, 0.0)
        while True:
            left = deadline - time.monotonic()
            if left <= 0.0:
                return None
            ready, _, _ = select.select([fd], [], [], min(left, 0.5))
            if not ready:
                continue
            line = fd.readline()
            if line == "":
                raise WorkerDied("crash", f"worker {self.id} EOF "
                                          f"(rc={self.proc.poll()})")
            msg = protocol.parse_msg(line)
            if msg is not None:
                return msg

    def kill(self) -> None:
        if self.proc.poll() is None:
            try:
                self.proc.send_signal(signal.SIGKILL)
            except OSError:
                pass
        try:
            self.proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            pass
        for fp in (self.proc.stdin, self.proc.stdout):
            try:
                if fp is not None:
                    fp.close()
            except OSError:
                pass


class SmtPool:
    """See module docstring.  Thread-safe; one instance per run/server."""

    def __init__(self, cfg: PoolConfig = PoolConfig()):
        import numpy as np

        self.cfg = cfg
        self._cv = threading.Condition()
        self._idle: List[_Worker] = []
        self._spawned: List[_Worker] = []  # every worker ever forked
        self._n_live = 0
        self._queued = 0
        self._active = 0
        self._closed = False
        self._query_s_ema: Optional[float] = None
        self._rng = np.random.default_rng(cfg.seed)
        self._threads: List[threading.Thread] = []
        # (future, query, soft_timeout, retry_tiers, trace_ctx): the trace
        # context is captured at submit and re-bound in the dispatch lane —
        # lanes are pool threads, and thread-locals never cross a handoff.
        self._pending: List[Tuple[Future, dict, float, tuple,
                                  Optional[trace_mod.TraceContext]]] = []

    # --- introspection (heartbeat / admission) ----------------------------

    def stats(self) -> Dict[str, int]:
        with self._cv:
            return {"workers": self._n_live, "active": self._active,
                    "queued": self._queued}

    def live_workers(self) -> List[subprocess.Popen]:
        """Procs of every live worker (chaos tests SIGKILL these)."""
        with self._cv:
            return [w.proc for w in self._spawned if w.alive()]

    def backlog_s(self) -> float:
        """Predicted seconds of queued+active host solving (0 until a
        query-time EMA exists — no evidence, no backlog claim).  The serve
        admission controller folds this into SLA feasibility so an
        UNKNOWN-heavy request cannot admit a deadline the Z3 phase will
        blow."""
        with self._cv:
            if self._query_s_ema is None:
                return 0.0
            depth = self._queued + self._active
            lanes = max(self.cfg.workers, 1)
            return depth * self._query_s_ema / lanes

    def _observe_query_s(self, elapsed: float) -> None:
        with self._cv:
            self._query_s_ema = elapsed if self._query_s_ema is None else \
                0.3 * elapsed + 0.7 * self._query_s_ema

    def _gauges(self) -> None:
        reg = obs.registry()
        st = self.stats()
        reg.gauge("smt_pool_workers").set(st["workers"])
        reg.gauge("smt_pool_active").set(st["active"])
        reg.gauge("smt_pool_queue_depth").set(st["queued"])

    # --- worker lifecycle -------------------------------------------------

    def _spawn(self, cap_mb: int) -> _Worker:
        """Fresh worker under supervision of the ``smt.worker.spawn`` site."""
        from fairify_tpu.resilience.supervisor import classify

        retries = 0
        while True:
            try:
                faults_mod.check("smt.worker.spawn")
                w = _Worker(self.cfg, cap_mb)
                with self._cv:
                    self._spawned.append(w)
                return w
            except BaseException as exc:
                cls = classify(exc)
                if cls == "propagate":
                    raise
                if cls == "fatal" or retries >= self.cfg.max_retries:
                    inj = exc.kind if isinstance(exc, InjectedFault) else None
                    raise WorkerDied("spawn", f"{type(exc).__name__}: {exc}",
                                     injected=inj)
                retries += 1
                time.sleep(self.cfg.backoff_s * (2.0 ** (retries - 1))
                           * (1.0 + float(self._rng.random())))

    def _checkout(self, cap_mb: Optional[int] = None) -> _Worker:
        """An idle worker with an adequate cap (spawning under the pool
        size limit; a higher-cap memout retry always spawns fresh)."""
        want = self.cfg.memory_cap_mb if cap_mb is None else cap_mb
        if cap_mb is not None:
            # Dedicated higher-cap worker: never pulled from the idle set
            # (those run at the configured cap), never counted against the
            # pool width — it exists for exactly one retry.
            return self._spawn(cap_mb)
        with self._cv:
            while True:
                if self._closed:
                    raise WorkerDied("spawn", "pool closed")
                if self._idle:
                    return self._idle.pop()
                if self._n_live < self.cfg.workers:
                    self._n_live += 1
                    break
                self._cv.wait(timeout=0.5)
        try:
            return self._spawn(want)
        except BaseException:
            with self._cv:
                self._n_live -= 1
                self._cv.notify_all()
            raise

    def _checkin(self, w: _Worker, dedicated: bool = False) -> None:
        if dedicated:
            w.kill()
            return
        with self._cv:
            if w.alive() and not self._closed:
                self._idle.append(w)
            else:
                w.kill()
                self._n_live -= 1
            self._cv.notify_all()

    def _discard(self, w: _Worker, dedicated: bool = False) -> None:
        w.kill()
        if not dedicated:
            with self._cv:
                self._n_live -= 1
                self._cv.notify_all()

    # --- dispatch ---------------------------------------------------------

    def _dispatch(self, query: dict, timeout_s: float, seed: int,
                  cap_mb: Optional[int] = None) -> dict:
        """One query on one worker under the hard deadline.

        Raises :class:`WorkerDied` on any worker death; the chaos sites
        fire here and convert to real subprocess events (see module
        docstring)."""
        directive = None
        injected: Optional[str] = None
        try:
            faults_mod.check("smt.worker.crash")
            faults_mod.check("smt.worker.hang")
            faults_mod.check("smt.worker.memout")
        except InjectedFault as f:
            directive = f.site.rsplit(".", 1)[-1]
            injected = f.kind
            if f.kind == "crash":
                raise  # crash-kind faults always propagate (taxonomy)
        dedicated = cap_mb is not None
        w = self._checkout(cap_mb)
        with self._cv:
            self._active += 1
        self._gauges()
        t0 = time.perf_counter()
        try:
            if directive == "crash":
                # Chaos: SIGKILL the live worker mid-query — dispatch
                # proceeds against the corpse so the REAL death path runs.
                w.kill()
            elif directive == "hang":
                w.send({"op": "hang", "duration_s": 3600.0})
            elif directive == "memout":
                w.send({"op": "memout", "qid": 0})
            try:
                w.send(protocol.solve_request(
                    0, query, timeout_s, seed=seed,
                    trace=trace_mod.context_fields().get("trace")))
                resp = w.recv(timeout_s + self.cfg.grace_s)
            except WorkerDied as exc:
                self._discard(w, dedicated)
                obs.registry().counter("smt_worker_crashes").inc(kind="crash")
                raise WorkerDied("crash", str(exc), injected=injected)
            if resp is None:
                # Hard deadline: the worker ignored its soft timeout
                # (wedged tactic / chaos hang) — SIGKILL within grace.
                self._discard(w, dedicated)
                obs.registry().counter("smt_worker_crashes").inc(kind="hang")
                raise WorkerDied(
                    "hang", f"no answer within {timeout_s}s + "
                            f"{self.cfg.grace_s}s grace", injected=injected)
            if resp.get("exit") or resp.get("reason") == "memout":
                # A worker that just blew its heap is not reusable.
                self._discard(w, dedicated)
                obs.registry().counter("smt_memouts").inc()
                if injected == "memout" or resp.get("chaos") or directive:
                    resp = dict(resp, injected=injected)
            else:
                self._checkin(w, dedicated)
            self._observe_query_s(time.perf_counter() - t0)
            return resp
        finally:
            with self._cv:
                self._active -= 1
            self._gauges()

    def _solve_attempts(self, query: dict, tiers: Sequence[float],
                        seed: int) -> SmtResult:
        """The containment state machine for ONE query (no portfolio):
        tier escalation on clean timeouts, bounded fresh-worker retries on
        deaths, one higher-cap retry on memout."""
        t_start = time.perf_counter()
        attempts = 0
        crash_retries = 0
        memout_retried = False
        cap_override: Optional[int] = None
        last_reason = "timeout"
        ti = 0
        while ti < len(tiers):
            attempts += 1
            try:
                resp = self._dispatch(query, float(tiers[ti]), seed,
                                      cap_mb=cap_override)
            except WorkerDied as exc:
                reason = {"spawn": protocol.REASON_SPAWN,
                          "hang": protocol.REASON_HANG}.get(
                              exc.kind, protocol.REASON_CRASH)
                if exc.injected == "fatal" or exc.kind == "spawn" \
                        or crash_retries >= self.cfg.max_retries:
                    obs.registry().counter("smt_queries").inc(
                        verdict="unknown", reason=reason)
                    return SmtResult("unknown", None, reason,
                                     attempts=attempts,
                                     elapsed_s=time.perf_counter() - t_start)
                crash_retries += 1
                obs.registry().counter("launch_retries").inc(
                    site="smt.worker")
                time.sleep(self.cfg.backoff_s * (2.0 ** (crash_retries - 1)))
                continue  # fresh worker, same tier
            cap_override = None
            verdict = resp.get("verdict", "unknown")
            if verdict in ("sat", "unsat"):
                obs.registry().counter("smt_queries").inc(verdict=verdict)
                return SmtResult(verdict, protocol.result_ce(resp), None,
                                 attempts=attempts,
                                 elapsed_s=time.perf_counter() - t_start,
                                 backend=resp.get("backend", ""))
            reason = resp.get("reason") or "solver-error"
            last_reason = reason
            if reason == "timeout":
                ti += 1  # escalate to the next tier of the ladder
                continue
            if reason == "memout":
                died = bool(resp.get("exit") or resp.get("chaos"))
                worker_reason = protocol.REASON_MEMOUT if died else "memout"
                if not memout_retried and self.cfg.memory_cap_mb > 0:
                    # The sanctioned second attempt: same tier, one fresh
                    # worker at double the RSS cap — never a bigger time
                    # budget (that only OOMs harder).
                    memout_retried = True
                    cap_override = self.cfg.memory_cap_mb * 2
                    continue
                obs.registry().counter("smt_queries").inc(
                    verdict="unknown", reason=worker_reason)
                return SmtResult("unknown", None, worker_reason,
                                 attempts=attempts,
                                 elapsed_s=time.perf_counter() - t_start)
            obs.registry().counter("smt_queries").inc(verdict="unknown",
                                                      reason=reason)
            return SmtResult("unknown", None, reason, attempts=attempts,
                             elapsed_s=time.perf_counter() - t_start)
        obs.registry().counter("smt_queries").inc(verdict="unknown",
                                                  reason=last_reason)
        return SmtResult("unknown", None, last_reason, attempts=attempts,
                         elapsed_s=time.perf_counter() - t_start)

    def solve_serialized(self, query: dict, soft_timeout_s: float = 100.0,
                         retry_timeouts_s: Sequence[float] = ()) -> SmtResult:
        """Decide one serialized query (build with ``verify.smt.
        build_query``), racing ``portfolio`` seed variants when enabled."""
        tiers = (float(soft_timeout_s),) + tuple(retry_timeouts_s)
        k = max(int(self.cfg.portfolio), 1)
        with obs.span("smt.pool_query", tiers=len(tiers), portfolio=k):
            if k <= 1:
                return self._solve_attempts(query, tiers, self.cfg.seed)
            return self._solve_portfolio(query, tiers, k)

    def _solve_portfolio(self, query: dict, tiers: Sequence[float],
                         k: int) -> SmtResult:
        """Race k seed variants; first DECISIVE answer wins.

        Soundness makes the verdict deterministic — any two decisive
        answers agree — so losers are simply abandoned (their workers
        finish their soft timeout and return to the idle set; no kill
        races).  All-indecisive keeps the most actionable reason
        (worker-death > memout > timeout > solver-error)."""
        done = threading.Event()
        state_lock = threading.Lock()
        results: List[Optional[SmtResult]] = [None] * k
        remaining = [k]

        def run(i: int) -> None:
            res = self._solve_attempts(query, tiers, self.cfg.seed + i)
            with state_lock:
                results[i] = res
                remaining[0] -= 1
                if res.verdict != "unknown" or remaining[0] == 0:
                    done.set()

        threads = [threading.Thread(target=run, args=(i,), daemon=True)
                   for i in range(k)]
        for t in threads:
            t.start()
        # First decisive answer wins IMMEDIATELY — the losers are left to
        # run out their soft timeouts in the background (their workers
        # rejoin the idle set on their own); joining them here would make
        # portfolio strictly slower than a single attempt.
        done.wait()
        with state_lock:
            snapshot = list(results)
        decisive = [r for r in snapshot if r is not None
                    and r.verdict != "unknown"]
        if decisive:
            return decisive[0]
        rank = {protocol.REASON_CRASH: 0, protocol.REASON_HANG: 0,
                protocol.REASON_SPAWN: 0, protocol.REASON_MEMOUT: 1,
                "memout": 1, "timeout": 2, "solver-error": 3}
        known = [r for r in snapshot if r is not None]
        if not known:
            return SmtResult("unknown", None, protocol.REASON_CRASH)
        return sorted(known, key=lambda r: rank.get(r.reason, 4))[0]

    # --- fan-out ----------------------------------------------------------

    def submit_serialized(self, query: dict, soft_timeout_s: float = 100.0,
                          retry_timeouts_s: Sequence[float] = ()) -> Future:
        """Async fan-out: queue the query, return a Future[SmtResult].

        Dispatch lanes are sized to the worker count (each lane occupies
        one worker; a portfolio solve occupies K), so submitting a whole
        chunk of UNKNOWN boxes saturates the pool."""
        fut: Future = Future()
        with self._cv:
            if self._closed:
                fut.set_result(SmtResult(
                    "unknown", None, protocol.REASON_SPAWN))
                return fut
            self._queued += 1
            self._pending.append(
                (fut, query, float(soft_timeout_s), tuple(retry_timeouts_s),
                 trace_mod.current_context()))
            lanes = max(self.cfg.workers // max(self.cfg.portfolio, 1), 1)
            live = [t for t in self._threads if t.is_alive()]
            self._threads = live
            if len(live) < min(lanes, self._queued):
                t = threading.Thread(target=self._lane, daemon=True,
                                     name=f"smt-lane-{len(live)}")
                self._threads.append(t)
                t.start()
            self._cv.notify_all()
        self._gauges()
        return fut

    def _lane(self) -> None:
        """One dispatch lane: drain pending queries until none are left."""
        while True:
            with self._cv:
                if not self._pending or self._closed:
                    return
                fut, query, soft, retries, ctx = self._pending.pop(0)
                self._queued -= 1
            self._gauges()
            if not fut.set_running_or_notify_cancel():
                continue  # cancelled while queued (e.g. heuristic decided)
            try:
                with trace_mod.context(ctx):
                    fut.set_result(self.solve_serialized(
                        query, soft_timeout_s=soft, retry_timeouts_s=retries))
            except BaseException as exc:
                from fairify_tpu.resilience.supervisor import classify

                fut.set_exception(exc)
                if classify(exc) == "propagate":
                    return  # interrupt/crash-fault: the lane dies with it
                # Anything else is contained in the future; the lane keeps
                # draining so sibling queries never stall.

    # --- lifecycle --------------------------------------------------------

    def close(self) -> None:
        with self._cv:
            self._closed = True
            pending = list(self._pending)
            self._pending.clear()
            self._queued = 0
            idle = list(self._idle)
            self._idle.clear()
            threads = list(self._threads)
            self._cv.notify_all()
        for fut, _q, _s, _r, _ctx in pending:
            if fut.cancel():
                continue
            if not fut.done():
                fut.set_result(SmtResult("unknown", None,
                                         protocol.REASON_SPAWN))
        for w in idle:
            w.kill()
        for t in threads:
            t.join(timeout=10.0)
        with self._cv:
            self._n_live = max(self._n_live - len(idle), 0)
            spawned = list(self._spawned)
            self._spawned.clear()
        for w in spawned:  # belt-and-braces: no worker outlives its pool
            w.kill()
        self._gauges()  # a closed pool reads 0/0 on the heartbeat

    def __enter__(self) -> "SmtPool":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def solve_box(pool: SmtPool, net, enc, lo, hi, soft_timeout_s: float = 100.0,
              retry_timeouts_s: Sequence[float] = ()):
    """Pooled drop-in for ``verify.smt.decide_box_smt``: same
    ``(verdict, ce, reason)`` triple, solver out of process."""
    from fairify_tpu.verify import smt as smt_mod

    query = smt_mod.build_query(net, enc, lo, hi)
    return pool.solve_serialized(
        query, soft_timeout_s=soft_timeout_s,
        retry_timeouts_s=retry_timeouts_s).triple


def submit_box(pool: SmtPool, net, enc, lo, hi,
               soft_timeout_s: float = 100.0,
               retry_timeouts_s: Sequence[float] = ()) -> Future:
    """Async ``solve_box`` (Future[SmtResult]) — the sweep's fan-out API."""
    from fairify_tpu.verify import smt as smt_mod

    query = smt_mod.build_query(net, enc, lo, hi)
    return pool.submit_serialized(query, soft_timeout_s=soft_timeout_s,
                                  retry_timeouts_s=retry_timeouts_s)

from fairify_tpu.cli import main

raise SystemExit(main())

"""Out-of-process replica fleet: OS-process workers, hard-kill containment.

The thread fleet (``serve/fleet.py``) bounds *scheduling* blast radius —
but a thread replica cannot be contained: a wedged XLA launch hangs the
process, a native crash or memory blowup takes every replica down, and
``ReplicaKilled`` only simulates a kill at cooperative yield points.
This router runs N replicas as real OS processes (``python -m
fairify_tpu.serve.replica``), applying the PR 10 SMT-pool containment
contract to the device-launch side itself — the last uncontained failure
domain in the serving stack:

* **control plane** — newline-framed JSON over each replica's pipes
  (:mod:`fairify_tpu.smt.protocol` framing: a SIGKILL tears at most one
  line) carries hello/status/drain; **data plane** is the spool — the
  router owns the fleet inbox and routes payload files into per-replica
  sub-inboxes (``<spool>/replicas/<i>/inbox``) by atomic rename, while
  every replica writes request sinks into the SHARED ``<spool>/requests``
  tree, so a request's result_dir (and its crash-safe verdict ledger)
  survives any number of owner changes.
* **death is classified, not guessed** (the PR 4 taxonomy at process
  granularity): ``crash`` — waitpid returned (any signal or nonzero
  exit); ``memout`` — the replica's distinct ``EXIT_MEMOUT`` code (its
  ``RLIMIT_AS`` cap landed); ``hang`` — the file lease
  (``replicas/<i>/lease``, beaten at batch iterations and span granules)
  aged past ``lease_s`` while the process lived, answered by escalating
  SIGTERM → SIGKILL after ``term_grace_s`` — the watchdog a thread fleet
  can never have, and the only cure for a SIGSTOP/wedged-launch replica;
  ``spawn`` — no hello within ``spawn_timeout_s`` or a fork/exec
  failure; ``integrity`` — the replica's metrics beat reported
  ``integrity_violations > 0`` (it detected silent data corruption in
  its own data path, DESIGN.md §21): the process is alive but no longer
  trusted, so it is killed and failed over like a death.
  ``replica.spawn`` and ``replica.lease`` are the chaos sites.
* **restarts are bounded, jittered backoff** — each death schedules a
  respawn at ``backoff_s * 2^n * jitter`` up to ``max_restarts`` per
  slot; an exhausted slot is abandoned (its work re-homes) rather than
  flap-looped.
* **failover is loss-free** — a dead replica's unpicked sub-inbox
  payloads move back to the fleet inbox by rename, and every picked but
  non-terminal request (tracked via the control-pipe status stream,
  cross-checked against the on-disk terminal ``status.json``) is
  re-written there from the router's payload table; the next scan routes
  them to survivors.  The payload carries the original ``submitted_ts``
  (SLA clock) and ``id`` (result_dir), so the survivor's ``resume=True``
  run replays the partial ledger — decided verdicts survive a literal
  ``kill -9`` bit-for-bit, and only undecided work is re-attempted.
  With no survivors the payloads simply WAIT in the fleet inbox: loss-
  free by construction, picked up by the next healthy replica or fleet.

Because replicas are processes, they are not GIL-bound: on a multi-core
host N replicas verify N requests genuinely in parallel (SERVE_r03
measures this against SERVE_r02's thread fleet).  The shared persistent
executable cache (``exec_cache``) makes a restarted replica warm from
disk — cold restart compiles nothing.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from fairify_tpu import obs
from fairify_tpu.obs.heartbeat import FleetPulse
from fairify_tpu.resilience import faults as faults_mod
from fairify_tpu.resilience.supervisor import classify
from fairify_tpu.serve.client import write_atomic_json
from fairify_tpu.serve.request import DONE, FAILED, REJECTED
from fairify_tpu.serve.server import ServeConfig
from fairify_tpu.smt import protocol

#: Statuses after which a request needs no re-homing (``requeued`` is NOT
#: terminal here: a replica-drain requeue parks the payload back in a
#: sub-inbox, and the router must still collect it).
_TERMINAL = (DONE, FAILED, REJECTED)

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@dataclass(frozen=True)
class ProcFleetConfig:
    """Fleet knobs (``fairify_tpu serve --replica-procs N``)."""

    n_replicas: int = 2
    # Fleet spool root (REQUIRED: processes have no in-process submit
    # path — the spool protocol is the data plane).
    spool: str = ""
    # Router tick: inbox scan + health sweep interval.
    poll_s: float = 0.05
    # Hang detection: a replica whose file lease is older than this is
    # declared wedged and killed (SIGTERM → SIGKILL).  0 disables — a
    # granule-less request legitimately goes dark for its whole runtime,
    # so pair a nonzero lease with ``replica.span_chunks > 0``.
    lease_s: float = 0.0
    # SIGTERM → SIGKILL escalation window for hang containment (a
    # SIGSTOPped replica ignores SIGTERM; only the SIGKILL lands).
    term_grace_s: float = 2.0
    # Hello deadline: jax import + device init + exec-cache load happen
    # before the replica says hello.
    spawn_timeout_s: float = 120.0
    # Bounded restart policy per replica slot.
    max_restarts: int = 3
    backoff_s: float = 0.25          # first respawn backoff (jittered, 2x)
    # RLIMIT_AS per replica process, MB; 0 = uncapped (no memout
    # containment).
    memory_cap_mb: int = 0
    # Shared persistent executable cache ("auto" = <spool>/exec-cache;
    # None/"" = off).  What makes a restarted replica warm from disk.
    exec_cache: Optional[str] = "auto"
    # Throttled "replicas alive k/N" stderr line interval; 0 disables.
    pulse_s: float = 5.0
    # Shared trace-shard directory (DESIGN.md §19).  When set, every
    # replica appends spans to ``trace.<pid>.jsonl`` there (and hands the
    # directory on to its SMT workers); ``fairify_tpu report --trace-dir``
    # merges the shards into one fleet-wide Perfetto timeline.  None = no
    # per-replica shards (replicas trace only if the template says so).
    trace_dir: Optional[str] = None
    # Graceful-drain wait per replica before SIGTERM/SIGKILL escalation.
    drain_timeout_s: float = 120.0
    # Per-replica server template (batch window, span granule, SMT pool,
    # overload knobs).  ``spool``/``requests_dir``/``lease_path``/
    # ``exec_cache``/``replica_id`` are owned by the fleet and stamped per
    # replica; whatever they say here is ignored.
    replica: ServeConfig = field(default_factory=ServeConfig)
    seed: int = 0


class _ReplicaProc:
    """One live replica subprocess: pipes, lease path, reader thread.

    NOT thread-safe by itself — ownership of mutation is the router's;
    the reader thread only flips ``hello``/``pid`` (monotonic, write-once)
    and feeds the fleet's status table through a locked callback.
    """

    def __init__(self, idx: int, proc: subprocess.Popen, inbox: str,
                 lease_path: str):
        self.idx = idx
        self.proc = proc
        self.inbox = inbox
        self.lease_path = lease_path
        self.spawned_at = time.monotonic()
        self.hello = threading.Event()
        self.pid: Optional[int] = None

    def alive(self) -> bool:
        return self.proc.poll() is None

    def send(self, obj: dict) -> bool:
        try:
            self.proc.stdin.write(protocol.dump_msg(obj))
            self.proc.stdin.flush()
            return True
        except (OSError, ValueError):
            return False  # a dead pipe IS a death; waitpid classifies it

    def kill(self) -> None:
        if self.proc.poll() is None:
            try:
                self.proc.kill()
            except OSError:
                pass
        try:
            self.proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            pass
        for fp in (self.proc.stdin, self.proc.stdout):
            try:
                if fp is not None:
                    fp.close()
            except OSError:
                pass


class ProcessFleet:
    """N OS-process replicas behind one spool router (module docstring).

    API mirrors the operations a spool client or bench needs:
    ``start`` / ``drain`` / ``alive`` / ``replicas_alive`` / ``wait`` /
    ``pids``; submission is the spool protocol
    (:func:`fairify_tpu.serve.client.submit`) — there is no in-process
    submit across a process boundary.
    """

    def __init__(self, cfg: ProcFleetConfig):
        if not cfg.spool:
            raise ValueError("ProcessFleet requires a spool directory")
        if cfg.n_replicas < 1:
            raise ValueError("fleet needs n_replicas >= 1")
        import numpy as np

        self.cfg = cfg
        self._cv = threading.Condition(threading.Lock())
        self._slots: List[Optional[_ReplicaProc]] = [None] * cfg.n_replicas
        self._restarts: List[int] = [0] * cfg.n_replicas
        self._respawn_at: Dict[int, float] = {}
        self._owner: Dict[str, int] = {}      # request id -> replica slot
        self._payloads: Dict[str, dict] = {}  # request id -> spool payload
        self._status: Dict[str, str] = {}     # request id -> last status
        self._drain_stats: Dict[int, dict] = {}  # slot -> last drained msg
        self._replica_metrics: Dict[int, dict] = {}  # slot -> last beat
        self._suspect_slots: set = set()  # integrity violations seen (§21)
        self._fleet_metrics_at = 0.0          # last fleet_metrics.json dump
        self._rehomed_total = 0
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self._rng = np.random.default_rng(cfg.seed)
        self._pulse = FleetPulse(interval_s=cfg.pulse_s)
        os.makedirs(os.path.join(cfg.spool, "inbox"), exist_ok=True)
        os.makedirs(os.path.join(cfg.spool, "requests"), exist_ok=True)
        from fairify_tpu.resilience.journal import JournalWriter
        from fairify_tpu.resilience.supervisor import Supervisor

        self._journal_writer = JournalWriter(
            os.path.join(cfg.spool, "procfleet.journal.jsonl"),
            supervisor=Supervisor(max_retries=2, backoff_s=0.05))

    # --- plumbing ---------------------------------------------------------

    def _exec_cache_dir(self) -> Optional[str]:
        if self.cfg.exec_cache == "auto":
            return os.path.join(self.cfg.spool, "exec-cache")
        return self.cfg.exec_cache or None

    def _journal(self, rec: dict) -> None:
        self._journal_writer.append({"ts": round(time.time(), 3), **rec})

    def _lease_age(self, rp: _ReplicaProc) -> float:
        """Seconds since the replica's worker last beat its file lease
        (epoch mtime vs epoch now — same host, same clock)."""
        try:
            return max(time.time() - os.stat(rp.lease_path).st_mtime, 0.0)
        except OSError:
            # Lease not born yet: measure from spawn so a replica wedged
            # before its first beat still expires.
            return time.monotonic() - rp.spawned_at

    # --- spawn / restart --------------------------------------------------

    def _replica_cmd(self, idx: int) -> List[str]:
        r = self.cfg.replica
        cmd = [sys.executable, "-m", "fairify_tpu.serve.replica",
               "--spool", self.cfg.spool, "--replica", str(idx),
               "--batch-window", str(r.batch_window_s),
               "--max-batch", str(r.max_batch),
               "--span-chunks", str(r.span_chunks),
               "--poll-interval", str(r.poll_s),
               "--smt-workers", str(r.smt_workers),
               "--smt-memory-cap", str(r.smt_memory_cap_mb),
               "--smt-portfolio", str(r.smt_portfolio),
               "--max-queue", str(r.max_queue),
               "--preempt-factor", str(r.preempt_factor),
               "--max-preemptions", str(r.max_preemptions),
               "--fair-share", str(r.fair_share_factor),
               "--fair-share-min", str(r.fair_share_min_s)]
        if not r.fair_share_idle_exempt:
            cmd.append("--fair-share-strict")
        if r.default_deadline_s is not None:
            cmd += ["--default-deadline", str(r.default_deadline_s)]
        cache = self._exec_cache_dir()
        if cache:
            cmd += ["--exec-cache", cache]
        if self.cfg.memory_cap_mb > 0:
            cmd += ["--memory-cap-mb", str(self.cfg.memory_cap_mb)]
        if self.cfg.trace_dir:
            cmd += ["--trace-dir", self.cfg.trace_dir]
        return cmd

    def _spawn(self, idx: int) -> Optional[_ReplicaProc]:
        """Fork one replica (the ``replica.spawn`` chaos site).  Returns
        None on a spawn failure — already recorded and rescheduled."""
        try:
            faults_mod.check("replica.spawn")
            rdir = os.path.join(self.cfg.spool, "replicas", str(idx))
            os.makedirs(os.path.join(rdir, "inbox"), exist_ok=True)
            proc = subprocess.Popen(
                self._replica_cmd(idx), stdin=subprocess.PIPE,
                stdout=subprocess.PIPE, text=True, bufsize=1, cwd=_ROOT)
        except BaseException as exc:
            if classify(exc) == "propagate":
                raise
            self._on_spawn_fail(idx, exc)
            return None
        rp = _ReplicaProc(idx, proc,
                          inbox=os.path.join(self.cfg.spool, "replicas",
                                             str(idx), "inbox"),
                          lease_path=os.path.join(self.cfg.spool, "replicas",
                                                  str(idx), "lease"))
        threading.Thread(target=self._reader, args=(rp,),
                         name=f"procfleet-r{idx}", daemon=True).start()
        obs.event("replica", replica=idx, event="spawn", pid=proc.pid)
        self._journal({"event": "spawn", "replica": idx, "pid": proc.pid})
        return rp

    def _on_spawn_fail(self, idx: int, exc: BaseException) -> None:
        obs.registry().counter("replica_deaths").inc(kind="spawn")
        obs.event("replica", replica=idx, event="death", kind="spawn",
                  error=type(exc).__name__, detail=str(exc)[:200])
        self._journal({"event": "death", "replica": idx, "kind": "spawn",
                       "error": type(exc).__name__})
        self._schedule_restart(idx)

    def _schedule_restart(self, idx: int) -> None:
        """Bounded jittered-backoff respawn; exhaustion abandons the slot."""
        with self._cv:
            if self._draining:
                return
            n = self._restarts[idx]
            if n >= self.cfg.max_restarts:
                abandoned = True
            else:
                abandoned = False
                self._restarts[idx] = n + 1
                delay = self.cfg.backoff_s * (2.0 ** n) \
                    * (1.0 + float(self._rng.random()))
                self._respawn_at[idx] = time.monotonic() + delay
        if abandoned:
            obs.event("replica", replica=idx, event="abandoned",
                      restarts=n)
            self._journal({"event": "abandoned", "replica": idx,
                           "restarts": n})

    def _respawn_due(self) -> None:
        due: List[int] = []
        with self._cv:
            now = time.monotonic()
            for idx, at in list(self._respawn_at.items()):
                if at <= now and self._slots[idx] is None \
                        and not self._draining:
                    del self._respawn_at[idx]
                    due.append(idx)
        for idx in due:
            rp = self._spawn(idx)
            if rp is None:
                continue
            with self._cv:
                self._slots[idx] = rp
                n = self._restarts[idx]
            obs.registry().counter("replica_restarts").inc(replica=idx)
            obs.event("replica", replica=idx, event="restart", pid=rp.proc.pid,
                      restarts=n)
            self._journal({"event": "restart", "replica": idx,
                           "pid": rp.proc.pid, "restarts": n})

    # --- control-pipe reader ----------------------------------------------

    def _reader(self, rp: _ReplicaProc) -> None:
        """Drain one replica's stdout: hello + lifecycle status stream.

        Exits on EOF (the replica died; waitpid classifies it).  Torn or
        garbage lines are ignored — a SIGKILL tears at most one."""
        for line in rp.proc.stdout:
            msg = protocol.parse_msg(line)
            if msg is None:
                continue
            if msg.get("hello"):
                rp.pid = int(msg.get("pid") or rp.proc.pid)
                rp.hello.set()
                obs.event("replica", replica=rp.idx, event="hello",
                          pid=rp.pid)
                continue
            if msg.get("op") == "status":
                rid = msg.get("request")
                status = msg.get("status")
                if rid is None or status is None:
                    continue
                with self._cv:
                    if status in _TERMINAL:
                        # Terminal: evict the whole tracking entry, not
                        # just the payload — _owner/_status otherwise
                        # grow one record per request ever served, and
                        # _route_target scans _owner per routed payload.
                        # status.json on disk stays the durable answer.
                        self._payloads.pop(str(rid), None)
                        self._owner.pop(str(rid), None)
                        self._status.pop(str(rid), None)
                    else:
                        self._status[str(rid)] = str(status)
                attrs = {k: v for k, v in msg.items() if k != "op"}
                obs.event("request", **attrs)
                continue
            if msg.get("op") == "metrics":
                self._on_metrics(rp.idx, msg)
                continue
            if msg.get("op") == "drained":
                # Process-lifetime compile accounting (exec-cache health):
                # kept per slot so tests and the report can assert that a
                # restarted replica warmed from disk compiled nothing.
                # The drained frame carries the same registry snapshot as
                # a metrics beat, so it also finalizes that slot's entry
                # in fleet_metrics.json.
                with self._cv:
                    self._drain_stats[rp.idx] = dict(msg)
                self._on_metrics(rp.idx, msg, beat=False)

    def _on_metrics(self, idx: int, msg: dict, beat: bool = True) -> None:
        """Fold one replica's labelled registry snapshot into the fleet
        view: per-replica derived gauges (satellite of DESIGN.md §19) and
        the merged ``fleet_metrics.json`` written by the router loop.

        Derived here, not replica-side: the frames ship raw lifetime
        totals, so a restarted replica's counters visibly reset instead
        of corrupting an average.  ``launches_per_model`` mirrors the
        per-run ThroughputCounter field — in serving, one request is one
        model, so launches per DONE request is the live analog.
        """
        snap = {k: v for k, v in msg.items()
                if k not in ("op", "replica", "requeued")}
        hits = int(msg.get("exec_cache_hits") or 0)
        compiles = int(msg.get("n_compiles") or 0)
        done = int(msg.get("serve_requests_done") or 0)
        launches = int(msg.get("device_launches") or 0)
        reg = obs.registry()
        if hits + compiles > 0:
            snap["exec_cache_hit_rate"] = round(hits / (hits + compiles), 4)
            reg.gauge("replica_exec_cache_hit_rate").set(
                snap["exec_cache_hit_rate"], replica=idx)
        if done > 0:
            snap["launches_per_model"] = round(launches / done, 2)
            reg.gauge("replica_launches_per_model").set(
                snap["launches_per_model"], replica=idx)
        with self._cv:
            self._replica_metrics[idx] = snap
            if int(msg.get("integrity_violations") or 0) > 0:
                # The replica detected SDC in its own data path (the
                # affected request already degraded in-replica, so no
                # wrong verdict shipped) — mark the slot suspect; the
                # next health sweep quarantines it like a death.
                self._suspect_slots.add(idx)
        if beat:
            obs.event("replica", replica=idx, event="metrics", **snap)

    def fleet_metrics(self) -> dict:
        """Merged fleet-wide metrics document (what ``fleet_metrics.json``
        holds): per-replica labelled snapshots from the latest beats,
        final drain summaries, and fleet-level recovery counters."""
        reg = obs.registry()
        with self._cv:
            per_replica = {str(i): dict(v)
                           for i, v in sorted(self._replica_metrics.items())}
            drained = {str(i): {k: v for k, v in rec.items() if k != "op"}
                       for i, rec in sorted(self._drain_stats.items())}
            alive = sum(1 for s in self._slots
                        if s is not None and s.alive())
            restarts = list(self._restarts)
            rehomed = self._rehomed_total
        return {"replicas": per_replica, "drained": drained,
                "fleet": {"n_replicas": self.cfg.n_replicas,
                          "alive": alive, "restarts": restarts,
                          "rehomed": rehomed,
                          "deaths": int(reg.counter(
                              "replica_deaths").total())}}

    def _dump_fleet_metrics(self) -> None:
        try:
            write_atomic_json(
                os.path.join(self.cfg.spool, "fleet_metrics.json"),
                self.fleet_metrics())
        except OSError:
            pass  # telemetry must never take the router down

    # --- lifecycle --------------------------------------------------------

    def start(self) -> "ProcessFleet":
        # Recover a previous fleet's orphans: payloads parked in replica
        # sub-inboxes (an orphaned replica's EOF drain, or a crash between
        # routing and pickup) rejoin the fleet inbox before anyone routes.
        self._collect_sub_inboxes()
        for idx in range(self.cfg.n_replicas):
            rp = self._spawn(idx)
            if rp is None:
                continue
            with self._cv:
                self._slots[idx] = rp
        if self._thread is None:
            self._thread = threading.Thread(target=self._router,
                                            name="fairify-procfleet",
                                            daemon=True)
            self._thread.start()
        return self

    def __enter__(self) -> "ProcessFleet":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.drain()
        return False

    def wait_ready(self, timeout: float = 180.0) -> int:
        """Block until every CURRENT replica said hello (or the deadline);
        returns how many are ready.  Spawning includes a jax import, so
        benches/tests should wait before measuring."""
        deadline = time.monotonic() + timeout
        while True:
            with self._cv:
                slots = [s for s in self._slots if s is not None]
            ready = sum(1 for s in slots if s.hello.is_set())
            if ready == len(slots) and slots:
                return ready
            if time.monotonic() >= deadline:
                return ready
            time.sleep(0.05)

    def alive(self) -> bool:
        """True while the router runs and the fleet can still take work.

        A slot is *viable* when it is occupied (live, or dead but not yet
        swept — the sweep turns it into a restart or an abandonment) or
        when its respawn is pending in the backoff window.  An operator
        loop that drained on ``not alive()`` during either window would
        turn every recoverable crash into a fleet shutdown, defeating the
        bounded-restart policy; only a fleet whose every slot is
        abandoned (or drained) reads dead."""
        with self._cv:
            router = self._thread is not None and self._thread.is_alive()
            viable = any(s is not None for s in self._slots) \
                or bool(self._respawn_at)
        return router and viable

    def replicas_alive(self) -> int:
        with self._cv:
            slots = [s for s in self._slots if s is not None]
        return sum(1 for s in slots if s.alive())

    def pids(self) -> Dict[int, int]:
        """Live replica pids by slot (chaos harnesses SIGKILL/SIGSTOP
        these — the whole point of process replicas)."""
        with self._cv:
            slots = list(self._slots)
        return {i: s.proc.pid for i, s in enumerate(slots)
                if s is not None and s.alive()}

    def restarts(self) -> List[int]:
        with self._cv:
            return list(self._restarts)

    def drain_stats(self) -> Dict[int, dict]:
        """Per-slot ``drained`` control messages (compile accounting of
        the replica's whole process lifetime) — populated by drain()."""
        with self._cv:
            return {i: dict(v) for i, v in self._drain_stats.items()}

    def status_of(self, request_id: str) -> Optional[str]:
        with self._cv:
            return self._status.get(request_id)

    def owner_of(self, request_id: str) -> Optional[int]:
        """Replica slot currently routed this request (None after a
        re-home put it back in the fleet inbox)."""
        with self._cv:
            return self._owner.get(request_id)

    def inject_memout(self, idx: int) -> bool:
        """Chaos: tell replica ``idx`` to allocate past its RSS cap (the
        process-level analog of the SMT worker's memout directive)."""
        with self._cv:
            rp = self._slots[idx]
        return rp is not None and rp.send({"op": "memout"})

    def wait(self, request_id: str, timeout: Optional[float] = None
             ) -> Optional[dict]:
        """Terminal status record via the shared spool (status.json is the
        cross-process source of truth), or None on timeout."""
        from fairify_tpu.serve import client

        return client.wait(self.cfg.spool, request_id, timeout=timeout,
                           poll_s=0.05)

    def drain(self) -> List[str]:
        """Graceful shutdown: drain every replica, collect requeues back
        into the fleet inbox; returns the requeued request ids."""
        with self._cv:
            if self._draining:
                return []  # idempotent: a second drain is a no-op
            self._draining = True
            self._respawn_at.clear()
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        with self._cv:
            slots = [(i, s) for i, s in enumerate(self._slots)
                     if s is not None]
            self._slots = [None] * self.cfg.n_replicas
        for _idx, rp in slots:
            rp.send({"op": "drain"})
        for idx, rp in slots:
            try:
                rp.proc.wait(timeout=self.cfg.drain_timeout_s)
            except subprocess.TimeoutExpired:
                rp.kill()
            self._journal({"event": "drained", "replica": idx,
                           "rc": rp.proc.poll()})
        # Give the reader threads a beat to deliver the final ``drained``
        # control messages (compile accounting) of cleanly-exited replicas.
        want = {idx for idx, rp in slots if rp.proc.poll() == 0}
        deadline = time.monotonic() + 2.0
        while want and time.monotonic() < deadline:
            with self._cv:
                if want <= set(self._drain_stats):
                    break
            time.sleep(0.02)
        requeued = self._collect_sub_inboxes()
        # Final authoritative dump: the drained frames just folded in, so
        # this is the complete fleet lifetime (beats + drain summaries).
        self._dump_fleet_metrics()
        self._journal({"event": "fleet_drained", "requeued": requeued})
        self._journal_writer.close()
        return requeued

    def _collect_sub_inboxes(self) -> List[str]:
        """Move every payload parked in a replica sub-inbox back to the
        fleet inbox (rename-atomic); returns the request ids moved."""
        root = os.path.join(self.cfg.spool, "replicas")
        inbox = os.path.join(self.cfg.spool, "inbox")
        moved: List[str] = []
        try:
            replicas = sorted(os.listdir(root))
        except OSError:
            return moved
        for sub in replicas:
            sub_inbox = os.path.join(root, sub, "inbox")
            try:
                names = sorted(os.listdir(sub_inbox))
            except OSError:
                continue
            for name in names:
                if not name.endswith(".json"):
                    continue
                try:
                    os.replace(os.path.join(sub_inbox, name),
                               os.path.join(inbox, name))
                except OSError:
                    continue  # racing consumer; the payload still exists
                moved.append(name[:-len(".json")])
        return moved

    # --- router loop ------------------------------------------------------

    def _router(self) -> None:
        while True:
            with self._cv:
                if self._draining:
                    return
            try:
                self._scan_inbox()
                self._health_sweep()
                self._respawn_due()
            except BaseException as exc:
                # Propagate-class (interrupt/crash faults) must kill the
                # router — a zombie fleet scanning nothing is worse than a
                # dead one; anything else degrades with a recorded reason.
                if classify(exc) == "propagate":
                    raise
                obs.event("degraded", site="procfleet.router",
                          error=type(exc).__name__, detail=str(exc)[:200])
            with self._cv:
                alive = sum(1 for s in self._slots
                            if s is not None and s.alive())
                restarting = len(self._respawn_at)
                rehomed = self._rehomed_total
                if self._draining:
                    return
            self._pulse.pulse(alive, self.cfg.n_replicas,
                              restarting=restarting, rehomed=rehomed)
            obs.registry().gauge("procfleet_replicas_alive").set(alive)
            # Fleet-wide metrics dump rides the router tick, throttled to
            # ~1 Hz: replicas beat at that cadence, so dumping faster only
            # rewrites identical bytes.
            now = time.monotonic()
            if now - self._fleet_metrics_at >= 1.0:
                self._fleet_metrics_at = now
                self._dump_fleet_metrics()
            with self._cv:
                if self._draining:
                    return
                self._cv.wait(timeout=self.cfg.poll_s)

    # --- routing ----------------------------------------------------------

    def _route_target(self) -> Optional[_ReplicaProc]:
        """Least-loaded live replica (fewest owned non-terminal requests,
        hello'd replicas preferred), or None — in which case payloads WAIT
        in the fleet inbox (loss-free when the whole fleet is down)."""
        with self._cv:
            live = [(i, s) for i, s in enumerate(self._slots)
                    if s is not None and s.alive()]
            if not live:
                return None
            owned = {i: 0 for i, _s in live}
            for rid, idx in self._owner.items():
                if idx in owned \
                        and self._status.get(rid) not in _TERMINAL:
                    owned[idx] += 1
            return min(live, key=lambda kv: (not kv[1].hello.is_set(),
                                             owned[kv[0]], kv[0]))[1]

    def _scan_inbox(self) -> None:
        """Route fleet-inbox payloads into replica sub-inboxes.

        Mirrors the thread fleet's scan where it matters: corruption is
        quarantined with a terminal, client-visible rejection; routing is
        write-then-remove of JSON (both halves atomic), so a crash between
        the two at worst duplicates a payload — which ``resume=True``
        replay makes idempotent."""
        from fairify_tpu.serve.request import new_request_id

        inbox = os.path.join(self.cfg.spool, "inbox")
        try:
            names = sorted(os.listdir(inbox))
        except OSError:
            return
        for name in names:
            with self._cv:
                if self._draining:
                    return
            if not name.endswith(".json"):
                continue
            path = os.path.join(inbox, name)
            try:
                with open(path) as fp:
                    payload = json.load(fp)
            except OSError:
                continue  # consumed by a racing router, or an fs flake
            except json.JSONDecodeError as exc:
                self._quarantine(path, name, exc)
                continue
            target = self._route_target()
            if target is None:
                return  # no live replicas: payloads wait, loss-free
            req_id = str(payload.get("id") or new_request_id())
            payload = dict(payload, id=req_id)
            try:
                write_atomic_json(
                    os.path.join(target.inbox, f"{req_id}.json"), payload)
                os.remove(path)
            except OSError:
                continue
            with self._cv:
                self._owner[req_id] = target.idx
                self._payloads[req_id] = payload
                self._status[req_id] = "routed"
            self._journal({"event": "route", "request": req_id,
                           "replica": target.idx,
                           "model": payload.get("model",
                                                payload.get("init", "?"))})

    def _quarantine(self, path: str, name: str, exc: Exception) -> None:
        try:
            os.replace(path, f"{path}.corrupt")
        except OSError:
            return
        rid = name[:-len(".json")]
        rec = {"request": rid, "status": REJECTED, "model": "?",
               "preset": "?",
               "reason": f"corrupt payload (quarantined to {name}.corrupt): "
                         f"{str(exc)[:200]}"}
        obs.registry().counter("serve_requests").inc(status=REJECTED)
        obs.event("request", **rec)
        self._journal(rec)
        rdir = os.path.join(self.cfg.spool, "requests", rid)
        os.makedirs(rdir, exist_ok=True)
        write_atomic_json(os.path.join(rdir, "status.json"), rec)

    # --- health + failover ------------------------------------------------

    def _health_sweep(self) -> None:
        """One pass: waitpid + spawn deadline + file-lease check per
        replica, each death classified and failed over."""
        # Imported lazily: a module-scope import would pre-load serve.replica
        # in every `python -m fairify_tpu.serve.replica` subprocess (runpy's
        # found-in-sys.modules double-execution warning).
        from fairify_tpu.serve.replica import EXIT_MEMOUT

        with self._cv:
            slots = [(i, s) for i, s in enumerate(self._slots)
                     if s is not None]
        for idx, rp in slots:
            rc = rp.proc.poll()
            if rc is not None:
                kind = "memout" if rc == EXIT_MEMOUT else "crash"
                self._fail_over(idx, rp, kind, rc=rc)
                continue
            with self._cv:
                suspect = idx in self._suspect_slots
                self._suspect_slots.discard(idx)
            if suspect:
                # Integrity quarantine (DESIGN.md §21): a replica whose
                # metrics beat reported integrity_violations > 0 cannot
                # be trusted with further requests.  Kill + fail over —
                # re-homing resumes its work on a clean process, and the
                # bounded-backoff restart gives the slot a fresh replica
                # whose counters start at zero.
                rp.kill()
                self._fail_over(idx, rp, "integrity", rc=rp.proc.poll())
                continue
            if not rp.hello.is_set():
                if time.monotonic() - rp.spawned_at \
                        > self.cfg.spawn_timeout_s:
                    rp.kill()
                    self._fail_over(idx, rp, "spawn")
                continue
            if self.cfg.lease_s <= 0:
                continue
            forced = False
            try:
                faults_mod.check("replica.lease")
            except BaseException as exc:
                cls = classify(exc)
                if cls == "propagate":
                    raise
                if cls == "transient":
                    # A stat blip: skip this tick's lease verdict; the
                    # next sweep re-reads the real mtime.
                    obs.event("degraded", site="replica.lease", replica=idx,
                              error=type(exc).__name__)
                    continue
                # fatal: force the lease expired so the REAL escalating
                # hang-containment path runs against the live process.
                forced = True
            age = self._lease_age(rp)
            obs.registry().gauge("replica_lease_age_s").set(age, replica=idx)
            if forced or age > self.cfg.lease_s:
                self._contain_hang(idx, rp, age)

    def _contain_hang(self, idx: int, rp: _ReplicaProc, age: float) -> None:
        """Escalating SIGTERM → SIGKILL for a lease-expired replica.

        SIGTERM first (a merely-slow replica may still die cleanly and
        flush its pipes); a process that ignores it — SIGSTOPped, wedged
        in native code — takes the SIGKILL after ``term_grace_s``.  Only
        then does failover run: the kill precedes re-homing, so two
        processes never write one request's ledger concurrently."""
        obs.event("replica", replica=idx, event="lease_expired",
                  lease_age=round(age, 3), pid=rp.proc.pid)
        try:
            rp.proc.terminate()
        except OSError:
            pass
        try:
            rp.proc.wait(timeout=self.cfg.term_grace_s)
        except subprocess.TimeoutExpired:
            rp.kill()
        self._fail_over(idx, rp, "hang", rc=rp.proc.poll())

    def _fail_over(self, idx: int, rp: _ReplicaProc, kind: str,
                   rc: Optional[int] = None) -> None:
        """Quarantine a dead replica's slot, re-home its work, schedule
        the bounded-backoff restart."""
        with self._cv:
            if self._slots[idx] is not rp:
                return  # already failed over
            self._slots[idx] = None
        rp.kill()  # reap + close pipes (no-op on an already-dead proc)
        obs.registry().counter("replica_deaths").inc(kind=kind)
        obs.event("replica", replica=idx, event="death", kind=kind,
                  pid=rp.proc.pid, rc=rc)
        self._journal({"event": "death", "replica": idx, "kind": kind,
                       "pid": rp.proc.pid, "rc": rc})
        rehomed = self._rehome(idx, rp)
        if rehomed:
            obs.registry().counter("replica_rehomed").inc(rehomed)
            obs.event("replica", replica=idx, event="rehome",
                      requests=rehomed)
        self._schedule_restart(idx)

    def _rehome(self, idx: int, rp: _ReplicaProc) -> int:
        """Every non-terminal request the dead replica owned goes back to
        the fleet inbox: unpicked sub-inbox payloads by rename, picked
        ones re-written from the router's payload table (cross-checked
        against the on-disk terminal status.json — the control-pipe
        stream may be missing its torn last line).  The next scan routes
        them to survivors; ``submitted_ts`` in the payload keeps the SLA
        clock, the stable id keeps the result_dir, and ``resume=True``
        replays the decided rows."""
        from fairify_tpu.serve import client

        inbox = os.path.join(self.cfg.spool, "inbox")
        moved: set = set()
        try:
            names = sorted(os.listdir(rp.inbox))
        except OSError:
            names = []
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                os.replace(os.path.join(rp.inbox, name),
                           os.path.join(inbox, name))
            except OSError:
                continue
            moved.add(name[:-len(".json")])
        with self._cv:
            owned = [(rid, dict(self._payloads[rid]))
                     for rid, o in self._owner.items()
                     if o == idx and rid not in moved
                     and self._status.get(rid) not in _TERMINAL
                     and rid in self._payloads]
        for rid, payload in owned:
            rec = client.status(self.cfg.spool, rid)
            if rec is not None and rec.get("status") in _TERMINAL:
                with self._cv:  # pipe stream missed the terminal: catch up
                    self._payloads.pop(rid, None)
                    self._owner.pop(rid, None)
                    self._status.pop(rid, None)
                continue
            try:
                write_atomic_json(os.path.join(inbox, f"{rid}.json"), payload)
            except OSError:
                continue
            moved.add(rid)
        with self._cv:
            for rid in moved:
                if self._owner.get(rid) == idx:
                    del self._owner[rid]
                self._status[rid] = "rehomed"
            self._rehomed_total += len(moved)
        for rid in sorted(moved):
            self._journal({"event": "rehome", "request": rid,
                           "replica": idx})
        return len(moved)

"""Replica worker process: ``python -m fairify_tpu.serve.replica``.

One OS process owning one full :class:`~fairify_tpu.serve.server.
VerificationServer` — its own device client, worker loop, SMT pool and
launch pipeline — managed by :class:`~fairify_tpu.serve.procfleet.
ProcessFleet`.  Unlike the thread replicas of ``serve/fleet.py``, this
process is a real containment domain: a wedged XLA launch, a native
crash, or a memory blowup dies HERE, and the router's recovery runs
against a true corpse (``kill -9`` works), not a cooperative simulation.

Contract with the router (DESIGN.md §18):

* **control plane** — newline-framed JSON on stdin/stdout (the
  :mod:`fairify_tpu.smt.protocol` framing: a SIGKILL tears at most one
  line, and any undecodable read is treated as a death, not a protocol
  error).  The replica sends ``{"hello": true, pid, replica}`` once its
  server is live (jax import + device init happen before this, so the
  router's spawn deadline covers them), forwards every request lifecycle
  transition as ``{"op": "status", ...}``, and answers ``ping`` with
  ``pong``.  The router sends ``{"op": "drain"}`` for graceful shutdown;
  EOF on stdin (the router died) also drains — an orphan must park its
  queued payloads back in its sub-inbox, never strand them.
* **file lease** — the server touches ``<spool>/replicas/<i>/lease`` at
  every worker yield point (batch-loop iterations and span granules, via
  ``ServeConfig.lease_path``); the router reads its mtime.  A wedged
  worker — SIGSTOP, a hung launch — stops beating while the process
  stays alive, which is exactly the failure ``waitpid`` cannot see.
* **spool layout** — the replica scans its OWN sub-inbox
  (``<spool>/replicas/<i>/inbox``) but writes request sinks into the
  fleet's shared ``<spool>/requests`` (``ServeConfig.requests_dir``):
  stable result_dirs are what make a cross-process failover's
  ``resume=True`` ledger replay loss-free.
* **death taxonomy** — exit 0 only after a completed drain; a worker
  thread killed by a propagate-class error exits ``EXIT_CRASH``; a
  ``MemoryError`` anywhere (the ``RLIMIT_AS`` cap landing) exits
  ``EXIT_MEMOUT`` via ``os._exit`` — a heap that just failed allocation
  is not trustworthy for cleanup, and the distinct code lets the router
  classify the death without a word from the corpse.

The module imports only stdlib + :mod:`fairify_tpu.smt.protocol` at the
top so ``--memory-cap-mb`` (``RLIMIT_AS``) is applied BEFORE the jax
stack allocates its arenas.
"""
from __future__ import annotations

import argparse
import os
import sys
import threading
import time

from fairify_tpu.smt import protocol

#: Replica exit codes (the router's waitpid-side death taxonomy).
EXIT_DRAINED = 0
EXIT_CRASH = 3
EXIT_MEMOUT = 86


def _apply_memory_cap(cap_mb: int) -> None:
    if cap_mb <= 0:
        return
    import resource

    cap = int(cap_mb) * 1024 * 1024
    resource.setrlimit(resource.RLIMIT_AS, (cap, cap))


def _hijack_stdout():
    """Reserve fd 1 for the control channel.

    The verify stack legitimately writes progress to stderr, but any
    stray stdout write (a library banner, a debug print) would corrupt
    the framed control stream — so the ORIGINAL fd 1 is dup'd for the
    channel and fd 1 itself is pointed at stderr.  ``parse_msg`` on the
    router side ignores garbage lines anyway; this makes them not happen.
    """
    chan = os.fdopen(os.dup(1), "w", buffering=1)
    os.dup2(2, 1)
    sys.stdout = sys.stderr
    return chan


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--spool", required=True,
                    help="the FLEET spool root (this replica uses "
                         "replicas/<i>/ under it)")
    ap.add_argument("--replica", type=int, required=True)
    ap.add_argument("--batch-window", type=float, default=0.05)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--span-chunks", type=int, default=0)
    ap.add_argument("--poll-interval", type=float, default=0.05)
    ap.add_argument("--default-deadline", type=float, default=None)
    ap.add_argument("--smt-workers", type=int, default=1)
    ap.add_argument("--smt-memory-cap", type=int, default=0)
    ap.add_argument("--smt-portfolio", type=int, default=0)
    ap.add_argument("--max-queue", type=int, default=0)
    ap.add_argument("--preempt-factor", type=float, default=0.0)
    ap.add_argument("--max-preemptions", type=int, default=2)
    ap.add_argument("--fair-share", type=float, default=0.0)
    ap.add_argument("--fair-share-min", type=float, default=2.0)
    ap.add_argument("--fair-share-strict", action="store_true")
    ap.add_argument("--exec-cache", default=None,
                    help="shared persistent executable cache directory "
                         "(a restarted replica warms from disk)")
    ap.add_argument("--memory-cap-mb", type=int, default=0,
                    help="RLIMIT_AS for THIS replica process (0 = off)")
    ap.add_argument("--trace-out", default=None,
                    help="optional obs event log for this replica")
    ap.add_argument("--trace-dir", default=None,
                    help="shared trace-shard directory (DESIGN.md §19): "
                         "this replica appends to trace.<pid>.jsonl there "
                         "and hands the directory to its SMT workers; "
                         "overrides --trace-out")
    args = ap.parse_args(argv)

    chan = _hijack_stdout()
    send_lock = threading.Lock()

    def send(obj: dict) -> None:
        try:
            with send_lock:
                chan.write(protocol.dump_msg(obj))
                chan.flush()
        except (OSError, ValueError):
            pass  # router gone mid-write: the reader's EOF drain handles it

    # A MemoryError ANYWHERE (the RLIMIT_AS cap landing in the worker, the
    # SMT drainer, a decode) means this heap is done: exit immediately with
    # the distinct memout code — cleanup on a failed heap is how a memout
    # becomes a hang.
    prev_hook = threading.excepthook

    def _thread_hook(hook_args):
        if issubclass(hook_args.exc_type, MemoryError):
            os._exit(EXIT_MEMOUT)
        prev_hook(hook_args)

    threading.excepthook = _thread_hook

    _apply_memory_cap(args.memory_cap_mb)

    rdir = os.path.join(args.spool, "replicas", str(args.replica))
    os.makedirs(os.path.join(rdir, "inbox"), exist_ok=True)

    try:
        from fairify_tpu import obs
        from fairify_tpu.serve.server import ServeConfig, VerificationServer

        scfg = ServeConfig(
            spool=rdir,
            requests_dir=os.path.join(args.spool, "requests"),
            lease_path=os.path.join(rdir, "lease"),
            batch_window_s=args.batch_window, max_batch=args.max_batch,
            span_chunks=args.span_chunks, poll_s=args.poll_interval,
            default_deadline_s=args.default_deadline,
            smt_workers=args.smt_workers,
            smt_memory_cap_mb=args.smt_memory_cap,
            smt_portfolio=args.smt_portfolio, max_queue=args.max_queue,
            preempt_factor=args.preempt_factor,
            max_preemptions=args.max_preemptions,
            fair_share_factor=args.fair_share,
            fair_share_min_s=args.fair_share_min,
            fair_share_idle_exempt=not args.fair_share_strict,
            exec_cache=args.exec_cache, replica_id=args.replica,
            trace_dir=args.trace_dir)

        def forward(rec: dict) -> None:
            send({"op": "status", "replica": args.replica, **rec})

        def metrics_snapshot() -> dict:
            """Labelled registry snapshot shipped on the control pipe.

            Raw lifetime totals, never rates: the router computes the
            derived gauges (exec-cache hit rate, launches per request) so
            a restarted replica's counters resetting to zero shows up as
            exactly that — a reset — instead of silently corrupting a
            replica-side running average.
            """
            reg = obs.registry()

            def _tot(name: str) -> int:
                try:
                    return int(reg.counter(name).total())
                except (KeyError, TypeError):
                    return 0

            try:
                done = int(reg.counter("serve_requests").value(status="done"))
            except (KeyError, TypeError):
                done = 0
            snap = {"exec_cache_hits": _tot("exec_cache_hits"),
                    "device_launches": _tot("device_launches"),
                    "serve_shed": _tot("serve_shed"),
                    "serve_preemptions": _tot("serve_preemptions"),
                    "integrity_violations": _tot("integrity_violations"),
                    "serve_requests_done": done}
            try:
                from fairify_tpu.obs import compile as compile_obs

                tot = compile_obs.snapshot_totals()
                snap["n_compiles"] = int(tot["n_compiles"])
                snap["compile_s"] = round(float(tot["compile_s"]), 3)
            except (ImportError, KeyError):
                pass
            return snap

        stop = threading.Event()

        def _chaos_memout() -> None:
            # Allocate past the RSS cap so the REAL containment path runs
            # (mirrors the SMT worker's memout directive).
            blocks = []
            try:
                while True:
                    blocks.append(bytearray(16 * 1024 * 1024))
            except MemoryError:
                del blocks
                os._exit(EXIT_MEMOUT)

        def _reader() -> None:
            for line in sys.stdin:
                msg = protocol.parse_msg(line)
                if msg is None:
                    continue
                op = msg.get("op")
                if op == "drain":
                    stop.set()
                    return
                if op == "ping":
                    send({"op": "pong", "replica": args.replica})
                elif op == "memout":
                    _chaos_memout()
            # EOF: the router died.  Drain so queued payloads park in the
            # sub-inbox for the next fleet instead of stranding here.
            stop.set()

        # --trace-dir wins over --trace-out: the shard name embeds this
        # process's pid, which is what lets the router's merged export
        # give every fleet process its own Perfetto track.
        trace_out = args.trace_out
        if args.trace_dir:
            trace_out = obs.shard_path(args.trace_dir)
        with obs.tracing(trace_out, run_id=f"replica-{args.replica}"):
            srv = VerificationServer(scfg, transition_fn=forward).start()
            threading.Thread(target=_reader, name="replica-ctl",
                             daemon=True).start()
            send({"hello": True, "replica": args.replica,
                  "pid": os.getpid(), "lease": scfg.lease_path})
            crashed = False
            last_beat = 0.0
            while not stop.is_set():
                if not srv.alive():
                    # A propagate-class error killed the worker thread
                    # (MemoryError already _exit'd via the hook): die
                    # loudly so waitpid classifies a crash and the router
                    # re-homes this replica's requests.
                    crashed = True
                    break
                # Metrics beat: a labelled registry snapshot rides the
                # control pipe about once a second, same framing as the
                # status stream.  The router folds these into its
                # fleet-wide gauges and fleet_metrics.json — a replica
                # that stops beating simply goes stale there, which the
                # lease sweep already covers.
                now = time.monotonic()
                if now - last_beat >= 1.0:
                    last_beat = now
                    send({"op": "metrics", "replica": args.replica,
                          **metrics_snapshot()})
                stop.wait(0.2)
            if crashed:
                send({"op": "dead", "replica": args.replica})
                return EXIT_CRASH
            requeued = srv.drain()
            # Process-lifetime accounting rides the drained message: it
            # is how the router (and the exec-cache tests) see that a
            # restarted replica warmed from disk compiled NOTHING —
            # per-request records only carry per-run deltas.  The drain
            # summary is the final, authoritative metrics snapshot; the
            # periodic beats above are the same fields, earlier.
            send({"op": "drained", "replica": args.replica,
                  "requeued": [r.id for r in requeued],
                  **metrics_snapshot()})
        return EXIT_DRAINED
    except MemoryError:
        os._exit(EXIT_MEMOUT)


if __name__ == "__main__":
    sys.exit(main())

"""Arch-bucketed cross-request batcher: many requests, few mega-launches.

α,β-CROWN's "rapid massively-parallel incomplete verifier" framing
(PAPERS.md: arxiv 2011.13824) coalesces many small verification problems
into few large device launches; the sweep already does that *within* one
run (family stacking, chunk bucketing, the async pipeline).  This module
does it *across concurrent service requests*:

* requests are bucketed by **stage-0 signature** (every config field that
  shapes the grid and the attack RNG streams — identical signature means
  identical ``(lo, hi)`` grid, identical per-chunk seeds) and then by
  **architecture** (``(in_dim,) + layer_sizes``, the family-stack key);
* every arch bucket with ≥2 members stacks its requests' nets into ONE
  vmapped family (:func:`parallel.mesh.stack_models`) and all buckets'
  (family, segment) blocks ride ONE shared :class:`LaunchPipeline` through
  :func:`verify.sweep.stage0_families` — under the device-resident
  mega-loop (DESIGN.md §17) that is one ``lax.scan`` launch per
  ``mega_chunks``-chunk segment per family, instead of one launch per
  chunk per *request*; the stage-0 signature deliberately excludes
  ``mega_chunks`` (it shapes launch structure, never results, so requests
  with different knob values still coalesce);
* the **model axis is a compiled-shape bucket** exactly like the chunk
  axis: ``pad_models`` (the server passes its ``max_batch``) pads every
  stack to one fixed width by repeating the last member, so a bucket of
  2 and a bucket of 7 hit the SAME family executable (pad-slot results
  are discarded).  Under-filled buckets waste vmapped compute, but only
  at low concurrency — where the device is idle anyway — and in exchange
  a warm server owns exactly ONE family executable per architecture;
* the ragged-chunk padding inside ``_family_block_submit`` (PR 3) then
  means every coalesced block hits that same compiled executable — a warm
  server recompiles nothing, whatever mix of requests arrives.

Bit-equality contract: the family kernels are the solo kernels under
``vmap`` with the same globally-keyed RNG streams (``seed_offset`` pins
span-local slices to global chunk starts), so each request's stage-0
results — and therefore its verdict ledger — are bit-equal to the run it
would have done alone (pinned in ``tests/test_serve.py``).  Requests whose
signature or architecture matches nobody else's simply run the normal
single-model path; they still share the server's warm ``obs_jit`` cache.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from fairify_tpu import obs


def stage0_signature(cfg, partition_span) -> tuple:
    """Everything that must match for two requests' stage-0 streams to be
    interchangeable: the grid construction knobs, the seeds that key the
    attack RNG, and the chunking that buckets launches.  Budgets and
    result sinks deliberately excluded — they shape refinement, not the
    shared stage-0 launches."""
    eng = cfg.engine
    return (
        cfg.dataset, tuple(cfg.protected), tuple(cfg.relaxed), cfg.relax_eps,
        cfg.partition_threshold, cfg.capped_partitions, cfg.max_partitions,
        tuple(sorted(cfg.domain_overrides.items())), cfg.seed,
        cfg.grid_chunk, eng.seed, eng.attack_samples, eng.use_crown,
        tuple(partition_span) if partition_span is not None else None,
    )


def arch_key(net) -> tuple:
    return (net.in_dim,) + tuple(net.layer_sizes)


def plan_buckets(requests: Sequence) -> List[List]:
    """Group requests into coalescible buckets (≥2 requests each).

    Returns the list of buckets; requests not in any bucket run solo.
    Bucket membership is (stage-0 signature, architecture) equality —
    the two conditions under which one vmapped family launch can serve
    every member with its solo-run RNG streams.
    """
    groups: Dict[tuple, List] = {}
    for req in requests:
        key = (stage0_signature(req.cfg, req.partition_span),
               arch_key(req.net))
        groups.setdefault(key, []).append(req)
    return [reqs for reqs in groups.values() if len(reqs) >= 2]


def slice_stage0(stage0, s: int, e: int):
    """Span-local slice of a precomputed ``(unsat, sat, witnesses)`` triple
    (for span-granular refinement under a drainable server)."""
    unsat, sat, wits = stage0
    return (unsat[s:e], sat[s:e],
            {k - s: v for k, v in wits.items() if s <= k < e})


def batched_stage0(requests: Sequence, pipe=None,
                   pad_models: int = 0, grid_fn=None) -> Dict[str, tuple]:
    """Cross-request coalesced stage 0: request id → its stage-0 triple.

    Requests that coalesced get their certificates + attacks from shared
    family launches; ids absent from the returned map found no partner and
    should run the normal solo path.  All buckets share one launch
    pipeline, so bucket B's first chunk dispatches while bucket A's last
    chunks still decode host-side — the device queue never drains between
    buckets, same as the AC-suite family sweep.

    ``grid_fn(cfg) -> (lo, hi)`` supplies the full partition grid; the
    server passes its per-signature memo so a steady stream of coalesced
    batches doesn't rebuild a multi-second stress grid on the worker
    thread every batch window.
    """
    from fairify_tpu.parallel.mesh import stack_models
    from fairify_tpu.verify import sweep as sweep_mod
    from fairify_tpu.verify.property import encode

    buckets = plan_buckets(requests)
    out: Dict[str, tuple] = {}
    if not buckets:
        return out
    occupancy = sum(len(b) for b in buckets)
    # One coalesced launch serves many requests, so this span belongs to
    # several traces at once: it lists every member's trace id, and the
    # critical-path extractor charges its duration to each listed request
    # as the batch-coalesce stage.
    trace_ids = sorted({req.trace.trace_id for b in buckets for req in b
                        if getattr(req, "trace", None) is not None})
    with obs.span("serve.batch_stage0", buckets=len(buckets),
                  requests=occupancy, trace_ids=trace_ids):
        # Buckets may differ in signature (different grids), so each
        # signature group gets its own stage0_families call — but they all
        # submit into the SAME pipe, which is what keeps the device fed.
        by_sig: Dict[tuple, List[List]] = {}
        for bucket in buckets:
            sig = stage0_signature(bucket[0].cfg, bucket[0].partition_span)
            by_sig.setdefault(sig, []).append(bucket)
        for sig_buckets in by_sig.values():
            ref = sig_buckets[0][0]
            cfg = ref.cfg
            enc = encode(cfg.query())
            if grid_fn is not None:
                lo, hi = grid_fn(cfg)
            else:
                _, lo, hi = sweep_mod.build_partitions(cfg)
            span_start = 0
            if ref.partition_span is not None:
                span_start, span_stop = ref.partition_span
                lo, hi = lo[span_start:span_stop], hi[span_start:span_stop]
            stacks = []
            for bucket in sig_buckets:
                members = [req.net for req in bucket]
                if pad_models > len(members):
                    # Fixed model-axis width: pad slots recompute the last
                    # member and are sliced away below — shape stability
                    # (zero recompiles on a warm server) over idle FLOPs.
                    members += [members[-1]] * (pad_models - len(members))
                stacks.append(stack_models(members))
            fams = sweep_mod.stage0_families(
                stacks, enc, lo, hi, cfg, pipe=pipe, seed_offset=span_start)
            for bucket, fam in zip(sig_buckets, fams):
                for req, s0 in zip(bucket, fam):
                    out[req.id] = s0
    if out:
        obs.registry().histogram("serve_batch_occupancy").observe(occupancy)
    return out

"""Replicated serving: N server replicas, arch-bucket routing, failover.

One :class:`VerificationServer` melts when its single worker loop
saturates (SERVE_r01: 16 clients → p50 123 s).  The fleet runs N replicas
— each its own worker loop, launch pipeline, and
:class:`resilience.Supervisor` fault domain, mirroring the PR 7
shard-quarantine pattern at the *server* level — behind one router:

* **Routing is bucket-sticky with load spill-over.**  Requests are keyed
  by the batcher's coalescing bucket (stage-0 signature × architecture,
  :func:`serve.batcher.stage0_signature` / :func:`~serve.batcher.arch_key`)
  and a bucket is pinned to one replica (least-loaded at first sight).
  That keeps the batcher's same-executable trick intact per replica: every
  replica sees a closed set of architectures, so its warm executable cache
  is exactly the set it serves — requests of one bucket never smear
  compiles across the fleet.  Stickiness yields to overload: once the
  pinned replica's committed load passes ``spill_load``, new requests of
  the bucket spill to the least-loaded replica (the pin is unchanged) —
  the shared kernel registry in-process and the persistent executable
  cache across processes make the spill's compiles a non-event, while a
  hot bucket stops serializing behind one worker loop.
* **Death is detected, not assumed.**  The router health-checks every
  replica each tick: the worker thread gone (``server.alive()``) outside a
  drain, or a heartbeat lease expired (``lease_s``; 0 disables — a wedged
  worker is indistinguishable from a long granule without one).
  ``replica.lost`` is the chaos site for the check: an injected
  ``transient`` fault is a blip the router absorbs, ``fatal`` *kills the
  replica* (cooperative SIGKILL analog, :meth:`VerificationServer.kill`)
  so the real failover machinery runs, ``crash`` propagates.
* **Failover is loss-free.**  A dead replica performs no cleanup (that is
  the point); the router walks its request table and re-homes every
  non-terminal request — queued, running, or parked on the SMT drainer —
  to a survivor via ``submit(readmit=True)`` (admission accounts the
  backlog but must not shed an already-admitted request).  The request
  keeps its id, result_dir, and SLA clock; its partial verdict ledger
  replays ``resume=True`` on the survivor, so decided verdicts survive the
  handoff bit-for-bit and only undecided work is re-attempted.  With no
  survivors, spool-backed requests requeue to the inbox and in-process
  ones fail terminally with a machine-readable ``replica lost`` reason —
  never silently stranded.

A replica fleet shares the process-wide ``obs_jit`` kernel registry, so
in-process replicas share warm executables; across *processes* (one fleet
per host, restarted replicas) the persistent executable cache
(``ServeConfig.exec_cache`` → :func:`obs.compile.enable_exec_cache`) is
what makes a fresh replica warm from disk instead of recompiling.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from fairify_tpu import obs
from fairify_tpu.resilience import faults as faults_mod
from fairify_tpu.resilience.supervisor import classify
from fairify_tpu.serve import batcher
from fairify_tpu.serve.request import (
    DONE,
    FAILED,
    PRIORITY_NORMAL,
    REJECTED,
    REQUEUED,
    VerifyRequest,
)
from fairify_tpu.serve.server import ServeConfig, VerificationServer

_TERMINAL = (DONE, FAILED, REJECTED, REQUEUED)


@dataclass(frozen=True)
class FleetConfig:
    """Fleet knobs (``fairify_tpu serve --replicas N``)."""

    n_replicas: int = 2
    # Spool directory (the fleet scans the inbox and routes; replicas run
    # in-process submits only — one durable inbox, N workers).
    spool: Optional[str] = None
    # Router tick: inbox scan + health sweep interval.
    poll_s: float = 0.05
    # Heartbeat lease: a replica whose worker hasn't reached a yield point
    # in this long is declared lost even if the thread object is alive
    # (wedged).  0 disables — granule-less requests legitimately go dark
    # for their whole runtime.
    lease_s: float = 0.0
    # Bucket spill-over: stickiness is a preference, not a constraint.
    # When a bucket's pinned replica already holds this many committed
    # requests (queued + in-flight), the router places the NEW request on
    # the least-loaded live replica instead — the bucket pin is unchanged,
    # so locality returns as soon as the hot replica drains.  The
    # executable cache (in-process shared registry; on-disk across
    # processes) makes the spilled replica's compiles a non-event.  0
    # disables spill (strict stickiness).
    spill_load: int = 2
    # Per-replica server template; spool is forced None (the fleet owns
    # the spool) and replica_id is stamped per replica.
    replica: ServeConfig = field(default_factory=ServeConfig)


class ServerFleet:
    """N replicas behind one bucket-sticky router (see module docstring).

    API-compatible with :class:`VerificationServer` for the operations a
    client or bench needs: ``submit`` / ``get`` / ``wait`` / ``drain`` /
    ``alive``.
    """

    def __init__(self, cfg: FleetConfig = FleetConfig()):
        if cfg.n_replicas < 1:
            raise ValueError("fleet needs n_replicas >= 1")
        self.cfg = cfg
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # replica index -> server; None = quarantined (lost and failed
        # over; never reused — mirroring the shard-quarantine pattern).
        self._journal_writer = None
        if cfg.spool:
            import os

            from fairify_tpu.resilience.journal import JournalWriter
            from fairify_tpu.resilience.supervisor import Supervisor

            os.makedirs(os.path.join(cfg.spool, "inbox"), exist_ok=True)
            os.makedirs(os.path.join(cfg.spool, "requests"), exist_ok=True)
            # One fleet-wide lifecycle journal: replicas run spool-less,
            # but the operator contract (serve.journal.jsonl records every
            # transition) must hold for `--replicas N` exactly as for a
            # single server — the writer is thread-safe, so all replicas
            # share it.
            self._journal_writer = JournalWriter(
                os.path.join(cfg.spool, "serve.journal.jsonl"),
                supervisor=Supervisor(max_retries=2, backoff_s=0.05))
        self._replicas: List[Optional[VerificationServer]] = [
            VerificationServer(self._replica_cfg(i),
                               journal=self._journal_writer)
            for i in range(cfg.n_replicas)]
        # Quarantined replicas stay readable: a request that finished (or
        # was terminally failed) on a replica that later died must remain
        # visible through get()/wait() — "never silently stranded" covers
        # lookups too.
        self._dead: Dict[int, VerificationServer] = {}
        self._owner: Dict[str, int] = {}      # request id -> replica index
        self._assign: Dict[tuple, int] = {}   # coalescing bucket -> replica
        self._draining = False
        self._thread: Optional[threading.Thread] = None

    def _replica_cfg(self, idx: int) -> ServeConfig:
        from dataclasses import replace

        return replace(self.cfg.replica, spool=None, replica_id=idx)

    # --- lifecycle --------------------------------------------------------

    def start(self) -> "ServerFleet":
        with self._cv:
            replicas = list(self._replicas)
        for srv in replicas:
            if srv is not None:
                srv.start()
        if self._thread is None:
            self._thread = threading.Thread(target=self._router,
                                            name="fairify-fleet",
                                            daemon=True)
            self._thread.start()
        return self

    def __enter__(self) -> "ServerFleet":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.drain()
        return False

    def alive(self) -> bool:
        """True while the router runs and ≥1 replica can take work."""
        with self._cv:
            replicas = list(self._replicas)
            router = self._thread is not None and self._thread.is_alive()
        return router and any(s is not None and s.alive() for s in replicas)

    def replicas_alive(self) -> int:
        with self._cv:
            replicas = list(self._replicas)
        return sum(1 for s in replicas if s is not None and s.alive())

    def drain(self) -> List[VerifyRequest]:
        """Drain every live replica; returns all requeued requests."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()
            replicas = list(self._replicas)
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        requeued: List[VerifyRequest] = []
        for srv in replicas:
            if srv is None:
                continue
            for req in srv.drain():
                requeued.append(req)
                self._respool(req)
        if self._journal_writer is not None:
            self._journal_writer.close()
        return requeued

    def _journal_record(self, rec: dict) -> None:
        """Fleet-level lifecycle record: the shared serve.journal.jsonl
        (when spooled) plus the obs event stream, mirroring the server's
        ``_journal_record``."""
        if self._journal_writer is not None:
            self._journal_writer.append({"ts": round(time.time(), 3), **rec})
        obs.event("request", **rec)

    def _respool(self, req: VerifyRequest) -> None:
        """Write a requeued request's payload back to the FLEET inbox (the
        replicas have no spool of their own)."""
        if not self.cfg.spool or req.spool_payload is None:
            return
        import os

        from fairify_tpu.serve.client import write_atomic_json

        write_atomic_json(
            os.path.join(self.cfg.spool, "inbox", f"{req.id}.json"),
            req.spool_payload)

    # --- submission / lookup ----------------------------------------------

    def _route(self, cfg, net, partition_span) -> int:
        """Replica index for a request: sticky per coalescing bucket with
        load spill-over, least-loaded (fewest owned buckets, then fewest
        owned requests) on first sight.  Caller must NOT hold the lock."""
        key = (batcher.stage0_signature(cfg, partition_span),
               batcher.arch_key(net))
        with self._cv:
            live = [i for i, s in enumerate(self._replicas) if s is not None]
            if not live:
                raise RuntimeError("no live replicas")
            loads = {i: self._replicas[i].load() for i in live}
            idx = self._assign.get(key)
            if idx is not None and self._replicas[idx] is not None:
                if self.cfg.spill_load <= 0 \
                        or loads[idx] < self.cfg.spill_load \
                        or loads[idx] <= min(loads.values()):
                    return idx
                # Spill: the pinned replica is saturated; place THIS
                # request on the least-loaded replica (pin unchanged).
                spilled = min(live, key=lambda i: (loads[i], i))
                obs.registry().counter("fleet_spills").inc()
                return spilled
            buckets = {i: 0 for i in live}
            for b_idx in self._assign.values():
                if b_idx in buckets:
                    buckets[b_idx] += 1
            owned = {i: 0 for i in live}
            for o_idx in self._owner.values():
                if o_idx in owned:
                    owned[o_idx] += 1
            idx = min(live, key=lambda i: (buckets[i], owned[i], i))
            self._assign[key] = idx
            return idx

    def submit(self, cfg, net, model_name: str, dataset=None,
               deadline_s: Optional[float] = None,
               partition_span: Optional[Tuple[int, int]] = None,
               request_id: Optional[str] = None,
               spool_payload: Optional[dict] = None,
               submitted_at: Optional[float] = None,
               priority: int = PRIORITY_NORMAL,
               readmit: bool = False) -> VerifyRequest:
        idx = self._route(cfg, net, partition_span)
        with self._cv:
            srv = self._replicas[idx]
        if srv is None:  # quarantined between _route and here
            return self.submit(cfg, net, model_name, dataset=dataset,
                               deadline_s=deadline_s,
                               partition_span=partition_span,
                               request_id=request_id,
                               spool_payload=spool_payload,
                               submitted_at=submitted_at, priority=priority,
                               readmit=readmit)
        req = srv.submit(cfg, net, model_name, dataset=dataset,
                         deadline_s=deadline_s, partition_span=partition_span,
                         request_id=request_id, spool_payload=spool_payload,
                         submitted_at=submitted_at, priority=priority,
                         readmit=readmit)
        if req.status == REQUEUED and req.reason.startswith("replica killed"):
            # Raced a failover: the replica was killed around our enqueue.
            # The failover's orphan snapshot may already have re-homed the
            # id — prefer that copy; otherwise route it again ourselves.
            with self._cv:
                cur = self._owner.get(req.id)
                cur_srv = None if cur is None else self._replicas[cur]
            if cur_srv is not None:
                existing = cur_srv.get(req.id)
                if existing is not None:
                    return existing
            return self.submit(cfg, net, model_name, dataset=dataset,
                               deadline_s=deadline_s,
                               partition_span=partition_span,
                               request_id=req.id,
                               spool_payload=spool_payload,
                               submitted_at=submitted_at, priority=priority,
                               readmit=readmit)
        with self._cv:
            self._owner[req.id] = idx
        return req

    def owner_of(self, request_id: str) -> Optional[int]:
        with self._cv:
            return self._owner.get(request_id)

    def get(self, request_id: str) -> Optional[VerifyRequest]:
        with self._cv:
            idx = self._owner.get(request_id)
            srv = None if idx is None \
                else (self._replicas[idx] or self._dead.get(idx))
        return None if srv is None else srv.get(request_id)

    def wait(self, request_id: str, timeout: Optional[float] = None
             ) -> Optional[VerifyRequest]:
        """Block until terminal — across failovers: the owner may change
        mid-wait, so this polls ownership between short replica waits."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cv:
                idx = self._owner.get(request_id)
                # Quarantined replicas stay readable: a request that went
                # terminal before (or during) its replica's death is
                # still the answer — and a re-homed one flips _owner to
                # the survivor, which the next loop iteration picks up.
                srv = None if idx is None \
                    else (self._replicas[idx] or self._dead.get(idx))
            if srv is not None:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                step = 0.2 if left is None else max(min(0.2, left), 0.0)
                req = srv.wait(request_id, timeout=step)
                if req is not None and req.status in _TERMINAL:
                    return req
            if deadline is not None and time.monotonic() >= deadline:
                return self.get(request_id)
            if srv is None:
                time.sleep(0.05)

    # --- router loop ------------------------------------------------------

    def _router(self) -> None:
        while True:
            with self._cv:
                if self._draining:
                    return
            if self.cfg.spool:
                try:
                    self._scan_inbox()
                except BaseException as exc:
                    # Propagate-class (KeyboardInterrupt/SystemExit/
                    # ReplicaKilled/crash faults) must kill the router —
                    # a swallowed interrupt here would leave a zombie
                    # fleet scanning nothing; everything else degrades
                    # with a recorded reason and the router lives.
                    if classify(exc) == "propagate":
                        raise
                    obs.event("degraded", site="fleet.inbox",
                              error=type(exc).__name__,
                              detail=str(exc)[:200])
            self._health_sweep()
            with self._cv:
                if self._draining:
                    return
                self._cv.wait(timeout=self.cfg.poll_s)

    def _scan_inbox(self) -> None:
        """Route spool payloads to replicas.

        The fleet owns the inbox (replicas run spool-less), so it resolves
        payloads itself and routes through :meth:`submit`, mirroring
        ``VerificationServer._scan_inbox`` where it matters: rename-atomic
        consume, corruption quarantine, and a terminal ``status.json`` for
        unprocessable payloads so a waiting client always unblocks.
        """
        import json
        import os

        from fairify_tpu.serve.client import resolve_payload, \
            write_atomic_json
        from fairify_tpu.serve.request import monotonic_from_epoch, \
            new_request_id, parse_priority

        inbox = os.path.join(self.cfg.spool, "inbox")
        try:
            names = sorted(os.listdir(inbox))
        except OSError:
            return
        for name in names:
            with self._cv:
                if self._draining:
                    return
            if not name.endswith(".json"):
                continue
            path = os.path.join(inbox, name)
            try:
                with open(path) as fp:
                    payload = json.load(fp)
            except OSError:
                continue
            except json.JSONDecodeError as exc:
                try:
                    os.replace(path, f"{path}.corrupt")
                except OSError:
                    continue
                rid = name[: -len(".json")]
                rec = {"request": rid, "status": REJECTED, "model": "?",
                       "preset": "?",
                       "reason": f"corrupt payload (quarantined to "
                                 f"{name}.corrupt): {str(exc)[:200]}"}
                obs.registry().counter("serve_requests").inc(status=REJECTED)
                self._journal_record(rec)
                rdir = os.path.join(self.cfg.spool, "requests", rid)
                os.makedirs(rdir, exist_ok=True)
                write_atomic_json(os.path.join(rdir, "status.json"), rec)
                continue
            try:
                os.remove(path)
            except OSError:
                continue
            req_id = payload.get("id") or new_request_id()
            payload = dict(payload, id=req_id)
            rdir = os.path.join(self.cfg.spool, "requests", req_id)
            os.makedirs(rdir, exist_ok=True)
            write_atomic_json(os.path.join(rdir, "request.json"), payload)
            try:
                cfg, net, model_name, dataset = resolve_payload(payload,
                                                                rdir)
                deadline = payload.get("deadline_s",
                                       self.cfg.replica.default_deadline_s)
                span = payload.get("span")
                ts = payload.get("submitted_ts")
                self.submit(
                    cfg, net, model_name, dataset=dataset,
                    deadline_s=None if deadline is None else float(deadline),
                    partition_span=None if span is None
                    else (int(span[0]), int(span[1])),
                    request_id=req_id, spool_payload=payload,
                    submitted_at=None if ts is None
                    else monotonic_from_epoch(float(ts)),
                    priority=parse_priority(payload.get("priority",
                                                        PRIORITY_NORMAL)))
            except BaseException as exc:
                # Same contract as the router loop: kills/interrupts
                # re-raise; only genuinely per-payload failures become a
                # terminal rejection the waiting client can see.
                if classify(exc) == "propagate":
                    raise
                rec = {"request": req_id, "status": REJECTED,
                       "model": payload.get("model", "?"),
                       "preset": payload.get("preset", "?"),
                       "reason": f"{type(exc).__name__}: {str(exc)[:200]}"}
                obs.registry().counter("serve_requests").inc(status=REJECTED)
                self._journal_record(rec)
                write_atomic_json(os.path.join(rdir, "status.json"), rec)

    # --- health + failover ------------------------------------------------

    def _health_sweep(self) -> None:
        """One pass over the replicas: chaos site + liveness + lease."""
        with self._cv:
            replicas = list(self._replicas)
        for i, srv in enumerate(replicas):
            if srv is None:
                continue
            try:
                faults_mod.check("replica.lost")
            except BaseException as exc:
                # crash-kind faults and real interrupts re-raise (the
                # router is supposed to die with the process on those);
                # transient/fatal drive the absorb-vs-kill split below.
                kind = classify(exc)
                if kind == "propagate":
                    raise
                if kind == "transient":
                    # A heartbeat blip: absorbed, the replica lives.
                    obs.event("degraded", site="replica.lost", replica=i,
                              error=type(exc).__name__,
                              detail=str(exc)[:200])
                    continue
                # fatal: the injected loss IS the loss — kill the replica
                # so the genuine death-detection + failover path runs.
                srv.kill()
            started = srv.started()
            dead = srv.killed() or (started and not srv.alive())
            if not dead and srv.suspect():
                # An integrity violation fired inside this replica
                # (DESIGN.md §21): its data path is no longer trusted.
                # Quarantine = the death path — kill it and let failover
                # re-home its requests to clean replicas (resume replay
                # re-attempts the degraded integrity.* partitions).
                obs.registry().counter("replica_quarantined").inc(
                    replica=i, why="integrity")
                obs.event("replica_quarantined", replica=i, why="integrity")
                srv.kill()
                dead = True
            if not dead and self.cfg.lease_s > 0 and started:
                dead = srv.lease_age() > self.cfg.lease_s
            if dead:
                self._fail_over(i, srv)

    def _fail_over(self, idx: int, srv: VerificationServer) -> None:
        """Quarantine a dead replica and re-home its non-terminal requests.

        The dead replica did no cleanup (by design): every request it
        owned that is not terminal — queued, running mid-span, parked on
        its SMT drainer — is re-submitted to a survivor with
        ``readmit=True`` (no shedding of already-admitted work), the same
        id and result_dir, and the original SLA clock.  The survivor's
        ``resume=True`` run replays the partial ledger: decided verdicts
        are settled rows, so nothing decided is ever lost or recomputed.
        """
        registry = obs.registry()
        srv.kill()
        with self._cv:
            if self._replicas[idx] is None:  # already failed over
                return
            self._replicas[idx] = None
            self._dead[idx] = srv  # stays readable for get()/wait()
            self._assign = {k: v for k, v in self._assign.items()
                            if v != idx}
            survivors = [s for s in self._replicas if s is not None]
        registry.counter("replica_failures").inc(replica=idx)
        registry.gauge("fleet_replicas_alive").set(len(survivors))
        orphans = [r for r in srv.requests() if r.status not in _TERMINAL]
        obs.event("replica_lost", replica=idx, orphans=len(orphans),
                  survivors=len(survivors))
        with obs.span("fleet.failover", replica=idx, orphans=len(orphans),
                      survivors=len(survivors)):
            for req in orphans:
                self._journal_record({"request": req.id, "status": "requeued",
                                      "model": req.model_name,
                                      "replica": idx,
                                      "reason": f"replica {idx} lost"})
                if not survivors:
                    if self.cfg.spool and req.spool_payload is not None:
                        req.status = REQUEUED
                        req.reason = f"replica {idx} lost; no survivors"
                        self._respool(req)
                    else:
                        req.status = FAILED
                        req.reason = (f"replica {idx} lost; no survivors "
                                      f"to fail over to")
                        registry.counter("serve_requests").inc(status=FAILED)
                    self._journal_record(req.to_record())
                    with self._cv:
                        self._cv.notify_all()
                    continue
                self.submit(req.cfg, req.net, req.model_name,
                            dataset=req.dataset, deadline_s=req.deadline_s,
                            partition_span=req.partition_span,
                            request_id=req.id,
                            spool_payload=req.spool_payload,
                            submitted_at=req.submitted_at,
                            priority=req.priority, readmit=True)
        with self._cv:
            self._cv.notify_all()

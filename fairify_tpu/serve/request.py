"""Service request model: what a client asks for, and what it gets back.

A :class:`VerifyRequest` is one verification job handed to the persistent
server (:mod:`fairify_tpu.serve.server`): a resolved network + sweep
config, an optional partition span, and an optional wall-clock deadline
(the request's SLA).  The server owns the request's lifecycle:

``queued`` → ``running`` → ``done`` | ``failed`` | ``requeued``
                                   (``rejected`` never leaves admission)

* **rejected** — admission refused it (queue draining, or the SLA is
  infeasible against the measured backlog); nothing executed.
* **failed** — a runtime fault escaped the request's own fault domain
  (classified non-propagate): the *request* degrades with a
  machine-readable reason, the server loop stays alive.
* **requeued** — a graceful drain stopped the server before (or mid-way
  through) this request; its spool record is journaled so the next server
  picks it up with ``resume=True`` and its partial ledger replays.

Each request's sweep writes into its own ``result_dir`` (one directory per
request under the spool), so the verdict ledger the sweep streams through
:class:`resilience.journal.JournalWriter` doubles as the client-visible
incremental result feed — clients tail
``requests/<id>/<preset>-<model>@<span>.ledger.jsonl`` while the request
runs and read ``status.json`` for the terminal summary.
"""
from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Optional, Tuple

#: Lifecycle states (see module docstring for the transitions).
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
REJECTED = "rejected"
REQUEUED = "requeued"

#: Priority tiers (DESIGN.md §15): higher pops first, sheds last, and may
#: preempt a lower tier at a span-granule boundary.  Names are the client
#: payload vocabulary; the int is what the queue sorts on.
PRIORITIES = {"low": 0, "normal": 1, "high": 2}
PRIORITY_NORMAL = PRIORITIES["normal"]


def parse_priority(value) -> int:
    """Payload priority (name or int) → tier; raises on garbage so a bad
    payload rejects at submit instead of silently running ``normal``."""
    if isinstance(value, bool):
        raise ValueError(f"bad priority {value!r}")
    if isinstance(value, int):
        if value not in PRIORITIES.values():
            raise ValueError(f"bad priority {value!r} "
                             f"(want 0..2 or {sorted(PRIORITIES)})")
        return value
    if isinstance(value, str) and value.lower() in PRIORITIES:
        return PRIORITIES[value.lower()]
    raise ValueError(f"bad priority {value!r} (want {sorted(PRIORITIES)})")


def new_request_id() -> str:
    """Sortable-ish unique id: epoch millis + random suffix."""
    return f"r{int(time.time() * 1000):013d}-{uuid.uuid4().hex[:8]}"


def monotonic_from_epoch(ts: float) -> float:
    """Map an epoch stamp onto this process's monotonic clock.

    How a requeued request's original submit time (``submitted_ts`` in the
    spool payload) becomes the new server's ``submitted_at`` — the SLA
    clock keeps running across the handoff.  Clamped so a skewed future
    stamp can't grant extra budget."""
    return time.monotonic() - max(0.0, time.time() - ts)


@dataclass
class VerifyRequest:
    """One verification job: model + config + SLA.

    ``cfg.result_dir`` must already point at the request's own directory —
    the server never shares sinks between requests (per-request ledgers
    are the isolation boundary the bit-equality tests pin).
    """

    id: str
    cfg: object                 # verify.config.SweepConfig, fully resolved
    net: object                 # models.mlp.MLP
    model_name: str
    dataset: Optional[object] = None
    # Wall-clock SLA in seconds, measured from submit time; None = best
    # effort (no deadline, admission never rejects on feasibility).
    deadline_s: Optional[float] = None
    # [start, stop) global partition indices; None = the whole grid.
    partition_span: Optional[Tuple[int, int]] = None
    # Scheduling tier (PRIORITIES): pops before lower tiers, sheds after
    # them, and may preempt a running lower-tier request mid-flight.
    priority: int = PRIORITY_NORMAL
    # Spool-protocol payload (client.py): carried so a drain can journal
    # the request back for the next server; None for in-process submits.
    spool_payload: Optional[dict] = None
    # Distributed-trace context (obs.trace.TraceContext) recovered from
    # the payload's ``trace`` field; the server binds it around every
    # stage this request runs so spans/events/SMT frames carry the id.
    trace: Optional[object] = None

    # --- server-owned lifecycle state -------------------------------------
    status: str = QUEUED
    reason: str = ""            # rejection/failure/requeue detail
    submitted_at: float = field(default_factory=time.monotonic)
    started_at: Optional[float] = None
    finished_at: Optional[float] = None
    deadline_missed: bool = False
    report: Optional[object] = None   # verify.sweep.ModelReport when done
    # Times this request was preempted at a span-granule boundary and
    # requeued (bounded by the server's preemption cap — see DESIGN.md §15
    # starvation note); its partial ledger replays on the next run.
    preemptions: int = 0
    # Partitions this request's span covers (estimated at admission from
    # the grid size; exact once the report lands).
    partitions: int = 0

    @property
    def queue_wait_s(self) -> float:
        t = self.started_at if self.started_at is not None else time.monotonic()
        return max(t - self.submitted_at, 0.0)

    @property
    def run_s(self) -> float:
        if self.started_at is None:
            return 0.0
        t = self.finished_at if self.finished_at is not None else time.monotonic()
        return max(t - self.started_at, 0.0)

    def deadline_left(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds of SLA remaining (negative = already missed); None = no SLA."""
        if self.deadline_s is None:
            return None
        now = time.monotonic() if now is None else now
        return self.deadline_s - (now - self.submitted_at)

    def to_record(self) -> dict:
        """Lifecycle journal record (serve.journal.jsonl / obs events)."""
        rec = {
            "request": self.id, "status": self.status,
            "model": self.model_name, "preset": self.cfg.name,
            "queue_wait_s": round(self.queue_wait_s, 4),
            "run_s": round(self.run_s, 4),
            "deadline_s": self.deadline_s,
            "deadline_missed": self.deadline_missed,
            "partitions": self.partitions,
            "priority": self.priority,
        }
        if self.trace is not None:
            rec["trace_id"] = self.trace.trace_id
        if self.preemptions:
            rec["preemptions"] = self.preemptions
        if self.partition_span is not None:
            rec["span"] = f"{self.partition_span[0]}-{self.partition_span[1]}"
        if self.reason:
            rec["reason"] = self.reason
        if self.report is not None:
            rec.update(self.report.counts)
            rec["degraded"] = self.report.degraded
            fun = getattr(self.report, "funnel", None)
            if fun:
                # Funnel telemetry (obs.funnel, DESIGN.md §20): the state
                # counts and decided fraction ride the journal/status
                # records; histograms stay on the funnel event.
                rec["funnel"] = fun.get("states", {})
                rec["decided_fraction"] = round(
                    float(fun.get("decided_fraction", 0.0)), 6)
        return rec

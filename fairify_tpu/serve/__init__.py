"""Verification-as-a-service: the persistent ``fairify_tpu serve`` process.

One long-lived server owns the device and its warm ``obs_jit`` kernel
cache; concurrent verification requests share both.  The subsystem turns
the operational substrate of PRs 1–7 (spans/metrics, the async launch
pipeline, compile accounting, fault supervision, journals, the shard
fleet) into a service:

* :mod:`fairify_tpu.serve.request` — the job model and its lifecycle;
* :mod:`fairify_tpu.serve.admission` — SLA-aware admission over the
  budgeted-sweep predicate (``scripts/_sweeplib.py`` delegates here);
* :mod:`fairify_tpu.serve.batcher` — arch-bucketed cross-request
  coalescing into shared vmapped family launches;
* :mod:`fairify_tpu.serve.server` — the queue → admit → batch → stream
  worker loop with graceful SIGTERM drain;
* :mod:`fairify_tpu.serve.fleet` — N thread replicas behind one
  arch-bucket router with heartbeat failover (``fairify_tpu serve
  --replicas N``);
* :mod:`fairify_tpu.serve.procfleet` / :mod:`fairify_tpu.serve.replica`
  — N OS-process replicas with hard-kill containment, file-lease hang
  detection, and loss-free cross-process failover (``fairify_tpu serve
  --replica-procs N``, DESIGN.md §18);
* :mod:`fairify_tpu.serve.client` — the file-spool submit protocol
  (``fairify_tpu submit``).
"""
from fairify_tpu.serve.admission import (  # noqa: F401
    AdmissionController,
    AdmissionRejected,
    span_admissible,
)
from fairify_tpu.serve.fleet import FleetConfig, ServerFleet  # noqa: F401
from fairify_tpu.serve.procfleet import (  # noqa: F401
    ProcessFleet,
    ProcFleetConfig,
)
from fairify_tpu.serve.request import (  # noqa: F401
    PRIORITIES,
    VerifyRequest,
    new_request_id,
    parse_priority,
)
from fairify_tpu.serve.server import (  # noqa: F401
    ReplicaKilled,
    ServeConfig,
    VerificationServer,
)

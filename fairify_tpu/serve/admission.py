"""SLA-aware admission: the budgeted-sweep predicate, promoted to a service.

The budget-admission rule the sweep harness has used since PR 2
(``scripts/_sweeplib.py``): once a throughput rate is measured, never START
work whose committed in-flight cost exceeds a fraction of the remaining
budget — with the async launch pipeline, the moment a span starts,
``depth × chunk`` partitions are committed device work that must drain
even if the budget trips mid-span.  :func:`span_admissible` is that
predicate as a library function (``_sweeplib`` delegates to it), and
:class:`AdmissionController` applies the same logic at the request level:

* **throughput EMA** — completed requests update an exponential moving
  average of partitions/second (the service analog of the harness's
  per-span measured rate; an EMA because a long-lived server sees drift —
  cold compiles early, warm caches later).
* **backlog accounting** — every admitted request adds its estimated cost
  (``partitions / rate``) to the committed backlog; completion removes it.
* **SLA admission** — a request with a deadline is rejected at submit time
  when ``backlog + its own cost`` cannot fit inside the deadline (scaled
  by the same safety factor the harness uses: rate estimates are noisy and
  a hard-root tail can run ~2× its stage-0-dominated prediction).  With no
  measured rate yet every request admits — the first request is the
  throughput probe, exactly like the harness's first span.

``request.admit`` is the registered fault-injection site for the decision
(chaos cells reject a request instead of crashing the server).

**Load shedding** (DESIGN.md §15): under overload the controller stops
being a binary admit/reject and becomes honest triage.  A request is
*shed* — fast-failed ``rejected`` with a machine-readable ``shed: ...``
reason, before it costs any device time — when either

* the bounded queue is full (``max_queue``; higher tiers get a deeper
  allowance so interactive work still lands while batch work sheds), or
* the backlog EMA says its SLA is infeasible: predicted completion
  (committed device backlog + SMT backlog + its own cost) exceeds its
  deadline window, scaled by a per-priority headroom — low-priority work
  sheds earliest, high-priority last.

SERVE_r01 is why: without shedding, 16 concurrent clients drove p50 to
123 s and missed 62.5 % of deadlines — every queued request eventually
ran, uselessly, after its SLA.  A shed is a *rejection the client can act
on immediately* (resubmit later, lower the span, raise the deadline), not
a miss discovered two minutes too late.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from fairify_tpu.resilience import faults as faults_mod

#: Fraction of the remaining budget a newly started span (or admitted
#: request) may commit.  0.4 absorbs rate misestimates — see the budget-
#: honesty note in ``scripts/_sweeplib.py`` (a span that hits a hard-root
#: tail can run ~2x its stage-0-dominated prediction).
SAFETY_FACTOR = 0.4

#: Per-priority headroom multipliers on the SLA-feasibility factor and the
#: queue-depth bound: low-priority work sheds first (60 % of the normal
#: window), high-priority last (130 % — it may even borrow into the safety
#: margin, since a preemption path exists to reclaim the time).
PRIORITY_HEADROOM = {0: 0.6, 1: 1.0, 2: 1.3}


def span_admissible(rate: Optional[float], depth: int, chunk: int,
                    left_s: float, factor: float = SAFETY_FACTOR) -> bool:
    """May a span START given the measured rate and the remaining budget?

    ``rate`` is partitions/second (None = not yet measured: admit — the
    span doubles as the throughput probe).  The committed cost of starting
    is the whole in-flight backlog ``depth × chunk``, not one chunk.
    """
    if rate is None:
        return True
    return (depth * chunk) / max(rate, 1e-9) <= factor * left_s


class AdmissionController:
    """Thread-safe request admission over a throughput EMA + backlog.

    ``smt_backlog`` (a zero-arg callable returning seconds) folds
    HOST-side solver work into feasibility: the device-rate EMA knows
    nothing about the SMT pool's queue, so an UNKNOWN-heavy request
    stream could otherwise admit deadlines the Z3 phase is guaranteed to
    blow.  The server wires this to ``SmtPool.backlog_s``.
    """

    def __init__(self, ema_alpha: float = 0.3, factor: float = 0.8,
                 smt_backlog: Optional[Callable[[], float]] = None,
                 max_queue: int = 0):
        # ``factor`` is the admission analog of the harness's span factor:
        # the fraction of a request's SLA window its predicted completion
        # (backlog ahead of it + its own cost) may fill.  0.8 leaves the
        # headroom rate noise deserves without rejecting feasible work —
        # spans inside a budget use the stricter SAFETY_FACTOR because a
        # budget overrun has no retry, while a deadline miss is counted
        # and visible.
        self._alpha = float(ema_alpha)
        self._factor = float(factor)
        self._smt_backlog = smt_backlog
        # Bounded queue (0 = unbounded): the shed threshold in requests.
        # Scaled by PRIORITY_HEADROOM, so at max_queue=8 a low-priority
        # submit sheds at depth 4 while a high-priority one still lands
        # until depth 10.
        self._max_queue = int(max_queue)
        self._lock = threading.Lock()
        self._rate: Optional[float] = None      # partitions/sec EMA
        self._backlog_s: float = 0.0            # committed cost, seconds
        self._est: Dict[str, float] = {}        # request id -> admitted cost

    def rate(self) -> Optional[float]:
        with self._lock:
            return self._rate

    def backlog_s(self) -> float:
        with self._lock:
            return self._backlog_s

    def estimate_s(self, partitions: int) -> Optional[float]:
        """Predicted cost of a request (None until a rate is measured)."""
        with self._lock:
            if self._rate is None:
                return None
            return partitions / max(self._rate, 1e-9)

    def admit(self, request, queue_depth: int = 0) -> None:
        """Admit ``request`` or raise :class:`AdmissionRejected`.

        The decision is a named fault site (``request.admit``): an
        injected fault here surfaces as a rejection reason, never a server
        crash (the server classifies and converts; crash-kind propagates).

        ``queue_depth`` is the server queue length at submit time — the
        bounded-queue shed input.  Shed rejections carry ``kind="shed"``
        and a ``shed: ...`` reason prefix so clients, the lifecycle
        journal, and serve_bench can count them as honest triage rather
        than failures.
        """
        faults_mod.check("request.admit")
        headroom = PRIORITY_HEADROOM.get(
            getattr(request, "priority", 1), 1.0)
        if self._max_queue > 0 and queue_depth >= self._max_queue * headroom:
            raise AdmissionRejected(
                f"shed: queue full ({queue_depth} queued >= "
                f"{self._max_queue} x {headroom} priority headroom)",
                kind="shed")
        # Host-side solver backlog (measured outside the lock: the pool
        # has its own): committed work the device-rate EMA cannot see.
        smt_s = self._smt_backlog() if self._smt_backlog is not None else 0.0
        with self._lock:
            est = None if self._rate is None \
                else request.partitions / max(self._rate, 1e-9)
            if request.deadline_s is not None and est is not None:
                predicted = self._backlog_s + smt_s + est
                if predicted > self._factor * headroom * request.deadline_s:
                    raise AdmissionRejected(
                        f"shed: deadline-infeasible: predicted "
                        f"{predicted:.2f}s of committed work against a "
                        f"{request.deadline_s:.2f}s deadline "
                        f"(rate {self._rate:.1f} parts/s, backlog "
                        f"{self._backlog_s:.2f}s device + {smt_s:.2f}s smt, "
                        f"priority headroom {headroom})", kind="shed")
            self._est[request.id] = est or 0.0
            self._backlog_s += est or 0.0

    def readmit(self, request) -> None:
        """Account an already-admitted request re-homed by failover.

        No shed/feasibility decision: the request passed admission once on
        the replica that died, and turning a replica loss into a client-
        visible rejection would violate the loss-free handoff contract.
        Backlog is still committed so subsequent admits see the true load.
        """
        with self._lock:
            est = None if self._rate is None \
                else request.partitions / max(self._rate, 1e-9)
            self._est[request.id] = est or 0.0
            self._backlog_s += est or 0.0

    def release(self, request) -> None:
        """Drop an admitted request's backlog share (rejected-after-admit
        or drained before running)."""
        with self._lock:
            self._backlog_s -= self._est.pop(request.id, 0.0)
            self._backlog_s = max(self._backlog_s, 0.0)

    def finished(self, request, partitions: int, elapsed_s: float) -> None:
        """Fold a completed request into the rate EMA and free its backlog."""
        with self._lock:
            self._backlog_s -= self._est.pop(request.id, 0.0)
            self._backlog_s = max(self._backlog_s, 0.0)
            if elapsed_s <= 0.0 or partitions <= 0:
                return
            sample = partitions / elapsed_s
            self._rate = sample if self._rate is None \
                else (1.0 - self._alpha) * self._rate + self._alpha * sample


class AdmissionRejected(RuntimeError):
    """Raised by :meth:`AdmissionController.admit`; the reason is the str.

    ``kind`` distinguishes a *shed* (honest overload triage — the client
    should back off and resubmit) from any other refusal (draining, an
    unprocessable request); serve_bench and perfdiff count the two
    differently.
    """

    def __init__(self, reason: str, kind: str = "rejected"):
        super().__init__(reason)
        self.kind = kind

"""The persistent verification server: queue → admission → batch → stream.

One long-lived process owns the device and its warm ``obs_jit`` kernel
cache; requests share both.  The event loop is a single worker thread
(the device is a serial resource — cross-request parallelism comes from
*coalescing* work into wider launches, not from racing threads at the
dispatch ring):

1. **submit** (any thread, or the spool inbox): the request is admitted
   against the SLA feasibility predicate (:mod:`serve.admission`) and
   queued; rejected requests never execute.
2. **batch**: the worker collects up to ``max_batch`` queued requests
   inside a ``batch_window_s`` coalescing window, hands them to the
   arch-bucketed batcher (:mod:`serve.batcher`) — same-architecture
   requests get their stage-0 certificates/attacks from shared vmapped
   family launches through one :class:`LaunchPipeline` — then runs each
   request's refinement in FIFO order with its precomputed stage 0.
3. **stream**: every request's sweep writes its own JSONL verdict ledger
   incrementally (the normal ``verify_model`` ledger, atomic + fsync'd via
   :class:`resilience.journal.JournalWriter`), so clients tail results
   while the request runs; lifecycle transitions land in
   ``serve.journal.jsonl`` and as obs ``request`` events.

Fault semantics (the per-request blast radius, DESIGN.md §13): a runtime
fault inside one request's execution is classified by the resilience
taxonomy — transient faults are already absorbed per chunk by the sweep's
own supervisor; anything that still escapes marks *that request* failed
with a machine-readable reason and the server loop continues.  Only
propagate-class errors (crash faults, KeyboardInterrupt) kill the server.

Graceful drain (SIGTERM): in-flight work finishes — the running batch's
launches drain through the normal pipeline; with ``span_chunks > 0`` the
running request itself yields at its next chunk-aligned span boundary —
and every request still queued (or preempted mid-request) is journaled
``requeued`` with its spool payload written back to the inbox, so the next
server picks it up and its ledger replays ``resume=True``.

Overload survival (DESIGN.md §15): the queue is priority-ordered (higher
tiers pop first), admission sheds honestly once the bounded queue or the
backlog EMA says an SLA is infeasible (``rejected`` with a ``shed:``
reason, counted separately from misses), and with ``span_chunks > 0`` a
running over-budget request is *preempted* at its next chunk-aligned
granule when strictly-higher-priority work waits — the same yield
machinery the drain uses, fired mid-flight: the request requeues with its
partial ledger intact and replays ``resume=True`` when it next pops.
``request.preempt`` is the chaos site for the decision.

Replica mode (:mod:`serve.fleet`): a fleet-managed replica can be
:meth:`kill`-ed — the worker, SMT drainer, and any span-granular request
abandon at their next yield point with NO cleanup (no drain journaling, no
terminal transitions), mirroring a process SIGKILL as closely as a thread
can.  The fleet router detects the death via :meth:`alive` and re-spools
the replica's in-flight + queued requests to survivors; ``resume=True``
ledger replay makes that handoff loss-free.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from fairify_tpu import obs
from fairify_tpu.obs import funnel as funnel_mod
from fairify_tpu.obs import trace as trace_mod
from fairify_tpu.resilience import faults as faults_mod
from fairify_tpu.resilience.journal import JournalWriter
from fairify_tpu.resilience.supervisor import Supervisor, classify
from fairify_tpu.serve import batcher
from fairify_tpu.serve.admission import AdmissionController, AdmissionRejected
from fairify_tpu.serve.client import write_atomic_json as _atomic_json
from fairify_tpu.serve.request import (
    DONE,
    FAILED,
    PRIORITY_NORMAL,
    QUEUED,
    REJECTED,
    REQUEUED,
    RUNNING,
    VerifyRequest,
    monotonic_from_epoch,
    new_request_id,
    parse_priority,
)


class ReplicaKilled(BaseException):
    """Raised at cooperative yield points after :meth:`kill`.

    A ``BaseException`` so no request-level handler converts it into a
    per-request failure: a killed replica must abandon everything exactly
    as a SIGKILL'd process would — recovery belongs to the fleet router's
    failover, which re-spools the dead replica's requests to survivors.
    """


@dataclass(frozen=True)
class ServeConfig:
    """Server knobs (the CLI flags of ``fairify_tpu serve``)."""

    # Spool directory (inbox/ + requests/ + serve.journal.jsonl); None =
    # in-process submits only (tests, embedding).
    spool: Optional[str] = None
    # How long the worker waits after the first queued request for more to
    # coalesce into the same batch (the cross-request batching window).
    batch_window_s: float = 0.05
    # Most requests coalesced into one batch — AND the fixed model-axis
    # width every coalesced family stack is padded to (batcher
    # ``pad_models``): one compiled family executable per architecture,
    # whatever the batch occupancy.  The vmapped kernels scale linearly
    # in it, so under-filled batches trade idle FLOPs for zero recompiles.
    max_batch: int = 8
    # Refinement granule in grid chunks: 0 = each request runs as ONE
    # verify_model call (no mid-request preemption; bit-identical to its
    # solo run), N > 0 = the request yields every N chunks so drain and
    # deadline checks interleave mid-request (chunk-aligned spans keep the
    # RNG streams global, so decided verdicts are granule-invariant).
    span_chunks: int = 0
    # Inbox poll interval (seconds) when a spool is configured.
    poll_s: float = 0.1
    # Deadline applied to spool requests that do not carry one; None =
    # best effort.
    default_deadline_s: Optional[float] = None
    # Route each request through the PR 7 shard fleet instead of the
    # single-mesh sweep (per-request fault domains over the visible
    # devices; disables cross-request stage-0 stacking, which is
    # grid-global while shards are span-local).
    n_shards: Optional[int] = None
    # --- SMT worker pool (fairify_tpu/smt, DESIGN.md §14) ---------------
    # One server-wide pool shared by every request whose cfg enables the
    # SMT UNKNOWN-retry ladder; sized here (not per request) because the
    # workers are a host resource like the device.  The worker loop's SMT
    # phase is NON-blocking: still-solving queries come back as a
    # report.smt_pending drain that a background thread finishes while
    # the next request's device launches proceed.
    smt_workers: int = 1
    smt_memory_cap_mb: int = 0
    smt_portfolio: int = 0
    # --- overload control (DESIGN.md §15) -------------------------------
    # Bounded queue: submits past this depth are shed (rejected with a
    # machine-readable "shed:" reason) instead of queued into an SLA they
    # can no longer meet.  Scaled per priority tier (admission.
    # PRIORITY_HEADROOM); 0 = unbounded (the pre-overload-control
    # behavior).
    max_queue: int = 0
    # Preemption: with span_chunks > 0, a running request that has spent
    # more than preempt_factor x its admission estimate (or is
    # best-effort) yields at its next granule when strictly-higher-
    # priority work waits.  0 disables preemption.
    preempt_factor: float = 0.0
    # Starvation bound: a request preempted this many times runs to
    # completion regardless of waiters.
    max_preemptions: int = 2
    # Fair-share budget clamp (overload control): when > 0 and other work
    # is committed at dispatch time, a request's hard refinement budget is
    # clamped to fair_share_factor x its admission estimate (but never
    # below fair_share_min_s).  Device time a request cannot have without
    # starving the queue becomes honest budget-exhausted UNKNOWNs —
    # ledgered, client-visible, and resumable off-peak — instead of tail
    # latency for everything behind it.  The SERVE_r01 16-client collapse
    # was exactly this shape: one mispredicted request legally consumed
    # its whole 120 s SLA while the queue starved.  0 = off (a request
    # may spend up to its SLA, the pre-overload-control behavior).
    fair_share_factor: float = 0.0
    fair_share_min_s: float = 2.0
    # With the exemption on (default), an uncontended dispatch (nothing
    # queued, nothing else in the batch) escapes the clamp and may spend
    # its whole SLA on optional refinement.  A latency-predictable
    # serving tier turns it off: EVERY dispatch is clamped to its fair
    # share, so the tail request of a burst cannot stretch the level by
    # 10x just because the queue happened to be empty when it popped —
    # exhaustive refinement is batch mode's job.
    fair_share_idle_exempt: bool = True
    # Persistent executable cache directory (obs.compile.
    # enable_exec_cache): a restarted server or fresh replica loads
    # AOT-serialized executables instead of recompiling — near-zero cold
    # start.  None = per-process compile behavior unchanged.
    exec_cache: Optional[str] = None
    # Fleet bookkeeping: the replica's index when this server is one of
    # serve.fleet's replicas (labels journal records and metrics; enables
    # nothing by itself).
    replica_id: Optional[int] = None
    # Request-sink root override (serve.procfleet): a process-fleet
    # replica spools from its OWN sub-inbox (<fleet spool>/replicas/<i>)
    # but must write request dirs into the FLEET's shared requests/ — the
    # stable result_dir is what makes a cross-process failover's
    # ``resume=True`` ledger replay loss-free.  None = <spool>/requests.
    requests_dir: Optional[str] = None
    # File-lease heartbeat (serve.procfleet): when set, the worker touches
    # this file at every yield point it already beats (batch-loop
    # iterations, span granules) — the cross-process analog of
    # ``lease_age()``, readable by a router in another process via mtime.
    lease_path: Optional[str] = None
    # Shared trace-shard directory (DESIGN.md §19): handed to the SMT
    # pool so its worker subprocesses append their own trace.<pid>.jsonl
    # shards next to this process's.  The server itself does NOT open a
    # tracer off this — whoever owns the process (cli serve, replica
    # main) activates the shard; this only propagates the directory to
    # the next process boundary down.
    trace_dir: Optional[str] = None
    # XLA profiler capture directory (``--xprof-dir``): every request's
    # device phases run inside ``jax.profiler.trace(xprof_dir)`` via the
    # sweep's ``profile_dir`` (utils.profiling.xla_trace), stamping the
    # device timeline with the obs span names.  None = no capture.
    xprof_dir: Optional[str] = None


class VerificationServer:
    """Single-process verification service (see module docstring).

    Use as a context manager, or ``start()`` / ``drain()`` explicitly::

        with VerificationServer(ServeConfig(spool="spool")) as srv:
            req = srv.submit(cfg, net, "GC-1", deadline_s=60.0)
            srv.wait(req.id, timeout=120.0)
    """

    def __init__(self, cfg: ServeConfig = ServeConfig(), journal=None,
                 transition_fn=None):
        """``journal`` injects a shared lifecycle JournalWriter (the fleet
        passes its fleet-wide one to every replica; the owner closes it).
        ``transition_fn`` observes every lifecycle journal record (the
        process-fleet replica forwards them over its control pipe)."""
        self.cfg = cfg
        self._transition_fn = transition_fn
        self.admission = AdmissionController(smt_backlog=self._smt_backlog_s,
                                             max_queue=cfg.max_queue)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._requests: Dict[str, VerifyRequest] = {}
        self._grids: Dict[tuple, Tuple] = {}
        self._draining = False
        self._killed = False
        self._suspect = False  # integrity violation seen; router quarantines
        self._last_beat = time.monotonic()
        self._inflight = 0  # popped-batch members not yet terminal
        self._thread: Optional[threading.Thread] = None
        self._sup = Supervisor(max_retries=2, backoff_s=0.05)
        self._journal_writer: Optional[JournalWriter] = journal
        self._owns_journal = journal is None
        self._smt_pool = None                   # lazy; server-wide
        self._smt_drain_q: deque = deque()      # (req, report) to finish
        self._smt_drainer: Optional[threading.Thread] = None
        self._smt_draining_id: Optional[str] = None  # popped, in drain()
        if cfg.spool:
            os.makedirs(os.path.join(cfg.spool, "inbox"), exist_ok=True)
            os.makedirs(self._requests_root(), exist_ok=True)
            if self._journal_writer is None:
                self._journal_writer = JournalWriter(
                    os.path.join(cfg.spool, "serve.journal.jsonl"),
                    supervisor=self._sup)
        if cfg.lease_path:
            # Born fresh: the router's lease clock starts at spawn, not at
            # the first batch iteration (a replica that wedges before its
            # first beat must still expire).
            with open(cfg.lease_path, "a"):
                pass
            os.utime(cfg.lease_path, None)
        if cfg.exec_cache:
            from fairify_tpu.obs import compile as compile_obs

            compile_obs.enable_exec_cache(cfg.exec_cache)

    def _requests_root(self) -> str:
        """Root of the per-request sink dirs (``requests_dir`` override or
        ``<spool>/requests``) — a process-fleet replica points this at the
        fleet's shared tree so failover keeps every result_dir stable."""
        return self.cfg.requests_dir or os.path.join(self.cfg.spool,
                                                     "requests")

    def _touch_lease(self) -> None:
        """Beat the cross-process file lease (no-op without one).

        Called at the worker's yield points OUTSIDE ``_cv`` — file I/O
        under a lock is a blocking-under-lock violation, and the lease
        needs no serialization (any beat sets mtime = now)."""
        if not self.cfg.lease_path:
            return
        try:
            os.utime(self.cfg.lease_path, None)
        except OSError:
            try:
                with open(self.cfg.lease_path, "a"):
                    pass
            except OSError:
                pass  # a missing/readonly lease must never kill the worker

    # --- lifecycle --------------------------------------------------------

    def start(self) -> "VerificationServer":
        if self._thread is None:
            self._thread = threading.Thread(target=self._worker,
                                            name="fairify-serve", daemon=True)
            self._thread.start()
        return self

    def __enter__(self) -> "VerificationServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.drain()
        return False

    def drain(self) -> List[VerifyRequest]:
        """Graceful shutdown: finish in-flight work, requeue the rest.

        Returns the requests that were journaled ``requeued``.  The
        ``serve.drain`` fault site fires here; a non-crash injected fault
        is recorded and drain proceeds — shutdown must not be deniable.
        """
        try:
            faults_mod.check("serve.drain")
        except BaseException as exc:
            if classify(exc) == "propagate":
                raise
            obs.event("degraded", site="serve.drain",
                      error=type(exc).__name__, detail=str(exc)[:200])
        with self._cv:
            self._draining = True
            queued = list(self._queue)
            self._queue.clear()
            self._cv.notify_all()
        requeued = [self._requeue(req) for req in queued]
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        # In-flight work finishes — including requests parked on the SMT
        # drainer: everything queued BEFORE the sentinel completes (the
        # pool's hard deadlines bound the wait), then the pool's workers
        # are reaped.
        with self._cv:
            drainer = self._smt_drainer
            if drainer is not None:
                self._smt_drain_q.append(None)
                self._cv.notify_all()
        if drainer is not None:
            drainer.join()
            with self._cv:
                self._smt_drainer = None
        with self._cv:
            pool = self._smt_pool
            self._smt_pool = None
        if pool is not None:
            pool.close()
        # The worker may have preempted its running request at a span
        # boundary; it requeues that one itself before exiting — fold it
        # into the return value so the drain report is complete.
        with self._cv:
            seen = {r.id for r in requeued}
            requeued += [r for r in self._requests.values()
                         if r.status == REQUEUED and r.id not in seen]
        if self._journal_writer is not None and self._owns_journal:
            self._journal_writer.close()
        return requeued

    def kill(self) -> None:
        """Hard-stop for fleet failover and chaos: NO cleanup.

        The worker loop, SMT drainer, and any span-granular request raise
        :class:`ReplicaKilled` at their next cooperative yield point and
        abandon everything — no drain journaling, no terminal
        transitions, no requeues.  That is deliberate: a real replica
        loss (OOM kill, host death) performs no cleanup either, and the
        recovery contract lives entirely in the fleet router's failover
        (re-spool to survivors) + the crash-safe ledger (``resume=True``
        replay).  After ``kill()``, :meth:`alive` flips False as soon as
        the worker reaches a yield point.
        """
        with self._cv:
            self._killed = True
            self._cv.notify_all()

    def killed(self) -> bool:
        with self._cv:
            return self._killed

    def suspect(self) -> bool:
        """True once an integrity violation fired inside one of this
        replica's requests (DESIGN.md §21).  The request itself already
        contained the damage (its partitions degraded to
        ``unknown:failure:integrity.*``), but a replica that has seen SDC
        once cannot be trusted for the next request — the fleet router
        treats a suspect replica like a dead one: kill + fail over, so
        every re-homed request resumes on clean hardware."""
        with self._cv:
            return self._suspect

    def started(self) -> bool:
        """Has :meth:`start` ever launched the worker (live or not)?"""
        return self._thread is not None

    def lease_age(self) -> float:
        """Seconds since the worker last reached a yield point (its
        heartbeat lease input): batch-loop iterations and span-granule
        boundaries beat; a long granule-less request legitimately goes
        dark for its whole runtime — see ``FleetConfig.lease_s``."""
        with self._cv:
            return time.monotonic() - self._last_beat

    def requests(self) -> List[VerifyRequest]:
        """Snapshot of every request this server has seen (fleet failover
        walks this to find the dead replica's non-terminal requests)."""
        with self._cv:
            return list(self._requests.values())

    def load(self) -> int:
        """Committed request count (queued + popped-but-unfinished): the
        fleet router's spill-over input."""
        with self._cv:
            return len(self._queue) + self._inflight

    def _requeue(self, req: VerifyRequest) -> VerifyRequest:
        req.status = REQUEUED
        req.reason = req.reason or "server draining"
        self.admission.release(req)
        self._journal(req)
        if self.cfg.spool and req.spool_payload is not None:
            # Back into the inbox for the next server; its result_dir is
            # stable (requests/<id>/), so the replayed run resumes from
            # the ledger instead of recomputing.
            _atomic_json(os.path.join(self.cfg.spool, "inbox",
                                      f"{req.id}.json"), req.spool_payload)
        with self._cv:
            self._cv.notify_all()   # wake wait()ers: requeued is terminal
        return req

    # --- submission -------------------------------------------------------

    def submit(self, cfg, net, model_name: str, dataset=None,
               deadline_s: Optional[float] = None,
               partition_span: Optional[Tuple[int, int]] = None,
               request_id: Optional[str] = None,
               spool_payload: Optional[dict] = None,
               submitted_at: Optional[float] = None,
               priority: int = PRIORITY_NORMAL,
               readmit: bool = False,
               trace: Optional[trace_mod.TraceContext] = None
               ) -> VerifyRequest:
        """Queue one verification job; returns the request (possibly
        already ``rejected`` — check ``status``).  Thread-safe.

        ``submitted_at`` (monotonic) backdates the SLA clock — spool
        pickups pass the payload's original submit stamp so a
        drain/requeue handoff doesn't silently extend the deadline.

        ``readmit=True`` skips the shed/feasibility decision (backlog is
        still accounted): the fleet's failover path re-homes requests a
        dead replica already admitted once — shedding them again would
        turn a replica loss into client-visible rejections."""
        req = VerifyRequest(
            id=request_id or new_request_id(), cfg=cfg, net=net,
            model_name=model_name, dataset=dataset, deadline_s=deadline_s,
            partition_span=partition_span, spool_payload=spool_payload,
            priority=priority,
            trace=trace if trace is not None
            else trace_mod.TraceContext.from_fields(spool_payload)
            or trace_mod.current_context()
            # In-process submits with a live tracer but no inherited
            # context (a bench thread, a notebook) still get a root id —
            # otherwise their spans never join a critical-path row.
            or (trace_mod.TraceContext(trace_id=trace_mod.new_trace_id())
                if trace_mod.current() is not None else None))
        if submitted_at is not None:
            req.submitted_at = submitted_at
        req.partitions = self._span_size(cfg, partition_span)
        registry = obs.registry()
        with self._cv:
            draining = self._draining
            if self._killed:
                # Killed (fleet failover in progress): nothing will ever
                # pop this queue.  Hand the request straight back as
                # REQUEUED so the fleet's submit re-routes it to a
                # survivor instead of stranding it here.
                req.status = REQUEUED
                req.reason = "replica killed"
                self._requests[req.id] = req
                return req
        if draining and self.cfg.spool and spool_payload is not None:
            # A spool-backed request arriving during drain (the worker's
            # last inbox scan racing the shutdown) must NOT be consumed as
            # a rejection — requeue it so the payload lands back in the
            # inbox and the next server picks it up.
            with self._cv:
                self._requests[req.id] = req
            return self._requeue(req)
        try:
            if draining:
                raise AdmissionRejected("server draining")
            with self._cv:
                depth = len(self._queue) + self._inflight
            # The admission stage of the critical path: bound to the
            # request's trace so the merged view shows where a shed/reject
            # decision was made (and how long feasibility sizing took).
            with trace_mod.context(req.trace), \
                    obs.span("serve.admit", request=req.id,
                             queue_depth=depth):
                if readmit:
                    self.admission.readmit(req)
                else:
                    self.admission.admit(req, queue_depth=depth)
        except BaseException as exc:
            if classify(exc) == "propagate":
                raise
            req.status = REJECTED
            req.reason = str(exc)
            if getattr(exc, "kind", "") == "shed":
                registry.counter("serve_shed").inc(priority=req.priority)
            registry.counter("serve_requests").inc(status=REJECTED)
            with self._cv:
                self._requests[req.id] = req
            # Rejection is terminal: a spool client polling status.json
            # must unblock, not wait out its timeout.
            self._finish(req)
            return req
        with self._cv:
            self._requests[req.id] = req
            if self._draining:
                # drain() snapped the queue between our draining check
                # and this append — enqueueing now would strand the
                # request (the worker is gone).  Hand it to the drain
                # path instead.
                drained_in_race = True
            else:
                drained_in_race = False
                self._queue.append(req)
                registry.gauge("serve_queue_depth").set(len(self._queue))
                self._cv.notify_all()
        if drained_in_race:
            if self.cfg.spool and spool_payload is not None:
                return self._requeue(req)       # releases its admission
            self.admission.release(req)
            req.status = REJECTED
            req.reason = "server draining"
            registry.counter("serve_requests").inc(status=REJECTED)
            self._finish(req)
            return req
        with self._cv:
            if self._killed and req in self._queue:
                # kill() landed between the killed check above and the
                # enqueue — and possibly after the failover's orphan
                # snapshot, which would then never see this request.
                # Take it back out and return it REQUEUED for re-routing.
                self._queue.remove(req)
                req.status = REQUEUED
                req.reason = "replica killed during submit"
                self.admission.release(req)
                return req
        registry.counter("serve_requests").inc(status=QUEUED)
        self._journal(req)
        return req

    def _grid(self, cfg) -> Tuple:
        """Full-grid ``(lo, hi)`` memoized per stage-0 signature — stress
        grids reach millions of boxes and must not be rebuilt per request
        (admission sizing) or per coalesced batch (the worker thread)."""
        sig = batcher.stage0_signature(cfg, None)
        with self._cv:
            got = self._grids.get(sig)
        if got is None:
            from fairify_tpu.verify import sweep as sweep_mod

            _, lo, hi = sweep_mod.build_partitions(cfg)
            got = (lo, hi)
            with self._cv:
                self._grids[sig] = got
        return got

    def _span_size(self, cfg, partition_span) -> int:
        """Partition count of the request's span (admission cost input)."""
        if partition_span is not None:
            return int(partition_span[1]) - int(partition_span[0])
        lo, _hi = self._grid(cfg)
        return int(lo.shape[0])

    # --- SMT pool (server-wide; DESIGN.md §14) ----------------------------

    def _smt_backlog_s(self) -> float:
        """Host-solver backlog for SLA admission (0 without a pool)."""
        with self._cv:
            pool = self._smt_pool
        return pool.backlog_s() if pool is not None else 0.0

    def _smt_pool_get(self, cfg):
        """The shared pool, created on the first SMT-enabled request."""
        if not cfg.smt_retry_timeouts_s:
            return None
        with self._cv:
            if self._smt_pool is None:
                from fairify_tpu.smt.pool import PoolConfig, SmtPool

                self._smt_pool = SmtPool(PoolConfig(
                    workers=max(int(self.cfg.smt_workers), 1),
                    memory_cap_mb=self.cfg.smt_memory_cap_mb,
                    portfolio=self.cfg.smt_portfolio,
                    trace_dir=self.cfg.trace_dir))
            return self._smt_pool

    def _smt_defer(self, req: VerifyRequest, report) -> None:
        """Park a request whose SMT queries are still solving: the worker
        loop moves on to the next request's device launches; a background
        drainer finishes this one when the pool answers."""
        with self._cv:
            self._smt_drain_q.append((req, report))
            if self._smt_drainer is None or not self._smt_drainer.is_alive():
                self._smt_drainer = threading.Thread(
                    target=self._smt_drain_loop, name="fairify-smt-drain",
                    daemon=True)
                self._smt_drainer.start()
            self._cv.notify_all()

    def _smt_drain_loop(self) -> None:
        registry = obs.registry()
        while True:
            with self._cv:
                while not self._smt_drain_q:
                    if self._killed:
                        return  # abandon parked requests: failover re-runs
                    self._cv.wait(timeout=0.5)
                if self._killed:
                    # Parked requests stay RUNNING with their ledger rows
                    # WITHHELD (smt_defer contract) — the fleet re-spools
                    # them and resume re-attempts, sound.
                    return
                item = self._smt_drain_q.popleft()
                self._smt_draining_id = None if item is None else item[0].id
            if item is None:
                return  # drain() sentinel: everything before it is done
            req, report = item
            try:
                # Same suspect attribution as _run_request: an integrity
                # violation surfacing during the deferred SMT drain (an
                # invalid witness) marks this replica suspect too.
                iv0 = registry.counter("integrity_violations").total()
                try:
                    with trace_mod.context(req.trace), \
                            obs.span("serve.smt_drain", request=req.id,
                                     queries=report.smt_pending.pending):
                        report.smt_pending.drain()
                finally:
                    if registry.counter(
                            "integrity_violations").total() > iv0:
                        with self._cv:
                            self._suspect = True
                        registry.counter("replica_suspect").inc()
                        obs.event("replica_suspect", request=req.id,
                                  model=req.model_name)
                report.smt_pending = None
            except BaseException as exc:
                if classify(exc) == "propagate":
                    # Leave the request client-visible before the drainer
                    # dies (mirrors the worker-loop crash contract).
                    req.status = FAILED
                    req.reason = f"smt drain crash: {type(exc).__name__}"
                    req.finished_at = time.monotonic()
                    self.admission.release(req)
                    self._finish(req)
                    raise
                req.status = FAILED
                req.reason = f"{type(exc).__name__}: {str(exc)[:200]}"
                req.finished_at = time.monotonic()
                registry.counter("serve_requests").inc(status=FAILED)
                registry.counter("serve_request_failures").inc(
                    error=type(exc).__name__)
                self.admission.release(req)
                self._finish(req)
                with self._cv:
                    self._smt_draining_id = None
                continue
            with trace_mod.context(req.trace):
                self._complete(req, report)
            with self._cv:
                self._smt_draining_id = None

    def alive(self) -> bool:
        """True while the worker thread is running.

        False after a drain — or after a propagate-class crash killed the
        worker (by design, see ``_worker``): the process may look healthy
        while the inbox is never scanned again, so operators (``fairify_tpu
        serve``) must poll this and drain when it flips."""
        return self._thread is not None and self._thread.is_alive()

    def get(self, request_id: str) -> Optional[VerifyRequest]:
        with self._cv:
            return self._requests.get(request_id)

    def wait(self, request_id: str, timeout: Optional[float] = None
             ) -> Optional[VerifyRequest]:
        """Block until the request reaches a terminal state.

        Event-driven: terminal transitions notify ``_cv`` (the 0.5 s cap
        on each wait is a backstop, not the latency)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        terminal = (DONE, FAILED, REJECTED, REQUEUED)
        with self._cv:
            while True:
                req = self._requests.get(request_id)
                if req is not None and req.status in terminal:
                    return req
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0.0:
                    return req
                self._cv.wait(timeout=0.5 if left is None
                              else min(0.5, left))

    # --- worker loop ------------------------------------------------------

    def _worker(self) -> None:
        while True:
            try:
                batch = self._next_batch()
            except ReplicaKilled:
                return  # abandoned: fleet failover owns recovery
            if not batch:
                return
            with self._cv:
                # Popped work is still committed load: the shed decision
                # must see it, or a burst that pops straight into a batch
                # resets the bounded queue to "empty" while the device owes
                # minutes of work.
                self._inflight = len(batch)
            try:
                self._run_batch(batch)
            except ReplicaKilled:
                # Killed mid-batch: leave every member exactly as it was
                # (RUNNING/QUEUED) — the fleet re-spools them to survivors
                # and resume=True replays their partial ledgers.  Cleanup
                # here would turn a loss-free failover into failures.
                return
            except BaseException as exc:
                # A propagate-class error (crash fault, interrupt) escaped
                # a request: leave every batch member in a client-visible
                # terminal state and let the thread die — the
                # process-level contract is the ledger's, not ours.  The
                # batch was already popped from the queue, so members the
                # crash beat to the device would otherwise be stranded
                # ``queued`` forever: spool-backed ones go back to the
                # inbox for the next server, in-process ones fail.
                with self._cv:
                    draining_ids = {item[0].id for item in self._smt_drain_q
                                    if item is not None}
                    if self._smt_draining_id is not None:
                        # Popped and actively draining: that thread owns
                        # its terminal transition — touching it here would
                        # double-release its admission share and flip a
                        # client-visible FAILED back to DONE.
                        draining_ids.add(self._smt_draining_id)
                for req in batch:
                    if req.status not in (QUEUED, RUNNING):
                        continue
                    if req.id in draining_ids:
                        # Parked on the SMT drainer: that thread owns its
                        # terminal transition and survives this crash.
                        continue
                    req.reason = f"server crash: {type(exc).__name__}"
                    if req.status == QUEUED and self.cfg.spool \
                            and req.spool_payload is not None:
                        self._requeue(req)
                        continue
                    req.status = FAILED
                    self.admission.release(req)
                    self._finish(req)
                raise
            with self._cv:
                self._inflight = 0

    def _next_batch(self) -> List[VerifyRequest]:
        window_until: Optional[float] = None
        while True:
            if self.cfg.spool:
                try:
                    self._scan_inbox()
                except BaseException as exc:
                    # A scan flake (fs blip, racing server) must not kill
                    # the worker — queued requests would strand forever.
                    if classify(exc) == "propagate":
                        raise
                    obs.event("degraded", site="serve.inbox",
                              error=type(exc).__name__,
                              detail=str(exc)[:200])
            self._touch_lease()
            with self._cv:
                now = time.monotonic()
                self._last_beat = now
                if self._killed:
                    raise ReplicaKilled()
                if self._draining:
                    return []
                if self._queue:
                    if window_until is None:
                        window_until = now + self.cfg.batch_window_s
                    if len(self._queue) >= self.cfg.max_batch \
                            or now >= window_until:
                        batch = self._pop_batch(self.cfg.max_batch)
                        obs.registry().gauge("serve_queue_depth").set(
                            len(self._queue))
                        return batch
                    self._cv.wait(timeout=window_until - now)
                    continue
                window_until = None
                self._cv.wait(timeout=self.cfg.poll_s)

    def _pop_batch(self, n: int) -> List[VerifyRequest]:
        """Pop up to ``n`` requests, highest priority first (FIFO within a
        tier — queue position doubles as the submit sequence).  Caller
        holds ``_cv``."""
        order = sorted(range(len(self._queue)),
                       key=lambda i: (-self._queue[i].priority, i))[:n]
        picked = set(order)
        batch = [self._queue[i] for i in order]
        survivors = deque(r for i, r in enumerate(self._queue)
                          if i not in picked)
        self._queue.clear()
        self._queue.extend(survivors)
        return batch

    def _run_batch(self, batch: List[VerifyRequest]) -> None:
        registry = obs.registry()
        batch_traces = sorted({r.trace.trace_id for r in batch
                               if r.trace is not None})
        with obs.span("serve.batch", requests=len(batch),
                      trace_ids=batch_traces):
            registry.histogram("serve_batch_size").observe(len(batch))
            stage0_by_id = {}
            if self.cfg.n_shards is None and len(batch) >= 2:
                try:
                    pipe = self._batch_pipe(batch[0].cfg)
                    stage0_by_id = batcher.batched_stage0(
                        batch, pipe=pipe, pad_models=self.cfg.max_batch,
                        grid_fn=self._grid)
                except BaseException as exc:
                    # Losing the coalesced pass costs throughput, never
                    # correctness: every request falls back to its solo
                    # stage 0.  (Chunk-level faults inside the shared
                    # launches are already degraded per chunk by the
                    # pipeline's supervisor and never raise to here.
                    # ReplicaKilled is propagate-class by taxonomy.)
                    if classify(exc) == "propagate":
                        raise
                    obs.event("degraded", site="serve.batch",
                              error=type(exc).__name__,
                              detail=str(exc)[:200])
                    stage0_by_id = {}
            for req in batch:
                with self._cv:
                    if self._killed:
                        raise ReplicaKilled()
                self._run_request(req, stage0_by_id.get(req.id))

    def _batch_pipe(self, cfg):
        from fairify_tpu.parallel.pipeline import LaunchPipeline

        sup = Supervisor(max_retries=cfg.max_launch_retries,
                         backoff_s=cfg.launch_backoff_s,
                         deadline_s=cfg.chunk_deadline_s, seed=cfg.seed)
        return LaunchPipeline(cfg.pipeline_depth, supervisor=sup)

    # --- request execution ------------------------------------------------

    def _run_request(self, req: VerifyRequest, stage0) -> None:
        registry = obs.registry()
        # Integrity attribution: any growth of the (process-global)
        # integrity_violations counter across this request's execution
        # marks the replica suspect.  Thread-fleet replicas share the
        # registry, so a concurrent violation can over-mark — acceptable:
        # suspicion errs toward quarantine, never toward trust.
        iv0 = registry.counter("integrity_violations").total()
        try:
            self._run_request_inner(req, stage0)
        finally:
            if registry.counter("integrity_violations").total() > iv0:
                with self._cv:
                    self._suspect = True
                registry.counter("replica_suspect").inc()
                obs.event("replica_suspect", request=req.id,
                          model=req.model_name)

    def _run_request_inner(self, req: VerifyRequest, stage0) -> None:
        registry = obs.registry()
        req.started_at = time.monotonic()
        registry.histogram("serve_queue_wait_s").observe(req.queue_wait_s)
        with trace_mod.context(req.trace), \
                obs.span("serve.request", request=req.id,
                         model=req.model_name, preset=req.cfg.name) as sp:
            try:
                faults_mod.check("request.deadline")
                left = req.deadline_left()
                if left is not None and left <= 0.0:
                    req.deadline_missed = True
                    registry.counter("serve_deadline_miss").inc(stage="queue")
                    raise AdmissionRejected(
                        f"deadline expired in queue "
                        f"(SLA {req.deadline_s:.2f}s, waited "
                        f"{req.queue_wait_s:.2f}s)")
                req.status = RUNNING
                self._journal(req)
                share = self._fair_share(req)
                if share is not None:
                    left = share if left is None else min(left, share)
                    sp.set(fair_share_s=round(share, 3))
                report = self._execute(req, stage0, left)
            except BaseException as exc:
                # Kills (ReplicaKilled) and interrupts are propagate-class:
                # the worker abandons, fleet failover owns recovery.
                if classify(exc) == "propagate":
                    raise
                req.status = FAILED
                req.reason = req.reason or \
                    f"{type(exc).__name__}: {str(exc)[:200]}"
                req.finished_at = time.monotonic()
                registry.counter("serve_requests").inc(status=FAILED)
                registry.counter("serve_request_failures").inc(
                    error=type(exc).__name__)
                self.admission.release(req)
                sp.set(status=req.status, reason=req.reason)
                self._finish(req)
                return
            if req.status == REQUEUED:
                # Span-granular drain preempted it: _execute_spans already
                # journaled the requeue (and released its backlog share);
                # the rate EMA must not see its partial elapsed time.
                req.finished_at = time.monotonic()
                sp.set(status=req.status)
                return
            if req.status == QUEUED:
                # Preempted mid-flight: _execute_spans re-enqueued it with
                # its partial ledger intact; it keeps its admission
                # backlog share (the remaining work is still committed)
                # and finishes — with resume replay — when it next pops.
                sp.set(status="preempted", preemptions=req.preemptions)
                return
            if getattr(report, "smt_pending", None) is not None \
                    and report.smt_pending.pending:
                # Non-blocking SMT phase: the request stays RUNNING while
                # the pool finishes its host solving on the drainer
                # thread; the worker loop is free for the next request's
                # device launches RIGHT NOW.
                req.report = report
                sp.set(status=req.status,
                       smt_pending=report.smt_pending.pending)
                self._smt_defer(req, report)
                return
            report.smt_pending = None  # empty drain: nothing to wait for
            self._complete(req, report, sp=sp)

    def _complete(self, req: VerifyRequest, report, sp=None) -> None:
        """Terminal DONE bookkeeping — from the worker loop (inline SMT or
        none) or from the drainer thread (deferred SMT finished).  The SLA
        clock includes drain time: ``finished_at`` is stamped HERE."""
        registry = obs.registry()
        req.finished_at = time.monotonic()
        req.report = report
        req.partitions = report.partitions_total
        req.status = DONE
        left = req.deadline_left(req.finished_at)
        if left is not None and left < 0.0 and not req.deadline_missed:
            # not already counted by a span-granular deadline break
            req.deadline_missed = True
            registry.counter("serve_deadline_miss").inc(stage="run")
        registry.counter("serve_requests").inc(status=DONE)
        fun = getattr(report, "funnel", None)
        if fun:
            # One funnel event per REQUEST (DESIGN.md §20) — the request-
            # granular sibling of the sweep's per-model-run event, keyed by
            # the request id so report consumers can tell the two apart.
            obs.event("funnel", request=req.id, model=req.model_name, **fun)
        self.admission.finished(req, partitions=req.partitions,
                                elapsed_s=req.run_s)
        if sp is not None:
            sp.set(status=req.status,
                   queue_wait_s=round(req.queue_wait_s, 4),
                   deadline_missed=req.deadline_missed)
        self._finish(req)

    def _execute(self, req: VerifyRequest, stage0, deadline_left):
        """One request's sweep: whole-span, span-granular, or sharded."""
        from fairify_tpu.verify import sweep as sweep_mod

        cfg = req.cfg
        if self.cfg.xprof_dir and not cfg.profile_dir:
            # --xprof-dir: the sweep wraps its device phases in
            # jax.profiler.trace(profile_dir); a request carrying its own
            # profile_dir keeps it.
            cfg = cfg.with_(profile_dir=self.cfg.xprof_dir)
        if deadline_left is not None:
            # The SLA bounds refinement spend the same way the hard budget
            # does; the sweep's own budget honesty enforces it per phase.
            cfg = cfg.with_(hard_timeout_s=min(cfg.hard_timeout_s,
                                               deadline_left))
        if self.cfg.n_shards is not None:
            from fairify_tpu.parallel import shards as shards_mod

            return shards_mod.sweep_sharded(
                req.net, cfg, model_name=req.model_name, dataset=req.dataset,
                n_shards=self.cfg.n_shards, resume=True,
                partition_span=req.partition_span)
        pool = self._smt_pool_get(cfg)
        if self.cfg.span_chunks <= 0:
            return sweep_mod.verify_model(
                req.net, cfg, model_name=req.model_name, dataset=req.dataset,
                resume=True, stage0=stage0,
                partition_span=req.partition_span,
                smt_pool=pool, smt_defer=pool is not None)
        return self._execute_spans(req, cfg, stage0, sweep_mod)

    def _execute_spans(self, req: VerifyRequest, cfg, stage0, sweep_mod):
        """Span-granular refinement: yield points for drain + deadline.

        Sub-spans are chunk-aligned so every RNG stream keeps its global
        key; all sub-runs share ONE sink (the request's full span), so the
        ledger is a single resumable file whatever the granule.
        """
        full = req.partition_span
        if full is None:
            full = (0, self._span_size(cfg, None))
        start, stop = int(full[0]), int(full[1])
        sink = f"{req.model_name}@{start}-{stop}"
        granule = max(1, self.cfg.span_chunks) * max(cfg.grid_chunk, 1)
        outcomes = []
        reports = []
        attempted = 0
        for s in range(start, stop, granule):
            self._touch_lease()
            with self._cv:
                draining = self._draining
                self._last_beat = time.monotonic()
                if self._killed:
                    raise ReplicaKilled()
            if draining:
                req.status = REQUEUED
                req.reason = f"drained mid-request at partition {s}"
                self._requeue(req)
                break
            if s > start:  # progress guarantee: ≥1 granule per dispatch
                why = self._should_preempt(req, s)
                if why is not None:
                    self._preempt(req, why)
                    return None
            faults_mod.check("request.deadline")
            left = req.deadline_left()
            if left is not None and left <= 0.0:
                req.deadline_missed = True
                obs.registry().counter("serve_deadline_miss").inc(stage="run")
                req.reason = (f"deadline hit at partition {s} "
                              f"({s - start}/{stop - start} attempted)")
                # Fail, don't report partial coverage as ``done``: the
                # unattempted tail has NO ledger records (unlike the
                # whole-span path, whose clamped budget at least ledgers
                # UNKNOWNs), and §13's contract is expired-SLA → fails
                # fast.  The partial ledger stays for resume.
                raise AdmissionRejected(req.reason)
            e = min(s + granule, stop)
            sub_cfg = cfg if left is None else \
                cfg.with_(hard_timeout_s=min(cfg.hard_timeout_s, left))
            rep = sweep_mod.verify_model(
                req.net, sub_cfg, model_name=req.model_name,
                dataset=req.dataset, resume=True,
                stage0=(None if stage0 is None else
                        batcher.slice_stage0(stage0, s - start, e - start)),
                partition_span=(s, e), sink_name=sink,
                # Shared pool, but BLOCKING per sub-span: a granule must
                # be fully ledgered before the next drain/deadline check
                # (the span-preemption contract) — fan-out inside the
                # granule still parallelizes its own queries.
                smt_pool=self._smt_pool_get(sub_cfg))
            reports.append(rep)
            outcomes.extend(rep.outcomes)
            attempted += e - s
        return sweep_mod.ModelReport(
            model=req.model_name, dataset=cfg.dataset, outcomes=outcomes,
            original_acc=next((r.original_acc for r in reports
                               if r.original_acc), 0.0),
            total_time_s=sum(r.total_time_s for r in reports),
            # Attempted, not span width: a deadline break leaves the tail
            # unattempted with no ledger records, and this count feeds the
            # admission rate EMA — inflating it would cascade into
            # admitting infeasible deadlines.
            partitions_total=attempted, sink_name=sink,
            ledger_skipped_lines=sum(r.ledger_skipped_lines for r in reports),
            degraded=sum(r.degraded for r in reports),
            funnel=funnel_mod.merge_payloads(r.funnel for r in reports),
        )

    def _fair_share(self, req: VerifyRequest) -> Optional[float]:
        """Fair-share hard-budget clamp for one dispatch (None = no clamp).

        Applies only under contention (other requests queued or sharing
        the popped batch) — an idle server still lets a request spend its
        whole SLA on optional refinement."""
        if self.cfg.fair_share_factor <= 0:
            return None
        if self.cfg.fair_share_idle_exempt:
            with self._cv:
                contended = bool(self._queue) or self._inflight > 1
            if not contended:
                return None
        est = self.admission.estimate_s(req.partitions)
        if est is None:
            return None
        return max(self.cfg.fair_share_factor * est,
                   self.cfg.fair_share_min_s)

    # --- preemption (DESIGN.md §15) ---------------------------------------

    def _should_preempt(self, req: VerifyRequest, at_partition: int
                        ) -> Optional[str]:
        """Preemption decision at one span-granule boundary.

        Preempt when strictly-higher-priority work waits AND the running
        request is over budget — it has spent more than ``preempt_factor
        ×`` its admission estimate, or it is best-effort (no deadline: by
        definition over any budget once SLA work is waiting).  Bounded by
        ``max_preemptions`` so a hard request cannot starve.

        ``request.preempt`` is the chaos site: an injected (non-crash)
        fault FORCES the preemption, so the requeue/resume machinery is
        testable without manufacturing real overload.
        """
        try:
            faults_mod.check("request.preempt")
        except BaseException as exc:
            if classify(exc) == "propagate":
                raise
            return (f"preempted at partition {at_partition} "
                    f"(injected: {exc})")
        if self.cfg.preempt_factor <= 0 or self.cfg.span_chunks <= 0:
            return None
        if req.preemptions >= self.cfg.max_preemptions:
            return None
        with self._cv:
            waiter = any(q.priority > req.priority for q in self._queue)
        if not waiter:
            return None
        est = self.admission.estimate_s(req.partitions)
        over_budget = (req.deadline_s is None
                       or (est is not None
                           and req.run_s > self.cfg.preempt_factor * est))
        if not over_budget:
            return None
        return (f"preempted at partition {at_partition}: over budget "
                f"(ran {req.run_s:.2f}s vs estimate "
                f"{0.0 if est is None else est:.2f}s, "
                f"priority {req.priority}) with higher-priority waiter")

    def _preempt(self, req: VerifyRequest, why: str) -> None:
        """RUNNING → QUEUED at a granule boundary: the span-granular
        requeue fired mid-flight instead of at SIGTERM.  The partial
        ledger stays; the next dispatch replays it ``resume=True``.  The
        admission backlog share is kept — the remaining work is still
        committed."""
        req.preemptions += 1
        req.status = QUEUED
        req.reason = why
        registry = obs.registry()
        registry.counter("serve_preemptions").inc(priority=req.priority)
        self._journal(req)
        with self._cv:
            draining = self._draining
            if not draining:
                self._queue.append(req)
                registry.gauge("serve_queue_depth").set(len(self._queue))
                self._cv.notify_all()
        if draining:
            # Drain snapped between the granule's drain check and here:
            # hand it to the drain path so it isn't stranded in a queue
            # nobody will pop.
            req.status = REQUEUED
            req.reason = f"{why}; server draining"
            self._requeue(req)

    # --- sinks ------------------------------------------------------------

    def _journal(self, req: VerifyRequest) -> None:
        self._journal_record(req.to_record())

    def _journal_record(self, rec: dict) -> None:
        if self._journal_writer is not None:
            self._journal_writer.append({"ts": round(time.time(), 3), **rec})
        obs.event("request", **rec)
        if self._transition_fn is not None:
            # Cross-process visibility (serve.procfleet): the replica
            # forwards every lifecycle transition over its control pipe so
            # the router's request table tracks pickups and terminals.
            self._transition_fn(rec)

    def _finish(self, req: VerifyRequest) -> None:
        """Terminal bookkeeping: journal + client-visible status.json."""
        self._journal(req)
        if os.path.isdir(req.cfg.result_dir):
            _atomic_json(os.path.join(req.cfg.result_dir, "status.json"),
                         req.to_record())
        with self._cv:
            self._cv.notify_all()   # wake wait()ers on the terminal state

    # --- spool inbox ------------------------------------------------------

    def _scan_inbox(self) -> None:
        inbox = os.path.join(self.cfg.spool, "inbox")
        try:
            names = sorted(os.listdir(inbox))
        except OSError:
            return
        for name in names:
            with self._cv:
                if self._draining:
                    # Leave the rest of the inbox untouched for the next
                    # server (submit() requeues any file already in
                    # flight, so nothing is lost either way).
                    return
            if not name.endswith(".json"):
                continue
            path = os.path.join(inbox, name)
            try:
                with open(path) as fp:
                    payload = json.load(fp)
            except OSError:
                continue  # consumed by a racing server, or an fs flake
            except json.JSONDecodeError as exc:
                # The client commit is rename-atomic, so a visible .json
                # is complete: this is permanent corruption, not a
                # mid-write.  Quarantine it (never re-parse every poll)
                # and reject terminally so the client unblocks.
                self._quarantine(path, name, exc)
                continue
            try:
                os.remove(path)
            except OSError:
                continue  # a racing server consumed it first
            try:
                self._submit_payload(payload)
            except BaseException as exc:
                if classify(exc) == "propagate":
                    raise
                obs.event("degraded", site="serve.inbox", file=name,
                          error=type(exc).__name__, detail=str(exc)[:200])

    def _quarantine(self, path: str, name: str, exc: Exception) -> None:
        """Move a corrupt inbox payload aside and reject it terminally."""
        try:
            os.replace(path, f"{path}.corrupt")
        except OSError:
            return  # a racing server got to it first
        obs.event("degraded", site="serve.inbox", file=name,
                  error=type(exc).__name__, detail=str(exc)[:200])
        rid = name[:-len(".json")]
        rec = {"request": rid, "status": REJECTED, "model": "?",
               "preset": "?",
               "reason": f"corrupt payload (quarantined to {name}.corrupt): "
                         f"{str(exc)[:200]}"}
        obs.registry().counter("serve_requests").inc(status=REJECTED)
        self._journal_record(rec)
        rdir = os.path.join(self._requests_root(), rid)
        os.makedirs(rdir, exist_ok=True)
        _atomic_json(os.path.join(rdir, "status.json"), rec)

    def _submit_payload(self, payload: dict) -> Optional[VerifyRequest]:
        from fairify_tpu.serve.client import resolve_payload

        req_id = payload.get("id") or new_request_id()
        payload = dict(payload, id=req_id)
        rdir = os.path.join(self._requests_root(), req_id)
        os.makedirs(rdir, exist_ok=True)
        _atomic_json(os.path.join(rdir, "request.json"), payload)
        try:
            cfg, net, model_name, dataset = resolve_payload(payload, rdir)
            deadline = payload.get("deadline_s", self.cfg.default_deadline_s)
            span = payload.get("span")
            ts = payload.get("submitted_ts")
            prio = parse_priority(payload.get("priority", PRIORITY_NORMAL))
            return self.submit(
                cfg, net, model_name, dataset=dataset,
                deadline_s=None if deadline is None else float(deadline),
                partition_span=None if span is None else (int(span[0]),
                                                          int(span[1])),
                request_id=req_id, spool_payload=payload,
                submitted_at=None if ts is None
                else monotonic_from_epoch(float(ts)),
                priority=prio)
        except BaseException as exc:
            if classify(exc) == "propagate":
                raise
            # An unprocessable payload — unresolvable (unknown
            # preset/model, mismatched net) or one whose overrides blow
            # up grid construction before it queues — is a terminal
            # rejection: the inbox file is already consumed, so the
            # waiting client needs a status.json and the journal needs
            # the transition.  (submit() reports admission refusals by
            # return value; anything raising through it never queued.)
            rec = {"request": req_id, "status": REJECTED,
                   "model": payload.get("model", "?"),
                   "preset": payload.get("preset", "?"),
                   "reason": f"{type(exc).__name__}: {str(exc)[:200]}"}
            obs.registry().counter("serve_requests").inc(status=REJECTED)
            self._journal_record(rec)
            _atomic_json(os.path.join(rdir, "status.json"), rec)
            return None



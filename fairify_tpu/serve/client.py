"""File-spool client protocol: how ``fairify_tpu submit`` talks to ``serve``.

The transport is deliberately a directory, not a socket: the server's
spool is the one durable thing a drain already preserves, atomic rename is
the only concurrency primitive both sides need, and a file-based inbox
makes ``resume=True`` pickup of requeued requests free (a drain just
writes the payload back).  Layout under ``--spool``::

    inbox/<id>.json                 submitted payloads (rename-atomic)
    requests/<id>/request.json      the accepted payload (server copy)
    requests/<id>/status.json       terminal lifecycle record
    requests/<id>/*.ledger.jsonl    the streaming verdict ledger (tail it)
    serve.journal.jsonl             every lifecycle transition, JSONL

A **payload** is JSON with:

``preset``       required preset name (``fairify_tpu list``)
``model``        zoo model name (e.g. ``GC-1``), or
``init``         ``{"sizes": [...], "seed": N}`` synthetic net
                 (bench/chaos harnesses; exactly one of model/init)
``overrides``    ``SweepConfig.with_`` keyword overrides (timeouts,
                 grid_chunk, pipeline_depth, inject_faults, ...)
``deadline_s``   wall-clock SLA from submit; absent = server default
``priority``     scheduling tier: ``low`` | ``normal`` | ``high`` (or
                 0/1/2) — higher tiers pop first, shed last, and may
                 preempt a running lower tier; absent = ``normal``
``span``         ``[start, stop)`` global partition indices; absent = all
``model_root``   zoo root override (defaults to the server's environment)
``id``           optional caller-chosen request id
``submitted_ts`` epoch submit time, stamped by :func:`submit`; the SLA
                 clock is measured from here so it survives drain/requeue
                 handoffs between servers
``trace``        ``{"id": <hex>, "span": <sender span id>}`` — the
                 distributed-trace context, stamped by :func:`submit` and
                 preserved verbatim across routing/requeue/re-home hops
                 (DESIGN.md §19); every span the request's life produces,
                 in any process, records this id
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional, Tuple


def build_payload(preset: str, model: Optional[str] = None,
                  init: Optional[dict] = None,
                  overrides: Optional[dict] = None,
                  deadline_s: Optional[float] = None,
                  span: Optional[Tuple[int, int]] = None,
                  model_root: Optional[str] = None,
                  request_id: Optional[str] = None,
                  priority: Optional[object] = None) -> dict:
    """Validated payload dict (the submit-side half of the protocol)."""
    from fairify_tpu.serve.request import parse_priority

    if (model is None) == (init is None):
        raise ValueError("exactly one of model= / init= is required")
    payload = {"preset": preset}
    if priority is not None:
        payload["priority"] = parse_priority(priority)
    if model is not None:
        payload["model"] = model
    if init is not None:
        sizes = [int(s) for s in init["sizes"]]
        if len(sizes) < 2:
            raise ValueError("init.sizes needs at least [in_dim, out]")
        payload["init"] = {"sizes": sizes, "seed": int(init.get("seed", 0))}
    if overrides:
        payload["overrides"] = dict(overrides)
    if deadline_s is not None:
        payload["deadline_s"] = float(deadline_s)
    if span is not None:
        payload["span"] = [int(span[0]), int(span[1])]
    if model_root is not None:
        payload["model_root"] = model_root
    if request_id is not None:
        payload["id"] = request_id
    return payload


def resolve_payload(payload: dict, result_dir: str):
    """Server-side payload → ``(cfg, net, model_name, dataset)``.

    ``result_dir`` becomes the request's private sink directory (the
    per-request isolation boundary); the payload's own ``result_dir``
    override is ignored — a client must not write outside its request
    directory.
    """
    from fairify_tpu.verify import presets

    cfg = presets.get(payload["preset"])
    overrides = dict(payload.get("overrides") or {})
    overrides["result_dir"] = result_dir
    cfg = cfg.with_(**overrides)
    if "init" in payload:
        from fairify_tpu.models.train import init_mlp

        init = payload["init"]
        net = init_mlp(tuple(init["sizes"]), seed=int(init.get("seed", 0)))
        model_name = payload.get(
            "model", f"init{'x'.join(str(s) for s in init['sizes'])}"
            f"-s{init.get('seed', 0)}")
    else:
        from fairify_tpu.models import zoo

        model_name = payload["model"]
        net = zoo.load(cfg.dataset, model_name,
                       root=payload.get("model_root"))
    # Same gate run_sweep applies to zoo models: a net whose input width
    # doesn't match the verification domain would fatally degrade every
    # launch — reject it here, before it costs device time.
    n_attrs = len(cfg.query().columns)
    if net.in_dim != n_attrs:
        raise ValueError(
            f"{model_name}: input dim {net.in_dim} != domain dim {n_attrs} "
            f"of preset {payload['preset']!r}")
    return cfg, net, model_name, None


def write_atomic_json(path: str, obj: dict) -> None:
    """Write-then-rename so readers never observe a torn file.

    The one atomic primitive both halves of the spool protocol share —
    inbox payloads, status.json, drain requeues all go through it."""
    tmp = f"{path}.tmp"
    with open(tmp, "w") as fp:
        json.dump(obj, fp)
    os.replace(tmp, path)


def submit(spool: str, payload: dict) -> str:
    """Drop a payload into the server's inbox; returns the request id.

    Stamps the epoch submit time (``submitted_ts``) so the request's SLA
    clock survives a drain/requeue handoff — the next server restores it
    instead of restarting the deadline from pickup, and the trace context
    (``trace``) that every downstream process binds its spans to.  Both
    use ``setdefault``: a requeued payload keeps its original identity."""
    from fairify_tpu.obs import trace as trace_mod
    from fairify_tpu.serve.request import new_request_id

    req_id = payload.get("id") or new_request_id()
    payload = dict(payload, id=req_id)
    payload.setdefault("submitted_ts", time.time())
    ctx_fields = trace_mod.context_fields()
    payload.setdefault(
        "trace", ctx_fields.get("trace") or {"id": trace_mod.new_trace_id()})
    inbox = os.path.join(spool, "inbox")
    os.makedirs(inbox, exist_ok=True)
    write_atomic_json(os.path.join(inbox, f"{req_id}.json"), payload)
    return req_id


def status(spool: str, request_id: str) -> Optional[dict]:
    """Terminal lifecycle record, or None while the request is in flight."""
    path = os.path.join(spool, "requests", request_id, "status.json")
    try:
        with open(path) as fp:
            return json.load(fp)
    except (OSError, json.JSONDecodeError):
        return None


def wait(spool: str, request_id: str, timeout: Optional[float] = None,
         poll_s: float = 0.2) -> Optional[dict]:
    """Poll until the request's status.json lands (or timeout)."""
    t0 = time.monotonic()
    while True:
        rec = status(spool, request_id)
        if rec is not None:
            return rec
        if timeout is not None and time.monotonic() - t0 > timeout:
            return None
        time.sleep(poll_s)


def ledger_paths(spool: str, request_id: str) -> list:
    """The request's streaming verdict ledgers (tail these for results)."""
    rdir = os.path.join(spool, "requests", request_id)
    try:
        names = sorted(os.listdir(rdir))
    except OSError:
        return []
    return [os.path.join(rdir, n) for n in names
            if n.endswith(".ledger.jsonl")]

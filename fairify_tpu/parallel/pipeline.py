"""Bounded async launch pipeline: overlap device dispatch with host decode.

Every kernel launch on the tunnelled single-chip setup costs ~110 ms flat
regardless of batch size (``audits/device_util_r4.json``), so the sweep's
throughput currency is launch round-trips.  The chunked stage-0 loops used
to fetch each chunk synchronously (``np.asarray(cert)`` straight after the
fused launch) before dispatching the next chunk — the device sat idle for
the whole host decode (flip extraction, exact ``validate_pair``, ledger
writes) of every chunk.

JAX dispatch is natively asynchronous: a jitted call returns device arrays
immediately and only blocks when the host *reads* them.  This module turns
that into a disciplined structure instead of an accident:

* :class:`LaunchPipeline` — a bounded in-flight queue.  ``submit(fn)``
  first drains the oldest entries until at most ``depth - 1`` launches
  remain in flight, then calls ``fn()`` (which dispatches the launch and
  returns its device arrays), so at ``depth`` the queue keeps the device
  fed while the host consumes results.  ``depth=1`` restores the
  synchronous fetch order — launch N's device arrays are pulled before
  launch N+1 dispatches (only the pure-host decode of already-fetched
  results still runs after the dispatch).
* The **only** host↔device sync point is the dequeue-time
  :func:`jax.device_get` inside the drain — call sites never
  ``np.asarray`` device arrays in their chunk loops (enforced by the
  ``obs-loop-fetch`` lint rule).
* :class:`FlightStats` — max and time-weighted mean launches in flight,
  recorded per pipeline and mirrored into the obs ``launches_in_flight``
  gauge (labels ``stat="max"`` / ``stat="mean"``) so every sweep's
  ``*.throughput.json`` and ``--trace-out`` log carry the overlap actually
  achieved.

Verdict-map invariance: the pipeline changes only *when* results are
fetched, never which kernels run or with which seeds (chunk RNG streams
are keyed to global chunk starts) — decided/UNSAT/SAT sets are bit-equal
at every depth (``tests/test_pipeline.py``).

Fault tolerance (``resilience/``): dispatch and dequeue are the named
fault sites ``launch.submit`` / ``launch.decode``.  With a
:class:`resilience.supervisor.Supervisor` attached, a transient error at
either site is retried (a failed decode re-dispatches its ``fn`` — submit
fns are idempotent: their RNG streams are keyed, not shared); exhaustion
or a fatal error yields the chunk as ``(meta, ctx, ChunkFailure)`` instead
of a host payload, the queue stays primed, and later chunks are
unaffected.  Consumers check ``isinstance(host, ChunkFailure)`` and
degrade exactly that chunk's partitions.  Without a supervisor (the
default) errors propagate unchanged.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Iterator, List, Optional, Tuple


class FlightStats:
    """In-flight launch accounting: current, max, and time-weighted mean.

    ``update(n)`` is called on every queue-depth transition; the mean is the
    integral of depth over time divided by elapsed time since the first
    transition, i.e. the average number of launches the device had queued
    while the pipeline was live.
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter):
        self._clock = clock
        self.max = 0
        self._cur = 0
        self._t0: Optional[float] = None
        self._t_last: Optional[float] = None
        self._area = 0.0

    def update(self, n: int) -> None:
        now = self._clock()
        if self._t0 is None:
            self._t0 = now
        else:
            self._area += self._cur * (now - self._t_last)
        self._t_last = now
        self._cur = n
        if n > self.max:
            self.max = n

    def mean(self) -> float:
        if self._t0 is None or self._t_last == self._t0:
            return float(self._cur)
        return self._area / (self._t_last - self._t0)

    def summary(self) -> dict:
        return {"max": int(self.max), "mean": round(self.mean(), 3)}


class LaunchPipeline:
    """Bounded in-flight queue over JAX's async dispatch.

    ``submit(fn, meta)`` expects ``fn() -> (payload, ctx)`` where ``payload``
    is a pytree of device arrays the launch produced (dispatch happens
    inside ``fn``) and ``ctx`` is opaque host-side context the decode step
    needs (never device-transferred).  It returns the list of entries that
    had to be drained to make room — each as ``(meta, ctx, host_payload)``
    with ``host_payload = jax.device_get(payload)``.  ``drain()`` flushes
    the remainder in submission order.

    One pipeline instance can serve several phases of a run back-to-back
    (stage-0 certify, parity, PGD): its lifetime :class:`FlightStats` then
    describe the whole run, which is what lands in ``*.throughput.json``.
    """

    def __init__(self, depth: int = 2, stats: Optional[FlightStats] = None,
                 gauge: bool = True, supervisor=None,
                 fault_sites: bool = True):
        self.depth = max(1, int(depth))
        self.stats = stats if stats is not None else FlightStats()
        # ``gauge=False`` for engine-internal micro-pipelines (e.g. a
        # single-root Phase A): the ``launches_in_flight`` gauge's mean is
        # last-write-wins per run, and a one-launch pipeline would
        # overwrite the run pipeline's overlap record with ~0.
        self._gauge = gauge
        # ``fault_sites=False`` for pipelines whose whole phase is already
        # supervised as ONE unit at the call site (the prune pass runs
        # under ``sup.run(site="prune")``): their launches must not consume
        # ``launch.submit``/``launch.decode`` arrivals, or every existing
        # chaos schedule (arrival-count based, see resilience/faults.py)
        # would shift when an internal phase changes its launch structure.
        self._fault_sites = fault_sites
        self.supervisor = supervisor
        self._q: deque = deque()
        self.stats.update(0)

    def __len__(self) -> int:
        return len(self._q)

    def submit(self, fn: Callable[[], Tuple[Any, Any]],
               meta: Any = None) -> List[Tuple[Any, Any, Any]]:
        ready = []
        while len(self._q) >= self.depth:
            ready.append(self._drain_one())
        self._q.append(self._dispatch(fn, meta))
        self.stats.update(len(self._q))
        return ready

    def _dispatch(self, fn, meta) -> Tuple[Any, Any, Any, Any, Any]:
        """One supervised dispatch → queue entry
        ``(meta, ctx, payload, fn, trace_ctx)``.

        A degraded dispatch enqueues the :class:`ChunkFailure` as the
        payload so FIFO order (and the consumer's span bookkeeping) is
        preserved — the failure surfaces at this chunk's drain slot.
        ``trace_ctx`` is the submit-time trace context (obs.trace),
        re-bound at drain so the sync-point span attributes to the request
        whose launch it waits on, not whichever request happens to be
        running when the queue finally drains.
        """
        from fairify_tpu.obs import trace as trace_mod
        from fairify_tpu.resilience import faults
        from fairify_tpu.resilience.supervisor import ChunkDegraded

        tctx = trace_mod.current_context()

        def attempt():
            if self._fault_sites:
                faults.check("launch.submit")
            return fn()

        if self.supervisor is None:
            payload, ctx = attempt()
            return meta, ctx, payload, fn, tctx
        try:
            payload, ctx = self.supervisor.run(attempt, site="launch.submit")
        except ChunkDegraded as exc:
            return meta, None, exc.failure, None, tctx
        return meta, ctx, payload, fn, tctx

    def drain(self) -> Iterator[Tuple[Any, Any, Any]]:
        while self._q:
            yield self._drain_one()

    def _drain_one(self) -> Tuple[Any, Any, Any]:
        import jax

        from fairify_tpu import obs
        from fairify_tpu.obs import trace as trace_mod
        from fairify_tpu.resilience import faults
        from fairify_tpu.resilience.supervisor import ChunkDegraded, ChunkFailure

        meta, ctx, payload, fn, tctx = self._q.popleft()
        if isinstance(payload, ChunkFailure):  # degraded at dispatch
            self.stats.update(len(self._q))
            self._record_gauge()
            return meta, ctx, payload

        state = {"payload": payload}

        def fetch():
            if self._fault_sites:
                faults.check("launch.decode")
            return jax.device_get(state["payload"])

        def redispatch():
            # A failed decode may have poisoned the device arrays (e.g. a
            # donated-buffer error): re-run the launch for a fresh payload.
            # Submit fns are idempotent (per-chunk keyed RNG), so the
            # replayed kernel is bit-identical.
            if fn is not None:
                state["payload"], _ = fn()

        # The pipeline's single sanctioned sync point: visible as its own
        # span so Perfetto traces show the drain-wait lane against the
        # in-flight device lanes (short waits = real overlap).
        with trace_mod.context(tctx), \
                obs.span("pipeline.drain", in_flight=len(self._q) + 1,
                         depth=self.depth):
            if self.supervisor is None:
                host = fetch()
            else:
                try:
                    host = self.supervisor.run(fetch, site="launch.decode",
                                               on_retry=redispatch)
                except ChunkDegraded as exc:
                    host = exc.failure
        if self._fault_sites and isinstance(host, dict):
            # Data-plane chaos: an armed launch.decode:corrupt spec flips
            # one bit in the fetched payload (no error raised) — only the
            # consumer's integrity layer (canary + fold checksum,
            # resilience/integrity.py) may notice.  Separate arrival
            # stream from faults.check above, so corrupt schedules never
            # shift the control-plane ones.
            n = faults.corruption("launch.decode")
            if n is not None:
                from fairify_tpu.resilience import integrity

                host = integrity.corrupt_host(host, n)
        self.stats.update(len(self._q))
        self._record_gauge()
        return meta, ctx, host

    def _record_gauge(self) -> None:
        if not self._gauge:
            return
        from fairify_tpu import obs

        g = obs.registry().gauge("launches_in_flight")
        prev = g.value(stat="max")
        if prev is None or self.stats.max > prev:
            g.set(self.stats.max, stat="max")
        g.set(round(self.stats.mean(), 3), stat="mean")

"""Multi-host (DCN) scaling of the partition sweep.

The reference has no distributed runtime (SURVEY.md §0, §5.8) — its cluster
story is provisioning notebooks that run independent processes.  The rebuild
treats multi-host as a first-class axis:

* **Inside a host/pod**: the ``(parts, models)`` mesh of
  :mod:`fairify_tpu.parallel.mesh`; XLA collectives ride ICI.
* **Across hosts**: `jax.distributed` + a global mesh; each process feeds
  its addressable shard of the partition grid, and per-partition verdict
  summaries are combined with a device all-gather over DCN (below), while
  the JSONL ledger (one per host) provides the crash-resume story.

With one process this degrades to the single-host path, so everything here
is exercised in CI; the multi-process path follows jax's standard
initialize() contract.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join (or no-op into) a jax distributed runtime.

    Call once per process before device use; with no arguments jax reads the
    standard cluster env vars. Single-process callers may skip entirely.
    """
    import jax

    if num_processes is not None and num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def host_slice(n_partitions: int, process_index: Optional[int] = None,
               process_count: Optional[int] = None) -> Tuple[int, int]:
    """Contiguous [start, stop) slice of the partition grid owned by this host.

    Deterministic balanced split so any host can recompute every other
    host's assignment (needed to merge ledgers after a crash).
    """
    import jax

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    base, rem = divmod(n_partitions, pc)
    start = pi * base + min(pi, rem)
    stop = start + base + (1 if pi < rem else 0)
    return start, stop


def allgather_verdicts(local_codes: np.ndarray, mesh=None) -> np.ndarray:
    """All-gather per-partition verdict codes across the mesh (DCN/ICI).

    ``local_codes``: int8 array (local_P,) with 0=unknown, 1=sat, 2=unsat.
    Returns the concatenated global array on every host.  Uses
    `jax.experimental.multihost_utils` when running multi-process; identity
    on one process.
    """
    import jax

    if jax.process_count() == 1:
        return np.asarray(local_codes)
    from jax.experimental import multihost_utils

    return np.asarray(
        multihost_utils.process_allgather(np.asarray(local_codes), tiled=True)
    )

"""Multi-host (DCN) scaling of the partition sweep.

The reference has no distributed runtime (SURVEY.md §0, §5.8) — its cluster
story is provisioning notebooks that run independent processes.  The rebuild
treats multi-host as a first-class axis:

* **Inside a host/pod**: the ``(parts, models)`` mesh of
  :mod:`fairify_tpu.parallel.mesh`; XLA collectives ride ICI.
* **Across hosts**: `jax.distributed` + a global mesh; each process feeds
  its addressable shard of the partition grid, and per-partition verdict
  summaries are combined with a device all-gather over DCN (below), while
  the JSONL ledger (one per host) provides the crash-resume story.

With one process this degrades to the single-host path, so everything here
is exercised in CI; the multi-process path follows jax's standard
initialize() contract.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join (or no-op into) a jax distributed runtime.

    Call once per process before device use; with no arguments jax reads the
    standard cluster env vars. Single-process callers may skip entirely.
    """
    import jax

    if num_processes is not None and num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def host_slice(n_partitions: int, process_index: Optional[int] = None,
               process_count: Optional[int] = None) -> Tuple[int, int]:
    """Contiguous [start, stop) slice of the partition grid owned by this host.

    Deterministic balanced split so any host can recompute every other
    host's assignment (needed to merge ledgers after a crash).
    """
    import jax

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    base, rem = divmod(n_partitions, pc)
    start = pi * base + min(pi, rem)
    stop = start + base + (1 if pi < rem else 0)
    return start, stop


def allgather_verdicts(local_codes: np.ndarray, mesh=None) -> np.ndarray:
    """All-gather per-partition verdict codes across the mesh (DCN/ICI).

    ``local_codes``: int8 array (local_P,) with 0=unknown, 1=sat, 2=unsat.
    Returns the concatenated global array on every host.  Uses
    `jax.experimental.multihost_utils` when running multi-process; identity
    on one process.

    ``process_allgather(tiled=True)`` requires identical shapes on every
    process, but :func:`host_slice` spans legitimately differ by one row —
    so each host pads its codes to the common ceiling with a -1 sentinel
    and the padding is dropped after the gather.
    """
    import jax

    local_codes = np.asarray(local_codes, dtype=np.int8)
    if jax.process_count() == 1:
        return local_codes
    from jax.experimental import multihost_utils

    pc = jax.process_count()
    # Common padded width: every span is base or base+1 (host_slice), so the
    # max across hosts is simply the max of the gathered lengths.
    lengths = np.asarray(multihost_utils.process_allgather(
        np.array([local_codes.shape[0]], dtype=np.int32), tiled=True))
    width = int(lengths.max())
    padded = np.full(width, -1, dtype=np.int8)
    padded[: local_codes.shape[0]] = local_codes
    gathered = np.asarray(
        multihost_utils.process_allgather(padded, tiled=True)
    ).reshape(pc, width)
    return np.concatenate([gathered[i, : lengths[i]] for i in range(pc)])


def merge_ledgers(paths) -> dict:
    """Merge per-host JSONL verdict ledgers into one {partition_id: record}.

    Hosts own disjoint partition-id spans (:func:`host_slice`), so a
    collision can only come from re-running with a different host count;
    later files win, matching single-host resume semantics.
    """
    import json
    import os

    from fairify_tpu.verify.sweep import _load_ledger

    merged: dict = {}
    for path in paths:
        merged.update(_load_ledger(path))
    return merged


def sweep_host(net, cfg, model_name: str = "model", dataset=None, mesh=None,
               process_index=None, process_count=None):
    """Run this host's slice of the partition sweep and gather global counts.

    The grid is split contiguously across processes (:func:`host_slice`);
    each host runs the normal single-host sweep on its span.  Partition ids
    and pruning PRNG keys are global, so masks and decided verdicts are
    host-count invariant (attack streams are span-relative — see
    ``verify_model``); sinks are span-qualified (``model@start-stop``) so
    hosts can share ``cfg.result_dir`` on a network filesystem, and the
    per-partition verdict codes are all-gathered over DCN.  Returns
    ``(local_report, global_codes)`` where ``global_codes`` is the int8
    verdict array for the whole grid (0=unknown, 1=sat, 2=unsat) on every
    host.
    """
    import jax
    import numpy as np

    from fairify_tpu.verify import sweep as sweep_mod

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    report = sweep_mod.verify_model(
        net, cfg, model_name=model_name, dataset=dataset, mesh=mesh,
        host_index=pi, host_count=pc)
    code = {"unknown": 0, "sat": 1, "unsat": 2}
    local = np.array([code[o.verdict] for o in report.outcomes], dtype=np.int8)
    return report, allgather_verdicts(local, mesh=mesh)

"""Device-mesh parallelism for the partition sweep (ICI/DCN scaling)."""

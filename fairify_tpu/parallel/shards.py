"""Sharded sweep runtime: per-shard fault domains + elastic re-sharding.

ROADMAP item 2 makes the (family, chunk) grid multi-chip; this module makes
it multi-chip *and degradation-safe*.  A **shard** is a contiguous,
chunk-aligned span of the partition grid bound to a device group; each
shard runs the normal sweep (:func:`verify.sweep.verify_model`) on a
``(parts, models)`` submesh built from exactly its devices, inside its own
:class:`resilience.supervisor.Supervisor` fault domain with three shard-
level fault sites (``shard.dispatch``, ``shard.gather``, ``device.lost``).

Failure semantics (the blast-radius contract, DESIGN.md §12):

* a **transient** shard fault (``device.lost:transient``, a flaky DCN
  gather) is absorbed by the shard supervisor's bounded retry — the retry
  re-runs the shard with ``resume=True`` so already-ledgered verdicts
  replay instead of recomputing;
* a **fatal** / retry-exhausted shard fault quarantines the shard's whole
  device group and **elastically re-shards**: the failed span is re-split
  at grid-chunk boundaries over the surviving device set, meshes are
  rebuilt smaller, and the work re-dispatches — down to a single-chip
  mesh when one device survives;
* with **no survivors** the remaining spans are ledgered UNKNOWN with a
  machine-readable ``failure`` record (``site:kind`` + shard index), so a
  later ``resume=True`` pass re-attempts exactly those partitions.

Verdict determinism: shard boundaries land on multiples of
``cfg.grid_chunk``, and the stage-0 attack RNG streams are keyed to global
chunk starts (:func:`verify.sweep._stage0_certify_and_attack`), so decided
verdicts are shard-count and re-shard invariant; each initial shard span
keeps ONE journal (``<preset>-<model>@<start>-<stop>.ledger.jsonl``) that
every re-dispatch of its partitions appends to, and cross-shard merge is
:func:`verify.sweep.merge_ledgers`' decided-wins semantics.

Shards are dispatched sequentially in-process (the multi-process axis is
:mod:`fairify_tpu.parallel.multihost`); cross-device parallelism comes
from the WIDTH of each shard's mesh — ``n_shards=1`` puts the whole fleet
under one launch (max throughput, coarsest fault domain), ``n_shards=N``
gives single-device shards (finest blast radius, no cross-device launch).
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from fairify_tpu import obs
from fairify_tpu.resilience import faults as faults_mod
from fairify_tpu.resilience.journal import JournalWriter
from fairify_tpu.resilience.supervisor import (
    ChunkDegraded,
    ChunkFailure,
    Supervisor,
)


class DeviceLostError(RuntimeError):
    """A shard's device set is gone (injected ``device.lost:fatal`` or a
    platform 'device lost'): retrying on the same devices cannot help, so
    the shard runtime quarantines them and re-shards onto survivors."""


@dataclass(frozen=True)
class Shard:
    """One dispatch unit: a device group owning a span of the grid."""

    index: int                 # monotone dispatch counter (obs label)
    devices: Tuple             # the group's jax devices
    span: Tuple[int, int]      # [start, stop) global partition indices
    sink_span: Tuple[int, int]  # initial-shard span that names the journal

    @property
    def sink(self) -> str:
        return f"{self.sink_span[0]}-{self.sink_span[1]}"


def shard_spans(start: int, stop: int, n_shards: int,
                align: int = 1) -> List[Tuple[int, int]]:
    """Contiguous balanced spans of ``[start, stop)``, boundaries aligned.

    Interior boundaries land on multiples of ``align`` (the sweep's
    ``grid_chunk``): the stage-0 attack RNG streams are keyed to global
    chunk starts, so aligned spans draw exactly the samples a single-shard
    run would — and a re-split of a failed span cannot move any chunk's
    seed.  ``n_shards`` is capped at the number of whole chunks.
    """
    n = stop - start
    if n <= 0:
        return []
    align = max(1, int(align))
    blocks = -(-n // align)  # ceil: the final block may be ragged
    n_shards = max(1, min(int(n_shards), blocks))
    base, rem = divmod(blocks, n_shards)
    spans = []
    b0 = 0
    for i in range(n_shards):
        nb = base + (1 if i < rem else 0)
        spans.append((start + b0 * align,
                      min(start + (b0 + nb) * align, stop)))
        b0 += nb
    return spans


def device_groups(devices: Sequence, n_groups: int) -> List[Tuple]:
    """Balanced contiguous split of ``devices`` into ``n_groups`` tuples."""
    devices = list(devices)
    n_groups = max(1, min(int(n_groups), len(devices)))
    base, rem = divmod(len(devices), n_groups)
    out = []
    i = 0
    for g in range(n_groups):
        n = base + (1 if g < rem else 0)
        out.append(tuple(devices[i:i + n]))
        i += n
    return out


def _shard_mesh(devices: Tuple):
    """The shard's ``(parts, models)`` submesh over exactly its devices."""
    from fairify_tpu.parallel.mesh import submesh

    return submesh(devices)


def _rewrite_device_lost(failure: ChunkFailure) -> ChunkFailure:
    """Attribute device-loss failures to the ``device.lost`` site.

    The supervisor labels every failure with its ``run(site=...)`` (the
    dispatch site); a loss that fired at the ``device.lost`` fault site —
    or surfaced as :class:`DeviceLostError` — should carry the loss site in
    its ``site:kind`` reason code so report tables bucket it correctly.
    """
    if failure.error == "DeviceLostError" or "device.lost" in failure.detail:
        return ChunkFailure("device.lost", failure.kind, failure.error,
                            failure.detail, failure.retries, failure.shard)
    return failure


def sweep_sharded(
    net,
    cfg,
    model_name: str = "model",
    dataset=None,
    devices: Optional[Sequence] = None,
    n_shards: Optional[int] = None,
    resume: bool = True,
    partition_span: Optional[Tuple[int, int]] = None,
    max_rounds: Optional[int] = None,
):
    """Run one model's sweep sharded over a device fleet; returns the merged
    :class:`verify.sweep.ModelReport`.

    ``n_shards`` fault domains over ``devices`` (default: every visible
    device, one shard per device up to the chunk count).  Each initial
    shard span owns one journal; re-dispatches after a loss append to the
    same journal with ``resume=True``, so no decided verdict is ever
    recomputed and ``resume=True`` on a later call re-attempts exactly the
    partitions no shard ever decided.
    """
    import jax

    from fairify_tpu.verify import sweep as sweep_mod

    devices = list(devices if devices is not None else jax.devices())
    if not devices:
        raise ValueError("sweep_sharded: no devices")
    _, lo, _hi = sweep_mod.build_partitions(cfg)
    span0 = (0, int(lo.shape[0])) if partition_span is None \
        else (int(partition_span[0]), int(partition_span[1]))
    P = span0[1] - span0[0]
    align = cfg.grid_chunk if cfg.grid_chunk > 0 else max(P, 1)
    n_shards = int(n_shards) if n_shards else len(devices)
    init_spans = shard_spans(span0[0], span0[1], min(n_shards, len(devices)),
                             align)
    if max_rounds is None:
        # Every round either finishes work or shrinks the fleet, so the
        # loop terminates on its own; the cap is a defense against a
        # pathological schedule, generous enough to never bind in practice.
        max_rounds = 2 * (len(init_spans) + len(devices)) + 2

    if not resume:
        # resume=False is a clean slate for THIS run's journals: stale
        # records from an earlier run must not leak into the re-dispatch
        # path (which always resumes so a failed attempt's partial work is
        # kept, never recomputed).
        for s, e in init_spans:
            path = sweep_mod._ledger_path(cfg, f"{model_name}@{s}-{e}")
            if os.path.isfile(path):
                os.remove(path)

    with obs.span("sweep_sharded", model=model_name, preset=cfg.name,
                  shards=len(init_spans), devices=len(devices)) as sp, \
            faults_mod.armed(cfg.inject_faults, seed=cfg.seed):
        out = _sweep_sharded_impl(
            net, cfg, model_name, dataset, devices, n_shards, resume,
            init_spans, P, align, max_rounds, sweep_mod)
        sp.set(partitions=P, **out.counts)
        if out.degraded:
            sp.set(degraded=out.degraded)
        return out


def _sweep_sharded_impl(net, cfg, model_name, dataset, devices, n_shards,
                        resume, init_spans, P, align, max_rounds, sweep_mod):
    surviving = list(devices)
    registry = obs.registry()
    registry.gauge("mesh_size").set(len(surviving))

    # Work items: (span, sink_span, failure) — failure is the ChunkFailure
    # that last hit this span's lineage (None until its first loss).  A
    # re-split keeps the ORIGINAL shard's sink_span, so every re-dispatch
    # appends to the initial shard journal; carrying the failure per
    # lineage keeps abandoned spans' ledger records attributed to the
    # shard/site that actually lost them, not whichever shard failed last.
    pending = [(sp_, sp_, None) for sp_ in init_spans]
    reports = []          # ModelReports of completed span runs
    abandoned = []        # (span, sink_span, ChunkFailure)
    shard_counter = 0
    rounds = 0

    def run_one(shard: Shard, first_resume: bool):
        mesh = _shard_mesh(shard.devices)
        sup = Supervisor(max_retries=cfg.max_launch_retries,
                         backoff_s=cfg.launch_backoff_s,
                         deadline_s=cfg.chunk_deadline_s,
                         seed=cfg.seed + 101 * (shard.index + 1))
        state = {"resume": first_resume}

        def dispatch():
            try:
                faults_mod.check("device.lost")
            except faults_mod.InjectedFault as exc:
                if exc.kind == "fatal":
                    # Retrying on a dead chip cannot help: surface as a
                    # loss so the runtime re-shards instead of retrying.
                    raise DeviceLostError(str(exc)) from exc
                raise  # transient blip (retried) / crash (propagates)
            faults_mod.check("shard.dispatch")
            r, state["resume"] = state["resume"], True
            return sweep_mod.verify_model(
                net, cfg, model_name=model_name, dataset=dataset, mesh=mesh,
                resume=r, partition_span=shard.span,
                sink_name=f"{model_name}@{shard.sink}")

        with obs.span("shard.run", shard=shard.index,
                      span=f"{shard.span[0]}-{shard.span[1]}",
                      devices=len(shard.devices)):
            rep = sup.run(dispatch, site="shard.dispatch")
            # The gather site models pulling the shard's verdict summary
            # back for the cross-shard merge (a DCN fetch on real fleets).
            sup.run(lambda: faults_mod.check("shard.gather"),
                    site="shard.gather")
            return rep

    while pending:
        if not surviving or rounds >= max_rounds:
            abandoned.extend(pending)
            pending = []
            break
        groups = device_groups(surviving, min(n_shards, len(surviving)))
        lost_by = {}  # lost device -> the ChunkFailure that killed its group
        requeue = []
        for i, (span, sink_span, lineage_failure) in enumerate(pending):
            grp = groups[i % len(groups)]
            dead = next((d for d in grp if d in lost_by), None)
            if dead is not None:
                # The group already lost a member this round: don't burn a
                # retry budget on known-dead hardware, requeue directly —
                # attributed to the failure that killed the group.
                requeue.append((span, sink_span, lost_by[dead]))
                continue
            shard = Shard(shard_counter, grp, span, sink_span)
            shard_counter += 1
            try:
                rep = run_one(shard, first_resume=resume or rounds > 0)
            except ChunkDegraded as exc:
                failure = _rewrite_device_lost(exc.failure)
                failure.shard = shard.index
                registry.counter("shard_failures").inc(
                    site=failure.site, kind=failure.kind)
                obs.event("shard_failed", **failure.to_record(),
                          span=f"{span[0]}-{span[1]}",
                          devices=len(grp))
                lost_by.update((d, failure) for d in grp)
                requeue.append((span, sink_span, failure))
                continue
            reports.append(rep)
        if lost_by:
            surviving = [d for d in surviving if d not in lost_by]
            registry.gauge("mesh_size").set(len(surviving))
        if requeue and surviving:
            # Elastic re-shard: split each failed span over the shrunken
            # fleet at chunk boundaries; journals stay pinned to the
            # initial shard span.
            n_next = min(n_shards, len(surviving))
            next_pending = []
            for span, sink_span, lineage_failure in requeue:
                subs = shard_spans(span[0], span[1], n_next, align)
                next_pending.extend((s, sink_span, lineage_failure)
                                    for s in subs)
                obs.event("reshard", span=f"{span[0]}-{span[1]}",
                          subspans=len(subs), devices=len(surviving))
            pending = next_pending
        else:
            pending = requeue
        rounds += 1

    degraded_extra = 0
    synthesized = []
    for span, sink_span, failure in abandoned:
        outs, n_deg = _ledger_abandoned(cfg, model_name, span, sink_span,
                                        failure, sweep_mod)
        synthesized.extend(outs)
        degraded_extra += n_deg

    outcomes = [o for rep in reports for o in rep.outcomes] + synthesized
    outcomes.sort(key=lambda o: o.partition_id)
    return sweep_mod.ModelReport(
        model=model_name, dataset=cfg.dataset, outcomes=outcomes,
        original_acc=next((r.original_acc for r in reports
                           if r.original_acc), 0.0),
        total_time_s=sum(r.total_time_s for r in reports),
        partitions_total=P, sink_name=model_name,
        ledger_skipped_lines=sum(r.ledger_skipped_lines for r in reports),
        degraded=sum(r.degraded for r in reports) + degraded_extra,
    )


def _ledger_abandoned(cfg, model_name, span, sink_span, failure, sweep_mod):
    """Ledger a span no device could run: UNKNOWN + failure per partition.

    Partitions the failed attempts already settled keep their records
    (decided-wins); everything else gets a shard-failure record so the
    degradation is machine-readable and ``resume=True`` re-attempts it.
    """
    if failure is None:  # max_rounds safety valve with no recorded failure
        failure = ChunkFailure(site="shard.dispatch", kind="fatal",
                               error="ReshardExhausted",
                               detail="re-shard rounds exhausted")
    sink = f"{model_name}@{sink_span[0]}-{sink_span[1]}"
    path = sweep_mod._ledger_path(cfg, sink)
    done, _degraded, _skipped = sweep_mod.merge_ledgers([path])
    rec_f = failure.to_record()
    outs = []
    n_deg = 0
    with JournalWriter(path, fault_site=None) as writer:
        for gi in range(span[0], span[1]):
            pid = gi + 1
            rec = done.get(pid)
            if rec is not None:
                outs.append(sweep_mod.PartitionOutcome(
                    pid, rec["verdict"],
                    counterexample=sweep_mod._ledger_ce(rec.get("ce"))))
                continue
            writer.append({"partition_id": pid, "verdict": "unknown",
                           "failure": rec_f})
            outs.append(sweep_mod.PartitionOutcome(pid, "unknown"))
            n_deg += 1
    if n_deg:
        obs.registry().counter("chunks_degraded").inc(site=failure.site,
                                                      n=1)
        obs.event("degraded", **rec_f, phase="sweep_sharded",
                  partitions=n_deg)
    return outs, n_deg

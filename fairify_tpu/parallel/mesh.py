"""Partition-grid sharding over a `jax.sharding.Mesh`.

The reference's one axis of scale is the embarrassingly parallel partition
loop (``src/GC/Verify-GC.py:106``; SURVEY.md §5.7-5.8).  Here the partition
grid is a ``(P, d)`` box tensor, so scaling out is data-parallel sharding of
axis 0 across chips: within a pod the all-gather of per-partition verdict
summaries rides ICI; across hosts, DCN.  XLA inserts the collectives from
the sharding annotations — no hand-written NCCL/MPI analog is needed.

Two composable axes:

* ``parts`` — partitions (pure data parallel, the dominant axis);
* ``models`` — same-architecture model batches (the AC suite is 12+
  same-input-width MLPs; `vmap` over stacked weights + sharding over this
  axis covers the reference's outer model loop, ``src/GC/Verify-GC.py:79``).
"""
from __future__ import annotations

import warnings
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# One warning per process for silent device truncation in make_mesh: every
# sweep builds meshes repeatedly and a per-call warning would drown the
# heartbeat, but losing chips silently is exactly how a "multi-chip" run
# ends up single-chip for weeks.
_TRUNCATION_WARNED = False


def _record_mesh_devices(n_used: int) -> None:
    """Expose the devices actually in the mesh as the ``mesh_devices`` gauge
    (vs. ``jax.device_count()``): a truncated mesh is visible in every
    ``*.throughput.json`` / report snapshot, not just at build time."""
    from fairify_tpu import obs

    obs.registry().gauge("mesh_devices").set(n_used)


def make_mesh(n_parts: Optional[int] = None, n_models: int = 1) -> Mesh:
    """Mesh over available devices: ``(parts, models)`` axes.

    ``n_parts * n_models`` larger than the visible device count is an
    error; smaller uses a prefix of the devices and warns once (the rest
    of the fleet would otherwise idle silently).
    """
    global _TRUNCATION_WARNED
    devs = np.array(jax.devices())
    n_parts = n_parts or (len(devs) // n_models)
    used = n_parts * n_models
    if used > len(devs):
        raise ValueError(
            f"make_mesh: requested {n_parts}x{n_models} mesh needs {used} "
            f"devices but only {len(devs)} are visible")
    if used < len(devs) and not _TRUNCATION_WARNED:
        _TRUNCATION_WARNED = True
        warnings.warn(
            f"make_mesh: {n_parts}x{n_models} mesh uses {used} of "
            f"{len(devs)} visible devices; {len(devs) - used} idle "
            f"(pick n_parts/n_models that factor the fleet, or shard the "
            f"remainder via parallel.shards)", RuntimeWarning, stacklevel=2)
    _record_mesh_devices(used)
    devs = devs[:used].reshape(n_parts, n_models)
    return Mesh(devs, axis_names=("parts", "models"))


def submesh(devices: Sequence, n_models: int = 1) -> Mesh:
    """``(parts, models)`` mesh over an EXPLICIT device subset.

    The shard runtime (:mod:`fairify_tpu.parallel.shards`) rebuilds meshes
    from whatever devices survive a loss, so the device set is an argument,
    not ``jax.devices()``.  ``len(devices)`` must be a multiple of
    ``n_models``; the ``parts`` axis takes the rest.
    """
    devs = np.array(list(devices))
    if len(devs) == 0 or len(devs) % n_models:
        raise ValueError(
            f"submesh: {len(devs)} device(s) do not factor into "
            f"models={n_models}")
    _record_mesh_devices(len(devs))
    return Mesh(devs.reshape(len(devs) // n_models, n_models),
                axis_names=("parts", "models"))


def pad_to_multiple(arr: np.ndarray, multiple: int, axis: int = 0):
    """Pad ``axis`` (default 0) by repeating its last slice so its length
    divides ``multiple`` (the mesh axis size).

    Returns (padded, original_length).  Padded rows recompute an existing
    partition — harmless and branch-free (verdicts are deduplicated by
    index downstream).
    """
    n = arr.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return arr, n
    pad_block = np.repeat(np.take(arr, [-1], axis=axis), rem, axis=axis)
    return np.concatenate([arr, pad_block], axis=axis), n


def shard_parts(mesh: Mesh, *arrays: np.ndarray):
    """Place arrays with axis 0 sharded over the ``parts`` mesh axis."""
    sharding = NamedSharding(mesh, P("parts"))
    out = []
    for a in arrays:
        padded, n = pad_to_multiple(np.asarray(a), mesh.shape["parts"])
        out.append(jax.device_put(padded, sharding))
    return tuple(out)


def replicated(mesh: Mesh, tree):
    """Replicate a pytree (e.g. model weights) across the whole mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda a: jax.device_put(a, sharding), tree)


def stack_models(nets: Sequence) -> object:
    """Stack same-architecture MLPs into one batched pytree (vmap axis 0).

    Covers the reference's sequential model loop for families with uniform
    architecture (e.g. the CP zoo is eleven 32-32-1 nets, SURVEY.md §2.4).
    """
    from fairify_tpu.models.mlp import MLP

    first = nets[0]
    if any(n.layer_sizes != first.layer_sizes or n.in_dim != first.in_dim for n in nets):
        raise ValueError("stack_models requires uniform architectures")
    import jax.numpy as jnp

    return MLP(
        tuple(jnp.stack([n.weights[i] for n in nets]) for i in range(first.depth)),
        tuple(jnp.stack([n.biases[i] for n in nets]) for i in range(first.depth)),
        tuple(jnp.stack([n.masks[i] for n in nets]) for i in range(first.depth)),
    )

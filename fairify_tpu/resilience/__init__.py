"""Resilient sweep runtime: fault injection, launch supervision, degradation.

Fairify's soundness contract is asymmetric: a partition may always be
answered UNKNOWN but never answered wrongly (the reference leans on
per-partition Z3 timeouts, ``src/GC/Verify-GC.py:225-254``).  This package
extends that contract from *solver* faults to *runtime* faults — a device
launch that raises ``XlaRuntimeError``, a decode that dies mid-drain, a
ledger append over a flaky filesystem — so a single transient error
degrades exactly the affected partitions to UNKNOWN-with-reason instead of
killing the whole budgeted run:

* :mod:`fairify_tpu.resilience.faults` — a deterministic fault-injection
  registry.  Named sites (``launch.submit``, ``launch.decode``,
  ``compile``, ``smt.query``, ``ledger.append``) are armed from config/CLI
  specs (``--inject-fault site:kind:nth``), so chaos tests and
  ``scripts/chaos_matrix.py`` replay exact failure schedules.
* :mod:`fairify_tpu.resilience.supervisor` — transient/fatal error
  classification, bounded retries with jittered backoff and a per-chunk
  deadline; exhaustion raises :class:`ChunkDegraded` carrying a
  machine-readable :class:`ChunkFailure` reason that lands in the ledger.
* :mod:`fairify_tpu.resilience.journal` — the atomic (single-write) +
  fsync'd JSONL append helper behind the verdict ledger, shared with the
  obs event log's writer.

The degradation contract is pinned by ``tests/test_resilience.py``: for
every injected-fault schedule, partitions decided around the fault match
the fault-free run's verdicts exactly, faulted partitions are UNKNOWN with
a structured ``failure`` record, and a subsequent ``resume=True`` pass
converges to the fault-free verdict map (DESIGN.md §10).
"""
from __future__ import annotations

from fairify_tpu.resilience.faults import (  # noqa: F401
    FAULT_SITES,
    InjectedFault,
    armed,
    check,
    disarm,
    parse_specs,
)
from fairify_tpu.resilience.journal import JournalWriter, write_line  # noqa: F401
from fairify_tpu.resilience.supervisor import (  # noqa: F401
    ChunkDegraded,
    ChunkFailure,
    Supervisor,
    classify,
)

"""Launch supervision: classify, retry with backoff, degrade to UNKNOWN.

"Fast and Complete" (PAPERS.md) gets verification throughput from cheap
incomplete passes that are *allowed to fail upward* to a complete
fallback; this module applies the same principle to runtime faults.  Any
error at a supervised site is classified:

* **propagate** — control-flow and resource exhaustion
  (``KeyboardInterrupt``, ``SystemExit``, ``MemoryError``,
  ``GeneratorExit``), the fleet's cooperative kill signal
  (``serve.server.ReplicaKilled`` — the thread analog of SIGKILL, matched
  by name to keep this module import-light), and injected ``crash``
  faults: never handled, the thread/process is supposed to die
  (crash-resume is the ledger's job, failover is the fleet router's).
* **transient** — plausibly succeeds on re-attempt: XLA/JAX runtime
  errors (a dropped tunnelled launch), ``OSError``/``TimeoutError``
  (filesystem/network hiccups), injected ``transient`` faults.  Retried
  up to ``max_retries`` times with jittered exponential backoff, bounded
  by the per-chunk ``deadline_s``.
* **fatal** — everything else (a shape error, an injected ``fatal``
  fault): re-attempting cannot help, degrade immediately.

Exhaustion or a fatal error raises :class:`ChunkDegraded`, carrying a
:class:`ChunkFailure` — the machine-readable reason record the sweep
ledgers with the chunk's partitions (``verdict=unknown`` + ``failure``),
surfaces in ``fairify_tpu report``'s degradation table and the heartbeat's
``degraded=`` counter, and that a later ``resume=True`` pass re-attempts.
Every retry bumps the ``launch_retries`` counter (labelled by site) under
a ``resilience.retry`` span, so a flaky device is visible in the event log
long before it exhausts anything.

The deadline is cooperative: a supervised attempt cannot be interrupted
mid-call (there is no safe way to cancel a blocking ``device_get``), so
``deadline_s`` bounds when *another* attempt may start, not the wall time
of a hung one.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional

from fairify_tpu.resilience.faults import InjectedFault

#: Exceptions no supervisor may convert into a degradation.
PROPAGATE = (KeyboardInterrupt, SystemExit, MemoryError, GeneratorExit)

#: Propagate-class exception type names matched without importing their
#: modules (ReplicaKilled lives in serve.server; resilience must not
#: import the serve stack).  A killed replica abandons everything with no
#: cleanup — converting the kill into a retry or a degradation would turn
#: loss-free failover into partial work.
_PROPAGATE_NAMES = frozenset({"ReplicaKilled"})

#: Exception type names classified transient without importing their
#: modules (jaxlib's XlaRuntimeError moves between modules across
#: versions; matching by name keeps the classifier import-light).
_TRANSIENT_NAMES = frozenset({
    "XlaRuntimeError", "JaxRuntimeError", "RpcError", "UnavailableError",
    "InternalError", "DeadlineExceededError",
})


def classify(exc: BaseException) -> str:
    """``'propagate'`` | ``'transient'`` | ``'fatal'`` for one exception."""
    if isinstance(exc, InjectedFault):
        return {"transient": "transient", "fatal": "fatal"}.get(
            exc.kind, "propagate")
    if isinstance(exc, PROPAGATE) \
            or any(c.__name__ in _PROPAGATE_NAMES
                   for c in type(exc).__mro__):
        # MRO scan, not just the leaf name: a ReplicaKilled SUBCLASS is
        # still a kill (isinstance semantics, kept import-light).
        return "propagate"
    if isinstance(exc, OSError):
        # Covers ConnectionError/TimeoutError too (both OSError subclasses).
        # Permanent errno values (EROFS, ENOSPC) are knowingly retried —
        # retries are bounded and the exhaustion is counted, while treating
        # them fatal would skip retries real NFS flakes deserve.
        return "transient"
    if type(exc).__name__ in _TRANSIENT_NAMES:
        return "transient"
    return "fatal"


@dataclass
class ChunkFailure:
    """Machine-readable degradation reason for one chunk of partitions."""

    site: str            # which supervised site exhausted/refused
    kind: str            # 'transient-exhausted' | 'fatal' | 'deadline'
    error: str           # exception type name
    detail: str          # str(exception), truncated
    retries: int = 0     # re-attempts actually spent
    # Shard index when the failure happened in a shard-level fault domain
    # (parallel.shards); None for single-chip chunk failures.  Lands in the
    # ledger record so `fairify_tpu report` can bucket degradation per shard.
    shard: Optional[int] = None

    @property
    def reason(self) -> str:
        """Compact reason code for tables/counters: ``site:kind``."""
        return f"{self.site}:{self.kind}"

    def to_record(self) -> dict:
        rec = {"reason": self.reason, "site": self.site, "kind": self.kind,
               "error": self.error, "detail": self.detail[:200],
               "retries": self.retries}
        if self.shard is not None:
            rec["shard"] = self.shard
        return rec


class ChunkDegraded(RuntimeError):
    """Raised by :meth:`Supervisor.run` when a chunk cannot be completed."""

    def __init__(self, failure: ChunkFailure):
        super().__init__(f"chunk degraded: {failure.reason} "
                         f"({failure.error}: {failure.detail[:120]})")
        self.failure = failure


class Supervisor:
    """Bounded-retry wrapper for device launches and pipeline drains.

    One instance per run (seeded, so backoff jitter is reproducible);
    cheap enough to construct per call site.  ``deadline_s <= 0`` disables
    the per-chunk deadline.
    """

    def __init__(self, max_retries: int = 2, backoff_s: float = 0.05,
                 backoff_mult: float = 2.0, deadline_s: float = 0.0,
                 seed: int = 0, sleep: Callable[[float], None] = time.sleep):
        import numpy as np

        self.max_retries = max(0, int(max_retries))
        self.backoff_s = float(backoff_s)
        self.backoff_mult = float(backoff_mult)
        self.deadline_s = float(deadline_s)
        self._sleep = sleep
        self._rng = np.random.default_rng(seed)

    def _backoff(self, attempt: int) -> float:
        base = self.backoff_s * (self.backoff_mult ** attempt)
        return base * (1.0 + float(self._rng.random()))  # full jitter, 1-2x

    def run(self, fn: Callable, site: str,
            on_retry: Optional[Callable[[], None]] = None):
        """``fn()`` with supervision; returns its value or raises.

        ``on_retry`` runs before each re-attempt (e.g. re-dispatching a
        launch whose device arrays a failed decode poisoned); an error
        inside it counts as the attempt's failure.
        """
        from fairify_tpu import obs

        t0 = time.perf_counter()
        retries = 0
        while True:
            try:
                if retries and on_retry is not None:
                    on_retry()
                return fn()
            except BaseException as exc:
                cls = classify(exc)
                if cls == "propagate":
                    raise
                if cls == "fatal":
                    raise ChunkDegraded(ChunkFailure(
                        site=site, kind="fatal", error=type(exc).__name__,
                        detail=str(exc), retries=retries)) from exc
                elapsed = time.perf_counter() - t0
                if 0 < self.deadline_s <= elapsed:
                    raise ChunkDegraded(ChunkFailure(
                        site=site, kind="deadline", error=type(exc).__name__,
                        detail=str(exc), retries=retries)) from exc
                if retries >= self.max_retries:
                    raise ChunkDegraded(ChunkFailure(
                        site=site, kind="transient-exhausted",
                        error=type(exc).__name__, detail=str(exc),
                        retries=retries)) from exc
                retries += 1
                obs.registry().counter("launch_retries").inc(site=site)
                with obs.span("resilience.retry", site=site, attempt=retries,
                              error=type(exc).__name__):
                    self._sleep(self._backoff(retries - 1))

"""Result-integrity layer: silent-data-corruption detection & injection.

The rest of :mod:`fairify_tpu.resilience` contains *control-plane*
failures — a launch raises, a process dies, a journal line tears.  The
data plane was trusted blindly: a bit flipped in a fetched certify
buffer, a durable ledger row, or a solver witness becomes a certified
verdict that is **wrong**, which for a verifier is a soundness bug, not a
perf bug (DESIGN.md §21).  This module owns both halves of the story:

**Injection** (chaos side) — deterministic bit-flip helpers driven by
``faults`` ``corrupt``-kind specs (``launch.decode:corrupt:N``,
``ledger.append:corrupt:N``, ``smt.query:corrupt:N``).  The flip is keyed
on the corruption arrival number, so a schedule reproduces the exact
same wrong bit every run.

**Detection** (always-on side):

* *canary chunk* — the sweep's mega-``lax.scan`` segments carry one extra
  all-invalid chunk row whose answer is known analytically (an all-masked
  chunk certifies vacuously: ``cert=1, found=0, wit=0, reason=1``)
  independent of the network, so a corrupted fetch of the packed buffers
  is caught at decode with zero extra launches.
* *fold checksum* — the mega kernels fold the packed (cert, wit, reason,
  stats) buffers into one wraparound ``int32`` sum **on device**; the
  host recomputes the same fold over the fetched buffers
  (:func:`fold_host`) and any disagreement marks the transfer corrupt.
* *per-row CRC* — verdict-ledger rows carry ``_crc`` (CRC-32 of the
  canonical JSON body, :func:`record_crc`), written by
  :class:`resilience.journal.JournalWriter` and verified on every ledger
  read (:func:`verify_records`), so decided-wins resume can never replay
  a corrupted verdict.
* *sampled recheck* — :func:`sampled` deterministically selects a
  configurable fraction of decided chunks / SMT UNSATs for independent
  re-execution (bit-equality) and exact-rational escalation
  (``verify/exact_check.py``).

Containment on any mismatch rides the existing ChunkFailure/degradation
contract: the affected span demotes to ``unknown:failure:integrity.*``
and is re-attempted on resume — never trusted, never lost.
"""
from __future__ import annotations

import json
import zlib
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

# Default sampled-recheck rate used by bench.py's overhead A/B and quoted
# in the DESIGN.md guidance.  SweepConfig.integrity_recheck itself
# defaults to 0.0 so the launch-economy pins (one executable per segment
# shape, launches_per_model) hold exactly unless an operator opts in.
DEFAULT_RECHECK_RATE = 0.05

# Mega-segment payload keys covered by the device fold, in fold order.
# The host mirror must walk them in the same order (int32 wraparound sums
# commute, but keeping the order pinned keeps the contract obvious).
FOLD_KEYS = ("cert", "wit", "reason", "stats")

# Device-BaB segment payload keys (engine._bab_segment_kernel outputs), in
# the kernel's fold order — the packed frontier queue plus the per-root
# counters.  Floats fold through the same int32 truncation on both sides
# (XLA convert_element_type f32→s32 and numpy's C cast both round toward
# zero), so equal buffers fold equal on any backend.
BAB_FOLD_KEYS = ("q_lo", "q_hi", "q_root", "q_live", "found",
                 "wit_a", "wit_b", "wit_pt", "nodes", "splits", "overflow")


# --------------------------------------------------------------------------
# deterministic corruption (chaos injection side)

def flip_bit(arr: np.ndarray, n: int) -> np.ndarray:
    """Return a copy of ``arr`` with one deterministically-chosen bit flipped.

    ``n`` (the corruption arrival number) picks the element and the bit,
    so a chaos schedule reproduces the same flip every run.  Booleans are
    inverted wholesale (their one semantic bit); floats get their exponent
    MSB flipped (a magnitude-scale error — the classic SDC signature — so
    downstream range checks cannot accidentally absorb it); integers get
    a low-order XOR.
    """
    out = np.array(arr, copy=True)
    flat = out.reshape(-1)
    if flat.size == 0:
        return out
    i = n % flat.size
    if out.dtype == np.bool_:
        flat[i] = not flat[i]
        return out
    if np.issubdtype(out.dtype, np.floating):
        bits = flat.view(np.uint32 if out.dtype.itemsize == 4 else np.uint64)
        bits[i] ^= np.asarray(1 << (out.dtype.itemsize * 8 - 2), bits.dtype)
        return out
    nbits = out.dtype.itemsize * 8
    flat[i] = flat[i] ^ np.asarray(1 << (n % max(nbits - 1, 1)), out.dtype)
    return out


def corrupt_host(payload: Dict[str, np.ndarray], n: int) -> Dict[str, np.ndarray]:
    """Flip one bit in a fetched device payload (``launch.decode:corrupt``).

    Targets the data buffers, never the riding ``csum`` scalar — the model
    is a flipped bit in the result the host is about to *trust*; the
    checksum is the detector.  (A flipped checksum with intact data would
    also be flagged, conservatively, as a corrupt transfer.)
    """
    keys = sorted(k for k, v in payload.items()
                  if k != "csum" and isinstance(v, np.ndarray) and v.size)
    if not keys:
        return payload
    key = keys[n % len(keys)]
    out = dict(payload)
    out[key] = flip_bit(payload[key], n)
    return out


def corrupt_record(rec: dict, n: int) -> dict:
    """Mutate a ledger row (``ledger.append:corrupt``) post-CRC.

    The nastiest possible flip is chosen on purpose: a decided verdict
    inverts (``unsat`` <-> ``sat``), anything else gets its partition id
    bit-flipped.  The row stays valid JSON — this is a *corrupt* row, not
    a torn line, and must be caught by the CRC, not the JSON parser.
    """
    out = dict(rec)
    v = out.get("verdict")
    if v == "unsat":
        out["verdict"] = "sat"
    elif v == "sat":
        out["verdict"] = "unsat"
    elif isinstance(out.get("partition_id"), int):
        out["partition_id"] = out["partition_id"] ^ (1 << (n % 8))
    return out


def corrupt_witness(ce: Tuple[np.ndarray, np.ndarray],
                    n: int) -> Tuple[np.ndarray, np.ndarray]:
    """Flip one bit in an SMT counterexample pair (``smt.query:corrupt``)."""
    x, xp = np.asarray(ce[0], dtype=np.float64), np.asarray(ce[1], np.float64)
    if n % 2 == 0:
        return flip_bit(x, n), xp
    return x, flip_bit(xp, n)


# --------------------------------------------------------------------------
# detection: host-side fold + canary

def fold_host(payload: Dict[str, np.ndarray],
              keys: Iterable[str] = FOLD_KEYS) -> int:
    """Mirror of the device-side packed-buffer fold (wraparound int32).

    The mega kernels compute ``sum(int32(buf))`` over each packed buffer
    with int32 accumulation (two's-complement wraparound in XLA); numpy's
    ``np.sum(dtype=int32)`` has the same C semantics, so equal data folds
    equal on any backend.
    """
    total = np.int32(0)
    with np.errstate(over="ignore"):
        for k in keys:
            arr = np.asarray(payload[k])
            total = np.int32(
                total + np.sum(arr.astype(np.int32), dtype=np.int32))
    return int(total)


def check_canary(payload: Dict[str, np.ndarray]) -> bool:
    """True iff the trailing canary chunk row holds its known answer.

    The canary is an all-invalid chunk (``valid=0`` everywhere, ``nv=0``):
    the certify kernel vacuously certifies it and the attack finds
    nothing, net-independent — ``cert`` all True, ``reason`` all 1
    (certified, no flip), ``wit`` all zero.
    """
    cert = np.asarray(payload["cert"])
    wit = np.asarray(payload["wit"])
    reason = np.asarray(payload["reason"])
    return (bool(np.all(cert[-1])) and bool(np.all(reason[-1] == 1))
            and bool(np.all(wit[-1] == 0)))


def check_bab_canary(payload: Dict[str, np.ndarray]) -> bool:
    """True iff the BaB queue's trailing canary slot holds its known answer.

    The canary slot is never allocated (``slot_ok`` False): it enters the
    segment dead and all-zero, the kernel's compaction can never scatter a
    child into it, and its latch can never set — so it must come back
    exactly as it went in: not live, not found, zero box, zero witness
    point.  Any deviation means the fetched frontier buffers (or the
    kernel's slot bookkeeping) were corrupted.
    """
    return (not bool(np.any(np.asarray(payload["q_live"])[-1]))
            and not bool(np.any(np.asarray(payload["found"])[-1]))
            and bool(np.all(np.asarray(payload["q_lo"])[-1] == 0))
            and bool(np.all(np.asarray(payload["q_hi"])[-1] == 0))
            and bool(np.all(np.asarray(payload["wit_pt"])[-1] == 0)))


def verify_bab_segment(payload: Dict[str, np.ndarray]) -> Optional[str]:
    """Integrity-check one fetched device-BaB segment payload.

    Same contract as :func:`verify_segment`, over the BaB frontier buffers:
    None when clean, else ``"checksum"`` (host fold over
    :data:`BAB_FOLD_KEYS` != device fold) or ``"canary"`` (the
    never-allocated trailing slot came back non-zero).
    """
    if "csum" in payload and \
            fold_host(payload, keys=BAB_FOLD_KEYS) != int(payload["csum"]):
        return "checksum"
    if not check_bab_canary(payload):
        return "canary"
    return None


def verify_segment(payload: Dict[str, np.ndarray]) -> Optional[str]:
    """Integrity-check one fetched mega-segment payload.

    Returns None when clean, else which detector tripped: ``"checksum"``
    (host fold != device fold) or ``"canary"`` (known-answer row wrong).
    Checksum first — it covers every buffer; the canary additionally
    catches a transfer that was corrupted *consistently* (e.g. a stuck
    line flipping the same bit in data and fold).
    """
    if "csum" in payload and fold_host(payload) != int(payload["csum"]):
        return "checksum"
    if not check_canary(payload):
        return "canary"
    return None


# --------------------------------------------------------------------------
# detection: ledger CRC

def record_crc(rec: dict) -> int:
    """CRC-32 of the canonical JSON body (sans ``_crc``), as written/verified.

    Canonical = ``sort_keys=True`` so writer and reader agree regardless
    of dict insertion order; JSON floats round-trip exactly through
    ``repr`` so re-serialising a parsed row reproduces the bytes.
    """
    body = {k: v for k, v in rec.items() if k != "_crc"}
    return zlib.crc32(json.dumps(body, sort_keys=True).encode("utf-8"))


def verify_records(recs: Iterable[dict]) -> Tuple[List[dict], int]:
    """Split ledger records into (trusted, n_crc_mismatch).

    Rows carrying ``_crc`` must match the recomputed CRC; mismatches are
    dropped (the pid is simply un-ledgered, so decided-wins resume
    re-attempts it — a corrupted verdict is never trusted).  Legacy rows
    without ``_crc`` pass through, keeping old ledgers resumable.  The
    ``_crc`` field is stripped from trusted rows so downstream merge /
    bit-equality comparisons see the verdict body only.
    """
    good: List[dict] = []
    bad = 0
    for rec in recs:
        if "_crc" not in rec:
            good.append(rec)
            continue
        if record_crc(rec) == rec["_crc"]:
            good.append({k: v for k, v in rec.items() if k != "_crc"})
        else:
            bad += 1
    return good, bad


# --------------------------------------------------------------------------
# sampled recheck selection

def sampled(seed: int, key: str, rate: float) -> bool:
    """Deterministic Bernoulli(rate) draw keyed on ``(seed, key)``.

    Hash-based (CRC-32 of the key string), not RNG-state-based, so the
    selection is independent of arrival order, thread interleaving, and
    resume — the same chunk is rechecked in the original run and its
    resume, which is what makes recheck results comparable.
    """
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    h = zlib.crc32(f"{seed}:{key}".encode("utf-8"))
    return (h % 1_000_000) / 1_000_000.0 < rate

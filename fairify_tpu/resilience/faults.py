"""Deterministic, seedable fault-injection registry for chaos testing.

Real runtime faults (a tunnelled chip dropping a launch, a network
filesystem tearing an append) are rare and unreproducible; the retry /
degradation machinery they exercise must not be.  This registry lets a
test — or an operator via ``--inject-fault`` — schedule exact failures at
named **sites**, the places the sweep talks to something that can die:

====================  =====================================================
site                  where :func:`check` is called
====================  =====================================================
``launch.submit``     :class:`parallel.pipeline.LaunchPipeline` dispatch
``launch.decode``     the pipeline's dequeue-time ``jax.device_get``
``compile``           ``obs.compile.ObsJit`` explicit AOT compile
``smt.query``         :func:`verify.smt.decide_box_smt` solver call
``ledger.append``     :class:`resilience.journal.JournalWriter` appends
``shard.dispatch``    :func:`parallel.shards.sweep_sharded` handing a
                      shard's span to its device group
``shard.gather``      collecting a completed shard's verdict summary back
                      into the cross-shard merge
``device.lost``       a shard's device set dying mid-sweep (``fatal``
                      triggers elastic re-sharding onto the survivors;
                      ``transient`` models a link blip the shard
                      supervisor's retry absorbs)
``request.admit``     :meth:`serve.admission.AdmissionController.admit`
                      deciding whether a service request is accepted
``request.deadline``  the server's per-request deadline check before a
                      request (or its next span) starts executing
``serve.drain``       :meth:`serve.server.VerificationServer.drain`
                      journaling queued requests for resume pickup
``request.preempt``   the server's preemption decision at a span-granule
                      boundary (an injected fault FORCES the preemption,
                      so the requeue/resume machinery is chaos-testable
                      without real overload)
``replica.lost``      the fleet router's per-replica health check
                      (:mod:`serve.fleet`) — ``fatal`` kills that replica
                      and exercises failover re-spooling; ``transient``
                      models a heartbeat blip the router absorbs
``replica.spawn``     :class:`serve.procfleet.ProcessFleet` forking a
                      replica worker process (an injected fault models a
                      fork/exec failure; exhaustion abandons the slot and
                      re-homes its requests to survivors)
``replica.lease``     the process-fleet router reading a replica's
                      file-lease heartbeat — ``transient`` is a stat blip
                      the router absorbs for one tick; ``fatal`` forces
                      the lease expired, so the REAL escalating
                      SIGTERM→SIGKILL hang-containment path runs
``smt.worker.spawn``  :class:`smt.pool.SmtPool` forking a solver worker
                      subprocess (an injected fault models a fork/exec
                      failure; exhaustion degrades the query)
``smt.worker.crash``  pool dispatch of one query to a live worker — an
                      injected fault here SIGKILLs the worker subprocess
                      mid-query, so the real death-containment path runs
``smt.worker.hang``   pool dispatch — an injected fault wedges the worker
                      (it sleeps through its deadline), exercising the
                      hard wall-clock kill after grace
``smt.worker.memout`` pool dispatch — an injected fault makes the worker
                      allocate past its RSS cap, exercising the memout
                      containment + higher-cap retry policy
====================  =====================================================

A **spec** is ``site:kind:nth``:

* ``kind`` — ``transient`` (retryable; the supervisor backs off and
  re-attempts), ``fatal`` (non-retryable; the chunk degrades immediately),
  ``crash`` (never handled; propagates like a SIGKILL would, for
  crash-resume chaos tests), or ``corrupt`` (silent-data-corruption: the
  site does NOT raise — it deterministically flips a bit in the payload it
  was about to trust, and the integrity layer
  (:mod:`resilience.integrity`) must catch it downstream).  ``corrupt`` is
  only valid at the data-plane sites ``launch.decode`` (decoded device
  buffers), ``ledger.append`` (durable verdict rows), and ``smt.query``
  (solver witness payloads); it is consumed via :func:`corruption`, which
  keeps its own per-site arrival counters so arming a corrupt spec never
  shifts an existing ``check``-based chaos schedule.
* ``nth`` — which arrivals at the site fire: ``3`` (the 3rd arrival only),
  ``3+`` (every arrival from the 3rd), ``3-5`` (an inclusive range), or
  ``p0.25`` (each arrival independently with probability 0.25, drawn from
  the registry's seeded RNG — deterministic for a given seed and arrival
  order).

Scheduling is arrival-count based, so a schedule is reproducible whenever
the instrumented call order is (the async pipeline keeps submission order
depth-invariant precisely so this holds).  Arrivals are counted per site
from :func:`arm` time; every fired fault bumps the ``fault_injected``
counter (labelled by site and kind) and emits a ``fault_injected`` obs
event.
"""
from __future__ import annotations

import re
import threading
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

FAULT_SITES = frozenset(
    {"launch.submit", "launch.decode", "compile", "smt.query", "ledger.append",
     "shard.dispatch", "shard.gather", "device.lost",
     "request.admit", "request.deadline", "serve.drain",
     "request.preempt", "replica.lost", "replica.spawn", "replica.lease",
     "smt.worker.spawn", "smt.worker.crash", "smt.worker.hang",
     "smt.worker.memout"})
FAULT_KINDS = frozenset({"transient", "fatal", "crash", "corrupt"})
# ``corrupt`` models a bit flip in data the site hands downstream, not a
# failed call — it only makes sense where a payload exists to corrupt AND
# an integrity detector exists to catch it (resilience/integrity.py).
CORRUPT_SITES = frozenset({"launch.decode", "ledger.append", "smt.query"})

_SPEC_RE = re.compile(
    r"^(?P<site>[a-z.]+):(?P<kind>[a-z]+):"
    r"(?P<nth>\d+|\d+\+|\d+-\d+|p(0?\.\d+|1(\.0+)?))$")


class InjectedFault(RuntimeError):
    """The error a scheduled fault raises at its site.

    ``kind`` drives the supervisor's classification: ``transient`` is
    retried, ``fatal`` degrades without retry, ``crash`` always propagates
    (it models a failure no in-process handler may paper over).
    """

    def __init__(self, site: str, kind: str, n: int):
        super().__init__(f"injected {kind} fault at {site} (arrival #{n})")
        self.site = site
        self.kind = kind
        self.n = n


@dataclass(frozen=True)
class FaultSpec:
    site: str
    kind: str
    start: int = 0          # first firing arrival (1-based); 0 = probabilistic
    stop: Optional[int] = None  # inclusive; None with start>0 = single arrival
    every: bool = False     # start+ : every arrival from start
    rate: float = 0.0       # p<rate> : per-arrival probability

    def fires(self, n: int, rng) -> bool:
        if self.rate:
            return bool(rng.random() < self.rate)
        if self.every:
            return n >= self.start
        if self.stop is not None:
            return self.start <= n <= self.stop
        return n == self.start


def parse_spec(spec: str) -> FaultSpec:
    m = _SPEC_RE.match(spec.strip())
    if not m:
        raise ValueError(
            f"bad fault spec {spec!r}: want site:kind:nth with nth one of "
            f"'3', '3+', '3-5', 'p0.25'")
    site, kind, nth = m.group("site"), m.group("kind"), m.group("nth")
    if site not in FAULT_SITES:
        raise ValueError(f"unknown fault site {site!r} "
                         f"(known: {sorted(FAULT_SITES)})")
    if kind not in FAULT_KINDS:
        raise ValueError(f"unknown fault kind {kind!r} "
                         f"(known: {sorted(FAULT_KINDS)})")
    if kind == "corrupt" and site not in CORRUPT_SITES:
        raise ValueError(
            f"fault kind 'corrupt' is only valid at data-plane sites "
            f"{sorted(CORRUPT_SITES)}, not {site!r}")
    if nth.startswith("p"):
        return FaultSpec(site, kind, rate=float(nth[1:]))
    if nth.endswith("+"):
        out = FaultSpec(site, kind, start=int(nth[:-1]), every=True)
    elif "-" in nth:
        a, b = nth.split("-")
        out = FaultSpec(site, kind, start=int(a), stop=int(b))
    else:
        out = FaultSpec(site, kind, start=int(nth))
    if out.start < 1:  # arrivals are 1-based; 0 could never fire
        raise ValueError(f"bad fault spec {spec!r}: nth arrivals are 1-based")
    return out


def parse_specs(specs: Iterable[str]) -> List[FaultSpec]:
    return [parse_spec(s) for s in specs]


class FaultPlan:
    """Armed schedule: per-site arrival counters + the specs they drive."""

    def __init__(self, specs: Iterable[str], seed: int = 0):
        import numpy as np

        self.specs = parse_specs(specs)
        self._rng = np.random.default_rng(seed)
        self._arrivals: Dict[str, int] = {}
        self._corrupt_arrivals: Dict[str, int] = {}
        self._lock = threading.Lock()

    def arrivals(self, site: str) -> int:
        return self._arrivals.get(site, 0)

    def check(self, site: str) -> None:
        """Count one arrival at ``site``; raise if a spec schedules it.

        ``corrupt`` specs are invisible here — they live on their own
        arrival stream (:meth:`corruption`), so arming one can never shift
        the arrival numbering an existing chaos schedule depends on.
        """
        with self._lock:
            n = self._arrivals.get(site, 0) + 1
            self._arrivals[site] = n
            hit = next((s for s in self.specs
                        if s.site == site and s.kind != "corrupt"
                        and s.fires(n, self._rng)), None)
        if hit is None:
            return
        from fairify_tpu import obs

        obs.registry().counter("fault_injected").inc(site=site, kind=hit.kind)
        obs.event("fault_injected", site=site, kind=hit.kind, arrival=n)
        raise InjectedFault(site, hit.kind, n)

    def corruption(self, site: str) -> Optional[int]:
        """Count one data-plane arrival at ``site``; return the arrival
        number if a ``corrupt`` spec schedules a bit flip there, else None.

        Never raises: the caller is expected to mutate the payload it was
        about to trust (:mod:`resilience.integrity` provides deterministic
        flip helpers keyed on the returned arrival number) and carry on —
        the whole point is that only the integrity layer may notice.
        """
        with self._lock:
            n = self._corrupt_arrivals.get(site, 0) + 1
            self._corrupt_arrivals[site] = n
            hit = next((s for s in self.specs
                        if s.site == site and s.kind == "corrupt"
                        and s.fires(n, self._rng)), None)
        if hit is None:
            return None
        from fairify_tpu import obs

        obs.registry().counter("fault_injected").inc(site=site, kind="corrupt")
        obs.event("fault_injected", site=site, kind="corrupt", arrival=n)
        return n


_active: Optional[FaultPlan] = None
_lock = threading.Lock()


def arm(specs: Iterable[str], seed: int = 0) -> Optional[FaultPlan]:
    """Activate a fault schedule (replacing any previous one); None if empty."""
    global _active
    plan = FaultPlan(specs, seed=seed) if specs else None
    with _lock:
        _active = plan
    return plan


def disarm() -> None:
    global _active
    with _lock:
        _active = None


def active() -> Optional[FaultPlan]:
    return _active


def check(site: str) -> None:
    """One arrival at ``site`` — no-op unless a plan is armed.

    The disarmed path is one global read, so instrumented hot paths (every
    pipeline dispatch/drain) pay nothing in production.
    """
    plan = _active
    if plan is not None:
        plan.check(site)


def corruption(site: str) -> Optional[int]:
    """One data-plane arrival at ``site`` — None unless an armed ``corrupt``
    spec fires there (see :meth:`FaultPlan.corruption`)."""
    plan = _active
    if plan is None:
        return None
    return plan.corruption(site)


class armed:
    """Scope a fault schedule: ``with faults.armed(specs, seed): ...``.

    Nested scopes stack (the inner schedule wins for its duration); an
    empty ``specs`` is a true no-op, so call sites can pass config fields
    unconditionally.
    """

    def __init__(self, specs: Iterable[str], seed: int = 0):
        self._specs = tuple(specs or ())
        self._seed = seed
        self._prev: Optional[FaultPlan] = None
        self.plan: Optional[FaultPlan] = None

    def __enter__(self) -> Optional[FaultPlan]:
        if not self._specs:
            self.plan = _active
            return self.plan
        self._prev = _active
        self.plan = arm(self._specs, seed=self._seed)
        return self.plan

    def __exit__(self, *exc) -> bool:
        if self._specs:
            global _active
            with _lock:
                _active = self._prev
        return False

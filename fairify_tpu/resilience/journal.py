"""Atomic + fsync'd JSONL appends: the one writer behind ledger and event log.

A crash-resumable JSONL ledger is only as good as its appends.  Three
hazards, three answers:

* **Torn lines** — a record split across two ``write`` calls can be cut
  mid-line by a crash, corrupting the *previous* record's framing too.
  :func:`write_line` hands the OS exactly one ``write`` per record, so
  the only possible tear is a truncated final line — precisely what
  ``sweep._load_ledger`` / ``obs.load_events`` tolerate (and count).
* **Lost buffers** — a flush moves bytes to the OS, not the platter; a
  host power-cut still loses them.  The verdict ledger fsyncs per append
  (verdicts are minutes of device work each; the syscall is noise), the
  obs event log flushes only (spans are dense and advisory — the ledger
  is the record of truth, per DESIGN.md §6).
* **Flaky filesystems** — a network FS returning ``EIO`` on one append
  must not kill a budgeted sweep.  :class:`JournalWriter` routes appends
  through a :class:`resilience.supervisor.Supervisor` when given one:
  transient errors are retried with backoff; exhaustion is *recorded*
  (``ledger_append_failures`` counter + a ``degraded`` event) and
  reported to the caller as ``False``, never raised — the verdict stays
  in the in-memory report, and a later resume re-decides it (sound:
  UNKNOWN-ward only).

``JournalWriter`` is also a named fault-injection site (``ledger.append``)
so the chaos suite can pin all of the above.

A fourth hazard is *silent* (DESIGN.md §21): a bit flipped in a row that
still parses — the framing survives, the verdict is wrong.  Verdict
ledgers therefore opt into a per-row CRC (``crc=True``): each record is
written with a ``_crc`` field (CRC-32 of the canonical JSON body,
:func:`resilience.integrity.record_crc`) that ``sweep._read_ledger`` /
``merge_ledgers`` verify on replay.  A mismatched row is dropped and
counted (``ledger_crc_mismatch`` — distinct from torn lines), so the pid
is simply un-ledgered and a resume re-decides it: re-attempted, never
trusted.  The ``ledger.append:corrupt`` chaos spec injects exactly this
hazard — the row mutates *after* its CRC is computed, staying valid JSON.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Optional


def write_line(fp, line: str, fsync: bool = True) -> None:
    """One record, one ``write``, flushed (and fsync'd) before returning."""
    fp.write(line)
    fp.flush()
    if fsync:
        os.fsync(fp.fileno())


class JournalWriter:
    """Append-only JSONL sink with crash-safe, supervised appends."""

    def __init__(self, path: str, fsync: bool = True,
                 fault_site: Optional[str] = None, supervisor=None,
                 crc: bool = False):
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self.path = path
        self._fp = open(path, "a")
        self._fsync = fsync
        self._site = fault_site
        self._sup = supervisor
        self._crc = crc
        self._lock = threading.Lock()

    def _append_once(self, line: str) -> None:
        from fairify_tpu.resilience import faults

        if self._site:
            faults.check(self._site)
        with self._lock:
            write_line(self._fp, line, fsync=self._fsync)

    def append(self, rec: dict) -> bool:
        """Append one record; ``False`` if supervised retries exhausted.

        Without a supervisor, errors propagate (callers that cannot
        tolerate a lost record should not pass one).
        """
        if self._crc:
            from fairify_tpu.resilience import faults, integrity

            crc = integrity.record_crc(rec)
            n = faults.corruption(self._site or "ledger.append")
            if n is not None:
                # Injected SDC: mutate AFTER the CRC is sealed, keeping
                # the row valid JSON — the reader's CRC check, not its
                # parser, must be what catches it.
                rec = integrity.corrupt_record(rec, n)
            rec = dict(rec)
            rec["_crc"] = crc
        line = json.dumps(rec) + "\n"
        if self._sup is None:
            self._append_once(line)
            return True
        from fairify_tpu import obs
        from fairify_tpu.resilience.supervisor import ChunkDegraded

        try:
            self._sup.run(lambda: self._append_once(line),
                          site=self._site or "ledger.append")
        except ChunkDegraded as exc:
            obs.registry().counter("ledger_append_failures").inc()
            obs.event("degraded", **exc.failure.to_record(), path=self.path)
            return False
        return True

    def close(self) -> None:
        with self._lock:
            if not self._fp.closed:
                self._fp.close()

    def __enter__(self) -> "JournalWriter":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

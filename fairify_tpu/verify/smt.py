"""Gated Z3 SMT backend (optional; the native engine does not need it).

The reference's decision procedure is a Z3 query over the pruned network
(``src/GC/Verify-GC.py:128-214``; generic encoder pattern in
``utils/DF-1-Model-Functions.py:62-137``).  ``z3-solver`` is not part of
this framework's environment, so the module is import-gated: when Z3 *is*
available, :func:`decide_box_smt` offers a drop-in second opinion for
cross-checking native verdicts (useful for parity audits against the
reference); otherwise :data:`HAVE_Z3` is False and callers fall back to
:func:`fairify_tpu.verify.engine.decide_box`.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

try:  # pragma: no cover - exercised only where z3-solver is installed
    import z3  # type: ignore

    HAVE_Z3 = True
except ImportError:
    z3 = None
    HAVE_Z3 = False

from fairify_tpu.models.mlp import MLP, excise
from fairify_tpu.verify.property import PairEncoding


def _require_z3():
    if not HAVE_Z3:
        raise RuntimeError("z3-solver is not installed; use the native engine "
                           "(fairify_tpu.verify.engine.decide_box)")


def _z3_net(x, weights, biases):
    """Depth-generic symbolic forward: ToReal input, ReLU hidden, linear out
    (one encoder replaces the reference's 53 per-model files)."""
    h = [z3.ToReal(v) if isinstance(v, z3.ArithRef) and v.is_int() else v for v in x]
    n = len(weights)
    for i, (w, b) in enumerate(zip(weights, biases)):
        w = np.asarray(w, dtype=np.float64)
        bb = np.asarray(b, dtype=np.float64)
        z = [
            sum(float(w[t, j]) * h[t] for t in range(w.shape[0])) + float(bb[j])
            for j in range(w.shape[1])
        ]
        h = z if i == n - 1 else [z3.If(v >= 0, v, 0) for v in z]
    return h[0]


def decide_box_smt(
    net: MLP,
    enc: PairEncoding,
    lo: np.ndarray,
    hi: np.ndarray,
    soft_timeout_s: float = 100.0,
) -> Tuple[str, Optional[Tuple[np.ndarray, np.ndarray]]]:
    """Z3 verdict for one partition box (masked net is excised first)."""
    _require_z3()
    small = excise(net)
    weights = [np.asarray(w) for w in small.weights]
    biases = [np.asarray(b) for b in small.biases]
    d = len(lo)
    x = [z3.Int(f"x{i}") for i in range(d)]
    xp = [z3.Int(f"x_{i}") for i in range(d)]
    s = z3.Solver()
    s.set("timeout", int(soft_timeout_s * 1000))

    pa = set(int(i) for i in enc.pa_idx)
    ra = set(int(i) for i in enc.ra_idx)
    for i in range(d):
        s.add(x[i] >= int(lo[i]), x[i] <= int(hi[i]))
        if i in pa:
            s.add(xp[i] >= int(lo[i]), xp[i] <= int(hi[i]))
            s.add(x[i] != xp[i])
        elif i in ra:
            diff = x[i] - xp[i]
            s.add(z3.If(diff >= 0, diff, -diff) <= enc.eps)
        else:
            s.add(x[i] == xp[i])
    y = _z3_net(x, weights, biases)
    yp = _z3_net(xp, weights, biases)
    s.add(z3.Or(z3.And(y < 0, yp > 0), z3.And(y > 0, yp < 0)))

    res = s.check()
    if res == z3.sat:
        m = s.model()

        def val(v):
            return int(m.eval(v, model_completion=True).as_long())

        return "sat", (np.array([val(v) for v in x], dtype=np.int64),
                       np.array([val(v) for v in xp], dtype=np.int64))
    if res == z3.unsat:
        return "unsat", None
    return "unknown", None

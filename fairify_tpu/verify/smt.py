"""SMT encodings of the pair property: SMT-LIB2 export + gated Z3 backend.

The reference's decision procedure is a Z3 query over the pruned network
(``src/GC/Verify-GC.py:128-214``; generic encoder pattern in
``utils/DF-1-Model-Functions.py:62-137``).  ``z3-solver`` is not part of
this framework's environment, so the module has two faces:

* :func:`to_smtlib` — a pure-Python SMT-LIB2 emitter (exact dyadic-rational
  weight literals, QF_LIRA) that needs no solver.  It is exercised in CI
  (semantic tests evaluate the emitted formula against exact witnesses) and
  powers ``scripts/smt_export.py``, which dumps per-partition ``.smt2``
  files + native verdicts so ANY external SMT solver (z3, cvc5, yices) can
  replay the native-vs-SMT agreement audit offline.
* :func:`decide_box_smt` — a live Z3 second opinion, import-gated on
  :data:`HAVE_Z3`; picked up automatically (tests included) wherever
  ``z3-solver`` is installed.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

try:  # pragma: no cover - exercised only where z3-solver is installed
    import z3  # type: ignore

    HAVE_Z3 = True
except ImportError:
    z3 = None
    HAVE_Z3 = False

from fairify_tpu import obs
from fairify_tpu.models.mlp import MLP, excise
from fairify_tpu.verify.property import PairEncoding


def _require_z3():
    if not HAVE_Z3:
        raise RuntimeError("z3-solver is not installed; use the native engine "
                           "(fairify_tpu.verify.engine.decide_box)")


def _z3_net(x, weights, biases):
    """Depth-generic symbolic forward: ToReal input, ReLU hidden, linear out
    (one encoder replaces the reference's 53 per-model files).

    Weight literals are built with :class:`fractions.Fraction` so z3 reasons
    about the *exact dyadic value* of each f32 weight — the same formula
    :func:`to_smtlib` exports (feeding raw Python floats would let z3 coerce
    via decimal repr, e.g. 0.1 → 1/10, a different network).
    """
    from fractions import Fraction

    h = [z3.ToReal(v) if isinstance(v, z3.ArithRef) and v.is_int() else v for v in x]
    n = len(weights)
    for i, (w, b) in enumerate(zip(weights, biases)):
        w = np.asarray(w, dtype=np.float64)
        bb = np.asarray(b, dtype=np.float64)
        z = [
            sum(z3.RealVal(Fraction(float(w[t, j]))) * h[t]
                for t in range(w.shape[0])) + z3.RealVal(Fraction(float(bb[j])))
            for j in range(w.shape[1])
        ]
        h = z if i == n - 1 else [z3.If(v >= 0, v, 0) for v in z]
    return h[0]


def _unknown_reason(reason_str: str) -> str:
    """Map z3's ``reason_unknown`` to the degradation taxonomy's codes.

    ``timeout`` (budget ran out — escalating the timeout may decide it),
    ``memout`` (memory/resource exhaustion — re-running at a BIGGER time
    budget only OOMs harder, so the escalation ladder must skip it; the
    worker pool instead retries once on a higher-RSS-cap worker), or
    ``solver-error`` (the query itself defeated the solver — more time
    rarely helps).  All are sound: UNKNOWN is always a legal answer.
    """
    from fairify_tpu.smt import protocol as smt_protocol

    return smt_protocol.unknown_reason(reason_str)


def decide_box_smt(
    net: MLP,
    enc: PairEncoding,
    lo: np.ndarray,
    hi: np.ndarray,
    soft_timeout_s: float = 100.0,
    retry_timeouts_s: Tuple[float, ...] = (),
) -> Tuple[str, Optional[Tuple[np.ndarray, np.ndarray]], Optional[str]]:
    """Z3 verdict for one partition box (masked net is excised first).

    Returns ``(verdict, counterexample, reason)``: ``reason`` is ``None``
    for decided verdicts and a machine-readable code for UNKNOWN —
    ``"timeout"`` / ``"solver-error"`` (a deterministic solver failure) /
    ``"transient"`` (a retryable runtime fault exhausted the ladder) /
    ``"injected"``.  Z3 exceptions are
    mapped to UNKNOWN instead of propagating (the reference's soundness
    contract: a partition may be answered UNKNOWN but never wrongly, and
    never crash the sweep, ``src/GC/Verify-GC.py:225-254``).

    ``retry_timeouts_s`` is the escalating-timeout ladder for the
    UNKNOWN-retry path (``SweepConfig.smt_retry_timeouts_s``): each entry
    re-checks the same solver state with a larger per-attempt budget, so
    a timeout at 100 s can fall upward to 300 s / 900 s before the box is
    finally conceded as UNKNOWN.
    """
    _require_z3()
    small = excise(net)
    weights = [np.asarray(w) for w in small.weights]
    biases = [np.asarray(b) for b in small.biases]
    d = len(lo)
    x = [z3.Int(f"x{i}") for i in range(d)]
    xp = [z3.Int(f"x_{i}") for i in range(d)]
    s = z3.Solver()

    pa = set(int(i) for i in enc.pa_idx)
    ra = set(int(i) for i in enc.ra_idx)
    for i in range(d):
        s.add(x[i] >= int(lo[i]), x[i] <= int(hi[i]))
        if i in pa:
            s.add(xp[i] >= int(lo[i]), xp[i] <= int(hi[i]))
            s.add(x[i] != xp[i])
        elif i in ra:
            diff = x[i] - xp[i]
            s.add(z3.If(diff >= 0, diff, -diff) <= enc.eps)
        else:
            s.add(x[i] == xp[i])
    y = _z3_net(x, weights, biases)
    yp = _z3_net(xp, weights, biases)
    s.add(z3.Or(z3.And(y < 0, yp > 0), z3.And(y > 0, yp < 0)))

    reason: Optional[str] = None
    for attempt, t in enumerate((soft_timeout_s,) + tuple(retry_timeouts_s)):
        s.set("timeout", int(t * 1000))
        with obs.span("smt.z3_query", timeout_s=t, dims=d,
                      attempt=attempt) as sp:
            try:
                from fairify_tpu.resilience import faults

                faults.check("smt.query")
                res = s.check()
            except BaseException as exc:
                from fairify_tpu.resilience.faults import InjectedFault
                from fairify_tpu.resilience.supervisor import classify

                cls = classify(exc)
                if cls == "propagate":
                    raise
                reason = "injected" if isinstance(exc, InjectedFault) \
                    else ("transient" if cls == "transient"
                          else "solver-error")
                sp.set(verdict="unknown", reason=reason,
                       error=type(exc).__name__)
                obs.registry().counter("smt_queries").inc(verdict="unknown",
                                                          reason=reason)
                if cls == "transient":
                    continue  # plausibly succeeds at the next tier
                break  # a deterministic solver error repeats at any budget
            if res == z3.sat:
                verdict = "sat"
                m = s.model()

                def val(v):
                    return int(m.eval(v, model_completion=True).as_long())

                ce = (np.array([val(v) for v in x], dtype=np.int64),
                      np.array([val(v) for v in xp], dtype=np.int64))
            elif res == z3.unsat:
                verdict, ce = "unsat", None
            else:
                verdict, ce = "unknown", None
                reason = _unknown_reason(s.reason_unknown())
            sp.set(verdict=verdict, **({"reason": reason}
                                       if verdict == "unknown" else {}))
        if verdict == "unknown":
            obs.registry().counter("smt_queries").inc(verdict="unknown",
                                                      reason=reason)
            if reason == "timeout":
                continue  # escalate to the next timeout tier
            break  # solver-error/memout: more time never helps (a memout
            # re-run at a bigger budget only OOMs harder — the pool's
            # higher-RSS-cap retry is the sanctioned second attempt)
        obs.registry().counter("smt_queries").inc(verdict=verdict)
        return verdict, ce, None
    return "unknown", None, reason


def build_query(net: MLP, enc: PairEncoding, lo: np.ndarray, hi: np.ndarray,
                name: str = "partition") -> dict:
    """Wire-format query for the out-of-process worker pool
    (:mod:`fairify_tpu.smt`): the :func:`to_smtlib` serialization plus the
    box/property metadata a backend needs to bound enumeration and to name
    the witness variables (``x{i}``/``xp{i}``) when extracting a model.

    This is the ONLY serialization the pool ships to workers — a worker
    never receives Python objects, so a solver crash can corrupt nothing
    but its own process.
    """
    return {
        "smtlib": to_smtlib(net, enc, lo, hi, name=name),
        "meta": {
            "dims": int(len(lo)),
            "lo": [int(v) for v in lo],
            "hi": [int(v) for v in hi],
            "pa": [int(i) for i in enc.pa_idx],
            "ra": [int(i) for i in enc.ra_idx],
            "eps": int(enc.eps),
            "name": name,
        },
    }


# ---------------------------------------------------------------------------
# SMT-LIB2 export (no solver required)
# ---------------------------------------------------------------------------


def _rat(v: float) -> str:
    """Exact SMT-LIB Real literal for a float (floats are dyadic rationals)."""
    from fractions import Fraction

    f = Fraction(float(v))
    if f.denominator == 1:
        body = f"{abs(f.numerator)}.0"
    else:
        body = f"(/ {abs(f.numerator)} {f.denominator})"
    return body if f >= 0 else f"(- {body})"


def to_smtlib(net: MLP, enc: PairEncoding, lo: np.ndarray, hi: np.ndarray,
              name: str = "partition", get_model: bool = False) -> str:
    """SMT-LIB2 script deciding the pair property on one partition box.

    Semantics match :mod:`fairify_tpu.verify.property` (and the reference's
    constraint builders, ``utils/verif_utils.py:631-945``): integer points;
    every PA differs and both are box-constrained on PA dims; RA dims obey
    ``|x_i − x'_i| ≤ ε`` with x' *not* box-constrained (the reference
    comments that constraint out); all other dims equal; violation = strict
    logit sign flip.  Weights enter as exact dyadic rationals, so ``sat`` /
    ``unsat`` from any sound solver is ground truth for the f32 network —
    the same quantity the native engine's exact leaf checks reason about.
    """
    small = excise(net)
    weights = [np.asarray(w) for w in small.weights]
    biases = [np.asarray(b) for b in small.biases]
    d = len(lo)
    pa = set(int(i) for i in enc.pa_idx)
    ra = set(int(i) for i in enc.ra_idx)
    # Strict SMT-LIB ordering: options precede set-logic; (get-model) is
    # only legal after a sat answer, so it is opt-in (expected-sat exports).
    out = [f"; fairify_tpu pair property — {name}"]
    if get_model:
        out.append("(set-option :produce-models true)")
    out.append("(set-logic QF_LIRA)")
    for i in range(d):
        out.append(f"(declare-const x{i} Int)")
        out.append(f"(declare-const xp{i} Int)")
    for i in range(d):
        out.append(f"(assert (and (>= x{i} {int(lo[i])}) (<= x{i} {int(hi[i])})))")
        if i in pa:
            out.append(
                f"(assert (and (>= xp{i} {int(lo[i])}) (<= xp{i} {int(hi[i])})))")
            out.append(f"(assert (distinct x{i} xp{i}))")
        elif i in ra and enc.eps:
            out.append(f"(assert (let ((dd (- x{i} xp{i})))"
                       f" (<= (ite (>= dd 0) dd (- dd)) {int(enc.eps)})))")
        else:
            out.append(f"(assert (= xp{i} x{i}))")

    def emit_net(prefix: str, var: str):
        prev = [f"(to_real {var}{i})" for i in range(d)]
        n = len(weights)
        for li, (w, b) in enumerate(zip(weights, biases)):
            cur = []
            for j in range(w.shape[1]):
                terms = [f"(* {_rat(w[t, j])} {prev[t]})" for t in range(w.shape[0])]
                terms.append(_rat(b[j]))
                z = f"(+ {' '.join(terms)})" if len(terms) > 1 else terms[0]
                zname = f"{prefix}z{li}_{j}"
                out.append(f"(define-fun {zname} () Real {z})")
                if li < n - 1:
                    hname = f"{prefix}h{li}_{j}"
                    out.append(f"(define-fun {hname} () Real"
                               f" (ite (>= {zname} 0.0) {zname} 0.0))")
                    cur.append(hname)
                else:
                    cur.append(zname)
            prev = cur
        return prev[0]

    y = emit_net("a_", "x")
    yp = emit_net("b_", "xp")
    out.append(f"(assert (or (and (< {y} 0.0) (> {yp} 0.0))"
               f" (and (> {y} 0.0) (< {yp} 0.0))))")
    out.append("(check-sat)")
    if get_model:
        out.append("(get-model)")
    return "\n".join(out) + "\n"

"""The individual-fairness pair property, encoded for static-shape kernels.

Reference semantics (``src/GC/Verify-GC.py:134-154`` + constraint builders in
``utils/verif_utils.py:631-945``):

* ``x`` and ``x'`` are integer points; for every protected attribute (PA)
  ``x[i] != x'[i]`` (conjunction over PA); for every relaxed attribute (RA)
  ``|x[i] - x'[i]| <= ε``; every other attribute is equal.
* Domain box: PA dims of *both* points are box-constrained; non-PA dims are
  box-constrained on ``x`` only (``in_const_domain_german``,
  ``utils/verif_utils.py:743-760`` — the ``x_`` constraint is commented out),
  so an RA-shifted ``x'`` may leave the box by up to ε.
* Violation: strict sign flip on the logits,
  ``Or(And(y<0, y_>0), And(y>0, y_<0))`` (``src/GC/Verify-GC.py:154``).

TPU encoding: PA dims have tiny integer ranges, so instead of free variables
the engine *enumerates* all PA assignments of the full domain (a static set,
V = Π width(PA)) and expresses the pair as (shared non-PA coordinates,
assignment a for ``x``, assignment b for ``x'``) with the valid-(a,b) matrix
``a_i != b_i`` for every PA dim.  Each assignment yields two *role boxes*
per partition box — the ``x`` role (PA pinned, other dims = box) and the
``x'`` role (PA pinned, RA dims widened ±ε, unclamped) — which batch
directly into the CROWN/IBP kernels.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Tuple

import numpy as np

from fairify_tpu.data.domains import DomainSpec


@dataclass(frozen=True)
class FairnessQuery:
    """One verification question: domain + protected/relaxed attributes.

    The 21 reference drivers are instances of this (plus partition policy):
    base = PA only; relaxed adds RA/ε (``relaxed/AC/Verify-AC.py:48-51``);
    targeted/targeted2 override domain ranges (``targeted/GC/Verify-GC.py:55``).
    """

    domain: DomainSpec
    protected: Tuple[str, ...]
    relaxed: Tuple[str, ...] = ()
    relax_eps: int = 0

    def __post_init__(self):
        for a in tuple(self.protected) + tuple(self.relaxed):
            if a not in self.domain.ranges:
                raise KeyError(f"{self.domain.name}: unknown attribute {a}")
        if set(self.protected) & set(self.relaxed):
            raise ValueError("an attribute cannot be both protected and relaxed")

    @property
    def columns(self) -> Tuple[str, ...]:
        return self.domain.columns

    @property
    def pa_idx(self) -> np.ndarray:
        return np.array([self.columns.index(a) for a in self.protected], dtype=np.int32)

    @property
    def ra_idx(self) -> np.ndarray:
        return np.array([self.columns.index(a) for a in self.relaxed], dtype=np.int32)

    @property
    def dim(self) -> int:
        return len(self.columns)


@dataclass(frozen=True)
class PairEncoding:
    """Static tensors encoding the property for a query.

    ``assignments``: (V, n_pa) int32 — every PA assignment in the full domain.
    ``valid_pair``: (V, V) bool — all PA attrs differ between a and b.
    ``pa_idx``/``ra_idx``: dimension indices; ``eps``: RA radius.
    """

    pa_idx: np.ndarray
    ra_idx: np.ndarray
    eps: int
    assignments: np.ndarray
    valid_pair: np.ndarray
    n_dim: int = field(default=0)

    @property
    def n_assign(self) -> int:
        return int(self.assignments.shape[0])


def encode(query: FairnessQuery, max_assignments: int = 1024) -> PairEncoding:
    """Enumerate PA assignments and the valid-pair matrix for a query."""
    pa_idx = query.pa_idx
    ranges = [query.domain.ranges[a] for a in query.protected]
    sizes = [hi - lo + 1 for lo, hi in ranges]
    total = int(np.prod(sizes)) if sizes else 1
    if total > max_assignments:
        raise ValueError(
            f"PA assignment space {total} exceeds {max_assignments}; "
            "protected attributes must have small integer ranges"
        )
    assignments = np.array(
        list(itertools.product(*(range(lo, hi + 1) for lo, hi in ranges))),
        dtype=np.int32,
    ).reshape(total, len(pa_idx))
    # (a, b) is a legal pair iff every PA coordinate differs (conjunction of
    # `neq`, matching in_const_german(..., 'neq', x_)).
    diff = assignments[:, None, :] != assignments[None, :, :]
    valid = diff.all(axis=2) if len(pa_idx) else np.zeros((total, total), dtype=bool)
    return PairEncoding(
        pa_idx=pa_idx,
        ra_idx=query.ra_idx,
        eps=int(query.relax_eps),
        assignments=assignments,
        valid_pair=valid,
        n_dim=query.dim,
    )


def shared_dims(enc: PairEncoding, d: int) -> np.ndarray:
    """Non-PA dimensions: the coordinates a fair pair shares.  The single
    definition used by BaB branching (``engine._branch_dims``) and lattice
    enumeration (``ops.lattice``) — these must never disagree."""
    mask = np.ones(d, dtype=bool)
    if len(enc.pa_idx):
        mask[np.asarray(enc.pa_idx)] = False
    return np.where(mask)[0]


def valid_assignments(enc: PairEncoding, lo: np.ndarray, hi: np.ndarray):
    """PA assignments whose values lie inside the box — the in-box pair
    universe shared by ``engine.decide_leaf`` and ``ops.lattice``."""
    return [
        a for a in range(enc.n_assign)
        if all(lo[enc.pa_idx[k]] <= enc.assignments[a, k] <= hi[enc.pa_idx[k]]
               for k in range(len(enc.pa_idx)))
    ]


def role_boxes(enc: PairEncoding, lo: np.ndarray, hi: np.ndarray):
    """Role boxes for a batch of partition boxes.

    ``lo``/``hi``: (..., d) float/int arrays.  Returns
    ``(x_lo, x_hi, xp_lo, xp_hi, valid_assign)`` where the role boxes have
    shape (..., V, d) and ``valid_assign`` (..., V) marks assignments whose
    PA values lie inside the partition box (PA dims of both points are
    box-constrained, ``utils/verif_utils.py:752-754``).
    """
    lo = np.asarray(lo, dtype=np.float32)
    hi = np.asarray(hi, dtype=np.float32)
    V = enc.n_assign
    x_lo = np.repeat(lo[..., None, :], V, axis=-2).copy()
    x_hi = np.repeat(hi[..., None, :], V, axis=-2).copy()
    assign = enc.assignments.astype(np.float32)  # (V, n_pa)
    if len(enc.pa_idx):
        x_lo[..., :, enc.pa_idx] = assign
        x_hi[..., :, enc.pa_idx] = assign
        valid = (
            (assign >= lo[..., None, enc.pa_idx]) & (assign <= hi[..., None, enc.pa_idx])
        ).all(axis=-1)
    else:
        valid = np.zeros(lo.shape[:-1] + (V,), dtype=bool)
    xp_lo = x_lo.copy()
    xp_hi = x_hi.copy()
    if len(enc.ra_idx) and enc.eps:
        xp_lo[..., :, enc.ra_idx] -= enc.eps
        xp_hi[..., :, enc.ra_idx] += enc.eps
    return x_lo, x_hi, xp_lo, xp_hi, valid


def flip_matrix(logit_x: np.ndarray, logit_xp: np.ndarray, valid_pair: np.ndarray):
    """Strict sign-flip indicator over assignment pairs.

    ``logit_x``: (..., V) logits of the x role; ``logit_xp``: (..., V) of the
    x' role.  Returns (..., V, V) bool where entry (a, b) is True iff the
    pair (x with assignment a, x' with assignment b) flips.
    """
    pos_x = logit_x > 0.0
    neg_x = logit_x < 0.0
    pos_p = logit_xp > 0.0
    neg_p = logit_xp < 0.0
    flips = (pos_x[..., :, None] & neg_p[..., None, :]) | (
        neg_x[..., :, None] & pos_p[..., None, :]
    )
    return flips & valid_pair

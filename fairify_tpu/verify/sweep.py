"""The partition sweep: grid → batched stage-0 kernels → refinement → ledger.

Replaces the reference's per-driver main loop (``src/GC/Verify-GC.py:79-314``):

* **Stage 0 (whole grid, one device pass):** sound pruning stats for every
  partition (:mod:`fairify_tpu.verify.pruning`), root CROWN certificates and
  a sampling attack for every partition — most partitions are decided here
  without ever touching the host branch-and-bound.  This is the TPU speedup:
  the reference runs its IBP/simulation/SMT serially per partition.
* **Stage 1 (leftovers):** per-partition branch-and-bound
  (:func:`fairify_tpu.verify.engine.decide_box`) under the soft timeout; an
  UNKNOWN triggers the reference's heuristic-prune retry
  (``src/GC/Verify-GC.py:172-211``) with the masked network.
* **Ledger:** verdicts are appended to a JSONL ledger per model, giving the
  crash resume the reference lacks (SURVEY.md §5.3-5.4); the 24-column CSV
  (:mod:`fairify_tpu.verify.csvio`) is written alongside.

A `jax.sharding.Mesh` can be supplied to shard stage 0 over the ``parts``
axis (ICI/DCN); the sweep's verdict multiset is mesh-size invariant (tested
on a virtual 8-device CPU mesh).
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fairify_tpu import obs
from fairify_tpu.obs import obs_jit
from fairify_tpu.obs import compile as compile_obs
from fairify_tpu.obs import funnel as funnel_mod
from fairify_tpu.data import loaders
from fairify_tpu.models import mlp as mlp_mod
from fairify_tpu.models import zoo
from fairify_tpu.ops import heuristic as heur_ops
from fairify_tpu.ops import masks as mask_ops
from fairify_tpu.parallel.pipeline import LaunchPipeline
from fairify_tpu.partition import grid as grid_mod
from fairify_tpu.resilience import faults as faults_mod
from fairify_tpu.resilience import integrity as integrity_mod
from fairify_tpu.resilience.journal import JournalWriter
from fairify_tpu.resilience.supervisor import ChunkDegraded, ChunkFailure, Supervisor, classify
from fairify_tpu.utils import profiling
from fairify_tpu.utils.prng import shuffled_order
from fairify_tpu.utils.timing import PhaseTimer
from fairify_tpu.verify import csvio, engine, pruning
from fairify_tpu.verify.config import SweepConfig
from fairify_tpu.verify.property import PairEncoding, encode, role_boxes


@dataclass
class PartitionOutcome:
    partition_id: int
    verdict: str
    counterexample: Optional[tuple] = None
    h_attempt: int = 0
    h_success: int = 0
    nodes: int = 0
    times: Dict[str, float] = field(default_factory=dict)
    compressions: Dict[str, float] = field(default_factory=dict)
    c_check: int = 0
    v_accurate: int = 0
    pruned_acc: float = 0.0


@dataclass
class ModelReport:
    model: str
    dataset: str
    outcomes: List[PartitionOutcome]
    original_acc: float = 0.0
    total_time_s: float = 0.0
    partitions_total: int = 0
    # Result-sink base name ("model" or span-qualified "model@start-stop"
    # for multi-host runs); derived files (e.g. decoded CE CSVs) must use
    # this so sibling sinks never collide across hosts.
    sink_name: str = ""
    # Torn/undecodable JSONL lines skipped while loading this run's resume
    # ledger (mirrors obs.load_events' skipped_lines; >0 after a crash).
    ledger_skipped_lines: int = 0
    # Partitions answered UNKNOWN because a runtime fault degraded their
    # chunk (subset of counts["unknown"]; each carries a ledger `failure`
    # record and is re-attempted by a later resume=True pass).
    degraded: int = 0
    # Deferred SMT finalization (smt_defer mode, serve stack): a
    # sweep.SmtDrain whose drain() consumes the still-in-flight pool
    # futures and patches outcomes/ledger in place; None when the SMT
    # tier completed inline (the default) or never ran.
    smt_pending: Optional[object] = None
    # Funnel telemetry block (obs.funnel, DESIGN.md §20): terminal-state
    # counts, decided_fraction, margin/gap histograms and per-layer bound
    # looseness, exactly as dumped into the run's throughput JSON.  None
    # when the run collected no funnel (e.g. a merged multi-span report).
    funnel: Optional[dict] = None

    @property
    def counts(self) -> Dict[str, int]:
        c = {"sat": 0, "unsat": 0, "unknown": 0}
        for o in self.outcomes:
            c[o.verdict] += 1
        return c


def build_partitions(cfg: SweepConfig):
    """Partition grid as (p_list, lo, hi) in deterministic shuffled order."""
    query = cfg.query()
    domain = query.domain
    ranges = {k: list(v) for k, v in domain.ranges.items()}
    attrs = list(domain.columns)
    if cfg.capped_partitions:
        p_dict = grid_mod.partition_attributes_capped(ranges, cfg.partition_threshold)
        p_list = grid_mod.partitioned_ranges_capped(
            attrs, list(query.protected), p_dict, ranges,
            max_partitions=cfg.max_partitions,
            rng=np.random.default_rng(cfg.seed),
        )
    else:
        # Vectorized cartesian product: stress/relaxed grids reach millions
        # of boxes, so they are built as arrays (identical ordering to the
        # dict path) with a lazy dict view for the few content consumers.
        p_dict = grid_mod.partition_attributes(ranges, cfg.partition_threshold)
        lo0, hi0 = grid_mod.product_boxes(domain.columns, p_dict, ranges)
        order = shuffled_order(lo0.shape[0], cfg.seed)  # random.shuffle :73
        lo, hi = lo0[order], hi0[order]
        return grid_mod.BoxList(lo, hi, domain.columns), lo, hi
    order = shuffled_order(len(p_list), cfg.seed)  # replaces random.shuffle :73
    p_list = [p_list[i] for i in order]
    lo, hi = grid_mod.boxes_from_partitions(p_list, domain.columns)
    return p_list, lo.astype(np.int64), hi.astype(np.int64)


_chunk_spans = grid_mod.chunk_spans
_pad_rows = grid_mod.pad_rows


_segment_spans = grid_mod.segment_spans
_pad_chunk_axis = grid_mod.pad_chunk_axis


def _use_mega(cfg: SweepConfig, mesh) -> bool:
    """Whether this run's stage-0 rides the device-resident mega-loop.

    The mega kernels scan the FUSED certify+attack body, so they exist only
    where that body does: CROWN certificates on an unsharded device
    (``mesh`` runs shard per-chunk arrays and keep the chunk loop; the IBP
    path never had a fused kernel to scan).  ``mega_chunks=0`` opts back
    into the per-chunk launch loop everywhere.
    """
    return cfg.mega_chunks > 0 and cfg.engine.use_crown and mesh is None


def _segment_tick(phase: str, done: int, total: int, partitions: int,
                  in_flight: int = 0) -> None:
    """Segment-granular progress: event-log record + throttled heartbeat.

    Partitions decided INSIDE an in-flight mega launch are invisible to the
    host until the segment drains, so per-partition progress stalls for the
    whole launch; these ticks are what keeps a long single launch from
    looking hung (``fairify_tpu report`` renders the events, the live
    heartbeat prints the done/total line).
    """
    from fairify_tpu.obs import heartbeat as hb_mod

    obs.event("segment", phase=phase, done=done, total=total,
              partitions=partitions)
    hb = hb_mod.active()
    if hb is not None:
        hb.segment(phase, done, total, in_flight=in_flight)


def _supervisor(cfg: SweepConfig) -> Supervisor:
    """The run's launch supervisor, configured from the sweep knobs."""
    return Supervisor(max_retries=cfg.max_launch_retries,
                      backoff_s=cfg.launch_backoff_s,
                      deadline_s=cfg.chunk_deadline_s, seed=cfg.seed)


def _unretried_failure(site: str, exc: BaseException) -> ChunkFailure:
    """Failure record for a fault caught OUTSIDE the supervisor's retry loop
    (sequential engine phases), kept inside the documented kind taxonomy:
    a transient-class error here is 'transient-exhausted' at retries=0."""
    kind = "transient-exhausted" if classify(exc) == "transient" else "fatal"
    return ChunkFailure(site=site, kind=kind, error=type(exc).__name__,
                        detail=str(exc), retries=0)


class _SmtTier:
    """The sweep's out-of-process SMT second-opinion tier (DESIGN.md §14).

    Created right after BaB: every still-unknown root's serialized query
    fans out across the worker pool IMMEDIATELY and in parallel (the
    pre-pool ladder ran one in-process Z3 query per partition, serially),
    and the reporting loop consumes each answer when it reaches that
    partition — host solving overlaps the loop's own work, and under the
    serve stack's shared pool, other requests' device launches.  A
    partition the heuristic retry decides meanwhile has its query
    cancelled, never awaited.
    """

    def __init__(self, net, enc, lo, hi, candidates, cfg, pool=None):
        from fairify_tpu.smt import pool as pool_mod

        self._owns = pool is None
        if pool is None:
            pool = pool_mod.SmtPool(pool_mod.PoolConfig(
                workers=max(int(cfg.smt_workers), 1),
                memory_cap_mb=cfg.smt_memory_cap_mb,
                portfolio=cfg.smt_portfolio, seed=cfg.seed,
                # Worker deaths spend the same retry budget as any other
                # transient fault in this run (DESIGN.md §10/§14).
                max_retries=cfg.max_launch_retries,
                backoff_s=cfg.launch_backoff_s))
        self.pool = pool
        self._futures = {
            p: pool_mod.submit_box(
                pool, net, enc, lo[p], hi[p],
                soft_timeout_s=cfg.soft_timeout_s,
                retry_timeouts_s=cfg.smt_retry_timeouts_s)
            for p in candidates}

    def __contains__(self, p) -> bool:
        return p in self._futures

    def done(self, p) -> bool:
        """Non-blocking: is this partition's answer already in?"""
        fut = self._futures.get(p)
        return fut is None or fut.done()

    def result(self, p):
        """Blocking ``(verdict, ce, reason)`` for one partition — bounded
        by the pool's hard per-dispatch deadlines, so a wedged solver can
        never hang the reporting loop.  Never raises a non-propagate
        error: anything escaping the pool's own containment is one more
        worker-crash UNKNOWN."""
        from concurrent.futures import CancelledError

        from fairify_tpu.smt import protocol

        fut = self._futures.pop(p)
        try:
            v, ce, reason = fut.result().triple
        except CancelledError:
            return "unknown", None, protocol.REASON_SPAWN
        except BaseException as exc:
            if classify(exc) == "propagate":
                raise
            return "unknown", None, protocol.REASON_CRASH
        n = faults_mod.corruption("smt.query")
        if n is not None and v == "sat" and ce is not None:
            # Data-plane chaos (smt.query:corrupt): flip a bit in the
            # witness payload crossing the pool boundary — the host-side
            # validate_pair replay is the detector that must catch it.
            ce = integrity_mod.corrupt_witness(ce, n)
        return v, ce, reason

    def cancel(self, p) -> None:
        fut = self._futures.pop(p, None)
        if fut is not None:
            fut.cancel()

    def close(self) -> None:
        for fut in self._futures.values():
            fut.cancel()
        self._futures.clear()
        if self._owns:
            self.pool.close()


@dataclass
class SmtDrain:
    """Deferred SMT finalization — the serve stack's non-blocking phase.

    Under ``smt_defer`` the reporting loop never blocks on a pool future:
    partitions whose query is still in flight get a provisional UNKNOWN
    outcome whose LEDGER row is withheld (a crash before the drain leaves
    them unledgered, so ``resume=True`` re-attempts them — sound), and
    this object finishes them off the device thread.  ``drain()`` blocks
    on the remaining futures (bounded by the pool's hard per-dispatch
    deadlines), replays SAT witnesses on the host net, appends the final
    ledger records, and mutates the report's outcomes in place — so the
    serve worker loop hands it to a background drainer and moves on to
    the next request's device launches while host solving finishes.

    The per-request CSV keeps the provisional UNKNOWN rows (the ledger is
    the serve result contract; DESIGN.md §14 documents the drift).
    """

    tier: _SmtTier
    items: List  # (local index, pid, PartitionOutcome) still in flight
    report: "ModelReport"
    cfg: SweepConfig
    weights: List
    biases: List
    ledger_path: str
    model_name: str
    sink_name: str

    @property
    def pending(self) -> int:
        return len(self.items)

    def drain(self) -> Dict[str, int]:
        """Consume every deferred answer; returns decided/degraded counts."""
        decided = degraded = 0
        ledger = JournalWriter(self.ledger_path, fault_site="ledger.append",
                               crc=self.cfg.integrity)
        try:
            with obs.span("smt.drain", queries=len(self.items)):
                for p, pid, out in self.items:
                    v, ce, reason = self.tier.result(p)
                    fail_rec = None
                    if v == "sat" and ce is not None \
                            and not engine.validate_pair(self.weights,
                                                         self.biases, *ce):
                        # A witness that fails its host replay is an
                        # integrity violation (a sound backend never
                        # produces one) — degrade with a failure record
                        # so resume re-attempts the partition instead of
                        # settling a corrupted answer as unknown.
                        v, ce, reason = "unknown", None, "invalid-witness"
                        fail_rec = _integrity_failure(
                            "smt.query", "invalid-witness").to_record()
                        degraded += 1
                        self.report.degraded += 1
                        obs.registry().counter("chunks_degraded").inc(
                            site="integrity.smt.query")
                        obs.event("degraded", **fail_rec, phase="smt_drain",
                                  partitions=1)
                    extra = {}
                    if v != "unknown":
                        out.verdict = v
                        out.counterexample = ce
                        decided += 1
                        via = "smt"
                    elif fail_rec is not None:
                        extra = {"failure": fail_rec["reason"]}
                        via = "degraded"
                    elif reason is not None \
                            and reason.startswith("smt.worker:"):
                        fail_rec = ChunkFailure(
                            site="smt.worker", kind=reason.split(":", 1)[1],
                            error="WorkerDied", detail=reason,
                            retries=self.cfg.max_launch_retries).to_record()
                        degraded += 1
                        self.report.degraded += 1
                        obs.registry().counter("chunks_degraded").inc(
                            site="smt.worker")
                        obs.event("degraded", **fail_rec, phase="smt_drain",
                                  partitions=1)
                        extra = {"failure": fail_rec["reason"]}
                        via = "degraded"
                    else:
                        via = "bab"
                        if reason is not None:
                            extra = {"smt_reason": reason}
                    # Last-record-wins everywhere downstream: the drain's
                    # verdict event supersedes the loop's provisional one,
                    # and this append is the partition's FIRST ledger row.
                    obs.event("verdict", model=self.model_name,
                              partition_id=pid, verdict=out.verdict,
                              via=via, **extra)
                    rec = {"partition_id": pid, "verdict": out.verdict,
                           "ce": [out.counterexample[0].tolist(),
                                  out.counterexample[1].tolist()]
                           if out.counterexample else None,
                           "time_s": round(out.times.get("total", 0.0), 4)}
                    if fail_rec is not None:
                        rec["failure"] = fail_rec
                    ledger.append(rec)
                    if out.counterexample is not None:
                        # The reporting loop appends the ce CSV only for
                        # rows it ledgers itself; drain-decided SATs are
                        # this sink's responsibility or the artifact
                        # silently misses every deferred witness.
                        self._append_ce_csv(pid, out.counterexample)
        finally:
            ledger.close()
            self.tier.close()
            self.items = []
        return {"decided": decided, "degraded": degraded}

    def _append_ce_csv(self, pid: int, ce) -> None:
        import csv as _csv

        ce_path = os.path.join(self.cfg.result_dir,
                               f"{self.sink_name}-counterexamples.csv")
        new_file = not os.path.isfile(ce_path)
        with open(ce_path, "a", newline="") as fp:
            wr = _csv.writer(fp)
            if new_file:
                wr.writerow(["partition_id", "role"]
                            + list(self.cfg.query().columns))
            wr.writerow([pid, "x"] + [int(v) for v in ce[0]])
            wr.writerow([pid, "x'"] + [int(v) for v in ce[1]])


def _integrity_failure(site: str, detector: str) -> ChunkFailure:
    """Record one tripped integrity detector → the ChunkFailure that
    contains it (DESIGN.md §21).

    The failure's composite site ``integrity.<site>`` is what the funnel's
    ``failure_state`` buckets on, so the affected partitions land in
    ``unknown:failure:integrity.<site>`` — a *contained wrong answer*, not
    a dead process — and the decided-wins resume contract re-attempts
    them.  ``kind=fatal``: a corrupted payload is never retried in place
    (the data already on the host cannot be trusted; a resume re-runs the
    launch from scratch).
    """
    obs.registry().counter("integrity_violations").inc(site=site)
    obs.event("integrity_violation", site=site, detector=detector)
    return ChunkFailure(site=f"integrity.{site}", kind="fatal",
                        error="IntegrityViolation",
                        detail=f"{detector} mismatch ({site})", retries=0)


def _sampled_recheck(net, enc, lo, hi, cfg: SweepConfig, mesh, seed_offset,
                     step, drained, unsat, sat, witnesses, on_failure):
    """Re-run a deterministic sample of DECIDED chunks; require bit-equality.

    The recheck tier of the integrity contract (DESIGN.md §21): each
    selected chunk is re-executed through the per-chunk path (bit-equal to
    the mega decode by construction, tests/test_mega.py) and its
    (unsat, sat, witnesses) triple must match the banked one EXACTLY —
    selection is hash-keyed on ``(seed, global chunk start)``
    (``integrity.sampled``), so a resume rechecks the same chunks.  A
    mismatch demotes that chunk's partitions to
    ``unknown:failure:integrity.recheck`` (the corrupted copy cannot be
    told from the fresh one, so neither is trusted).  Each clean recheck
    additionally escalates the chunk's first certified partition to the
    exact-rational oracle (``verify/exact_check.py``) — the device-free
    second opinion; a refuted certificate is the worst possible SDC and
    demotes just that partition.  Costs one launch per selected chunk, so
    ``cfg.integrity_recheck`` defaults to 0 (see config.py).
    """
    from fairify_tpu.verify import exact_check

    rechecks = obs.registry().counter("integrity_rechecks")
    weights = biases = None
    for s, e in drained:
        if not integrity_mod.sampled(cfg.seed, f"chunk:{seed_offset + s}",
                                     cfg.integrity_recheck):
            continue
        rechecks.inc(kind="chunk")
        payload, ctx = _stage0_block_submit(
            net, enc, lo[s:e], hi[s:e], cfg, mesh,
            cfg.engine.seed + seed_offset + s, pad_to=step)
        u2, s2, w2 = _stage0_block_decode(jax.device_get(payload), ctx)
        n = e - s
        w2 = {k: v for k, v in w2.items() if k < n}
        have = {k - s: v for k, v in witnesses.items() if s <= k < e}
        clean = (np.array_equal(u2[:n], unsat[s:e])
                 and np.array_equal(s2[:n], sat[s:e])
                 and set(w2) == set(have)
                 and all(np.array_equal(w2[k][0], have[k][0])
                         and np.array_equal(w2[k][1], have[k][1])
                         for k in w2))
        if not clean:
            # Neither copy is trustworthy — erase the banked verdicts and
            # degrade the chunk (re-attempted on resume, never guessed).
            unsat[s:e] = False
            sat[s:e] = False
            for k in range(s, e):
                witnesses.pop(k, None)
            if on_failure is not None:
                on_failure(s, e, _integrity_failure("recheck",
                                                    "bit-equality"))
            continue
        cert_idx = np.flatnonzero(unsat[s:e])
        if not cert_idx.size:
            continue
        if weights is None:
            weights = [np.asarray(w) for w in net.weights]
            biases = [np.asarray(b) for b in net.biases]
        p = s + int(cert_idx[0])
        rechecks.inc(kind="exact")
        res = exact_check.decide_pair_box_exact(
            weights, biases, enc, lo[p], hi[p], max_nodes=2000)
        if res["verdict"] == "refuted":
            unsat[p] = False
            if on_failure is not None:
                on_failure(p, p + 1, _integrity_failure(
                    "exact", "refuted-certificate"))
        # 'budget' is inconclusive, never a violation: exhaustion must not
        # demote a sound certificate (exact_check's own contract).


def _stage0_certify_and_attack(net, enc: PairEncoding, lo, hi, cfg: SweepConfig,
                               mesh=None, seed_offset: int = 0, pipe=None,
                               on_failure=None, stats=None):
    """Root certificates + attack for the whole grid, in grid-chunk blocks.

    ``seed_offset`` ties the attack RNG to the grid's global start index
    (multi-host spans), so spans aligned to ``grid_chunk`` draw the same
    samples a single-host run would.

    Blocks ride the async launch ``pipe`` (a caller-owned
    :class:`parallel.pipeline.LaunchPipeline`, or a local one at
    ``cfg.pipeline_depth``): block N+1's fused kernel is dispatched while
    block N's device arrays are still materializing, and the host-side
    decode (flip extraction, exact ``validate_pair``) of block N overlaps
    the in-flight device work.  Submission order — hence every RNG stream,
    keyed to global block starts — is identical at every depth.

    Under the mega-loop (:func:`_use_mega`, DESIGN.md §17) the pipeline
    entry is a SEGMENT of ``cfg.mega_chunks`` chunks — one ``lax.scan``
    launch, one packed decode, one supervisor retry/degrade unit — and
    the chunk-granular loop below is the mesh/IBP fallback.  Verdict maps
    are bit-equal between the two paths (tests/test_mega.py).

    ``stats`` (an ``obs.funnel.StageStats``, optional) accumulates the
    grid's certified-margin / attack-gap histograms: the mega path adds
    each segment's device-carried ``(2, N_BUCKETS)`` buffer, the chunk path
    buckets the fetched per-box values host-side under the same rule —
    histograms are bit-identical across ``mega_chunks`` settings, like the
    verdict maps they ride along with.  Degraded segments/chunks contribute
    nothing (their partitions never produced margins).
    """
    P = lo.shape[0]
    step, spans = _chunk_spans(P, cfg.grid_chunk)
    if pipe is None:
        pipe = LaunchPipeline(cfg.pipeline_depth, supervisor=_supervisor(cfg))
    unsat = np.zeros(P, dtype=bool)
    sat = np.zeros(P, dtype=bool)
    witnesses: Dict[int, tuple] = {}
    # Chunks that actually drained clean — the sampled-recheck candidate
    # pool (degraded/corrupt chunks are already contained; rechecking them
    # would double-count their failure).
    drained_chunks: List[tuple] = []

    def _maybe_recheck():
        if cfg.integrity and cfg.integrity_recheck > 0.0 and drained_chunks:
            with obs.span("integrity.recheck", chunks=len(drained_chunks),
                          rate=cfg.integrity_recheck):
                _sampled_recheck(net, enc, lo, hi, cfg, mesh, seed_offset,
                                 step, drained_chunks, unsat, sat, witnesses,
                                 on_failure)

    if _use_mega(cfg, mesh):
        # Device-resident mega-loop (DESIGN.md §17): one ``lax.scan``
        # launch certifies + attacks a whole SEGMENT of chunks; the host
        # decodes its packed verdict/witness buffers once per segment.
        # The pipeline now pipelines segments, so the supervisor's
        # ``launch.submit``/``launch.decode`` sites fire — and exhaustion
        # degrades — per segment (the configured blast radius).
        _, segs = _segment_spans(P, cfg.grid_chunk, cfg.mega_chunks)
        # Chunk-axis bucket: a multi-segment grid pads its ragged final
        # segment up to mega_chunks so every segment hits ONE executable.
        bucket = cfg.mega_chunks if len(segs) > 1 else 0
        done = {"n": 0}

        def consume_seg(meta, ctx, host):
            seg_s, seg_e, chunks = meta
            done["n"] += 1
            drained = 0
            if not isinstance(host, ChunkFailure) and ctx.get("integrity"):
                # Verify BEFORE decoding: a corrupted packed buffer must
                # never reach witness extraction or the verdict arrays —
                # the whole segment degrades (exact blast radius) instead.
                tripped = integrity_mod.verify_segment(host)
                if tripped is not None:
                    host = _integrity_failure("launch.decode", tripped)
            if isinstance(host, ChunkFailure):
                # A degraded segment still counts toward done/total, but
                # NONE of its partitions drained (the report's segments
                # table must agree with the degradation table beside it).
                if on_failure is not None:
                    on_failure(seg_s, seg_e, host)
            else:
                drained = seg_e - seg_s
                if stats is not None:
                    # Device-carried (2, N_BUCKETS) histogram: padding rows
                    # were masked on device via the per-chunk n_valid input.
                    stats.add_packed(host["stats"])
                for (s, e), (u, sa, w) in zip(
                        chunks, _mega_segment_decode(host, ctx)):
                    unsat[s:e], sat[s:e] = u[: e - s], sa[: e - s]
                    witnesses.update(
                        {s + k: v for k, v in w.items() if k < e - s})
                drained_chunks.extend(chunks)
            _segment_tick("stage0_decide", done["n"], len(segs),
                          drained, in_flight=len(pipe))

        for si, (seg_s, seg_e, chunks) in enumerate(segs):
            # Step-annotated submit: one XProf step per segment dispatch,
            # named after the phase span (profiling.annotate_step is a
            # no-op unless an --xprof-dir capture is open).
            for item in pipe.submit(
                    lambda chunks=chunks, si=si: profiling.annotate_step(
                        "stage0_decide", si,
                        lambda: _mega_segment_submit(
                            net, enc, lo, hi, cfg, chunks, step, seed_offset,
                            pad_chunks=bucket)),
                    meta=(seg_s, seg_e, chunks)):
                consume_seg(*item)
        for item in pipe.drain():
            consume_seg(*item)
        _maybe_recheck()
        return unsat, sat, witnesses

    def consume(meta, ctx, host):
        s, e = meta
        if isinstance(host, ChunkFailure):
            # Supervised retries exhausted: this chunk's partitions degrade
            # (the caller ledgers them UNKNOWN-with-reason); the pipeline
            # stays primed and later chunks are unaffected.
            if on_failure is not None:
                on_failure(s, e, host)
            return
        u, sa, w = _stage0_block_decode(host, ctx, stats)
        unsat[s:e], sat[s:e] = u[: e - s], sa[: e - s]
        witnesses.update({s + k: v for k, v in w.items() if k < e - s})
        drained_chunks.append((s, e))

    for ci, (s, e) in enumerate(spans):
        for item in pipe.submit(
                lambda s=s, e=e, ci=ci: profiling.annotate_step(
                    "stage0_decide", ci,
                    lambda: _stage0_block_submit(
                        net, enc, lo[s:e], hi[s:e], cfg, mesh,
                        cfg.engine.seed + seed_offset + s, pad_to=step)),
                meta=(s, e)):
            consume(*item)
    for item in pipe.drain():
        consume(*item)
    _maybe_recheck()
    return unsat, sat, witnesses


def _stage0_block_submit(net, enc: PairEncoding, lo, hi, cfg: SweepConfig,
                         mesh, rng_seed, pad_to: int = 0):
    """Dispatch one grid block's stage-0 kernels; no sync on their results.

    Returns ``(payload, ctx)`` for the launch pipeline: ``payload`` holds
    the launch's device arrays (fetched only at dequeue), ``ctx`` the
    host-side state :func:`_stage0_block_decode` needs.

    ``pad_to`` > 0 pads a ragged final chunk up to the chunk bucket (last
    row repeated) BEFORE the attack RNG draws, so every block of a sweep —
    including the last — hits the one compiled executable per kernel
    instead of triggering a second XLA compile per model, and the padded
    rows' RNG draws are identical to an all-full-chunk grid's.  The pad
    lives here (not at call sites) so the invariant cannot drift per
    caller; decode trims via ``ctx["n"]`` + the consumer's span slice.
    """
    if pad_to:
        lo, hi = _pad_rows(lo, pad_to), _pad_rows(hi, pad_to)
    flo, fhi = lo.astype(np.float32), hi.astype(np.float32)
    x_lo, x_hi, xp_lo, xp_hi, valid = role_boxes(enc, flo, fhi)
    plo, phi, valid_in = flo, fhi, valid
    if mesh is not None:
        from fairify_tpu.parallel import mesh as mesh_mod

        x_lo, x_hi, xp_lo, xp_hi, plo, phi, valid_in = mesh_mod.shard_parts(
            mesh, x_lo, x_hi, xp_lo, xp_hi, flo, fhi, valid)
        net = mesh_mod.replicated(mesh, net)
    rng = np.random.default_rng(rng_seed)
    xr, pr = engine.build_attack_candidates(enc, rng, lo, hi, cfg.engine.attack_samples)
    ctx = {"net": net, "enc": enc, "n": lo.shape[0], "valid": valid,
           "xr": xr, "pr": pr}
    if cfg.engine.use_crown and mesh is None:
        # Combined certificate (separate role bounds + tied pair-difference
        # kills, engine._certify_impl) AND the attack + flip detection in ONE
        # launch per block — each launch costs ~110 ms flat on the tunnelled
        # chip (audits/device_util_r4.json), and keeping flip detection on
        # device shrinks the pull to (found, wit) instead of the (P, S, V)
        # logit tensors (VERDICT r4 #3).
        assign_vals, pa_mask, ra_mask = engine._enc_tensors(enc, lo.shape[1])
        profiling.bump_launch()
        cert, _, found_d, wit_d, margin_d, gap_d = engine._certify_attack_kernel(
            net, jnp.asarray(x_lo), jnp.asarray(x_hi), jnp.asarray(xp_lo),
            jnp.asarray(xp_hi), jnp.asarray(plo), jnp.asarray(phi),
            jnp.asarray(assign_vals), jnp.asarray(pa_mask), jnp.asarray(ra_mask),
            float(enc.eps), jnp.asarray(valid_in), jnp.asarray(enc.valid_pair),
            jnp.asarray(xr), jnp.asarray(pr), alpha_iters=0,
        )
        ctx["kind"] = "fused"
        return {"cert": cert, "found": found_d, "wit": wit_d,
                "margin": margin_d, "gap": gap_d}, ctx
    if cfg.engine.use_crown:
        assign_vals, pa_mask, ra_mask = engine._enc_tensors(enc, lo.shape[1])
        profiling.bump_launch()
        cert, _, margin_d = engine._role_certify_kernel(
            net, jnp.asarray(x_lo), jnp.asarray(x_hi), jnp.asarray(xp_lo),
            jnp.asarray(xp_hi), jnp.asarray(plo), jnp.asarray(phi),
            jnp.asarray(assign_vals), jnp.asarray(pa_mask), jnp.asarray(ra_mask),
            float(enc.eps), jnp.asarray(valid_in), jnp.asarray(enc.valid_pair),
            alpha_iters=0,
        )
        profiling.bump_launch()
        lx, lp = engine._attack_logits(net, jnp.asarray(xr), jnp.asarray(pr))
        ctx["kind"] = "crown"
        return {"cert": cert, "margin": margin_d, "lx": lx, "lp": lp}, ctx
    profiling.bump_launch()
    lb_x, ub_x, lb_p, ub_p = engine._role_logit_bounds(
        net, jnp.asarray(x_lo), jnp.asarray(x_hi), jnp.asarray(xp_lo), jnp.asarray(xp_hi),
        cfg.engine.use_crown,
    )
    profiling.bump_launch()
    lx, lp = engine._attack_logits(net, jnp.asarray(xr), jnp.asarray(pr))
    ctx["kind"] = "ibp"
    return {"lb_x": lb_x, "ub_x": ub_x, "lb_p": lb_p, "ub_p": ub_p,
            "lx": lx, "lp": lp}, ctx


def _stage0_block_decode(host, ctx, stats=None):
    """Host decode of a drained stage-0 block → ``(unsat, sat, witnesses)``.

    Everything here is numpy + exact arithmetic — the work the pipeline
    overlaps with the next block's in-flight launch.  ``stats`` (an
    ``obs.funnel.StageStats``) accumulates the block's certified-margin /
    attack-gap histograms: kernel-computed per-box values on the fused
    path, host mirrors of the same formulas on the crown/IBP fallbacks —
    one bucket rule everywhere (obs.funnel), so the chunk loop's histograms
    are bit-identical to the mega loop's carried ones.
    """
    net, enc, n = ctx["net"], ctx["enc"], ctx["n"]
    xr, pr, valid = ctx["xr"], ctx["pr"], ctx["valid"]
    margin = gap = None
    if ctx["kind"] == "fused":
        unsat = np.asarray(host["cert"])[:n]
        found, wit = np.asarray(host["found"]), np.asarray(host["wit"])
        if stats is not None:
            margin = np.asarray(host["margin"])[:n]
            gap = np.asarray(host["gap"])[:n]
    else:
        lx, lp = np.asarray(host["lx"]), np.asarray(host["lp"])
        if ctx["kind"] == "crown":
            unsat = np.asarray(host["cert"])[:n]
            if stats is not None:
                margin = np.asarray(host["margin"])[:n]
        else:
            lb_x, ub_x, lb_p, ub_p = (
                np.asarray(host[k])[:n]
                for k in ("lb_x", "ub_x", "lb_p", "ub_p"))
            unsat = engine.no_flip_certified(lb_x, ub_x, lb_p, ub_p, valid,
                                             enc.valid_pair)
            if stats is not None:
                margin = engine.role_bound_margin(
                    lb_x, ub_x, lb_p, ub_p, valid[:n], enc.valid_pair)
        if stats is not None:
            gap = engine.attack_gap(lx[:n], lp[:n], valid[:n],
                                    enc.valid_pair)
        found, wit = engine.find_flips(enc, lx, lp, valid)
    if stats is not None:
        stats.add_values(margin, gap)
    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    witnesses = engine.extract_witnesses(found, wit, xr, pr, weights, biases)
    sat = np.zeros(n, dtype=bool)
    sat[list(witnesses)] = True
    return unsat, sat, witnesses


def _stage0_block(net, enc: PairEncoding, lo, hi, cfg: SweepConfig, mesh, rng_seed):
    """Synchronous submit+decode of one block (tests, ad-hoc tooling).

    The sweep itself routes every block — single-span grids included —
    through the supervised launch pipeline, so faults degrade per chunk."""
    payload, ctx = _stage0_block_submit(net, enc, lo, hi, cfg, mesh, rng_seed)
    return _stage0_block_decode(jax.device_get(payload), ctx)


# ---------------------------------------------------------------------------
# Device-resident stage-0 mega-loop (DESIGN.md §17)
# ---------------------------------------------------------------------------


def _chunk_stats_dev(margin, gap, n):
    """(2, N_BUCKETS) int32 histogram increment for one scanned chunk.

    The device half of the funnel's fixed-bucket layout (obs.funnel.EDGES):
    ``idx = Σ (v >= edge)`` then a one-hot reduce — comparisons + reduce_sum
    only, so the certify-path kernels stay inside the sound-ops allowlist
    (no searchsorted/sort).  ``n`` masks the padded rows of a ragged chunk
    (and a whole ``n == 0`` chunk padded onto the segment axis), so the
    carried histogram counts exactly the real grid rows.
    """
    edges = jnp.asarray(funnel_mod.EDGES)
    ok = jnp.arange(margin.shape[0], dtype=jnp.int32) < n

    def h(v):
        idx = (v[:, None] >= edges[None, :]).sum(axis=1)
        onehot = (idx[:, None] == jnp.arange(funnel_mod.N_BUCKETS)[None, :]) \
            & ok[:, None]
        return onehot.sum(axis=0).astype(jnp.int32)

    return jnp.stack([h(margin), h(gap)])


def _fold_dev(*bufs):
    """Wraparound-int32 fold over the packed result buffers, ON DEVICE.

    The integrity layer's transfer checksum (DESIGN.md §21): the mega
    kernels fold (cert, wit, reason, stats) into one scalar that rides the
    payload; the host recomputes the identical fold over the fetched
    buffers (``resilience.integrity.fold_host`` — numpy's int32 sums share
    XLA's two's-complement wraparound), so a bit flipped anywhere in the
    fetched segment disagrees.  Casts + reduce_sum only, so the certify
    path stays inside the lint's sound-ops allowlist.
    """
    total = jnp.int32(0)
    for b in bufs:
        total = total + jnp.sum(b.astype(jnp.int32), dtype=jnp.int32)
    return total


@obs_jit(static_argnames=("alpha_iters",))
def _mega_stage0_kernel(net, x_lo, x_hi, xp_lo, xp_hi, plo, phi, av, pm, rm,
                        eps, va, vp, xr, pr, nv, alpha_iters):
    """Stage-0 certify + attack for a whole SEGMENT of chunks, ONE launch.

    ``lax.scan`` over the leading chunk axis (C) of every per-chunk tensor:
    each step runs the exact fused body the chunk loop launches
    (:func:`engine._certify_attack_impl`), so C chunks cost one dispatch
    round-trip instead of C — the α,β-CROWN "rapid massively-parallel
    incomplete verifier" shape (PAPERS.md: arxiv 2011.13824) with the
    incomplete pass living entirely on device.  The scan carry is the chunk
    cursor plus a ``(2, N_BUCKETS)`` int32 funnel-statistics accumulator
    (certified-margin and attack-gap histograms, obs.funnel's fixed-bucket
    layout; ``nv (C,)`` masks padded rows); the per-chunk attack RNG stays
    keyed to GLOBAL chunk starts and is drawn host-side at submit (stacked
    on the scan axis), so the packed results are bit-equal to the chunk
    loop's by construction.

    Returns ``(cert (C, P), wit (C, P, 3), reason (C, P), stats (2, NB))``:
    the packed verdict array, the counterexample index buffer (sample and
    role-pair indices into the host-kept candidates), a per-partition int8
    reason code (0 = undecided, 1 = certified UNSAT, 2 = attack flip,
    3 = both) the host decodes once per segment — the decode derives the
    flip mask from the codes (``reason >= 2``), skips witness extraction
    for flip-free chunks, and resolves flips via exact witness replay —
    and the whole segment's histogram carry: the segment's margin statistics
    cost ONE extra fetched buffer and zero extra launches (DESIGN.md §20).
    """
    def chunk_step(carry, inp):
        cursor, stats = carry
        a, b, c, d, l, h, v, xr_c, pr_c, n = inp
        cert, _, found, wit, margin, gap = engine._certify_attack_impl(
            net, a, b, c, d, l, h, av, pm, rm, eps, v, vp, xr_c, pr_c,
            alpha_iters)
        reason = cert.astype(jnp.int8) + 2 * found.astype(jnp.int8)
        stats = stats + _chunk_stats_dev(margin, gap, n)
        return (cursor + 1, stats), (cert, wit, reason)

    (_, stats), packed = jax.lax.scan(
        chunk_step,
        (jnp.int32(0), jnp.zeros((2, funnel_mod.N_BUCKETS), jnp.int32)),
        (x_lo, x_hi, xp_lo, xp_hi, plo, phi, va, xr, pr, nv))
    return packed + (stats, _fold_dev(*packed, stats))


@obs_jit(static_argnames=("alpha_iters",))
def _mega_family_stage0_kernel(stacked, x_lo, x_hi, xp_lo, xp_hi, plo, phi,
                               av, pm, rm, eps, va, vp, xr, pr, nv,
                               alpha_iters):
    """:func:`_mega_stage0_kernel` for a stacked model family: scan over the
    chunk axis of a vmapped fused body — the whole (models × chunks) stage-0
    pass of a family is ONE launch per segment, which is what turns the
    serve batcher's coalesced buckets into mega-launches.  The funnel
    statistics carry is per model: ``stats (M, 2, N_BUCKETS)``."""
    from fairify_tpu.models.mlp import MLP

    M = stacked.weights[0].shape[0]

    def chunk_step(carry, inp):
        cursor, stats = carry
        a, b, c, d, l, h, v, xr_c, pr_c, n = inp
        cert, _, found, wit, margin, gap = jax.vmap(
            lambda net: engine._certify_attack_impl(
                net, a, b, c, d, l, h, av, pm, rm, eps, v, vp, xr_c, pr_c,
                alpha_iters)
        )(MLP(stacked.weights, stacked.biases, stacked.masks))
        reason = cert.astype(jnp.int8) + 2 * found.astype(jnp.int8)
        stats = stats + jax.vmap(
            lambda m_, g_: _chunk_stats_dev(m_, g_, n))(margin, gap)
        return (cursor + 1, stats), (cert, wit, reason)

    (_, stats), packed = jax.lax.scan(
        chunk_step,
        (jnp.int32(0), jnp.zeros((M, 2, funnel_mod.N_BUCKETS), jnp.int32)),
        (x_lo, x_hi, xp_lo, xp_hi, plo, phi, va, xr, pr, nv))
    return packed + (stats, _fold_dev(*packed, stats))


def _mega_chunk_inputs(enc: PairEncoding, lo, hi, cfg: SweepConfig,
                       chunks, step: int, seed_offset: int,
                       pad_chunks: int = 0, canary: bool = False):
    """Stacked per-chunk device inputs for one segment.

    Each chunk is padded to the chunk bucket and its attack candidates are
    drawn from the SAME host RNG derivation the chunk loop uses
    (``engine.seed + seed_offset + chunk_start``, on the padded rows) —
    the per-chunk key derivation folded into the scan's input stack, so
    segment grouping can never shift an RNG stream.  ``pad_chunks`` pads
    the CHUNK axis to the segment bucket (:func:`_pad_chunk_axis`) so a
    ragged final segment reuses the full-segment executable.

    The trailing ``nv (C,) int32`` buffer is each chunk's REAL row count —
    0 for chunk-axis padding, ``e - s`` for a ragged final chunk — which the
    kernels' funnel-statistics carry uses to mask padded rows out of the
    on-device histograms (padding repeats real rows and would double-count).

    ``canary`` appends the integrity layer's known-answer chunk as the LAST
    scan row (after any chunk-axis padding): all-zero boxes with an
    all-zero valid mask and ``nv = 0``, whose packed answer is analytically
    fixed regardless of the network — every row vacuously certifies
    (``cert=1, reason=1``) and the masked attack finds nothing
    (``wit=0``).  Zero extra launches, no RNG draw (so every real chunk's
    attack stream is untouched), no histogram contribution, and the
    decoder never iterates it (``ctx["chunks"]`` is the real list) — it
    exists only for ``resilience.integrity.check_canary`` to verify at
    fetch time (DESIGN.md §21).
    """
    bufs = [[] for _ in range(9)]
    blk = _pad_chunk_axis(chunks, pad_chunks)
    for s, e in blk:
        clo, chi = _pad_rows(lo[s:e], step), _pad_rows(hi[s:e], step)
        flo, fhi = clo.astype(np.float32), chi.astype(np.float32)
        x_lo, x_hi, xp_lo, xp_hi, valid = role_boxes(enc, flo, fhi)
        rng = np.random.default_rng(cfg.engine.seed + seed_offset + s)
        xr, pr = engine.build_attack_candidates(enc, rng, clo, chi,
                                                cfg.engine.attack_samples)
        for buf, arr in zip(bufs, (x_lo, x_hi, xp_lo, xp_hi, flo, fhi,
                                   valid, xr, pr)):
            buf.append(arr)
    n_real = [e - s if ci < len(chunks) else 0
              for ci, (s, e) in enumerate(blk)]
    if canary:
        for buf in bufs:
            buf.append(np.zeros_like(buf[0]))
        n_real.append(0)
    nv = np.asarray(n_real, np.int32)
    return tuple(np.stack(b) for b in bufs) + (nv,)


def _mega_segment_submit(net, enc: PairEncoding, lo, hi, cfg: SweepConfig,
                         chunks, step: int, seed_offset: int,
                         pad_chunks: int = 0):
    """Dispatch one segment's mega launch; no sync on its results.

    Same ``(payload, ctx)`` contract as :func:`_stage0_block_submit`, one
    pipeline entry per SEGMENT: the supervisor's retry/degrade unit — and
    therefore a fault's blast radius — is the segment.
    """
    (x_lo, x_hi, xp_lo, xp_hi, plo, phi, valid,
     xr, pr, nv) = _mega_chunk_inputs(enc, lo, hi, cfg, chunks, step,
                                      seed_offset, pad_chunks,
                                      canary=cfg.integrity)
    assign_vals, pa_mask, ra_mask = engine._enc_tensors(enc, lo.shape[1])
    profiling.bump_launch()
    cert, wit, reason, stats, csum = _mega_stage0_kernel(
        net, jnp.asarray(x_lo), jnp.asarray(x_hi), jnp.asarray(xp_lo),
        jnp.asarray(xp_hi), jnp.asarray(plo), jnp.asarray(phi),
        jnp.asarray(assign_vals), jnp.asarray(pa_mask),
        jnp.asarray(ra_mask), float(enc.eps), jnp.asarray(valid),
        jnp.asarray(enc.valid_pair), jnp.asarray(xr), jnp.asarray(pr),
        jnp.asarray(nv), alpha_iters=0,
    )
    ctx = {"net": net, "enc": enc, "chunks": chunks, "xr": xr, "pr": pr,
           "kind": "mega", "integrity": cfg.integrity}
    payload = {"cert": cert, "wit": wit, "reason": reason, "stats": stats}
    if cfg.integrity:
        payload["csum"] = csum
    return payload, ctx


def _mega_family_segment_submit(stacked, enc: PairEncoding, lo, hi,
                                cfg: SweepConfig, chunks, step: int,
                                seed_offset: int, pad_chunks: int = 0):
    """Family-stacked :func:`_mega_segment_submit` (one launch per
    (family, segment) — the AC suite and every coalesced serve bucket)."""
    (x_lo, x_hi, xp_lo, xp_hi, plo, phi, valid,
     xr, pr, nv) = _mega_chunk_inputs(enc, lo, hi, cfg, chunks, step,
                                      seed_offset, pad_chunks,
                                      canary=cfg.integrity)
    assign_vals, pa_mask, ra_mask = engine._enc_tensors(enc, lo.shape[1])
    profiling.bump_launch()
    cert, wit, reason, stats, csum = _mega_family_stage0_kernel(
        stacked, jnp.asarray(x_lo), jnp.asarray(x_hi), jnp.asarray(xp_lo),
        jnp.asarray(xp_hi), jnp.asarray(plo), jnp.asarray(phi),
        jnp.asarray(assign_vals), jnp.asarray(pa_mask),
        jnp.asarray(ra_mask), float(enc.eps), jnp.asarray(valid),
        jnp.asarray(enc.valid_pair), jnp.asarray(xr), jnp.asarray(pr),
        jnp.asarray(nv), alpha_iters=0,
    )
    ctx = {"stacked": stacked, "enc": enc, "chunks": chunks,
           "M": stacked.weights[0].shape[0], "xr": xr, "pr": pr,
           "kind": "mega_family", "integrity": cfg.integrity}
    payload = {"cert": cert, "wit": wit, "reason": reason, "stats": stats}
    if cfg.integrity:
        payload["csum"] = csum
    return payload, ctx


def _mega_segment_decode(host, ctx):
    """Host decode of one drained mega segment → per-chunk results.

    ONE decode per segment: the packed reason codes bucket each chunk's
    partitions (certified / flip found / undecided) — the flip mask is
    ``reason >= 2`` and flip-free chunks skip witness extraction
    entirely; flip hits pay the same exact ``validate_pair`` replay as
    the per-chunk decode.  Returns the chunk loop's ``(unsat, sat,
    witnesses)`` triple per chunk (padded rows included; the consumer's
    span slice trims, as everywhere else).  Padded CHUNK-axis entries
    (``_pad_chunk_axis``) are simply never iterated — ``ctx["chunks"]``
    is the real list.
    """
    net, enc, chunks = ctx["net"], ctx["enc"], ctx["chunks"]
    cert = np.asarray(host["cert"])
    wit, reason = np.asarray(host["wit"]), np.asarray(host["reason"])
    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    out = []
    for ci in range(len(chunks)):
        found = reason[ci] >= 2
        witnesses = engine.extract_witnesses(
            found, wit[ci], ctx["xr"][ci], ctx["pr"][ci],
            weights, biases) if found.any() else {}
        sat = np.zeros(cert.shape[1], dtype=bool)
        sat[list(witnesses)] = True
        out.append((cert[ci], sat, witnesses))
    return out


def _mega_family_segment_decode(host, ctx):
    """Family decode: per-chunk LIST of per-model ``(unsat, sat, wits)``."""
    stacked, enc, M = ctx["stacked"], ctx["enc"], ctx["M"]
    chunks = ctx["chunks"]
    cert = np.asarray(host["cert"])  # (C, M, P)
    wit, reason = np.asarray(host["wit"]), np.asarray(host["reason"])
    weights_m = [[np.asarray(w[m]) for w in stacked.weights]
                 for m in range(M)]
    biases_m = [[np.asarray(b[m]) for b in stacked.biases]
                for m in range(M)]
    out = []
    for ci in range(len(chunks)):
        per_model = []
        for m in range(M):
            found = reason[ci, m] >= 2
            witnesses = engine.extract_witnesses(
                found, wit[ci, m], ctx["xr"][ci], ctx["pr"][ci],
                weights_m[m], biases_m[m]) if found.any() else {}
            sat = np.zeros(cert.shape[2], dtype=bool)
            sat[list(witnesses)] = True
            per_model.append((cert[ci, m], sat, witnesses))
        out.append(per_model)
    return out


@obs_jit(static_argnames=("alpha_iters",))
def _family_certify_kernel(stacked, a, b, c, d, plo, phi, av, pm, rm, eps,
                           va, vp, alpha_iters):
    """vmapped stage-0 combined certificate over a stacked model family.

    Module-level (not a closure inside ``_stage0_family``): per-chunk
    recursive calls and repeated invocations must hit one jit cache —
    locally-defined wrappers start with an empty cache every call and
    re-pay retrace+compile per chunk."""
    from fairify_tpu.models.mlp import MLP

    return jax.vmap(
        lambda net: engine._certify_impl(
            net, a, b, c, d, plo, phi, av, pm, rm, eps, va, vp, alpha_iters)
    )(MLP(stacked.weights, stacked.biases, stacked.masks))


@obs_jit(static_argnames=("alpha_iters",))
def _family_stage0_kernel(stacked, a, b, c, d, plo, phi, av, pm, rm, eps,
                          va, vp, xr, pr, alpha_iters):
    """Certificate + attack + flip detection for a stacked family, ONE launch.

    vmapped :func:`engine._certify_attack_impl`: the (M, P, S, V) attack
    logit tensors never leave the device — only per-(model, partition)
    booleans and witness index triples do, which is what makes the 12-model
    adult suite transfer-light on the tunnelled chip."""
    from fairify_tpu.models.mlp import MLP

    return jax.vmap(
        lambda net: engine._certify_attack_impl(
            net, a, b, c, d, plo, phi, av, pm, rm, eps, va, vp, xr, pr,
            alpha_iters)
    )(MLP(stacked.weights, stacked.biases, stacked.masks))


@obs_jit
def _family_bounds_kernel(stacked, a, b, c, d, use_crown):
    from fairify_tpu.models.mlp import MLP

    return jax.vmap(
        lambda net: engine._role_logit_bounds.__wrapped__(net, a, b, c, d, use_crown)
    )(MLP(stacked.weights, stacked.biases, stacked.masks))


@obs_jit
def _family_logits_kernel(stacked, xr, pr):
    from fairify_tpu.models.mlp import MLP, forward

    net = MLP(stacked.weights, stacked.biases, stacked.masks)
    return jax.vmap(lambda n: (forward(n, xr), forward(n, pr)))(net)


def _stage0_family(stacked, enc: PairEncoding, lo, hi, cfg: SweepConfig,
                   mesh=None, pipe=None):
    """Stage 0 for a whole same-architecture model family in one kernel.

    The reference iterates models serially (``src/GC/Verify-GC.py:79``); here
    the family is a stacked weight pytree and `vmap` lifts the role-bound and
    attack kernels over the model axis, so the MXU sees one
    (models × partitions × assignments) batch.  Returns per-model
    (unsat, sat, witnesses) tuples.  Grids larger than ``cfg.grid_chunk``
    are processed in fixed-size blocks (same scheme as the single-model
    stage 0) so the model axis never multiplies an unbounded partition axis.
    """
    return stage0_families([stacked], enc, lo, hi, cfg, mesh=mesh,
                           pipe=pipe)[0]


def stage0_families(stacks, enc: PairEncoding, lo, hi, cfg: SweepConfig,
                    mesh=None, pipe=None, seed_offset: int = 0, stats=None):
    """Stage 0 for SEVERAL stacked families through one shared launch queue.

    Every (family, segment) block — (family, grid-chunk) on the fallback
    chunk path — is an independent launch, so they all ride the same async
    pipeline: the per-model host decode of one family's block (witness
    extraction, exact ``validate_pair``) overlaps the next block's — or
    the next *family's* — in-flight kernel, and the 12-model AC suite
    never drains the device queue between architecture groups.  Under the
    mega-loop one ``lax.scan`` launch covers a whole family × segment
    (DESIGN.md §17), which is what turns the serve batcher's coalesced
    buckets into mega-launches.  Returns one result list (per-model
    ``(unsat, sat, witnesses)``) per entry of ``stacks``.

    ``seed_offset`` ties the attack RNG to the grid's GLOBAL start index
    (same contract as :func:`_stage0_certify_and_attack`): a caller handing
    a span-local ``lo``/``hi`` slice (the serve batcher coalescing span
    requests) passes the span start so every chunk draws exactly the
    samples a whole-grid run would.

    ``stats`` (optional) is a dict the caller owns; the mega path and the
    fused chunk path accumulate one ``obs.funnel.StageStats`` per
    ``(stack_index, model_index)`` key into it (created on first touch).
    The crown/IBP fallback family paths skip statistics — they are the
    mesh/degraded tiers and their partitions re-enter the per-model
    pipeline, which records margins itself.
    """
    P = lo.shape[0]
    step, spans = _chunk_spans(P, cfg.grid_chunk)
    if pipe is None:
        pipe = LaunchPipeline(cfg.pipeline_depth, supervisor=_supervisor(cfg))
    accs = []
    for stacked in stacks:
        M = stacked.weights[0].shape[0]
        accs.append(([np.zeros(P, dtype=bool) for _ in range(M)],
                     [np.zeros(P, dtype=bool) for _ in range(M)],
                     [{} for _ in range(M)]))

    if _use_mega(cfg, mesh):
        # Mega-loop path (DESIGN.md §17): one scan launch per (family,
        # segment) — C chunks × M models of fused certify+attack in a
        # single dispatch; a degraded segment leaves exactly its span
        # undecided (upward degradation to the per-model PGD/BaB tier,
        # same contract as the chunk loop).
        _, segs = _segment_spans(P, cfg.grid_chunk, cfg.mega_chunks)
        bucket = cfg.mega_chunks if len(segs) > 1 else 0
        total = len(segs) * len(stacks)
        done = {"n": 0}

        def consume_seg(meta, ctx, host):
            gi, seg_s, seg_e, chunks = meta
            done["n"] += 1
            drained = 0
            if not isinstance(host, ChunkFailure) and ctx.get("integrity"):
                # Same fetch-time gate as the single-model path: the fold
                # and canary checks work unchanged on the family-stacked
                # (C, M, ...) buffers, and a trip degrades the whole
                # (family, segment) block before any model decodes.
                tripped = integrity_mod.verify_segment(host)
                if tripped is not None:
                    host = _integrity_failure("launch.decode", tripped)
            if isinstance(host, ChunkFailure):
                obs.registry().counter("chunks_degraded").inc(site=host.site)
                obs.event("degraded", **host.to_record(),
                          phase="stage0_family", partitions=seg_e - seg_s)
            else:
                drained = seg_e - seg_s
                unsat, sat, wits = accs[gi]
                if stats is not None:
                    seg_stats = np.asarray(host["stats"])  # (M, 2, NB)
                    for m in range(seg_stats.shape[0]):
                        stats.setdefault(
                            (gi, m), funnel_mod.StageStats()
                        ).add_packed(seg_stats[m])
                for (s, e), per_model in zip(
                        chunks, _mega_family_segment_decode(host, ctx)):
                    for m, (u, sa, w) in enumerate(per_model):
                        unsat[m][s:e], sat[m][s:e] = u[: e - s], sa[: e - s]
                        wits[m].update(
                            {s + k: v for k, v in w.items() if k < e - s})
            _segment_tick("stage0_family", done["n"], total, drained,
                          in_flight=len(pipe))

        for gi, stacked in enumerate(stacks):
            for seg_s, seg_e, chunks in segs:
                for item in pipe.submit(
                        lambda stacked=stacked, chunks=chunks:
                        _mega_family_segment_submit(
                            stacked, enc, lo, hi, cfg, chunks, step,
                            seed_offset, pad_chunks=bucket),
                        meta=(gi, seg_s, seg_e, chunks)):
                    consume_seg(*item)
        for item in pipe.drain():
            consume_seg(*item)
        return [list(zip(*acc)) for acc in accs]

    def consume(meta, ctx, host):
        gi, s, e = meta
        if isinstance(host, ChunkFailure):
            # A degraded family chunk leaves its span UNDECIDED (not
            # UNKNOWN): these are precomputed stage-0 results, and every
            # undecided partition gets the per-model PGD/BaB path anyway —
            # degradation upward to the slower-but-complete tier.
            obs.registry().counter("chunks_degraded").inc(site=host.site)
            obs.event("degraded", **host.to_record(), phase="stage0_family",
                      partitions=e - s)
            return
        unsat, sat, wits = accs[gi]
        for m, (u, sa, w) in enumerate(
                _family_block_decode(host, ctx, stats=stats, gi=gi)):
            unsat[m][s:e], sat[m][s:e] = u[: e - s], sa[: e - s]
            wits[m].update({s + k: v for k, v in w.items() if k < e - s})

    for gi, stacked in enumerate(stacks):
        for s, e in spans:
            for item in pipe.submit(
                    lambda gi=gi, stacked=stacked, s=s, e=e:
                    _family_block_submit(
                        stacked, enc, lo[s:e], hi[s:e], cfg, mesh,
                        cfg.engine.seed + seed_offset + s, pad_to=step),
                    meta=(gi, s, e)):
                consume(*item)
    for item in pipe.drain():
        consume(*item)
    return [list(zip(*acc)) for acc in accs]


def _family_block_submit(stacked, enc: PairEncoding, lo, hi, cfg: SweepConfig,
                         mesh, rng_seed, pad_to: int = 0):
    """Dispatch one family block's stage-0 kernels; no sync on results.

    ``pad_to`` pads a ragged final chunk to the chunk bucket before the RNG
    draws (see :func:`_stage0_block_submit`) — one compiled executable per
    stacked family, no second XLA compile on the last block.
    """
    if pad_to:
        lo, hi = _pad_rows(lo, pad_to), _pad_rows(hi, pad_to)
    M = stacked.weights[0].shape[0]
    flo, fhi = lo.astype(np.float32), hi.astype(np.float32)
    x_lo, x_hi, xp_lo, xp_hi, valid = role_boxes(enc, flo, fhi)
    plo, phi, valid_in = flo, fhi, valid
    if mesh is not None:
        from fairify_tpu.parallel import mesh as mesh_mod

        x_lo, x_hi, xp_lo, xp_hi, plo, phi, valid_in = mesh_mod.shard_parts(
            mesh, x_lo, x_hi, xp_lo, xp_hi, flo, fhi, valid)
        stacked = mesh_mod.replicated(mesh, stacked)
    rng = np.random.default_rng(rng_seed)
    xr, pr = engine.build_attack_candidates(enc, rng, lo, hi,
                                            cfg.engine.attack_samples)
    ctx = {"stacked": stacked, "enc": enc, "M": M, "n": lo.shape[0],
           "valid": valid, "xr": xr, "pr": pr}

    if cfg.engine.use_crown and mesh is None:
        # Fused per-chunk launch: certificates, attack forwards AND flip
        # detection for the whole stacked family (_family_stage0_kernel);
        # only (M, P) masks + (M, P, 3) witness indices cross the tunnel.
        assign_vals, pa_mask, ra_mask = engine._enc_tensors(enc, lo.shape[1])
        profiling.bump_launch()
        cert, _, found_d, wit_d, margin_d, gap_d = _family_stage0_kernel(
            stacked, jnp.asarray(x_lo), jnp.asarray(x_hi), jnp.asarray(xp_lo),
            jnp.asarray(xp_hi), jnp.asarray(plo), jnp.asarray(phi),
            jnp.asarray(assign_vals), jnp.asarray(pa_mask), jnp.asarray(ra_mask),
            float(enc.eps), jnp.asarray(valid_in), jnp.asarray(enc.valid_pair),
            jnp.asarray(xr), jnp.asarray(pr), alpha_iters=0,
        )
        ctx["kind"] = "fused"
        return {"cert": cert, "found": found_d, "wit": wit_d,
                "margin": margin_d, "gap": gap_d}, ctx

    if cfg.engine.use_crown:
        assign_vals, pa_mask, ra_mask = engine._enc_tensors(enc, lo.shape[1])
        profiling.bump_launch()
        cert, _, _margin_d = _family_certify_kernel(
            stacked, jnp.asarray(x_lo), jnp.asarray(x_hi), jnp.asarray(xp_lo),
            jnp.asarray(xp_hi), jnp.asarray(plo), jnp.asarray(phi),
            jnp.asarray(assign_vals), jnp.asarray(pa_mask), jnp.asarray(ra_mask),
            float(enc.eps), jnp.asarray(valid_in), jnp.asarray(enc.valid_pair),
            alpha_iters=0,
        )
        profiling.bump_launch()
        lx, lp = _family_logits_kernel(stacked, jnp.asarray(xr), jnp.asarray(pr))
        ctx["kind"] = "crown"
        return {"cert": cert, "lx": lx, "lp": lp}, ctx

    profiling.bump_launch()
    lb_x, ub_x, lb_p, ub_p = _family_bounds_kernel(
        stacked, jnp.asarray(x_lo), jnp.asarray(x_hi), jnp.asarray(xp_lo),
        jnp.asarray(xp_hi), cfg.engine.use_crown,
    )
    profiling.bump_launch()
    lx, lp = _family_logits_kernel(stacked, jnp.asarray(xr), jnp.asarray(pr))
    ctx["kind"] = "ibp"
    return {"lb_x": lb_x, "ub_x": ub_x, "lb_p": lb_p, "ub_p": ub_p,
            "lx": lx, "lp": lp}, ctx


def _family_block_decode(host, ctx, stats=None, gi: int = 0):
    """Host decode of a drained family block → per-model results.

    ``stats``/``gi``: see :func:`stage0_families` — the fused path banks
    each model's margin/gap histograms under ``(gi, m)``; the crown/IBP
    fallbacks don't record statistics."""
    stacked, enc, M, n = ctx["stacked"], ctx["enc"], ctx["M"], ctx["n"]
    xr, pr, valid = ctx["xr"], ctx["pr"], ctx["valid"]
    if ctx["kind"] == "fused":
        unsat_all = np.asarray(host["cert"])[:, :n]
        found_all, wit_all = np.asarray(host["found"]), np.asarray(host["wit"])
        if stats is not None:
            margin_all = np.asarray(host["margin"])[:, :n]
            gap_all = np.asarray(host["gap"])[:, :n]
            for m in range(M):
                stats.setdefault(
                    (gi, m), funnel_mod.StageStats()
                ).add_values(margin_all[m], gap_all[m])
        results = []
        for m in range(M):
            weights = [np.asarray(w[m]) for w in stacked.weights]
            biases = [np.asarray(b[m]) for b in stacked.biases]
            witnesses = engine.extract_witnesses(
                found_all[m], wit_all[m], xr, pr, weights, biases)
            sat = np.zeros(n, dtype=bool)
            sat[list(witnesses)] = True
            results.append((unsat_all[m], sat, witnesses))
        return results

    if ctx["kind"] == "crown":
        unsat_all = np.asarray(host["cert"])[:, :n]
    else:
        lb_x, ub_x, lb_p, ub_p = (
            np.asarray(host[k])[:, :n]
            for k in ("lb_x", "ub_x", "lb_p", "ub_p"))
        unsat_all = np.stack([
            engine.no_flip_certified(lb_x[m], ub_x[m], lb_p[m], ub_p[m],
                                     valid, enc.valid_pair)
            for m in range(M)
        ])
    lx, lp = np.asarray(host["lx"]), np.asarray(host["lp"])
    results = []
    for m in range(M):
        found, wit = engine.find_flips(enc, lx[m], lp[m], valid)
        weights = [np.asarray(w[m]) for w in stacked.weights]
        biases = [np.asarray(b[m]) for b in stacked.biases]
        witnesses = engine.extract_witnesses(found, wit, xr, pr, weights, biases)
        sat = np.zeros(n, dtype=bool)
        sat[list(witnesses)] = True
        results.append((unsat_all[m], sat, witnesses))
    return results


@obs_jit(static_argnames=("sim_size",))
def _parity_grid_from_keys(net, keys, lo, hi, alive, sim_size: int):
    """Pruned-vs-original prediction parity for the whole grid, one kernel.

    Replaces the reference's per-partition ``pruned_acc`` loop
    (``src/GC/Verify-GC.py:265-270``).  The simulation samples are
    regenerated on device from their per-partition keys (bit-identical to
    ``PruneResult.sim``: same ``simulate_box`` + key), so the (P, S, d)
    sample tensor never crosses the host↔device link — on the tunnelled
    single-chip setup that transfer dominated the whole stage-0 wall time
    for the adult grid (~0.8 GB per model).

    ``alive`` covers the HIDDEN layers only; the final layer is never
    pruned, so its all-ones mask is rebuilt from the net here instead of
    shipping a per-partition (P, 1) ones buffer the kernel never reads
    (the ``ir-buffers`` pass flagged exactly that dead argument).
    """
    from fairify_tpu.ops import simulate as sim_ops

    def one(k, l, h, masks):
        s = sim_ops.simulate_box(k, l, h, sim_size)
        orig = mlp_mod.forward(net, s) > 0.0
        pruned = net.with_masks(tuple(masks) + (net.masks[-1],))
        masked = mlp_mod.forward(pruned, s) > 0.0
        return jnp.mean((orig == masked).astype(jnp.float32))

    return jax.vmap(one)(keys, lo, hi, alive)


@obs_jit(static_argnames=("sim_size",))
def _mega_parity_kernel(net, keys, lo, hi, alive, sim_size: int):
    """Whole-segment parity pass: ``lax.scan`` over the chunk axis of
    :func:`_parity_grid_from_keys`'s body — one launch per segment instead
    of one per chunk, same launch economics as the stage-0 mega kernel.
    Inputs carry a leading (C) chunk axis; simulation keys stay the global
    per-partition ``grid_keys`` derivation, so every sample row is
    bit-identical to the chunk loop's."""
    def chunk_step(cursor, inp):
        k, l, h, masks = inp
        return cursor + 1, _parity_grid_from_keys.__wrapped__(
            net, k, l, h, masks, sim_size)

    _, parity = jax.lax.scan(chunk_step, jnp.int32(0), (keys, lo, hi, alive))
    return parity


@obs_jit(static_argnames=("sim_size",))
def _sim_rows(key, lo, hi, sim_size: int):
    """One partition's simulation samples, regenerated from its key."""
    from fairify_tpu.ops import simulate as sim_ops

    return sim_ops.simulate_box(key, lo, hi, sim_size)


def _parity_resim(weights, biases, dead, key, lo_p, hi_p, sim_size: int) -> float:
    """Pruned-vs-original parity for ONE partition whose masks changed after
    the batched parity pass (heuristic retry).  A single tiny launch whose
    result is needed immediately by this row's CSV — the sanctioned
    synchronous fetch outside the pipeline's drain API."""
    sim_p = np.asarray(_sim_rows(
        key, jnp.asarray(lo_p, jnp.float32), jnp.asarray(hi_p, jnp.float32),
        sim_size))
    return float((
        mlp_mod.predict_np(weights, biases, sim_p)
        == mlp_mod.predict_np(weights, biases, sim_p, dead=dead)
    ).mean())


def _c_check_np(weights, biases, dead, ce) -> tuple:
    """C-check / V-accurate replay (``src/GC/Verify-GC.py:225-250``), host-side.

    Two points through two tiny nets — numpy, not a device round-trip.
    """
    pts = np.stack(ce)
    orig_cls = mlp_mod.predict_np(weights, biases, pts)
    pruned_cls = mlp_mod.predict_np(weights, biases, pts, dead=dead)
    v_accurate = int(orig_cls[0] != orig_cls[1])
    c_check = int((pruned_cls == orig_cls).all())
    return c_check, v_accurate


def _ledger_ce(ce) -> Optional[tuple]:
    """Counterexample pair from a ledger record's JSON lists (host data —
    no device arrays anywhere near this path)."""
    if not ce:
        return None
    return tuple(np.asarray(c, dtype=np.int64) for c in ce)


def _ledger_path(cfg: SweepConfig, model_name: str) -> str:
    return os.path.join(cfg.result_dir, f"{cfg.name}-{model_name}.ledger.jsonl")


def _read_ledger(path: str):
    """One ledger file's records in file order, plus the torn-line count.

    Same tolerant JSONL loader as the obs event log (ONE implementation,
    ``obs.load_events``): truncated/undecodable lines — a crash
    mid-append, a network FS tearing a write — are skipped but COUNTED; a
    resume that silently dropped records would under-report exactly when
    it matters most.

    Rows carrying a ``_crc`` (written when ``cfg.integrity`` is on) are
    verified against the canonical body (``resilience.integrity``); a
    mismatch — a bit flipped at rest or in the append path, NOT a torn
    line — drops the row and bumps ``ledger_crc_mismatch``, so the pid is
    simply un-ledgered and the decided-wins resume re-attempts it: a
    corrupted verdict is never replayed (DESIGN.md §21).
    """
    if not os.path.isfile(path):
        return [], 0
    recs, skipped = obs.load_events(path, count_skipped=True)
    recs, bad = integrity_mod.verify_records(recs)
    if bad:
        obs.registry().counter("ledger_crc_mismatch").inc(bad)
        obs.event("ledger_crc_mismatch", path=path, rows=bad)
    return recs, skipped


def merge_ledgers(paths) -> tuple:
    """Decided-wins merge of one or more ledger files.

    Promoted from the script layer (``scripts/deep_retry_variants.py`` /
    ``_sweeplib.merge_span_ledgers``) so resume-after-fault is a library
    guarantee with ONE merge semantics:

    * a partition any file records as **decided** stays decided — a later
      file's (or a later line's) budget-cut ``unknown`` never demotes it;
    * an ``unknown`` carrying a ``failure`` record (a fault-degraded
      chunk) is **not settled** — resume re-attempts it;
    * among plain unknowns and degradations, the last record wins (a
      resumed run that re-attempts a degraded partition and hits a genuine
      budget UNKNOWN settles it).

    Returns ``(done, degraded, skipped_lines)``: settled pid → record,
    degraded pid → record, torn-line count.
    """
    done: Dict[int, dict] = {}
    degraded: Dict[int, dict] = {}
    skipped = 0
    for path in paths:
        recs, sk = _read_ledger(path)
        skipped += sk
        for rec in recs:
            pid = rec["partition_id"]
            prev = done.get(pid)
            if rec["verdict"] != "unknown":
                done[pid] = rec
                degraded.pop(pid, None)
            elif prev is not None and prev["verdict"] != "unknown":
                continue  # decided-wins
            elif rec.get("failure"):
                degraded[pid] = rec
                done.pop(pid, None)
            else:
                done[pid] = rec
                degraded.pop(pid, None)
    return done, degraded, skipped


def _load_ledger(path: str) -> Dict[int, dict]:
    """Partition-id → record map for one ledger (decided-wins merge).

    Fault-degraded records are included (their verdict is ``unknown``), so
    script-layer consumers that bucket on ``verdict`` treat them as
    retryable — only :func:`verify_model`'s resume distinguishes them.
    """
    done, degraded, _skipped = merge_ledgers([path])
    done.update(degraded)
    return done


def verify_model(
    net,
    cfg: SweepConfig,
    model_name: str = "model",
    dataset: Optional[loaders.LoadedDataset] = None,
    mesh=None,
    resume: bool = True,
    retry_unknown: bool = False,
    stage0=None,
    partition_span=None,
    host_index=None,
    host_count=None,
    sink_name=None,
    smt_pool=None,
    smt_defer: bool = False,
) -> ModelReport:
    """Run the full sweep for one model; write CSV + ledger rows as we go.

    ``cfg.trace_out`` activates the obs span tracer for this call unless an
    outer scope (CLI ``--trace-out``, ``run_sweep``) already owns one; the
    model-level span carries the final verdict counts as attributes.

    ``sink_name`` overrides the derived result-sink base name (normally
    ``model`` or span-qualified ``model@start-stop``): the shard runtime
    (:mod:`fairify_tpu.parallel.shards`) pins every re-dispatch of a failed
    shard's partitions to the INITIAL shard's journal, so a span keeps one
    ledger across elastic re-shards.

    ``smt_pool`` shares an existing :class:`fairify_tpu.smt.SmtPool` for
    the UNKNOWN-retry SMT tier (the serve stack's server-wide pool);
    None = the run owns a pool sized from ``cfg.smt_workers`` for exactly
    this call (created only if the tier has candidates).  ``smt_defer``
    makes the SMT phase non-blocking: in-flight queries come back on
    ``report.smt_pending`` (an :class:`SmtDrain`) instead of stalling the
    reporting loop — the serve worker's contract.
    """
    from fairify_tpu.obs import heartbeat as hb_mod

    with obs.maybe_tracing(cfg.trace_out, run_id=f"{cfg.name}-{model_name}"):
        with obs.span("verify_model", model=model_name, dataset=cfg.dataset,
                      preset=cfg.name) as sp, \
                faults_mod.armed(cfg.inject_faults, seed=cfg.seed):
            try:
                rep = _verify_model_impl(
                    net, cfg, model_name, dataset, mesh, resume, retry_unknown,
                    stage0, partition_span, host_index, host_count, sink_name,
                    smt_pool, smt_defer)
            except BaseException:
                # The impl registers this run's heartbeat as the live one
                # (compile flags); a raise would otherwise leak it, and
                # later runs' compiles would print against the dead label.
                hb = hb_mod.active()
                if hb is not None:
                    hb.close()
                raise
            sp.set(partitions=rep.partitions_total, **rep.counts)
            if rep.degraded:
                sp.set(degraded=rep.degraded)
            return rep


def _verify_model_impl(
    net,
    cfg: SweepConfig,
    model_name: str,
    dataset: Optional[loaders.LoadedDataset],
    mesh,
    resume: bool,
    retry_unknown: bool,
    stage0,
    partition_span,
    host_index,
    host_count,
    sink_override,
    smt_pool=None,
    smt_defer: bool = False,
) -> ModelReport:
    from fairify_tpu.utils.cache import enable_persistent_cache

    enable_persistent_cache()
    timer = PhaseTimer()
    query = cfg.query()
    enc = encode(query)
    p_list, lo, hi = build_partitions(cfg)
    span_start = 0
    sink_name = model_name
    if host_count is not None and partition_span is None:
        from fairify_tpu.parallel.multihost import host_slice

        partition_span = host_slice(len(p_list), host_index, host_count)
    if partition_span is not None:
        # Multi-host sweeps hand each host a contiguous slice of the global
        # grid (parallel.multihost.host_slice).  Partition ids and the
        # pruning/simulation PRNG keys are global, so masks and every
        # *decided* verdict are host-assignment invariant; the stage-0
        # attack streams are span-relative, so only the SAT-vs-UNKNOWN
        # frontier of undecidable partitions may shift with the host count.
        span_start, span_stop = partition_span
        p_list = p_list[span_start:span_stop]
        lo, hi = lo[span_start:span_stop], hi[span_start:span_stop]
        # Hosts may share result_dir (network fs): qualify sinks by span so
        # concurrent appends never interleave.
        sink_name = f"{model_name}@{span_start}-{span_stop}"
    if sink_override is not None:
        sink_name = sink_override
    P = len(p_list)
    if P == 0:  # e.g. more hosts than partitions — an empty but valid span
        return ModelReport(model=model_name, dataset=cfg.dataset, outcomes=[],
                           partitions_total=0, sink_name=sink_name)

    os.makedirs(cfg.result_dir, exist_ok=True)
    ledger_path = _ledger_path(cfg, sink_name)
    led_skipped = 0
    if resume:
        # Decided-wins merge (library guarantee, not script lore): decided
        # verdicts stay settled; fault-degraded UNKNOWNs (records with a
        # `failure` reason) are NOT settled — this resume re-attempts them.
        done, _degraded_prev, led_skipped = merge_ledgers([ledger_path])
        if led_skipped:
            import sys

            print(f"warning: skipped {led_skipped} torn/undecodable ledger "
                  f"line(s) in {ledger_path} (crash mid-append)",
                  file=sys.stderr)
    else:
        done = {}
    if retry_unknown:
        # Re-attempt budget-exhausted partitions (e.g. with a larger soft
        # timeout); decided verdicts stay settled.  The re-decided rows are
        # re-appended to the ledger, and the decided-wins merge makes the
        # retry the record of truth on the next resume.
        done = {pid: rec for pid, rec in done.items()
                if rec["verdict"] != "unknown"}
    csv_path = os.path.join(cfg.result_dir, f"{sink_name}.csv")

    from fairify_tpu.utils.profiling import ThroughputCounter, xla_trace

    counter = ThroughputCounter(n_devices=1 if mesh is None else int(np.prod(list(mesh.shape.values()))))
    # Verification-funnel telemetry (obs.funnel, DESIGN.md §20): the run's
    # certified-margin / attack-gap histograms (device-carried on the mega
    # path) and the per-partition terminal-state tally behind the one
    # ``funnel`` event + ``decided_fraction`` this report ships.
    stage_stats = funnel_mod.StageStats()
    funnel = funnel_mod.FunnelCounts()
    launch0 = profiling.launch_count()
    compile0 = compile_obs.snapshot_totals()
    # Integrity baseline totals (process-global counters): the throughput
    # record reports this RUN's deltas so perfdiff can gate them at zero
    # growth without a registry reset between models.
    integrity0 = {
        name: obs.registry().counter(name).total()
        for name in ("integrity_violations", "integrity_rechecks",
                     "ledger_crc_mismatch")}
    heartbeat = obs.Heartbeat(cfg.heartbeat_s, total=P, label=sink_name) \
        if cfg.heartbeat_s > 0 else None
    # One launch pipeline for the whole run: the stage-0 certify, parity
    # and deep-PGD chunk loops all share it, so its lifetime stats (max +
    # time-weighted mean launches in flight) are the run's overlap record
    # (dumped in the throughput JSON next to device_launches).  The
    # attached supervisor retries transient launch faults; exhaustion
    # degrades exactly the affected chunk's partitions to UNKNOWN-with-
    # reason (recorded in `failed`, ledgered below) and the sweep goes on.
    sup = _supervisor(cfg)
    pipe = LaunchPipeline(cfg.pipeline_depth, supervisor=sup)
    failed: Dict[int, dict] = {}  # local partition index -> failure record

    def _degrade(idxs, failure: ChunkFailure, phase: str) -> None:
        rec = failure.to_record()
        n_new = 0
        for i in idxs:
            if 0 <= i < P and i not in failed:
                failed[i] = rec
                n_new += 1
        if n_new:  # a chunk already degraded in an earlier phase counts once
            obs.registry().counter("chunks_degraded").inc(site=failure.site)
            obs.event("degraded", **rec, phase=phase, partitions=n_new)

    with xla_trace(cfg.profile_dir):
        with obs.timed_span(timer, "stage0_prune", partitions=P):
            try:
                prune = sup.run(lambda: pruning.sound_prune_grid(
                    net, lo, hi, cfg.sim_size, cfg.seed,
                    exact_certify=cfg.exact_certify_masks, chunk=cfg.grid_chunk,
                    index_offset=span_start, keep_sim=False,
                    # One switch for the whole run's launch structure: the
                    # prune pass segments its chunks exactly when stage 0
                    # does (DESIGN.md §17), so launches stay O(segments).
                    mega_chunks=cfg.mega_chunks if _use_mega(cfg, mesh)
                    else 0,
                ), site="prune")
            except ChunkDegraded as exc:
                # Pruning feeds only mask-derived REPORTING (compression
                # columns, pruned_acc parity, the heuristic retry) — no
                # verdict depends on it.  Losing it degrades nothing:
                # stage 0 / PGD / BaB all proceed, the mask columns read
                # zero, and only the UNKNOWN-improving heuristic retry is
                # skipped.  A genuinely sick device will fault again in
                # stage 0 and degrade there, chunk by chunk.
                prune = None
                obs.registry().counter("chunks_degraded").inc(
                    site=exc.failure.site)
                obs.event("degraded", **exc.failure.to_record(),
                          phase="stage0_prune", partitions=0)
        with obs.timed_span(timer, "stage0_decide", partitions=P) as sp0:
            if stage0 is not None:  # precomputed by the stacked family kernel
                if len(stage0) == 4:  # family path forwarded its StageStats
                    unsat0, sat0, witnesses, pre_stats = stage0
                    stage_stats.merge(pre_stats)
                else:  # serve's 3-tuple slices carry no histograms
                    unsat0, sat0, witnesses = stage0
                sp0.set(precomputed=True)
            else:
                unsat0, sat0, witnesses = _stage0_certify_and_attack(
                    net, enc, lo, hi, cfg, mesh=mesh, seed_offset=span_start,
                    pipe=pipe, stats=stage_stats,
                    on_failure=lambda s, e, f: _degrade(range(s, e), f,
                                                        "stage0_decide"))
            sp0.set(unsat=int(unsat0.sum()), sat=int(sat0.sum()))
        with obs.timed_span(timer, "stage0_parity"):
            step, spans = _chunk_spans(P, cfg.grid_chunk)
            parity = np.zeros(P, dtype=np.float32)

            def _parity_submit(s, e):
                # Hidden layers only: the final layer is never pruned and
                # the kernel rebuilds its all-ones mask from the net.
                alive = tuple(
                    jnp.asarray(_pad_rows(1.0 - d[s:e], step), jnp.float32)
                    for d in prune.st_deads[:-1])
                keys = pruning.grid_keys(cfg.seed, span_start + s, step)
                profiling.bump_launch()
                block = _parity_grid_from_keys(
                    net, keys,
                    jnp.asarray(_pad_rows(lo[s:e], step), jnp.float32),
                    jnp.asarray(_pad_rows(hi[s:e], step), jnp.float32),
                    alive, cfg.sim_size)
                return block, None

            def _parity_consume(meta, _ctx, host):
                s, e = meta
                if isinstance(host, ChunkFailure):
                    # The parity kernel feeds only the pruned_acc CSV
                    # column, never a verdict — partitions stage 0 already
                    # decided keep their sound SAT/UNSAT (pruned_acc reads
                    # 0.0 for the lost chunk); only still-undecided ones
                    # degrade, since their remaining path shares the sick
                    # device anyway.
                    _degrade([i for i in range(s, e)
                              if not sat0[i] and not unsat0[i]],
                             host, "stage0_parity")
                    return
                parity[s:e] = np.asarray(host)[: e - s]

            def _mega_parity_submit(chunks, pad_chunks=0):
                keys_c, lo_c, hi_c = [], [], []
                alive_c = [[] for _ in prune.st_deads[:-1]]
                for s, e in _pad_chunk_axis(chunks, pad_chunks):
                    for buf, d in zip(alive_c, prune.st_deads[:-1]):
                        buf.append(_pad_rows(1.0 - d[s:e],
                                             step).astype(np.float32))
                    keys_c.append(pruning.grid_keys(cfg.seed,
                                                    span_start + s, step))
                    lo_c.append(_pad_rows(lo[s:e], step).astype(np.float32))
                    hi_c.append(_pad_rows(hi[s:e], step).astype(np.float32))
                profiling.bump_launch()
                block = _mega_parity_kernel(
                    net, jnp.stack(keys_c),
                    jnp.asarray(np.stack(lo_c)), jnp.asarray(np.stack(hi_c)),
                    tuple(jnp.asarray(np.stack(b)) for b in alive_c),
                    cfg.sim_size)
                return block, chunks

            def _mega_parity_consume(meta, ctx, host):
                seg_s, seg_e, chunks = meta
                if isinstance(host, ChunkFailure):
                    _degrade([i for i in range(seg_s, seg_e)
                              if not sat0[i] and not unsat0[i]],
                             host, "stage0_parity")
                    return
                block = np.asarray(host)
                for ci, (s, e) in enumerate(chunks):
                    parity[s:e] = block[ci, : e - s]

            if _use_mega(cfg, mesh) and prune is not None:
                # Segment-granular parity launches (DESIGN.md §17): the
                # parity pass shares the stage-0 segment spans so a model's
                # launch count stays O(segments) end to end, and a fault
                # here degrades (still-undecided partitions of) exactly one
                # segment, same as the chunk loop's per-chunk radius.
                _, psegs = _segment_spans(P, cfg.grid_chunk, cfg.mega_chunks)
                pbucket = cfg.mega_chunks if len(psegs) > 1 else 0
                for seg_s, seg_e, chunks in psegs:
                    for item in pipe.submit(
                            lambda chunks=chunks: _mega_parity_submit(
                                chunks, pad_chunks=pbucket),
                            meta=(seg_s, seg_e, chunks)):
                        _mega_parity_consume(*item)
                for item in pipe.drain():
                    _mega_parity_consume(*item)
            else:
                for s, e in (spans if prune is not None else ()):
                    for item in pipe.submit(
                            lambda s=s, e=e: _parity_submit(s, e),
                            meta=(s, e)):
                        _parity_consume(*item)
                for item in pipe.drain():
                    _parity_consume(*item)
        stage0_per_part = 0.0  # finalized (incl. the PGD phase) below

        outcomes: List[PartitionOutcome] = []
        sat_count = unsat_count = unk_count = degraded_count = 0
        weights = [np.asarray(w) for w in net.weights]
        biases = [np.asarray(b) for b in net.biases]

        # Batched refinement: every stage-0 leftover shares one BaB frontier
        # (engine.decide_many).  The hard budget caps the phase after stage-0
        # spend — the reference's cumulative-break semantics
        # (``src/GC/Verify-GC.py:312-314``) applied to actual solver work;
        # verdicts already computed are always reported (the reporting loop
        # itself is cheap and never discards work).
        pending = [p for p in range(P)
                   if (span_start + p + 1) not in done
                   and not sat0[p] and not unsat0[p] and p not in failed]
        # Gradient attack on the stage-0 leftovers: counterexamples the
        # random sampler misses (logit zero-crossings on thin slabs) are
        # found by batched PGD in one jit, sparing those roots the BaB tree.
        pgd_covered_all = False  # every pending root got the deep PGD pass
        if pending:
            with obs.timed_span(timer, "stage0_pgd", pending=len(pending)):
                pgd_wit = {}
                pgd_covered_all = True
                # The slab refinement below is serial host work (exact
                # arithmetic per seed); on hard models with thousands of
                # near-zero boxes it would otherwise dwarf the hard budget
                # (observed: ~1 h on AC-11's 16k-partition grid).  Cap the
                # slab-only time (PGD/jit excluded) at a quarter of the
                # remaining budget — skipped boxes keep their BaB/unknown
                # path, so only SAT-discovery opportunity is traded, never
                # soundness.  Like every budget-bound path here, which boxes
                # get refined is wall-clock dependent when the cap binds;
                # decided verdicts stay ground-truth-checked either way.
                slab_budget = 0.25 * max(cfg.hard_timeout_s - timer.total(), 0.0)
                slab_spent = 0.0
                step = min(cfg.grid_chunk, len(pending)) if cfg.grid_chunk > 0 \
                    else len(pending)

                def _pgd_consume(meta, ctx, host):
                    nonlocal slab_spent
                    s, blk = meta
                    if isinstance(host, ChunkFailure):
                        _degrade(blk, host, "stage0_pgd")
                        return
                    w, near_zero, near_abs = engine.pgd_attack_decode(
                        host, ctx, return_points=True)
                    pgd_wit.update({s + k: v for k, v in w.items()})
                    # Exact flip-slab refinement from the PGD near-zero seeds:
                    # finds the measure-tiny SAT slabs f32 attacks cannot
                    # resolve (wide domains like default-credit).  Gated on
                    # PGD having actually reached the zero-crossing region —
                    # boxes whose best |logit| stays large have no slab to
                    # refine, and skipping them keeps this host-side pass off
                    # the narrow-domain hot path.  Serial exact arithmetic —
                    # exactly the host work the pipeline overlaps with the
                    # next chunk's in-flight PGD kernel.
                    seed_rng = np.random.default_rng(cfg.engine.seed + 77 + span_start + s)
                    for k in range(len(blk)):
                        if slab_spent > slab_budget:
                            break
                        if (s + k) in pgd_wit or near_abs[k] > 50.0:
                            continue
                        p_g = blk[k]
                        # Seed diversity matters: each start lands in a
                        # different activation region, and regions differ in
                        # whether their slab contains a lattice point.
                        seeds = [near_zero[k], (lo[p_g] + hi[p_g]) / 2.0]
                        seeds += [seed_rng.integers(lo[p_g], hi[p_g] + 1)
                                  for _ in range(6)]
                        t_slab = time.perf_counter()
                        for seed_pt in seeds:
                            ce = engine.slab_search(
                                weights, biases, enc, lo[p_g], hi[p_g], seed_pt)
                            if ce is not None:
                                pgd_wit[s + k] = ce
                                break
                        slab_spent += time.perf_counter() - t_slab

                for s in range(0, len(pending), step):
                    if timer.total() > cfg.hard_timeout_s:
                        # Budget honesty: leftovers keep their BaB path, and
                        # decide_many must NOT be told they were attacked.
                        # Blocks already in flight are committed device work
                        # and drain below — they WERE attacked.
                        pgd_covered_all = False
                        break
                    blk = pending[s:s + step]
                    # Deep settings (Phase-A depth, engine.EngineConfig
                    # pgd_steps/pgd_restarts): this is THE attack pass for
                    # these roots — decide_many is told attacked=True below
                    # and skips its Phase A re-launch (VERDICT r5 #1).
                    for item in pipe.submit(
                            lambda s=s, blk=blk: engine.pgd_attack_submit(
                                net, enc, lo[blk], hi[blk],
                                np.random.default_rng(
                                    cfg.engine.seed + 1 + span_start + s),
                                steps=cfg.engine.pgd_steps,
                                restarts=cfg.engine.pgd_restarts),
                            meta=(s, blk)):
                        _pgd_consume(*item)
                for item in pipe.drain():
                    _pgd_consume(*item)
            for i, ce in pgd_wit.items():
                p = pending[i]
                sat0[p] = True
                witnesses[p] = ce
            pending = [p for p in pending if not sat0[p] and p not in failed]
        stage0_per_part = sum(
            timer.get(ph) for ph in
            ("stage0_prune", "stage0_decide", "stage0_parity", "stage0_pgd")
        ) / max(P, 1)
        bab: Dict[int, engine.Decision] = {}
        if pending:
            hard_left = max(cfg.hard_timeout_s - timer.total(), 1.0)
            deadline = min(cfg.soft_timeout_s * len(pending), hard_left)
            with obs.timed_span(timer, "bab", roots=len(pending),
                                deadline_s=round(deadline, 3)):
                try:
                    decisions = engine.decide_many(
                        net, enc, lo[pending], hi[pending],
                        replace(cfg.engine, pipeline_depth=cfg.pipeline_depth,
                                max_launch_retries=cfg.max_launch_retries,
                                launch_backoff_s=cfg.launch_backoff_s,
                                device_bab=(cfg.device_bab
                                            and cfg.engine.device_bab),
                                integrity=(cfg.integrity
                                           and cfg.engine.integrity)),
                        deadline_s=deadline, mesh=mesh,
                        attacked=pgd_covered_all,
                    )
                except BaseException as exc:
                    # The engine's pipelined Phase A degrades per chunk on
                    # its own; a fault escaping the sequential BaB phases
                    # has no finer-grained blast radius than the batch —
                    # degrade every pending root and keep the run alive
                    # (re-running the whole batch would multiply its
                    # deadline, so faults here get no whole-batch retry:
                    # a transient is 'exhausted' at zero retries).
                    if classify(exc) == "propagate":
                        raise
                    _degrade(pending, _unretried_failure("bab", exc), "bab")
                    decisions = []
                    pending = []
            bab = dict(zip(pending, decisions))
            # Per-phase attribution (VERDICT r3): where inside the engine
            # ladder the BaB seconds went, summed over roots — S (sign
            # frontier) / L (sign-phase host LP) / bab (input split) /
            # P (pair LP) / E (lattice).  Lands in the throughput record
            # (raw floats; rounding happens at serialization).
            for ph in ("t_attack", "t_sign", "t_lp", "t_bab", "t_pair",
                       "t_lattice"):
                tot = sum(d.stats.get(ph, 0.0) for d in decisions)
                if tot > 0.0:
                    timer.phases[f"engine_{ph[2:]}"] = tot
    # Out-of-process SMT second opinions (fairify_tpu/smt, DESIGN.md §14):
    # every root still unknown after BaB fans its serialized query out
    # across the worker pool NOW, so host solving runs in parallel with
    # the reporting loop below (and, under a shared serve pool, with other
    # requests' device work).  This tier is the sweep's ONLY road to a
    # native solver — nothing in-process can wedge or crash the run.
    smt_tier: Optional[_SmtTier] = None
    smt_deferred_items: List = []
    smt_transfer = False
    if cfg.smt_retry_timeouts_s and timer.total() <= cfg.hard_timeout_s:
        smt_candidates = [p for p, d in bab.items()
                          if d.verdict == "unknown" and p not in failed]
        if smt_candidates:
            with obs.timed_span(timer, "smt_fanout",
                                queries=len(smt_candidates)):
                smt_tier = _SmtTier(net, enc, lo, hi, smt_candidates, cfg,
                                    pool=smt_pool)
    cumulative = timer.total()

    orig_acc = 0.0
    pm = None  # per-partition group-metric sink (src/CP/Verify-CP.py:398-458)
    if dataset is not None:
        pred = np.asarray(mlp_mod.predict(net, jnp.asarray(dataset.X_test, jnp.float32)))
        orig_acc = float((pred.astype(int) == dataset.y_test).mean())
        if cfg.partition_metrics and len(enc.pa_idx):
            from fairify_tpu.analysis import metrics as gm

            pm = {
                "path": os.path.join(cfg.result_dir,
                                     f"{sink_name}-metrics.csv"),
                "X": np.asarray(dataset.X_test, dtype=np.float64),
                "y": np.asarray(dataset.y_test).astype(int),
                # Reference semantics: the protected column of the TEST
                # matrix, privileged value 1 (``src/CP/Verify-CP.py:
                # 402-417``); multi-PA queries use the first PA dim.
                "prot": np.asarray(dataset.X_test)[:, int(enc.pa_idx[0])],
                "orig_f1": gm.f1_score(dataset.y_test, pred.astype(int)),
                "gm": gm,
            }

    # Atomic + fsync'd appends (resilience.journal): one OS write per
    # record, synced before the next partition is attempted — the strongest
    # crash-resume story a JSONL ledger can give.  Appends are supervised:
    # a transient filesystem error is retried; exhaustion is counted
    # (`ledger_append_failures`) and the sweep continues — the verdict
    # stays in this report, and a later resume re-decides it (sound).
    try:
        ledger = JournalWriter(ledger_path, fault_site="ledger.append",
                               supervisor=sup, crc=cfg.integrity)
        for p in range(P):
            pid = span_start + p + 1
            if pid in done:
                rec = done[pid]
                ce = rec.get("ce")
                out = PartitionOutcome(pid, rec["verdict"],
                                       counterexample=_ledger_ce(ce))
                outcomes.append(out)
                counts = {"sat": sat_count, "unsat": unsat_count, "unknown": unk_count}
                counts[rec["verdict"]] += 1
                sat_count, unsat_count, unk_count = counts["sat"], counts["unsat"], counts["unknown"]
                obs.event("verdict", model=model_name, partition_id=pid,
                          verdict=rec["verdict"], via="ledger")
                # Replayed rows don't record their original provenance tier;
                # via="ledger" classifies decided verdicts into the BaB
                # buckets (best effort — fresh runs, where the bit-invariance
                # contract applies, never take this branch).
                funnel.add(funnel_mod.classify(
                    rec["verdict"], "ledger",
                    failure=(rec.get("failure") or {}).get("reason")))
                if heartbeat is not None:
                    heartbeat.beat(decided=sat_count + unsat_count,
                                   attempted=len(outcomes), unknown=unk_count)
                continue
            t_part = time.perf_counter()
            fail_rec = failed.get(p)
            dead = pruning.partition_masks(prune, p) if prune is not None else None

            h_attempt = h_success = 0
            smt_decided = False
            smt_unknown_reason = None
            smt_deferred_this = False
            sv_time = hv_time = h_time = 0.0
            ce = None
            nodes = 0
            if fail_rec is not None:
                # A runtime fault degraded this partition's chunk: UNKNOWN with
                # a machine-readable reason, never a wrong answer — the row is
                # ledgered with the failure record and re-attempted on resume.
                verdict = "unknown"
            elif sat0[p]:
                verdict, ce = "sat", witnesses[p]
            elif unsat0[p]:
                verdict = "unsat"
            else:
                dec = bab[p]
                sv_time = dec.elapsed_s  # per-root attributed cost (engine.decide_many)
                nodes = dec.nodes
                verdict, ce = dec.verdict, dec.counterexample
                if verdict == "unknown" and prune is not None \
                        and cumulative <= cfg.hard_timeout_s:
                    # Heuristic retry: kill borderline-quiet neurons, re-decide on
                    # the masked net (``src/GC/Verify-GC.py:172-211``).
                    h_attempt = 1
                    obs.registry().counter("unknown_retries").inc()
                    t_h = time.perf_counter()
                    try:
                        h_dead, merged = heur_ops.heuristic_prune(
                            [l[p] for l in prune.ws_lb], [l[p] for l in prune.ws_ub],
                            [l[p] for l in prune.candidates], [l[p] for l in prune.surviving],
                            dead, cfg.heuristic_threshold,
                        )
                        h_net = mask_ops.apply_dead_masks(net, [jnp.asarray(d) for d in merged])
                        dec2 = engine.decide_box(
                            h_net, enc, lo[p], hi[p],
                            replace(cfg.engine, soft_timeout_s=cfg.soft_timeout_s),
                        )
                    except BaseException as exc:
                        # A fault in the retry only loses the retry: the root's
                        # verdict stays the (sound) UNKNOWN it already has.
                        if classify(exc) == "propagate":
                            raise
                        _degrade([p], _unretried_failure("bab", exc),
                                 "heuristic_retry")
                        fail_rec = failed.get(p)
                        h_time = time.perf_counter() - t_h
                    else:
                        hv_time = dec2.elapsed_s
                        h_time = time.perf_counter() - t_h
                        nodes += dec2.nodes
                        if dec2.verdict != "unknown":
                            h_success = 1
                            verdict, ce = dec2.verdict, dec2.counterexample
                            # A SAT from the unsoundly-pruned net must replay on the
                            # original to count (the reference's V-accurate check).
                            if verdict == "sat" and not engine.validate_pair(weights, biases, *ce):
                                verdict, ce = "unknown", None
                                h_success = 0
                        dead = merged
                if smt_tier is not None and p in smt_tier:
                    if verdict != "unknown" or fail_rec is not None \
                            or cumulative > cfg.hard_timeout_s:
                        # The heuristic retry decided it (or its chunk
                        # degraded / the budget tripped): the prefetched
                        # query's answer is no longer needed — cancel, never
                        # await.
                        smt_tier.cancel(p)
                    elif smt_defer and not smt_tier.done(p):
                        # Non-blocking serve mode: the answer is still
                        # solving out of process — report a provisional
                        # UNKNOWN whose ledger row is WITHHELD, and let
                        # the SmtDrain attached to the report finish it
                        # off the device thread.
                        smt_deferred_this = True
                    else:
                        # Last tier of the UNKNOWN-retry ladder (opt-in via
                        # cfg.smt_retry_timeouts_s): the out-of-process worker
                        # pool's second opinion on the ORIGINAL net with the
                        # escalating per-attempt timeout ladder — the
                        # reference's re-run-with-a-larger-argv-soft-timeout
                        # escalation (src/GC/Verify-GC.py:146-149), prefetched
                        # in parallel right after BaB (_SmtTier).  Worker
                        # faults come back as UNKNOWN-with-reason, never a
                        # crashed run (DESIGN.md §14).
                        smt_verdict, smt_ce, smt_reason = smt_tier.result(p)
                        if smt_verdict == "sat" and smt_ce is not None \
                                and not engine.validate_pair(weights, biases,
                                                             *smt_ce):
                            # An out-of-process witness must replay on the host
                            # net to count (the same V-accurate rule the
                            # heuristic retry obeys): a sound backend never
                            # fails this — only a corrupted reply does, so
                            # the miss is an INTEGRITY violation, not a
                            # plain unknown: the partition degrades with a
                            # failure record (re-attempted on resume, so
                            # the fault-free answer is recovered) instead
                            # of settling as an unledgerable maybe.
                            smt_verdict, smt_ce, smt_reason = \
                                "unknown", None, "invalid-witness"
                            _degrade([p], _integrity_failure(
                                "smt.query", "invalid-witness"), "smt")
                            fail_rec = failed.get(p)
                        if smt_verdict != "unknown":
                            verdict, ce = smt_verdict, smt_ce
                            smt_decided = True
                            if verdict == "unsat" and cfg.integrity \
                                    and integrity_mod.sampled(
                                        cfg.seed, f"smt:{pid}",
                                        cfg.integrity_recheck):
                                # Sampled cross-check of SMT UNSATs: SAT
                                # witnesses already replay above, but an
                                # UNSAT crossing the pool boundary had no
                                # independent check until the exact-
                                # rational oracle (DESIGN.md §21).
                                obs.registry().counter(
                                    "integrity_rechecks").inc(kind="smt")
                                from fairify_tpu.verify import exact_check

                                xres = exact_check.decide_pair_box_exact(
                                    weights, biases, enc, lo[p], hi[p],
                                    max_nodes=2000)
                                if xres["verdict"] == "refuted":
                                    verdict, ce = "unknown", None
                                    smt_decided = False
                                    _degrade([p], _integrity_failure(
                                        "exact", "refuted-smt-unsat"), "smt")
                                    fail_rec = failed.get(p)
                        elif smt_reason is not None \
                                and smt_reason.startswith("smt.worker:"):
                            # Worker-death exhaustion degrades EXACTLY this
                            # partition: a machine-readable failure record in
                            # the ledger, re-attempted by resume=True.
                            _degrade([p], ChunkFailure(
                                site="smt.worker",
                                kind=smt_reason.split(":", 1)[1],
                                error="WorkerDied", detail=smt_reason,
                                retries=cfg.max_launch_retries), "smt")
                            fail_rec = failed.get(p)
                        else:
                            smt_unknown_reason = smt_reason

            c_check = v_accurate = 0
            if verdict == "sat" and ce is not None and dead is not None:
                # dead is None only when pruning itself degraded — a C-check
                # against a nonexistent pruned net would trivially "pass";
                # report 0, consistent with the zeroed compression columns.
                c_check, v_accurate = _c_check_np(weights, biases, dead, ce)
            if h_attempt and fail_rec is None:  # masks changed after parity pass
                pruned_acc = _parity_resim(
                    weights, biases, dead,
                    pruning.grid_keys(cfg.seed, span_start + p, 1)[0],
                    lo[p], hi[p], cfg.sim_size)
            else:
                pruned_acc = float(parity[p])

            if verdict == "sat":
                sat_count += 1
            elif verdict == "unsat":
                unsat_count += 1
            else:
                unk_count += 1
            if fail_rec is not None:
                degraded_count += 1
            counter.record(verdict, via_stage0=bool(sat0[p] or unsat0[p]))
            if h_success:
                obs.registry().counter("unknown_retry_success").inc()
            extra = {"failure": fail_rec["reason"]} if fail_rec is not None else {}
            if smt_unknown_reason is not None:
                extra["smt_reason"] = smt_unknown_reason
            if verdict == "unknown" and fail_rec is None and p in bab \
                    and bab[p].reason is not None:
                # Budget-vs-hardness attribution for the event log: did
                # the engine run out of deadline or out of ideas?
                extra["engine_reason"] = bab[p].reason
            via = ("degraded" if fail_rec is not None
                   else "stage0" if (sat0[p] or unsat0[p])
                   else "smt" if smt_decided
                   else ("heuristic" if h_success else "bab"))
            obs.event("verdict", model=model_name, partition_id=pid,
                      verdict=verdict, via=via, **extra)
            # Terminal funnel state (obs.funnel, DESIGN.md §20).  An SMT-
            # deferred partition is tallied at its provisional UNKNOWN; the
            # SmtDrain's superseding verdict event carries the final state
            # for trace-log consumers (report --funnel dedups last-wins).
            funnel.add(funnel_mod.classify(
                verdict, via,
                failure=fail_rec["reason"] if fail_rec is not None else None,
                engine_reason=extra.get("engine_reason")))

            # Per-row accounting: amortized stage-0 share + this row's attributed
            # BaB cost (sv_time) + its own loop work (heuristic retry, replay).
            total_time = stage0_per_part + sv_time + (time.perf_counter() - t_part)
            cumulative += time.perf_counter() - t_part
            obs.registry().histogram("partition_latency_s").observe(total_time)
            if prune is not None:
                comp = {
                    "b": mask_ops.compression_ratio([l[p] for l in prune.b_deads]),
                    "s": mask_ops.compression_ratio([l[p] for l in prune.s_deads]),
                    "st": mask_ops.compression_ratio([l[p] for l in prune.st_deads]),
                    "h": mask_ops.compression_ratio(dead) if h_attempt else 0.0,
                    "t": mask_ops.compression_ratio(dead),
                }
            else:  # pruning itself degraded — no masks exist for this span
                comp = {"b": 0.0, "s": 0.0, "st": 0.0, "h": 0.0, "t": 0.0}
            out = PartitionOutcome(
                pid, verdict, ce, h_attempt, h_success, nodes,
                times={"sv": sv_time, "s": stage0_per_part + sv_time, "hv": hv_time,
                       "h": h_time, "total": total_time},
                compressions=comp, c_check=c_check, v_accurate=v_accurate,
                pruned_acc=pruned_acc,
            )
            outcomes.append(out)
            if smt_deferred_this:
                smt_deferred_items.append((p, pid, out))
            if heartbeat is not None:
                heartbeat.beat(decided=sat_count + unsat_count,
                               attempted=len(outcomes), unknown=unk_count)

            if pm is not None and fail_rec is None and dead is not None:
                # Reference artifact shape (``src/CP/Verify-CP.py:448-458``):
                # Partition ID, orig/pruned test acc + F1, then the group
                # metrics.  One deliberate delta, documented: the reference
                # recomputes DI..TI from the UNPRUNED net every partition
                # (identical numbers each row); here they come from the
                # partition's masked net, so the column actually varies with
                # the partition — the per-partition quantity worth recording.
                import csv as _csv

                p_pred = mlp_mod.predict_np(weights, biases, pm["X"], dead=dead)
                rep = pm["gm"].group_report(
                    pm["X"], pm["y"], p_pred, pm["prot"], privileged_value=1)
                new_file = not os.path.isfile(pm["path"])
                with open(pm["path"], "a", newline="") as fp:
                    wr = _csv.writer(fp)
                    if new_file:
                        wr.writerow(["Partition ID", "Original Accuracy",
                                     "Original F1 Score", "Pruned Accuracy",
                                     "Pruned F1", "DI", "SPD", "EOD", "AOD",
                                     "ERD", "CNT", "TI"])
                    wr.writerow([
                        pid, round(orig_acc, 6), round(pm["orig_f1"], 6),
                        round(float((p_pred == pm["y"]).mean()), 6),
                        round(pm["gm"].f1_score(pm["y"], p_pred), 6),
                        round(rep.disparate_impact, 6),
                        round(rep.statistical_parity_difference, 6),
                        round(rep.equal_opportunity_difference, 6),
                        round(rep.average_odds_difference, 6),
                        round(rep.error_rate_difference, 6),
                        round(rep.consistency, 6),
                        round(rep.theil_index, 6)])

            csvio.append_row(csv_path, csvio.PartitionRow(
                partition_id=pid, verdict=verdict,
                sat_count=sat_count, unsat_count=unsat_count, unk_count=unk_count,
                h_attempt=h_attempt, h_success=h_success,
                b_compression=comp["b"], s_compression=comp["s"], st_compression=comp["st"],
                h_compression=comp["h"], t_compression=comp["t"],
                sv_time=sv_time, s_time=out.times["s"], hv_time=hv_time, h_time=h_time,
                total_time=total_time, c_check=c_check, v_accurate=v_accurate,
                original_acc=orig_acc, pruned_acc=pruned_acc,
                c1=ce[0] if ce else None, c2=ce[1] if ce else None,
            ))
            led_rec = {
                "partition_id": pid, "verdict": verdict,
                "ce": [ce[0].tolist(), ce[1].tolist()] if ce else None,
                "time_s": round(total_time, 4),
            }
            if fail_rec is not None:
                led_rec["failure"] = fail_rec
            if not smt_deferred_this:
                # A deferred partition's ledger row is written by the
                # SmtDrain once its pool answer lands — leaving it
                # UNLEDGERED until then, so a crash in between resumes it.
                ledger.append(led_rec)
            if ce is not None:
                # Counterexample CSV, encoded form (``src/CP/Verify-CP.py:310-326``),
                # appended per partition like the ledger: crash-safe, and resumed
                # partitions (written by the run that decided them) never repeat.
                # Decoded form: analysis.decode.counterexample_table.
                import csv as _csv

                ce_path = os.path.join(cfg.result_dir, f"{sink_name}-counterexamples.csv")
                new_file = not os.path.isfile(ce_path)
                with open(ce_path, "a", newline="") as fp:
                    wr = _csv.writer(fp)
                    if new_file:
                        wr.writerow(["partition_id", "role"] + list(cfg.query().columns))
                    wr.writerow([pid, "x"] + [int(v) for v in ce[0]])
                    wr.writerow([pid, "x'"] + [int(v) for v in ce[1]])

            # Hard budget is enforced where work happens: the BaB deadline above
            # and the heuristic-retry guard.  Verdicts already computed are always
            # reported — no work is discarded by a reporting-loop break.

        ledger.close()
        smt_transfer = bool(smt_deferred_items)
    finally:
        if smt_tier is not None and not smt_transfer:
            # Unconsumed futures (partitions decided elsewhere) are
            # cancelled; a run-owned pool's workers are reaped here even
            # when the loop above raised.  In smt_defer mode a CLEAN exit
            # hands the tier to the report's SmtDrain instead.
            smt_tier.close()
    if retry_unknown:
        # Re-decided rows were appended after their original 'unknown' rows;
        # restore one-row-per-partition ascending order for row-for-row
        # comparison against reference CSVs.  Same for the per-partition
        # metrics CSV (retried pids re-enter the loop and re-append).
        csvio.rewrite_deduped(csv_path)
        if pm is not None and os.path.isfile(pm["path"]):
            import csv as _csv

            with open(pm["path"], newline="") as fp:
                rows_m = list(_csv.reader(fp))
            header, body = rows_m[0], rows_m[1:]
            last = {r[0]: r for r in body}  # last row per Partition ID wins
            with open(pm["path"], "w", newline="") as fp:
                wr = _csv.writer(fp)
                wr.writerow(header)
                for k in sorted(last, key=lambda v: int(v)):
                    wr.writerow(last[k])
    counter.launches = profiling.launch_count() - launch0
    # The run's funnel block: terminal-state counts (they sum to P), the
    # decided fraction (ROADMAP item-1's success metric, perfdiff-gated),
    # the stage-0 margin/gap histograms, and the prune pass's per-layer
    # bound-looseness attribution.  One ``funnel`` event per model run +
    # the same block in the throughput JSON and on the ModelReport.
    funnel_payload = {
        "states": funnel.to_dict(),
        "total": funnel.total,
        "decided": funnel.decided,
        "decided_fraction": funnel.decided_fraction,
        "margin_hist": stage_stats.to_payload() if stage_stats.boxes else None,
        "looseness": (None if prune is None or prune.looseness is None
                      else [float(v) for v in prune.looseness]),
    }
    obs.event("funnel", model=model_name, **funnel_payload)
    counter.dump(os.path.join(cfg.result_dir, f"{cfg.name}-{sink_name}.throughput.json"),
                 phases=timer.phases,
                 pipeline={"depth": cfg.pipeline_depth, **pipe.stats.summary()},
                 compile=compile_obs.totals_delta(compile0),
                 resilience={"degraded": degraded_count,
                             "ledger_skipped_lines": led_skipped,
                             # Integrity deltas (DESIGN.md §21): all zero
                             # on a healthy run; perfdiff gates growth.
                             **{name: int(obs.registry().counter(name).total()
                                          - integrity0[name])
                                for name in integrity0}},
                 funnel=funnel_payload)
    if heartbeat is not None:  # final line regardless of throttle state
        heartbeat.beat(decided=sat_count + unsat_count, attempted=len(outcomes),
                       unknown=unk_count, force=True)
        heartbeat.close()
    report = ModelReport(
        model=model_name, dataset=cfg.dataset, outcomes=outcomes,
        original_acc=orig_acc, total_time_s=timer.total(), partitions_total=P,
        sink_name=sink_name, ledger_skipped_lines=led_skipped,
        degraded=degraded_count, funnel=funnel_payload,
    )
    if smt_deferred_items:
        report.smt_pending = SmtDrain(
            tier=smt_tier, items=smt_deferred_items, report=report, cfg=cfg,
            weights=weights, biases=biases, ledger_path=ledger_path,
            model_name=model_name, sink_name=sink_name)
    return report


def run_sweep(
    cfg: SweepConfig, model_root=None, data_root=None, mesh=None, stack: bool = True,
    host_index=None, host_count=None, retry_unknown: bool = False,
    n_shards=None,
) -> List[ModelReport]:
    """Sweep every model of the configured family (the drivers' outer loop).

    With ``stack=True``, models sharing an architecture get their stage-0
    certificates and attacks from one vmapped family kernel (e.g. the eleven
    32-32-1 CP nets run as a single batch) before per-model refinement.

    ``host_count`` distributes the partition grid across processes: this
    process sweeps only its :func:`fairify_tpu.parallel.multihost.host_slice`
    span of every model (family stacking is disabled — stage-0 results are
    span-local).

    ``n_shards`` routes every model through the fault-domain sharded runtime
    (:func:`fairify_tpu.parallel.shards.sweep_sharded`): the grid is split
    into per-shard spans over the visible devices, a shard loss elastically
    re-shards onto survivors, and cross-shard verdicts merge decided-wins.
    Mutually exclusive with ``host_count`` (shard *within* each host slice
    by calling ``sweep_sharded`` with ``partition_span`` directly).
    """
    if n_shards and host_count is not None:
        raise ValueError("run_sweep: n_shards and host_count are mutually "
                         "exclusive (call shards.sweep_sharded with "
                         "partition_span to shard inside a host slice)")
    if n_shards and retry_unknown:
        raise ValueError("run_sweep: retry_unknown is not supported with "
                         "n_shards yet — resume=True re-attempts degraded "
                         "partitions; budget UNKNOWNs stay settled")
    with obs.maybe_tracing(cfg.trace_out, run_id=cfg.name):
        with obs.span("run_sweep", preset=cfg.name, dataset=cfg.dataset) as sp:
            reports = _run_sweep_impl(cfg, model_root, data_root, mesh, stack,
                                      host_index, host_count, retry_unknown,
                                      n_shards)
            sp.set(models=len(reports))
            return reports


def _run_sweep_impl(cfg, model_root, data_root, mesh, stack,
                    host_index, host_count, retry_unknown,
                    n_shards=None) -> List[ModelReport]:
    import sys

    from fairify_tpu.utils.cache import enable_persistent_cache

    enable_persistent_cache()  # before the stacked-family compiles below

    dataset = loaders.load(cfg.dataset, root=data_root)
    n_attrs = len(cfg.query().columns)
    nets, skipped = zoo.load_matching(
        cfg.dataset, n_attrs, models=cfg.models, root=model_root)
    for name in skipped:
        print(f"skipping {name}: input dim != domain dim {n_attrs}",
              file=sys.stderr)
    if not nets:
        return []

    if n_shards:
        # Sharded runtime: per-shard fault domains + elastic re-shard.
        # Family stacking is disabled for the same reason as multi-host
        # (stage-0 family results are grid-global, shards are span-local).
        from fairify_tpu.parallel import shards as shards_mod

        return [
            shards_mod.sweep_sharded(net, cfg, model_name=name,
                                     dataset=dataset, n_shards=n_shards)
            for name, net in nets.items()
        ]

    stage0_by_model = {}
    if host_count is not None:
        stack = False  # stage-0 family results would be grid-global
    if stack:
        from collections import defaultdict

        from fairify_tpu.parallel.mesh import stack_models

        groups = defaultdict(list)
        for name, net in nets.items():
            groups[(net.in_dim,) + net.layer_sizes].append(name)
        enc = encode(cfg.query())
        _, lo, hi = build_partitions(cfg)
        multi = [names for names in groups.values() if len(names) >= 2]
        if multi:
            # One shared launch pipeline across every architecture group:
            # the device queue never drains between families — group B's
            # first chunk is dispatched while group A's last chunks are
            # still decoding per-model witnesses on host.
            stacks = [stack_models([nets[n] for n in names]) for names in multi]
            fam_pipe = LaunchPipeline(cfg.pipeline_depth,
                                      supervisor=_supervisor(cfg))
            fam_stats: Dict = {}
            with obs.span("stage0_family",
                          models=sum(len(n) for n in multi),
                          groups=len(multi), partitions=int(lo.shape[0])) as sp:
                fams = stage0_families(stacks, enc, lo, hi, cfg, mesh=mesh,
                                       pipe=fam_pipe, stats=fam_stats)
                sp.set(in_flight_max=fam_pipe.stats.max,
                       in_flight_mean=round(fam_pipe.stats.mean(), 3))
            for gi, (names, fam) in enumerate(zip(multi, fams)):
                for m, (name, s0) in enumerate(zip(names, fam)):
                    # Forward the family kernel's per-model margin/gap
                    # histograms so the per-model funnel block matches an
                    # unstacked run's (4-tuple; verify_model unpacks it).
                    st = fam_stats.get((gi, m))
                    stage0_by_model[name] = s0 + (st,) if st is not None \
                        else s0

    reports = []
    for name, net in nets.items():
        reports.append(
            verify_model(net, cfg, model_name=name, dataset=dataset, mesh=mesh,
                         stage0=stage0_by_model.get(name),
                         host_index=host_index, host_count=host_count,
                         retry_unknown=retry_unknown)
        )
    return reports

"""Per-partition sound pruning, batched over the whole partition grid.

Mirrors the reference's ``sound_prune_*`` pipeline (``utils/prune.py:671-859``)
— simulate → candidate dead neurons → IBP bounds → bound-dead → exact
verification → merge, keep-one guard — but every numeric stage runs once for
*all* partitions as a batched XLA kernel, and the reference's per-neuron Z3
"singular verification" (``utils/prune.py:276-644``) is the closed-form exact
rational pass of :mod:`fairify_tpu.ops.exact` (see that module's equivalence
argument).

The derived masks do not gate the decision engine's soundness (bounds treat
dead neurons identically with or without masks); they feed the compression /
parity stats of the CSV schema and the pruned-network replay (C-check).
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fairify_tpu import obs
from fairify_tpu.obs import obs_jit
from fairify_tpu.models.mlp import MLP
from fairify_tpu.ops import exact as exact_ops
from fairify_tpu.ops import interval as interval_ops
from fairify_tpu.ops import masks as mask_ops
from fairify_tpu.ops import simulate as sim_ops
from fairify_tpu.utils import profiling


@dataclass
class PruneResult:
    """Per-partition masks and stats (arrays have leading partition axis P)."""

    candidates: List[np.ndarray]  # (P, n_l) 1 = never activated in simulation
    surviving: List[np.ndarray]  # candidates not proven dead (s_candidates)
    b_deads: List[np.ndarray]  # bound-proven dead (IBP criterion)
    s_deads: List[np.ndarray]  # exact-pass-proven dead beyond b_deads
    st_deads: List[np.ndarray]  # merged sound dead, keep-one guarded
    pos_prob: List[np.ndarray]  # activation frequency per neuron
    ws_lb: List[np.ndarray]
    ws_ub: List[np.ndarray]
    sim: Optional[np.ndarray]  # (P, sim_size, d) samples; None if keep_sim=False
    # (consumers regenerate rows on device via ops.simulate.simulate_box with
    # grid_keys(seed, global_index, 1) — bit-identical)
    sv_time_s: float  # exact-verification phase (analog of SV solver time)
    looseness: Optional[np.ndarray] = None  # (L,) Σ (ub - lb) per layer
    # (pre-activation, final linear layer included) over the whole grid
    # (funnel telemetry's per-layer bound-looseness attribution, §20).  Device-carried f32 sums on the mega path,
    # host f64 sums on the chunk path — approximately, not bitwise, equal
    # (funnel COUNTS carry the bit-invariance contract, not these sums).


@obs_jit(static_argnames=("sim_size", "with_sim"))
def _sim_and_bounds(net: MLP, keys, lo, hi, sim_size: int,
                    with_sim: bool = True):
    stats, sim = jax.vmap(
        lambda k, l, h: sim_ops.simulate_and_stats(net, k, l, h, sim_size)
    )(keys, lo, hi)
    bounds = interval_ops.network_bounds(net, lo, hi)
    # ``with_sim=False`` drops the (P, S, d) sample tensor from the jit
    # outputs: XLA dead-code-eliminates its materialization and — the real
    # win on a tunnelled TPU — it is never transferred to the host (the
    # adult grid's samples are ~0.8 GB; consumers regenerate rows on device
    # from the deterministic per-partition keys instead).
    return stats, (sim if with_sim else None), bounds


from fairify_tpu.utils.prng import grid_keys  # canonical key derivation


@obs_jit(static_argnames=("sim_size",))
def _mega_sim_and_bounds(net: MLP, keys, lo, hi, nv, sim_size: int):
    """Whole-segment prune pass: ``lax.scan`` over the chunk axis of the
    transfer-light (``with_sim=False``) :func:`_sim_and_bounds` body — one
    launch per segment (DESIGN.md §17).  Keys keep the global per-partition
    derivation, so masks are bit-equal to the chunk loop's.

    The scan carry also accumulates the segment's per-layer bound-looseness
    sums — ``Σ (ub - lb)`` over every pre-activation unit of every real
    partition row (``nv (C,) int32`` masks padded rows) — a ``(L,) f32``
    vector (one entry per layer, final linear layer included) that rides
    the one packed fetch (DESIGN.md §20: which layer's bounds blow up
    first, at zero extra launches)."""
    L = len(net.weights)

    def chunk_step(carry, inp):
        cursor, loos = carry
        k, l, h, n = inp
        stats, _, bounds = _sim_and_bounds.__wrapped__(
            net, k, l, h, sim_size, False)
        ok = (jnp.arange(l.shape[0]) < n).astype(jnp.float32)
        per = jnp.stack([((ub - lb) * ok[:, None]).sum()
                         for lb, ub in zip(bounds.ws_lb, bounds.ws_ub)])
        return (cursor + 1, loos + per), (stats, bounds)

    (_, loos), (stats, bounds) = jax.lax.scan(
        chunk_step, (jnp.int32(0), jnp.zeros((L,), jnp.float32)),
        (keys, lo, hi, nv))
    return stats, bounds, loos


@obs_jit(static_argnames=("sim_size",))
def _sim_stats(net: MLP, keys, lo, hi, sim_size: int):
    """Simulation statistics only — no IBP bounds (harsh prune needs none)."""
    stats, _ = jax.vmap(
        lambda k, l, h: sim_ops.simulate_and_stats(net, k, l, h, sim_size)
    )(keys, lo, hi)
    return stats


def sound_prune_grid(
    net: MLP,
    lo: np.ndarray,
    hi: np.ndarray,
    sim_size: int,
    seed: int,
    exact_certify: bool = True,
    chunk: int = 0,
    index_offset: int = 0,
    keep_sim: bool = True,
    pipeline_depth: int = 2,
    mega_chunks: int = 0,
) -> PruneResult:
    """Sound pruning for a (P, d) box grid in batched device passes.

    ``exact_certify=False`` skips the host-side rational pass (masks then
    rest on widened-f32 IBP only — still what the engine uses; the exact
    pass is the parity anchor and the analog of singular verification).

    ``chunk`` > 0 bounds device memory for huge grids (the adult domain is
    16k partitions): the grid is processed in fixed-size chunks (final chunk
    padded, so the kernel compiles once) and results concatenated.  Each
    partition's PRNG key is derived from its *global* index
    (``index_offset``), so verdicts are chunk-size invariant.

    Chunk launches submit through a :class:`LaunchPipeline`
    (``pipeline_depth`` in flight; 1 = the old synchronous fetch order), so
    the host-side slicing of chunk k overlaps the device work of chunk k+1.
    The pipeline changes only *when* results are fetched — launch order,
    kernel arguments, and per-partition keys are depth-invariant, so masks
    and samples are bit-equal at every depth (``tests/test_chunking.py``).

    ``mega_chunks`` > 0 routes the transfer-light path (``keep_sim=False``)
    through the device-resident mega-loop (DESIGN.md §17): segments of that
    many chunks run as ONE ``lax.scan`` launch each (keys keep the global
    per-partition derivation, masks bit-equal to the chunk loop).  The
    sample-keeping path stays chunk-looped — stacking (P, S, d) sample
    tensors across a segment would defeat the transfer bound.
    """
    from fairify_tpu.parallel.pipeline import LaunchPipeline
    from fairify_tpu.partition.grid import (chunk_spans, pad_chunk_axis,
                                            pad_rows, segment_spans)

    P = lo.shape[0]
    step, spans = chunk_spans(P, chunk)
    span_obs = obs.span("prune.sim_and_bounds", partitions=P,
                        chunks=len(spans))
    lo_np, hi_np = np.asarray(lo), np.asarray(hi)
    cand_c, pos_c, lb_c, ub_c, sim_c = [], [], [], [], []
    loos_acc = {"v": None}  # (L,) f64 per-layer Σ (ub - lb) over the grid

    def _chunk_submit(s: int, e: int):
        """Dispatch one padded chunk; returns (device payload, n valid rows)."""
        clo = pad_rows(lo_np[s:e], step)
        chi = pad_rows(hi_np[s:e], step)
        keys = grid_keys(seed, index_offset + s, step)
        profiling.bump_launch()
        payload = _sim_and_bounds(
            net, keys, jnp.asarray(clo, jnp.float32),
            jnp.asarray(chi, jnp.float32), sim_size, with_sim=keep_sim,
        )
        return payload, e - s

    def _chunk_decode(n: int, host) -> None:
        """Append one drained chunk's HOST arrays (padding rows dropped)."""
        stats, sim, bounds = host
        cand_c.append([c[:n] for c in stats.candidates])
        pos_c.append([p[:n] for p in stats.positive_prob])
        lb_c.append([b[:n] for b in bounds.ws_lb])
        ub_c.append([b[:n] for b in bounds.ws_ub])
        per = np.asarray([
            (np.asarray(ub[:n], np.float64) - np.asarray(lb[:n], np.float64)).sum()
            for lb, ub in zip(bounds.ws_lb, bounds.ws_ub)])
        loos_acc["v"] = per if loos_acc["v"] is None else loos_acc["v"] + per
        if keep_sim:
            sim_c.append(sim[:n])

    def _mega_submit(chunks, pad_chunks=0):
        """One segment's prune launch: stacked chunk keys/boxes, one scan.

        ``pad_chunks`` pads the scan's chunk axis to the segment bucket
        (last chunk repeated) so a ragged FINAL segment reuses the
        full-segment executable; the decode iterates the real ``chunks``
        list, so padded iterations are never read.
        """
        blk = pad_chunk_axis(chunks, pad_chunks)
        keys_c = [grid_keys(seed, index_offset + s, step) for s, _e in blk]
        lo_c = [pad_rows(lo_np[s:e], step).astype(np.float32)
                for s, e in blk]
        hi_c = [pad_rows(hi_np[s:e], step).astype(np.float32)
                for s, e in blk]
        nv = np.asarray([e - s if ci < len(chunks) else 0
                         for ci, (s, e) in enumerate(blk)], np.int32)
        profiling.bump_launch()
        payload = _mega_sim_and_bounds(
            net, jnp.stack(keys_c), jnp.asarray(np.stack(lo_c)),
            jnp.asarray(np.stack(hi_c)), jnp.asarray(nv), sim_size)
        return payload, chunks

    def _mega_decode(chunks, host) -> None:
        stats, bounds, loos = host
        per = np.asarray(loos, np.float64)
        loos_acc["v"] = per if loos_acc["v"] is None else loos_acc["v"] + per
        for ci, (s, e) in enumerate(chunks):
            n = e - s
            cand_c.append([c[ci, :n] for c in stats.candidates])
            pos_c.append([p[ci, :n] for p in stats.positive_prob])
            lb_c.append([b[ci, :n] for b in bounds.ws_lb])
            ub_c.append([b[ci, :n] for b in bounds.ws_ub])

    with span_obs:
        # gauge=False: a prune-phase micro-pipeline must not overwrite the
        # run pipeline's launches_in_flight overlap record.  fault_sites=
        # False: the whole prune pass is supervised as ONE unit by the
        # sweep (`sup.run(site="prune")`, blast radius: masks only), so its
        # launches must not consume launch.submit/launch.decode arrivals
        # the stage-0 chaos schedules count on.
        pipe = LaunchPipeline(depth=pipeline_depth, gauge=False,
                              fault_sites=False)
        if mega_chunks > 0 and not keep_sim:
            # Same segment grouping + ragged-tail bucket rule as the
            # stage-0/parity loops (partition.grid.segment_spans), so the
            # prune pass's launch signatures can never desync from theirs.
            _, segs = segment_spans(P, chunk, mega_chunks)
            bucket = mega_chunks if len(segs) > 1 else 0
            for _seg_s, _seg_e, blk in segs:
                for _meta, chunks, host in pipe.submit(
                        lambda blk=blk: _mega_submit(blk,
                                                     pad_chunks=bucket)):
                    _mega_decode(chunks, host)
            for _meta, chunks, host in pipe.drain():
                _mega_decode(chunks, host)
        else:
            for s, e in spans:
                for _meta, n, host in pipe.submit(
                        lambda s=s, e=e: _chunk_submit(s, e)):
                    _chunk_decode(n, host)
            for _meta, n, host in pipe.drain():
                _chunk_decode(n, host)

    L = len(cand_c[0])
    _cat = lambda parts: [np.concatenate([p[l] for p in parts]) for l in range(L)]
    candidates, pos_prob = _cat(cand_c), _cat(pos_c)
    ws_lb, ws_ub = _cat(lb_c), _cat(ub_c)
    sim = np.concatenate(sim_c) if keep_sim else None
    bounds = interval_ops.LayerBounds(
        ws_lb=tuple(ws_lb), ws_ub=tuple(ws_ub), pl_lb=(), pl_ub=())

    ibp_dead = [np.asarray(d) for d in interval_ops.dead_from_ws_ub(bounds)]
    # Bound-dead requires simulation candidacy, as in the reference
    # (``utils/prune.py:241-242``).
    b_deads = [c * d for c, d in zip(candidates, ibp_dead)]

    t0 = time.perf_counter()
    s_deads = [np.zeros_like(c) for c in candidates]
    certified = b_deads
    if exact_certify:
        with obs.span("prune.exact_certify", partitions=P):
            from fairify_tpu.ops import exact_native

            weights = [np.asarray(w) for w in net.weights]
            biases = [np.asarray(b) for b in net.biases]
            batched = exact_native.certify_dead_batch(weights, biases, lo, hi, candidates)
            if batched is not None:
                certified = batched[: len(candidates)]
            else:
                certified = []
                for p in range(P):
                    cert = exact_ops.certify_dead_masks(
                        weights, biases, lo[p], hi[p], [c[p] for c in candidates]
                    )
                    certified.append(cert)
                certified = [
                    np.stack([certified[p][l] for p in range(P)]) for l in range(len(candidates))
                ]
            s_deads = [np.maximum(c - b, 0.0) for c, b in zip(certified, b_deads)]
    sv_time = time.perf_counter() - t0

    merged = [np.maximum(b, s) for b, s in zip(b_deads, s_deads)]
    st_deads = [np.asarray(d) for d in mask_ops.keep_one_alive(merged)]
    surviving = [np.maximum(c - m, 0.0) for c, m in zip(candidates, certified)]
    return PruneResult(
        candidates=candidates,
        surviving=surviving,
        b_deads=b_deads,
        s_deads=s_deads,
        st_deads=st_deads,
        pos_prob=pos_prob,
        ws_lb=ws_lb,
        ws_ub=ws_ub,
        sim=sim,
        sv_time_s=sv_time,
        looseness=loos_acc["v"],
    )


def partition_masks(prune: PruneResult, p: int) -> list:
    """Dead masks of one partition (list of (n_l,) arrays)."""
    return [layer[p] for layer in prune.st_deads]


def harsh_prune_grid(net: MLP, lo: np.ndarray, hi: np.ndarray, sim_size: int, seed: int) -> list:
    """Unsound candidate-only pruning (``harsh_prune``, ``utils/prune.py:89-102``).

    Simulation candidates are taken as dead directly — no bound or exact
    verification, and (faithfully to the reference) no keep-one guard.
    Returns per-layer (P, n_l) dead masks for the box grid.
    """
    P = lo.shape[0]
    keys = grid_keys(seed, 0, P)
    stats = _sim_stats(
        net, keys, jnp.asarray(lo, jnp.float32), jnp.asarray(hi, jnp.float32), sim_size
    )
    return [np.asarray(c) for c in stats.candidates]


def sound_prune_global(
    net: MLP,
    lo: np.ndarray,
    hi: np.ndarray,
    sim_size: int,
    seed: int,
    exact_certify: bool = True,
) -> PruneResult:
    """Whole-domain sound pruning (``sound_prune_global``, ``utils/prune.py:646-667``):
    the grid pass on the single full-range box (P = 1)."""
    return sound_prune_grid(
        net,
        np.asarray(lo)[None, :],
        np.asarray(hi)[None, :],
        sim_size,
        seed,
        exact_certify=exact_certify,
    )

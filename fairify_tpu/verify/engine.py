"""Native complete decision engine for the pair property.

Replaces the reference's per-partition Z3 query (``src/GC/Verify-GC.py:145-214``)
with a TPU-first procedure:

1. **Bound certificate (UNSAT)** — batched CROWN/IBP logit bounds for every
   protected-assignment role box; a box is certified fair iff for every
   valid assignment pair (a, b) both flip directions are impossible
   (``ub ≤ 0`` on one side or ``lb ≥ 0`` on the other).  One XLA launch for
   the whole batch.
2. **Sampling attack (SAT)** — batched integer sampling of shared
   coordinates, PA assignments enumerated, RA deltas sampled; any strict
   sign flip yields a counterexample pair, exactness-checked on host.
3. **Branch-and-bound** — undecided boxes split along the widest shared
   dimension into an on-device frontier (static shapes, padded); leaves
   (all shared dims collapsed to a point) are decided *exactly* in rational
   arithmetic (RA ball enumerated), so the procedure is complete on the
   integer lattice.  Budget exhaustion → UNKNOWN, like the reference's
   solver timeout.

Soundness: device bounds are outward-widened f32; leaf decisions and
counterexample validation are exact (``fairify_tpu.ops.exact``).  A
float-certified UNSAT can optionally be re-derived with exact IBP
(``exact_certify=True``) at extra host cost.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from fractions import Fraction
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fairify_tpu import obs
from fairify_tpu.obs import obs_jit
from fairify_tpu.models.mlp import MLP
from fairify_tpu.ops import crown as crown_ops
from fairify_tpu.ops import interval as interval_ops
from fairify_tpu.utils import profiling
from fairify_tpu.verify.property import PairEncoding

# ---------------------------------------------------------------------------
# Device kernels (jitted; net pytree is a traced argument, so one compile per
# model architecture × batch shape)
# ---------------------------------------------------------------------------


@obs_jit
def _role_logit_bounds(net: MLP, x_lo, x_hi, xp_lo, xp_hi, use_crown: bool):
    """Logit bounds of both roles; inputs (..., V, d) → four (..., V) arrays."""

    def bounds(lo, hi):
        return jax.lax.cond(
            use_crown,
            lambda: crown_ops.crown_output_bounds(net, lo, hi),
            lambda: interval_ops.output_bounds(net, lo, hi),
        )

    lb_x, ub_x = bounds(x_lo, x_hi)
    lb_p, ub_p = bounds(xp_lo, xp_hi)
    return lb_x, ub_x, lb_p, ub_p


# ---------------------------------------------------------------------------
# Tied pair-difference certificate
# ---------------------------------------------------------------------------
#
# Separate role bounds discard the defining structure of the fairness pair:
# x and x' agree on every non-PA coordinate (RA dims within ±ε).  A flip
# x⁺/x'⁻ forces f(x) − f(x') > 0, so an upper bound of the *difference over
# the tied pair set* that is ≤ 0 kills the flip even when both role logit
# ranges straddle zero — which is exactly the regime where the hard models
# (large logit range, tiny PA sensitivity) leave the separate-bound
# certificate stuck.  The difference bound comes from the CROWN output
# linear forms: f(x) ≤ Aᵘ·x + cᵘ over the x role box and f(x') ≥ Aˡ·x' + cˡ
# over the x' role box, so over tied pairs
#
#   f(x) − f(x') ≤ Σ_{j∉PA} max_{s_j∈[lo,hi]} (Aᵘ_j − Aˡ_j)·s_j
#                + Σ_{j∈PA} (Aᵘ_j·a_j − Aˡ_j·b_j)  + ε·Σ_{j∈RA} |Aˡ_j|
#                + cᵘ − cˡ
#
# — the shared-dim coefficients *cancel* instead of concretizing twice.


def _tied_diff_ub(A_pos, c_pos, A_neg, c_neg, lo, hi, shared_mask):
    """Upper bounds of (pos-form − neg-form) over tied shared coordinates.

    ``A_pos``/``c_pos``: (B, Vp, d)/(B, Vp) upper linear form of the role
    that must be positive; ``A_neg``/``c_neg``: lower form of the role that
    must be negative (constants include their PA/ε contributions).
    ``lo``/``hi``: (B, d) shared box.  Returns ``(M, coef, mag)``: the
    (B, Vp, Vn) bound matrix, the per-dim max |Aᵖᵒˢ − Aⁿᵉᵍ| (B, d) branching
    score, and the (B, Vp, Vn) magnitude against which outward slack must
    be scaled.  ``mag`` has two parts: the concretized-term magnitude
    Σ_j |D_j|·max(|lo_j|,|hi_j|) + |cᵘ| + |cⁿ| (f32 summation error of the
    row reduction), **plus** Σ_j (|Aᵖᵒˢ_j| + |Aⁿᵉᵍ_j|)·max(|lo_j|,|hi_j|)
    (the rounding already baked into the unwidened f32 form coefficients by
    their separate backward passes — in the near-cancellation regime
    |D| ≪ |A|, an error ∝ |A| would otherwise escape a |D|-scaled slack
    entirely).  The bound itself cancels (that is the whole point of the
    certificate) while the summands it nets out can be large (wide integer
    domains, e.g. default-credit dims spanning ~10⁶), so slack ∝ |bound|
    would under-cover both error sources.
    The Vp axis is mapped with ``lax.scan`` so the (B, V, V, d) tensor is
    never materialised (GC's PA=age has V=57).
    """
    absbox = jnp.maximum(jnp.abs(lo), jnp.abs(hi))
    # |A_neg|-side coefficient-magnitude term, shared across scan steps.
    neg_coef_mag = (jnp.abs(A_neg) * absbox[:, None, :]).sum(-1)  # (B, Vn)

    def one(carry, au_cu):
        au, cu = au_cu
        D = (au[:, None, :] - A_neg) * shared_mask
        m = jnp.where(D > 0, D * hi[:, None, :], D * lo[:, None, :])
        row = m.sum(-1) + cu[:, None] - c_neg
        pos_coef_mag = (jnp.abs(au) * absbox).sum(-1)  # (B,)
        mag = (jnp.abs(D) * absbox[:, None, :]).sum(-1) \
            + pos_coef_mag[:, None] + neg_coef_mag \
            + jnp.abs(cu)[:, None] + jnp.abs(c_neg)
        return jnp.maximum(carry, jnp.abs(D).max(axis=1)), (row, mag)

    coef0 = jnp.zeros(lo.shape, dtype=A_pos.dtype)
    coef, (rows, mags) = jax.lax.scan(
        one, coef0, (jnp.moveaxis(A_pos, 1, 0), jnp.moveaxis(c_pos, 1, 0)))
    return jnp.moveaxis(rows, 0, 1), coef, jnp.moveaxis(mags, 0, 1)


def _fold_dev(*bufs):
    """Wraparound int32 fold of packed BaB buffers (device side).

    Same body as the sweep's mega-segment fold and the same host mirror
    (``resilience.integrity.fold_host``): int32 two's-complement wraparound
    sums commute across backends, so equal data folds equal anywhere."""
    total = jnp.int32(0)
    for b in bufs:
        total = total + jnp.sum(b.astype(jnp.int32), dtype=jnp.int32)
    return total


def _tied_diff_ub_keep(A_pos, c_pos, A_neg, c_neg, lo, hi, shared_mask, alive):
    """:func:`_tied_diff_ub` plus per-dim KEEP intervals for domain clipping.

    Identical bound math (same ``row``/``coef``/``mag`` values, one scan
    over the Vp axis), additionally deriving, per alive pair, the interval
    of each shared coordinate outside which the pair's flip direction is
    provably impossible — the Clip-and-Verify move (arxiv 2512.11087) on
    the tied difference form.  The widened pair bound w is the form's max
    over the box, attained at a corner; moving coordinate j a distance t
    off its optimal corner lowers the form by |D_j|·t with every other
    coordinate still at its optimum, so ``|D_j|·t ≥ w ⇒ no flip``:

        D_j > 0 ⇒ flip needs s_j > hi_j − w/|D_j|
        D_j < 0 ⇒ flip needs s_j < lo_j + w/|D_j|

    The shift w/|D_j| is inflated by the standard outward slack so f32
    division rounding cannot shave a feasible lattice point; a dead pair
    (``alive`` False, or w ≤ 0 — killed by this very bound) contributes
    the empty interval.  Per-dim union over pairs is folded into the scan
    carry, so the output is the (B, d) hull ``(keep_lo, keep_hi)`` of
    everything any alive pair might still need.  ``alive``: (B, Vp, Vn)
    pair mask in the SAME [pos, neg] layout as the returned bound matrix.
    Returns ``(M, coef, mag, keep_lo, keep_hi)``.
    """
    from fairify_tpu.ops.interval import SOUND_SLACK_ABS, SOUND_SLACK_REL

    absbox = jnp.maximum(jnp.abs(lo), jnp.abs(hi))
    neg_coef_mag = (jnp.abs(A_neg) * absbox[:, None, :]).sum(-1)  # (B, Vn)
    big = jnp.asarray(jnp.finfo(lo.dtype).max, lo.dtype)
    tiny = jnp.asarray(1e-12, lo.dtype)

    def one(carry, au_cu):
        coef, keep_lo, keep_hi = carry
        au, cu, alive_a = au_cu
        D = (au[:, None, :] - A_neg) * shared_mask
        m = jnp.where(D > 0, D * hi[:, None, :], D * lo[:, None, :])
        row = m.sum(-1) + cu[:, None] - c_neg
        pos_coef_mag = (jnp.abs(au) * absbox).sum(-1)  # (B,)
        mag = (jnp.abs(D) * absbox[:, None, :]).sum(-1) \
            + pos_coef_mag[:, None] + neg_coef_mag \
            + jnp.abs(cu)[:, None] + jnp.abs(c_neg)
        absD = jnp.abs(D)
        # Widened bound of this pair row — the same value the certificate
        # compares against 0 (widen-before-min-over-sets, _certify_impl).
        w = row + SOUND_SLACK_REL * mag + SOUND_SLACK_ABS  # (B, Vn)
        live = alive_a & (w > 0.0)
        shift = w[..., None] / jnp.maximum(absD, tiny)
        shift = shift + SOUND_SLACK_REL * shift + SOUND_SLACK_ABS
        kl = jnp.where(D > tiny, hi[:, None, :] - shift, lo[:, None, :])
        kh = jnp.where(D < -tiny, lo[:, None, :] + shift, hi[:, None, :])
        kl = jnp.where(live[..., None], kl, big)
        kh = jnp.where(live[..., None], kh, -big)
        keep_lo = jnp.minimum(keep_lo, kl.min(axis=1))
        keep_hi = jnp.maximum(keep_hi, kh.max(axis=1))
        return ((jnp.maximum(coef, absD.max(axis=1)), keep_lo, keep_hi),
                (row, mag))

    coef0 = jnp.zeros(lo.shape, dtype=A_pos.dtype)
    init = (coef0, jnp.full(lo.shape, big, lo.dtype),
            jnp.full(lo.shape, -big, lo.dtype))
    (coef, keep_lo, keep_hi), (rows, mags) = jax.lax.scan(
        one, init, (jnp.moveaxis(A_pos, 1, 0), jnp.moveaxis(c_pos, 1, 0),
                    jnp.moveaxis(alive, 1, 0)))
    return (jnp.moveaxis(rows, 0, 1), coef, jnp.moveaxis(mags, 0, 1),
            keep_lo, keep_hi)


def _certify_impl(net: MLP, x_lo, x_hi, xp_lo, xp_hi, lo, hi, assign_vals,
                  pa_mask, ra_mask, eps, valid, valid_pair, alpha_iters: int):
    """Combined fairness certificate + branch scores for a batch of boxes.

    Per box: CROWN (α-CROWN when ``alpha_iters > 0``) role logit bounds give
    the separate-bound kills of :func:`no_flip_certified`; the output linear
    forms additionally give tied pair-difference kills per direction.  A box
    is certified iff every valid assignment pair has both flip directions
    killed by either mechanism.  Returns ``(certified (B,), score (B, d),
    margin (B,))`` where ``score`` is the max difference-form coefficient
    magnitude per shared dim — the input-split analog of bound-improvement
    branching (splitting dim j tightens the difference bound by
    ~score_j·width_j/2) — and ``margin`` is the certified margin: the min
    over valid pairs of each pair's kill slack, with ``margin >= 0 ⟺
    certified`` EXACTLY (every clause mirrors one of the ``*_dead``
    comparisons).  The margin's distribution is the funnel telemetry's
    "how close were the bounds" signal (obs.funnel, DESIGN.md §20).
    """
    sets_x, lb_x, ub_x = crown_ops.crown_output_form_sets(
        net, x_lo, x_hi, alpha_iters)
    sets_p, lb_p, ub_p = crown_ops.crown_output_form_sets(
        net, xp_lo, xp_hi, alpha_iters)
    t1_dead = (ub_x[..., :, None] <= 0.0) | (lb_p[..., None, :] >= 0.0)
    t2_dead = (lb_x[..., :, None] >= 0.0) | (ub_p[..., None, :] <= 0.0)

    shared = 1.0 - pa_mask
    pa_dot = lambda A: jnp.sum(A * assign_vals[None, :, :], axis=-1)
    ra_abs = lambda A: eps * jnp.sum(jnp.abs(A) * ra_mask, axis=-1)
    # Outward slack scaled by the *concretized term magnitudes*, not the
    # cancelled bound value: each set's bound is widened with its own
    # magnitude before the min over sets (widening after the min would pair
    # one set's bound with another's magnitude).  Forms are unwidened f32
    # (crown_output_form_sets); accumulation error scales with what was
    # summed, which the certificate exists precisely to cancel.
    from fairify_tpu.ops.interval import SOUND_SLACK_ABS, SOUND_SLACK_REL

    widen = lambda u, g: u + SOUND_SLACK_REL * g + SOUND_SLACK_ABS
    ub1 = ub2 = None
    score = jnp.zeros(lo.shape, dtype=lo.dtype)
    for (Alx, clx, Aux, cux), (Alp, clp, Aup, cup) in zip(sets_x, sets_p):
        # Direction x⁺/x'⁻: needs f(x_a) − f(x'_b) > 0.
        m1, s1, g1 = _tied_diff_ub(
            Aux, cux + pa_dot(Aux), Alp, clp + pa_dot(Alp) - ra_abs(Alp),
            lo, hi, shared)
        # Direction x⁻/x'⁺: needs f(x'_b) − f(x_a) > 0 (matrix built [b, a]).
        m2, s2, g2 = _tied_diff_ub(
            Aup, cup + pa_dot(Aup) + ra_abs(Aup), Alx, clx + pa_dot(Alx),
            lo, hi, shared)
        w1 = widen(m1, g1)
        w2 = jnp.swapaxes(widen(m2, g2), -1, -2)
        ub1 = w1 if ub1 is None else jnp.minimum(ub1, w1)
        ub2 = w2 if ub2 is None else jnp.minimum(ub2, w2)
        score = jnp.maximum(score, jnp.maximum(s1, s2))
    t1_dead = t1_dead | (ub1 <= 0.0)
    t2_dead = t2_dead | (ub2 <= 0.0)

    pair_ok = valid_pair[None] & valid[..., :, None] & valid[..., None, :]
    possible = pair_ok & ~(t1_dead & t2_dead)
    # Certified margin: a pair's direction-1 kill slack is
    # max(-ub_x[a], lb_p[b], -ub1[a,b]) (>= 0 ⟺ t1_dead), direction 2
    # symmetric, and a pair is killed iff BOTH directions are (min); the box
    # margin is the min over valid pairs.  Invalid pairs — and boxes with no
    # valid pair at all — saturate to +FLT_MAX (the histogram's top bucket),
    # never to an arithmetic ±inf that could contaminate reductions.
    m1 = jnp.maximum(jnp.maximum(-ub_x[..., :, None], lb_p[..., None, :]),
                     -ub1)
    m2 = jnp.maximum(jnp.maximum(lb_x[..., :, None], -ub_p[..., None, :]),
                     -ub2)
    big = jnp.asarray(jnp.finfo(m1.dtype).max, m1.dtype)
    margin = jnp.where(pair_ok, jnp.minimum(m1, m2), big).min(axis=(-2, -1))
    return ~possible.any(axis=(-2, -1)), score, margin


_role_certify_kernel = obs_jit(_certify_impl, name="engine.role_certify",
                               static_argnames=("alpha_iters",))


def _find_flips_impl(xp, lx, lp, valid, valid_pair):
    """Strict-flip detection, backend-agnostic (``xp`` = numpy or jnp).

    ONE implementation serves both the host path (:func:`find_flips`) and
    the fused device kernels (:func:`_find_flips_dev`) — the two must never
    diverge in flip semantics (strict signs, valid-ordered-pair masking,
    first-hit argmax tie-break), since the device path feeds the same
    ``extract_witnesses`` exact validation as the host path."""
    va = valid[:, None, :]
    pos_x = (lx > 0.0) & va
    neg_x = (lx < 0.0) & va
    pos_p = (lp > 0.0) & va
    neg_p = (lp < 0.0) & va
    flips = (pos_x[..., :, None] & neg_p[..., None, :]) | (
        neg_x[..., :, None] & pos_p[..., None, :]
    )
    flips = flips & valid_pair[None, None, :, :]
    B, S, V, _ = flips.shape
    flat = flips.reshape(B, -1)
    found = flat.any(axis=1)
    idx = flat.argmax(axis=1).astype(xp.int32)
    s, rem = idx // (V * V), idx % (V * V)
    a, b = rem // V, rem % V
    return found, xp.stack([s, a, b], axis=1)


def _find_flips_dev(lx, lp, valid, valid_pair):
    """Device strict-flip detection: (found (B,), wit (B, 3)) in jnp.

    Flip DETECTION stays on device so only a boolean plus three indices per
    box cross the tunnel — the (B, S, V) logit tensors are ~MB-scale per
    chunk and were the stage-0 transfer bottleneck on the family sweeps."""
    return _find_flips_impl(jnp, lx, lp, valid, valid_pair)


def _attack_gap_impl(xp, lx, lp, valid, valid_pair):
    """Best strict-flip gap over all (sample, pair) — backend-agnostic.

    Per (s, a, b): direction-1 flip needs ``lx[s,a] > 0 and lp[s,b] < 0``,
    i.e. ``min(lx[s,a], -lp[s,b]) > 0``; direction 2 symmetric.  The box gap
    is the max over valid masked triples, so ``gap > 0 ⟺ found`` EXACTLY
    (the strict signs of :func:`_find_flips_impl`).  Masked-out triples
    saturate to -FLT_MAX — a box with no valid pair lands in the bottom
    histogram bucket.  Feeds the funnel telemetry's attack-gap histogram
    (obs.funnel, DESIGN.md §20)."""
    neg = xp.float32(np.finfo(np.float32).min) if xp is np \
        else jnp.asarray(jnp.finfo(lx.dtype).min, lx.dtype)
    pair = (valid[:, None, :, None] & valid[:, None, None, :]
            & valid_pair[None, None])
    g1 = xp.minimum(lx[..., :, None], -lp[..., None, :])
    g2 = xp.minimum(-lx[..., :, None], lp[..., None, :])
    return xp.where(pair, xp.maximum(g1, g2), neg).max(axis=(-3, -2, -1))


def attack_gap(lx, lp, valid, valid_pair) -> np.ndarray:
    """Host mirror of the device attack gap (numpy logits, (B,) f32)."""
    return _attack_gap_impl(np, np.asarray(lx, np.float32),
                            np.asarray(lp, np.float32),
                            np.asarray(valid, bool),
                            np.asarray(valid_pair, bool))


def role_bound_margin(lb_x, ub_x, lb_p, ub_p, valid, valid_pair) -> np.ndarray:
    """Certified margin from role logit bounds alone (the IBP path's host
    analog of ``_certify_impl``'s margin: no tied pair-difference term).
    ``margin >= 0 ⟺ no_flip_certified`` exactly."""
    lb_x, ub_x, lb_p, ub_p = (np.asarray(v, np.float32)
                              for v in (lb_x, ub_x, lb_p, ub_p))
    m1 = np.maximum(-ub_x[..., :, None], lb_p[..., None, :])
    m2 = np.maximum(lb_x[..., :, None], -ub_p[..., None, :])
    va = np.asarray(valid, bool)
    pair_ok = (np.asarray(valid_pair, bool)
               & va[..., :, None] & va[..., None, :])
    big = np.float32(np.finfo(np.float32).max)
    return np.where(pair_ok, np.minimum(m1, m2), big).min(axis=(-2, -1))


def _certify_attack_impl(net: MLP, x_lo, x_hi, xp_lo, xp_hi, lo, hi,
                         assign_vals, pa_mask, ra_mask, eps, valid, valid_pair,
                         xr, pr, alpha_iters: int):
    """Certificate + attack + flip detection in ONE launch.

    The BaB loop and stage 0 both pay ~110 ms relay round-trip per launch on
    the tunnelled chip regardless of batch size; evaluating the attack
    forwards for every box inside the certificate kernel costs negligible
    MXU time and removes a whole launch per iteration/chunk, and returning
    only ``(found, wit)`` instead of the logits removes the dominant
    device→host transfer (attack candidates stay host-built, so witness
    extraction needs no pull).  Also returns the per-box certified margin
    and attack gap — two (B,) floats for the funnel histograms, microseconds
    of extra reduction over tensors the kernel already materializes."""
    cert, score, margin = _certify_impl(net, x_lo, x_hi, xp_lo, xp_hi, lo, hi,
                                        assign_vals, pa_mask, ra_mask, eps,
                                        valid, valid_pair, alpha_iters)
    lx, lp = _attack_logits(net, xr, pr)
    found, wit = _find_flips_dev(lx, lp, valid, valid_pair)
    gap = _attack_gap_impl(jnp, lx, lp, valid, valid_pair)
    return cert, score, found, wit, margin, gap


_certify_attack_kernel = obs_jit(_certify_attack_impl,
                                 name="engine.certify_attack",
                                 static_argnames=("alpha_iters",))


def _certify_clip_impl(net: MLP, x_lo, x_hi, xp_lo, xp_hi, lo, hi,
                       assign_vals, pa_mask, ra_mask, eps, valid, valid_pair,
                       alpha_iters: int):
    """:func:`_certify_impl` plus the per-box domain-clip hull.

    Same form sets, role deadness, widen-before-min-over-sets and score as
    the certificate kernel, but each direction's tied bound runs through
    :func:`_tied_diff_ub_keep` with the pairs still alive after role
    deadness, so the launch additionally yields the (B, d) KEEP hull of
    the box: per set, the union over alive pairs/directions of where a
    flip is still possible; across sets, the intersection (each set's
    bound is independently valid, so each set's keep region independently
    covers every flip).  Clipping ``[lo, hi]`` to the hull before
    splitting discards lattice points no pair can flip on — provably
    counterexample-free, so the shrink is verdict-preserving.
    Returns ``(cert (B,), score (B, d), keep_lo (B, d), keep_hi (B, d))``.
    """
    # Stacked (not listed) form sets: the BaB scan body wants the set axis
    # static so one executable serves every segment (ops.crown docstring).
    stk_x, lb_x, ub_x = crown_ops.output_form_stack(
        net, x_lo, x_hi, alpha_iters)
    stk_p, lb_p, ub_p = crown_ops.output_form_stack(
        net, xp_lo, xp_hi, alpha_iters)
    sets_x = [tuple(a[i] for a in stk_x) for i in range(stk_x[0].shape[0])]
    sets_p = [tuple(a[i] for a in stk_p) for i in range(stk_p[0].shape[0])]
    t1_dead = (ub_x[..., :, None] <= 0.0) | (lb_p[..., None, :] >= 0.0)
    t2_dead = (lb_x[..., :, None] >= 0.0) | (ub_p[..., None, :] <= 0.0)
    pair_ok = valid_pair[None] & valid[..., :, None] & valid[..., None, :]
    alive1 = pair_ok & ~t1_dead
    # Direction-2 matrices are built [b, a] (_certify_impl), so its alive
    # mask transposes into that layout.
    alive2 = jnp.swapaxes(pair_ok & ~t2_dead, -1, -2)

    shared = 1.0 - pa_mask
    pa_dot = lambda A: jnp.sum(A * assign_vals[None, :, :], axis=-1)
    ra_abs = lambda A: eps * jnp.sum(jnp.abs(A) * ra_mask, axis=-1)
    from fairify_tpu.ops.interval import SOUND_SLACK_ABS, SOUND_SLACK_REL

    widen = lambda u, g: u + SOUND_SLACK_REL * g + SOUND_SLACK_ABS
    ub1 = ub2 = keep_lo = keep_hi = None
    score = jnp.zeros(lo.shape, dtype=lo.dtype)
    for (Alx, clx, Aux, cux), (Alp, clp, Aup, cup) in zip(sets_x, sets_p):
        m1, s1, g1, kl1, kh1 = _tied_diff_ub_keep(
            Aux, cux + pa_dot(Aux), Alp, clp + pa_dot(Alp) - ra_abs(Alp),
            lo, hi, shared, alive1)
        m2, s2, g2, kl2, kh2 = _tied_diff_ub_keep(
            Aup, cup + pa_dot(Aup) + ra_abs(Aup), Alx, clx + pa_dot(Alx),
            lo, hi, shared, alive2)
        w1 = widen(m1, g1)
        w2 = jnp.swapaxes(widen(m2, g2), -1, -2)
        ub1 = w1 if ub1 is None else jnp.minimum(ub1, w1)
        ub2 = w2 if ub2 is None else jnp.minimum(ub2, w2)
        score = jnp.maximum(score, jnp.maximum(s1, s2))
        # A pair is possible at s iff EITHER direction is: union the two
        # direction hulls within the set (the pair axes are already folded
        # away inside the keep scan, so layout is moot here).
        skl = jnp.minimum(kl1, kl2)
        skh = jnp.maximum(kh1, kh2)
        keep_lo = skl if keep_lo is None else jnp.maximum(keep_lo, skl)
        keep_hi = skh if keep_hi is None else jnp.minimum(keep_hi, skh)
    t1_dead = t1_dead | (ub1 <= 0.0)
    t2_dead = t2_dead | (ub2 <= 0.0)
    possible = pair_ok & ~(t1_dead & t2_dead)
    return ~possible.any(axis=(-2, -1)), score, keep_lo, keep_hi


def _bab_segment_impl(net: MLP, q_lo, q_hi, q_root, q_live, q_found,
                      wit_a, wit_b, wit_pt, slot_ok, root_valid, assign_vals,
                      pa_mask, ra_mask, eps, valid_pair, branch_mask,
                      rounds: int, alpha_iters: int):
    """One device-resident BaB segment: ``rounds`` branching rounds, 1 launch.

    The frontier is a fixed-capacity slot queue (padded, static shapes)
    carried through a ``lax.scan``: per round every live slot is
    CROWN-certified with domain clipping (:func:`_certify_clip_impl`),
    probed at its integer midpoint for a flip witness, scored
    (widest-gradient ``score·width``), split along its best dim, and the
    upper child compacted into a free slot — K rounds cost ONE launch
    instead of the host frontier's one launch per batch (DESIGN.md §22).

    Queue contract (all arrays slot-major, capacity Q static):
      ``q_lo``/``q_hi`` (Q, d) f32 integer box bounds; ``q_root`` (Q,) i32
      group-local root of each slot; ``q_live`` (Q,) open boxes;
      ``q_found``/``wit_a``/``wit_b``/``wit_pt`` per-slot witness latch
      (first probe flip in the slot's lifetime — a latched slot is retired
      from the free pool so the latch survives to host decode, where it is
      exact-validated and cleared); ``slot_ok`` marks real slots (the
      trailing canary row is never allocated and must come back all-zero);
      ``root_valid`` (G, V) the per-root valid-assignment mask (PA dims are
      never split, so it is row-constant for the whole segment).

    Splits match the host BaB exactly where they overlap: integer midpoint
    ``⌊(lo+hi)/2⌋``, score·width dim choice with widest-dim fallback and
    first-max tie-break.  A split with no free slot is an OVERFLOW: the
    parent keeps its whole box (nothing is lost — it re-splits when a slot
    frees) and the root's overflow counter records the capacity fall.

    Returns the updated queue plus per-root (G,) ``nodes``/``splits``/
    ``overflow`` counters and the device fold checksum of every returned
    buffer (integrity.BAB_FOLD_KEYS order).
    """
    from fairify_tpu.models.mlp import forward

    Q, d = q_lo.shape
    G = root_valid.shape[0]
    shared = 1.0 - pa_mask
    dim_ids = jnp.arange(d, dtype=jnp.int32)

    def round_body(carry, _):
        (q_lo, q_hi, q_root, q_live, found, wa, wb, wpt,
         r_nodes, r_splits, r_over) = carry
        # Role boxes of every slot (device mirror of property.role_boxes;
        # xp is the ε-shifted partner, unclamped).
        x_lo = q_lo[:, None, :] * shared + assign_vals[None]
        x_hi = q_hi[:, None, :] * shared + assign_vals[None]
        xp_lo = x_lo - eps * ra_mask
        xp_hi = x_hi + eps * ra_mask
        valid = jnp.take(root_valid, q_root, axis=0) & q_live[:, None]
        cert, score, keep_lo, keep_hi = _certify_clip_impl(
            net, x_lo, x_hi, xp_lo, xp_hi, q_lo, q_hi, assign_vals,
            pa_mask, ra_mask, eps, valid, valid_pair, alpha_iters)
        r_nodes = r_nodes.at[q_root].add(q_live.astype(jnp.int32),
                                         mode="drop")
        # Clip: integer points outside the keep hull cannot flip, so the
        # box shrinks to the hull's lattice rounding (ceil/floor INWARD —
        # the hull itself is already outward-inflated).  An emptied box is
        # as decided as a certified one.
        n_lo = jnp.maximum(q_lo, jnp.ceil(keep_lo))
        n_hi = jnp.minimum(q_hi, jnp.floor(keep_hi))
        empty = (n_lo > n_hi).any(-1)
        cert = cert | empty
        keep = q_live & ~cert
        q_lo = jnp.where(keep[:, None], n_lo, q_lo)
        q_hi = jnp.where(keep[:, None], n_hi, q_hi)
        q_live = keep
        # Midpoint probe: one forward over every slot's integer midpoint,
        # flips latched per slot (delta-0 candidates; exact validation
        # happens host-side at decode, same as every other attack path).
        mid = jnp.floor((q_lo + q_hi) * 0.5)
        x_mid = mid[:, None, :] * shared + assign_vals[None]
        lm = forward(net, x_mid)
        valid_fresh = jnp.take(root_valid, q_root, axis=0) & q_live[:, None]
        found_now, wit = _find_flips_impl(jnp, lm[:, None, :], lm[:, None, :],
                                          valid_fresh, valid_pair)
        newly = found_now & ~found
        wa = jnp.where(newly, wit[:, 1], wa)
        wb = jnp.where(newly, wit[:, 2], wb)
        wpt = jnp.where(newly[:, None], mid, wpt)
        found = found | found_now
        # Split scoring: host BaB's score·width with widest-dim fallback,
        # first-max tie-break (= its stable argsort head); PA dims barred.
        widths = (q_hi - q_lo) * branch_mask
        can = q_live & (widths.max(-1) > 0.0)
        sc = score * widths
        sc = jnp.where(sc.max(-1, keepdims=True) > 0.0, sc, widths)
        sc = jnp.where(branch_mask > 0.0, sc, -1.0)
        dim = jnp.argmax(sc, axis=-1).astype(jnp.int32)
        lo_d = jnp.take_along_axis(q_lo, dim[:, None], axis=1)[:, 0]
        hi_d = jnp.take_along_axis(q_hi, dim[:, None], axis=1)[:, 0]
        mid_d = jnp.floor((lo_d + hi_d) * 0.5)
        # Compaction: rank the free slots and the splitters, pair them up.
        # A latched slot is NOT free (the witness must survive to decode);
        # the canary slot (slot_ok False) is never allocated.
        free = (~q_live) & slot_ok & (~found)
        rank_f = jnp.cumsum(free.astype(jnp.int32)) - 1
        n_free = free.sum()
        table = jnp.full((Q,), Q, jnp.int32).at[
            jnp.where(free, rank_f, Q)].set(
                jnp.arange(Q, dtype=jnp.int32), mode="drop")
        rank_c = jnp.cumsum(can.astype(jnp.int32)) - 1
        fits = can & (rank_c < n_free)
        dest = jnp.where(
            fits,
            jnp.take(table,
                     jnp.minimum(jnp.maximum(rank_c, 0), Q - 1)),
            Q)
        over = can & ~fits
        r_over = r_over.at[q_root].add(over.astype(jnp.int32), mode="drop")
        r_splits = r_splits.at[q_root].add(fits.astype(jnp.int32),
                                           mode="drop")
        # Children: upper half [mid+1, hi] into the free slot; the parent
        # keeps the lower half — unless the split overflowed, in which case
        # it keeps the WHOLE box and retries when capacity frees up.
        oh = dim_ids[None, :] == dim[:, None]
        child_lo = jnp.where(oh, mid_d[:, None] + 1.0, q_lo)
        child_hi = q_hi
        q_hi = jnp.where(oh & fits[:, None], mid_d[:, None], q_hi)
        q_lo = q_lo.at[dest].set(child_lo, mode="drop")
        q_hi = q_hi.at[dest].set(child_hi, mode="drop")
        q_root = q_root.at[dest].set(q_root, mode="drop")
        q_live = q_live.at[dest].set(fits, mode="drop")
        return ((q_lo, q_hi, q_root, q_live, found, wa, wb, wpt,
                 r_nodes, r_splits, r_over), None)

    zeros_g = jnp.zeros((G,), jnp.int32)
    carry = (q_lo, q_hi, q_root, q_live, q_found, wit_a, wit_b, wit_pt,
             zeros_g, zeros_g, zeros_g)
    carry, _ = jax.lax.scan(round_body, carry, None, length=rounds)
    return carry + (_fold_dev(*carry),)


_bab_segment_kernel = obs_jit(_bab_segment_impl, name="engine.bab_segment",
                              static_argnames=("rounds", "alpha_iters"))


def no_flip_certified(
    lb_x, ub_x, lb_p, ub_p, valid_assign: np.ndarray, valid_pair: np.ndarray
) -> np.ndarray:
    """Per-box fairness certificate from role logit bounds (all numpy).

    For a valid pair (a, b): flip x⁺/x'⁻ impossible iff ``ub_x[a] ≤ 0`` or
    ``lb_p[b] ≥ 0``; flip x⁻/x'⁺ impossible iff ``lb_x[a] ≥ 0`` or
    ``ub_p[b] ≤ 0``.  Certified iff impossible for every valid pair.  This is
    strictly finer than requiring a uniform output sign over the box.
    """
    lb_x, ub_x, lb_p, ub_p = (np.asarray(v) for v in (lb_x, ub_x, lb_p, ub_p))
    pair_ok = valid_pair & valid_assign[..., :, None] & valid_assign[..., None, :]
    t1_dead = (ub_x[..., :, None] <= 0.0) | (lb_p[..., None, :] >= 0.0)
    t2_dead = (lb_x[..., :, None] >= 0.0) | (ub_p[..., None, :] <= 0.0)
    possible = pair_ok & ~(t1_dead & t2_dead)
    return ~possible.any(axis=(-2, -1))


@obs_jit
def _attack_logits(net: MLP, x_roles, xp_roles):
    """Forward logits for attack candidates; shapes (..., V, d) → (..., V)."""
    from fairify_tpu.models.mlp import forward

    return forward(net, x_roles), forward(net, xp_roles)


def build_attack_candidates(
    enc: PairEncoding, rng: np.random.Generator, lo: np.ndarray, hi: np.ndarray, n_samples: int
):
    """Integer attack samples for a batch of boxes.

    Returns ``(x_roles, xp_roles)`` of shape (B, S, V, d): shared coordinates
    drawn uniformly per box, PA dims overwritten by each assignment, RA dims
    of the x' role shifted by a uniform delta in [-ε, ε] (unclamped, see
    ``property.role_boxes``).
    """
    lo = np.asarray(lo, dtype=np.int64)
    hi = np.asarray(hi, dtype=np.int64)
    B, d = lo.shape
    V = enc.n_assign
    shared = rng.integers(lo[:, None, :], hi[:, None, :] + 1, size=(B, n_samples, d))
    x_roles = np.repeat(shared[:, :, None, :], V, axis=2).astype(np.float32)
    if len(enc.pa_idx):
        x_roles[..., enc.pa_idx] = enc.assignments.astype(np.float32)
    xp_roles = x_roles.copy()
    if len(enc.ra_idx) and enc.eps:
        delta = rng.integers(-enc.eps, enc.eps + 1, size=(B, n_samples, 1, len(enc.ra_idx)))
        xp_roles[..., enc.ra_idx] = xp_roles[..., enc.ra_idx] + delta.astype(np.float32)
    return x_roles, xp_roles


def find_flips(
    enc: PairEncoding,
    logit_x: np.ndarray,
    logit_p: np.ndarray,
    valid_assign: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Locate strict sign flips among attack candidates.

    ``logit_x``/``logit_p``: (B, S, V).  ``valid_assign``: (B, V).
    Returns (found (B,), witness (B, 3) of [sample, a, b]).
    """
    return _find_flips_impl(np, logit_x, logit_p, valid_assign,
                            enc.valid_pair)


# ---------------------------------------------------------------------------
# Gradient attack (PGD on the flip objective over shared coordinates)
# ---------------------------------------------------------------------------


@obs_jit(static_argnames=("steps", "restarts"))
def _pgd_attack_kernel(
    net: MLP, lo, hi, assign_vals, pa_mask, ra_mask, valid, eps, key, steps: int, restarts: int
):
    """Projected-gradient attack on the pair property, fully batched.

    For each box, maximise ``min(max_a f(x(s,a)), -min_b f(x'(s,b,r)))`` over
    the *shared* coordinates ``s`` (continuous relaxation of the box) and the
    RA shift ``r`` — positive objective ⇒ some assignment pair flips.  The
    counterexamples the random sampler misses live in narrow slabs of the
    shared space (the logit crosses zero on a measure-tiny band); following
    the logit gradient finds them in tens of steps.  One jit: ``lax.scan``
    over PGD steps of a (boxes × restarts × assignments) forward/backward
    batch.  Final points are rounded to the integer lattice.

    ``assign_vals``: (V, d) assignments scattered into input dims (0 off-PA);
    ``pa_mask``/``ra_mask``: (d,) indicator of PA / RA dims; ``valid``:
    (B, V) in-box assignment mask.
    """
    from fairify_tpu.models.mlp import forward

    B, d = lo.shape
    lo_b = lo[:, None, :]
    hi_b = hi[:, None, :]
    width = hi_b - lo_b

    def build(s, r):
        x = s[..., None, :] * (1.0 - pa_mask) + assign_vals
        xp = x + (r * ra_mask)[..., None, :]
        return x, xp

    def objective(s, r):
        x, xp = build(s, r)
        fx = forward(net, x)
        fp = forward(net, xp)
        fxm = jnp.where(valid[:, None, :], fx, -jnp.inf).max(axis=-1)
        fpm = jnp.where(valid[:, None, :], fp, jnp.inf).min(axis=-1)
        return jnp.minimum(fxm, -fpm)

    k_s, k_r = jax.random.split(key)
    s0 = lo_b + jax.random.uniform(k_s, (B, restarts, d)) * width
    r0 = jax.random.uniform(k_r, (B, restarts, d), minval=-1.0, maxval=1.0) * eps

    grad_fn = jax.grad(lambda s, r: objective(s, r).sum(), argnums=(0, 1))

    def step(carry, t):
        s, r = carry
        g_s, g_r = grad_fn(s, r)
        decay = 0.85 ** t
        alpha = jnp.maximum(0.35 * width, 0.5) * decay
        s = jnp.clip(s + alpha * jnp.sign(g_s), lo_b, hi_b)
        r = jnp.clip(r + (0.35 * eps + 0.5) * decay * jnp.sign(g_r), -eps, eps)
        return (s, r), None

    (s, r), _ = jax.lax.scan(step, (s0, r0), jnp.arange(steps))
    s = jnp.clip(jnp.round(s), lo_b, hi_b)
    r = jnp.round(r) * ra_mask
    x, xp = build(s, r)
    return forward(net, x), forward(net, xp), x, xp


def _enc_tensors(enc: PairEncoding, d: int):
    """Dense scatter tensors of an encoding for the PGD kernel."""
    assign_vals = np.zeros((enc.n_assign, d), dtype=np.float32)
    pa_mask = np.zeros(d, dtype=np.float32)
    ra_mask = np.zeros(d, dtype=np.float32)
    if len(enc.pa_idx):
        assign_vals[:, enc.pa_idx] = enc.assignments.astype(np.float32)
        pa_mask[enc.pa_idx] = 1.0
    if len(enc.ra_idx):
        ra_mask[enc.ra_idx] = 1.0
    return assign_vals, pa_mask, ra_mask


def pgd_attack_submit(
    net: MLP,
    enc: PairEncoding,
    lo: np.ndarray,
    hi: np.ndarray,
    rng: np.random.Generator,
    steps: int = 30,
    restarts: int = 32,
):
    """Dispatch one PGD attack launch without syncing on its results.

    Returns ``(payload, ctx)`` for :class:`parallel.pipeline.LaunchPipeline`:
    ``payload`` is the kernel's device-array tuple (materializing
    asynchronously), ``ctx`` the host-side state :func:`pgd_attack_decode`
    needs.  The batch is padded to the next power of two so the scan+grad
    kernel compiles once per (net, padded-size), not once per leftover
    count.
    """
    lo = np.asarray(lo, dtype=np.int64)
    hi = np.asarray(hi, dtype=np.int64)
    B, d = lo.shape
    pad_to = 1 << max(B - 1, 0).bit_length()
    lo_p, hi_p = _pad(lo, pad_to), _pad(hi, pad_to)
    assign_vals, pa_mask, ra_mask = _enc_tensors(enc, d)
    if len(enc.pa_idx):
        valid = (
            (enc.assignments[None, :, :] >= lo_p[:, None, enc.pa_idx])
            & (enc.assignments[None, :, :] <= hi_p[:, None, enc.pa_idx])
        ).all(axis=-1)
    else:
        valid = np.zeros((pad_to, enc.n_assign), dtype=bool)
    key = jax.random.PRNGKey(int(rng.integers(2**31)))
    profiling.bump_launch()
    payload = _pgd_attack_kernel(
        net,
        jnp.asarray(lo_p, jnp.float32), jnp.asarray(hi_p, jnp.float32),
        jnp.asarray(assign_vals), jnp.asarray(pa_mask), jnp.asarray(ra_mask),
        jnp.asarray(valid), float(enc.eps), key, steps, restarts,
    )
    ctx = {"net": net, "enc": enc, "valid": valid, "B": B, "pad_to": pad_to}
    return payload, ctx


def pgd_attack_decode(host_payload, ctx, return_points: bool = False):
    """Host decode of a drained PGD launch: flip extraction + exact checks."""
    fx, fp, x, xp = (np.asarray(v) for v in host_payload)
    enc, valid, B, pad_to = ctx["enc"], ctx["valid"], ctx["B"], ctx["pad_to"]
    found, wit = find_flips(enc, fx, fp, valid)
    net = ctx["net"]
    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    witnesses = extract_witnesses(found, wit, x, xp, weights, biases, limit=B)
    if not return_points:
        return witnesses
    # Per box, the role point with the smallest |logit| among valid
    # assignments — the natural seed for the exact flip-slab search.
    fx_np = np.abs(np.asarray(fx, dtype=np.float64))
    fx_np = np.where(valid[:, None, :], fx_np, np.inf)
    flat = fx_np.reshape(pad_to, -1)
    idx = flat.argmin(axis=1)
    V = fx_np.shape[2]
    si, vi = np.divmod(idx, V)
    pts = x[np.arange(pad_to), si, vi][:B]
    best_abs = flat[np.arange(pad_to), idx][:B]
    return witnesses, pts, best_abs


def pgd_attack(
    net: MLP,
    enc: PairEncoding,
    lo: np.ndarray,
    hi: np.ndarray,
    rng: np.random.Generator,
    steps: int = 30,
    restarts: int = 32,
    return_points: bool = False,
):
    """Gradient attack over a batch of boxes → exact-validated witnesses.

    Returns ``{box_index: (x, xp)}`` for every box where a rounded PGD point
    is a genuine strict flip (checked in exact arithmetic).  Synchronous
    composition of :func:`pgd_attack_submit` + :func:`pgd_attack_decode`;
    pipelined callers use the split form so the next chunk's launch is in
    flight while this one's witnesses are validated.
    """
    payload, ctx = pgd_attack_submit(net, enc, lo, hi, rng,
                                     steps=steps, restarts=restarts)
    return pgd_attack_decode(jax.device_get(payload), ctx,
                             return_points=return_points)


def extract_witnesses(found, wit, x_cand, xp_cand, weights, biases, limit=None) -> dict:
    """Exact-validated witness dict from ``find_flips`` output.

    ``x_cand``/``xp_cand``: (B, S, V, d) candidate role points.  Shared by
    the stage-0 random attack, the family-stacked attack, and the PGD
    attack so the extraction semantics can never diverge between them.
    """
    witnesses = {}
    for i in np.where(found)[0]:
        if limit is not None and i >= limit:
            continue
        s, a, b = wit[i]
        x = x_cand[i, s, a].astype(np.int64)
        xp = xp_cand[i, s, b].astype(np.int64)
        if validate_pair(weights, biases, x, xp):
            witnesses[int(i)] = (x, xp)
    return witnesses


# ---------------------------------------------------------------------------
# Exact host-side checks
# ---------------------------------------------------------------------------


def exact_logit_sign(weights, biases, x: np.ndarray) -> int:
    """Sign of the network logit at integer point x, exact when ambiguous.

    Float64 forward first; if the result is within 1e-6 of zero, re-evaluate
    in rational arithmetic (f32 weights are dyadic rationals, so this is the
    true sign — the quantity Z3 would have reasoned about,
    ``utils/GC-1-Model-Functions.py:32-44``).
    """
    from fairify_tpu.models.mlp import forward_np

    v = float(forward_np(weights, biases, np.asarray(x, dtype=np.float64)))
    if abs(v) > 1e-6:
        return 1 if v > 0 else -1
    from fairify_tpu.ops import exact_native

    nat = exact_native.forward_signs(weights, biases, np.asarray(x, dtype=np.int64)[None, :])
    if nat is not None:
        return int(nat[0])
    hf = [Fraction(int(t)) for t in np.asarray(x, dtype=np.int64)]
    for i, (w, b) in enumerate(zip(weights, biases)):
        wf = np.asarray(w, dtype=np.float64)
        bf = np.asarray(b, dtype=np.float64)
        nxt = []
        for j in range(wf.shape[1]):
            acc = Fraction(float(bf[j]))
            for t in range(wf.shape[0]):
                acc += Fraction(float(wf[t, j])) * hf[t]
            if i < len(weights) - 1 and acc < 0:
                acc = Fraction(0)
            nxt.append(acc)
        hf = nxt
    v = hf[0]
    return 0 if v == 0 else (1 if v > 0 else -1)


def validate_pair(weights, biases, x: np.ndarray, xp: np.ndarray) -> bool:
    """Exact strict-flip check for a candidate counterexample pair."""
    sx = exact_logit_sign(weights, biases, x)
    sp = exact_logit_sign(weights, biases, xp)
    return (sx > 0 and sp < 0) or (sx < 0 and sp > 0)


def decide_leaf(enc: PairEncoding, weights, biases, point: np.ndarray, lo, hi):
    """Exactly decide a leaf box (all shared dims collapsed to one point).

    Enumerates PA assignment pairs and, for RA dims, the full delta lattice
    [-ε, ε]^|RA|.  Returns ('sat', (x, xp)), ('unsat', None), or
    ('unknown', None) when the delta lattice is too large to enumerate —
    (2ε+1)^|RA| is exponential in the relaxed-attribute count, so a future
    preset with several RA dims degrades to an honest UNKNOWN instead of
    silently stalling the sweep (today's presets use |RA| ≤ 1, ε = 5).
    """
    import itertools as it

    if len(enc.ra_idx) and enc.eps and \
            (2 * enc.eps + 1) ** len(enc.ra_idx) > 100_000:
        return "unknown", None
    from fairify_tpu.verify.property import valid_assignments

    lo = np.asarray(lo)
    hi = np.asarray(hi)
    valid = valid_assignments(enc, lo, hi)
    deltas = (
        list(it.product(range(-enc.eps, enc.eps + 1), repeat=len(enc.ra_idx)))
        if (len(enc.ra_idx) and enc.eps)
        else [()]
    )
    sign_x = {}
    for a in valid:
        x = np.array(point, dtype=np.int64)
        x[enc.pa_idx] = enc.assignments[a]
        sign_x[a] = exact_logit_sign(weights, biases, x)
    for a in valid:
        if sign_x[a] == 0:
            continue
        for b in valid:
            if not enc.valid_pair[a, b]:
                continue
            for dl in deltas:
                xp = np.array(point, dtype=np.int64)
                xp[enc.pa_idx] = enc.assignments[b]
                for k, dv in enumerate(dl):
                    xp[enc.ra_idx[k]] += dv
                sp = (
                    sign_x[b]
                    if not dl or all(v == 0 for v in dl)
                    else exact_logit_sign(weights, biases, xp)
                )
                if (sign_x[a] > 0 and sp < 0) or (sign_x[a] < 0 and sp > 0):
                    x = np.array(point, dtype=np.int64)
                    x[enc.pa_idx] = enc.assignments[a]
                    return "sat", (x, xp)
    return "unsat", None


# ---------------------------------------------------------------------------
# Uniform-sign branch-and-bound (neuron splits)
# ---------------------------------------------------------------------------


@obs_jit(static_argnames=("alpha_iters",))
def _sign_bound_kernel(net: MLP, lo, hi, signs, alpha_iters: int):
    return crown_ops.sign_constrained_output_bounds(net, lo, hi, signs,
                                                    alpha_iters=alpha_iters)


@obs_jit
def _inter_bounds_kernel(net: MLP, lo, hi):
    """Batched CROWN pre-activation bounds (device) for the host LP phase."""
    b = crown_ops.crown_bounds(net, lo, hi)
    return b.ws_lb, b.ws_ub


def _leaf_sign_lp(weights, biases, masks, pattern, lo, hi, want_positive: bool):
    """LP endgame for a fully-resolved sign-BaB branch (affine region).

    With every alive neuron's activation sign resolved, the network is
    affine over the branch region {x ∈ box : s_j·z_j(x) ≥ 0 ∀j}, so the
    region extremum is one small LP (13-30 vars, ≤ ~130 constraints;
    scipy/HiGHS solves it in milliseconds) — the LP-duality optimum the
    iterative β optimizer approaches.  Evidence class: f64-with-margin,
    the same posture as the f32+slack CROWN certificates this engine's
    UNSAT verdicts already rest on (and audited the same way, by the
    certificate-attack harness) — NOT exact rational arithmetic like the
    SAT-witness path.  'certified' therefore requires the extremum to clear
    0 by an absolute+relative margin, and borderline extrema return
    'mixed' so the pair BaB re-examines the root.  Returns 'certified' |
    'infeasible' (region empty per HiGHS) | 'mixed'.
    """
    from scipy.optimize import linprog

    d = len(lo)
    A = np.eye(d)
    c = np.zeros(d)
    A_cons, b_cons = [], []
    for i, (w, b) in enumerate(zip(weights, biases)):
        w = np.asarray(w, np.float64)
        b = np.asarray(b, np.float64)
        Az = A @ w
        cz = c @ w + b
        if i < len(weights) - 1:
            s = np.asarray(pattern[i])
            m = np.asarray(masks[i]) > 0.5
            if ((s == 0) & m).any():
                return "mixed"  # unresolved neuron: region not affine
            for j in np.where(m)[0]:
                sj = float(s[j])
                A_cons.append(-sj * Az[:, j])
                b_cons.append(sj * cz[j])
            act = (m & (s > 0)).astype(np.float64)
            A = Az * act[None, :]
            c = cz * act
        else:
            A, c = Az, cz
    g = A[:, 0]
    c0 = float(c[0])
    sense = 1.0 if want_positive else -1.0
    res = linprog(sense * g,
                  A_ub=np.stack(A_cons) if A_cons else None,
                  b_ub=np.asarray(b_cons) if b_cons else None,
                  bounds=list(zip(np.asarray(lo, float), np.asarray(hi, float))),
                  method="highs")
    if res.status == 2:
        return "infeasible"
    if res.status != 0 or res.fun is None:
        return "mixed"
    extremum = sense * res.fun + c0  # min f if want_positive else max f
    # Margin against f64 accumulation in the affine forms and HiGHS
    # tolerances: scaled by the form magnitudes, floor 1e-5.
    scale = max(abs(c0), float(np.abs(g).sum()), 1.0)
    margin = 1e-5 + 1e-7 * scale
    if want_positive and extremum > margin:
        return "certified"
    if (not want_positive) and extremum < -margin:
        return "certified"
    return "mixed"


@obs_jit
def _sample_role_logits(net: MLP, x_roles, xp_roles):
    from fairify_tpu.models.mlp import forward

    return forward(net, x_roles), forward(net, xp_roles)


def uniform_sign_bab(
    net: MLP,
    enc: PairEncoding,
    roots_lo: np.ndarray,
    roots_hi: np.ndarray,
    cfg: "EngineConfig",
    deadline_s: float,
    mesh=None,
) -> list:
    """Prove a uniform logit sign over each root box via neuron-split BaB.

    A uniform output sign over the (RA-widened) partition box forbids every
    flip pair at once — the decisive certificate for deep nets whose logit
    is far from zero on average but whose input-split bounds converge too
    slowly (e.g. the adult AC-7 64-32-16-8-4-1 model, where the reference's
    Z3 also times out, ``BASELINE.md`` AC7 rows).  Branching is on *neuron
    activation signs* (β-CROWN-family splits, primal form — see
    :func:`fairify_tpu.ops.crown.sign_constrained_output_bounds`), with all
    roots sharing one padded device frontier like :func:`decide_many`.

    Per root the conjectured sign comes from sampled role logits; a sample
    with the opposite sign, an exhausted node budget, or a branch whose
    bound contradicts the conjecture marks the root 'mixed' (hand it to the
    pair BaB).  Returns ``(verdicts, nodes, cost_s, lp_cost_s)``: per-root
    verdicts ('unsat' | 'mixed'), sign-BaB node counts, attributed wall time
    (each batch's time split evenly over its sub-boxes, same additive
    accounting as :func:`decide_many`, so per-root costs sum ≈ phase total),
    and the Phase-L (host LP) share of that time — per-phase attribution
    surfaced through ``Decision.stats``.
    """
    t0 = time.perf_counter()
    R = roots_lo.shape[0]
    n_hidden = net.depth - 1
    if n_hidden == 0 or not len(enc.pa_idx):
        return (["mixed"] * R, np.zeros(R, np.int64),
                np.zeros(R, np.float64), np.zeros(R, np.float64))
    F = cfg.frontier_size
    if mesh is not None:
        from fairify_tpu.parallel import mesh as mesh_mod

        bound_net = mesh_mod.replicated(mesh, net)
    else:
        bound_net = net
    host_w = [np.asarray(w) for w in net.weights]
    host_b = [np.asarray(b) for b in net.biases]
    host_m = [np.asarray(m) for m in net.masks]

    # The sign box: PA dims already span the partition's PA range; RA dims
    # widen by ε because x' may leave the box (property.role_boxes).
    slo = np.asarray(roots_lo, dtype=np.int64).copy()
    shi = np.asarray(roots_hi, dtype=np.int64).copy()
    if len(enc.ra_idx) and enc.eps:
        slo[:, enc.ra_idx] -= enc.eps
        shi[:, enc.ra_idx] += enc.eps

    # Sign conjecture: role logits at sampled shared points — any mixed
    # sample disqualifies the root immediately (it cannot be uniform).
    rng = np.random.default_rng(cfg.seed + 3)
    xr, pr = build_attack_candidates(enc, rng, roots_lo, roots_hi, 32)
    # Pad the root axis to the next power of two AFTER drawing candidates
    # (RNG consumption — and therefore every verdict — is unchanged): R
    # tracks the UNKNOWN frontier and varies per model, so an unpadded
    # launch compiles one executable per distinct root count — the
    # signature churn behind the SERVE_r01 mid-load recompiles (7 at 16
    # clients).  Pad rows recompute the last root and are sliced away.
    r_pad = 1 << max(xr.shape[0] - 1, 0).bit_length()
    profiling.bump_launch()
    lx, lp = _sample_role_logits(net, jnp.asarray(_pad(xr, r_pad)),
                                 jnp.asarray(_pad(pr, r_pad)))
    lx, lp = np.asarray(lx)[:xr.shape[0]], np.asarray(lp)[:xr.shape[0]]
    va = None
    if len(enc.pa_idx):
        from fairify_tpu.verify.property import role_boxes

        _, _, _, _, va = role_boxes(enc, roots_lo.astype(np.float32),
                                    roots_hi.astype(np.float32))
    # ±inf fill (not NaN) keeps nanmin's all-NaN RuntimeWarning out of long
    # sweeps; a root with no valid PA assignment is trivially non-candidate
    # (has_valid guard), not a numerical edge case.
    allv_min = np.concatenate([
        np.where(va[:, None, :], lx, np.inf).reshape(R, -1),
        np.where(va[:, None, :], lp, np.inf).reshape(R, -1)], axis=1)
    allv_max = np.concatenate([
        np.where(va[:, None, :], lx, -np.inf).reshape(R, -1),
        np.where(va[:, None, :], lp, -np.inf).reshape(R, -1)], axis=1)
    has_valid = va.any(axis=-1)
    want_pos = (allv_min.min(axis=1) > 0.0) & has_valid
    want_neg = (allv_max.max(axis=1) < 0.0) & has_valid
    candidate = want_pos | want_neg

    from collections import deque

    hidden_sizes = [int(b.shape[0]) for b in net.biases[:n_hidden]]
    zero_signs = [np.zeros(n, dtype=np.int8) for n in hidden_sizes]
    verdicts = ["mixed"] * R
    settled = np.zeros(R, dtype=bool)
    settled[~candidate] = True
    nodes = np.zeros(R, dtype=np.int64)
    # Device-frontier splits only — the sign_max_nodes cap must not count
    # Phase-L LP nodes (a root whose LP tree returned 'budget' with
    # n_lp > sign_max_nodes would otherwise be failed before its first
    # device split, losing the sign path entirely).
    dev_nodes = np.zeros(R, dtype=np.int64)
    cost_s = np.zeros(R, dtype=np.float64)
    lp_cost = np.zeros(R, dtype=np.float64)  # Phase-L share of cost_s

    # Phase L — complete LP BaB (ops.lp) on candidates with few unstable
    # ReLUs.  One batched device launch computes CROWN pre-activation bounds
    # for every candidate box; each box with ≤ lp_sign_max_unstable unstable
    # neurons is then closed by the host triangle-relaxation BaB (tens of
    # millisecond-LPs — the AC-7 residue that round 2's device β-CROWN
    # frontier burned 2,000+ s on closes in ~0.1 s/box this way).  'refuted'
    # boxes are settled as 'mixed' immediately (no sign method can certify
    # them); only 'budget' boxes fall through to the device frontier.
    if cfg.lp_sign and candidate.any():
        from fairify_tpu.ops import lp as lp_ops

        cand = np.where(candidate)[0]
        n_layers = net.depth
        pre_lb_all = [None] * n_layers
        pre_ub_all = [None] * n_layers
        for s in range(0, len(cand), F):
            blk = cand[s: s + F]
            blo = _pad(slo[blk].astype(np.float32), F)
            bhi = _pad(shi[blk].astype(np.float32), F)
            if mesh is not None:
                blo, bhi = mesh_mod.shard_parts(mesh, blo, bhi)
            profiling.bump_launch()
            wl, wu = _inter_bounds_kernel(bound_net, jnp.asarray(blo), jnp.asarray(bhi))
            for L in range(n_layers):
                if pre_lb_all[L] is None:
                    width = int(wl[L].shape[-1])
                    pre_lb_all[L] = np.zeros((R, width), np.float32)
                    pre_ub_all[L] = np.zeros((R, width), np.float32)
                pre_lb_all[L][blk] = np.asarray(wl[L])[: len(blk)]
                pre_ub_all[L][blk] = np.asarray(wu[L])[: len(blk)]
        unstable = np.zeros(R, dtype=np.int64)
        for L in range(n_hidden):
            alive = host_m[L] > 0.5
            unstable[cand] += (
                (pre_lb_all[L][cand] < 0.0)
                & (pre_ub_all[L][cand] > 0.0)
                & alive[None, :]
            ).sum(axis=1)
        for r in cand[np.argsort(unstable[cand], kind="stable")]:
            r = int(r)
            remaining = deadline_s - (time.perf_counter() - t0)
            if remaining <= 0.0:
                break
            if unstable[r] > cfg.lp_sign_max_unstable:
                break  # sorted ascending: the rest are all larger
            t_r = time.perf_counter()
            outcome, n_lp = lp_ops.sign_bab_lp(
                host_w, host_b, host_m, slo[r], shi[r],
                [pre_lb_all[L][r] for L in range(n_hidden)],
                [pre_ub_all[L][r] for L in range(n_hidden)],
                bool(want_pos[r]),
                max_nodes=cfg.lp_sign_max_nodes,
                deadline_s=min(cfg.soft_timeout_s, remaining),
            )
            nodes[r] += n_lp
            dt_r = time.perf_counter() - t_r
            cost_s[r] += dt_r
            lp_cost[r] += dt_r
            if outcome == "certified":
                verdicts[r] = "unsat"
                settled[r] = True
            elif outcome == "refuted":
                settled[r] = True  # verdict stays 'mixed'

    frontier = deque((r, zero_signs) for r in range(R)
                     if candidate[r] and not settled[r])
    open_n = (candidate & ~settled).astype(np.int64)

    def fail(r):
        settled[r] = True  # verdict stays 'mixed'

    while frontier:
        if (time.perf_counter() - t0) > deadline_s:
            break
        t_iter = time.perf_counter()
        batch_items = []
        while frontier and len(batch_items) < F:
            r, sgn = frontier.popleft()
            if settled[r]:
                continue
            batch_items.append((r, sgn))
        if not batch_items:
            break
        batch = len(batch_items)
        broot = np.array([r for r, _ in batch_items])
        blo = _pad(slo[broot].astype(np.float32), F)
        bhi = _pad(shi[broot].astype(np.float32), F)
        bsigns = tuple(
            _pad(np.stack([sgn[j] for _, sgn in batch_items]).astype(np.float32), F)
            for j in range(n_hidden))
        # subtract.at, not fancy-index -=: a root's two children routinely
        # share a batch, and x[idx] -= 1 decrements duplicates only once.
        np.subtract.at(open_n, broot, 1)
        np.add.at(nodes, broot, 1)
        np.add.at(dev_nodes, broot, 1)
        if mesh is not None:
            blo, bhi, *bsigns = mesh_mod.shard_parts(mesh, blo, bhi, *bsigns)
            bsigns = tuple(bsigns)
        profiling.bump_launch()
        out_lo, out_hi, feasible, scores, resolved = _sign_bound_kernel(
            bound_net, jnp.asarray(blo), jnp.asarray(bhi),
            tuple(jnp.asarray(s) for s in bsigns), cfg.alpha_iters)
        out_lo = np.asarray(out_lo)[:batch]
        out_hi = np.asarray(out_hi)[:batch]
        feasible = np.asarray(feasible)[:batch]
        scores = [np.asarray(s)[:batch] for s in scores]
        resolved = [np.asarray(s)[:batch] for s in resolved]

        for k, (r, sgn) in enumerate(batch_items):
            if settled[r]:
                continue
            if not feasible[k]:
                pass  # empty branch region: discharged
            elif (want_pos[r] and out_lo[k] > 0.0) or \
                    (want_neg[r] and out_hi[k] < 0.0):
                pass  # branch certified
            elif dev_nodes[r] > cfg.sign_max_nodes:
                fail(r)
                continue
            elif (want_pos[r] and out_hi[k] < 0.0) or \
                    (want_neg[r] and out_lo[k] > 0.0):
                # Bound contradicts the conjecture on a (possibly empty)
                # branch — heuristic bail, the pair BaB owns this root.
                fail(r)
                continue
            else:
                flat = [s[k] for s in scores]
                best_layer, best_idx, best_val = -1, -1, 0.0
                for j, s in enumerate(flat):
                    i = int(s.argmax())
                    if s[i] > best_val:
                        best_layer, best_idx, best_val = j, i, float(s[i])
                if best_layer < 0:
                    # Fully-resolved branch: the region is affine — finish
                    # it exactly with the leaf LP (β at its dual optimum).
                    outcome = _leaf_sign_lp(
                        host_w, host_b, host_m, [rv[k] for rv in resolved],
                        slo[r], shi[r], bool(want_pos[r]))
                    if outcome == "mixed":
                        fail(r)
                        continue
                    # certified / infeasible: branch discharged.
                else:
                    for forced in (1, -1):
                        child = list(sgn)
                        child[best_layer] = child[best_layer].copy()
                        child[best_layer][best_idx] = forced
                        frontier.append((r, child))
                    open_n[r] += 2
        # Settle only after the whole batch: settling inside the item loop
        # would declare a root done while its popped-but-unevaluated sibling
        # is still in this very batch (it would then be skipped unsoundly).
        for r in set(int(x) for x in broot):
            if not settled[r] and open_n[r] == 0:
                verdicts[r] = "unsat"
                settled[r] = True
        np.add.at(cost_s, broot, (time.perf_counter() - t_iter) / batch)
    return verdicts, nodes, cost_s, lp_cost


# ---------------------------------------------------------------------------
# Branch-and-bound
# ---------------------------------------------------------------------------


@dataclass
class EngineConfig:
    use_crown: bool = True
    # α-CROWN signed-gradient slope-optimization steps for branch-and-bound
    # bounds (0 = plain CROWN).  Stage-0 stays plain CROWN — the whole grid
    # rarely needs the extra backward passes; the BaB leftovers do.
    alpha_iters: int = 8
    attack_samples: int = 128
    bab_attack_samples: int = 16
    frontier_size: int = 512
    max_nodes: int = 200_000
    soft_timeout_s: float = 100.0
    seed: int = 0
    # Phase A: deep PGD attack on every root before any certificate work.
    # The r4 profile (audits/profile_r4.json) showed the slow tail is
    # mostly SAT roots whose witnesses the stage-0 attack missed: sign-BaB
    # then burned ~10k nodes/root "certifying" boxes that have
    # counterexamples (BM-4: 80 of 115 s), and the pair BaB spent seconds
    # of serial kernel launches re-finding them by sub-box sampling.  One
    # deeper PGD launch (more restarts than stage 0, fresh seed) settles
    # 35-58% of those leftovers up front.
    pgd_phase: bool = True
    pgd_steps: int = 60
    pgd_restarts: int = 96
    # Uniform-sign neuron-split BaB pre-phase (uniform_sign_bab): the
    # certificate of choice for deep nets whose logit range excludes zero
    # over most of the box; sign_bab_frac caps its share of the deadline.
    # 0.2 (round 4, was 0.5): with Phase A settling missed-witness SATs
    # and Phase L closing the deep UNSATs, the device frontier is a
    # narrower specialist — r4 knob study: BM-4 sample 76.8→35.6 s and
    # AC-7 sample 86.3→28.4 s at identical verdicts.
    sign_bab: bool = True
    sign_bab_frac: float = 0.2
    # Per-root cap on the DEVICE sign frontier (Phase L's LP trees have
    # their own lp_sign_max_nodes): a root that has not certified by a
    # thousand-odd sign splits almost never will (the genuinely-deep UNSAT
    # roots close via the LP path), while SAT roots the attack missed can
    # otherwise burn 10k+ nodes here before the pair BaB gets a chance
    # (BM-4 class, audits/profile_r4.json).
    sign_max_nodes: int = 1500
    # Phase L: complete triangle-relaxation LP BaB (ops.lp) for sign
    # candidates whose box has few unstable ReLUs — the AC-7-residue
    # closer.  max_unstable gates which roots take the host LP path;
    # max_nodes bounds each root's LP tree.
    lp_sign: bool = True
    lp_sign_max_unstable: int = 64
    lp_sign_max_nodes: int = 4000
    # Phase P: relational pair LP BaB (ops.lp.pair_bab_lp) for roots the
    # input-split BaB leaves unknown — the certificate for boxes whose
    # role logits straddle zero but track each other (ε-relaxed AC-7
    # class).  lp_pair_frac of the deadline is reserved for it;
    # max_dirs caps the assignment-pair fan-out per root.
    lp_pair: bool = True
    lp_pair_frac: float = 0.25
    lp_pair_max_nodes: int = 800
    lp_pair_max_dirs: int = 32
    # Phase E: exhaustive integer-lattice enumeration (ops.lattice) for
    # RA-free and k-RA (ε-dilated, separable window) roots still unknown
    # after every other phase — the complete decision for wide flip-slab
    # boxes the input-split BaB diverges on (stress-AC box 768: 67M lattice
    # points beat 3.4M BaB nodes).  Queries whose (2ε+1)^k delta window
    # exceeds the 10^5 margin-resolver cap are excluded (ADVICE r3 #3
    # scope note; 2-RA in round 4, any k within the cap in round 5).  lattice_max
    # gates the (ε-expanded) scan size (points); lattice_chunk is the
    # device batch per forward launch.
    lattice_exhaustive: bool = True
    lattice_max: float = 2.0e8
    # Chunk size trades XLA compile time (once per architecture) against
    # launch count; warm launches return only scalars/small buffers, so
    # smaller chunks win on the tunnelled single-chip setup (2^18: ~75 s
    # compile vs ~130 s at 2^21, ~3 ms per warm launch).
    lattice_chunk: int = 1 << 18
    # Fraction of the deadline reserved for Phase E when it is applicable —
    # without a reserve the input-split BaB and Phase P spend the whole
    # budget first and enumeration never runs.  The reserve PREEMPTS the
    # BaB, so it only engages when some eligible root is at least
    # lattice_reserve_min points — the flip-slab monsters BaB grinds on
    # fruitlessly.  Small-lattice roots don't need it: when BaB gives up
    # early (node caps), deadline is left over and Phase E runs anyway;
    # when BaB is productive, preempting it only slows the sweep (GC-1
    # headline: 3.4 s → 10.3 s with an unconditional reserve).
    lattice_frac: float = 0.2
    lattice_reserve_min: float = 1.0e6
    # Phase E0: roots whose (ε-dilated) enumerable lattice is at most this
    # many points get a TIME-BOXED exhaustive-enumeration probe BEFORE the
    # input-split BaB.  The scan early-exits on the first flip, so SAT
    # flip-slab boxes (the class BaB grinds 15-30 s on — r5 relaxed-AC
    # profile: 3 SAT roots burned 30.5 s of BaB before Phase E closed them)
    # usually settle in a chunk or two; a probe that neither flips nor
    # completes within lattice_first_cap_s returns unknown and the root
    # keeps its full BaB/P/E path, so at most the cap is wasted per root
    # (total bounded by 40% of the batch deadline).  Exact either way.
    lattice_first_max: float = 6.4e7
    lattice_first_cap_s: float = 5.0
    # Async launch pipeline depth for the engine's independent-batch loops
    # (Phase A PGD chunks): how many chunk launches stay in flight while the
    # host validates the previous chunk's witnesses.  The sweep syncs this
    # to SweepConfig.pipeline_depth; 1 restores synchronous order.
    pipeline_depth: int = 2
    # Launch supervision for the engine's pipelined loops (the sweep syncs
    # these to SweepConfig.max_launch_retries / launch_backoff_s): a
    # transient Phase-A chunk fault is retried this many times, then the
    # chunk's roots simply stay unattacked — they keep their full
    # certificate/BaB path, so only SAT-discovery speed is traded.
    max_launch_retries: int = 2
    launch_backoff_s: float = 0.05
    # --- Device-resident BaB (DESIGN.md §22) ---------------------------
    # Run the input-split pair BaB as lax.scan segments on device: the
    # frontier lives in a fixed-capacity slot queue carried through the
    # scan, with CROWN certify + domain clip + midpoint probe + split per
    # round, so bab_rounds_per_segment branching rounds cost ONE launch
    # instead of the host frontier's one launch per batch.  Requires
    # use_crown and no mesh (same gate as the sweep's mega path); the
    # host frontier loop remains the fallback.
    device_bab: bool = True
    # Slot capacity of the device box queue (+1 hidden canary slot when
    # integrity is on).  A split with no free slot overflows: the parent
    # keeps its whole box and retries later, and roots still overflowed
    # at exit report reason 'frontier:overflow' (raise this knob) instead
    # of 'frontier:hard'.  Decided verdicts are capacity-invariant
    # (tests/test_bab.py): slot scheduling never changes a box's bounds,
    # probes, or split points.
    bab_frontier_cap: int = 512
    # Branching rounds folded into one segment launch.  Segment 0 runs
    # plain CROWN (alpha_iters=0) and later segments α-CROWN — the host
    # loop's cheap-first escalation, keyed on the segment INDEX rather
    # than wall time so verdicts stay machine-independent.  Exactly two
    # kernel signatures per net (analysis/avals.py budget).
    bab_rounds_per_segment: int = 8
    # Device fold checksum + all-zero canary slot on the packed BaB
    # frontier buffers, verified at every segment decode
    # (integrity.verify_bab_segment); a mismatch degrades the segment's
    # root group, never trusts it.  The sweep syncs this to
    # SweepConfig.integrity.
    integrity: bool = True


@dataclass
class Decision:
    verdict: str  # 'sat' | 'unsat' | 'unknown'
    counterexample: Optional[Tuple[np.ndarray, np.ndarray]] = None
    nodes: int = 0
    leaves: int = 0
    elapsed_s: float = 0.0
    stats: dict = field(default_factory=dict)
    # Why an 'unknown' root stayed unknown: 'deadline' (the batch budget
    # tripped with sub-boxes still open — more time may decide it),
    # 'budget' (the per-root node budget ran out — more nodes may decide
    # it), 'frontier:overflow' (the device BaB queue ran out of slots
    # while the root still had splittable boxes — a CAPACITY fall, raise
    # bab_frontier_cap), 'frontier:hard' (the device BaB stalled at full
    # capacity / an exact leaf returned unknown: genuinely hard), or
    # legacy 'frontier' (the host-frontier path, or a degraded segment,
    # survived every phase).  None for decided roots.  Surfaced as the
    # `engine_reason` attr on the sweep's unknown verdict events and as
    # the funnel's `unknown:*` states (obs.funnel), so budget-vs-hardness
    # reads off the event log (the deep-retry harnesses re-attempt all
    # kinds today).
    reason: Optional[str] = None


def _branch_dims(enc: PairEncoding, d: int) -> np.ndarray:
    """Shared dims eligible for splitting: everything except PA (enumerated).
    Same universe lattice enumeration scans (``property.shared_dims``)."""
    from fairify_tpu.verify.property import shared_dims

    return shared_dims(enc, d)


def _pad(arr: np.ndarray, n: int) -> np.ndarray:
    if arr.shape[0] == n:
        return arr
    pad = np.repeat(arr[-1:], n - arr.shape[0], axis=0)
    return np.concatenate([arr, pad], axis=0)


def _device_bab_phase(net, enc, roots_lo, roots_hi, cfg, t0, deadline_s,
                      verdicts, ces, settle, nodes, leaves, cost_s,
                      weights, biases, assign_vals, pa_mask, ra_mask,
                      valid_pair_dev):
    """Drive the device-resident BaB over every still-undecided root.

    Roots are processed in fixed-size groups sharing one slot queue
    (capacity ``bab_frontier_cap``, + a canary slot when integrity is on);
    each group runs :func:`_bab_segment_kernel` segments — K branching
    rounds per launch — until every root settles, the queue stalls, the
    node budget trips, or the deadline does.  Between segments the host
    does only what MUST be exact or is intrinsically serial: witness
    latches are exact-validated (rational arithmetic, smallest candidate
    first so the settled counterexample is capacity-invariant), point
    leaves go through :func:`decide_leaf`, emptied roots settle UNSAT,
    and slots of settled roots are recycled.  Launch supervision and
    chaos/corruption injection ride the standard LaunchPipeline sites
    (``launch.submit`` / ``launch.decode``); the fold checksum + canary
    are re-verified at every decode, and a failed or corrupt segment
    degrades exactly its root group (the queue state never advances on a
    failed fetch, so nothing unsound can be absorbed).
    """
    from fairify_tpu.parallel.pipeline import LaunchPipeline
    from fairify_tpu.resilience import integrity as integrity_mod
    from fairify_tpu.resilience.supervisor import ChunkFailure, Supervisor

    d = roots_lo.shape[1]
    V = enc.n_assign
    Q = max(4, int(cfg.bab_frontier_cap))
    Qs = Q + 1 if cfg.integrity else Q
    G = max(1, Q // 4)
    branch_mask = np.zeros(d, np.float32)
    bd = _branch_dims(enc, d)
    if len(bd):
        branch_mask[bd] = 1.0
    branch_mask_dev = jnp.asarray(branch_mask)
    assignments = np.asarray(enc.assignments, np.int64)
    pa_idx = np.asarray(enc.pa_idx, dtype=np.int64)
    slot_ok = np.zeros(Qs, bool)
    slot_ok[:Q] = True
    slot_ok_dev = jnp.asarray(slot_ok)
    pending = [r for r in range(roots_lo.shape[0]) if verdicts[r] is None]
    pipe = LaunchPipeline(
        1, gauge=False,
        supervisor=Supervisor(max_retries=cfg.max_launch_retries,
                              backoff_s=cfg.launch_backoff_s, seed=cfg.seed))
    payload_keys = integrity_mod.BAB_FOLD_KEYS + ("csum",)

    for g0 in range(0, len(pending), G):
        group = pending[g0:g0 + G]
        if (time.perf_counter() - t0) > deadline_s:
            for r in pending[g0:]:
                settle(r, "unknown", reason="deadline")
            break
        g = len(group)
        q_lo = np.zeros((Qs, d), np.float32)
        q_hi = np.zeros((Qs, d), np.float32)
        q_root = np.zeros(Qs, np.int32)
        q_live = np.zeros(Qs, bool)
        q_found = np.zeros(Qs, bool)
        wit_a = np.zeros(Qs, np.int32)
        wit_b = np.zeros(Qs, np.int32)
        wit_pt = np.zeros((Qs, d), np.float32)
        # root_valid stays (G, V) even for a short tail group (pad rows are
        # unreachable: no slot carries their index) — one kernel signature.
        root_valid = np.zeros((G, V), bool)
        for k, r in enumerate(group):
            lo_r = np.asarray(roots_lo[r], dtype=np.int64)
            hi_r = np.asarray(roots_hi[r], dtype=np.int64)
            q_lo[k] = lo_r
            q_hi[k] = hi_r
            q_root[k] = k
            q_live[k] = True
            root_valid[k] = ((assignments >= lo_r[pa_idx][None, :])
                             & (assignments <= hi_r[pa_idx][None, :])
                             ).all(axis=-1)
        root_valid_dev = jnp.asarray(root_valid)
        overflowed = np.zeros(g, bool)
        deadline_hit = False
        seg = 0
        while True:
            if (time.perf_counter() - t0) > deadline_s:
                deadline_hit = True
                break
            if not any(q_live[i] and verdicts[group[int(q_root[i])]] is None
                       for i in range(Q)):
                break
            prev_state = (q_lo.tobytes(), q_hi.tobytes(), q_live.tobytes())
            seg_t = time.perf_counter()
            # Segment-INDEXED escalation (not wall-time like the host
            # loop): segment 0 plain CROWN, later segments α-CROWN — two
            # executables total, and verdicts independent of host speed.
            seg_alpha = 0 if seg == 0 else int(cfg.alpha_iters)

            def fn(q_lo=q_lo, q_hi=q_hi, q_root=q_root, q_live=q_live,
                   q_found=q_found, wit_a=wit_a, wit_b=wit_b, wit_pt=wit_pt,
                   seg_alpha=seg_alpha):
                profiling.bump_launch()
                outs = _bab_segment_kernel(
                    net, jnp.asarray(q_lo), jnp.asarray(q_hi),
                    jnp.asarray(q_root), jnp.asarray(q_live),
                    jnp.asarray(q_found), jnp.asarray(wit_a),
                    jnp.asarray(wit_b), jnp.asarray(wit_pt),
                    slot_ok_dev, root_valid_dev, assign_vals, pa_mask,
                    ra_mask, float(enc.eps), valid_pair_dev, branch_mask_dev,
                    rounds=int(cfg.bab_rounds_per_segment),
                    alpha_iters=seg_alpha)
                return dict(zip(payload_keys, outs)), None

            items = list(pipe.submit(fn))
            items.extend(pipe.drain())
            _meta, _ctx, host = items[0]
            failure = host if isinstance(host, ChunkFailure) else None
            if failure is None and cfg.integrity:
                tripped = integrity_mod.verify_bab_segment(host)
                if tripped is not None:
                    from fairify_tpu.obs.metrics import registry

                    registry().counter("integrity_violations").inc(
                        1, site="launch.decode")
                    obs.event("integrity_violation", site="launch.decode",
                              detector=tripped, phase="engine.device_bab")
                    failure = ChunkFailure(
                        site="integrity.launch.decode", kind="fatal",
                        error="IntegrityViolation",
                        detail=f"{tripped} mismatch (launch.decode)",
                        retries=0)
            if failure is not None:
                # Blast radius = exactly this segment's root group: the
                # queue never advances on a failed fetch, nothing from it
                # is trusted, and the group's open roots degrade to the
                # legacy catch-all for the sweep's retry/SMT tiers.
                from fairify_tpu.obs.metrics import registry

                registry().counter("chunks_degraded").inc(
                    1, site=failure.site)
                obs.event("degraded", **failure.to_record(),
                          phase="engine.device_bab", partitions=len(group))
                for r in group:
                    settle(r, "unknown")
                break
            # np.array (not asarray): fetched device buffers are read-only
            # views, and the queue state mutates between segments.
            q_lo = np.array(host["q_lo"], np.float32)
            q_hi = np.array(host["q_hi"], np.float32)
            q_root = np.array(host["q_root"], np.int32)
            q_live = np.array(host["q_live"], bool)
            q_found = np.array(host["found"], bool)
            wit_a = np.array(host["wit_a"], np.int32)
            wit_b = np.array(host["wit_b"], np.int32)
            wit_pt = np.array(host["wit_pt"], np.float32)
            seg_nodes = np.asarray(host["nodes"], np.int64)
            seg_over = np.asarray(host["overflow"], np.int64)
            for k, r in enumerate(group):
                nodes[r] += int(seg_nodes[k])
                if seg_over[k] > 0:
                    overflowed[k] = True
            open_rs = [r for r in group if verdicts[r] is None]
            dt = time.perf_counter() - seg_t
            for r in open_rs:
                cost_s[r] += dt / len(open_rs)
            had_latch = bool(q_found.any())
            progressed = False
            # Witness latches: exact-validate, smallest candidate first so
            # the settled counterexample never depends on slot scheduling.
            cands: dict = {}
            for i in range(Qs):
                if not (q_found[i] and slot_ok[i]):
                    continue
                k = int(q_root[i])
                if k >= g or verdicts[group[k]] is not None:
                    continue
                pt = wit_pt[i].astype(np.int64)
                cands.setdefault(k, []).append(
                    (tuple(pt.tolist()), int(wit_a[i]), int(wit_b[i]), pt))
            for k in sorted(cands):
                r = group[k]
                for _pk, a, b, pt in sorted(cands[k],
                                            key=lambda c: (c[0], c[1], c[2])):
                    if verdicts[r] is not None:
                        break
                    x = pt.copy()
                    xp = pt.copy()
                    if len(pa_idx):
                        x[pa_idx] = assignments[a]
                        xp[pa_idx] = assignments[b]
                    if validate_pair(weights, biases, x, xp):
                        settle(r, "sat", (x, xp))
                        progressed = True
            q_found[:] = False  # latched slots rejoin the free pool
            # Point leaves (every branchable dim collapsed): exact decision,
            # the same endgame as the host loop's decide_leaf.
            for i in range(Q):
                if not q_live[i]:
                    continue
                r = group[int(q_root[i])]
                if verdicts[r] is not None:
                    continue
                w = (q_hi[i] - q_lo[i]) * branch_mask
                if w.size == 0 or float(w.max()) <= 0.0:
                    leaves[r] += 1
                    l_i = q_lo[i].astype(np.int64)
                    h_i = q_hi[i].astype(np.int64)
                    verdict, ce = decide_leaf(enc, weights, biases,
                                              l_i.copy(), l_i, h_i)
                    if verdict == "sat":
                        settle(r, "sat", ce)
                        progressed = True
                    elif verdict == "unknown":
                        settle(r, "unknown", reason="frontier:hard")
                        progressed = True
                    else:
                        q_live[i] = False
            for r in group:
                if verdicts[r] is None and nodes[r] > cfg.max_nodes:
                    settle(r, "unknown", reason="budget")
                    progressed = True
            for i in range(Q):
                if q_live[i] and verdicts[group[int(q_root[i])]] is not None:
                    q_live[i] = False
            live_k = {int(q_root[i]) for i in range(Q) if q_live[i]}
            for k, r in enumerate(group):
                if verdicts[r] is None and k not in live_k:
                    settle(r, "unsat")
                    progressed = True
            if (not progressed and not had_latch
                    and (q_lo.tobytes(), q_hi.tobytes(),
                         q_live.tobytes()) == prev_state):
                break  # stalled: no clip/split/settle progress possible
            seg += 1
        for k, r in enumerate(group):
            if verdicts[r] is None:
                if deadline_hit:
                    settle(r, "unknown", reason="deadline")
                elif overflowed[k]:
                    settle(r, "unknown", reason="frontier:overflow")
                else:
                    settle(r, "unknown", reason="frontier:hard")


def decide_many(
    net: MLP,
    enc: PairEncoding,
    roots_lo: np.ndarray,
    roots_hi: np.ndarray,
    cfg: EngineConfig,
    deadline_s: Optional[float] = None,
    mesh=None,
    attacked: bool = False,
) -> list:
    """Branch-and-bound over MANY root boxes sharing one device frontier.

    The reference decides partitions serially, one Z3 call each
    (``src/GC/Verify-GC.py:106``).  Here every undecided partition
    contributes sub-boxes to a single padded frontier, so one CROWN launch
    and one attack forward serve all of them — sub-boxes of easy and hard
    partitions ride the same MXU batch.  Per root: verdict 'sat' retires
    all its sub-boxes immediately; exceeding ``max_nodes`` (per root) or the
    global deadline marks it 'unknown'; an emptied sub-tree is 'unsat'.

    ``deadline_s`` defaults to ``soft_timeout_s × n_roots`` — the same total
    budget the reference would spend, but shared work-conservingly.

    With a ``mesh``, the padded frontier batch is sharded over the
    ``parts`` axis for the bound and attack kernels (the host branching
    logic is unchanged), so stage 1 scales across chips like stage 0.
    """
    from fairify_tpu.verify.property import role_boxes

    if mesh is not None:
        from fairify_tpu.parallel import mesh as mesh_mod

        net_sharded = mesh_mod.replicated(mesh, net)
    t0 = time.perf_counter()
    R = roots_lo.shape[0]
    if deadline_s is None:
        deadline_s = cfg.soft_timeout_s * max(R, 1)
    rng = np.random.default_rng(cfg.seed)
    weights = [np.asarray(w) for w in net.weights]
    biases = [np.asarray(b) for b in net.biases]
    branch_dims = _branch_dims(enc, roots_lo.shape[1])
    F = cfg.frontier_size
    assign_vals, pa_mask, ra_mask = _enc_tensors(enc, roots_lo.shape[1])
    assign_vals, pa_mask, ra_mask = (
        jnp.asarray(assign_vals), jnp.asarray(pa_mask), jnp.asarray(ra_mask))
    valid_pair_dev = jnp.asarray(enc.valid_pair)

    from collections import deque

    verdicts: list = [None] * R
    ces: list = [None] * R

    # Phase A — deep PGD attack on every root (one jitted launch per 1024-
    # root chunk; fixed chunk size so the kernel compiles once per net).
    # Settles the SAT roots whose witnesses shallower attacks missed BEFORE
    # the certificate phases can waste their budget on them
    # (audits/profile_r4.json: the BM-4 sign phase and most pair-BaB
    # seconds were spent re-discovering missed witnesses).
    # ``attacked=True``: the caller already ran the deep PGD + slab attack on
    # exactly these roots (sweep stage0_pgd) — re-attacking them here is pure
    # launch overhead (VERDICT r4: on grids where stage 0 decides 95%+,
    # Phase A re-ran a kernel that had just failed to find witnesses).
    attack_cost = np.zeros(R, dtype=np.float64)
    if cfg.pgd_phase and not attacked and len(enc.pa_idx) and R:
        with obs.span("engine.attack", roots=R) as sp_a:
            t_a = time.perf_counter()
            rng_a = np.random.default_rng(cfg.seed + 17)
            # Chunk cap scales down for small calls (decide_box, heuristic
            # retry: R=1) — pgd_attack pads to the next power of two itself,
            # so tiny calls stay tiny; large calls amortize at 1024/launch.
            CH = min(1024, 1 << max(R - 1, 0).bit_length())
            # Budget guard: the attack must never eat the certificate phases'
            # deadline — cap it at a quarter and stop between chunks.
            # Chunks are independent roots, so they ride the async launch
            # pipeline: chunk N+1's scan+grad kernel is in flight while
            # chunk N's witnesses go through exact validation on host.
            # Submission order is the synchronous order, so the shared
            # ``rng_a`` stream (consumed at submit time) is depth-invariant.
            from fairify_tpu.parallel.pipeline import LaunchPipeline
            from fairify_tpu.resilience.supervisor import ChunkFailure, Supervisor

            pipe = LaunchPipeline(
                cfg.pipeline_depth, gauge=False,
                supervisor=Supervisor(max_retries=cfg.max_launch_retries,
                                      backoff_s=cfg.launch_backoff_s,
                                      seed=cfg.seed))

            def _consume(meta, ctx, host):
                if isinstance(host, ChunkFailure):
                    # Degraded attack chunk: its roots stay unattacked and
                    # keep the full certificate/BaB path — graceful, sound.
                    obs.event("degraded", **host.to_record(),
                              phase="engine.attack")
                    return
                s_blk, n_blk = meta
                for i, ce in pgd_attack_decode(host, ctx).items():
                    if i < n_blk and verdicts[s_blk + i] is None:
                        verdicts[s_blk + i] = "sat"
                        ces[s_blk + i] = ce

            def _replayable_submit(blk):
                # Chunks share ``rng_a`` (submission-order invariant), but a
                # supervised retry must NOT advance it again — the first
                # dispatch snapshots the stream state and replays draw the
                # identical samples from a clone, keeping faulted runs'
                # verdicts bit-equal to fault-free ones.
                state = {}

                def fn():
                    if "s" not in state:
                        state["s"] = rng_a.bit_generator.state
                        r = rng_a
                    else:
                        r = np.random.default_rng()
                        r.bit_generator.state = state["s"]
                    return pgd_attack_submit(
                        net, enc,
                        np.asarray(roots_lo[blk], dtype=np.int64),
                        np.asarray(roots_hi[blk], dtype=np.int64), r,
                        steps=cfg.pgd_steps, restarts=cfg.pgd_restarts)
                return fn

            attack_deadline = 0.25 * deadline_s
            submitted = 0
            for s in range(0, R, CH):
                # Backlog-aware admission: in-flight chunks are committed
                # work that will drain (and decode) past any break, so the
                # deadline gates elapsed PLUS the estimated backlog cost —
                # without this, depth-1 overshoot of one in-progress chunk
                # becomes depth chunks of post-deadline exact validation.
                elapsed = time.perf_counter() - t_a
                est = elapsed / max(submitted, 1)
                if elapsed + len(pipe) * est > attack_deadline:
                    break
                submitted += 1
                blk = np.arange(s, min(s + CH, R))
                for item in pipe.submit(_replayable_submit(blk),
                                        meta=(s, len(blk))):
                    _consume(*item)
            for item in pipe.drain():
                _consume(*item)
            attack_cost[:] = (time.perf_counter() - t_a) / R
            sp_a.set(sat=sum(1 for v in verdicts if v == "sat"))

    # Phase S — uniform-sign neuron-split BaB.  Roots whose sampled role
    # logits are one-signed get a β-CROWN-style activation-split proof
    # attempt first; input splitting on deep nets converges too slowly for
    # exactly these roots (AC-7: 22k+ input-split nodes without progress).
    # alpha_iters > 0 is required: with no β optimization the split
    # constraints never reach the concretized bound and the phase cannot
    # progress past root-level certification (see crown.py docstring).
    # Sign-phase nodes/time are merged into the reported Decisions (so the
    # additive accounting invariant Σ per-root ≈ phase total holds and
    # sign-certified roots carry their true cost, not nodes=0/0 s) but are
    # kept OUT of the pair-BaB ``nodes`` budget counter — max_nodes keeps
    # governing the input-split tree alone, as before.
    sign_nodes = np.zeros(R, dtype=np.int64)
    sign_cost = np.zeros(R, dtype=np.float64)
    sign_lp_cost = np.zeros(R, dtype=np.float64)
    open_idx = np.array([r for r in range(R) if verdicts[r] is None])
    if cfg.sign_bab and cfg.use_crown and cfg.alpha_iters > 0 \
            and open_idx.size:
        with obs.span("engine.sign_bab", roots=int(open_idx.size)) as sp_s:
            sv, sn, sc, slp = uniform_sign_bab(
                net, enc, np.asarray(roots_lo)[open_idx].astype(np.int64),
                np.asarray(roots_hi)[open_idx].astype(np.int64), cfg,
                deadline_s=cfg.sign_bab_frac * deadline_s, mesh=mesh)
            sign_nodes[open_idx] = sn
            sign_cost[open_idx] = sc
            sign_lp_cost[open_idx] = slp
            unsat_n = 0
            for k, v in enumerate(sv):
                if v == "unsat":
                    verdicts[int(open_idx[k])] = "unsat"
                    unsat_n += 1
            sp_s.set(unsat=unsat_n, nodes=int(sn.sum()))

    # Phase E0 — immediate exhaustive enumeration of CHEAP enumerable roots.
    # A root whose (ε-dilated) lattice fits a few scan chunks is decided
    # completely in one or two warm launches (~110 ms each); the input-split
    # BaB diverges on exactly these wide flip-slab boxes and burned 30+ s per
    # batch on the relaxed-AC ladder before giving Phase E the leftovers
    # (r5 profile).  Enumeration is the exact oracle, so verdicts settled
    # here can only be right; the expensive-root reserve logic below still
    # governs the big lattices.
    lat_sizes = _eligible_lattice_roots(enc, roots_lo, roots_hi, cfg)
    lat_cost = np.zeros(R, dtype=np.float64)
    if cfg.lattice_exhaustive and lat_sizes:
        from fairify_tpu.ops import lattice as lattice_ops

        cheap = sorted((r for r in range(R) if verdicts[r] is None
                        and lat_sizes.get(r, np.inf) <= cfg.lattice_first_max),
                       key=lambda r: lat_sizes[r])
        with obs.span("engine.lattice_first", roots=len(cheap)) as sp_e0:
            decided_e0 = 0
            for r in cheap:
                spent = time.perf_counter() - t0
                if spent > 0.4 * deadline_s:
                    break
                t_r = time.perf_counter()
                verdict, ce = lattice_ops.decide_box_exhaustive(
                    net, enc, np.asarray(roots_lo[r], dtype=np.int64),
                    np.asarray(roots_hi[r], dtype=np.int64),
                    chunk=cfg.lattice_chunk,
                    deadline_s=min(deadline_s - spent, cfg.lattice_first_cap_s))
                lat_cost[r] += time.perf_counter() - t_r
                if verdict != "unknown":
                    verdicts[r] = verdict
                    ces[r] = ce
                    decided_e0 += 1
            sp_e0.set(decided=decided_e0)

    frontier = deque(
        (np.asarray(roots_lo[r], dtype=np.int64), np.asarray(roots_hi[r], dtype=np.int64), r)
        for r in range(R)
        if verdicts[r] is None
    )
    nodes = np.zeros(R, dtype=np.int64)
    leaves = np.zeros(R, dtype=np.int64)
    open_boxes = np.ones(R, dtype=np.int64)  # root boxes still in the frontier
    cost_s = np.zeros(R, dtype=np.float64)  # per-root attributed batch time

    # Phases P and E reserve deadline tails: hard roots the input-split BaB
    # cannot crack would otherwise eat the whole budget and leave nothing
    # for the certificates that can close them.
    n_dirs = int(enc.valid_pair.sum())
    use_pair = (cfg.lp_pair and len(enc.pa_idx)
                and 0 < n_dirs <= cfg.lp_pair_max_dirs)
    lat_sizes = {r: n for r, n in lat_sizes.items() if verdicts[r] is None}
    use_lattice = bool(lat_sizes)
    # Reserve no more than Phase E could conceivably use even if EVERY
    # eligible root stayed unknown (~1e6 pts/s conservative scan rate plus
    # one compile) — a batch with one tiny eligible root must not tax the
    # hard roots' BaB budget by a fixed 20%.
    # Deliberate tradeoff: a batch of MANY sub-threshold flip-slab roots
    # could still grind BaB to the wall and reach Phase E with nothing
    # left — but gating on the aggregate would re-preempt productive BaB
    # batches (the GC-1 case).  Those leftovers are not lost: the sweep's
    # soft-budget retry and the deep-retry ladder re-enter decide_many
    # with a fresh deadline, where Phase E runs with room.
    lat_frac = 0.0
    if use_lattice and any(n >= cfg.lattice_reserve_min
                           for n in lat_sizes.values()):
        est_s = 120.0 + sum(lat_sizes.values()) / 1.0e6
        lat_frac = min(cfg.lattice_frac, est_s / max(deadline_s, 1e-9))
    pair_deadline = deadline_s * (1.0 - lat_frac)
    main_deadline = pair_deadline * (1.0 - cfg.lp_pair_frac) if use_pair \
        else pair_deadline

    unknown_reasons: Dict[int, str] = {}

    def settle(r: int, verdict: str, ce=None, reason: Optional[str] = None):
        if verdicts[r] is None:
            verdicts[r] = verdict
            ces[r] = ce
            if verdict == "unknown":
                unknown_reasons[r] = reason or "frontier"

    # Device-resident BaB (DESIGN.md §22): when the fused certify path is
    # available the whole frontier runs as lax.scan segments on device —
    # bab_rounds_per_segment branching rounds per launch — and the host
    # batch loop below only serves the fallback paths (mesh-sharded,
    # non-CROWN, or device_bab off).
    use_dev_bab = (cfg.device_bab and cfg.use_crown and mesh is None
                   and len(enc.pa_idx) and len(frontier) > 0)
    if use_dev_bab:
        with obs.span("engine.device_bab", roots=int(len(frontier))) as sp_d:
            n_before = sum(1 for v in verdicts if v is None)
            _device_bab_phase(net, enc, roots_lo, roots_hi, cfg, t0,
                              main_deadline, verdicts, ces, settle, nodes,
                              leaves, cost_s, weights, biases, assign_vals,
                              pa_mask, ra_mask, valid_pair_dev)
            sp_d.set(decided=n_before
                     - sum(1 for v in verdicts if v == "unknown"),
                     nodes=int(nodes.sum()))
        frontier.clear()

    with obs.span("engine.bab", roots=int(len(frontier))) as sp_bab:
        while frontier:
            timed_out = (time.perf_counter() - t0) > main_deadline
            if timed_out:
                for _, _, r in frontier:
                    settle(r, "unknown", reason="deadline")
                break

            t_iter = time.perf_counter()
            # Pop a batch, dropping sub-boxes of roots that settled meanwhile.
            blo_l, bhi_l, broot_l = [], [], []
            while frontier and len(blo_l) < F:
                l, h, r = frontier.popleft()
                if verdicts[r] is not None:
                    continue
                blo_l.append(l)
                bhi_l.append(h)
                broot_l.append(r)
            if not blo_l:
                break
            batch = len(blo_l)
            blo, bhi, broot = np.stack(blo_l), np.stack(bhi_l), np.array(broot_l)
            for r in broot:
                open_boxes[r] -= 1
            np.add.at(nodes, broot, 1)

            live = np.array([verdicts[r] is None for r in broot])

            plo = _pad(blo, F).astype(np.float32)
            phi = _pad(bhi, F).astype(np.float32)
            x_lo, x_hi, xp_lo, xp_hi, valid = role_boxes(enc, plo, phi)
            bound_net = net
            valid_in = valid
            if mesh is not None:
                x_lo, x_hi, xp_lo, xp_hi, plo_in, phi_in, valid_in = \
                    mesh_mod.shard_parts(mesh, x_lo, x_hi, xp_lo, xp_hi, plo, phi, valid)
                bound_net = net_sharded
            else:
                plo_in, phi_in = plo, phi
            # Escalation: plain CROWN clears the easy boxes in one cheap pass;
            # once a fifth of the deadline is spent the survivors are the hard
            # ones, where α-CROWN's extra backward passes pay for themselves.
            use_alpha = (cfg.use_crown and cfg.alpha_iters > 0
                         and time.perf_counter() - t0 > 0.2 * deadline_s)
            score = None
            fused = cfg.use_crown and mesh is None
            if fused:
                # One launch per iteration: certificate + attack logits for ALL
                # boxes.  A launch costs ~110 ms flat on the tunnelled chip
                # (audits/device_util_r4.json) while the extra attack forwards on
                # to-be-certified boxes are microseconds of MXU time — attacking
                # unconditionally in the certify kernel halves the loop's launch
                # bill (VERDICT r4 #3).
                xr, pr = build_attack_candidates(enc, rng, _pad(blo, F),
                                                 _pad(bhi, F), cfg.bab_attack_samples)
                (cert_dev, score_dev, found_dev, wit_dev,
                 _margin_dev, _gap_dev) = _certify_attack_kernel(
                    bound_net, jnp.asarray(x_lo), jnp.asarray(x_hi),
                    jnp.asarray(xp_lo), jnp.asarray(xp_hi),
                    jnp.asarray(plo_in), jnp.asarray(phi_in),
                    assign_vals, pa_mask, ra_mask, float(enc.eps),
                    jnp.asarray(valid_in), valid_pair_dev,
                    jnp.asarray(xr), jnp.asarray(pr),
                    alpha_iters=cfg.alpha_iters if use_alpha else 0,
                )
                profiling.bump_launch()
                certified = np.asarray(cert_dev)[:batch]
                score = np.asarray(score_dev)[:F]
                found_all, wit_all = np.asarray(found_dev), np.asarray(wit_dev)
            elif cfg.use_crown:
                cert_dev, score_dev, _margin_dev = _role_certify_kernel(
                    bound_net, jnp.asarray(x_lo), jnp.asarray(x_hi),
                    jnp.asarray(xp_lo), jnp.asarray(xp_hi),
                    jnp.asarray(plo_in), jnp.asarray(phi_in),
                    assign_vals, pa_mask, ra_mask, float(enc.eps),
                    jnp.asarray(valid_in), valid_pair_dev,
                    alpha_iters=cfg.alpha_iters if use_alpha else 0,
                )
                profiling.bump_launch()
                certified = np.asarray(cert_dev)[:batch]
                score = np.asarray(score_dev)[:F]
            else:
                lb_x, ub_x, lb_p, ub_p = _role_logit_bounds(
                    bound_net, jnp.asarray(x_lo), jnp.asarray(x_hi), jnp.asarray(xp_lo),
                    jnp.asarray(xp_hi), cfg.use_crown,
                )
                profiling.bump_launch()
                lb_x, ub_x, lb_p, ub_p = (np.asarray(v)[:F] for v in (lb_x, ub_x, lb_p, ub_p))
                certified = no_flip_certified(lb_x, ub_x, lb_p, ub_p, valid, enc.valid_pair)[:batch]

            undecided = np.where(~certified & live)[0]
            if undecided.size:
                if fused:
                    found, wit = found_all[undecided], wit_all[undecided]
                    xr_u, pr_u = xr[undecided], pr[undecided]
                else:
                    # Attack the undecided boxes (padded so the forward compiles
                    # once).
                    ulo, uhi = _pad(blo[undecided], F), _pad(bhi[undecided], F)
                    xr_u, pr_u = build_attack_candidates(enc, rng, ulo, uhi,
                                                         cfg.bab_attack_samples)
                    if mesh is not None:
                        xr_s, pr_s = mesh_mod.shard_parts(mesh, xr_u, pr_u)
                        lx, lp = _attack_logits(bound_net, xr_s, pr_s)
                        lx, lp = np.asarray(lx)[:F], np.asarray(lp)[:F]
                    else:
                        lx, lp = _attack_logits(net, jnp.asarray(xr_u), jnp.asarray(pr_u))
                    profiling.bump_launch()
                    found, wit = find_flips(
                        enc, np.asarray(lx), np.asarray(lp), _pad(valid[undecided], F)
                    )
                found = found[: undecided.size]
                for k in np.where(found)[0]:
                    r = int(broot[undecided[k]])
                    if verdicts[r] is not None:
                        continue
                    s, a, b = wit[k]
                    x = xr_u[k, s, a].astype(np.int64)
                    xp = pr_u[k, s, b].astype(np.int64)
                    if validate_pair(weights, biases, x, xp):
                        settle(r, "sat", (x, xp))

                for k in undecided:
                    r = int(broot[k])
                    if verdicts[r] is not None:
                        continue
                    if nodes[r] > cfg.max_nodes:
                        # Node budget, not hardness: more nodes might decide
                        # it.  Distinct from 'frontier' so the funnel (and
                        # ROADMAP item 1's success metric) can separate
                        # budget-starved roots from genuinely hard ones.
                        settle(r, "unknown", reason="budget")
                        continue
                    l, h = blo[k], bhi[k]
                    widths = h[branch_dims] - l[branch_dims]
                    if widths.size == 0 or widths.max() == 0:
                        leaves[r] += 1
                        verdict, ce = decide_leaf(enc, weights, biases, l.copy(), l, h)
                        if verdict == "sat":
                            settle(r, "sat", ce)
                        elif verdict == "unknown":
                            settle(r, "unknown")
                        continue
                    # Coefficient-aware branching: split the dim whose width
                    # contributes most to the difference-certificate slack
                    # (score_j·width_j); zero-score frontier → widest-dim.
                    # Multi-way when the frontier is underfull: each kernel
                    # launch costs the full padded batch regardless of how many
                    # live boxes ride it, so on small frontiers (hard single
                    # roots — the r4 slow-tail profile measured 5-25 ms/node of
                    # pure launch latency) splitting the top-2/3 dims at once
                    # packs 2-3 binary levels into one launch.
                    if score is not None:
                        sc = score[k][branch_dims] * widths
                        if float(sc.max()) <= 0:
                            sc = widths.astype(np.float64)
                    else:
                        sc = widths.astype(np.float64)
                    n_dims = 1
                    if len(frontier) + 2 * undecided.size < F // 2:
                        n_dims = 3 if len(frontier) + 4 * undecided.size < F // 4 \
                            else 2
                    order = np.argsort(-sc, kind="stable")
                    chosen = [int(branch_dims[j]) for j in order[:n_dims]
                              if widths[j] > 0][: n_dims]
                    children = [(l, h)]
                    for dim in chosen:
                        nxt = []
                        for cl, ch_ in children:
                            mid = (cl[dim] + ch_[dim]) // 2
                            left_hi = ch_.copy()
                            left_hi[dim] = mid
                            right_lo = cl.copy()
                            right_lo[dim] = mid + 1
                            nxt.append((cl, left_hi))
                            nxt.append((right_lo, ch_))
                        children = nxt
                    for cl, ch_ in children:
                        frontier.append((cl, ch_, r))
                    open_boxes[r] += len(children)

            # Attribute this iteration's wall time to its roots, per sub-box, so
            # per-root costs are additive (sum ≈ total phase time).
            iter_dt = time.perf_counter() - t_iter
            np.add.at(cost_s, broot, iter_dt / batch)

            # Roots whose sub-tree emptied without a counterexample are fair.
            for r in set(int(x) for x in broot):
                if verdicts[r] is None and open_boxes[r] == 0:
                    settle(r, "unsat")
        sp_bab.set(nodes=int(nodes.sum()), leaves=int(leaves.sum()))

    for r in range(R):
        if verdicts[r] is None:
            # Open boxes at loop exit mean the deadline (not the proof)
            # ended this root — the distinction the SMT tier's ladder and
            # the deep-retry harness key off.
            settle(r, "unsat" if open_boxes[r] == 0 else "unknown",
                   reason="deadline")

    pair_cost = np.zeros(R, dtype=np.float64)  # lat_cost init'd at Phase E0
    if use_pair and any(v == "unknown" for v in verdicts):
        n_unk = sum(1 for v in verdicts if v == "unknown")
        with obs.span("engine.pair_lp", roots=n_unk) as sp_p:
            _pair_lp_phase(net, enc, roots_lo, roots_hi, verdicts, ces,
                           nodes, pair_cost, cfg, t0, pair_deadline)
            sp_p.set(decided=n_unk - sum(1 for v in verdicts if v == "unknown"))

    if use_lattice and any(v == "unknown" for v in verdicts):
        n_unk = sum(1 for v in verdicts if v == "unknown")
        with obs.span("engine.lattice", roots=n_unk) as sp_e:
            _lattice_phase(net, enc, roots_lo, roots_hi, verdicts, ces,
                           lat_cost, cfg, t0, deadline_s, lat_sizes=lat_sizes)
            sp_e.set(decided=n_unk - sum(1 for v in verdicts if v == "unknown"))

    # Per-root per-phase attribution: A = deep PGD attack (split evenly),
    # S = sign-BaB device frontier, L = host LP inside the sign phase,
    # bab = input-split pair BaB, P = relational pair LP, E = lattice
    # enumeration.  Sums to elapsed_s.
    return [
        Decision(verdicts[r], ces[r],
                 nodes=int(nodes[r] + sign_nodes[r]), leaves=int(leaves[r]),
                 elapsed_s=float(attack_cost[r] + cost_s[r] + sign_cost[r]
                                 + pair_cost[r] + lat_cost[r]),
                 stats={"t_attack": float(attack_cost[r]),
                        "t_sign": float(sign_cost[r] - sign_lp_cost[r]),
                        "t_lp": float(sign_lp_cost[r]),
                        "t_bab": float(cost_s[r]),
                        "t_pair": float(pair_cost[r]),
                        "t_lattice": float(lat_cost[r])},
                 reason=(unknown_reasons.get(r, "frontier")
                         if verdicts[r] == "unknown" else None))
        for r in range(R)
    ]


def _eligible_lattice_roots(enc, roots_lo, roots_hi, cfg) -> dict:
    """root index → enumerable scan size, for roots Phase E can decide.
    The single eligibility rule shared by decide_many's budget reserve and
    ``_lattice_phase``'s queue — these must never disagree.  RA-free,
    single-RA, and k-RA queries are enumerable (each RA axis dilates on
    device; the L∞ window separably); queries whose (2ε+1)^k window
    exceeds the margin resolver's 10⁵ cap are not
    (``lattice.enumerable_size`` returns None), nor are boxes whose
    ε-expanded coordinates reach 2²⁴ (f32-exactness guard)."""
    if not cfg.lattice_exhaustive:
        return {}
    from fairify_tpu.ops import lattice as lattice_ops

    sizes = {}
    for r in range(roots_lo.shape[0]):
        n = lattice_ops.enumerable_size(
            enc, np.asarray(roots_lo[r], dtype=np.int64),
            np.asarray(roots_hi[r], dtype=np.int64))
        if n is not None and n <= cfg.lattice_max:
            sizes[r] = n
    return sizes


def _lattice_phase(net, enc, roots_lo, roots_hi, verdicts, ces,
                   cost_s, cfg, t0, deadline_s, lat_sizes=None):
    """Phase E: exhaustive lattice enumeration of the still-unknown roots.

    Complete for RA-free and k-RA queries on boxes whose enumerable scan
    fits ``cfg.lattice_max`` — exactly the wide flip-slab class where
    input splitting diverges (the box is finite; enumerate it).  Each RA
    axis is expanded ±ε and partner-dilated on device (``decide_leaf``
    delta semantics, x′ unclamped; the L∞ window is separable for any k);
    queries past the 10⁵ delta-window cap are excluded.  Roots
    are visited smallest lattice first, so one near-cap root cannot starve
    trivially cheap ones.
    """
    from fairify_tpu.ops import lattice as lattice_ops

    if lat_sizes is None:
        lat_sizes = _eligible_lattice_roots(enc, roots_lo, roots_hi, cfg)
    pending = sorted(
        (r for r, v in enumerate(verdicts) if v == "unknown" and r in lat_sizes),
        key=lambda r: lat_sizes[r])
    for r in pending:
        remaining = deadline_s - (time.perf_counter() - t0)
        if remaining <= 1.0:
            break
        t_r = time.perf_counter()
        verdict, ce = lattice_ops.decide_box_exhaustive(
            net, enc, np.asarray(roots_lo[r], dtype=np.int64),
            np.asarray(roots_hi[r], dtype=np.int64),
            chunk=cfg.lattice_chunk, deadline_s=remaining)
        cost_s[r] += time.perf_counter() - t_r
        if verdict != "unknown":
            verdicts[r] = verdict
            ces[r] = ce


def _pair_lp_phase(net, enc, roots_lo, roots_hi, verdicts, ces,
                   nodes, cost_s, cfg, t0, deadline_s):
    """Phase P: relational pair LP BaB over the roots still unknown.

    Per root: CROWN pre-activation bounds for every assignment's role box
    in one device launch, then one host LP BaB per valid ordered pair
    (f_a > 0 ∧ f_b < 0).  Every direction killed → UNSAT; an exact-
    validated witness → SAT; any direction left open → stays unknown.
    """
    from fairify_tpu.ops import lp as lp_ops
    from fairify_tpu.verify.property import role_boxes

    host_w = [np.asarray(w) for w in net.weights]
    host_b = [np.asarray(b) for b in net.biases]
    host_m = [np.asarray(m) for m in net.masks]
    pending = [r for r, v in enumerate(verdicts) if v == "unknown"]
    for r in pending:
        remaining = deadline_s - (time.perf_counter() - t0)
        if remaining <= 1.0:
            break
        t_r = time.perf_counter()
        lo_r = np.asarray(roots_lo[r], dtype=np.int64)
        hi_r = np.asarray(roots_hi[r], dtype=np.int64)
        x_lo, x_hi, xp_lo, xp_hi, valid = role_boxes(
            enc, lo_r[None].astype(np.float32), hi_r[None].astype(np.float32))
        V = enc.n_assign
        boxes_lo = np.concatenate([x_lo[0], xp_lo[0]], axis=0)
        boxes_hi = np.concatenate([x_hi[0], xp_hi[0]], axis=0)
        wl, wu = _inter_bounds_kernel(
            net, jnp.asarray(boxes_lo), jnp.asarray(boxes_hi))
        wl = [np.asarray(w) for w in wl]
        wu = [np.asarray(w) for w in wu]
        nh = net.depth - 1

        def bounds_of(role_off, a):
            return ([wl[k][role_off + a] for k in range(nh)],
                    [wu[k][role_off + a] for k in range(nh)])

        outcome = "unsat"
        witness = None
        # With an RA shift both flip directions must be solved per ordered
        # pair: the shift stays attached to tower b, so the swapped pair is
        # NOT the mirror (its witness may need the out-of-box ε band).
        directions = (False,) if not enc.eps else (False, True)
        for a in range(V):
            if not valid[0, a]:
                continue
            for b2 in range(V):
                if not (valid[0, b2] and enc.valid_pair[a, b2]):
                    continue
                for flip in directions:
                    rem = deadline_s - (time.perf_counter() - t0)
                    if rem <= 0.5:
                        outcome = "open"
                        break
                    status, n_lp, wit = lp_ops.pair_bab_lp(
                        host_w, host_b, host_m, enc, lo_r, hi_r,
                        enc.assignments[a], enc.assignments[b2],
                        bounds_of(0, a), bounds_of(V, b2),
                        max_nodes=cfg.lp_pair_max_nodes,
                        deadline_s=min(cfg.soft_timeout_s, rem), flip=flip)
                    nodes[r] += n_lp
                    if status == "sat":
                        outcome, witness = "sat", wit
                        break
                    if status == "open":
                        outcome = "open"
                        break
                if outcome in ("sat", "open"):
                    break
            if outcome in ("sat", "open"):
                break
        cost_s[r] += time.perf_counter() - t_r
        if outcome == "unsat":
            verdicts[r] = "unsat"
        elif outcome == "sat":
            verdicts[r] = "sat"
            ces[r] = witness


def decide_box(
    net: MLP,
    enc: PairEncoding,
    lo: np.ndarray,
    hi: np.ndarray,
    cfg: EngineConfig,
) -> Decision:
    """Complete decision for one partition box (single-root wrapper)."""
    return decide_many(
        net, enc, np.asarray(lo)[None, :], np.asarray(hi)[None, :], cfg,
        deadline_s=cfg.soft_timeout_s,
    )[0]


def slab_search(weights, biases, enc: PairEncoding, lo, hi, shared0,
                max_iters: int = 24):
    """Deterministic exact flip-slab search from a near-zero seed point.

    On wide integer domains (default-credit: attribute ranges of ~10^6) the
    protected-attribute logit offset |δ| can sit at the f32 noise floor of
    the box's logit range, so the gradient attack cannot resolve the flip
    slab ``f(x) ∈ (0, -δ)``.  The logit is piecewise affine, so instead:
    evaluate ``(f, ∇f)`` exactly in f64 (:func:`models.mlp.local_affine_np`),
    and Newton-step an integer coordinate — preferring step granularity
    |∇f_j| finer than the slab width — until ``f`` lands inside the slab;
    the final pair is validated in exact rational arithmetic, so a returned
    witness is ground truth regardless of f64 rounding.

    Returns ``(x, xp)`` int64 arrays, or ``None``.
    """
    from fairify_tpu.models.mlp import local_affine_np

    lo = np.asarray(lo, dtype=np.int64)
    hi = np.asarray(hi, dtype=np.int64)
    if not len(enc.pa_idx):
        return None
    pa_idx = np.asarray(enc.pa_idx)
    pa_set = set(int(j) for j in pa_idx)
    V = enc.n_assign
    in_box = [
        bool((lo[pa_idx] <= enc.assignments[v]).all()
             and (enc.assignments[v] <= hi[pa_idx]).all())
        for v in range(V)
    ]
    shared = np.clip(np.round(np.asarray(shared0, dtype=np.float64)),
                     lo, hi).astype(np.float64)
    for a in range(V):
        for b in range(V):
            if not (enc.valid_pair[a, b] and in_box[a] and in_box[b]):
                continue
            x = shared.copy()
            x[pa_idx] = enc.assignments[a]
            for _ in range(max_iters):
                f, g = local_affine_np(weights, biases, x)
                delta = float(((enc.assignments[b] - enc.assignments[a])
                               * g[pa_idx]).sum())
                if delta == 0.0:
                    break
                t_lo, t_hi = (0.0, -delta) if delta < 0 else (-delta, 0.0)
                if t_lo < f < t_hi:
                    xp = x.copy()
                    xp[pa_idx] = enc.assignments[b]
                    if validate_pair(weights, biases,
                                     x.astype(np.int64), xp.astype(np.int64)):
                        return x.astype(np.int64), xp.astype(np.int64)
                    break  # f64 in-slab but exact sign disagrees — abandon
                need = (t_lo + t_hi) / 2.0 - f
                # Finest coordinate (ascending |g_j|) whose in-box step range
                # can actually reach the target; if none reaches, the one
                # making the most progress toward it.
                best_j, best_t = -1, 0
                fb_j, fb_t, fb_reach = -1, 0, 0.0
                for j in np.argsort(np.abs(g)):
                    j = int(j)
                    if j in pa_set or g[j] == 0.0:
                        continue
                    t_unc = need / g[j]
                    if not np.isfinite(t_unc):  # subnormal g[j]: unusable dim
                        continue
                    t = int(np.clip(round(t_unc), lo[j] - x[j], hi[j] - x[j]))
                    if t == 0:
                        continue
                    if lo[j] - x[j] - 0.5 <= t_unc <= hi[j] - x[j] + 0.5:
                        best_j, best_t = j, t
                        break
                    reach = abs(g[j] * t)
                    if reach > fb_reach:
                        fb_j, fb_t, fb_reach = j, t, reach
                if best_j < 0:
                    best_j, best_t = fb_j, fb_t
                if best_j < 0:
                    break
                x[best_j] += best_t
    return None

"""Declarative sweep configuration — the replacement for 21 driver scripts.

Every reference driver (``src/{GC,AC,BM,CP,DF}``, ``stress/*``, ``relaxed/*``,
``targeted/*``, ``targeted2/*``) is an instance of :class:`SweepConfig`; the
variants differ only in these fields (SURVEY.md §2.2).  Presets live in
:mod:`fairify_tpu.verify.presets`.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, Optional, Tuple

from fairify_tpu.data.domains import get_domain
from fairify_tpu.verify.engine import EngineConfig
from fairify_tpu.verify.property import FairnessQuery


@dataclass(frozen=True)
class SweepConfig:
    name: str
    dataset: str  # key into data.domains / data.loaders / models.zoo
    protected: Tuple[str, ...]
    relaxed: Tuple[str, ...] = ()
    relax_eps: int = 0
    partition_threshold: int = 100  # PARTITION_THRESHOLD
    capped_partitions: bool = False  # DF's partition_df path
    max_partitions: int = 100
    domain_overrides: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    soft_timeout_s: float = 100.0  # per-partition decision budget
    hard_timeout_s: float = 30 * 60.0  # per-model cumulative budget
    sim_size: int = 1000
    heuristic_threshold: float = 5.0  # HEURISTIC_PRUNE_THRESHOLD (percentile)
    models: Optional[Tuple[str, ...]] = None  # None = whole family
    seed: int = 42
    exact_certify_masks: bool = True
    # Stage-0 kernels process the grid in fixed-size partition chunks so HBM
    # stays bounded on huge grids (adult: 16k partitions); 0 = whole grid.
    grid_chunk: int = 2048
    # Async launch pipeline depth (parallel.pipeline.LaunchPipeline): how
    # many chunk launches may be in flight before the oldest is drained.
    # 2 overlaps each chunk's host decode (flip extraction, exact replay,
    # ledger writes) with the next chunk's device work; 1 restores strict
    # synchronous order.  Verdict maps are depth-invariant (chunk RNG
    # streams are keyed to global chunk starts, not fetch order).
    pipeline_depth: int = 2
    # Device-resident stage-0 mega-loop (DESIGN.md §17): how many grid
    # chunks one `lax.scan` launch certifies before the host sees results.
    # Each segment is ONE obs_jit launch for the fused certify+attack pass
    # (and the prune/parity passes), so a model's stage-0 launch count is
    # O(ceil(chunks / mega_chunks)) instead of O(chunks); it is also the
    # supervisor's retry/degrade unit (a fault degrades one segment) and
    # bounds the stacked per-segment host+device buffers (attack candidates
    # are drawn host-side per chunk and stacked on the scan axis).
    # 0 = per-chunk launches (the pre-mega loop; also the forced path on
    # mesh-sharded and non-CROWN runs, which have no fused body to scan).
    # Verdict maps, counterexamples, and ledgers are bit-equal at every
    # setting (tests/test_mega.py).
    mega_chunks: int = 4
    # Device-resident BaB (DESIGN.md §22): UNKNOWN partitions run their
    # input-split branch-and-bound as lax.scan segments on device (a
    # fixed-capacity box queue, EngineConfig.bab_frontier_cap slots,
    # bab_rounds_per_segment rounds per launch) instead of the host-side
    # frontier deque's one-launch-per-batch loop.  Verdict maps, ledgers
    # and funnels are bit-equal across frontier capacities × mega_chunks ×
    # pipeline_depth (tests/test_bab.py); off restores the host loop.
    device_bab: bool = True
    engine: EngineConfig = field(default_factory=EngineConfig)
    result_dir: str = "res"
    profile_dir: Optional[str] = None  # XLA trace output (TensorBoard/XProf)
    # Structured span/event log (fairify_tpu.obs): JSONL event log at this
    # path plus a Chrome-trace export alongside (<path>.chrome.json).
    # Composes with profile_dir: obs spans cover host-side phase structure,
    # the XLA trace covers device internals.  None = tracing off (default,
    # no measurable overhead).
    trace_out: Optional[str] = None
    # Throttled stderr progress line every N seconds during the partition
    # loop (obs.heartbeat); 0 = off.
    heartbeat_s: float = 0.0
    # Per-partition group-metric CSV (``<sink>-metrics.csv``), reproducing
    # the reference CP driver's artifact shape (``src/CP/Verify-CP.py:
    # 398-458``: Partition ID, orig/pruned acc+F1, DI/SPD/EOD/AOD/ERD/CNT/
    # TI).  Flag-gated: the consistency column is an O(|test|²) kNN per
    # partition, which only makes sense on modest grids.
    partition_metrics: bool = False
    # --- Resilience (fairify_tpu/resilience, DESIGN.md §10) -------------
    # Bounded retries for a transient fault at a supervised site (device
    # launch dispatch, pipeline decode, ledger append) before the chunk's
    # partitions degrade to UNKNOWN-with-reason.  A transient fault costs
    # at most this many extra launches per chunk.
    max_launch_retries: int = 2
    # First-retry backoff (seconds); grows exponentially with full jitter.
    launch_backoff_s: float = 0.05
    # Per-chunk retry deadline (seconds; 0 = off): once a chunk has spent
    # this long across attempts, no further retry starts — it degrades.
    # Cooperative (a hung device_get cannot be interrupted mid-call).
    chunk_deadline_s: float = 0.0
    # Fault-injection schedule for chaos testing: "site:kind:nth" specs
    # (resilience.faults.parse_spec), armed for the duration of each
    # verify_model call.  Empty = no injection (production).
    inject_faults: Tuple[str, ...] = ()
    # --- Result integrity (resilience/integrity.py, DESIGN.md §21) ------
    # Always-on SDC detection: a known-answer canary chunk rides every
    # mega-scan segment, the packed (cert, wit, reason, stats) buffers
    # carry a device-computed fold checksum re-verified host-side, and
    # verdict-ledger rows get a per-row CRC.  Zero extra launches; any
    # mismatch degrades the segment to unknown:failure:integrity.* and
    # bumps integrity_violations{site}.  Off only for A/B debugging.
    integrity: bool = True
    # Sampled recheck rate in [0, 1]: this fraction of DECIDED chunks is
    # deterministically re-executed (bit-equality required) and a sample
    # of certified / SMT-unsat verdicts escalates to the exact-rational
    # oracle (verify/exact_check.py).  Each selected chunk costs one
    # extra launch, so the default is 0.0 (the launch-economy pins hold
    # exactly); integrity.DEFAULT_RECHECK_RATE = 0.05 is the benched
    # operating point for paranoid fleets (--integrity-recheck).
    integrity_recheck: float = 0.0
    # Escalating per-attempt solver timeouts for the SMT UNKNOWN-retry
    # path.  Non-empty enables the tier: still-unknown boxes after BaB +
    # heuristic retry fan out to the out-of-process worker pool
    # (fairify_tpu/smt, DESIGN.md §14) with this ladder.
    smt_retry_timeouts_s: Tuple[float, ...] = ()
    # --- SMT worker pool (fairify_tpu/smt, DESIGN.md §14) ---------------
    # Solver worker subprocesses; UNKNOWN boxes fan out across all of
    # them in parallel (the solver is single-threaded — this is the SMT
    # phase's only concurrency).
    smt_workers: int = 1
    # RLIMIT_AS per worker in MB (0 = uncapped): a solver memory blowup
    # dies in its own process and is retried ONCE on a doubled cap.
    smt_memory_cap_mb: int = 0
    # Race this many solver seed variants per query and take the first
    # decisive answer (0/1 = off).  Verdicts stay deterministic (sound
    # backends agree); witnesses may differ between runs.
    smt_portfolio: int = 0

    def query(self) -> FairnessQuery:
        domain = get_domain(self.dataset)
        if self.domain_overrides:
            domain = domain.override(**self.domain_overrides)
        # Attributes named as PA/RA but absent from the dataset's columns are
        # dropped, matching the reference where constraint builders match by
        # column name and silently skip misses (e.g. the phantom
        # 'marital-status' PA of relaxed/GC, ``relaxed/GC/Verify-GC.py:58``).
        pa = tuple(a for a in self.protected if a in domain.ranges)
        ra = tuple(a for a in self.relaxed if a in domain.ranges)
        return FairnessQuery(domain=domain, protected=pa, relaxed=ra, relax_eps=self.relax_eps)

    def with_(self, **kw) -> "SweepConfig":
        return replace(self, **kw)

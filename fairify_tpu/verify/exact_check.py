"""Independent exact confirmation of UNSAT verdicts — no solver dependency.

``z3-solver`` cannot be installed in this environment, so the UNSAT half of
the SMT cross-check audit (``audits/smt/``) had only the framework's own
attack harness behind it.  This module is the missing independent decision
procedure: a **complete, exact-rational-arithmetic check** of the pair
property over a partition box, sharing *no code or numerics* with the
engine that produced the certificates (no CROWN, no f32, no HiGHS):

* all arithmetic is ``fractions.Fraction`` over the exact dyadic values of
  the f32 weights — the same semantics Z3 would use on the exported
  SMT-LIB2 artifacts (``verify/smt.py`` encodes exact dyadic rationals);
* ReLU phase patterns are enumerated depth-first; interval bounds with the
  fixed phases (computed in exact rationals) prune dead directions;
* a fully-fixed pattern's region is a rational polyhedron; feasibility of
  {region ∧ f_a ≥ 0 ∧ f_b ≤ 0} is decided by an exact phase-1 simplex
  (Bland's rule — terminating, no tolerances).

Semantics: the check runs over the **continuous** box, a superset of the
integer lattice the property quantifies over, so

* every direction infeasible      → UNSAT **confirmed** (exact, continuous
  ⇒ lattice);
* a feasible point whose rounding validates as an exact lattice flip
  → the certificate is **refuted**;
* a feasible region with no lattice witness found → **inconclusive** (the
  flip slab may contain no integer point — consistent with lattice-UNSAT,
  but this checker cannot confirm it).

Reference anchor: Z3 as the ground-truth decision procedure in
``/root/reference/src/GC/Verify-GC.py:145-214``; this module plays that
role for the replay audit (``scripts/exact_replay.py``).
"""
from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction
from typing import List, Optional, Sequence, Tuple

import numpy as np

ZERO = Fraction(0)


# ---------------------------------------------------------------------------
# Exact phase-1 simplex (feasibility of A·x ≤ b over box-bounded x)
# ---------------------------------------------------------------------------


def _feasible(A: List[List[Fraction]], b: List[Fraction],
              lo: List[Fraction], hi: List[Fraction]):
    """Exact feasibility of {lo ≤ x ≤ hi, A·x ≤ b}.

    Returns ``('feasible', point)``, ``('infeasible', None)`` — proven by a
    phase-1 optimum with positive artificials — or ``('unknown', None)``
    when the pivot cap was hit before optimality: budget exhaustion must
    never masquerade as a proof of emptiness.

    Shifts to y = x − lo ≥ 0, folds upper bounds into rows, adds slacks and
    artificials, and runs phase-1 simplex with Bland's anti-cycling rule on
    a dense Fraction tableau.  Small systems only (tens of vars/rows) — the
    audit's polyhedra, not a general-purpose LP.
    """
    n = len(lo)
    rows: List[List[Fraction]] = []
    rhs: List[Fraction] = []
    for Ai, bi in zip(A, b):
        rows.append(list(Ai))
        rhs.append(bi - sum(a * l for a, l in zip(Ai, lo)))
    for j in range(n):
        if hi[j] == lo[j]:
            continue  # width-0 dims are constants; y_j ≤ 0 via hi row below
        r = [ZERO] * n
        r[j] = Fraction(1)
        rows.append(r)
        rhs.append(hi[j] - lo[j])
    for j in range(n):
        if hi[j] == lo[j]:
            r = [ZERO] * n
            r[j] = Fraction(1)
            rows.append(r)
            rhs.append(ZERO)  # y_j ≤ 0 and y_j ≥ 0 (nonneg) pin it

    m = len(rows)
    # Normalize to rhs ≥ 0 by multiplying rows by −1 (turns ≤ into ≥; such
    # rows get a surplus −1 and an artificial +1, others a slack +1).
    n_slack = m
    tab = []
    art_cols = []
    total = n + n_slack + m  # worst case one artificial per row
    n_art = 0
    for i in range(m):
        row = list(rows[i])
        r = rhs[i]
        if r < 0:
            row = [-a for a in row]
            r = -r
            slack = Fraction(-1)
        else:
            slack = Fraction(1)
        line = row + [ZERO] * n_slack + [ZERO] * m
        line[n + i] = slack
        if slack < 0:
            line[n + n_slack + n_art] = Fraction(1)
            art_cols.append(n + n_slack + n_art)
            basis_col = n + n_slack + n_art
            n_art += 1
        else:
            basis_col = n + i
        tab.append((line, r, basis_col))

    ncols = n + n_slack + n_art
    T = [line[:ncols] + [r] for (line, r, _) in tab]
    basis = [bc for (_, _, bc) in tab]
    art_set = set(art_cols)
    if not art_set:
        # Origin y=0 is feasible for all rows (rhs ≥ 0 with + slacks).
        return "feasible", [lo[j] for j in range(n)]

    # Phase-1 objective: minimize sum of artificials.
    cost = [ZERO] * (ncols + 1)
    for i, bcol in enumerate(basis):
        if bcol in art_set:
            for k in range(ncols + 1):
                cost[k] += T[i][k]

    max_pivots = 200 * (ncols + 1)
    proven_optimal = False
    for _ in range(max_pivots):
        enter = -1
        for j in range(ncols):
            if j not in art_set and cost[j] > 0:
                enter = j  # Bland: smallest index with positive reduced cost
                break
        if enter < 0:
            proven_optimal = True
            break
        leave, best = -1, None
        for i in range(len(T)):
            if T[i][enter] > 0:
                ratio = T[i][ncols] / T[i][enter]
                if best is None or ratio < best or (
                        ratio == best and basis[i] < basis[leave]):
                    best, leave = ratio, i
        if leave < 0:
            break  # unbounded phase-1 direction (cannot happen; bail safe)
        piv = T[leave][enter]
        T[leave] = [v / piv for v in T[leave]]
        for i in range(len(T)):
            if i != leave and T[i][enter] != 0:
                f = T[i][enter]
                T[i] = [a - f * b2 for a, b2 in zip(T[i], T[leave])]
        f = cost[enter]
        if f != 0:
            cost = [a - f * b2 for a, b2 in zip(cost, T[leave])]
        basis[leave] = enter

    art_total = sum((T[i][ncols] if basis[i] in art_set else ZERO)
                    for i in range(len(T)))
    if art_total != 0:
        # Artificials positive: proof of emptiness ONLY at phase-1 optimum.
        return ("infeasible" if proven_optimal else "unknown"), None
    y = [ZERO] * ncols
    for i, bcol in enumerate(basis):
        y[bcol] = T[i][ncols]
    return "feasible", [y[j] + lo[j] for j in range(n)]


# ---------------------------------------------------------------------------
# Exact network forms
# ---------------------------------------------------------------------------


def _frac_weights(weights, biases):
    """f32 weights/biases as exact Fractions (f32 values are dyadic)."""
    W = [[[Fraction(float(w[i, j])) for j in range(w.shape[1])]
          for i in range(w.shape[0])] for w in (np.asarray(x, np.float64) for x in weights)]
    B = [[Fraction(float(b[j])) for j in range(b.shape[0])]
         for b in (np.asarray(x, np.float64) for x in biases)]
    return W, B


@dataclass
class _Tower:
    """One role's affine view: input map x = M·s + t (s = shared vars)."""
    M: List[List[Fraction]]  # (n_vars → in_dim)
    t: List[Fraction]


def _interval_forward(W, B, tower: _Tower, phases: List[List[int]],
                      s_lo: List[Fraction], s_hi: List[Fraction]):
    """Exact interval bounds through fixed/auto ReLU phases.

    Returns ``(ok, unstable, out_iv, pre_bounds)``: ``ok=False`` when a
    forced phase contradicts the interval (empty region); ``unstable`` =
    first (layer, neuron) unstable-unfixed or None; ``out_iv`` = (lb, ub)
    of the logit; ``pre_bounds`` = per hidden layer (lb, ub) pairs feeding
    the CROWN backward pass.
    """
    nh = len(W) - 1
    iv = []
    for i in range(len(tower.M)):
        lbv = tower.t[i] + sum((a * (s_lo[k] if a > 0 else s_hi[k]))
                               for k, a in enumerate(tower.M[i]))
        ubv = tower.t[i] + sum((a * (s_hi[k] if a > 0 else s_lo[k]))
                               for k, a in enumerate(tower.M[i]))
        iv.append((lbv, ubv))
    pre_bounds: List[List[Tuple[Fraction, Fraction]]] = []
    unstable_first = None
    for k in range(len(W)):
        niv = []
        for j in range(len(B[k])):
            lb2 = B[k][j] + sum(
                W[k][i][j] * (ivl if W[k][i][j] > 0 else ivu)
                for i, (ivl, ivu) in enumerate(iv))
            ub2 = B[k][j] + sum(
                W[k][i][j] * (ivu if W[k][i][j] > 0 else ivl)
                for i, (ivl, ivu) in enumerate(iv))
            niv.append((lb2, ub2))
        if k == nh:
            return True, unstable_first, niv[0], pre_bounds
        pre_bounds.append(niv)
        piv = []
        for j in range(len(B[k])):
            ph = phases[k][j]
            lbj, ubj = niv[j]
            if ph == 0 and lbj >= 0:
                ph = 1  # provably active over the node's box superset
            if ph == 0 and ubj <= 0:
                ph = -1  # provably inactive
            if ph == 1:
                if ubj < 0:
                    return False, None, None, pre_bounds
                piv.append((max(lbj, ZERO), max(ubj, ZERO)))
            elif ph == -1:
                if lbj > 0:
                    return False, None, None, pre_bounds
                piv.append((ZERO, ZERO))
            else:
                if unstable_first is None:
                    unstable_first = (k, j)
                piv.append((ZERO, max(ubj, ZERO)))
        iv = piv
    raise AssertionError("unreachable")


def _crown_out_form(W, B, tower: _Tower, phases, s_lo, s_hi,
                    pre_bounds, upper: bool):
    """Exact-rational CROWN linear form of the output logit over s.

    One backward pass with the triangle upper / adaptive lower ReLU
    relaxations, phase-fixed neurons crossed exactly — the rational twin of
    ``ops.crown`` used purely for DFS pruning (the audit's *decisions* come
    from the exact leaf LPs; a loose bound here costs nodes, never
    soundness).  Returns ``(gs, const)`` with f ≤ gs·s + const over the
    region when ``upper``, else f ≥ gs·s + const.  Keeping the *form*
    (not just the concretized bound) lets the caller bound the tied pair
    difference f_a − f_b with the shared coefficients cancelling — the
    exact twin of the engine's decisive stage-0 certificate
    (``engine._tied_diff_ub``).  ``pre_bounds``: per hidden layer (lb, ub)
    from the interval pass with the same phases.
    """
    nh = len(W) - 1
    sgn = Fraction(1) if upper else Fraction(-1)
    g = [sgn * W[nh][i][0] for i in range(len(W[nh]))]
    const = sgn * B[nh][0]
    for k in range(nh - 1, -1, -1):
        ng = []
        for j, gj in enumerate(g):
            if gj == 0:
                ng.append(ZERO)
                continue
            lb, ub = pre_bounds[k][j]
            ph = phases[k][j]
            if ph == 0 and lb >= 0:
                ph = 1
            if ph == 0 and ub <= 0:
                ph = -1
            if ph == 1:
                ng.append(gj)  # h = z exactly
            elif ph == -1:
                ng.append(ZERO)  # h = 0
            elif gj > 0:
                # Need h's upper relaxation: h ≤ s·(z − l).
                s = ub / (ub - lb)
                ng.append(gj * s)
                const += gj * (-s * lb)
            else:
                # Need h's lower relaxation: h ≥ α·z, α ∈ {0, 1} adaptive.
                alpha = Fraction(1) if ub > -lb else ZERO
                ng.append(gj * alpha)
        n_in = len(W[k])
        g = [sum(W[k][i][j] * ng[j] for j in range(len(ng))) for i in range(n_in)]
        const += sum(B[k][j] * ng[j] for j in range(len(ng)))
    nv = len(s_lo)
    gs = [sum(g[i] * tower.M[i][v] for i in range(len(g))) for v in range(nv)]
    const += sum(g[i] * tower.t[i] for i in range(len(g)))
    if not upper:
        gs = [-a for a in gs]
        const = -const
    return gs, const


def _concretize_ub(gs, const, s_lo, s_hi) -> Fraction:
    """sup of gs·s + const over the box."""
    return const + sum((a * (s_hi[v] if a > 0 else s_lo[v]))
                       for v, a in enumerate(gs))


def _exact_logit_sign_frac(W, B, x: Sequence[int]) -> int:
    """Exact sign of the logit at an integer point (pure Fractions)."""
    h = [Fraction(int(v)) for v in x]
    nh = len(W) - 1
    for k in range(len(W)):
        z = [B[k][j] + sum(W[k][i][j] * h[i] for i in range(len(h)))
             for j in range(len(B[k]))]
        if k < nh:
            h = [v if v > 0 else ZERO for v in z]
        else:
            v = z[0]
            return 0 if v == 0 else (1 if v > 0 else -1)
    raise AssertionError


def decide_pair_box_exact(
    weights, biases, enc, lo, hi, max_nodes: int = 60000,
) -> dict:
    """Exact, lattice-complete check of the pair property for one partition.

    The independent twin of the engine's input-split BaB, in exact
    rationals: recursively split the box; at each sub-box kill flip
    directions with exact-CROWN role bounds and the exact tied-difference
    bound (shared coefficients cancelling, ``engine._tied_diff_ub``'s
    rational twin); a box whose splittable dims have all collapsed is a
    lattice *point* — its finitely many assignment/δ pairs are evaluated in
    exact arithmetic.  No phase branching, no continuous relaxation at the
    leaves, hence no 'inconclusive': verdicts are 'unsat_confirmed',
    'refuted' (with an exact lattice witness), or 'budget'.

    ``enc`` is a :class:`fairify_tpu.verify.property.PairEncoding`.
    """
    W, B = _frac_weights(weights, biases)
    d = len(lo)
    pa_idx = list(enc.pa_idx)
    ra_idx = list(enc.ra_idx)
    eps = int(enc.eps)
    n_ra = len(ra_idx) if eps else 0
    npa = len(pa_idx)

    # Variable layout (free-PA form, used for every V): s = all d dims
    # (PA slots carry role a's value) + RA deltas + role b's PA values.
    nv = d + n_ra + npa
    base_lo = [Fraction(int(v)) for v in lo] + [Fraction(-eps)] * n_ra \
        + [Fraction(int(lo[i])) for i in pa_idx]
    base_hi = [Fraction(int(v)) for v in hi] + [Fraction(eps)] * n_ra \
        + [Fraction(int(hi[i])) for i in pa_idx]

    def tower(role_b: bool) -> _Tower:
        M = [[ZERO] * nv for _ in range(d)]
        t = [ZERO] * d
        for i in range(d):
            if i in pa_idx:
                M[i][(d + n_ra + pa_idx.index(i)) if role_b else i] = Fraction(1)
            else:
                M[i][i] = Fraction(1)
                if role_b and eps and i in ra_idx:
                    M[i][d + ra_idx.index(i)] = Fraction(1)
        return _Tower(M, t)

    ta, tb = tower(False), tower(True)
    zero_phases = [[0] * len(b) for b in B[:-1]]
    split_vars = [i for i in range(d)] + list(range(d + n_ra, nv))
    wnp = [np.asarray(w) for w in weights]
    bnp = [np.asarray(bb) for bb in biases]
    deltas = None
    if n_ra:
        import itertools as it

        deltas = list(it.product(range(-eps, eps + 1), repeat=n_ra))

    def leaf_point(s_lo):
        """All splittable dims collapsed: decide the lattice point exactly."""
        shared = [int(s_lo[i]) for i in range(d)]
        pa_a = [int(s_lo[i]) for i in pa_idx]
        pa_b = [int(s_lo[d + n_ra + k]) for k in range(npa)]
        # valid_pair semantics: EVERY PA attribute must differ
        # (property.encode builds the conjunction of neq per coordinate).
        if any(pa_a[k] == pa_b[k] for k in range(npa)):
            return None
        x = np.array(shared, dtype=np.int64)
        xp = np.array(shared, dtype=np.int64)
        for k, i in enumerate(pa_idx):
            x[i] = pa_a[k]
            xp[i] = pa_b[k]
        sx = _exact_logit_sign_frac(W, B, x)
        if sx == 0:
            return None
        for dl in (deltas or [()]):
            xq = xp.copy()
            for k, dv in enumerate(dl):
                xq[ra_idx[k]] += dv
            sp = _exact_logit_sign_frac(W, B, xq)
            if (sx > 0 and sp < 0) or (sx < 0 and sp > 0):
                return x, xq
        return None

    budget = {"n": 0}

    def sweep(pos_t, neg_t):
        """Input-split sweep for one flip direction: f_pos > 0 ∧ f_neg < 0.

        Returns ('refuted', witness) | ('unsat', None) | ('budget', None).
        """
        stack = [(base_lo, base_hi)]
        while stack:
            if budget["n"] >= max_nodes:
                return "budget", None
            s_lo, s_hi = stack.pop()
            budget["n"] += 1
            ok_p, _, iv_p, pre_p = _interval_forward(
                W, B, pos_t, zero_phases, s_lo, s_hi)
            ok_n, _, iv_n, pre_n = _interval_forward(
                W, B, neg_t, zero_phases, s_lo, s_hi)
            if not ok_p or not ok_n:
                continue
            dead = False
            if iv_p[1] <= 0 or iv_n[0] >= 0:
                dead = True
            if not dead:
                gs_p, c_p = _crown_out_form(W, B, pos_t, zero_phases,
                                            s_lo, s_hi, pre_p, upper=True)
                if _concretize_ub(gs_p, c_p, s_lo, s_hi) <= 0:
                    dead = True
            if not dead:
                gs_n, c_n = _crown_out_form(W, B, neg_t, zero_phases,
                                            s_lo, s_hi, pre_n, upper=False)
                lb_n = -_concretize_ub([-a for a in gs_n], -c_n, s_lo, s_hi)
                if lb_n >= 0:
                    dead = True
            if not dead:
                diff = [gp - gn for gp, gn in zip(gs_p, gs_n)]
                if _concretize_ub(diff, c_p - c_n, s_lo, s_hi) <= 0:
                    dead = True  # flip needs f_pos − f_neg > 0 somewhere
            if dead:
                continue
            v = max(split_vars, key=lambda i: s_hi[i] - s_lo[i])
            if s_hi[v] - s_lo[v] <= 0:
                wit = leaf_point(s_lo)
                if wit is not None:
                    return "refuted", wit
                continue
            import math

            mid = Fraction(math.floor((s_lo[v] + s_hi[v]) / 2))
            left_hi = list(s_hi)
            left_hi[v] = mid
            right_lo = list(s_lo)
            right_lo[v] = mid + 1
            stack.append((list(s_lo), left_hi))
            stack.append((right_lo, list(s_hi)))
        return "unsat", None

    # Direction 1: f_a > 0 ∧ f_b < 0 over all free-PA values.  With no RA
    # relaxation the towers differ only in which PA vars they read, so the
    # pa_a ↔ pa_b swap makes direction 2 the SAME problem and one sweep is
    # complete.  With an RA shift the symmetry breaks (only role b is
    # shifted; the mirrored witness may need a shared point outside the
    # box), so direction 2 gets its own sweep with the roles' sign
    # requirements swapped.
    directions = [(ta, tb)] if n_ra == 0 else [(ta, tb), (tb, ta)]
    for pos_t, neg_t in directions:
        status, wit = sweep(pos_t, neg_t)
        if status == "refuted":
            return {"verdict": "refuted", "nodes": budget["n"],
                    "witness": (wit[0].tolist(), wit[1].tolist())}
        if status == "budget":
            return {"verdict": "budget", "nodes": budget["n"]}
    return {"verdict": "unsat_confirmed", "nodes": budget["n"]}


# ---------------------------------------------------------------------------
# Float-search / exact-verify sign certification (the AC-7-class audit)
# ---------------------------------------------------------------------------


def _dyadic_down(x: Fraction, bits: int = 30) -> Fraction:
    import math

    return Fraction(math.floor(x * (1 << bits)), 1 << bits)


def _dyadic_up(x: Fraction, bits: int = 30) -> Fraction:
    import math

    return Fraction(math.ceil(x * (1 << bits)), 1 << bits)


def _exact_layer_bounds(W, B, tower: _Tower, s_lo, s_hi):
    """Exact CROWN pre-activation bounds for every layer (root, no phases).

    Per layer, one rational backward pass per bound side using the bounds
    of the shallower layers — the exact twin of ``ops.crown.crown_bounds``.
    Interval-intersected, so never looser than plain IBP.  The cost (a few
    seconds on the zoo's deepest nets) is paid once per audited box; the
    resulting bounds make the audit's triangle relaxation engine-grade
    tight *and* exactly valid.
    """
    nv = len(s_lo)
    nh = len(W) - 1
    bounds: List[List[Tuple[Fraction, Fraction]]] = []

    def backward(k: int, j: int, upper: bool) -> Fraction:
        sgn = Fraction(1) if upper else Fraction(-1)
        g = [sgn * W[k][i][j] for i in range(len(W[k]))]
        const = sgn * B[k][j]
        for kk in range(k - 1, -1, -1):
            ng = []
            for jj, gj in enumerate(g):
                if gj == 0:
                    ng.append(ZERO)
                    continue
                lb, ub = bounds[kk][jj]
                if lb >= 0:
                    ng.append(gj)
                elif ub <= 0:
                    ng.append(ZERO)
                elif gj > 0:
                    s = ub / (ub - lb)
                    ng.append(gj * s)
                    const += gj * (-s * lb)
                else:
                    ng.append(gj if ub > -lb else ZERO)
            g = [sum(W[kk][i][jj] * ng[jj] for jj in range(len(ng)))
                 for i in range(len(W[kk]))]
            const += sum(B[kk][jj] * ng[jj] for jj in range(len(ng)))
        gs = [sum(g[i] * tower.M[i][v] for i in range(len(g))) for v in range(nv)]
        const += sum(g[i] * tower.t[i] for i in range(len(g)))
        total = const + sum((a * (s_hi[v] if a > 0 else s_lo[v]))
                            for v, a in enumerate(gs))
        return total if upper else -total

    # Interval pass for the cheap baseline to intersect with.
    iv = []
    for i in range(len(tower.M)):
        lbv = tower.t[i] + sum((a * (s_lo[v] if a > 0 else s_hi[v]))
                               for v, a in enumerate(tower.M[i]))
        ubv = tower.t[i] + sum((a * (s_hi[v] if a > 0 else s_lo[v]))
                               for v, a in enumerate(tower.M[i]))
        iv.append((lbv, ubv))
    for k in range(nh):
        layer = []
        n_out = len(B[k])
        for j in range(n_out):
            lb_i = B[k][j] + sum(
                W[k][i][j] * (l if W[k][i][j] > 0 else u)
                for i, (l, u) in enumerate(iv))
            ub_i = B[k][j] + sum(
                W[k][i][j] * (u if W[k][i][j] > 0 else l)
                for i, (l, u) in enumerate(iv))
            if k == 0:
                lb_f, ub_f = lb_i, ub_i  # exact affine over the box
            else:
                lb_c = backward(k, j, upper=False)
                ub_c = backward(k, j, upper=True)
                lb_f, ub_f = max(lb_i, lb_c), min(ub_i, ub_c)
            # Outward dyadic rounding (2⁻³⁰): deeper backward passes and the
            # triangle rows built from these bounds would otherwise drag
            # thousand-bit rationals through every product — bounds stay
            # exactly valid, coefficients stay small.
            layer.append((_dyadic_down(lb_f), _dyadic_up(ub_f)))
        bounds.append(layer)
        iv = [(max(l, ZERO), max(u, ZERO)) for (l, u) in layer]
    return bounds


def _exact_dual_bound(c, A_ub, b_ub, A_eq, b_eq, lb_v, ub_v, y_ub, y_eq) -> Fraction:
    """Exact weak-duality lower bound of min cᵀx over the polyhedron.

    For ANY y_ub ≥ 0 and free y_eq (here: HiGHS duals rounded to exact
    rationals, negatives clipped), every feasible x satisfies

      cᵀx ≥ −y_ubᵀb_ub − y_eqᵀb_eq + min_{x∈[lb,ub]} (c + A_ubᵀy_ub + A_eqᵀy_eq)ᵀx

    so the right-hand side — evaluated in Fractions — is a sound bound no
    matter how approximate the float solve was.  Float work *searches*,
    exact work *certifies*: the same division of labour as the engine's
    SAT witnesses.
    """
    n = len(c)
    r = list(c)
    acc = ZERO
    for yi, row, bi in zip(y_ub, A_ub, b_ub):
        if yi <= 0:
            continue
        acc -= yi * bi
        for v in range(n):
            if row[v] != 0:
                r[v] += yi * row[v]
    for yi, row, bi in zip(y_eq, A_eq, b_eq):
        if yi == 0:
            continue
        acc -= yi * bi
        for v in range(n):
            if row[v] != 0:
                r[v] += yi * row[v]
    for v in range(n):
        if r[v] > 0:
            acc += r[v] * lb_v[v]
        elif r[v] < 0:
            acc += r[v] * ub_v[v]
    return acc


def _exact_infeasibility(A_ub, b_ub, A_eq, b_eq, lb_v, ub_v) -> bool:
    """Exactly confirm a region is empty via a slack LP's verified dual.

    Minimise s ≥ 0 over {A_ub·x ≤ b_ub + s, |A_eq·x − b_eq| ≤ s, x ∈ box}:
    the float solve *finds* near-optimal duals, :func:`_exact_dual_bound`
    turns them into a rigorous rational lower bound of min s — positive ⇒
    the original region is empty.  False means "could not confirm" (the
    region may or may not be empty), never an unsound claim.
    """
    from scipy.optimize import linprog

    n = len(lb_v)
    c = [ZERO] * n + [Fraction(1)]
    A2, b2 = [], []
    for row, bi in zip(A_ub, b_ub):
        A2.append(list(row) + [Fraction(-1)])
        b2.append(bi)
    for row, bi in zip(A_eq, b_eq):
        A2.append(list(row) + [Fraction(-1)])
        b2.append(bi)
        A2.append([-v for v in row] + [Fraction(-1)])
        b2.append(-bi)
    lb2 = list(lb_v) + [ZERO]
    ub2 = list(ub_v) + [Fraction(10**9)]
    res = linprog(
        [float(v) for v in c],
        A_ub=np.array([[float(v) for v in r] for r in A2]),
        b_ub=np.array([float(v) for v in b2]),
        bounds=[(float(l), float(u)) for l, u in zip(lb2, ub2)],
        method="highs")
    if res.status != 0 or res.fun is None or res.fun <= 0:
        return False
    y = [Fraction(max(float(-m), 0.0))
         for m in np.atleast_1d(res.ineqlin.marginals)]
    bound = _exact_dual_bound(c, A2, b2, [], [], lb2, ub2, y, [])
    return bound > 0


def confirm_sign_certificate(
    weights, biases, lo, hi, want_positive: bool,
    max_nodes: int = 2000,
    trace: bool = False,
) -> dict:
    """Independent exact confirmation of a uniform-sign certificate.

    Float LP (scipy/HiGHS) finds candidate discharges over the *exact*
    triangle relaxation (rows built in Fractions from exact root CROWN
    intermediate bounds, floatified only for the solver); every discharge
    is then verified by :func:`_exact_dual_bound` in rationals, and
    fully-resolved regions fall back to the exact simplex.  Verdicts:
    'confirmed' | 'not_confirmed' | 'budget'.
    """
    from scipy.optimize import linprog

    W, B = _frac_weights(weights, biases)
    if not want_positive:
        # Negate the output layer: one minimisation path serves both signs.
        W = W[:-1] + [[[-w for w in row] for row in W[-1]]]
        B = B[:-1] + [[-b for b in B[-1]]]
    d = len(lo)
    M = [[ZERO] * d for _ in range(d)]
    for i in range(d):
        M[i][i] = Fraction(1)
    tower = _Tower(M, [ZERO] * d)
    s_lo = [Fraction(int(v)) for v in lo]
    s_hi = [Fraction(int(v)) for v in hi]
    root_bounds = _exact_layer_bounds(W, B, tower, s_lo, s_hi)
    nh = len(W) - 1
    sizes = [len(b) for b in B[:-1]]

    def build_rows(phases):
        """Exact triangle LP rows for a phase pattern.

        Vars: x (d) then h per hidden layer.  Returns None on an interval
        contradiction, else (c, A_ub, b_ub, A_eq, b_eq, lb_v, ub_v, meta)
        with meta = free unstable (layer, neuron, hvar) list.
        """
        off = [d]
        for s in sizes[:-1]:
            off.append(off[-1] + s)
        nvar = d + sum(sizes)
        lb_v = list(s_lo) + [ZERO] * sum(sizes)
        ub_v = list(s_hi) + [ZERO] * sum(sizes)
        A_ub, b_ub, A_eq, b_eq = [], [], [], []
        meta = []
        prev_off, prev_n = 0, d
        for k in range(nh):
            for j in range(sizes[k]):
                hv = off[k] + j
                l, u = root_bounds[k][j]
                ph = phases[k][j]
                if ph == 0 and l >= 0:
                    ph = 1
                if ph == 0 and u <= 0:
                    ph = -1
                if ph == -1:
                    if l > 0:
                        return None
                    lb_v[hv] = ub_v[hv] = ZERO
                    if u > 0:  # force z ≤ 0
                        row = [ZERO] * nvar
                        for i in range(prev_n):
                            row[prev_off + i] = W[k][i][j]
                        A_ub.append(row)
                        b_ub.append(-B[k][j])
                    continue
                if ph == 1:
                    if u < 0:
                        return None
                    row = [ZERO] * nvar
                    for i in range(prev_n):
                        row[prev_off + i] = W[k][i][j]
                    row[hv] = Fraction(-1)
                    A_eq.append(row)
                    b_eq.append(-B[k][j])
                    lb_v[hv] = max(l, ZERO)
                    ub_v[hv] = max(u, ZERO)
                    continue
                # Free unstable: triangle.
                lb_v[hv] = ZERO
                ub_v[hv] = max(u, ZERO)
                row = [ZERO] * nvar     # z − h ≤ 0
                for i in range(prev_n):
                    row[prev_off + i] = W[k][i][j]
                row[hv] = Fraction(-1)
                A_ub.append(row)
                b_ub.append(-B[k][j])
                s = u / (u - l)
                row = [ZERO] * nvar     # h − s·z ≤ −s·l
                for i in range(prev_n):
                    row[prev_off + i] = -s * W[k][i][j]
                row[hv] = Fraction(1)
                A_ub.append(row)
                b_ub.append(s * B[k][j] - s * l)
                meta.append((k, j, hv))
            prev_off, prev_n = off[k], sizes[k]
        c = [ZERO] * nvar
        for i in range(prev_n):
            c[prev_off + i] = W[nh][i][0]
        return c, A_ub, b_ub, A_eq, b_eq, lb_v, ub_v, meta, B[nh][0]

    stack = [[[0] * n for n in sizes]]
    nodes = 0
    while stack:
        if nodes >= max_nodes:
            return {"verdict": "budget", "nodes": nodes}
        phases = stack.pop()
        nodes += 1
        built = build_rows(phases)
        if built is None:
            continue  # exact interval contradiction: empty region
        c, A_ub, b_ub, A_eq, b_eq, lb_v, ub_v, meta, out_b = built
        res = linprog(
            [float(v) for v in c],
            A_ub=np.array([[float(v) for v in row] for row in A_ub]) if A_ub else None,
            b_ub=np.array([float(v) for v in b_ub]) if b_ub else None,
            A_eq=np.array([[float(v) for v in row] for row in A_eq]) if A_eq else None,
            b_eq=np.array([float(v) for v in b_eq]) if b_eq else None,
            bounds=[(float(l), float(u)) for l, u in zip(lb_v, ub_v)],
            method="highs")
        discharged = False
        if res.status == 2:
            # Float claims the branch region is empty; confirm exactly via
            # the slack LP before discharging (an unconfirmed empty claim
            # falls through to branching — sound either way).
            if _exact_infeasibility(A_ub, b_ub, A_eq, b_eq, lb_v, ub_v):
                if trace:
                    print(f"node {nodes}: infeasible (exactly confirmed)")
                continue
        if res.status == 0 and res.fun is not None:
            y_ub = [Fraction(max(float(m), 0.0)) for m in
                    (np.atleast_1d(-res.ineqlin.marginals) if A_ub else [])]
            y_eq = [Fraction(float(m)) for m in
                    (np.atleast_1d(-res.eqlin.marginals) if A_eq else [])]
            bound = _exact_dual_bound(c, A_ub, b_ub, A_eq, b_eq,
                                      lb_v, ub_v, y_ub, y_eq) + out_b
            if trace:
                nfix = sum(1 for l in phases for p in l if p != 0)
                print(f"node {nodes}: fixed={nfix} lp={res.fun + float(out_b):.4f} "
                      f"exact_bound={float(bound):.4f} free={len(meta)}")
            if bound > 0:
                discharged = True
        if discharged:
            continue
        if not meta:
            # Fully resolved affine region, bound could not clear zero:
            # decide exactly — eliminate h (affine in x) via the equalities
            # is already encoded; run the exact simplex on {region ∧ f ≤ 0}.
            A2 = [list(r) for r in A_ub] + [list(r) for r in A_eq] \
                + [[-v for v in r] for r in A_eq]
            b2 = list(b_ub) + list(b_eq) + [-v for v in b_eq]
            A2.append(list(c))
            b2.append(-out_b)  # f = c·x + out_b ≤ 0
            st, _ = _feasible(A2, b2, lb_v, ub_v)
            if st != "infeasible":
                # 'feasible' (sign claim fails here) or 'unknown' (pivot
                # cap): either way the certificate is not confirmed —
                # budget exhaustion must not silently discharge.
                return {"verdict": "not_confirmed", "nodes": nodes}
            continue
        # Branch on the most triangle-violating free neuron (from the LP
        # point when available; else — no usable float point — the free
        # neuron with the largest triangle area, a static proxy).
        pick = max(meta, key=lambda t: float(
            root_bounds[t[0]][t[1]][1] * -root_bounds[t[0]][t[1]][0]))[:2]
        if res.status == 0 and res.x is not None:
            best = -1.0
            x = res.x
            off0 = d
            offs = [d]
            for s_ in sizes[:-1]:
                offs.append(offs[-1] + s_)
            for (k, j, hv) in meta:
                po = 0 if k == 0 else offs[k - 1]
                pn = d if k == 0 else sizes[k - 1]
                z = float(B[k][j]) + sum(
                    float(W[k][i][j]) * x[po + i] for i in range(pn))
                v = abs(x[hv] - max(0.0, z))
                if v > best:
                    best, pick = v, (k, j)
        if trace:
            print(f"  branch pick={pick}")
        k, j = pick
        for ph in (1, -1):
            child = [list(l) for l in phases]
            child[k][j] = ph
            stack.append(child)
    return {"verdict": "confirmed", "nodes": nodes}


def pair_is_legal(enc, lo, hi, x, xp) -> bool:
    """Well-formedness of a counterexample pair, independent of its signs.

    The replay audit must establish more than a strict flip: the pair has
    to be a *legal* fairness pair — every PA coordinate differs
    (``property.encode``'s conjunction of neq), non-PA coordinates are tied
    (RA dims within ±ε), and the x role lies inside the partition box (the
    x' role may leave it on RA dims only, ``property.role_boxes``).
    """
    x = np.asarray(x, dtype=np.int64)
    xp = np.asarray(xp, dtype=np.int64)
    pa = set(int(i) for i in enc.pa_idx)
    ra = set(int(i) for i in enc.ra_idx) if enc.eps else set()
    for i in range(len(x)):
        if i in pa:
            if x[i] == xp[i]:
                return False
            if not (lo[i] <= x[i] <= hi[i] and lo[i] <= xp[i] <= hi[i]):
                return False
        else:
            if not (lo[i] <= x[i] <= hi[i]):
                return False
            if i in ra:
                if abs(int(xp[i]) - int(x[i])) > enc.eps:
                    return False
            elif x[i] != xp[i]:
                return False
    return True

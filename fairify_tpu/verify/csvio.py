"""Result sink: the reference's 24-column per-partition CSV schema, bit-kept.

Schema and append-per-partition behavior from ``src/GC/Verify-GC.py:272-309``
(identical across all drivers).  Keeping the schema lets verdicts be diffed
row-for-row against reference outputs.
"""
from __future__ import annotations

import csv
import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

RES_COLS = [
    "Partition_ID", "Verification", "SAT_count", "UNSAT_count", "UNK_count",
    "h_attempt", "h_success",
    "B_compression", "S_compression", "ST_compression", "H_compression", "T_compression",
    "SV-time", "S-time", "HV-Time", "H-Time", "Total-Time",
    "C-check", "V-accurate", "Original-acc", "Pruned-acc", "Acc-dec", "C1", "C2",
]


@dataclass
class PartitionRow:
    partition_id: int
    verdict: str  # 'sat' | 'unsat' | 'unknown'
    sat_count: int
    unsat_count: int
    unk_count: int
    h_attempt: int = 0
    h_success: int = 0
    b_compression: float = 0.0
    s_compression: float = 0.0
    st_compression: float = 0.0
    h_compression: float = 0.0
    t_compression: float = 0.0
    sv_time: float = 0.0
    s_time: float = 0.0
    hv_time: float = 0.0
    h_time: float = 0.0
    total_time: float = 0.0
    c_check: int = 0
    v_accurate: int = 0
    original_acc: float = 0.0
    pruned_acc: float = 0.0
    c1: Optional[np.ndarray] = None
    c2: Optional[np.ndarray] = None
    extra: dict = field(default_factory=dict)

    def to_list(self) -> list:
        def fmt_ce(v):
            # The reference writes the str() of a float32 numpy array
            # (``src/GC/Verify-GC.py:226-227,307-308``) or '' when absent.
            return str(np.asarray(v, dtype=np.float32)) if v is not None else ""

        return [
            self.partition_id, self.verdict, self.sat_count, self.unsat_count,
            self.unk_count, self.h_attempt, self.h_success,
            round(self.b_compression, 4), round(self.s_compression, 4),
            round(self.st_compression, 4), round(self.h_compression, 4),
            round(self.t_compression, 4),
            round(self.sv_time, 4), round(self.s_time, 4), round(self.hv_time, 4),
            round(self.h_time, 4), round(self.total_time, 4),
            self.c_check, self.v_accurate,
            round(self.original_acc, 4), round(self.pruned_acc, 4), "-",
            fmt_ce(self.c1), fmt_ce(self.c2),
        ]


def append_row(path: str, row: PartitionRow) -> None:
    exists = os.path.isfile(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a", newline="") as fp:
        wr = csv.writer(fp, dialect="excel")
        if not exists:
            wr.writerow(RES_COLS)
        wr.writerow(row.to_list())


def rewrite_deduped(path: str) -> None:
    """Rewrite a partition CSV keeping the LAST row per Partition_ID, sorted,
    with the cumulative SAT/UNSAT/UNK counter columns recomputed.

    ``--retry-unknown`` re-decides budget-exhausted partitions and appends
    their fresh rows; this restores the one-row-per-partition, ascending-id
    shape — and counters consistent with the final verdicts — that
    row-for-row comparisons expect (the csv module handles the multi-line
    quoted counterexample cells).
    """
    if not os.path.isfile(path):
        return
    with open(path, newline="") as fp:
        rows = list(csv.reader(fp))
    if not rows:
        return
    header, body = rows[0], rows[1:]
    last = {}
    for row in body:
        last[int(row[0])] = row
    counts = {"sat": 0, "unsat": 0, "unknown": 0}
    with open(path, "w", newline="") as fp:
        wr = csv.writer(fp)
        wr.writerow(header)
        for pid in sorted(last):
            row = last[pid]
            verdict = row[1] if row[1] in counts else "unknown"
            counts[verdict] += 1
            row[2:5] = [counts["sat"], counts["unsat"], counts["unknown"]]
            wr.writerow(row)

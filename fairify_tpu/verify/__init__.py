"""Decision engine: native, complete individual-fairness verification.

The reference decides each partition with a host-side Z3 SMT query over the
pruned network (``src/GC/Verify-GC.py:128-214``).  This package replaces SMT
with a TPU-native complete procedure:

* :mod:`fairify_tpu.verify.property` — the pair property (PA ``neq``, RA
  ``|Δ|≤ε``, others ``eq``, both points in the domain box, strict logit sign
  flip) as enumerated protected-assignment *roles* with static shapes.
* :mod:`fairify_tpu.verify.engine` — per-box certificates: batched
  CROWN/IBP bound certificates for UNSAT, batched sampling attack for SAT,
  input-space branch-and-bound over the integer lattice for the rest
  (complete because the lattice is finite), exact rational leaf evaluation.
* :mod:`fairify_tpu.verify.sweep` — the partition sweep: stage-1 whole-grid
  kernels, per-partition refinement, verdict ledger with resume, timing and
  CSV output in the reference's 24-column schema.

A gated Z3 backend (:mod:`fairify_tpu.verify.smt`) is retained for
environments with ``z3-solver`` installed; it is not required.
"""

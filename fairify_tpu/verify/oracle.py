"""Brute-force ground-truth oracle for the pair-fairness property.

Enumerates every legal ``(x, x')`` pair of a query over a small integer box
— the ground truth the reference would obtain from Z3's complete search
(``src/GC/Verify-GC.py:134-154``).  Deliberately *independent* of the
engine's own property machinery: legality is re-derived here from the query
definition (all protected attributes differ, shared attributes equal,
relaxed attributes within ±ε, both points' non-relaxed coordinates inside
the box), with none of ``property.encode``'s assignment/valid-pair tensors
or ``engine.decide_leaf``'s enumeration reused — so a bug there cannot
cancel out in the comparison.  Only the exact rational sign evaluator is
shared; it is itself cross-checked against the native dyadic core in
``tests/test_native.py``.  Exponential in the domain, so strictly a
testing device: the engine-vs-oracle unit tests (``tests/test_engine.py``)
and the randomized soundness fuzzer (``scripts/fuzz_oracle.py``) are built
on it.
"""
from __future__ import annotations

import itertools

import numpy as np

from fairify_tpu.data.domains import DomainSpec
from fairify_tpu.models import mlp
from fairify_tpu.verify import engine


def tiny_domain(ranges) -> DomainSpec:
    return DomainSpec(name="tiny", label="y", ranges=dict(ranges))


def random_net(rng, sizes, scale=1.0) -> mlp.MLP:
    ws, bs = [], []
    for i in range(len(sizes) - 1):
        ws.append((scale * rng.normal(size=(sizes[i], sizes[i + 1]))).astype(np.float32))
        bs.append((scale * rng.normal(size=(sizes[i + 1],))).astype(np.float32))
    return mlp.from_numpy(ws, bs)


def exact_sign(net, x) -> int:
    return engine.exact_logit_sign(
        [np.asarray(w) for w in net.weights], [np.asarray(b) for b in net.biases], x
    )


def brute_force_verdict(net, query, lo, hi) -> str:
    """Exhaustive pair enumeration: ``'sat'`` iff any legal pair strictly flips.

    ``x`` ranges over every lattice point of the box.  ``x'`` agrees with
    ``x`` off the protected/relaxed attributes, differs from it on *every*
    protected attribute (within the box), and sits within ±ε of it on each
    relaxed attribute (ε displacements are not re-clamped to the box,
    matching the engine's relaxed semantics).
    """
    lo = np.asarray(lo, dtype=np.int64)
    hi = np.asarray(hi, dtype=np.int64)
    d = len(query.columns)
    pa = sorted(int(i) for i in query.pa_idx)
    ra = [int(i) for i in query.ra_idx]
    eps = int(query.relax_eps)

    signs = {}

    def sign_of(pt) -> int:
        if pt not in signs:
            signs[pt] = exact_sign(net, np.array(pt, dtype=np.int64))
        return signs[pt]

    for x in itertools.product(*(range(lo[i], hi[i] + 1) for i in range(d))):
        sx = sign_of(x)
        if sx == 0:
            continue  # a strict flip needs two nonzero, opposite signs
        pa_axes = [[v for v in range(lo[i], hi[i] + 1) if v != x[i]] for i in pa]
        ra_axes = [range(x[r] - eps, x[r] + eps + 1) for r in ra] if eps else []
        for pa_vals in itertools.product(*pa_axes):
            for ra_vals in itertools.product(*ra_axes) if ra_axes else [()]:
                xp = list(x)
                for i, v in zip(pa, pa_vals):
                    xp[i] = v
                for r, v in zip(ra, ra_vals):
                    xp[r] = v
                sp = sign_of(tuple(xp))
                if (sx > 0 and sp < 0) or (sx < 0 and sp > 0):
                    return "sat"
    return "unsat"

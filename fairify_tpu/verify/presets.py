"""The 21 reference drivers as declarative presets.

Every entry reproduces one reference driver's configuration constants
(SURVEY.md §2.2; extracted from the driver headers, e.g.
``src/GC/Verify-GC.py:29-68``, ``stress/GC/Verify-GC.py:31-35``,
``relaxed/AC/Verify-AC.py:23-51``, ``targeted/BM/Verify-BM.py:23-54``,
``targeted2/GC/Verify-GC.py:23-58``).  The reference spreads these over 21
near-identical scripts; here a variant is a config delta.

Notes kept faithful:

* relaxed/GC and targeted2/GC name a ``marital-status`` protected attribute
  that does not exist in the German feature set; the reference's constraint
  builders match by column name and silently skip it
  (``utils/verif_utils.py:659-685``), so it is dropped at query build time.
* The experiment drivers (``src/*/Verify-*-experiment*.py``) share these
  base configs; their extra analysis stages live in
  :mod:`fairify_tpu.analysis`.
"""
from __future__ import annotations

from fairify_tpu.verify.config import SweepConfig

_BASE = dict(soft_timeout_s=100.0, hard_timeout_s=30 * 60.0, sim_size=1000)
_HOUR = dict(hard_timeout_s=60 * 60.0)

PRESETS = {
    # ----- base drivers (src/) -----
    "GC": SweepConfig(name="GC", dataset="german", protected=("age",),
                      partition_threshold=100, heuristic_threshold=5, **_BASE),
    "AC": SweepConfig(name="AC", dataset="adult", protected=("sex",),
                      partition_threshold=10, heuristic_threshold=5, **_BASE),
    "BM": SweepConfig(name="BM", dataset="bank", protected=("age",),
                      partition_threshold=100, heuristic_threshold=5, **_BASE),
    # The reference CP driver runs only CP-11 (``src/CP/Verify-CP.py:91``);
    # the other CP .h5 files are 12-input models for the task4 notebooks'
    # different feature encoding and don't fit the 6-attribute domain.
    "CP": SweepConfig(name="CP", dataset="compass", protected=("Race",),
                      partition_threshold=5, heuristic_threshold=50,
                      models=("CP-11",), **_BASE),
    # The 12-input CP family (CP-2..10, aCP-1-Old) the reference verifies
    # only via its task4 node runs; width-mismatched models are skipped by
    # the zoo's input-dim filter automatically.
    "CP12": SweepConfig(name="CP12", dataset="compass12", protected=("race",),
                        partition_threshold=5, heuristic_threshold=50, **_BASE),
    # LSAC bar passage: the reference ships the dataset but never wires it
    # (``data/lsac``, SURVEY.md §2.4) and has no zoo models for it; this
    # preset makes it a first-class target for the trained-student
    # pipelines (scripts/predicted_labels.py, scripts/synthetic_models.py).
    "LSAC": SweepConfig(name="LSAC", dataset="lsac", protected=("race1",),
                        partition_threshold=10, heuristic_threshold=5, **_BASE),
    "DF": SweepConfig(name="DF", dataset="default", protected=("SEX_2",),
                      partition_threshold=8, heuristic_threshold=100,
                      capped_partitions=True, max_partitions=100,
                      soft_timeout_s=100.0, sim_size=1000, **_HOUR),
    # ----- stress/ -----
    # pipeline_depth 4 (default 2): the stress grids run to millions of
    # boxes — thousands of grid_chunk launches per model — and their
    # stage-0 results are tiny (bool masks + witness indices), so a deeper
    # in-flight queue hides host decode jitter at negligible HBM cost.
    # Verdict maps are depth-invariant (chunk RNG keyed to global starts).
    # max_launch_retries 3 (default 2): hour-budget runs over thousands of
    # launches see more transient tunnel hiccups, and one extra ~110 ms
    # retry is far cheaper than degrading (and later re-sweeping) a
    # 2048-partition chunk.
    "stress-GC": SweepConfig(name="stress-GC", dataset="german", protected=("age",),
                             partition_threshold=10, heuristic_threshold=20,
                             soft_timeout_s=200.0, sim_size=1000,
                             pipeline_depth=4, max_launch_retries=3, **_HOUR),
    "stress-AC": SweepConfig(name="stress-AC", dataset="adult", protected=("sex",),
                             partition_threshold=6, heuristic_threshold=20,
                             soft_timeout_s=200.0, sim_size=1000,
                             pipeline_depth=4, max_launch_retries=3, **_HOUR),
    "stress-BM": SweepConfig(name="stress-BM", dataset="bank", protected=("age",),
                             partition_threshold=10, heuristic_threshold=20,
                             soft_timeout_s=200.0, sim_size=1000,
                             pipeline_depth=4, max_launch_retries=3, **_HOUR),
    # ----- relaxed/ -----
    "relaxed-GC": SweepConfig(name="relaxed-GC", dataset="german",
                              protected=("sex", "marital-status"),
                              partition_threshold=10, heuristic_threshold=20,
                              soft_timeout_s=100.0, sim_size=1000, **_HOUR),
    "relaxed-AC": SweepConfig(name="relaxed-AC", dataset="adult", protected=("race",),
                              relaxed=("age",), relax_eps=5,
                              partition_threshold=6, heuristic_threshold=20,
                              soft_timeout_s=100.0, sim_size=1000, **_HOUR),
    "relaxed-BM": SweepConfig(name="relaxed-BM", dataset="bank", protected=("age",),
                              relaxed=("duration",), relax_eps=5,
                              partition_threshold=10, heuristic_threshold=20,
                              soft_timeout_s=100.0, sim_size=1000, **_HOUR),
    # Framework-native two-RA variant (the reference's relaxed drivers stop
    # at one relaxed attribute, ``relaxed/BM/Verify-BM.py:51-54``; this
    # generalizes the same ε mechanism to two).  Exercises the round-4
    # multi-RA paths end to end: the (2ε+1)² decide_leaf window, the
    # separable two-axis Phase E dilation, and the pair-property RA
    # constraints on both dims.
    "relaxed2-BM": SweepConfig(name="relaxed2-BM", dataset="bank",
                               protected=("age",),
                               relaxed=("duration", "campaign"), relax_eps=5,
                               partition_threshold=10, heuristic_threshold=20,
                               soft_timeout_s=100.0, sim_size=1000, **_HOUR),
    # Three-RA variant (round 5): the ε mechanism over three relaxed
    # attributes.  Exercises k-RA completeness end to end — the (2ε+1)³
    # decide_leaf delta window, the three-axis separable Phase E dilation
    # (``ops/lattice.py``), and the RA constraints on all three dims.
    "relaxed3-BM": SweepConfig(name="relaxed3-BM", dataset="bank",
                               protected=("age",),
                               relaxed=("duration", "campaign", "previous"),
                               relax_eps=5,
                               partition_threshold=10, heuristic_threshold=20,
                               soft_timeout_s=100.0, sim_size=1000, **_HOUR),
    # ----- targeted/ (sub-population domains) -----
    "targeted-GC": SweepConfig(name="targeted-GC", dataset="german", protected=("sex",),
                               domain_overrides={"number_of_credits": (2, 2)},
                               partition_threshold=10, heuristic_threshold=20,
                               soft_timeout_s=100.0, sim_size=1000, **_HOUR),
    "targeted-AC": SweepConfig(name="targeted-AC", dataset="adult", protected=("race",),
                               domain_overrides={"age": (30, 35)},
                               partition_threshold=6, heuristic_threshold=20,
                               soft_timeout_s=100.0, sim_size=1000, **_HOUR),
    "targeted-BM": SweepConfig(name="targeted-BM", dataset="bank", protected=("age",),
                               relaxed=("duration",), relax_eps=5,
                               domain_overrides={"job": (2, 2), "loan": (1, 1)},
                               partition_threshold=10, heuristic_threshold=20,
                               soft_timeout_s=100.0, sim_size=1000, **_HOUR),
    # ----- targeted2/ (different sub-populations) -----
    "targeted2-GC": SweepConfig(name="targeted2-GC", dataset="german",
                                protected=("sex", "marital-status"),
                                domain_overrides={"purpose": (7, 7), "foreign_worker": (0, 0)},
                                partition_threshold=10, heuristic_threshold=20,
                                soft_timeout_s=100.0, sim_size=1000, **_HOUR),
    "targeted2-AC": SweepConfig(name="targeted2-AC", dataset="adult", protected=("race",),
                                domain_overrides={"education": (9, 10)},
                                partition_threshold=6, heuristic_threshold=20,
                                soft_timeout_s=100.0, sim_size=1000, **_HOUR),
    "targeted2-BM": SweepConfig(name="targeted2-BM", dataset="bank", protected=("age",),
                                relaxed=("duration",), relax_eps=5,
                                domain_overrides={"poutcome": (2, 2)},
                                partition_threshold=10, heuristic_threshold=20,
                                soft_timeout_s=100.0, sim_size=1000, **_HOUR),
    # Framework-native DF variant (the reference ships no targeted DF
    # driver).  The stock DF grid is 8 enormous boxes — every monetary dim
    # spans up to ~10^6 values, so the sampling attack finds a witness
    # instantly and the certificate/BaB path never runs (grid invariance
    # under the cap is pinned in tests/test_df_audit.py).  Pinning the
    # monetary dims to a concrete applicant profile (a targeted
    # sub-population, like targeted/GC's number_of_credits=2,
    # ``targeted/GC/Verify-GC.py:55``) yields boxes the bound certificates
    # genuinely decide — the DF models' certificate-path coverage.
    "targeted-DF": SweepConfig(
        name="targeted-DF", dataset="default", protected=("SEX_2",),
        domain_overrides={
            "LIMIT_BAL": (50000, 50000),
            "BILL_AMT1": (10000, 10000), "BILL_AMT2": (10000, 10000),
            "BILL_AMT3": (10000, 10000), "BILL_AMT4": (10000, 10000),
            "BILL_AMT5": (10000, 10000), "BILL_AMT6": (10000, 10000),
            "PAY_AMT1": (2000, 2000), "PAY_AMT2": (2000, 2000),
            "PAY_AMT3": (2000, 2000), "PAY_AMT4": (2000, 2000),
            "PAY_AMT5": (2000, 2000), "PAY_AMT6": (2000, 2000),
        },
        partition_threshold=8, heuristic_threshold=100,
        capped_partitions=True, max_partitions=100,
        soft_timeout_s=100.0, sim_size=1000, **_HOUR),
}


def get(name: str) -> SweepConfig:
    return PRESETS[name]


def names() -> list:
    return sorted(PRESETS)

"""Trace-time analyses of jitted kernels: purity and signature stability.

Both rules start from the same discovery pass: every function the file
jits, whether decorator-style (``@obs_jit``, ``@obs_jit(...)``,
``@jax.jit``, ``@partial(jax.jit, ...)``) or call-style
(``kernel = obs_jit(_impl, static_argnames=(...))``), together with its
declared ``static_argnames``.

**jit-purity** — a jitted body executes exactly once per (signature,
static key), at trace time; anything it does besides building the traced
computation silently stops happening on cached calls.  Flagged: ``print``,
``global``/``nonlocal`` declarations, calls into the host observability
layer (obs spans/events, metrics, heartbeat, ``profiling.bump_launch`` —
these belong at the call site, outside the kernel), and mutation of
captured host state (``xs.append(...)`` / ``xs[i] = ...`` where ``xs`` is
not bound inside the kernel).

**recompile-hazard** — the signature churn behind the ~110 ms stalls that
``obs/compile.py`` can only count after the fact, caught before merge:

* a ``static_argnames`` entry that names no parameter (a typo leaves the
  argument traced — or, on strict jax versions, errors at call time);
* a float-typed static parameter (every distinct value is a new
  executable; floats rarely repeat exactly) or a mutable default for a
  static parameter (unhashable → TypeError at call time);
* a Python conditional (``if``/``while``/ternary/``assert``) on a traced
  (non-static) parameter — ConcretizationError at trace time, or, where
  it survives, one retrace per branch outcome;
* a call site passing an enclosing loop's iteration variable as a static
  argument — one compile per distinct value, inside a chunk loop;
* constructing ``jax.jit``/``obs_jit`` inside a loop body — every
  iteration starts a fresh executable cache and re-pays trace+compile.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

from fairify_tpu.lint.core import FileContext, Finding, Rule

#: Mutating container methods whose receiver must be kernel-local.
#: ``update`` is deliberately absent: optax's pure
#: ``GradientTransformation.update(grads, state)`` is ubiquitous inside
#: jitted train steps and indistinguishable from ``dict.update`` by AST.
MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "clear", "add",
    "discard", "setdefault", "popitem", "appendleft",
    "extendleft", "sort", "reverse", "write",
})

#: Host-observability roots whose calls are side effects at trace time.
OBS_ROOTS = frozenset({
    "obs", "profiling", "heartbeat", "heartbeat_mod", "metrics_mod",
    "trace_mod", "hb_mod",
})
OBS_BARE = frozenset({"bump_launch", "notify_compile"})


@dataclass
class JittedDef:
    """One jitted function: its def node, statics, and callable aliases."""

    node: ast.AST  # FunctionDef / AsyncFunctionDef
    statics: Tuple[str, ...]
    aliases: Tuple[str, ...]  # names a call site may use for this kernel
    jit_line: int  # decorator / wrapping-call line for def-level findings


def _static_names_from_call(call: ast.Call) -> Tuple[str, ...]:
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, str))
    return ()


def _is_jit_name(node: ast.AST) -> bool:
    """``obs_jit`` / ``jax.jit`` as a bare expression."""
    if isinstance(node, ast.Name) and node.id == "obs_jit":
        return True
    return (isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name) and node.value.id == "jax")


def _jit_call(node: ast.AST) -> Optional[ast.Call]:
    """The jit-constructing Call if ``node`` is one (incl. partial form)."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jit_name(node.func):
        return node
    if isinstance(node.func, ast.Name) and node.func.id == "partial" \
            and node.args and _is_jit_name(node.args[0]):
        return node
    return None


def _decorator_statics(dec: ast.AST) -> Optional[Tuple[str, ...]]:
    """statics tuple if ``dec`` is a jit decorator, else None."""
    if _is_jit_name(dec):
        return ()
    call = _jit_call(dec)
    if call is not None:
        return _static_names_from_call(call)
    return None


def jitted_defs(ctx: FileContext) -> List[JittedDef]:
    """Per-file jitted-def discovery, cached (both jit rules share it)."""
    cached = ctx.cache.get("jitted_defs")
    if cached is None:
        cached = ctx.cache["jitted_defs"] = collect_jitted(ctx.tree)
    return cached


def collect_jitted(tree: ast.AST) -> List[JittedDef]:
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)

    out: List[JittedDef] = []
    seen: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            statics = _decorator_statics(dec)
            if statics is not None:
                out.append(JittedDef(node, statics, (node.name,),
                                     dec.lineno))
                seen.add(id(node))
                break
    # Call style: ``alias = obs_jit(_impl, name=..., static_argnames=...)``.
    for stmt in ast.walk(tree):
        if not (isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, ast.Call)):
            continue
        call = _jit_call(stmt.value)
        if call is None or not call.args:
            continue
        target_fn = call.args[-1] if isinstance(call.func, ast.Name) \
            and call.func.id == "partial" else call.args[0]
        if not isinstance(target_fn, ast.Name):
            continue
        fn = defs.get(target_fn.id)
        if fn is None or id(fn) in seen:
            continue
        aliases = tuple(t.id for t in stmt.targets
                        if isinstance(t, ast.Name)) or (target_fn.id,)
        out.append(JittedDef(fn, _static_names_from_call(call), aliases,
                             stmt.lineno))
        seen.add(id(fn))
    return out


def _param_args(fn) -> list:
    a = fn.args
    return list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)


def _param_names(fn) -> List[str]:
    names = [p.arg for p in _param_args(fn)]
    if fn.args.vararg:
        names.append(fn.args.vararg.arg)
    if fn.args.kwarg:
        names.append(fn.args.kwarg.arg)
    return names


def _target_names(t: ast.AST) -> Iterable[str]:
    """Names *bound* by an assignment target.  A subscript/attribute store
    (``xs[i] = v`` / ``o.a = v``) binds nothing — its base must already be
    bound, and treating it as a binding would hide exactly the captured
    mutation the purity rule exists to flag."""
    if isinstance(t, ast.Name):
        yield t.id
    elif isinstance(t, ast.Starred):
        yield from _target_names(t.value)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for el in t.elts:
            yield from _target_names(el)


def _bound_names(fn) -> set:
    """Every name bound anywhere inside ``fn`` (params, assignments, loop
    and comprehension targets, with/except aliases, imports, nested defs).

    Nested scopes are merged — coarse, but it only makes the captured-state
    check *miss* shadowed captures, never flag kernel-local state.
    """
    bound = set(_param_names(fn))
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                bound.update(_target_names(t))
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            bound.update(_target_names(node.target))
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            bound.update(_target_names(node.target))
        elif isinstance(node, ast.comprehension):
            bound.update(_target_names(node.target))
        elif isinstance(node, ast.withitem) and node.optional_vars:
            bound.update(_target_names(node.optional_vars))
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            bound.add(node.name)
            if node is not fn and not isinstance(node, ast.ClassDef):
                bound.update(p.arg for p in _param_args(node))
        elif isinstance(node, ast.Lambda):
            bound.update(p.arg for p in _param_args(node))
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                bound.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, (ast.NamedExpr,)):
            bound.update(_target_names(node.target))
    return bound


def _call_root(expr: ast.AST) -> Optional[str]:
    """Leftmost Name of a dotted/chained call target
    (``obs.registry().counter("x").inc`` → ``obs``)."""
    while True:
        if isinstance(expr, ast.Attribute):
            expr = expr.value
        elif isinstance(expr, ast.Call):
            expr = expr.func
        elif isinstance(expr, ast.Name):
            return expr.id
        else:
            return None


class JitPurityRule(Rule):
    id = "jit-purity"
    description = ("side effects inside jit-traced bodies (run at trace "
                   "time only): print, global/nonlocal, obs/metrics/"
                   "heartbeat calls, mutation of captured state")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for jd in jitted_defs(ctx):
            fn_name = jd.node.name
            if self.allowed(ctx.rel, fn_name):
                continue
            bound = _bound_names(jd.node)
            for node in ast.walk(jd.node):
                yield from self._check_node(ctx, fn_name, node, bound)

    def _check_node(self, ctx, fn_name, node, bound):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            kind = "global" if isinstance(node, ast.Global) else "nonlocal"
            yield self.finding(
                ctx, node.lineno,
                f"{kind} mutation inside a jit-traced body — runs once at "
                f"trace time, never per execution; return the value "
                f"instead", function=fn_name)
            return
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id == "print":
                yield self.finding(
                    ctx, node.lineno,
                    "print() inside a jit-traced body — fires at trace "
                    "time only; use jax.debug.print for per-execution "
                    "output or move it to the call site",
                    function=fn_name)
            root = _call_root(f)
            if root in OBS_ROOTS or (isinstance(f, ast.Name)
                                     and f.id in OBS_BARE):
                yield self.finding(
                    ctx, node.lineno,
                    "host observability call inside a jit-traced body — "
                    "spans/metrics/heartbeat record trace time, not "
                    "execution; instrument the call site instead",
                    function=fn_name)
            if isinstance(f, ast.Attribute) and f.attr in MUTATORS \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id not in bound:
                yield self.finding(
                    ctx, node.lineno,
                    f"mutation of captured {f.value.id!r} "
                    f"(.{f.attr}) inside a jit-traced body — happens once "
                    f"at trace time; thread state through the kernel's "
                    f"returns", function=fn_name)
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id not in bound:
                    yield self.finding(
                        ctx, t.lineno,
                        f"subscript store into captured {t.value.id!r} "
                        f"inside a jit-traced body — happens once at trace "
                        f"time; return the value instead",
                        function=fn_name)


#: Call/test constructs whose result is concrete even on traced values.
_CONCRETE_FNS = frozenset({"len", "isinstance", "type", "getattr",
                           "hasattr", "callable"})


def _traced_cond_name(test: ast.AST, dyn: set) -> Optional[str]:
    """A dynamic-parameter Name the test's truthiness depends on, if any.

    Shape-level introspection stays legal: attribute access (``x.ndim``),
    ``len(x)``, ``isinstance``, and identity tests (``x is None``) are all
    concrete under tracing.  Calls are skipped entirely (their purity is
    the callee's business) — the rule prefers missing a hazard to flagging
    idiomatic shape code.
    """
    if isinstance(test, ast.Name):
        return test.id if test.id in dyn else None
    if isinstance(test, ast.Attribute):
        return None  # x.ndim / x.shape — concrete
    if isinstance(test, ast.Call):
        return None
    if isinstance(test, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return None  # `x is None` — identity on the Python object
        for sub in [test.left] + list(test.comparators):
            hit = _traced_cond_name(sub, dyn)
            if hit:
                return hit
        return None
    if isinstance(test, ast.BoolOp):
        for sub in test.values:
            hit = _traced_cond_name(sub, dyn)
            if hit:
                return hit
        return None
    if isinstance(test, ast.UnaryOp):
        return _traced_cond_name(test.operand, dyn)
    if isinstance(test, ast.BinOp):
        return (_traced_cond_name(test.left, dyn)
                or _traced_cond_name(test.right, dyn))
    if isinstance(test, ast.Subscript):
        # x[0] of a traced array is traced; the slice itself is not.
        return _traced_cond_name(test.value, dyn)
    return None


class RecompileHazardRule(Rule):
    id = "recompile-hazard"
    description = ("jit signature churn caught statically: bad/float/"
                   "mutable static args, Python conditionals on traced "
                   "values, per-iteration static kwargs, jit-in-loop")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        jds = jitted_defs(ctx)
        for jd in jds:
            if not self.allowed(ctx.rel, jd.node.name):
                yield from self._check_def(ctx, jd)
        yield from self._check_sites(ctx, jds)

    # -- definition-level hazards -----------------------------------------
    def _check_def(self, ctx, jd):
        fn = jd.node
        params = _param_names(fn)
        for s in jd.statics:
            if s not in params:
                yield self.finding(
                    ctx, jd.jit_line,
                    f"static_argnames entry {s!r} names no parameter of "
                    f"{fn.name} — the argument stays traced (typo?)",
                    function=fn.name)
        args = _param_args(fn)
        defaults = fn.args.defaults
        # Map trailing defaults onto positional params.
        pos = list(fn.args.posonlyargs) + list(fn.args.args)
        default_of = dict(zip([p.arg for p in pos[len(pos) - len(defaults):]],
                              defaults))
        default_of.update({p.arg: d for p, d in
                           zip(fn.args.kwonlyargs, fn.args.kw_defaults) if d})
        for p in args:
            if p.arg not in jd.statics:
                continue
            ann_float = (isinstance(p.annotation, ast.Name)
                         and p.annotation.id == "float")
            d = default_of.get(p.arg)
            d_float = (isinstance(d, ast.Constant)
                       and isinstance(d.value, float))
            if ann_float or d_float:
                yield self.finding(
                    ctx, p.lineno,
                    f"float-valued static arg {p.arg!r} — every distinct "
                    f"value compiles a new executable; pass it as a traced "
                    f"array or quantize it", function=fn.name)
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                yield self.finding(
                    ctx, p.lineno,
                    f"mutable default for static arg {p.arg!r} — "
                    f"unhashable static values fail the jit cache key",
                    function=fn.name)
        dyn = set(params) - set(jd.statics)
        for node in ast.walk(fn):
            tests = []
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                tests.append(node.test)
            elif isinstance(node, ast.Assert):
                tests.append(node.test)
            for t in tests:
                name = _traced_cond_name(t, dyn)
                if name:
                    yield self.finding(
                        ctx, t.lineno,
                        f"Python conditional on traced value {name!r} "
                        f"inside a jitted body — ConcretizationError at "
                        f"trace time or one retrace per outcome; use "
                        f"lax.cond/jnp.where or declare it static",
                        function=fn.name)

    # -- call-site hazards -------------------------------------------------
    def _check_sites(self, ctx, jds):
        """One pass over the shared walk: per-iteration static args at call
        sites of this file's kernels, and jit construction inside loops."""
        kernels: Dict[str, Tuple[Tuple[str, ...], List[str]]] = {}
        for jd in jds:
            info = (jd.statics, _param_names(jd.node))
            for alias in jd.aliases:
                kernels[alias] = info
        for node, fn, in_loop, loop_targets in ctx.attributed():
            if not isinstance(node, ast.Call):
                continue
            if in_loop and _jit_call(node) is not None \
                    and not self.allowed(ctx.rel, fn):
                yield self.finding(
                    ctx, node.lineno,
                    "jax.jit/obs_jit constructed inside a loop body — each "
                    "iteration starts an empty executable cache and "
                    "re-pays trace+compile; hoist the jitted callable out "
                    "of the loop", function=fn)
            if not (kernels and loop_targets):
                continue
            f = node.func
            name = f.id if isinstance(f, ast.Name) else None
            if name not in kernels:
                continue
            statics, params = kernels[name]
            varying = []
            for kw in node.keywords:
                if kw.arg in statics and isinstance(kw.value, ast.Name) \
                        and kw.value.id in loop_targets:
                    varying.append((kw.arg, kw.value.id))
            for i, a in enumerate(node.args):
                if i < len(params) and params[i] in statics \
                        and isinstance(a, ast.Name) and a.id in loop_targets:
                    varying.append((params[i], a.id))
            for static_name, var in varying:
                yield self.finding(
                    ctx, node.lineno,
                    f"static arg {static_name!r} of {name} is the loop "
                    f"variable {var!r} — one XLA compile per iteration "
                    f"value; pad/bucket to a fixed static instead",
                    function=fn)

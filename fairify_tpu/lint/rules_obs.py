"""The five core observability rules (migrated from the pre-PR-6
``scripts/lint_obs.py`` script, which has since been removed).

Two attribution bugs were fixed during the migration:

* the hot-loop fetch rule no longer flags fetches in a ``for``/``while``
  **``else:``** clause or in a ``for``'s iterable expression — both run
  once, not per iteration (the old walker used ``iter_child_nodes`` and
  could not tell ``body`` from ``orelse``);
* the broad-except and loop-fetch walkers reset function attribution at
  ``ClassDef`` boundaries, so a handler in a class body is attributed to
  the class name instead of silently inheriting the enclosing
  ``<module>``/function allowlist key.

The broad-except rule has since grown a stricter tier: handlers catching
``BaseException`` (or bare ``except:``) must guarantee that
propagate-class errors — ``KeyboardInterrupt``/``SystemExit``/
``ReplicaKilled`` — escape, via an unconditional re-raise or the
``classify(exc) == "propagate"`` guard (DESIGN.md §16).
"""
from __future__ import annotations

import ast
from typing import Iterable

from fairify_tpu.lint.core import FileContext, Finding, Rule

# ---------------------------------------------------------------------------
# Allowlists (reviewed exceptions; repo-relative '/'-separated paths).
# Shrink, don't grow, each of them.
# ---------------------------------------------------------------------------

ALLOW_TIME_TIME = frozenset({
    "fairify_tpu/obs/trace.py",  # the obs layer's wall-clock shim
    # Epoch timestamps by design, not phase timing: request ids sort by
    # submit wall-clock; lifecycle journal records carry a real `ts`.
    "fairify_tpu/serve/request.py::new_request_id",
    "fairify_tpu/serve/request.py::monotonic_from_epoch",
    "fairify_tpu/serve/client.py::submit",
    "fairify_tpu/serve/server.py::_journal_record",
    "fairify_tpu/serve/fleet.py::_journal_record",  # same epoch `ts` field
    "fairify_tpu/serve/procfleet.py::_journal",     # same epoch `ts` field
    # File-lease age is epoch-now minus file mtime BY DESIGN: mtimes are
    # wall-clock, and router + replica share one host clock (DESIGN.md §18).
    "fairify_tpu/serve/procfleet.py::_lease_age",
})

ALLOW_PRINT = frozenset({
    "fairify_tpu/cli.py",            # user-facing command output
    "fairify_tpu/obs/heartbeat.py",  # the sanctioned progress line
    "fairify_tpu/obs/report.py",     # report renderer (CLI body)
    "fairify_tpu/verify/sweep.py",   # legacy: stderr width-mismatch warning
    "fairify_tpu/verify/exact_check.py",  # legacy: gated debug prints
    "fairify_tpu/lint/core.py",      # the lint CLI's own report output
})

# Raw-jit rule scope: every device kernel of the verification core must go
# through obs.compile.obs_jit (named compile spans, recompile accounting).
RAW_JIT_SCOPE = ("fairify_tpu/verify/", "fairify_tpu/ops/")
# Repo-relative file paths reviewed as legitimate bare-jit users.  Empty:
# the whole core is migrated; a new entry needs a reason in review.
ALLOW_RAW_JIT: frozenset = frozenset()

# Hot-loop fetch rule scope: chunk/frontier loops of the verification core.
LOOP_FETCH_SCOPE = ("fairify_tpu/verify/",)
# ``file::function`` sync points reviewed as legitimate.  Everything else in
# a verify/ loop must route through parallel.pipeline.LaunchPipeline.
ALLOW_LOOP_FETCH = frozenset({
    # Drain-API decode bodies: the pipeline hands them HOST payloads; the
    # remaining np.asarray calls pull already-materialized model weights.
    "fairify_tpu/verify/sweep.py::_family_block_decode",
    # Per-partition heuristic-retry re-sim: one tiny launch whose result
    # this row's CSV needs immediately — scoped to its own helper so the
    # sweep's main loop body stays under the lint.
    "fairify_tpu/verify/sweep.py::_parity_resim",
    # BaB frontier iterations are sequentially dependent (each batch's
    # branching decides the next batch) — no independent work to overlap.
    "fairify_tpu/verify/engine.py::decide_many",
    # Device-BaB segment driver (DESIGN.md §22): launches DO go through
    # LaunchPipeline (depth 1 — each segment's queue state feeds the next,
    # so there is nothing to overlap); the flagged np.asarray/np.array
    # calls are the sanctioned at-dequeue conversions of already-drained
    # host payloads plus pure-host root-box coercions at group setup.
    "fairify_tpu/verify/engine.py::_device_bab_phase",
    "fairify_tpu/verify/engine.py::uniform_sign_bab",
    "fairify_tpu/verify/engine.py::_run_lp_phase",
    # Exact-certify chunk results feed the immediately-following host mask
    # assembly per chunk; candidate for pipelining, not yet converted.
    # (sound_prune_grid itself now submits through LaunchPipeline.)
    "fairify_tpu/verify/exact_check.py::exact_certify_grid",
    # Pure-host numpy coercions of weights/points inside exact/LP/SMT
    # loops — ``np.asarray`` on data that never lived on device.
    "fairify_tpu/verify/engine.py::exact_logit_sign",
    "fairify_tpu/verify/engine.py::_leaf_sign_lp",
    "fairify_tpu/verify/engine.py::_eligible_lattice_roots",
    "fairify_tpu/verify/smt.py::_z3_net",
    # Per-root host phases (lattice enumeration / pair LP): independent
    # roots, so genuine pipelining candidates — not yet converted; the
    # fetched payloads feed immediately-following serial host solvers.
    "fairify_tpu/verify/engine.py::_lattice_phase",
    "fairify_tpu/verify/engine.py::_pair_lp_phase",
    # Integrity sampled recheck (DESIGN.md §21): deliberately OFF-pipeline —
    # an independent synchronous re-execution whose result must be compared
    # bit-for-bit against the banked verdicts before the next chunk is
    # trusted; routing it through the shared pipeline would let a corrupted
    # launch path corrupt its own check.
    "fairify_tpu/verify/sweep.py::_sampled_recheck",
})

ALLOW_BROAD_EXCEPT = frozenset({
    # Compile fallbacks: an unusable AOT path serves the kernel via plain
    # jax.jit (counted in xla_compile_fallbacks) — observability must
    # never change results or availability.  (_compile's handler re-raises
    # propagate-class faults, so only __call__'s swallow sites need this.)
    "fairify_tpu/obs/compile.py::__call__",
    # Backend-optional executable analyses (cost/memory): absence degrades
    # to missing attrs.
    "fairify_tpu/obs/compile.py::_record_analysis",
    # IR analysis suite: a kernel that fails to lower/key/compile under the
    # analysis avals is not an error to swallow silently — each failure is
    # CAPTURED AS A FINDING (KernelIR.lower_error feeds the ir-recompile
    # pass; variant keys degrade to a reported 'variant key unavailable';
    # memory_analysis absence degrades the buffer cross-check exactly like
    # _record_analysis above).  The analysis layer must never crash the
    # lint gate over one kernel.
    "fairify_tpu/analysis/ir.py::from_obs_jit",
    "fairify_tpu/analysis/ir.py::from_fn",
    "fairify_tpu/analysis/ir.py::memory_analysis",
    "fairify_tpu/analysis/ir.py::aval_bytes",
    "fairify_tpu/analysis/ir.py::_rel",
    "fairify_tpu/analysis/passes_buffers.py::check_kernel",
    "fairify_tpu/analysis/passes_host.py::check_kernel",
    # SMT pool dispatch lane: any error is captured in the query's future
    # (the consumer classifies it); the lane itself must keep draining so
    # sibling queries never stall — it re-raises nothing by contract,
    # though it DOES return (die) on propagate-class errors.
    "fairify_tpu/smt/pool.py::_lane",
})

_FETCH_HINT = (
    "synchronous device fetch in a verify/ loop — submit through "
    "parallel.pipeline.LaunchPipeline and convert at dequeue "
    "(or extend ALLOW_LOOP_FETCH with file::function and a reason)")

_BROAD_HINT = (
    "broad except (bare/Exception/BaseException) that never re-raises — "
    "classify via fairify_tpu.resilience.supervisor.classify and degrade "
    "with a recorded reason, or extend ALLOW_BROAD_EXCEPT with a reviewed "
    "reason")

_BASE_HINT = (
    "BaseException handler without the propagate re-raise pattern — "
    "KeyboardInterrupt/SystemExit/ReplicaKilled must escape: re-raise "
    "unconditionally, or guard with `if classify(exc) == \"propagate\": "
    "raise` (resilience.supervisor.classify), or extend "
    "ALLOW_BROAD_EXCEPT with a reviewed reason")


# ---------------------------------------------------------------------------
# Node predicates (verbatim from the script)
# ---------------------------------------------------------------------------


def _is_time_time(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "time"
            and isinstance(f.value, ast.Name) and f.value.id == "time")


def _is_print(node: ast.Call) -> bool:
    return isinstance(node.func, ast.Name) and node.func.id == "print"


def _is_raw_jit(node: ast.AST) -> bool:
    """The ``jax.jit`` attribute itself: catches ``@jax.jit``,
    ``jax.jit(f, ...)`` and ``partial(jax.jit, ...)`` uniformly."""
    return (isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name) and node.value.id == "jax")


def _is_loop_fetch(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "block_until_ready":
            return True
        if isinstance(f.value, ast.Name):
            # np.asarray(...) / jax.device_get(...) on loop-carried arrays.
            if f.value.id in ("np", "numpy") and f.attr == "asarray":
                return True
            if f.value.id == "jax" and f.attr == "device_get":
                return True
    return False


def _is_broad_type(node) -> bool:
    """Does the handler's type expression name Exception/BaseException?"""
    if node is None:
        return True  # bare except:
    if isinstance(node, ast.Tuple):
        return any(_is_broad_type(el) for el in node.elts)
    return isinstance(node, ast.Name) and node.id in ("Exception",
                                                      "BaseException")


def _is_base_type(node) -> bool:
    """Catches BaseException (or is bare) — the handlers that can eat a
    KeyboardInterrupt/SystemExit/ReplicaKilled."""
    if node is None:
        return True
    if isinstance(node, ast.Tuple):
        return any(_is_base_type(el) for el in node.elts)
    return isinstance(node, ast.Name) and node.id == "BaseException"


def _guard_mentions_propagate(test: ast.AST) -> bool:
    """Does a guard POSITIVELY test the propagate class — ``classify(...)
    == 'propagate'`` (Eq, not NotEq) or ``isinstance(exc,
    KeyboardInterrupt/SystemExit/ReplicaKilled/PROPAGATE)`` not under a
    ``not``?  Polarity matters: ``!= "propagate"`` / ``not isinstance``
    guards select the NON-propagate class, so a raise in their body says
    nothing about kills escaping."""
    negated = {id(sub) for n in ast.walk(test)
               if isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.Not)
               for sub in ast.walk(n.operand)}
    for n in ast.walk(test):
        if id(n) in negated:
            continue
        if isinstance(n, ast.Compare) \
                and all(isinstance(op, ast.Eq) for op in n.ops) \
                and any(isinstance(c, ast.Constant) and c.value == "propagate"
                        for c in ast.walk(n)):
            return True
        name = n.id if isinstance(n, ast.Name) else \
            (n.attr if isinstance(n, ast.Attribute) else None)
        if name in ("KeyboardInterrupt", "SystemExit", "ReplicaKilled",
                    "PROPAGATE"):
            return True
    return False


def _reraises_propagate(handler: ast.ExceptHandler) -> bool:
    """Does this handler guarantee propagate-class errors escape
    UNCHANGED?  Either a bare ``raise`` directly in its body, or a
    positively propagate-guarded ``if`` whose body bare-raises — a
    ``raise Other(...) from exc`` converts the kill and does not count."""
    for st in handler.body:
        if isinstance(st, ast.Raise) and st.exc is None:
            return True
    for node in ast.walk(handler):
        if isinstance(node, ast.If) and _guard_mentions_propagate(node.test) \
                and any(isinstance(n, ast.Raise) and n.exc is None
                        for st in node.body for n in ast.walk(st)):
            return True
    return False


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------


class TimeTimeRule(Rule):
    id = "obs-time-time"
    description = ("raw time.time() banned in fairify_tpu/ — timing goes "
                   "through PhaseTimer / obs spans (monotonic clocks)")
    allowlist = ALLOW_TIME_TIME

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if self.allowed(ctx.rel):
            return
        for node, fn, _loop, _t in ctx.attributed():
            if isinstance(node, ast.Call) and _is_time_time(node) \
                    and not self.allowed(ctx.rel, fn):
                yield self.finding(
                    ctx, node.lineno,
                    "raw time.time() — use time.perf_counter() via "
                    "PhaseTimer/obs spans (or extend ALLOW_TIME_TIME for a "
                    "sanctioned shim)", function=fn)


class PrintRule(Rule):
    id = "obs-print"
    description = ("bare print() banned in fairify_tpu/ — progress goes "
                   "through obs.heartbeat, structured output through the "
                   "event log")
    allowlist = ALLOW_PRINT

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if self.allowed(ctx.rel):
            return
        for node, fn, _loop, _t in ctx.attributed():
            if isinstance(node, ast.Call) and _is_print(node):
                yield self.finding(
                    ctx, node.lineno,
                    "bare print() — progress goes through "
                    "fairify_tpu.obs.heartbeat, structured output through "
                    "the event log (or extend ALLOW_PRINT for user-facing "
                    "output)", function=fn)


class RawJitRule(Rule):
    id = "obs-raw-jit"
    description = ("bare jax.jit banned in verify/ and ops/ — kernels "
                   "register through obs.compile.obs_jit")
    scope = RAW_JIT_SCOPE
    allowlist = ALLOW_RAW_JIT

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if self.allowed(ctx.rel):
            return
        for node, fn, _loop, _t in ctx.attributed():
            if _is_raw_jit(node):
                yield self.finding(
                    ctx, node.lineno,
                    "bare jax.jit — register device kernels through "
                    "fairify_tpu.obs.compile.obs_jit so compiles are "
                    "named/counted/timed (or extend ALLOW_RAW_JIT with a "
                    "reviewed reason)", function=fn)


class BroadExceptRule(Rule):
    id = "obs-broad-except"
    description = ("broad except that never re-raises banned in "
                   "fairify_tpu/; BaseException handlers must use the "
                   "propagate re-raise pattern (interrupts and kills "
                   "always escape)")
    allowlist = ALLOW_BROAD_EXCEPT

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node, fn, _loop, _t in ctx.attributed():
            if not isinstance(node, ast.ExceptHandler) \
                    or self.allowed(ctx.rel, fn):
                continue
            if _is_broad_type(node.type) \
                    and not any(isinstance(n, ast.Raise)
                                for n in ast.walk(node)):
                yield self.finding(ctx, node.lineno, _BROAD_HINT, function=fn)
            elif _is_base_type(node.type) and not _reraises_propagate(node):
                # Stricter bar for handlers that can eat an interrupt or
                # a replica kill: a raise somewhere is not enough — the
                # propagate class specifically must escape.
                yield self.finding(ctx, node.lineno, _BASE_HINT, function=fn)


class LoopFetchRule(Rule):
    id = "obs-loop-fetch"
    description = ("synchronous device fetch inside a verify/ loop body "
                   "banned — submit through LaunchPipeline, convert at "
                   "dequeue")
    scope = LOOP_FETCH_SCOPE
    allowlist = ALLOW_LOOP_FETCH

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node, fn, in_loop, _t in ctx.attributed():
            if in_loop and isinstance(node, ast.Call) \
                    and _is_loop_fetch(node) \
                    and not self.allowed(ctx.rel, fn):
                yield self.finding(ctx, node.lineno, _FETCH_HINT, function=fn)

"""Lock discipline for the classes threads actually share.

The obs metrics registry, the launch pipeline, the resilience journal,
the serve subsystem (request queue, admission controller, server worker)
and the SMT worker pool (dispatch lanes racing checkout/checkin) are the
modules whose instances are touched concurrently (span and heartbeat
consumers, supervised retries, client submit threads racing the server
worker, multi-threaded tests).  Their concurrency contract is
simple: any instance attribute that is *assigned* inside a ``with
self.<lock>`` block is lock-protected, and every other read or write of it
in the same class must also hold that lock.

The rule is lexical and per-class:

* **lock attributes** — ``self.X = threading.Lock()`` / ``RLock()`` /
  ``Condition(...)`` (a Condition wraps a lock, and ``with self._cv:``
  acquires it — the serve queue's idiom);
* **protected attributes** — targets of ``self.Y = ...`` /
  ``self.Y[...] = ...`` / ``self.Y += ...`` inside any
  ``with self.<lock>:`` block, in any method;
* **violations** — any other appearance of ``self.Y`` outside a
  ``with self.<lock>:`` block, in any method except ``__init__``
  (construction precedes sharing, so unguarded ``__init__`` assignments
  are the normal way protected state is born).

A private helper that is only ever *called* under the lock is invisible to
a lexical analysis — restructure it, or suppress the line with
``# lint: disable=lock-discipline`` and a comment naming the caller that
holds the lock.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from fairify_tpu.lint.core import FileContext, Finding, Rule


def _self_attr(node: ast.AST, self_name: str) -> str:
    """``Y`` if node is ``<self>.Y`` (else '')."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == self_name:
        return node.attr
    return ""


def _store_target_attr(t: ast.AST, self_name: str) -> str:
    """``Y`` for targets ``self.Y`` or ``self.Y[...]``."""
    if isinstance(t, ast.Subscript):
        t = t.value
    return _self_attr(t, self_name)


def _locked_walk(method: ast.AST, self_name: str, locks: Set[str]):
    """Yield ``(node, under_lock)`` for every node in the method body.

    ``under_lock`` is lexical containment in a ``with self.<lock>:`` block
    (any of the class's lock attributes).  Nested defs keep the lexical
    context — the closures in these modules are invoked synchronously by
    their enclosing method.
    """
    out: List[Tuple[ast.AST, bool]] = []

    def rec(node: ast.AST, locked: bool) -> None:
        out.append((node, locked))
        child_locked = locked
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if _self_attr(item.context_expr, self_name) in locks:
                    child_locked = True
        for child in ast.iter_child_nodes(node):
            rec(child, child_locked)

    rec(method, False)
    return out


class LockDisciplineRule(Rule):
    id = "lock-discipline"
    description = ("attributes assigned under self._lock must never be "
                   "read or written outside a `with <lock>` block in the "
                   "same class (init exempt)")
    scope = (
        "fairify_tpu/obs/metrics.py",
        "fairify_tpu/parallel/pipeline.py",
        "fairify_tpu/resilience/journal.py",
        # The whole serve package: server/admission (PR 8), the thread
        # fleet router (serve/fleet.py) AND the process-fleet router
        # (serve/procfleet.py) — replica tables, bucket pins, owner/
        # payload/status maps are shared between router threads,
        # control-pipe readers, submit callers, and failover.
        "fairify_tpu/serve/",
        # The SMT worker pool: dispatch lanes, the serve drainer, and
        # client submit threads all share SmtPool's worker/queue state.
        "fairify_tpu/smt/",
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef
                     ) -> Iterable[Finding]:
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

        def self_name(m) -> str:
            pos = list(m.args.posonlyargs) + list(m.args.args)
            return pos[0].arg if pos else "self"

        # Lock attributes: self.X = threading.Lock() / threading.RLock().
        locks: Set[str] = set()
        for m in methods:
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    f = node.value.func
                    if isinstance(f, ast.Attribute) \
                            and f.attr in ("Lock", "RLock", "Condition") \
                            and isinstance(f.value, ast.Name) \
                            and f.value.id == "threading":
                        for t in node.targets:
                            attr = _self_attr(t, self_name(m))
                            if attr:
                                locks.add(attr)
        if not locks:
            return

        # Pass A: attributes assigned under a lock anywhere in the class.
        protected: Set[str] = set()
        for m in methods:
            sn = self_name(m)
            for node, locked in _locked_walk(m, sn, locks):
                if not locked:
                    continue
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        attr = _store_target_attr(t, sn)
                        if attr and attr not in locks:
                            protected.add(attr)
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    attr = _store_target_attr(node.target, sn)
                    if attr and attr not in locks:
                        protected.add(attr)
        if not protected:
            return

        # Pass B: any other access outside the lock (init exempt).
        for m in methods:
            if m.name == "__init__" or self.allowed(ctx.rel, m.name):
                continue
            sn = self_name(m)
            seen_lines: Set[Tuple[int, str]] = set()
            for node, locked in _locked_walk(m, sn, locks):
                if locked:
                    continue
                attr = _self_attr(node, sn)
                if attr in protected and (node.lineno, attr) not in seen_lines:
                    seen_lines.add((node.lineno, attr))
                    yield self.finding(
                        ctx, node.lineno,
                        f"{cls.name}.{attr} is lock-protected (assigned "
                        f"under {'/'.join(sorted(locks))}) but accessed "
                        f"outside a `with` block in {m.name}() — take the "
                        f"lock or move the access", function=m.name)
